"""Evaluation runtime: per-sample generator over a jitted inference step.

TPU redesign of the reference evaluator (src/evaluation/evaluator.py:4-37):
the forward pass runs as one jitted function per batch shape (model output
pytree + final flow returned together), results are fetched to host once
per batch, then unbatched per sample — same yield contract as the
reference so eval commands/scripts iterate identically.
"""

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry, utils


@dataclass
class EvalSample:
    """One evaluated sample: inputs, ground truth, and model output.

    ``final`` is the finest full-resolution flow (H, W, 2); ``output`` is
    the model-specific raw output for this sample (what the loss consumes),
    already on host.
    """

    img1: np.ndarray
    img2: np.ndarray
    target: Optional[np.ndarray]
    valid: Optional[np.ndarray]
    final: np.ndarray
    output: Any
    meta: Any


# eval programs memoized per (model, args) so repeated evaluate() calls —
# e.g. a validation pass every N training steps — hit the same registered
# program instead of re-tracing the full forward pass each time. Bounded
# FIFO (evicting an entry drops its closure + compiled executables) so
# long-lived processes sweeping many models don't pin every one forever.
# This is the fast in-module layer; cross-caller dedupe (training
# validation vs the eval CLI, same (model, bucket, wire) triple) lives in
# the process-wide compile.registry keyed by stable model id.
_EVAL_FN_CACHE = {}
_EVAL_FN_CACHE_MAX = 8


def static_args_key(args):
    """Repr-key an argument dict for memoizing jitted fns, or None when any
    value can't be keyed exactly.

    Array-valued args (e.g. ``flow_init``) are traced into the jit as
    constants, and their reprs truncate — two different arrays could share a
    key. Such calls must bypass the cache instead. Shared by every jit-fn
    cache in the framework (here, validation, intermediates capture).
    """
    parts = []
    for k, v in sorted(args.items()):
        if hasattr(v, "shape") or (
            isinstance(v, (list, tuple)) and any(hasattr(x, "shape") for x in v)
        ):
            return None
        parts.append((k, repr(v)))
    return tuple(parts)


def _cache_key(model, model_args, mesh=None, wire=None,
               variables_sharding=None):
    if variables_sharding is not None:
        # a sharding pytree has no stable value key; bypass the cache
        return None
    args_key = static_args_key(model_args)
    if args_key is None:
        return None
    mesh_key = None if mesh is None else tuple(d.id for d in mesh.devices.flat)
    wire_key = None if wire is None else (
        wire.images, wire.flow, wire.pack_valid, wire.clip, wire.range)
    return (id(model), args_key, mesh_key, wire_key)


@dataclass
class EvalRunStats:
    """Aggregate accounting for one evaluation/validation sweep.

    Tracks batches/samples per dispatch shape ("bucket"), the number of
    freshly compiled programs (read from the registry Program's exact
    per-program compile counter — 0 on warm jit/persistent/AOT caches),
    and the pad-waste ratio — the fraction of dispatched pixels that are
    padding (modulo/bucket pad plus batch fill). ``emit`` publishes the
    ``eval`` event into the active telemetry sink.
    """

    name: str = "eval"
    samples: int = 0
    batches: int = 0
    pad_samples: int = 0
    real_pixels: int = 0
    total_pixels: int = 0
    phases: Dict[str, float] = field(default_factory=dict)
    buckets: Dict[str, Dict[str, int]] = field(default_factory=dict)
    compiles: int = 0
    _t0: float = field(default_factory=time.perf_counter)

    def add_phase(self, phase, seconds):
        self.phases[phase] = self.phases.get(phase, 0.0) + seconds

    def add_batch(self, shape, samples, pad_samples, real_pixels, compiles=0):
        h, w = shape
        bucket = self._bucket(shape)
        bucket["batches"] += 1
        bucket["samples"] += samples
        bucket["compiles"] += compiles
        self.batches += 1
        self.samples += samples
        self.pad_samples += pad_samples
        self.compiles += compiles
        self.real_pixels += int(real_pixels)
        self.total_pixels += (samples + pad_samples) * h * w

    def add_warmup(self, shape, compiles):
        """Precompile-warmup compiles count toward the bucket's (and the
        run's) compile totals — they are the sweep's compile budget."""
        self._bucket(shape)["compiles"] += compiles
        self.compiles += compiles

    def _bucket(self, shape):
        key = f"{shape[0]}x{shape[1]}"
        return self.buckets.setdefault(
            key, {"batches": 0, "samples": 0, "compiles": 0})

    def pad_waste_ratio(self):
        if not self.total_pixels:
            return 0.0
        return 1.0 - self.real_pixels / self.total_pixels

    def samples_per_sec(self):
        dt = time.perf_counter() - self._t0
        return self.samples / dt if dt > 0 else 0.0

    def emit(self):
        tele = telemetry.get()
        if not tele.enabled or not self.batches:
            return
        tele.emit(
            "eval", name=self.name, samples=self.samples,
            batches=self.batches, seconds=round(time.perf_counter() - self._t0, 4),
            samples_per_sec=round(self.samples_per_sec(), 3),
            pad_samples=self.pad_samples, compiles=self.compiles,
            pad_waste_ratio=round(self.pad_waste_ratio(), 4),
            phases={k: round(v, 4) for k, v in self.phases.items()},
            buckets=self.buckets,
        )


def _real_pixels(meta, shape, samples):
    """Un-padded content pixels of a batch, from per-sample metadata
    extents; metadata without extents (plain test stubs) counts the full
    dispatch area, i.e. zero measured waste."""
    h, w = shape
    total = 0
    for m in meta:
        ext = getattr(m, "original_extents", None)
        if ext is None:
            total += h * w
        else:
            (y0, y1), (x0, x1) = ext
            total += (y1 - y0) * (x1 - x0)
    return total


def make_eval_fn(model, model_args=None, mesh=None, wire=None,
                 variables_sharding=None, model_id=None):
    """Registered eval program ``(variables, img1, img2) ->
    (raw_output, final_flow)``.

    With ``mesh`` the step runs SPMD like the training step: the batch
    shards on the leading axis over every mesh axis (reference wraps eval
    in nn.DataParallel, src/cmd/eval.py:144-145) — callers must pad
    batches to a multiple of the mesh size (``evaluate`` does). The
    shardings come from ``parallel.partition`` — the same place the train
    step gets them — so ``variables_sharding`` (e.g.
    ``Partitioner.variables_sharding(variables)``) lets eval consume
    model-sharded training params directly: they gather to replicated
    inside the step.

    ``wire`` (models.wire.WireFormat) accepts compact-dtype un-normalized
    images and decodes + normalizes them on device.

    ``model_id`` names the model stably (config id string): the program
    then dedupes process-wide in the compile registry — the eval CLI, the
    warmup pass, and training validation all get the *same* program for
    the same (model, bucket, wire) triple — and, when the AOT store is
    enabled, its per-shape executables round-trip through serialized
    artifacts so a repeat boot compiles nothing. Without it the program
    is keyed by object identity (process-local dedupe only).
    """
    from .. import compile as programs
    from ..parallel import partition

    model_args = dict(model_args or {})
    key = _cache_key(model, model_args, mesh, wire, variables_sharding)
    if key is not None and key in _EVAL_FN_CACHE:
        return _EVAL_FN_CACHE[key]

    def _cache(step):
        if key is not None:
            while len(_EVAL_FN_CACHE) >= _EVAL_FN_CACHE_MAX:
                _EVAL_FN_CACHE.pop(next(iter(_EVAL_FN_CACHE)))
            _EVAL_FN_CACHE[key] = step
        return step

    # registry identity: stable when the caller names the model and every
    # policy component reprs exactly; otherwise pinned to this model
    # object (the _refs reference keeps its id unique while cached).
    # The key hashes the model's *config-default* arguments merged under
    # the explicit overrides — Model.apply merges them the same way at
    # call time, so two models with the same id but different config
    # defaults (e.g. ``iterations``) must NOT share a program/AOT
    # artifact. Explicit-args-only keys silently collided here.
    pkey = None
    args_key = static_args_key(
        dict(getattr(model, "arguments", {})) | model_args)
    if args_key is not None and variables_sharding is None:
        mesh_key = (None if mesh is None
                    else tuple(d.id for d in mesh.devices.flat))
        wire_key = None if wire is None else (
            wire.images, wire.flow, wire.pack_valid, wire.clip, wire.range)
        pkey = programs.ProgramKey(
            kind="eval_step",
            model=model_id or programs.unstable(model),
            flags=programs.flag_items(
                args=args_key, mesh=mesh_key, wire=wire_key))
        existing = programs.registry().get(pkey)
        if existing is not None:
            return _cache(existing)

    adapter = model.get_adapter()
    gather = (mesh is not None and variables_sharding is not None
              and partition.is_sharded(variables_sharding))
    repl_one = partition.replicated(mesh) if mesh is not None else None

    def step(variables, img1, img2):
        if gather:
            variables = jax.lax.with_sharding_constraint(
                variables, repl_one)
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        out = model.apply(variables, img1, img2, train=False, **model_args)
        result = adapter.wrap_result(out, img1.shape[1:3])
        return out, result.final()

    if mesh is None:
        step = jax.jit(step)
    else:
        data = partition.data_sharding(mesh)
        variables_in = (variables_sharding if variables_sharding is not None
                        else partition.replicated(mesh))
        step = jax.jit(step, in_shardings=(variables_in, data, data))

    # registry Program: compile events attribute to 'eval_step', compiles
    # count per-program (warmup/stats read them), AOT artifacts for
    # stable keys; the raw jit stays reachable via __wrapped__
    step = programs.register_step("eval_step", step, key=pkey)
    step._refs = (model,)

    return _cache(step)


def make_rung_fn(model, iterations, cont=False, mesh=None, wire=None,
                 variables_sharding=None, model_id=None, model_args=None,
                 quant=None):
    """Registered ladder-rung program: a fixed-``iterations`` inference
    step that returns the continuation carry alongside the final flow.

    - ``cont=False``: ``(variables, img1, img2) -> (final_flow, state)``
      — a base rung starting from zero flow.
    - ``cont=True``: ``(variables, img1, img2, flow, hidden) ->
      (final_flow, state)`` — a continuation rung re-entering the
      recurrence from a previous rung's carry (bit-exact: the models
      carry flow, not coords, across iterations).

    ``state`` is ``{"flow", "hidden", "delta"}`` — coarse-grid carry
    arrays (left on device; hand them to the next rung unfetched) plus a
    per-sample convergence norm the host reads *between* programs. Each
    (iterations, cont) pair is its own ``ProgramKey`` flag variant
    (kind ``rung_step``), so rungs dedupe process-wide, AOT-export, and
    prefetch like any other program; ``serve --prebuild`` exports the
    whole ladder this way.

    ``quant`` selects the quantized matching tier (``'u8'``/``'i8'``,
    see ``ops.quant``): the rung runs with a quantized correlation
    volume pyramid, registered as its own ``quant=...`` ProgramKey flag
    variant of the same kind. The flag — like ``warm`` — is only
    present on quant programs, so existing rung keys, AOT artifacts,
    and budget pins are untouched; ``quant=None`` is byte-identical to
    the pre-quant builder. The clip ratio (``RMD_QUANT_CLIP``) is read
    at build time and keyed only when non-default.
    """
    from .. import compile as programs
    from ..ops import quant as quant_ops
    from ..parallel import partition
    from ..utils import env

    iterations = int(iterations)
    cont = bool(cont)
    quant = quant_ops.normalize_mode(quant)
    quant_clip = (float(env.get_float("RMD_QUANT_CLIP"))
                  if quant is not None else 1.0)
    model_args = dict(model_args or {})
    for reserved in ("iterations", "flow_init", "hidden_init",
                     "return_state", "quant", "quant_clip"):
        model_args.pop(reserved, None)

    base = _cache_key(model, model_args, mesh, wire, variables_sharding)
    key = (None if base is None
           else ("rung", iterations, cont, quant, quant_clip) + base)
    if key is not None and key in _EVAL_FN_CACHE:
        return _EVAL_FN_CACHE[key]

    def _cache(step):
        if key is not None:
            while len(_EVAL_FN_CACHE) >= _EVAL_FN_CACHE_MAX:
                _EVAL_FN_CACHE.pop(next(iter(_EVAL_FN_CACHE)))
            _EVAL_FN_CACHE[key] = step
        return step

    # same identity contract as make_eval_fn, including the config-default
    # argument merge (the iterations/cont flags are what distinguish the
    # rungs of one ladder)
    pkey = None
    args_key = static_args_key(
        dict(getattr(model, "arguments", {})) | model_args)
    if args_key is not None and variables_sharding is None:
        mesh_key = (None if mesh is None
                    else tuple(d.id for d in mesh.devices.flat))
        wire_key = None if wire is None else (
            wire.images, wire.flow, wire.pack_valid, wire.clip, wire.range)
        qflags = {}
        if quant is not None:
            qflags["quant"] = quant
            if quant_clip != 1.0:
                qflags["quant_clip"] = quant_clip
        pkey = programs.ProgramKey(
            kind="rung_step",
            model=model_id or programs.unstable(model),
            flags=programs.flag_items(
                args=args_key, iterations=iterations, cont=cont,
                mesh=mesh_key, wire=wire_key, **qflags))
        existing = programs.registry().get(pkey)
        if existing is not None:
            return _cache(existing)

    adapter = model.get_adapter()
    gather = (mesh is not None and variables_sharding is not None
              and partition.is_sharded(variables_sharding))
    repl_one = partition.replicated(mesh) if mesh is not None else None

    forward_args = dict(model_args)
    forward_args["iterations"] = iterations
    forward_args["return_state"] = True
    if quant is not None:
        forward_args["quant"] = quant
        forward_args["quant_clip"] = quant_clip

    def _forward(variables, img1, img2, flow, hidden):
        if gather:
            variables = jax.lax.with_sharding_constraint(
                variables, repl_one)
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        kwargs = dict(forward_args)
        if flow is not None:
            kwargs["flow_init"] = flow
        if hidden is not None:
            kwargs["hidden_init"] = hidden
        out, state = model.apply(variables, img1, img2, train=False,
                                 **kwargs)
        result = adapter.wrap_result(out, img1.shape[1:3])
        return result.final(), state

    if cont:
        def step(variables, img1, img2, flow, hidden):
            return _forward(variables, img1, img2, flow, hidden)
    else:
        def step(variables, img1, img2):
            return _forward(variables, img1, img2, None, None)

    if mesh is None:
        step = jax.jit(step)
    else:
        data = partition.data_sharding(mesh)
        variables_in = (variables_sharding if variables_sharding is not None
                        else partition.replicated(mesh))
        shardings = (variables_in, data, data)
        if cont:
            shardings = shardings + (data, data)
        step = jax.jit(step, in_shardings=shardings)

    step = programs.register_step("rung_step", step, key=pkey)
    step._refs = (model,)
    step.iterations = iterations
    step.cont = cont
    step.quant = quant

    return _cache(step)


def make_warm_fn(model, iterations, mesh=None, wire=None,
                 variables_sharding=None, model_id=None, model_args=None,
                 quant=None):
    """Registered temporal warm-start program for video sequences:
    ``(variables, img1, img2, flow) -> (final_flow, state)`` where
    ``flow`` is the *previous frame's* coarse flow (the ``state["flow"]``
    carry of any rung/warm program, unfetched).

    The previous flow is forward-projected to the current frame *inside*
    the program — ``warp_backwards(flow, -flow)`` approximates the
    forward splat as ``out(p) = flow(p - flow(p))`` with out-of-frame
    pixels masked to zero flow — and fed into ``flow_init``. The GRU
    hidden state is *not* re-initialised here (``hidden_init`` from a
    fresh context would break parity; cross-frame hidden carry rides the
    existing ``cont=True`` rung programs instead), so with ``flow=0`` the
    projection is exactly zero and the program is bit-exact vs the plain
    base rung — cache misses degrade to the cold path, never a different
    answer.

    Each (iterations, warm) pair is its own ``ProgramKey`` flag variant
    of kind ``rung_step`` (the ``warm=True`` flag is only present on
    warm programs, so existing rung keys/AOT artifacts/budget pins are
    untouched); warm programs dedupe, AOT-export, and prefetch like any
    rung, and ``serve --prebuild`` covers them via ``warm_pool()``.

    ``quant`` routes the warm program onto the quantized matching tier
    exactly like :func:`make_rung_fn` — video warm frames are the other
    latency-critical consumer of the quant tier, and with ``flow=0`` a
    quant warm program stays bit-exact versus the quant base rung (the
    parity argument above is mode-independent).
    """
    from .. import compile as programs
    from ..ops import quant as quant_ops
    from ..ops import warp
    from ..parallel import partition
    from ..utils import env

    iterations = int(iterations)
    quant = quant_ops.normalize_mode(quant)
    quant_clip = (float(env.get_float("RMD_QUANT_CLIP"))
                  if quant is not None else 1.0)
    model_args = dict(model_args or {})
    for reserved in ("iterations", "flow_init", "hidden_init",
                     "return_state", "quant", "quant_clip"):
        model_args.pop(reserved, None)

    base = _cache_key(model, model_args, mesh, wire, variables_sharding)
    key = (None if base is None
           else ("rung", iterations, "warm", quant, quant_clip) + base)
    if key is not None and key in _EVAL_FN_CACHE:
        return _EVAL_FN_CACHE[key]

    def _cache(step):
        if key is not None:
            while len(_EVAL_FN_CACHE) >= _EVAL_FN_CACHE_MAX:
                _EVAL_FN_CACHE.pop(next(iter(_EVAL_FN_CACHE)))
            _EVAL_FN_CACHE[key] = step
        return step

    pkey = None
    args_key = static_args_key(
        dict(getattr(model, "arguments", {})) | model_args)
    if args_key is not None and variables_sharding is None:
        mesh_key = (None if mesh is None
                    else tuple(d.id for d in mesh.devices.flat))
        wire_key = None if wire is None else (
            wire.images, wire.flow, wire.pack_valid, wire.clip, wire.range)
        qflags = {}
        if quant is not None:
            qflags["quant"] = quant
            if quant_clip != 1.0:
                qflags["quant_clip"] = quant_clip
        pkey = programs.ProgramKey(
            kind="rung_step",
            model=model_id or programs.unstable(model),
            flags=programs.flag_items(
                args=args_key, iterations=iterations, cont=False,
                warm=True, mesh=mesh_key, wire=wire_key, **qflags))
        existing = programs.registry().get(pkey)
        if existing is not None:
            return _cache(existing)

    adapter = model.get_adapter()
    gather = (mesh is not None and variables_sharding is not None
              and partition.is_sharded(variables_sharding))
    repl_one = partition.replicated(mesh) if mesh is not None else None

    forward_args = dict(model_args)
    forward_args["iterations"] = iterations
    forward_args["return_state"] = True
    if quant is not None:
        forward_args["quant"] = quant
        forward_args["quant_clip"] = quant_clip

    def step(variables, img1, img2, flow):
        if gather:
            variables = jax.lax.with_sharding_constraint(
                variables, repl_one)
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        flow = flow.astype(jnp.float32)
        init, _ = warp.warp_backwards(flow, -flow)
        kwargs = dict(forward_args)
        kwargs["flow_init"] = init
        out, state = model.apply(variables, img1, img2, train=False,
                                 **kwargs)
        result = adapter.wrap_result(out, img1.shape[1:3])
        return result.final(), state

    if mesh is None:
        step = jax.jit(step)
    else:
        data = partition.data_sharding(mesh)
        variables_in = (variables_sharding if variables_sharding is not None
                        else partition.replicated(mesh))
        step = jax.jit(step, in_shardings=(variables_in, data, data, data))

    step = programs.register_step("rung_step", step, key=pkey)
    step._refs = (model,)
    step.iterations = iterations
    step.cont = False
    step.warm = True
    step.quant = quant

    return _cache(step)


def _program_compile_counter(step):
    """Monotone compile counter for one step callable.

    Registry Programs carry an exact per-program count (incremented by
    the jax.monitoring listener on actual backend compiles, telemetry
    sink or not). Legacy callables fall back to the sink's label-
    qualified count, or — with no sink either — to a constant 0: never
    the old first-seen-shape guess of 1, which overcounted every sweep
    on a warm jit/persistent cache.
    """
    if hasattr(step, "compiles") and hasattr(step, "key"):
        return lambda: step.compiles
    tele = telemetry.get()
    if tele.enabled:
        label = getattr(step, "telemetry_label", "eval_step")
        return lambda: tele.counts().get(f"compile:{label}", 0)
    return lambda: 0


def warmup_eval_fn(eval_fn, variables, shapes, batch_size, wire=None,
                   stats=None):
    """Precompile an eval fn for every (H, W) bucket shape at
    ``batch_size`` before the sweep touches real data.

    Runs the jitted step on zero-filled dummies (one forward per shape) so
    the jit cache — and, where enabled, the persistent compile cache and
    AOT program store — is hot when the first real batch of each bucket
    arrives: a KITTI-like sweep then compiles nothing mid-epoch. Dummy
    images are created in the wire image dtype when a ``wire`` format is
    active.

    Warmup compiles are attributed through the registry Program's own
    counter, which tracks actual backend compiles even with telemetry
    disabled — so the sweep's ``compiles`` column reads 0 on a warm
    jit/persistent/AOT cache instead of overcounting one per shape (the
    pre-PR-7 fallback).
    """
    dtype = wire.image_dtype() if wire is not None else np.float32

    counter = _program_compile_counter(eval_fn)
    for h, w in shapes:
        t0 = time.perf_counter()
        c0 = counter()
        img = jnp.zeros((batch_size, int(h), int(w), 3), dtype)
        out = eval_fn(variables, img, img)
        jax.block_until_ready(out[1])
        if stats is not None:
            stats.add_phase("warmup", time.perf_counter() - t0)
            stats.add_warmup((int(h), int(w)), counter() - c0)


def evaluate(model, variables, data, model_args=None, show_progress=True,
             eval_fn=None, mesh=None, wire=None, pad_to=None, stats=None,
             variables_sharding=None):
    """Yield an ``EvalSample`` per dataset sample.

    ``data`` iterates batches ``(img1, img2, flow, valid, meta)`` in NHWC
    numpy (a ``models.input.Loader`` or any compatible iterable).
    Reference contract: src/evaluation/evaluator.py:4-37. Pass a prebuilt
    ``eval_fn`` (from ``make_eval_fn``) to control caching explicitly.

    With ``mesh`` the batch is sharded over the mesh's ``data`` axis;
    short batches are padded by repeating the last sample (padded outputs
    are dropped — only real samples are yielded). ``pad_to`` extends the
    same treatment to *every* short batch: partial batches (e.g. a
    bucket's epoch-end remainder under a shape-grouping loader) are
    filled up to a fixed batch size so they reuse the full batch's
    compiled program instead of compiling one per remainder size.

    With ``wire``, ``data`` must yield wire-format batches (an adapter
    built with the same WireFormat): images upload compact and decode on
    device; the yielded ``EvalSample.img1/img2`` are decoded back to the
    normalized f32 contract on the host.

    ``stats`` (an :class:`EvalRunStats`) accumulates throughput, per-shape
    batch/compile counts, and the pad-waste ratio; pass one to also emit
    the run's ``eval`` telemetry event via ``stats.emit()``.
    """
    adapter = model.get_adapter()
    step = (eval_fn if eval_fn is not None
            else make_eval_fn(model, model_args, mesh=mesh, wire=wire,
                              variables_sharding=variables_sharding))

    if show_progress:
        data = utils.logging.progress(data, unit="batch", leave=False)

    counter = _program_compile_counter(step)

    def dispatch(item):
        img1, img2, flow, valid, meta = item
        batch = img1.shape[0]

        target = batch
        if pad_to is not None:
            target = max(target, int(pad_to))
        if mesh is not None:
            n = mesh.devices.size
            target = -(-target // n) * n

        t0 = time.perf_counter()
        j1, j2 = jnp.asarray(img1), jnp.asarray(img2)
        pad = target - batch
        if pad:
            reps = [1] * (j1.ndim - 1)
            j1 = jnp.concatenate([j1, jnp.tile(j1[-1:], [pad] + reps)])
            j2 = jnp.concatenate([j2, jnp.tile(j2[-1:], [pad] + reps)])

        # compile accounting: the trace+compile happens synchronously
        # inside the step call, so a fresh dispatch shape that takes a
        # compile shows in the program's own counter delta — exact on
        # warm jit/persistent/AOT caches, where the pre-PR-7 first-seen-
        # shape fallback guessed 1 per shape
        c0 = counter()

        out, final = step(variables, j1, j2)
        compiles = counter() - c0

        if stats is not None:
            stats.add_phase("dispatch", time.perf_counter() - t0)
            stats.add_batch(
                img1.shape[1:3], batch, pad,
                _real_pixels(meta, img1.shape[1:3], batch),
                compiles=compiles,
            )
        return item, out, final

    def drain(dispatched):
        (img1, img2, flow, valid, meta), out, final = dispatched
        batch = img1.shape[0]
        t0 = time.perf_counter()
        if wire is not None:
            img1 = wire.decode_images_host(img1)
            img2 = wire.decode_images_host(img2)
        # device_get blocks the host, not the device — with the next
        # batch already dispatched (below) the result download and the
        # host-side metrics overlap its compute, instead of the strict
        # upload -> compute -> download serialization per batch that
        # dominated validation wall time on the tunneled backend
        out, final = jax.device_get((out, final))

        result = adapter.wrap_result(out, img1.shape[1:3])
        if stats is not None:
            stats.add_phase("drain", time.perf_counter() - t0)

        for b in range(batch):
            yield EvalSample(
                img1=img1[b],
                img2=img2[b],
                target=flow[b] if flow is not None else None,
                valid=valid[b] if valid is not None else None,
                final=np.asarray(final[b]),
                output=result.output(b),
                meta=meta[b],
            )

    # per-bucket liveness: long bucketed sweeps were silent between
    # warmup and the final ``eval`` event — emit one ``steptrace``
    # progress event (scope="eval") per finished bucket, reusing the
    # StepTrace phase vocabulary so /statusz and the report can show a
    # sweep heartbeat without per-batch events
    tele = telemetry.get()
    progress = {"bucket": None, "batches": 0, "samples": 0,
                "phases": {}, "t": time.perf_counter()}

    def bucket_progress(next_bucket):
        if stats is None or not tele.enabled:
            progress["bucket"] = next_bucket
            return
        if (progress["bucket"] is not None
                and stats.batches > progress["batches"]):
            now = time.perf_counter()
            phases = {k: round(v - progress["phases"].get(k, 0.0), 6)
                      for k, v in stats.phases.items()
                      if v - progress["phases"].get(k, 0.0) > 0}
            tele.emit("steptrace", scope="eval", name=stats.name,
                      step=stats.batches, bucket=progress["bucket"],
                      window=stats.batches - progress["batches"],
                      samples=stats.samples - progress["samples"],
                      phases=phases, total=round(now - progress["t"], 6))
            progress["t"] = now
        progress["bucket"] = next_bucket
        progress["batches"] = stats.batches
        progress["samples"] = stats.samples
        progress["phases"] = dict(stats.phases)

    pending = None
    for item in data:
        bucket = f"{item[0].shape[1]}x{item[0].shape[2]}"
        if bucket != progress["bucket"]:
            bucket_progress(bucket)
        dispatched = dispatch(item)
        if pending is not None:
            yield from drain(pending)
        pending = dispatched
    if pending is not None:
        yield from drain(pending)
    bucket_progress(None)
