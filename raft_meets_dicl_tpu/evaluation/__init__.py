"""Evaluation runtime: per-sample generator over a jitted inference step.

TPU redesign of the reference evaluator (src/evaluation/evaluator.py:4-37):
the forward pass runs as one jitted function per batch shape (model output
pytree + final flow returned together), results are fetched to host once
per batch, then unbatched per sample — same yield contract as the
reference so eval commands/scripts iterate identically.
"""

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import utils


@dataclass
class EvalSample:
    """One evaluated sample: inputs, ground truth, and model output.

    ``final`` is the finest full-resolution flow (H, W, 2); ``output`` is
    the model-specific raw output for this sample (what the loss consumes),
    already on host.
    """

    img1: np.ndarray
    img2: np.ndarray
    target: Optional[np.ndarray]
    valid: Optional[np.ndarray]
    final: np.ndarray
    output: Any
    meta: Any


# jitted eval fns memoized per (model, args) so repeated evaluate() calls —
# e.g. a validation pass every N training steps — hit the jit cache instead
# of re-tracing the full forward pass each time. Bounded FIFO (evicting an
# entry drops its closure + compiled executables) so long-lived processes
# sweeping many models don't pin every one forever.
_EVAL_FN_CACHE = {}
_EVAL_FN_CACHE_MAX = 8


def static_args_key(args):
    """Repr-key an argument dict for memoizing jitted fns, or None when any
    value can't be keyed exactly.

    Array-valued args (e.g. ``flow_init``) are traced into the jit as
    constants, and their reprs truncate — two different arrays could share a
    key. Such calls must bypass the cache instead. Shared by every jit-fn
    cache in the framework (here, validation, intermediates capture).
    """
    parts = []
    for k, v in sorted(args.items()):
        if hasattr(v, "shape") or (
            isinstance(v, (list, tuple)) and any(hasattr(x, "shape") for x in v)
        ):
            return None
        parts.append((k, repr(v)))
    return tuple(parts)


def _cache_key(model, model_args, mesh=None, wire=None):
    args_key = static_args_key(model_args)
    if args_key is None:
        return None
    mesh_key = None if mesh is None else tuple(d.id for d in mesh.devices.flat)
    wire_key = None if wire is None else (
        wire.images, wire.flow, wire.pack_valid, wire.clip, wire.range)
    return (id(model), args_key, mesh_key, wire_key)


def make_eval_fn(model, model_args=None, mesh=None, wire=None):
    """Jitted ``(variables, img1, img2) -> (raw_output, final_flow)``.

    With ``mesh`` (a 1-D ``jax.sharding.Mesh`` over a ``data`` axis) the
    step runs SPMD like the training step: variables replicated, batch
    sharded on the leading axis (reference wraps eval in nn.DataParallel,
    src/cmd/eval.py:144-145) — callers must pad batches to a multiple of
    the mesh size (``evaluate`` does).

    ``wire`` (models.wire.WireFormat) accepts compact-dtype un-normalized
    images and decodes + normalizes them on device.
    """
    model_args = dict(model_args or {})
    key = _cache_key(model, model_args, mesh, wire)
    if key is not None and key in _EVAL_FN_CACHE:
        return _EVAL_FN_CACHE[key]

    adapter = model.get_adapter()

    def step(variables, img1, img2):
        if wire is not None:
            img1, img2, _, _ = wire.decode(img1, img2)
        out = model.apply(variables, img1, img2, train=False, **model_args)
        result = adapter.wrap_result(out, img1.shape[1:3])
        return out, result.final()

    if mesh is None:
        step = jax.jit(step)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))
        step = jax.jit(step, in_shardings=(repl, data, data))

    if key is not None:
        while len(_EVAL_FN_CACHE) >= _EVAL_FN_CACHE_MAX:
            _EVAL_FN_CACHE.pop(next(iter(_EVAL_FN_CACHE)))
        _EVAL_FN_CACHE[key] = step
    return step


def evaluate(model, variables, data, model_args=None, show_progress=True,
             eval_fn=None, mesh=None, wire=None):
    """Yield an ``EvalSample`` per dataset sample.

    ``data`` iterates batches ``(img1, img2, flow, valid, meta)`` in NHWC
    numpy (a ``models.input.Loader`` or any compatible iterable).
    Reference contract: src/evaluation/evaluator.py:4-37. Pass a prebuilt
    ``eval_fn`` (from ``make_eval_fn``) to control caching explicitly.

    With ``mesh`` the batch is sharded over the mesh's ``data`` axis;
    short batches are padded by repeating the last sample (padded outputs
    are dropped — only real samples are yielded).

    With ``wire``, ``data`` must yield wire-format batches (an adapter
    built with the same WireFormat): images upload compact and decode on
    device; the yielded ``EvalSample.img1/img2`` are decoded back to the
    normalized f32 contract on the host.
    """
    adapter = model.get_adapter()
    step = (eval_fn if eval_fn is not None
            else make_eval_fn(model, model_args, mesh=mesh, wire=wire))

    if show_progress:
        data = utils.logging.progress(data, unit="batch", leave=False)

    def dispatch(item):
        img1, img2, flow, valid, meta = item
        batch = img1.shape[0]

        j1, j2 = jnp.asarray(img1), jnp.asarray(img2)
        if mesh is not None:
            n = mesh.devices.size
            pad = (-batch) % n
            if pad:
                reps = [1] * (j1.ndim - 1)
                j1 = jnp.concatenate([j1, jnp.tile(j1[-1:], [pad] + reps)])
                j2 = jnp.concatenate([j2, jnp.tile(j2[-1:], [pad] + reps)])

        out, final = step(variables, j1, j2)
        return item, out, final

    def drain(dispatched):
        (img1, img2, flow, valid, meta), out, final = dispatched
        batch = img1.shape[0]
        if wire is not None:
            img1 = wire.decode_images_host(img1)
            img2 = wire.decode_images_host(img2)
        # device_get blocks the host, not the device — with the next
        # batch already dispatched (below) the result download and the
        # host-side metrics overlap its compute, instead of the strict
        # upload -> compute -> download serialization per batch that
        # dominated validation wall time on the tunneled backend
        out, final = jax.device_get((out, final))

        result = adapter.wrap_result(out, img1.shape[1:3])

        for b in range(batch):
            yield EvalSample(
                img1=img1[b],
                img2=img2[b],
                target=flow[b] if flow is not None else None,
                valid=valid[b] if valid is not None else None,
                final=np.asarray(final[b]),
                output=result.output(b),
                meta=meta[b],
            )

    pending = None
    for item in data:
        dispatched = dispatch(item)
        if pending is not None:
            yield from drain(pending)
        pending = dispatched
    if pending is not None:
        yield from drain(pending)
