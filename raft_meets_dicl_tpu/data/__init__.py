"""Host-side data pipeline: I/O, datasets, augmentation, pairing.

Everything in this package is pure numpy/cv2 on the host; jax conversion
happens exclusively in the model input adapter.
"""

from . import augment, collection, combinators, config, dataset, fw_bw, io, patterns
from .collection import Collection, Metadata, SampleArgs, SampleId
from .config import load
from .fw_bw import estimate_backwards_flow, estimate_backwards_flow_sparse

__all__ = [
    "augment", "collection", "combinators", "config", "dataset", "fw_bw",
    "io", "patterns", "Collection", "Metadata", "SampleArgs", "SampleId",
    "load", "estimate_backwards_flow", "estimate_backwards_flow_sparse",
]
