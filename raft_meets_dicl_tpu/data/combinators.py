"""Dataset combinators: concat, repeat, and random subset.

Config-compatible with the reference combinators (src/data/concat.py,
repeat.py, subset.py) but implemented in one module — they are all thin
index-transformers over a source Collection.
"""

from dataclasses import replace

import numpy as np

from .collection import Collection


class Concat(Collection):
    type = "concat"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls([data_config.load(path, c) for c in cfg["sources"]])

    def __init__(self, sources):
        super().__init__()
        self.sources = sources

    def get_config(self):
        return {"type": self.type, "sources": [s.get_config() for s in self.sources]}

    def __getitem__(self, index):
        if index < 0:
            index += len(self)
        if not 0 <= index < len(self):
            raise IndexError("index out of range")
        for source in self.sources:
            if index < len(source):
                return source[index]
            index -= len(source)
        raise IndexError("index out of range")

    def __len__(self):
        return sum(len(s) for s in self.sources)

    def description(self):
        return f"[{', '.join(repr(s.description()) for s in self.sources)}]"


class Repeat(Collection):
    type = "repeat"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(cfg["times"], data_config.load(path, cfg["source"]))

    def __init__(self, times, source):
        super().__init__()
        self.times = times
        self.source = source

    def get_config(self):
        return {
            "type": self.type,
            "times": self.times,
            "source": self.source.get_config(),
        }

    def __getitem__(self, index):
        if not 0 <= index < len(self):
            raise IndexError(
                f"index '{index}' is out of range for dataset of size '{len(self)}'"
            )
        return self.source[index % len(self.source)]

    def __len__(self):
        return self.times * len(self.source)

    def description(self):
        return f"{self.source.description()}, repeat times {self.times}"


class Cache(Collection):
    """In-memory memoization of decoded samples by index.

    TPU-native substitute for the reference's multi-worker torch
    DataLoader (src/data/__init__.py collate path): on few-core TPU VM
    hosts the Python image-decode path cannot be parallelized away, so
    repeated epochs memoize the decoded (pre-augmentation) arrays
    instead — place UNDER `augment` so randomized augmentations stay
    fresh per epoch. First epoch pays the decode, later epochs are
    memory-bandwidth only. Measured on the 1-core dev box: 127 ms ->
    ~3 ms per sample.

    ``budget-gib`` caps the resident size (default 16 GiB); beyond it,
    further samples pass through uncached (a warning is logged once).
    """

    type = "cache"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(data_config.load(path, cfg["source"]),
                   budget_gib=cfg.get("budget-gib", 16.0))

    def __init__(self, source, budget_gib=16.0):
        super().__init__()
        self.source = source
        self.budget = int(budget_gib * 2 ** 30)
        self._cache = {}
        self._bytes = 0
        self._warned = False

    def get_config(self):
        return {
            "type": self.type,
            "budget-gib": self.budget / 2 ** 30,
            "source": self.source.get_config(),
        }

    def __getitem__(self, index):
        hit = self._cache.get(index)
        if hit is not None:
            return self._fresh_meta(hit)

        sample = self.source[index]
        img1, img2, flow, valid, meta = sample
        size = sum(a.nbytes for a in (img1, img2, flow, valid)
                   if a is not None)
        if self._bytes + size <= self.budget:
            for a in (img1, img2, flow, valid):
                # loud failure instead of silent cache corruption should
                # any consumer ever mutate a sample in place
                if a is not None and a.flags.owndata:
                    a.setflags(write=False)
            # store a pristine Metadata copy: the adapter flips
            # ``meta.valid`` in place on transiently-bad batches, and a
            # retained reference would poison this sample for every
            # later epoch
            self._cache[index] = self._fresh_meta(sample)
            self._bytes += size
        elif not self._warned:
            self._warned = True
            import logging

            logging.getLogger("rmdtpu").warning(
                f"sample cache budget ({self.budget / 2**30:.1f} GiB) "
                f"exhausted after {len(self._cache)} samples; further "
                f"samples stream uncached")
        return sample

    @staticmethod
    def _fresh_meta(sample):
        img1, img2, flow, valid, meta = sample
        return img1, img2, flow, valid, [replace(m) for m in meta]

    def __len__(self):
        return len(self.source)

    def description(self):
        return f"{self.source.description()}, cached"


class Subset(Collection):
    """Random subset with replacement, drawn once at construction.

    The draw comes from an own ``Generator``: an explicit config ``seed``
    pins the subset outright; without one the seed derives from the
    (run-seeded, utils.seeds) global numpy RNG — one draw, so the subset
    stays reproducible without coupling its contents to how many global
    draws other pipeline stages happened to consume first.
    """

    type = "subset"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(cfg["size"], data_config.load(path, cfg["source"]),
                   seed=cfg.get("seed"))

    def __init__(self, size, source, seed=None):
        super().__init__()
        self.size = size
        self.source = source
        if seed is None:
            seed = int(np.random.randint(0, 2**31 - 1))
        self.seed = int(seed)
        # an empty source yields an empty subset (a not-yet-populated
        # dataset root must still spec-load)
        n = len(source)
        rng = np.random.default_rng(self.seed)
        self.map = (rng.integers(0, n, size=size) if n
                    else np.empty(0, np.int64))

    def __len__(self):
        return len(self.map)

    def get_config(self):
        return {
            "type": self.type,
            "size": self.size,
            "seed": self.seed,
            "source": self.source.get_config(),
        }

    def __getitem__(self, index):
        return self.source[self.map[index]]

    def description(self):
        return f"{self.source.description()}, subset {self.size}"
