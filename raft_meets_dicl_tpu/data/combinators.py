"""Dataset combinators: concat, repeat, and random subset.

Config-compatible with the reference combinators (src/data/concat.py,
repeat.py, subset.py) but implemented in one module — they are all thin
index-transformers over a source Collection.
"""

import numpy as np

from .collection import Collection


class Concat(Collection):
    type = "concat"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls([data_config.load(path, c) for c in cfg["sources"]])

    def __init__(self, sources):
        super().__init__()
        self.sources = sources

    def get_config(self):
        return {"type": self.type, "sources": [s.get_config() for s in self.sources]}

    def __getitem__(self, index):
        if index < 0:
            index += len(self)
        if not 0 <= index < len(self):
            raise IndexError("index out of range")
        for source in self.sources:
            if index < len(source):
                return source[index]
            index -= len(source)
        raise IndexError("index out of range")

    def __len__(self):
        return sum(len(s) for s in self.sources)

    def description(self):
        return f"[{', '.join(repr(s.description()) for s in self.sources)}]"


class Repeat(Collection):
    type = "repeat"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(cfg["times"], data_config.load(path, cfg["source"]))

    def __init__(self, times, source):
        super().__init__()
        self.times = times
        self.source = source

    def get_config(self):
        return {
            "type": self.type,
            "times": self.times,
            "source": self.source.get_config(),
        }

    def __getitem__(self, index):
        if not 0 <= index < len(self):
            raise IndexError(
                f"index '{index}' is out of range for dataset of size '{len(self)}'"
            )
        return self.source[index % len(self.source)]

    def __len__(self):
        return self.times * len(self.source)

    def description(self):
        return f"{self.source.description()}, repeat times {self.times}"


class Subset(Collection):
    """Random subset with replacement, drawn once at construction."""

    type = "subset"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(cfg["size"], data_config.load(path, cfg["source"]))

    def __init__(self, size, source):
        super().__init__()
        self.size = size
        self.source = source
        # an empty source yields an empty subset (a not-yet-populated
        # dataset root must still spec-load)
        n = len(source)
        self.map = (np.random.randint(0, n, size=size) if n
                    else np.empty(0, np.int64))

    def __len__(self):
        return len(self.map)

    def get_config(self):
        return {
            "type": self.type,
            "size": self.size,
            "source": self.source.get_config(),
        }

    def __getitem__(self, index):
        return self.source[self.map[index]]

    def description(self):
        return f"{self.source.description()}, subset {self.size}"
