"""A small format-pattern engine for dataset file layouts.

Dataset layouts describe files with Python format strings like
``'{type}/{pass}/{scene}/frame_{idx:04d}.png'``. The reference framework uses
the third-party ``parse`` library to invert such patterns
(src/data/dataset.py:208); that library is not available here, so this module
implements the needed subset natively:

- ``to_glob(pattern)`` — turn a pattern into a glob for candidate discovery,
- ``FormatPattern.match(text)`` — invert a pattern into field values
  (``d``-typed fields become ints, untyped fields match lazily),
- formatting stays plain ``str.format``.

Supported field specs: ``{name}``, ``{name:d}``, ``{name:0Nd}``, ``{name:Nd}``
and positional ``{}`` / ``{:d}`` variants.
"""

import re
from string import Formatter

_SPEC_INT = re.compile(r"^0?(\d*)d$")


def _iter_fields(pattern):
    """Yield (literal, field_name_or_None, spec) parts of a format pattern."""
    for literal, field, spec, conversion in Formatter().parse(pattern):
        yield literal, field, spec or ""


def to_glob(pattern):
    """Replace every format field with ``*`` to get a filesystem glob."""
    out = []
    for literal, field, _ in _iter_fields(pattern):
        out.append(literal)
        if field is not None:
            out.append("*")
    return "".join(out)


class FormatPattern:
    """Compiled inverse of a format pattern.

    ``match`` returns a dict mapping field names to parsed values (ints for
    ``d``-typed fields), or None if the text doesn't fit the pattern.
    Positional fields get auto-generated integer keys ``0, 1, ...`` exposed
    via ``positional_fields``.
    """

    def __init__(self, pattern):
        self.pattern = pattern
        self.named_fields = []
        self.positional_fields = []
        self._int_fields = set()

        regex = ["^"]
        auto = 0
        for literal, field, spec in _iter_fields(pattern):
            regex.append(re.escape(literal))
            if field is None:
                continue

            if field == "":
                key, group = auto, f"_p{auto}"
                self.positional_fields.append(auto)
                auto += 1
            else:
                key, group = field, field
                if field not in self.named_fields:
                    self.named_fields.append(field)

            m = _SPEC_INT.match(spec)
            if m:
                self._int_fields.add(key)
                width = m.group(1)
                body = rf"[-+]?\d{{{width},}}" if width else r"[-+]?\d+"
            elif spec:
                raise ValueError(f"unsupported format spec '{spec}' in pattern '{pattern}'")
            else:
                body = r".+?"

            # a field may appear multiple times; later occurrences backreference
            if f"(?P<{group}>" in "".join(regex):
                regex.append(rf"(?P={group})")
            else:
                regex.append(rf"(?P<{group}>{body})")

        regex.append("$")
        self._re = re.compile("".join(regex))

    def match(self, text):
        m = self._re.match(str(text))
        if m is None:
            return None

        out = {}
        for field in self.named_fields:
            v = m.group(field)
            out[field] = int(v) if field in self._int_fields else v
        for i in self.positional_fields:
            v = m.group(f"_p{i}")
            out[i] = int(v) if i in self._int_fields else v
        return out
