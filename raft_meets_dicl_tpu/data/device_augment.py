"""On-device data augmentation: the host transform set as jitted XLA ops.

JAX port of the host augmentation pipeline (``data/augment.py``), compiled
*into* the registered train step via
``parallel.train.make_train_step(augment=...)`` so the accelerator — not
the input pipeline — pays for augmentation. At pod scale host decode +
augment is the next ``data_wait`` bottleneck (the goodput ledger's
``data_starved`` class measures it directly); moving the transforms into
the step removes them from the host critical path entirely.

Two design rules govern everything here:

- **One fused warp.** All geometric transforms — zoom/stretch (scale),
  rotation, window translation (crop jitter), flips, and the frame-2
  differential shift (translate) — compose into a single inverse-affine
  resampling of ``(img1, img2, flow, valid)``. Output pixel ``p = (y, x)``
  samples input coordinate ``q = A·p + b`` (``A`` the inverse linear map);
  frame 2 samples at ``q - Δ``, and the flow field remaps exactly as

      flow'(p) = M · (flow(q) + Δ),   M = A⁻¹

  which reproduces the host semantics transform by transform: flips negate
  the matching flow component, scaling multiplies vectors by the scale
  factor, the differential shift adds to the flow (``Translate``), and
  rotation rotates the vectors into the new frame. The output shape
  equals the input shape, so batches stay on the existing bucket grid and
  the registered program count is unchanged.

- **Stateless keying.** Every random draw derives from
  ``fold_in(fold_in(PRNGKey(seed), epoch), sample_id)`` — deterministic,
  order-independent, and resumable: re-running an epoch (or resuming
  mid-epoch from a checkpoint) reproduces bit-identical augmented batches,
  because a sample's key depends only on ``(sample_id, epoch)``, never on
  step order, host RNG state, or which worker decoded it.

Photometric transforms (color jitter with the asymmetric draw, gaussian
noise, the eraser occlusion) are elementwise device ops after the warp,
applied in the model's normalized value range (``bound``). One documented
deviation from the host: the jitter ops apply in fixed order
brightness→contrast→saturation→hue instead of a randomly drawn order —
a per-sample op permutation would need a 24-way ``lax.switch`` for a
statistically negligible effect.
"""

import hashlib

import numpy as np

import jax
import jax.numpy as jnp

# ITU-R 601 luma weights, as in the host jitter (augment._rgb_to_gray)
_LUMA = (0.2989, 0.587, 0.114)


def sample_id_array(meta):
    """Stable uint32 ids for a batch's metadata list.

    Hash of ``dataset_id/sample_id`` — independent of epoch order,
    shuffling, worker assignment, and resume point, which is what makes
    the device augmentation stream reproducible.
    """
    ids = np.empty(len(meta), dtype=np.uint32)
    for i, m in enumerate(meta):
        blob = f"{m.dataset_id}/{m.sample_id}".encode()
        ids[i] = int.from_bytes(
            hashlib.blake2s(blob, digest_size=4).digest(), "little")
    return ids


def _bilinear(img, qy, qx):
    """Bilinear sample at float coords (edge clamp); exact on the grid.

    At integer coordinates every weight is exactly 0.0 or 1.0, so pure
    crops and flips reproduce the host output bit for bit.
    """
    h, w = img.shape[0], img.shape[1]
    y0 = jnp.floor(qy)
    x0 = jnp.floor(qx)
    ty = (qy - y0).astype(jnp.float32)
    tx = (qx - x0).astype(jnp.float32)

    y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
    y1i = jnp.minimum(y0i + 1, h - 1)
    x1i = jnp.minimum(x0i + 1, w - 1)

    if img.ndim == 3:
        ty, tx = ty[..., None], tx[..., None]

    v00 = img[y0i, x0i]
    v01 = img[y0i, x1i]
    v10 = img[y1i, x0i]
    v11 = img[y1i, x1i]

    top = v00 * (1.0 - tx) + v01 * tx
    bot = v10 * (1.0 - tx) + v11 * tx
    return top * (1.0 - ty) + bot * ty


def warp_affine(img1, img2, flow, valid, mat, offset, delta=(0.0, 0.0),
                th_valid=0.99, out_shape=None):
    """Fused inverse-affine warp of one sample.

    ``mat`` (2×2) and ``offset`` (2,) define the *inverse* map in (y, x)
    coordinates: output pixel ``p`` samples input coordinate
    ``q = mat @ p + offset``. ``delta`` (y, x) shifts the frame-2
    sampling to ``q - delta`` (the translate augmentation); the flow
    remaps as ``M (flow(q) + delta)`` with ``M = inv(mat)``.

    ``valid`` resamples as a soft mask thresholded at ``th_valid`` and is
    cleared where the frame-1 source coordinate leaves the frame.
    ``out_shape`` defaults to the input shape (bucket-preserving); parity
    tests pass a smaller shape to reproduce a host crop exactly.
    """
    h, w = img1.shape[0], img1.shape[1]
    oh, ow = (h, w) if out_shape is None else out_shape
    mat = jnp.asarray(mat, jnp.float32)
    offset = jnp.asarray(offset, jnp.float32)
    delta = jnp.asarray(delta, jnp.float32)

    py, px = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32),
                          jnp.arange(ow, dtype=jnp.float32), indexing="ij")
    q1y = mat[0, 0] * py + mat[0, 1] * px + offset[0]
    q1x = mat[1, 0] * py + mat[1, 1] * px + offset[1]
    q2y = q1y - delta[0]
    q2x = q1x - delta[1]

    out1 = _bilinear(img1, q1y, q1x)
    out2 = _bilinear(img2, q2y, q2x)
    f = _bilinear(flow, q1y, q1x)
    v = _bilinear(valid.astype(jnp.float32)[..., None], q1y, q1x)[..., 0]

    # forward linear map M = inv(mat), closed-form 2x2
    det = mat[0, 0] * mat[1, 1] - mat[0, 1] * mat[1, 0]
    m00 = mat[1, 1] / det
    m01 = -mat[0, 1] / det
    m10 = -mat[1, 0] / det
    m11 = mat[0, 0] / det

    fy = f[..., 1] + delta[0]
    fx = f[..., 0] + delta[1]
    flow_out = jnp.stack((m10 * fy + m11 * fx,    # x component
                          m00 * fy + m01 * fx),   # y component
                         axis=-1)

    inb = (q1y >= 0) & (q1y <= h - 1) & (q1x >= 0) & (q1x <= w - 1)
    valid_out = inb & (v >= th_valid)
    return out1, out2, flow_out, valid_out


def _shift_hue(x, shift):
    """Hue rotation by ``shift`` (fraction of a full turn) on [0,1] RGB."""
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.max(x, axis=-1)
    mn = jnp.min(x, axis=-1)
    d = mx - mn
    safe = jnp.where(d > 0, d, 1.0)
    hue = jnp.where(mx == r, ((g - b) / safe) % 6.0,
                    jnp.where(mx == g, (b - r) / safe + 2.0,
                              (r - g) / safe + 4.0))
    hue = jnp.where(d > 0, hue / 6.0, 0.0)
    hue = (hue + shift) % 1.0
    sat = jnp.where(mx > 0, d / jnp.where(mx > 0, mx, 1.0), 0.0)

    def chan(n):
        k = (n + hue * 6.0) % 6.0
        return mx - mx * sat * jnp.clip(jnp.minimum(k, 4.0 - k), 0.0, 1.0)

    return jnp.stack((chan(5.0), chan(3.0), chan(1.0)), axis=-1)


def _gray(x):
    return (x[..., 0] * _LUMA[0] + x[..., 1] * _LUMA[1]
            + x[..., 2] * _LUMA[2])


def _apply_jitter(x, p):
    """Brightness/contrast/saturation/hue with torchvision factor
    semantics, fixed op order (see module docstring)."""
    if "b" in p:
        x = x * p["b"]
    if "c" in p:
        mean = jnp.mean(_gray(jnp.clip(x, 0.0, 1.0)))
        x = p["c"] * x + (1.0 - p["c"]) * mean
    if "s" in p:
        g = _gray(jnp.clip(x, 0.0, 1.0))[..., None]
        x = p["s"] * x + (1.0 - p["s"]) * g
    if "h" in p:
        x = _shift_hue(jnp.clip(x, 0.0, 1.0), p["h"])
    return jnp.clip(x, 0.0, 1.0)


class DeviceAugment:
    """Config-typed on-device augmentation pipeline.

    Geometry (all composed into one warp): ``scale`` is a log2 zoom range,
    ``stretch`` a log2 per-axis aspect jitter, ``rotate`` the max rotation
    in degrees, ``jitter`` the max window translation in pixels (the crop
    substitute: the sampling window shifts, the shape stays bucketed) and
    ``translate`` the max frame-2 differential shift in pixels (adds to
    the flow, like the host ``translate``); ``flip`` gives (horizontal,
    vertical) probabilities. Photometrics: ``brightness``/``contrast``/
    ``saturation``/``hue`` factor ranges with ``prob_asymmetric`` as in
    the host color jitter, ``noise`` a (lo, hi) stddev range, and
    ``occlusion``/``occlusion_num``/``occlusion_size`` the frame-2 eraser.

    ``bound(range)`` attaches the model's normalized value range (from the
    input spec) so photometric math happens on [0, 1]; ``describe()``
    yields the stable token used as the ProgramKey ``augment`` flag.
    """

    def __init__(self, scale=(-0.1, 0.3), stretch=0.05, rotate=0.0,
                 translate=4.0, jitter=8.0, flip=(0.5, 0.1),
                 brightness=0.4, contrast=0.4, saturation=0.4, hue=0.1,
                 prob_asymmetric=0.2, noise=(0.0, 0.02), occlusion=0.5,
                 occlusion_num=(1, 3), occlusion_size=(10, 60),
                 th_valid=0.99, seed=0, range=(-1.0, 1.0)):
        self.scale = (float(scale[0]), float(scale[1]))
        self.stretch = float(stretch)
        self.rotate = float(rotate)
        self.translate = float(translate)
        self.jitter = float(jitter)
        self.flip = (float(flip[0]), float(flip[1]))
        self.brightness = float(brightness)
        self.contrast = float(contrast)
        self.saturation = float(saturation)
        self.hue = float(hue)
        self.prob_asymmetric = float(prob_asymmetric)
        self.noise = (float(noise[0]), float(noise[1]))
        self.occlusion = float(occlusion)
        self.occlusion_num = (int(occlusion_num[0]), int(occlusion_num[1]))
        self.occlusion_size = (int(occlusion_size[0]),
                               int(occlusion_size[1]))
        self.th_valid = float(th_valid)
        self.seed = int(seed)
        self.range = (float(range[0]), float(range[1]))

    @classmethod
    def from_config(cls, cfg):
        cfg = dict(cfg or {})
        ty = cfg.pop("type", "device")
        if ty != "device":
            raise ValueError(f"invalid device augmentation type '{ty}'")
        return cls(**{k.replace("-", "_"): v for k, v in cfg.items()})

    def get_config(self):
        return {
            "type": "device",
            "scale": list(self.scale),
            "stretch": self.stretch,
            "rotate": self.rotate,
            "translate": self.translate,
            "jitter": self.jitter,
            "flip": list(self.flip),
            "brightness": self.brightness,
            "contrast": self.contrast,
            "saturation": self.saturation,
            "hue": self.hue,
            "prob-asymmetric": self.prob_asymmetric,
            "noise": list(self.noise),
            "occlusion": self.occlusion,
            "occlusion-num": list(self.occlusion_num),
            "occlusion-size": list(self.occlusion_size),
            "th-valid": self.th_valid,
            "seed": self.seed,
        }

    def bound(self, range):
        cfg = self.get_config()
        cfg.pop("type")
        return DeviceAugment(
            **{k.replace("-", "_"): v for k, v in cfg.items()}, range=range)

    def describe(self):
        """Stable identity token for the ProgramKey ``augment`` flag."""
        blob = repr(sorted(
            (k, repr(v)) for k, v in self.get_config().items()
        )) + repr(self.range)
        return "dev-" + hashlib.sha256(blob.encode()).hexdigest()[:12]

    # -- keying -------------------------------------------------------------

    def batch_keys(self, sample_ids, epoch):
        """Per-sample keys from ``(sample_id, epoch)`` — see docstring."""
        base = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                  jnp.asarray(epoch, jnp.uint32))
        return jax.vmap(lambda s: jax.random.fold_in(base, s))(
            jnp.asarray(sample_ids, jnp.uint32))

    # -- transform stages ---------------------------------------------------

    def _geometric(self, key, img1, img2, flow, valid):
        h, w = img1.shape[0], img1.shape[1]
        ks = jax.random.split(key, 6)

        s = 2.0 ** jax.random.uniform(
            ks[0], (), minval=self.scale[0], maxval=self.scale[1])
        st = 2.0 ** jax.random.uniform(
            ks[1], (2,), minval=-self.stretch, maxval=self.stretch)
        ang = jnp.deg2rad(jax.random.uniform(
            ks[2], (), minval=-self.rotate, maxval=self.rotate))
        fl = jax.random.uniform(ks[3], (2,))
        sh = jnp.where(fl[0] < self.flip[0], -1.0, 1.0)  # horizontal: x
        sv = jnp.where(fl[1] < self.flip[1], -1.0, 1.0)  # vertical: y
        jit = jax.random.uniform(
            ks[4], (2,), minval=-self.jitter, maxval=self.jitter)
        delta = jax.random.uniform(
            ks[5], (2,), minval=-self.translate, maxval=self.translate)

        # forward map M (input -> output) in (y, x): rotation ∘ scale/flip
        sy = s * st[0] * sv
        sx = s * st[1] * sh
        ca, sa = jnp.cos(ang), jnp.sin(ang)
        m00, m01 = ca * sy, -sa * sx
        m10, m11 = sa * sy, ca * sx

        det = m00 * m11 - m01 * m10
        a00, a01 = m11 / det, -m01 / det
        a10, a11 = -m10 / det, m00 / det
        mat = jnp.stack((jnp.stack((a00, a01)), jnp.stack((a10, a11))))

        # the output center maps onto the (jittered) input center
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        off = jnp.stack(((cy + jit[0]) - (a00 * cy + a01 * cx),
                         (cx + jit[1]) - (a10 * cy + a11 * cx)))

        return warp_affine(img1, img2, flow, valid, mat, off, delta,
                           self.th_valid)

    def _has_jitter(self):
        return any((self.brightness, self.contrast, self.saturation,
                    self.hue))

    def _draw_jitter(self, key):
        kb, kc, ks, kh = jax.random.split(key, 4)
        p = {}
        if self.brightness:
            p["b"] = jax.random.uniform(
                kb, (), minval=max(0.0, 1.0 - self.brightness),
                maxval=1.0 + self.brightness)
        if self.contrast:
            p["c"] = jax.random.uniform(
                kc, (), minval=max(0.0, 1.0 - self.contrast),
                maxval=1.0 + self.contrast)
        if self.saturation:
            p["s"] = jax.random.uniform(
                ks, (), minval=max(0.0, 1.0 - self.saturation),
                maxval=1.0 + self.saturation)
        if self.hue:
            p["h"] = jax.random.uniform(
                kh, (), minval=-self.hue, maxval=self.hue)
        return p

    def _occlude(self, key, x):
        h, w = x.shape[0], x.shape[1]
        kp, kn, kr = jax.random.split(key, 3)
        on = jax.random.uniform(kp, ()) < self.occlusion
        num = jax.random.randint(kn, (), self.occlusion_num[0],
                                 self.occlusion_num[1] + 1)
        mean = jnp.mean(x, axis=(0, 1))
        yy = jnp.arange(h)[:, None]
        xx = jnp.arange(w)[None, :]
        for i in range(self.occlusion_num[1]):
            k1, k2 = jax.random.split(jax.random.fold_in(kr, i))
            pos = jax.random.randint(k1, (2,), 0, jnp.array([h, w]))
            sz = jax.random.randint(k2, (2,), self.occlusion_size[0],
                                    self.occlusion_size[1] + 1)
            hit = (on & (i < num)
                   & (yy >= pos[0]) & (yy < pos[0] + sz[0])
                   & (xx >= pos[1]) & (xx < pos[1] + sz[1]))
            x = jnp.where(hit[..., None], mean, x)
        return x

    def _photometric(self, key, img1, img2):
        if not (self._has_jitter() or self.noise[1] > 0
                or self.occlusion > 0):
            return img1, img2  # fully disabled: bit-exact passthrough

        lo, hi = self.range
        x1 = (img1 - lo) / (hi - lo)
        x2 = (img2 - lo) / (hi - lo)
        kj, ka, kn, ko = jax.random.split(key, 4)

        if self._has_jitter():
            kj1, kj2 = jax.random.split(kj)
            p1 = self._draw_jitter(kj1)
            p2 = self._draw_jitter(kj2)
            asym = jax.random.uniform(ka, ()) < self.prob_asymmetric
            p2 = jax.tree.map(lambda a, b: jnp.where(asym, b, a), p1, p2)
            x1 = _apply_jitter(x1, p1)
            x2 = _apply_jitter(x2, p2)

        if self.noise[1] > 0:
            kn0, kn1, kn2 = jax.random.split(kn, 3)
            std = jax.random.uniform(kn0, (), minval=self.noise[0],
                                     maxval=self.noise[1])
            x1 = jnp.clip(x1 + std * jax.random.normal(kn1, x1.shape),
                          0.0, 1.0)
            x2 = jnp.clip(x2 + std * jax.random.normal(kn2, x2.shape),
                          0.0, 1.0)

        if self.occlusion > 0:
            # forward semantics: erase in frame 2 (occlusions in the
            # target frame, as the host occlusion-forward)
            x2 = self._occlude(ko, x2)

        return lo + (hi - lo) * x1, lo + (hi - lo) * x2

    def _augment_one(self, key, img1, img2, flow, valid):
        kgeo, kphoto = jax.random.split(key)
        img1, img2, flow, valid = self._geometric(
            kgeo, img1, img2, flow, valid)
        img1, img2 = self._photometric(kphoto, img1, img2)
        return img1, img2, flow, valid

    def apply(self, keys, img1, img2, flow, valid):
        """Augment one decoded batch under per-sample ``keys`` [B, 2]."""
        return jax.vmap(self._augment_one)(keys, img1, img2, flow, valid)
