"""Forwards/backwards flow pairing sources.

Two ways to train on both temporal directions (reference:
src/data/fw_bw_batch.py, fw_bw_est.py):

- ``forwards-backwards-batch`` zips a forward-layout and a backward-layout
  view of the same data and concatenates them along the batch axis (ground
  truth exists for both directions, e.g. FlyingChairs2).
- ``forwards-backwards-estimate`` *computes* the backward flow from the
  forward ground truth by inverse-flow estimation (weighted bilinear
  splatting after Sánchez, Salgado & Monzón 2015, methods 3/4) plus optional
  disocclusion fill.

All host-side numpy.
"""

import copy

import numpy as np

from .collection import Collection


class ForwardsBackwardsBatch(Collection):
    type = "forwards-backwards-batch"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)
        return cls(
            data_config.load(path, cfg["forwards"]),
            data_config.load(path, cfg["backwards"]),
        )

    def __init__(self, forwards, backwards):
        super().__init__()
        assert len(forwards) == len(backwards)
        self.forwards = forwards
        self.backwards = backwards

    def get_config(self):
        return {
            "type": self.type,
            "forwards": self.forwards.get_config(),
            "backwards": self.backwards.get_config(),
        }

    def __getitem__(self, index):
        # both layouts sort by first-frame key, so index i is the same pair
        img1_fw, img2_fw, flow_fw, valid_fw, meta_fw = self.forwards[index]
        img1_bw, img2_bw, flow_bw, valid_bw, meta_bw = self.backwards[index]

        assert img1_fw.shape[:3] == img1_bw.shape[:3]
        for mf, mb in zip(meta_fw, meta_bw):
            assert mf.sample_id.img1 == mb.sample_id.img2
            assert mf.sample_id.img2 == mb.sample_id.img1

        for m in meta_fw:
            m.direction = "forwards"
        for m in meta_bw:
            m.direction = "backwards"

        img1 = np.concatenate((img1_fw, img1_bw), axis=0)
        img2 = np.concatenate((img2_fw, img2_bw), axis=0)

        flow, valid = None, None
        if flow_fw is not None:
            flow = np.concatenate((flow_fw, flow_bw), axis=0)
            valid = np.concatenate((valid_fw, valid_bw), axis=0)

        return img1, img2, flow, valid, meta_fw + meta_bw

    def __len__(self):
        return len(self.forwards)

    def description(self):
        return f"Forwards/Backwards batch: '{self.forwards.description()}'"


class ForwardsBackwardsEstimate(Collection):
    type = "forwards-backwards-estimate"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)

        fill = cfg.get("fill", {})
        return cls(
            data_config.load(path, cfg["source"]),
            cfg.get("parameters", {}),
            fill.get("method", "none"),
            fill.get("parameters", {}),
        )

    def __init__(self, source, parameters, fill_method, fill_args):
        super().__init__()
        self.source = source
        self.parameters = parameters
        self.fill_method = fill_method
        self.fill_args = fill_args

    def get_config(self):
        return {
            "type": self.type,
            "source": self.source.get_config(),
            "fill": {"method": self.fill_method, "parameters": self.fill_args},
            "parameters": self.parameters,
        }

    def __getitem__(self, index):
        img1_fw, img2_fw, flow_fw, valid_fw, meta_fw = self.source[index]

        flow_bw, valid_bw = None, None
        if flow_fw is not None:
            est = [
                estimate_backwards_flow(
                    img1_fw[i], img2_fw[i], flow_fw[i], valid_fw[i],
                    fill_method=self.fill_method, fill_args=self.fill_args,
                    **self.parameters,
                )
                for i in range(img1_fw.shape[0])
            ]
            flow_bw = np.stack([e[0] for e in est], axis=0)
            valid_bw = np.stack([e[1] for e in est], axis=0)

        meta_bw = copy.deepcopy(meta_fw)
        for m in meta_fw:
            m.sample_id.format += "-fwd"
            m.direction = "forwards"
        for m in meta_bw:
            m.sample_id.format += "-bwd"
            m.direction = "backwards"

        img1 = np.concatenate((img1_fw, img2_fw), axis=0)
        img2 = np.concatenate((img2_fw, img1_fw), axis=0)

        flow, valid = None, None
        if flow_fw is not None:
            flow = np.concatenate((flow_fw, flow_bw), axis=0)
            valid = np.concatenate((valid_fw, valid_bw), axis=0)

        return img1, img2, flow, valid, meta_fw + meta_bw

    def __len__(self):
        return len(self.source)

    def description(self):
        return f"Forwards/Backwards estimation: '{self.source.description()}'"


def estimate_backwards_flow_sparse(img1, img2, flow, valid, th_weight=0.25,
                                   s_motion=1.0, p_motion=1.0, s_similarity=1.0,
                                   p_similarity=2.0, eps=1e-9):
    """Inverse a dense forward flow by weighted bilinear splatting.

    Each valid source pixel projects to ``p + flow(p)`` in frame 2 and
    splats ``-flow(p)`` onto the four surrounding integer pixels. Splat
    weights combine the bilinear kernel (zeroed below ``th_weight``) with a
    motion prior (larger motions win at occlusions, scaled ``s_motion``,
    power ``p_motion`` on the squared magnitude) and a visual-similarity
    prior between frame-1 source and frame-2 target pixels
    (``s_similarity * (1 - d)^p_similarity``). Pixels receiving no splats
    are disocclusions: invalid, NaN flow.

    Returns ``(flow_bw, valid_bw)``.
    """
    h, w = flow.shape[:2]

    ys, xs = np.mgrid[0:h, 0:w]
    tx = xs + flow[..., 0]
    ty = ys + flow[..., 1]

    mag2 = np.sum(np.square(flow), axis=-1)
    motion_score = s_motion * mag2**p_motion

    fx = np.floor(tx)
    fy = np.floor(ty)

    accum_uv = np.zeros(h * w * 2)
    accum_w = np.zeros(h * w)

    for cx, cy in ((fx, fy), (fx + 1, fy), (fx, fy + 1), (fx + 1, fy + 1)):
        # bilinear splat kernel; at integer targets the floor corner gets
        # weight 1 and the rest 0, so no degenerate special case is needed
        wgt = np.clip(1.0 - np.abs(tx - cx), 0.0, 1.0) * np.clip(
            1.0 - np.abs(ty - cy), 0.0, 1.0
        )
        wgt[wgt < th_weight] = 0.0

        inb = (cx >= 0) & (cx <= w - 1) & (cy >= 0) & (cy <= h - 1)
        ix = np.clip(cx, 0, w - 1).astype(np.int64)
        iy = np.clip(cy, 0, h - 1).astype(np.int64)

        # visual similarity between the source pixel and the splat target
        d = np.sum(np.square(img1 - img2[iy, ix]), axis=-1)

        wgt = wgt * (motion_score + s_similarity * (1.0 - d) ** p_similarity)
        wgt = np.where(valid & inb, wgt, 0.0)

        idx = iy * w + ix
        accum_w += np.bincount(idx.ravel(), weights=wgt.ravel(), minlength=h * w)
        duv = flow * wgt[..., None]
        accum_uv += np.bincount(
            (idx[..., None] * 2 + np.arange(2)).ravel(),
            weights=duv.ravel(),
            minlength=h * w * 2,
        )

    accum_uv = accum_uv.reshape(h, w, 2)
    accum_w = accum_w.reshape(h, w)

    valid_bw = accum_w >= eps
    denom = np.where(valid_bw, accum_w, 1.0)
    flow_bw = -accum_uv / denom[..., None]
    flow_bw[~valid_bw] = np.nan

    return flow_bw, valid_bw


def estimate_backwards_flow(img1, img2, flow, valid, th_weight=0.25, s_motion=1.0,
                            p_motion=1.0, s_similarity=1.0, p_similarity=2.0,
                            eps=1e-9, fill_method="none", fill_args={}):
    """Full backward-flow estimation: sparse inversion + disocclusion fill."""
    flow_bw, valid_bw = estimate_backwards_flow_sparse(
        img1, img2, flow, valid, th_weight, s_motion, p_motion,
        s_similarity, p_similarity, eps,
    )

    if fill_method == "minimum":
        flow_bw, valid_bw = fill_min(flow_bw, valid_bw, **fill_args)
    elif fill_method == "average":
        flow_bw, valid_bw = fill_avg(flow_bw, valid_bw, **fill_args)
    elif fill_method != "none":
        raise ValueError(f"invalid fill method '{fill_method}'")

    return flow_bw, valid_bw


def _windows(arr, kernel_size, fill):
    """Zero-padded sliding windows of shape (H, W, kh*kw)."""
    p_y, p_x = (kernel_size[0] - 1) // 2, (kernel_size[1] - 1) // 2
    padded = np.pad(arr, ((p_y, p_y), (p_x, p_x)), mode="constant", constant_values=fill)
    view = np.lib.stride_tricks.sliding_window_view(padded, kernel_size)
    return view.reshape(*view.shape[:2], -1)


def _fill_min_once(flow, valid, kernel_size):
    """Fill invalid pixels with the smallest-magnitude valid flow nearby."""
    u = np.where(valid, flow[..., 0], 0.0)
    v = np.where(valid, flow[..., 1], 0.0)
    mag = np.where(valid, u * u + v * v, np.inf)

    mag_w = _windows(mag, kernel_size, np.inf)
    idx = np.argmin(mag_w, axis=-1)[..., None]

    u_min = np.take_along_axis(_windows(u, kernel_size, 0.0), idx, axis=-1)[..., 0]
    v_min = np.take_along_axis(_windows(v, kernel_size, 0.0), idx, axis=-1)[..., 0]
    has_any = np.isfinite(np.take_along_axis(mag_w, idx, axis=-1)[..., 0])

    out = np.copy(flow)
    out[~valid, 0] = u_min[~valid]
    out[~valid, 1] = v_min[~valid]

    return out, valid | has_any


def fill_min(flow, valid, kernel_size=(5, 5), n_iter=None):
    """Iterate minimum-fill until dense (or for ``n_iter`` rounds)."""
    kernel_size = tuple(kernel_size)
    if n_iter is not None:
        for _ in range(n_iter):
            flow, valid = _fill_min_once(flow, valid, kernel_size)
    else:
        while not np.all(valid):
            flow, valid = _fill_min_once(flow, valid, kernel_size)
    return flow, valid


def _fill_avg_once(flow, valid, kernel_size, threshold):
    """Fill invalid pixels with the mean of ≥``threshold`` valid neighbors."""
    u = np.where(valid, flow[..., 0], 0.0)
    v = np.where(valid, flow[..., 1], 0.0)

    count = _windows(valid.astype(np.float64), kernel_size, 0.0).sum(axis=-1)
    denom = np.maximum(count, 1.0)
    u_avg = _windows(u, kernel_size, 0.0).sum(axis=-1) / denom
    v_avg = _windows(v, kernel_size, 0.0).sum(axis=-1) / denom

    enough = count >= threshold
    fill = ~valid & enough

    out = np.copy(flow)
    out[fill, 0] = u_avg[fill]
    out[fill, 1] = v_avg[fill]

    # previously-valid pixels stay valid (a fill must never lose data, and
    # dropping them can make the until-dense loop diverge)
    return out, valid | enough


def fill_avg(flow, valid, kernel_size=(5, 5), threshold=5, n_iter=None):
    """Iterate average-fill until dense (or for ``n_iter`` rounds)."""
    kernel_size = tuple(kernel_size)
    if n_iter is not None:
        for _ in range(n_iter):
            flow, valid = _fill_avg_once(flow, valid, kernel_size, threshold)
    else:
        while not np.all(valid):
            flow, valid = _fill_avg_once(flow, valid, kernel_size, threshold)
    return flow, valid
