"""Collection protocol: the dataset-pipeline building block.

A Collection yields *pre-batched* numpy samples
``(img1[B,H,W,3], img2[B,H,W,3], flow[B,H,W,2], valid[B,H,W], meta: list)``
— most sources have B=1, but pairing sources (forwards-backwards-batch)
return B=2, and the loader concatenates sample batches into the global batch.
Matches the reference protocol (src/data/collection.py:1-22).

Everything here is host-side numpy; conversion to jax arrays happens in the
model-input adapter, nowhere else.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Tuple, Union


class Collection:
    """Abstract indexed sample source, constructible from config."""

    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid data collection type '{cfg['type']}', expected '{cls.type}'"
            )

    def get_config(self):
        raise NotImplementedError

    def __getitem__(self, index):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def description(self):
        raise NotImplementedError

    def set_epoch(self, epoch):
        """Advance epoch-dependent state (seeded augmentation draws).

        Recurses through the wrapper graph via the conventional
        ``source``/``sources`` attributes; the trainer calls this before
        iterating each epoch, *before* decode workers fork, so the value
        is captured by every worker.
        """
        for attr in ("source", "sources"):
            val = getattr(self, attr, None)
            if val is None:
                continue
            for child in val if isinstance(val, (list, tuple)) else (val,):
                if isinstance(child, Collection):
                    child.set_epoch(epoch)


@dataclass
class SampleArgs:
    """Format arguments identifying one image of a sample."""

    args: List[Union[str, int]] = field(default_factory=list)
    kwargs: Dict[str, Union[str, int]] = field(default_factory=dict)


@dataclass
class SampleId:
    """Human-readable sample key: a format string plus per-image arguments."""

    format: str
    img1: SampleArgs
    img2: SampleArgs

    def __str__(self):
        return self.format.format(*self.img1.args, **self.img1.kwargs)


@dataclass
class Metadata:
    """Per-sample metadata carried through the pipeline.

    ``valid`` is flipped to False by the input adapter when a batch fails
    validation (non-finite data); the trainer skips such batches.
    ``original_extents`` tracks the un-padded region ((y0,y1),(x0,x1)) so
    outputs can be cropped back after modulo padding.
    """

    valid: bool
    dataset_id: str
    sample_id: SampleId
    original_extents: Tuple[Tuple[int, int], Tuple[int, int]]
