"""On-device synthetic scenario generator: layered scenes with exact flow.

AutoFlow-style synthetic data rendered by XLA instead of loaded from disk:
each scene is a textured background plus ``layers`` random convex polygons
and ellipses, every element carrying a sampled affine motion (translation,
spin, zoom about its center). Because the motion model is closed-form, the
dense optical flow between consecutive frames is *exact* — and so is the
occlusion reasoning: a pixel's flow is the affine motion of the topmost
layer covering it, and the pixel is valid iff the same layer is still the
topmost one at its landing position in the next frame.

Three consumers share the renderer:

- ``Synth`` — a ``data/config.py`` Collection (``type: synth``) that
  trains end-to-end like any dataset, with no disk or decode cost (the
  host pipeline just replays the generator on CPU; the samples are fully
  determined by ``(seed, index)``).
- ``render_sequence`` — coherent multi-frame motion for the streaming
  video path (BENCH_VIDEO): layers move along constant affine velocity,
  so warm-start benchmarks get realistic temporal coherence instead of
  constant-shift toys.
- ``perturb`` / ``perturbation_suite`` — standing robustness eval suites
  (fog / blur / noise / low-light at graded severities) over the same
  underlying scenes, with the exact valid masks preserved so metrics
  stay masked.

Values are [0, 1] float32 RGB on the host-collection contract; flow is
(x, y) pixels; valid is bool.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import env as _env
from .collection import Collection, Metadata, SampleArgs, SampleId

PERTURBATIONS = ("fog", "blur", "noise", "low-light")


def _draw_layers(key, h, w, layers, motion, spin, zoom):
    """Per-layer scene parameters, stacked over the leading axis."""
    r_lo, r_hi = 0.08 * min(h, w), 0.30 * min(h, w)

    def one(k):
        ks = jax.random.split(k, 10)
        c0 = jax.random.uniform(
            ks[0], (2,), minval=jnp.array([0.1 * h, 0.1 * w]),
            maxval=jnp.array([0.9 * h, 0.9 * w]))
        vel = jax.random.uniform(ks[1], (2,), minval=-motion, maxval=motion)
        om = jax.random.uniform(ks[2], (), minval=-spin, maxval=spin)
        sc = 2.0 ** jax.random.uniform(ks[3], (), minval=-zoom, maxval=zoom)
        ell = jax.random.bernoulli(ks[4])
        rad = jax.random.uniform(ks[5], (2,), minval=r_lo, maxval=r_hi)
        phi = jax.random.uniform(ks[6], (), maxval=2.0 * jnp.pi)
        prad = jax.random.uniform(ks[7], (5,), minval=r_lo, maxval=r_hi)
        color = jax.random.uniform(ks[8], (3,), minval=0.1, maxval=0.9)
        kt = jax.random.split(ks[9], 3)
        amp = jax.random.uniform(kt[0], (3,), minval=0.05, maxval=0.25)
        freq = jax.random.uniform(kt[1], (3, 2), minval=-0.15, maxval=0.15)
        phase = jax.random.uniform(kt[2], (3,), maxval=2.0 * jnp.pi)
        return dict(c0=c0, vel=vel, om=om, sc=sc, ell=ell, rad=rad, phi=phi,
                    prad=prad, color=color, amp=amp, freq=freq, phase=phase)

    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(layers, dtype=jnp.uint32))
    return jax.vmap(one)(keys)


def _texture(p, p0y, p0x):
    """Sinusoidal texture in layer-canonical coords (moves with the layer)."""
    args = (2.0 * jnp.pi * (p["freq"][:, 0, None, None] * p0y[None]
                            + p["freq"][:, 1, None, None] * p0x[None])
            + p["phase"][:, None, None])
    tex = p["color"][:, None, None] + p["amp"][:, None, None] * jnp.sin(args)
    return jnp.clip(jnp.moveaxis(tex, 0, -1), 0.0, 1.0)


def _layer_mask(p, p0y, p0x):
    """Shape membership in canonical coords: ellipse or 5-gon half-planes."""
    dy = p0y - p["c0"][0]
    dx = p0x - p["c0"][1]
    cphi, sphi = jnp.cos(p["phi"]), jnp.sin(p["phi"])
    u = cphi * dx + sphi * dy
    v = -sphi * dx + cphi * dy
    mell = (u / p["rad"][0]) ** 2 + (v / p["rad"][1]) ** 2 <= 1.0

    ang = p["phi"] + 2.0 * jnp.pi * jnp.arange(5) / 5.0
    dist = (jnp.cos(ang)[:, None, None] * dx[None]
            + jnp.sin(ang)[:, None, None] * dy[None])
    mpoly = jnp.all(dist <= p["prad"][:, None, None], axis=0)
    return jnp.where(p["ell"], mell, mpoly)


def _pose(p, t):
    """Layer pose at frame ``t``: center and canonical->frame linear map."""
    a = p["om"] * t
    s = p["sc"] ** t
    ca, sa = jnp.cos(a), jnp.sin(a)
    lin = s * jnp.stack((jnp.stack((ca, -sa)), jnp.stack((sa, ca))))
    return p["c0"] + t * p["vel"], lin


def _frame(bg, lay, t, h, w, layers):
    """Render frame ``t``: per-pixel topmost-layer index and RGB image."""
    py, px = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")

    # background (index 0): translation-only motion, full coverage
    bg0y = py - t * bg["vel"][0]
    bg0x = px - t * bg["vel"][1]
    img = _texture(bg, bg0y, bg0x)
    own = jnp.zeros((h, w), jnp.int32)

    for i in range(layers):
        p = jax.tree.map(lambda x: x[i], lay)
        c_t, lin = _pose(p, float(t))
        det = lin[0, 0] * lin[1, 1] - lin[0, 1] * lin[1, 0]
        i00, i01 = lin[1, 1] / det, -lin[0, 1] / det
        i10, i11 = -lin[1, 0] / det, lin[0, 0] / det
        dy, dx = py - c_t[0], px - c_t[1]
        p0y = p["c0"][0] + i00 * dy + i01 * dx
        p0x = p["c0"][1] + i10 * dy + i11 * dx
        mask = _layer_mask(p, p0y, p0x)
        img = jnp.where(mask[..., None], _texture(p, p0y, p0x), img)
        own = jnp.where(mask, i + 1, own)

    return own, img


def _flow_and_valid(bg, lay, own_t, own_next, t, h, w, layers):
    """Exact flow frame t -> t+1 plus the occlusion-derived valid mask."""
    py, px = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")

    # background flow: pure translation
    fy = jnp.broadcast_to(bg["vel"][0], (h, w))
    fx = jnp.broadcast_to(bg["vel"][1], (h, w))

    for i in range(layers):
        p = jax.tree.map(lambda x: x[i], lay)
        c_t, _ = _pose(p, float(t))
        # frame-to-frame map is constant per layer: B = R(om) * sc
        ca, sa = jnp.cos(p["om"]), jnp.sin(p["om"])
        b00, b01 = p["sc"] * ca, -p["sc"] * sa
        b10, b11 = p["sc"] * sa, p["sc"] * ca
        dy, dx = py - c_t[0], px - c_t[1]
        lfy = c_t[0] + p["vel"][0] + b00 * dy + b01 * dx - py
        lfx = c_t[1] + p["vel"][1] + b10 * dy + b11 * dx - px
        sel = own_t == i + 1
        fy = jnp.where(sel, lfy, fy)
        fx = jnp.where(sel, lfx, fx)

    # occlusion: the landing pixel must still belong to the same layer
    ly = py + fy
    lx = px + fx
    inb = (ly >= 0) & (ly <= h - 1) & (lx >= 0) & (lx <= w - 1)
    iy = jnp.clip(jnp.round(ly).astype(jnp.int32), 0, h - 1)
    ix = jnp.clip(jnp.round(lx).astype(jnp.int32), 0, w - 1)
    valid = inb & (own_next[iy, ix] == own_t)

    flow = jnp.stack((fx, fy), axis=-1)
    return flow, valid


@functools.partial(jax.jit,
                   static_argnames=("shape", "frames", "layers"))
def render_sequence(key, shape, frames=2, layers=4, motion=8.0,
                    background_motion=2.0, spin=0.05, zoom=0.05):
    """Render a coherent-motion sequence with exact inter-frame flow.

    Returns ``(imgs [T,H,W,3], flows [T-1,H,W,2], valids [T-1,H,W])``;
    flow ``t`` maps frame ``t`` onto frame ``t+1``. Fully determined by
    ``key`` and the static arguments.
    """
    h, w = shape
    kbg, klay = jax.random.split(key)
    lay = _draw_layers(klay, h, w, layers, motion, spin, zoom)

    kb = jax.random.split(kbg, 4)
    bg = dict(
        vel=jax.random.uniform(kb[0], (2,), minval=-background_motion,
                               maxval=background_motion),
        color=jax.random.uniform(kb[1], (3,), minval=0.25, maxval=0.75),
        amp=jnp.full((3,), 0.12),
        freq=jax.random.uniform(kb[2], (3, 2), minval=-0.08, maxval=0.08),
        phase=jax.random.uniform(kb[3], (3,), maxval=2.0 * jnp.pi),
    )

    owns, imgs = [], []
    for t in range(frames):
        own, img = _frame(bg, lay, t, h, w, layers)
        owns.append(own)
        imgs.append(img)

    flows, valids = [], []
    for t in range(frames - 1):
        flow, valid = _flow_and_valid(bg, lay, owns[t], owns[t + 1],
                                      t, h, w, layers)
        flows.append(flow)
        valids.append(valid)

    return (jnp.stack(imgs).astype(jnp.float32),
            jnp.stack(flows).astype(jnp.float32),
            jnp.stack(valids))


def render_pair(key, shape, layers=4, motion=8.0, background_motion=2.0,
                spin=0.05, zoom=0.05):
    """One frame pair: ``(img1, img2, flow, valid)``."""
    imgs, flows, valids = render_sequence(
        key, shape, frames=2, layers=layers, motion=motion,
        background_motion=background_motion, spin=spin, zoom=zoom)
    return imgs[0], imgs[1], flows[0], valids[0]


# -- perturbations ----------------------------------------------------------


def _smooth_field(key, h, w):
    """Cheap smooth [0,1] field: a few random low-frequency sinusoids."""
    py, px = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    kf, kp = jax.random.split(key)
    freq = jax.random.uniform(kf, (4, 2), minval=-0.02, maxval=0.02)
    phase = jax.random.uniform(kp, (4,), maxval=2.0 * jnp.pi)
    field = jnp.zeros((h, w))
    for i in range(4):
        field = field + jnp.sin(
            2.0 * jnp.pi * (freq[i, 0] * py + freq[i, 1] * px) + phase[i])
    return 0.5 + field / 8.0


def _gaussian_blur(img, sigma, taps=11):
    """Separable gaussian blur (depthwise conv, reflect-free same padding)."""
    r = taps // 2
    x = jnp.arange(-r, r + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / jnp.maximum(sigma, 1e-3)) ** 2)
    k = k / jnp.sum(k)
    nchw = jnp.moveaxis(img, -1, 0)[None]  # 1,C,H,W
    dn = ("NCHW", "OIHW", "NCHW")
    kv = jnp.broadcast_to(k[None, None, :, None], (3, 1, taps, 1))
    kh = jnp.broadcast_to(k[None, None, None, :], (3, 1, 1, taps))
    out = jax.lax.conv_general_dilated(
        nchw, kv, (1, 1), [(r, r), (0, 0)], dimension_numbers=dn,
        feature_group_count=3)
    out = jax.lax.conv_general_dilated(
        out, kh, (1, 1), [(0, 0), (r, r)], dimension_numbers=dn,
        feature_group_count=3)
    return jnp.moveaxis(out[0], 0, -1)


def perturb(key, img, kind, severity):
    """Apply one standing perturbation to a [0,1] RGB image.

    ``kind`` is one of ``PERTURBATIONS``; ``severity`` in [0, 1]. The
    geometry (and hence flow/valid) is untouched — these are photometric
    corruptions for robustness evals with masked metrics.
    """
    severity = jnp.clip(jnp.asarray(severity, jnp.float32), 0.0, 1.0)
    h, w = img.shape[0], img.shape[1]

    if kind == "fog":
        alpha = severity * (0.35 + 0.5 * _smooth_field(key, h, w))
        return img * (1.0 - alpha[..., None]) + 0.92 * alpha[..., None]
    if kind == "blur":
        return _gaussian_blur(img, 0.4 + 2.6 * severity)
    if kind == "noise":
        return jnp.clip(
            img + 0.12 * severity * jax.random.normal(key, img.shape),
            0.0, 1.0)
    if kind == "low-light":
        dark = img * (1.0 - 0.8 * severity)
        dark = dark ** (1.0 + 0.6 * severity)  # crushed shadows
        return jnp.clip(
            dark + 0.04 * severity * jax.random.normal(key, img.shape),
            0.0, 1.0)
    raise ValueError(f"unknown perturbation '{kind}', "
                     f"expected one of {PERTURBATIONS}")


# -- collection -------------------------------------------------------------


def _host_device():
    """Render on CPU when the host pipeline drives the generator."""
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


class Synth(Collection):
    """Config-typed synthetic scene source (``type: synth``).

    Samples are fully determined by ``(seed, index)`` — reproducible
    across workers, epochs, and resumes, with zero disk or decode cost.
    ``perturb: {kind, severity}`` applies a standing corruption to both
    frames (robustness eval suites); flow and valid stay exact.
    """

    type = "synth"

    @classmethod
    def from_config(cls, path, cfg):
        cls._typecheck(cfg)
        shape = cfg.get("shape", [96, 128])
        if len(shape) != 2:
            raise ValueError("invalid synth shape, expected [height, width]")
        pert = cfg.get("perturb")
        if pert is not None and pert.get("kind") not in PERTURBATIONS:
            raise ValueError(
                f"invalid perturb kind, expected one of {PERTURBATIONS}")
        return cls(
            size=int(cfg.get("size", 64)),
            shape=(int(shape[0]), int(shape[1])),
            layers=int(cfg.get("layers",
                               _env.get_int("RMD_SYNTH_LAYERS"))),
            motion=float(cfg.get("motion", 8.0)),
            background_motion=float(cfg.get("background-motion", 2.0)),
            seed=int(cfg.get("seed", _env.get_int("RMD_SYNTH_SEED"))),
            perturb=pert,
        )

    def __init__(self, size=64, shape=(96, 128), layers=4, motion=8.0,
                 background_motion=2.0, seed=0, perturb=None):
        super().__init__()
        self.size = int(size)
        self.shape = (int(shape[0]), int(shape[1]))
        self.layers = int(layers)
        self.motion = float(motion)
        self.background_motion = float(background_motion)
        self.seed = int(seed)
        self.perturb = dict(perturb) if perturb else None

    def get_config(self):
        cfg = {
            "type": self.type,
            "size": self.size,
            "shape": list(self.shape),
            "layers": self.layers,
            "motion": self.motion,
            "background-motion": self.background_motion,
            "seed": self.seed,
        }
        if self.perturb is not None:
            cfg["perturb"] = dict(self.perturb)
        return cfg

    def __getitem__(self, index):
        if not 0 <= index < self.size:
            raise IndexError(index)

        dev = _host_device()
        with jax.default_device(dev) if dev is not None else _nullcontext():
            key = jax.random.fold_in(
                jax.random.PRNGKey(self.seed), np.uint32(index))
            img1, img2, flow, valid = render_pair(
                key, self.shape, layers=self.layers, motion=self.motion,
                background_motion=self.background_motion)
            if self.perturb is not None:
                kind = self.perturb["kind"]
                sev = float(self.perturb.get("severity", 0.5))
                k1, k2 = jax.random.split(jax.random.fold_in(key, 1))
                img1 = perturb(k1, img1, kind, sev)
                img2 = perturb(k2, img2, kind, sev)

        h, w = self.shape
        meta = Metadata(
            valid=True, dataset_id="synth",
            sample_id=SampleId(f"synth-{self.seed}-{index}",
                               SampleArgs(), SampleArgs()),
            original_extents=((0, h), (0, w)),
        )
        return (np.asarray(img1)[None], np.asarray(img2)[None],
                np.asarray(flow)[None], np.asarray(valid)[None], [meta])

    def __len__(self):
        return self.size

    def description(self):
        pert = (f", {self.perturb['kind']} perturbed"
                if self.perturb is not None else "")
        return (f"synthetic scenes ({self.size} samples, "
                f"{self.shape[0]}x{self.shape[1]}, "
                f"{self.layers} layers{pert})")


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def perturbation_suite(base, severities=(0.25, 0.5, 0.75)):
    """Standing robustness suites over one base ``Synth`` config.

    Returns ``{"<kind>-<severity>": Synth}`` covering every perturbation
    kind at each severity — same seed and scene set as ``base``, so EPE
    deltas isolate the corruption (masked metrics stay exact).
    """
    cfg = base.get_config()
    suites = {}
    for kind in PERTURBATIONS:
        for sev in severities:
            c = dict(cfg, perturb={"kind": kind, "severity": sev})
            c.pop("type")
            shape = c.pop("shape")
            suites[f"{kind}-{sev:g}"] = Synth(
                shape=tuple(shape),
                **{k.replace("-", "_"): v for k, v in c.items()})
    return suites
