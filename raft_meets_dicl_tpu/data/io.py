"""Optical-flow and image file I/O.

Host-side, pure numpy/cv2 — these feed the TPU input pipeline and never touch
jax. Formats and conventions follow the reference framework
(src/data/io.py): images are returned HWC RGB float32 in [0, 1]; flow fields
are HWC float32 (u, v) in pixels.

Supported formats:
- generic images via OpenCV (any depth, grayscale promoted to RGB),
- Middlebury ``.flo`` (magic ``PIEH``, little-endian w/h + interleaved u,v),
- KITTI 16-bit PNG flow (``(value - 2^15) / 64`` with a validity channel),
- Freiburg ``.pfm`` (scale sign encodes endianness, rows stored bottom-up).
"""

from pathlib import Path

import cv2
import numpy as np

_FLO_MAGIC = b"PIEH"


def read_image_generic(file):
    """Read an image as HWC RGB float32 in [0, 1] (grayscale → RGB)."""
    file = Path(file)
    if not file.exists():
        raise FileNotFoundError(f"File '{file}' does not exist")

    raw = cv2.imread(str(file), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise ValueError(f"could not decode image file: {file}")

    scale = np.iinfo(raw.dtype).max
    return raw[:, :, ::-1].astype(np.float32) / scale  # BGR → RGB


def read_flow_kitti(file):
    """Read KITTI-format 16-bit PNG flow; returns (flow, valid)."""
    file = Path(file)
    if not file.exists():
        raise FileNotFoundError(f"File '{file}' does not exist")

    raw = cv2.imread(str(file), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise ValueError(f"could not decode flow file: {file}")

    raw = raw[:, :, ::-1]  # BGR → RGB: (u, v, valid)
    flow = (raw[:, :, :2].astype(np.float32) - 2.0**15) / 64.0
    return flow, raw[:, :, 2].astype(bool)


def write_flow_kitti(file, uv, valid=None):
    """Write flow as KITTI-format 16-bit PNG."""
    file = Path(file)
    if not file.parent.exists():
        raise FileNotFoundError(f"Directory '{file.parent}' does not exist")

    encoded = 64.0 * np.asarray(uv) + 2.0**15
    if valid is None:
        valid = np.ones(encoded.shape[:2])

    data = np.dstack((encoded, valid)).astype(np.uint16)
    cv2.imwrite(str(file), data[:, :, ::-1])


def read_flow_mb(file):
    """Read Middlebury ``.flo`` flow; returns (H, W, 2) float32."""
    data = Path(file).read_bytes()
    if data[:4] != _FLO_MAGIC:
        raise ValueError(f"Invalid flow file: {file}")

    w, h = np.frombuffer(data, dtype="<i4", count=2, offset=4)
    uv = np.frombuffer(data, dtype="<f4", count=int(w) * int(h) * 2, offset=12)
    return uv.reshape(int(h), int(w), 2).copy()


def write_flow_mb(file, uv):
    """Write Middlebury ``.flo`` flow."""
    uv = np.asarray(uv)
    h, w, _ = uv.shape
    with open(file, "wb") as fd:
        fd.write(_FLO_MAGIC)
        np.array([w, h], dtype="<i4").tofile(fd)
        uv.astype("<f4").tofile(fd)


def read_pfm(file):
    """Read a Freiburg ``.pfm`` image; returns (H, W, C) float, C in {1, 3}."""
    with open(file, "rb") as fd:
        header = fd.readline().rstrip()
        if header == b"PF":
            channels = 3
        elif header == b"Pf":
            channels = 1
        else:
            raise ValueError(f"Not a PFM file: {file}")

        dims = fd.readline().decode("ascii").split()
        if len(dims) != 2:
            raise ValueError(f"Invalid PFM file: {file}")
        w, h = int(dims[0]), int(dims[1])

        scale = float(fd.readline().decode("ascii").rstrip())
        endian = "<" if scale < 0 else ">"

        data = np.fromfile(fd, dtype=endian + "f4", count=w * h * channels)

    # PFM rows are stored bottom-to-top
    return data.reshape(h, w, channels)[::-1].copy()
