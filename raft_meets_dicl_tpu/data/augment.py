"""Data augmentation: 15 config-typed transforms over pre-batched samples.

Covers the reference augmentation set (src/data/augment.py, itself modeled on
the RAFT augmentor): color jitter (float and 8-bit variants), random/center
crop, flips, gaussian noise, occlusion eraser patches, flow-magnitude
restriction, dense/sparse linear/exponential scaling, translation, and
rotation.

All transforms are host-side numpy over ``(img1, img2, flow, valid, meta)``
batches — the TPU never sees augmentation code. Unlike the reference, color
jitter is implemented natively in numpy (HSV-based, torchvision-style
semantics: factor ranges, random op order, symmetric-vs-asymmetric draw)
rather than delegating to torchvision, which keeps the input pipeline free of
torch.

Random draws go through an explicit ``numpy.random.Generator`` threaded into
``process`` — ``Augment`` derives it per sample from
``(seed, epoch, sample_id)``, so augmentation is reproducible and
race-free across decode-pool workers (the module-level ``np.random`` state is
per-process and draw-order dependent). ``seed: legacy`` in the config keeps
the historical unseeded behavior.
"""

import hashlib

import cv2
import numpy as np
import scipy.ndimage as ndimage

from .collection import Collection


class _LegacyRandom:
    """Generator-API shim over the module-level ``np.random`` state.

    Keeps ``seed: legacy`` configs (and direct ``aug(*sample)`` calls without
    an explicit Generator) byte-compatible with the historical draw sequence.
    """

    def random(self):
        return np.random.rand()

    def uniform(self, low=0.0, high=1.0, size=None):
        return np.random.uniform(low, high, size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return np.random.normal(loc, scale, size)

    def permutation(self, x):
        return np.random.permutation(x)

    def integers(self, low, high=None, size=None):
        return np.random.randint(low, high, size)


_LEGACY = _LegacyRandom()

_CV2_MODES = {
    "nearest": cv2.INTER_NEAREST,
    "linear": cv2.INTER_LINEAR,
    "cubic": cv2.INTER_CUBIC,
    "area": cv2.INTER_AREA,
}


class Augment(Collection):
    """Wraps a source Collection and applies an augmentation list.

    ``sync=True`` applies each transform once across the whole pre-batched
    sample (one random draw per batch); ``sync=False`` splits the batch and
    augments each sample independently.

    ``seed`` keys a per-sample ``np.random.Generator`` from
    ``(seed, epoch, sample_id)`` — the same sample in the same epoch draws the
    same augmentation regardless of iteration order, worker assignment, or
    resume point (the device path keys identically). ``seed="legacy"``
    restores the historical module-level ``np.random`` draws.
    """

    type = "augment"

    @classmethod
    def from_config(cls, path, cfg):
        from . import config as data_config

        cls._typecheck(cfg)

        augs = [build_augmentation(a) for a in (cfg["augmentations"] or [])]
        return cls(augs, data_config.load(path, cfg["source"]), cfg.get("sync", True),
                   cfg.get("seed", 0))

    def __init__(self, augmentations, source, sync=True, seed=0):
        super().__init__()
        self.augmentations = augmentations
        self.source = source
        self.sync = sync
        self.seed = seed
        self.epoch = 0

    def get_config(self):
        return {
            "type": self.type,
            "augmentations": [a.get_config() for a in self.augmentations],
            "source": self.source.get_config(),
            "sync": self.sync,
            "seed": self.seed,
        }

    def set_epoch(self, epoch):
        self.epoch = int(epoch)
        super().set_epoch(epoch)

    def _rng_for(self, meta):
        if self.seed == "legacy":
            return _LEGACY
        sid = hashlib.blake2s(
            f"{meta.dataset_id}/{meta.sample_id}".encode(), digest_size=8
        ).digest()
        return np.random.default_rng(
            (int(self.seed), self.epoch, int.from_bytes(sid, "little"))
        )

    def _apply(self, sample, rng):
        for aug in self.augmentations:
            sample = aug(*sample, rng=rng)
        return sample

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.source[index]

        if self.sync:
            img1, img2, flow, valid, meta = self._apply(
                (img1, img2, flow, valid, meta), self._rng_for(meta[0])
            )
        else:
            parts = []
            for i in range(img1.shape[0]):
                f = flow[i : i + 1] if flow is not None else None
                v = valid[i : i + 1] if valid is not None else None
                parts.append(
                    self._apply((img1[i : i + 1], img2[i : i + 1], f, v, [meta[i]]),
                                self._rng_for(meta[i]))
                )

            img1 = np.concatenate([p[0] for p in parts], axis=0)
            img2 = np.concatenate([p[1] for p in parts], axis=0)
            if flow is not None:
                flow = np.concatenate([p[2] for p in parts], axis=0)
                valid = np.concatenate([p[3] for p in parts], axis=0)
            meta = [m for p in parts for m in p[4]]

        img1 = np.ascontiguousarray(img1)
        img2 = np.ascontiguousarray(img2)
        if flow is not None:
            flow = np.ascontiguousarray(flow)
            valid = np.ascontiguousarray(valid)

        return img1, img2, flow, valid, meta

    def __len__(self):
        return len(self.source)

    def description(self):
        return f"{self.source.description()}, augmented"


class Augmentation:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid augmentation type '{cfg['type']}', expected '{cls.type}'"
            )

    def get_config(self):
        raise NotImplementedError

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        raise NotImplementedError

    def __call__(self, img1, img2, flow, valid, meta, rng=None):
        return self.process(img1, img2, flow, valid, meta,
                            rng if rng is not None else _LEGACY)


# -- color jitter -----------------------------------------------------------


def _rgb_to_gray(img):
    # ITU-R 601 luma weights, as used by torchvision
    return img @ np.array([0.2989, 0.587, 0.114], dtype=img.dtype)


def _adjust_hue(img, shift):
    """Shift hue by ``shift`` (fraction of a full turn) via HSV round-trip."""
    hsv = cv2.cvtColor(np.clip(img, 0.0, 1.0).astype(np.float32), cv2.COLOR_RGB2HSV)
    hsv[..., 0] = (hsv[..., 0] + shift * 360.0) % 360.0
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)


def _jitter_once(img, params):
    """Apply brightness/contrast/saturation/hue factors in the drawn order."""
    order, b, c, s, h = params

    for op in order:
        if op == 0 and b is not None:
            img = img * b
        elif op == 1 and c is not None:
            mean = _rgb_to_gray(np.clip(img, 0.0, 1.0)).mean()
            img = c * img + (1 - c) * mean
        elif op == 2 and s is not None:
            gray = _rgb_to_gray(np.clip(img, 0.0, 1.0))[..., None]
            img = s * img + (1 - s) * gray
        elif op == 3 and h is not None:
            shape = img.shape
            img = _adjust_hue(img.reshape(-1, shape[-2], 3), h).reshape(shape)

    return np.clip(img, 0.0, 1.0).astype(np.float32)


class ColorJitter(Augmentation):
    """Photometric jitter with torchvision-style factor semantics.

    With probability ``prob-asymmetric`` the two frames get independent
    draws; otherwise one draw is shared (symmetric).
    """

    type = "color-jitter"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(
            cfg["prob-asymmetric"],
            cfg["brightness"],
            cfg["contrast"],
            cfg["saturation"],
            cfg["hue"],
        )

    def __init__(self, prob_asymmetric, brightness, contrast, saturation, hue):
        super().__init__()
        self.prob_asymmetric = prob_asymmetric
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def get_config(self):
        return {
            "type": self.type,
            "prob-asymmetric": self.prob_asymmetric,
            "brightness": self.brightness,
            "contrast": self.contrast,
            "saturation": self.saturation,
            "hue": self.hue,
        }

    @staticmethod
    def _factor_range(value, center=1.0, lower_bound=0.0):
        if value is None or (np.isscalar(value) and value == 0):
            return None
        if isinstance(value, (list, tuple)):
            return float(value[0]), float(value[1])
        return max(lower_bound, center - float(value)), center + float(value)

    def _draw(self, rng):
        b = self._factor_range(self.brightness)
        c = self._factor_range(self.contrast)
        s = self._factor_range(self.saturation)
        h = (
            (-float(self.hue), float(self.hue))
            if np.isscalar(self.hue)
            else tuple(map(float, self.hue))
        ) if self.hue else None

        return (
            rng.permutation(4),
            rng.uniform(*b) if b else None,
            rng.uniform(*c) if c else None,
            rng.uniform(*s) if s else None,
            rng.uniform(*h) if h else None,
        )

    def _transform(self, img, rng):
        return _jitter_once(img, self._draw(rng))

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        if rng.random() < self.prob_asymmetric:
            img1 = self._transform(img1, rng)
            img2 = self._transform(img2, rng)
        else:
            stack = _jitter_once(np.stack((img1, img2)), self._draw(rng))
            img1, img2 = stack[0], stack[1]

        return img1, img2, flow, valid, meta


class ColorJitter8bit(ColorJitter):
    """Color jitter with an 8-bit quantization round-trip (RAFT parity)."""

    type = "color-jitter-8bit"

    @staticmethod
    def _quantize(img):
        return np.round(np.clip(img, 0.0, 1.0) * 255.0).astype(np.uint8)

    def _transform(self, img, rng):
        img = self._quantize(img).astype(np.float32) / 255.0
        img = _jitter_once(img, self._draw(rng))
        return self._quantize(img).astype(np.float32) / 255.0

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        if rng.random() < self.prob_asymmetric:
            img1 = self._transform(img1, rng)
            img2 = self._transform(img2, rng)
        else:
            stack = self._transform(np.stack((img1, img2)), rng)
            img1, img2 = stack[0], stack[1]

        return img1, img2, flow, valid, meta


# -- geometric transforms ---------------------------------------------------


def _crop(img1, img2, flow, valid, meta, x0, y0, w, h):
    img1 = img1[:, y0 : y0 + h, x0 : x0 + w]
    img2 = img2[:, y0 : y0 + h, x0 : x0 + w]
    if flow is not None:
        flow = flow[:, y0 : y0 + h, x0 : x0 + w]
        valid = valid[:, y0 : y0 + h, x0 : x0 + w]

    for m in meta:
        m.original_extents = ((0, h), (0, w))

    return img1, img2, flow, valid, meta


class Crop(Augmentation):
    """Random crop to ``size`` = (width, height)."""

    type = "crop"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        size = list(cfg["size"])
        if len(size) != 2:
            raise ValueError("invalid crop size, expected list or tuple with two elements")
        return cls(size)

    def __init__(self, size):
        super().__init__()
        self.size = size

    def get_config(self):
        return {"type": self.type, "size": self.size}

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        assert img1.shape[:3] == img2.shape[:3]

        w, h = self.size
        mx = img1.shape[2] - w
        my = img1.shape[1] - h
        x0 = rng.integers(0, mx) if mx > 0 else 0
        y0 = rng.integers(0, my) if my > 0 else 0

        return _crop(img1, img2, flow, valid, meta, x0, y0, w, h)


class CropCenter(Crop):
    type = "crop-center"

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        assert img1.shape[:3] == img2.shape[:3]

        w, h = self.size
        x0 = (img1.shape[2] - w) // 2
        y0 = (img1.shape[1] - h) // 2

        return _crop(img1, img2, flow, valid, meta, x0, y0, w, h)


class Flip(Augmentation):
    """Independent horizontal/vertical flips; flow components change sign."""

    type = "flip"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        prob = list(cfg["probability"])
        if len(prob) != 2:
            raise ValueError("invalid flip probability, expected two elements")
        return cls(prob)

    def __init__(self, probability):
        super().__init__()
        self.probability = probability

    def get_config(self):
        return {"type": self.type, "probability": self.probability}

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        if rng.random() < self.probability[0]:  # horizontal
            img1, img2 = img1[:, :, ::-1], img2[:, :, ::-1]
            if flow is not None:
                flow = flow[:, :, ::-1] * (-1.0, 1.0)
                valid = valid[:, :, ::-1]

        if rng.random() < self.probability[1]:  # vertical
            img1, img2 = img1[:, ::-1], img2[:, ::-1]
            if flow is not None:
                flow = flow[:, ::-1] * (1.0, -1.0)
                valid = valid[:, ::-1]

        return img1, img2, flow, valid, meta


class NoiseNormal(Augmentation):
    type = "noise-normal"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        stddev = cfg["stddev"]
        if isinstance(stddev, list):
            if len(stddev) > 2:
                raise ValueError("invalid stddev, expected float or two floats")
        else:
            stddev = [float(stddev), float(stddev)]
        return cls(stddev)

    def __init__(self, stddev):
        super().__init__()
        self.stddev = stddev

    def get_config(self):
        return {"type": self.type, "stddev": self.stddev}

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        if self.stddev[0] < self.stddev[1]:
            stddev = rng.uniform(self.stddev[0], self.stddev[1])
        else:
            stddev = self.stddev[0]

        img1 = np.clip(img1 + rng.normal(0.0, stddev, img1.shape), 0.0, 1.0)
        img2 = np.clip(img2 + rng.normal(0.0, stddev, img2.shape), 0.0, 1.0)

        return img1, img2, flow, valid, meta


class _Occlusion(Augmentation):
    """Eraser patches filled with the image mean color (RAFT-style).

    With skew correction, patch corners may lie outside the image so the
    occluded-area distribution is uniform near borders.
    """

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        num = cfg["num"]
        if isinstance(num, list):
            if len(num) > 2:
                raise ValueError("invalid num, expected int or two ints")
        else:
            num = [int(num), int(num)]
        if num[0] > num[1]:
            raise ValueError("invalid num, expected num[0] <= num[1]")

        min_size = list(cfg["min-size"])
        max_size = list(cfg["max-size"])
        if len(min_size) != 2 or len(max_size) != 2:
            raise ValueError("invalid min-size/max-size, expected two elements")

        return cls(cfg["probability"], num, min_size, max_size,
                   bool(cfg.get("skew-correction", True)))

    def __init__(self, probability, num, min_size, max_size, skew_correction=True):
        super().__init__()
        self.probability = probability
        self.num = num
        self.min_size = min_size
        self.max_size = max_size
        self.skew_correction = skew_correction

    def get_config(self):
        return {
            "type": self.type,
            "probability": self.probability,
            "num": self.num,
            "min-size": self.min_size,
            "max-size": self.max_size,
            "skew-correction": self.skew_correction,
        }

    def _patch(self, img, rng):
        if rng.random() >= self.probability:
            return img

        img = img.copy()
        h, w = img.shape[1:3]
        num = self.num[0] if self.num[0] == self.num[1] else rng.integers(*self.num)

        for _ in range(num):
            dx, dy = rng.integers(self.min_size, self.max_size)
            if self.skew_correction:
                y0, x0 = rng.integers((-dy + 1, -dx + 1), (h, w))
            else:
                y0, x0 = rng.integers((0, 0), (h, w))

            ys, xs = max(0, y0), max(0, x0)
            ye, xe = min(h, y0 + dy), min(w, x0 + dx)
            for i in range(img.shape[0]):
                img[i, ys:ye, xs:xe, :] = img[i].mean(axis=(0, 1))

        return img


class OcclusionForward(_Occlusion):
    type = "occlusion-forward"

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        return img1, self._patch(img2, rng), flow, valid, meta


class OcclusionBackward(_Occlusion):
    type = "occlusion-backward"

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        return self._patch(img1, rng), img2, flow, valid, meta


class RestrictFlowMagnitude(Augmentation):
    """Invalidates pixels whose flow magnitude exceeds ``maximum``."""

    type = "restrict-flow-magnitude"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(float(cfg["maximum"]))

    def __init__(self, maximum):
        super().__init__()
        self.maximum = maximum

    def get_config(self):
        return {"type": self.type, "maximum": self.maximum}

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        mag = np.linalg.norm(flow, ord=2, axis=-1)
        return img1, img2, flow, valid & (mag < self.maximum), meta


# -- scaling ----------------------------------------------------------------


def _resize_batch(batch, size, mode):
    return np.stack([cv2.resize(x, size, interpolation=mode) for x in batch], axis=0)


def _scale_dense_flow(flow, valid, size, scale, mode, th_valid):
    """Resize flow and rescale vectors; soft-resampled valid mask thresholded."""
    flow_out, valid_out = [], []
    for f, v in zip(flow, valid):
        flow_out.append(cv2.resize(f, size, interpolation=mode) * scale)
        vf = cv2.resize(v.astype(np.float32), size, interpolation=mode)
        valid_out.append(vf >= th_valid)
    return np.stack(flow_out, axis=0), np.stack(valid_out, axis=0)


def _scale_sparse_flow(flow, valid, size, scale):
    """Re-scatter valid flow vectors onto the scaled grid (KITTI-style)."""
    flow_out, valid_out = [], []
    for f, v in zip(flow, valid):
        ys, xs = np.nonzero(v)
        coords = np.stack((xs, ys), axis=-1).astype(np.float32) * scale
        vecs = f[ys, xs] * scale

        coords = np.round(coords).astype(np.int32)
        inb = (
            (coords[:, 0] >= 0) & (coords[:, 0] < size[0])
            & (coords[:, 1] >= 0) & (coords[:, 1] < size[1])
        )
        coords, vecs = coords[inb], vecs[inb]

        new_flow = np.zeros((size[1], size[0], 2), dtype=np.float32)
        new_valid = np.zeros((size[1], size[0]), dtype=bool)
        new_flow[coords[:, 1], coords[:, 0]] = vecs
        new_valid[coords[:, 1], coords[:, 0]] = True

        flow_out.append(new_flow)
        valid_out.append(new_valid)

    return np.stack(flow_out, axis=0), np.stack(valid_out, axis=0)


class _ScaleBase(Augmentation):
    """Shared machinery for the four scale augmentations.

    Subclasses choose the scale-factor distribution (linear vs. exponential)
    and dense vs. sparse flow resampling. ``min_size`` clamps the output so
    downstream crops stay possible.
    """

    sparse = False

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        min_size = list(cfg.get("min-size", [0, 0]))
        if len(min_size) != 2 or min_size[0] < 0 or min_size[1] < 0:
            raise ValueError("invalid min-size, expected two unsigned integers")

        min_scale = float(cfg["min-scale"])
        max_scale = float(cfg["max-scale"])
        if min_scale > max_scale:
            raise ValueError("min-scale must be smaller than or equal to max-scale")

        max_stretch = float(cfg["max-stretch"])
        if max_stretch < 0:
            raise ValueError("stretch must be non-negative")

        prob_stretch = float(cfg.get("prob-stretch", 1.0))
        mode = cfg.get("mode", "linear")
        if mode not in _CV2_MODES:
            raise ValueError(f"invalid scaling mode '{mode}'")

        kwargs = {}
        if not cls.sparse:
            kwargs["th_valid"] = cfg.get("th-valid", 0.99)

        return cls(min_size, min_scale, max_scale, max_stretch, prob_stretch, mode, **kwargs)

    def __init__(self, min_size, min_scale, max_scale, max_stretch, prob_stretch,
                 mode, th_valid=None):
        super().__init__()
        self.min_size = min_size
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.max_stretch = max_stretch
        self.prob_stretch = prob_stretch
        self.mode = mode
        self.th_valid = th_valid

    def get_config(self):
        cfg = {
            "type": self.type,
            "min-size": self.min_size,
            "min-scale": self.min_scale,
            "max-scale": self.max_scale,
            "max-stretch": self.max_stretch,
            "prob-stretch": self.prob_stretch,
            "mode": self.mode,
        }
        if not self.sparse:
            cfg["th-valid"] = self.th_valid
        return cfg

    def _draw_factors(self, rng):
        raise NotImplementedError

    def _new_size(self, input_size, rng):
        sx, sy = self._draw_factors(rng)
        old = np.array(input_size)[::-1]  # (w, h)
        new = np.clip(np.ceil(old * [sx, sy]).astype(np.int32), self.min_size, None)
        return new, new / old

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        assert img1.shape[:3] == img2.shape[:3]

        size, scale = self._new_size(img1.shape[1:3], rng)
        mode = _CV2_MODES[self.mode]

        img1 = _resize_batch(img1, size, mode)
        img2 = _resize_batch(img2, size, mode)

        if flow is not None:
            if self.sparse:
                flow, valid = _scale_sparse_flow(flow, valid, size, scale)
            else:
                flow, valid = _scale_dense_flow(flow, valid, size, scale, mode, self.th_valid)

        for m in meta:
            m.original_extents = ((0, img1.shape[1]), (0, img1.shape[2]))

        return img1, img2, flow, valid, meta


class Scale(_ScaleBase):
    """Linear scale factor with multiplicative aspect stretch 2^±s."""

    type = "scale"

    def _draw_factors(self, rng):
        scale = rng.uniform(self.min_scale, self.max_scale)
        stretch = 0.0
        if rng.random() < self.prob_stretch:
            stretch = rng.uniform(-self.max_stretch, self.max_stretch)
        return scale * 2 ** (stretch / 2), scale * 2 ** -(stretch / 2)


class ScaleSparse(Scale):
    type = "scale-sparse"
    sparse = True


class ScaleExp(_ScaleBase):
    """RAFT-style 2^s scaling with independent per-axis stretch."""

    type = "scale-exp"

    def _draw_factors(self, rng):
        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        sx = sy = scale
        if rng.random() < self.prob_stretch:
            sx *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
            sy *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
        return sx, sy


class ScaleSparseExp(ScaleExp):
    type = "scale-sparse-exp"
    sparse = True


class Translate(Augmentation):
    """Shift frames against each other; the shift adds to the flow."""

    type = "translate"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        min_size = list(cfg.get("min-size", [0, 0]))
        if len(min_size) != 2 or min_size[0] < 0 or min_size[1] < 0:
            raise ValueError("invalid min-size, expected two unsigned integers")

        delta = [int(d) for d in cfg.get("delta", [10, 10])]
        if len(delta) != 2 or delta[0] < 0 or delta[1] < 0:
            raise ValueError("invalid delta, expected two unsigned integers")

        return cls(min_size, delta)

    def __init__(self, min_size, delta):
        super().__init__()
        self.min_size = min_size
        self.delta = delta

    def get_config(self):
        return {"type": self.type, "min-size": self.min_size, "delta": self.delta}

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        assert img1.shape[:3] == img2.shape[:3]

        _, h, w, _ = img1.shape
        dx = np.clip(w - self.min_size[0], 0, self.delta[0])
        dy = np.clip(h - self.min_size[1], 0, self.delta[1])
        tx, ty = rng.integers((-dx, -dy), (dx + 1, dy + 1))

        img1 = img1[:, max(0, ty) : min(h, h + ty), max(0, tx) : min(w, w + tx)]
        img2 = img2[:, max(0, -ty) : min(h, h - ty), max(0, -tx) : min(w, w - tx)]

        if flow is not None:
            flow = flow[:, max(0, ty) : min(h, h + ty), max(0, tx) : min(w, w + tx)]
            flow = flow + np.array([tx, ty])
            valid = valid[:, max(0, ty) : min(h, h + ty), max(0, tx) : min(w, w + tx)]

        for m in meta:
            m.original_extents = ((0, img1.shape[1]), (0, img1.shape[2]))

        return img1, img2, flow, valid, meta


class Rotate(Augmentation):
    """Rotate both frames (optionally by slightly different angles).

    Flow vectors are rotated into the new frame; a differential-rotation
    correction field accounts for the angle difference between the frames
    (after DICL-Flow's RandomRotate).
    """

    type = "rotate"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        rng = cfg["range"]
        if isinstance(rng, (int, float)):
            rng = (-rng, rng)

        return cls(rng, cfg.get("deviation", 0), cfg.get("order", 2),
                   cfg.get("reshape", False), cfg.get("th-valid", 0.99))

    def __init__(self, range, deviation, order, reshape, th_valid):
        super().__init__()
        self.range = range
        self.deviation = deviation
        self.order = order
        self.reshape = reshape
        self.th_valid = th_valid

    def get_config(self):
        return {
            "type": self.type,
            "range": self.range,
            "deviation": self.deviation,
            "order": self.order,
            "reshape": self.reshape,
            "th-valid": self.th_valid,
        }

    def process(self, img1, img2, flow, valid, meta, rng=_LEGACY):
        assert img1.shape == img2.shape

        angle = rng.uniform(self.range[0], self.range[1])
        diff = rng.uniform(-self.deviation, self.deviation)
        angle1 = angle - diff / 2
        angle2 = angle + diff / 2

        args = dict(order=self.order, reshape=self.reshape, mode="constant", cval=0.0)

        img1 = np.stack([ndimage.rotate(x, angle=angle1, **args) for x in img1], axis=0)
        img2 = np.stack([ndimage.rotate(x, angle=angle2, **args) for x in img2], axis=0)

        if flow is not None:
            _, h, w, _ = flow.shape
            a = np.deg2rad(angle1)
            drad = np.deg2rad(diff)

            # angular velocity field of the frame-2-relative rotation: a point
            # at (x, y) moves by ~omega x r for small angle differences
            yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
            delta = np.stack(
                ((yy - h / 2) * drad, -(xx - w / 2) * drad), axis=-1
            )

            flow_out, valid_out = [], []
            for f, v in zip(flow, valid):
                f = ndimage.rotate(f + delta, angle=angle1, **args)
                u = np.cos(a) * f[:, :, 0] + np.sin(a) * f[:, :, 1]
                w_ = -np.sin(a) * f[:, :, 0] + np.cos(a) * f[:, :, 1]
                flow_out.append(np.stack((u, w_), axis=-1))

                vf = ndimage.rotate(v.astype(np.float32), angle=angle1, **args)
                valid_out.append(vf >= self.th_valid)

            flow = np.stack(flow_out, axis=0)
            valid = np.stack(valid_out, axis=0)

        return img1, img2, flow, valid, meta


_AUGMENTATIONS = {
    cls.type: cls
    for cls in (
        ColorJitter, ColorJitter8bit, Crop, CropCenter, Flip, NoiseNormal,
        OcclusionForward, OcclusionBackward, RestrictFlowMagnitude, Rotate,
        Scale, ScaleExp, ScaleSparse, ScaleSparseExp, Translate,
    )
}


def build_augmentation(cfg):
    ty = cfg["type"]
    if ty not in _AUGMENTATIONS:
        raise ValueError(f"unknown augmentation type '{ty}'")
    return _AUGMENTATIONS[ty].from_config(cfg)
