"""Dataset: file-pattern layouts, parameters, splits, filters, and loaders.

Covers the reference's dataset machinery (src/data/dataset.py): a dataset
spec describes on-disk file layouts via format patterns
(``'{type}/{pass}/{scene}/frame_{idx:04d}.png'``), exposes user-selectable
parameters (e.g. ``pass: clean|final`` on Sintel) that substitute into the
patterns, supports split files (one token per sample) and sample filters,
and loads images/flow through pluggable per-format loaders.

Config types round-trip: ``dataset`` collections, ``generic`` /
``generic-backwards`` / ``multi`` layouts, ``combine`` / ``exclude`` /
``file`` filters, ``generic-image`` / ``generic-flow`` loaders.
"""

from pathlib import Path

import numpy as np

from ..utils import config
from . import io
from .collection import Collection, Metadata, SampleArgs, SampleId
from .patterns import FormatPattern, to_glob


class Dataset(Collection):
    type = "dataset"

    @classmethod
    def from_config(cls, path, cfg):
        cls._typecheck(cfg)

        path = Path(path)
        spec = cfg["spec"]
        params = cfg.get("parameters", {})
        filter_ = build_filter(path, cfg.get("filter"))

        # spec may be inline or a reference to another config file; referenced
        # paths resolve relative to the referencing file
        if not isinstance(spec, dict):
            specfile = spec
            spec = config.load(path / specfile)
            path = (path / specfile).parent

        return cls._from_spec(path, spec, params, filter_)

    @classmethod
    def _from_spec(cls, path, spec, params, filter_):
        loaders = spec.get("loader", {})
        split = spec.get("split")

        return cls(
            id=spec["id"],
            name=spec["name"],
            path=Path(path) / Path(spec.get("path", ".")),
            layout=build_layout(spec["layout"]),
            split=Split.from_config(path, split) if split is not None else None,
            filter=filter_,
            param_desc=ParameterDesc.from_config(spec.get("parameters", {})),
            param_vals=params,
            image_loader=build_loader(loaders.get("image", "generic-image")),
            flow_loader=build_loader(loaders.get("flow", "generic-flow")),
        )

    def __init__(self, id, name, path, layout, split, filter, param_desc,
                 param_vals, image_loader, flow_loader):
        super().__init__()

        if not path.exists():
            raise ValueError(f"dataset root path does not exist: {path}")

        self.id = id
        self.name = name
        self.path = path
        self.layout = layout
        self.split = split
        self.filter = filter
        self.param_desc = param_desc
        self.param_vals = param_vals
        self.image_loader = image_loader
        self.flow_loader = flow_loader

        self.files = layout.build_file_list(path, param_desc, param_vals)
        if self.split is not None:
            self.files = self.split.filter(self.files, param_vals)
        if self.filter is not None:
            self.files = self.filter.filter(self.files)

    def get_config(self):
        return {
            "type": self.type,
            "spec": {
                "id": self.id,
                "name": self.name,
                "path": str(self.path),
                "layout": self.layout.get_config(),
                "split": self.split.get_config() if self.split is not None else None,
                "parameters": self.param_desc.get_config(),
                "loader": {
                    "image": self.image_loader.get_config(),
                    "flow": self.flow_loader.get_config(),
                },
            },
            "parameters": self.param_vals,
            "filter": self.filter.get_config() if self.filter is not None else None,
        }

    def __str__(self):
        return f"Dataset {{ name: '{self.name}', path: '{self.path}' }}"

    def description(self):
        return self.name

    def __getitem__(self, index):
        img1_path, img2_path, flow_path, key = self.files[index]

        img1 = self.image_loader.load(img1_path)
        img2 = self.image_loader.load(img2_path)
        assert img1.shape[:2] == img2.shape[:2]

        # test datasets may not provide ground-truth flow
        if flow_path is not None and flow_path.exists():
            flow, valid = self.flow_loader.load(flow_path)
            assert img1.shape[:2] == flow.shape[:2] == valid.shape[:2]
            flow, valid = flow[None], valid[None]
        else:
            flow, valid = None, None

        meta = Metadata(
            valid=True,
            dataset_id=self.id,
            sample_id=key,
            original_extents=((0, img1.shape[0]), (0, img1.shape[1])),
        )

        return img1[None], img2[None], flow, valid, [meta]

    def __len__(self):
        return len(self.files)


# -- layouts ----------------------------------------------------------------


class Layout:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(f"invalid layout type '{cfg['type']}', expected '{cls.type}'")

    def get_config(self):
        raise NotImplementedError

    def build_file_list(self, path, param_desc, param_vals):
        raise NotImplementedError


def _discover(path, pat_img):
    """Glob candidates and invert the image pattern over them.

    Returns (groups, fields): ``groups`` is a list of
    ``(positional_args, named_without_idx, idx)`` and ``fields`` the named
    field order (minus ``idx``).
    """
    compiled = FormatPattern(str(path / pat_img))
    fields = [f for f in compiled.named_fields if f != "idx"]

    groups = []
    for candidate in path.glob(to_glob(pat_img)):
        parsed = compiled.match(candidate)
        if parsed is None:
            continue
        positional = tuple(parsed[i] for i in compiled.positional_fields)
        named = tuple(parsed[f] for f in fields)
        groups.append((positional, named, parsed["idx"]))

    return groups, fields


def _drop_sequence_tails(groups, step):
    """Remove the final frame of every consecutive-index run.

    Image sequences are paired frame-to-next (or frame-to-previous for
    ``step=-1``); the run's last frame has no partner, so it is dropped.
    ``groups`` must be sorted so that partners are adjacent.
    """
    kept = []
    prev = None
    for pos, named, idx in groups:
        if prev is not None and prev != (pos, named, idx - step):
            del kept[-1]
        kept.append((pos, named, idx))
        prev = (pos, named, idx)

    if kept:
        del kept[-1]
    return kept


class _SequenceLayout(Layout):
    """Shared implementation of the forward/backward sequence layouts."""

    step = None  # +1: pair (idx, idx+1); -1: pair (idx, idx-1)

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg["images"], cfg["flows"], cfg["key"])

    def __init__(self, pat_img, pat_flow, pat_key):
        super().__init__()
        self.pat_img = pat_img
        self.pat_flow = pat_flow
        self.pat_key = pat_key

    def get_config(self):
        return {
            "type": self.type,
            "images": self.pat_img,
            "flows": self.pat_flow,
            "key": self.pat_key,
        }

    def build_file_list(self, path, param_desc, param_vals):
        groups, fields = _discover(path, self.pat_img)
        groups.sort(key=lambda g: (g[0], g[1], self.step * g[2]))
        groups = _drop_sequence_tails(groups, self.step)

        subs = param_desc.get_substitutions(param_vals)

        files = []
        for positional, named_vals, idx in groups:
            named = dict(zip(fields, named_vals))

            # parameter selections must agree with what was parsed from disk
            if any(k in named and named[k] != v for k, v in subs.items()):
                continue
            named.update(subs)

            img1 = self.pat_img.format(*positional, idx=idx, **named)
            img2 = self.pat_img.format(*positional, idx=idx + self.step, **named)
            flow = self.pat_flow.format(*positional, idx=idx, **named)

            key = SampleId(
                format=self.pat_key,
                img1=SampleArgs(list(positional), named | {"idx": idx}),
                img2=SampleArgs(list(positional), named | {"idx": idx + self.step}),
            )
            files.append((path / img1, path / img2, path / flow, key))

        return sorted(files, key=lambda f: str(f[3]))


class GenericLayout(_SequenceLayout):
    type = "generic"
    step = 1


class GenericBackwardsLayout(_SequenceLayout):
    type = "generic-backwards"
    step = -1


class MultiLayout(Layout):
    """Selects one of several layouts via a dataset parameter."""

    type = "multi"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        instances = {k: build_layout(v) for k, v in cfg["instances"].items()}
        return cls(cfg["parameter"], instances)

    def __init__(self, param, layouts):
        super().__init__()
        self.param = param
        self.layouts = layouts

    def get_config(self):
        return {
            "type": self.type,
            "parameter": self.param,
            "instances": {k: v.get_config() for k, v in self.layouts.items()},
        }

    def build_file_list(self, path, param_desc, param_vals):
        layout = self.layouts[param_vals[self.param]]
        return layout.build_file_list(path, param_desc, param_vals)


# -- parameters and splits --------------------------------------------------


class Parameter:
    """A user-selectable dataset parameter with pattern substitutions.

    ``sub`` is either a field name (value substitutes directly) or a mapping
    from value to a dict of field substitutions.
    """

    @classmethod
    def from_config(cls, name, cfg):
        return cls(name, cfg.get("values"), cfg.get("sub"))

    def __init__(self, name, values, sub):
        self.name = name
        self.values = values
        self.sub = sub

    def get_config(self):
        return {"values": self.values, "sub": self.sub}

    def get_substitutions(self, value):
        if self.values is not None and value not in self.values:
            raise KeyError(f"value '{value}' is not valid for parameter '{self.name}'")

        if isinstance(self.sub, str):
            return {self.sub: value}
        return dict(self.sub[value])


class ParameterDesc:
    @classmethod
    def from_config(cls, cfg):
        return cls({name: Parameter.from_config(name, c) for name, c in cfg.items()})

    def __init__(self, parameters):
        self.parameters = parameters

    def get_config(self):
        return {p.name: p.get_config() for p in self.parameters.values()}

    def get_substitutions(self, values):
        subs = {}
        for k, v in values.items():
            if k in self.parameters:
                subs.update(self.parameters[k].get_substitutions(v))
        return subs


class Split:
    """Train/test split from a token file (one token per sample, in order)."""

    @classmethod
    def from_config(cls, path, cfg):
        return cls(Path(path) / cfg["file"], dict(cfg["values"]), cfg["parameter"])

    def __init__(self, file, values, parameter):
        self.file = file
        self.values = values
        self.parameter = parameter

    def get_config(self):
        return {
            "file": str(self.file),
            "values": self.values,
            "parameter": self.parameter,
        }

    def filter(self, files, params):
        selection = params.get(self.parameter)
        if selection is None:  # no selection made: use everything
            return files

        wanted = self.values[selection]
        tokens = Path(self.file).read_text().split()
        return [f for f, tok in zip(files, tokens) if tok == wanted]


# -- filters ----------------------------------------------------------------


class Filter:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        ty = cfg["type"] if isinstance(cfg, dict) else cfg
        if ty != cls.type:
            raise ValueError(f"invalid filter type '{ty}', expected '{cls.type}'")

    def get_config(self):
        raise NotImplementedError

    def filter(self, files):
        raise NotImplementedError


class CombineFilter(Filter):
    type = "combine"

    @classmethod
    def from_config(cls, path, cfg):
        cls._typecheck(cfg)
        return cls([build_filter(path, f) for f in cfg["filters"]])

    def __init__(self, filters):
        super().__init__()
        self.filters = filters

    def get_config(self):
        return {"type": self.type, "filters": [f.get_config() for f in self.filters]}

    def filter(self, files):
        for f in self.filters:
            files = f.filter(files)
        return files


class ExcludeFilter(Filter):
    """Excludes samples whose id arguments match any of the given rules."""

    type = "exclude"

    @classmethod
    def from_config(cls, path, cfg):
        cls._typecheck(cfg)
        return cls(cfg["exclude"])

    def __init__(self, exclude):
        super().__init__()
        self.exclude = exclude

    def get_config(self):
        return {"type": self.type, "exclude": self.exclude}

    def filter(self, files):
        def excluded(file):
            args = file[3].img1.kwargs
            return any(
                all(k in args and args[k] == v for k, v in rule.items())
                for rule in self.exclude
            )

        return [f for f in files if not excluded(f)]


class FileFilter(Filter):
    """Keeps samples whose split-file token equals ``value``."""

    type = "file"

    @classmethod
    def from_config(cls, path, cfg):
        cls._typecheck(cfg)
        return cls(Path(path) / cfg["file"], str(cfg["value"]))

    def __init__(self, file, value):
        super().__init__()
        self.file = file
        self.value = value

    def get_config(self):
        return {"type": self.type, "file": str(self.file), "value": self.value}

    def filter(self, files):
        tokens = Path(self.file).read_text().split()
        return [f for f, tok in zip(files, tokens) if tok == self.value]


# -- file loaders -----------------------------------------------------------


class FileLoader:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        ty = cfg["type"] if isinstance(cfg, dict) else cfg
        if ty != cls.type:
            raise ValueError(f"invalid loader type '{ty}', expected '{cls.type}'")

    def get_config(self):
        raise NotImplementedError

    def load(self, file):
        raise NotImplementedError


class GenericImageLoader(FileLoader):
    type = "generic-image"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls()

    def get_config(self):
        return self.type

    def load(self, file):
        if file is None:
            return None

        if Path(file).suffix == ".pfm":
            img = io.read_pfm(file)
        else:
            img = io.read_image_generic(file)

        if img.ndim == 2:
            img = img[:, :, None]
        if img.shape[2] == 1:
            img = np.tile(img, (1, 1, 3))
        return img


class GenericFlowLoader(FileLoader):
    """Loads flow by extension; synthesizes a valid mask from ``uvmax``."""

    type = "generic-flow"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        uvmax = cfg.get("uvmax") if isinstance(cfg, dict) else None
        if uvmax is None:
            uvmax = (1e3, 1e3)
        elif isinstance(uvmax, (list, tuple)):
            if len(uvmax) != 2:
                raise ValueError("uvmax must be a float or a list of two floats")
            uvmax = (float(uvmax[0]), float(uvmax[1]))
        else:
            uvmax = (float(uvmax), float(uvmax))

        return cls(uvmax)

    def __init__(self, max_uv):
        super().__init__()
        self.max_uv = max_uv

    def get_config(self):
        return {"type": self.type, "uvmax": self.max_uv}

    def load(self, file):
        if file is None:
            return None, None

        file = Path(file)
        valid = None

        if file.suffix == ".pfm":
            flow = io.read_pfm(file)[:, :, :2]
        elif file.suffix == ".flo":
            flow = io.read_flow_mb(file)
        elif file.suffix == ".png":
            flow, valid = io.read_flow_kitti(file)
        else:
            raise ValueError(f"Unsupported flow file format {file.suffix}")

        flow = flow.astype(np.float32)
        if valid is None:
            fabs = np.abs(flow)
            valid = (fabs[:, :, 0] < self.max_uv[0]) & (fabs[:, :, 1] < self.max_uv[1])

        return flow, valid


# -- registries -------------------------------------------------------------

_LAYOUTS = {cls.type: cls for cls in (GenericLayout, GenericBackwardsLayout, MultiLayout)}
_FILTERS = {cls.type: cls for cls in (CombineFilter, ExcludeFilter, FileFilter)}
_LOADERS = {cls.type: cls for cls in (GenericImageLoader, GenericFlowLoader)}


def build_layout(cfg):
    ty = cfg["type"]
    if ty not in _LAYOUTS:
        raise ValueError(f"unknown layout type '{ty}'")
    return _LAYOUTS[ty].from_config(cfg)


def build_filter(path, cfg):
    if cfg is None:
        return None
    ty = cfg["type"]
    if ty not in _FILTERS:
        raise ValueError(f"unknown filter type '{ty}'")
    return _FILTERS[ty].from_config(path, cfg)


def build_loader(cfg):
    ty = cfg["type"] if isinstance(cfg, dict) else cfg
    if ty not in _LOADERS:
        raise ValueError(f"unknown loader type '{ty}'")
    return _LOADERS[ty].from_config(cfg)
