"""Data-source config loading with file-relative path resolution.

``load`` accepts a config-file path, a (path, cfg-dict) pair, or a
(path, relative-config-file) pair; nested ``source`` references inside
configs resolve relative to the file they appear in, which is what makes the
``cfg/`` graph composable (reference: src/data/config.py).
"""

from pathlib import Path

from ..utils import config
from .augment import Augment
from .combinators import Cache, Concat, Repeat, Subset
from .dataset import Dataset
from .fw_bw import ForwardsBackwardsBatch, ForwardsBackwardsEstimate
from .synth import Synth

_TYPES = {
    cls.type: cls
    for cls in (
        Dataset, Augment, Cache, Concat, Repeat, Subset,
        ForwardsBackwardsBatch, ForwardsBackwardsEstimate, Synth,
    )
}


def _dispatch(path, cfg):
    ty = cfg["type"]
    if ty not in _TYPES:
        raise ValueError(f"unknown data collection type '{ty}'")
    return _TYPES[ty].from_config(path, cfg)


def load(path, cfg=None):
    path = Path(path)

    if cfg is None:  # path is a config file; resolve relative to it
        return _dispatch(path.parent, config.load(path))

    if not isinstance(cfg, dict):  # cfg is a file path relative to `path`
        return _dispatch((path / cfg).parent, config.load(path / cfg))

    return _dispatch(path, cfg)
