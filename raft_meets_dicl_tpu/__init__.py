"""raft_meets_dicl_tpu — a TPU-native optical-flow research framework.

A brand-new JAX/XLA/Pallas/pjit implementation of the capabilities of the
PyTorch reference framework "RAFT meets DICL" (qzed/raft-meets-dicl): a
config-driven model zoo (RAFT, DICL, and RAFT+DICL hybrids), a composable
dataset pipeline with rich augmentation, multi-stage training strategies,
metric-driven checkpoint management, inspection/validation machinery, and a
full evaluation/visualization CLI.

Layout (mirrors the reference's layer map, SURVEY.md §1, redesigned TPU-first):

- ``utils/``    — config load/store, expression evaluator, seeds (numpy +
                  ``jax.random`` key discipline), logging, misc.
- ``data/``     — host-side numpy dataset pipeline (I/O, layouts,
                  augmentations, combinators). Torch-free.
- ``ops/``      — the TPU compute layer: correlation volumes, bilinear
                  sampling/warping, convex upsampling; XLA-composite
                  implementations with Pallas kernels for the hot paths.
                  This replaces the reference's fused CUDA ops
                  (matmul/grid_sample/unfold per reference
                  src/models/impls/raft.py:31,80,323).
- ``models/``   — model framework (registry, adapters, input spec) and the
                  model zoo as Flax modules with ``lax.scan`` recurrence.
- ``parallel/`` — device mesh / sharding layer: SPMD data-parallel train
                  steps over ICI via ``jax.sharding`` + ``shard_map``
                  (replaces the reference's ``nn.DataParallel``,
                  reference src/cmd/train.py:183-184).
- ``strategy/`` — multi-stage training strategies, optimizers/schedulers
                  (optax), gradient handling, checkpoint management.
- ``evaluation/`` ``metrics/`` ``inspect/`` ``visual/`` — evaluation loop,
                  metric registry, TensorBoard inspection + hooks, flow
                  visualization.
- ``telemetry/`` — run-wide structured telemetry: span timers, versioned
                  JSONL event sink (``events.jsonl`` per run), compile /
                  memory / anomaly events, report rendering.
- ``serve/``    — online inference: continuous shape-bucketed batching,
                  admission control, warm compiled-program pools, the
                  open-loop SLO load generator.
- ``cmd/``      — CLI subcommands (train / evaluate / checkpoint / gencfg
                  / serve).
"""

__version__ = "0.1.0"

from . import (  # noqa: E402
    data,
    evaluation,
    metrics,
    models,
    ops,
    parallel,
    serve,
    strategy,
    telemetry,
    utils,
    visual,
)
from . import inspect  # noqa: E402  (module name mirrors the reference)

__all__ = [
    "data", "evaluation", "inspect", "metrics", "models", "ops", "parallel",
    "serve", "strategy", "telemetry", "utils", "visual",
]
