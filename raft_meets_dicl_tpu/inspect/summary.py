"""SummaryInspector: TensorBoard observability + validation-driven checkpoints.

Capability parity with the reference inspector stack
(src/inspect/summary.py:48-663), redesigned for the jitted training loop:

- train-batch metrics read the train step's aux outputs (loss, final flow,
  optionally gradients) instead of live module state,
- validation runs a memoized jitted forward+loss step per stage and reduces
  metrics host-side, then triggers ``CheckpointManager.create`` — the only
  place checkpoints are born during training, like the reference
  (src/inspect/summary.py:372-373),
- hooks declare ``needs_intermediates``/``needs_grads`` and the inspector
  provides both (auxiliary capture-intermediates forward at the hook's
  frequency; gradients compiled into the step's aux when requested).
"""

import logging
from collections import OrderedDict, defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics, strategy, telemetry, utils, visual
from ..strategy.inspector import Inspector
from .hooks import Hook
from .writer import SummaryWriter


class MetricsGroup:
    """Frequency-gated accumulate-and-reduce over train batches
    (src/inspect/summary.py:48-93)."""

    @classmethod
    def from_config(cls, cfg):
        return cls(
            int(cfg.get("frequency", 1)),
            str(cfg.get("prefix", "")),
            [metrics.Metric.from_config(m) for m in cfg.get("metrics", [])],
        )

    def __init__(self, frequency, prefix, mtx):
        self.frequency = frequency
        self.prefix = prefix
        self.metrics = mtx
        self.values = [defaultdict(list) for _ in self.metrics]

    def get_config(self):
        return {
            "frequency": self.frequency,
            "prefix": self.prefix,
            "metrics": [m.get_config() for m in self.metrics],
        }

    @property
    def wants_gradients(self):
        return any(m.type.startswith("grad-") for m in self.metrics)

    def reset(self):
        self.values = [defaultdict(list) for _ in self.metrics]

    def compute(self, ctx_m, estimate, target, valid, loss):
        for i, metric in enumerate(self.metrics):
            for k, v in metric(ctx_m, estimate, target, valid, loss).items():
                self.values[i][k].append(v)

    def reduce(self):
        result = OrderedDict()
        for i, values in enumerate(self.values):
            for k, v in self.metrics[i].reduce(values).items():
                result[f"{self.prefix}{k}"] = v
        return result


class ImagesSpec:
    @classmethod
    def from_config(cls, cfg):
        if cfg is None:
            return None
        return cls(cfg.get("frequency", 250), cfg.get("prefix", ""))

    def __init__(self, frequency, prefix):
        self.frequency = frequency
        self.prefix = prefix

    def get_config(self):
        return {"frequency": self.frequency, "prefix": self.prefix}


class CheckpointSpec:
    @classmethod
    def from_config(cls, cfg):
        keep = cfg.get("keep", {})
        return cls(
            cfg.get("path", "checkpoints"),
            cfg.get("name", "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt"),
            cfg.get("compare", "{n_steps}"),
            keep.get("latest"),
            keep.get("best"),
        )

    def __init__(self, path, name, compare, keep_latest=None, keep_best=None):
        self.path = Path(path)
        self.name = name
        self.compare = [compare] if isinstance(compare, str) else list(compare)
        self.keep_latest = keep_latest
        self.keep_best = keep_best

    def get_config(self):
        return {
            "path": str(self.path),
            "name": self.name,
            "compare": self.compare,
            "keep": {"latest": self.keep_latest, "best": self.keep_best},
        }

    def build(self, id, base_path):
        return strategy.CheckpointManager(
            id, Path(base_path) / self.path, self.name, self.compare,
            self.keep_latest, self.keep_best,
        )


class ValidationMetricSpec:
    @classmethod
    def from_config(cls, cfg):
        return cls(
            metrics.Metric.from_config(cfg["metric"]),
            str(cfg.get("reduce", "mean")),
            bool(cfg.get("log", True)),
        )

    def __init__(self, metric, reduce, do_log):
        self.metric = metric
        self.reduce = reduce
        self.do_log = do_log

    def get_config(self):
        return {
            "reduce": self.reduce,
            "log": self.do_log,
            "metric": self.metric.get_config(),
        }

    def build(self):
        return ValidationMetric(self.metric, self.reduce, self.do_log)


class ValidationMetric:
    """Per-validation-run accumulator (src/inspect/summary.py:192-217)."""

    def __init__(self, metric, reduce, do_log):
        if reduce not in ("mean",):
            raise ValueError("unsupported reduction type")

        self.metric = metric
        self.reduce = reduce
        self.do_log = do_log
        self.values = defaultdict(list)

    def add(self, ctx_m, estimate, target, valid, loss):
        for k, v in self.metric(ctx_m, estimate, target, valid, loss).items():
            self.values[k].append(v)

    def result(self):
        return [(k, float(np.mean(vs, axis=0))) for k, vs in self.values.items()]


class ValidationImages:
    @classmethod
    def from_config(cls, cfg):
        return cls(cfg.get("enabled", True), cfg.get("prefix", "Validation/"))

    def __init__(self, enabled, prefix):
        self.enabled = enabled
        self.prefix = prefix

    def get_config(self):
        return {"enabled": self.enabled, "prefix": self.prefix}


class Validation:
    """Base: frequency int (steps) or 'epoch' | 'stage'."""

    type: Optional[str] = None
    frequency: Union[str, int]

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid validation type '{cfg['type']}', expected '{cls.type}'"
            )

    @classmethod
    def from_config(cls, cfg):
        types = {StrategyValidation.type: StrategyValidation}
        return types[cfg["type"]].from_config(cfg)

    def __init__(self, frequency):
        if not isinstance(frequency, (str, int)):
            raise ValueError(
                "frequency must be either integer or one of 'epoch', 'stage'"
            )
        if isinstance(frequency, str) and frequency not in ("epoch", "stage"):
            raise ValueError(
                "frequency must be either integer or one of 'epoch', 'stage'"
            )
        self.frequency = frequency

    def get_config(self):
        raise NotImplementedError

    def run(self, log, ctx, writer, chkpt, stage, epoch):
        raise NotImplementedError


class StrategyValidation(Validation):
    """Runs the stage's validation datasets, logs + TB-writes reduced
    metrics, and creates a checkpoint with the metric dict
    (src/inspect/summary.py:276-434)."""

    type = "strategy"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(
            cfg["frequency"],
            bool(cfg.get("checkpoint", True)),
            str(cfg.get("tb-metrics-prefix", "")),
            [ValidationMetricSpec.from_config(m) for m in cfg.get("metrics", [])],
            ValidationImages.from_config(cfg.get("images", {})),
        )

    def __init__(self, frequency, checkpoint, tb_metrics_pfx, mtx, images):
        super().__init__(frequency)
        self.checkpoint = checkpoint
        self.tb_metrics_pfx = tb_metrics_pfx
        self.metrics = mtx
        self.images = images
        self._val_steps = {}

    def get_config(self):
        return {
            "type": self.type,
            "frequency": self.frequency,
            "checkpoint": self.checkpoint,
            "tb-metrics-prefix": self.tb_metrics_pfx,
            "metrics": [m.get_config() for m in self.metrics],
            "images": self.images.get_config(),
        }

    def _val_step(self, ctx, stage):
        """Memoized (variables, batch) → (final flow, loss).

        The forward pass is the SAME registered eval program the eval
        CLI and the warmup path build (``evaluation.make_eval_fn`` +
        compile registry, keyed by the stable model id): training
        validation no longer compiles a duplicate forward for a (model,
        bucket, wire) triple the process has already paid for, and a
        warm AOT store covers it too. Only the loss reduction — a small
        program over the forward's raw output — is validation-specific.
        The returned callable exposes ``programs`` (forward, loss) so
        the sweep's compile accounting reads exact per-program counters.
        """
        from .. import compile as programs, evaluation

        model_key = evaluation.static_args_key(stage.model_args)
        loss_key = evaluation.static_args_key(stage.loss_args)
        cacheable = model_key is not None and loss_key is not None
        key = (id(ctx.model), id(ctx.loss), model_key, loss_key)
        if cacheable and key in self._val_steps:
            return self._val_steps[key]

        model, loss_fn = ctx.model, ctx.loss
        model_args = dict(stage.model_args)
        loss_args = dict(stage.loss_args)

        fwd = evaluation.make_eval_fn(
            model, model_args, model_id=getattr(ctx, "model_id", None))

        lkey = None
        if cacheable:
            # loss identity: its config when it has one (stable — the
            # val_loss program then AOT-round-trips like the forward),
            # else pinned to the object (process-local dedupe only)
            try:
                loss_id = repr(loss_fn.get_config())
            except Exception:  # noqa: BLE001 - config-less test stubs
                loss_id = programs.unstable(loss_fn)
            lkey = programs.ProgramKey(
                kind="val_loss",
                model=getattr(ctx, "model_id", None)
                or programs.unstable(model),
                flags=programs.flag_items(
                    args=loss_key, model_args=model_key, loss=loss_id))
            lprog = programs.registry().get(lkey)
        else:
            lprog = None
        if lprog is None:
            def lstep(out, flow, valid):
                result = model.get_adapter().wrap_result(
                    out, flow.shape[1:3])
                return loss_fn(model, result.output(), flow, valid,
                               **loss_args)

            lprog = programs.register_step("val_loss", jax.jit(lstep),
                                           key=lkey)
            lprog._refs = (model, loss_fn)

        def step(variables, img1, img2, flow, valid):
            out, final = fwd(variables, img1, img2)
            return final, lprog(out, flow, valid)

        step.programs = (fwd, lprog)

        if cacheable:
            self._val_steps[key] = step
        return step

    def run(self, log, ctx, writer, chkpt, stage, epoch):
        if not stage.validation:
            log.warn("no validation data specified, skipping this validation step")
            return

        # multi-process: validation (and the checkpoint it triggers) is
        # primary-only — metrics, logs, and checkpoint writes are all
        # primary-owned, the val step emits no collectives to desync on,
        # and duplicating the full sweep on every worker is wasted compute
        if jax.process_count() > 1 and jax.process_index() != 0:
            return

        chkpmetrics = {}

        for i, val in enumerate(stage.validation):
            mtx = self._evaluate_one(ctx, writer, stage, val, epoch)
            kvmetrics = {}

            writer.set_fmtargs(dict(
                n_stage=stage.index,
                id_stage=stage.id.replace("/", "."),
                n_epoch=epoch,
                n_step=ctx.step,
                id_val=val.name,
            ))

            entries = []
            for m in mtx:
                res = m.result()
                kvmetrics |= dict(res)

                for k, v in res:
                    writer.add_scalar(self.tb_metrics_pfx + k, v, ctx.step)

                if m.do_log:
                    entries += [f"{k}: {v:.4f}" for k, v in res]

            if entries:
                log.info(f"validation ({val.name}): {', '.join(entries)}")

            # first run stores the main metrics; every run also under prefix
            if i == 0:
                chkpmetrics |= kvmetrics
            chkpmetrics |= {f"{val.name}:{k}": v for k, v in kvmetrics.items()}

        if self.checkpoint:
            chkpt.create(log, ctx, stage, epoch, ctx.step, chkpmetrics)

    def _evaluate_one(self, ctx, writer, stage, val, epoch):
        images = set(val.images) if self.images.enabled else set()
        mtx = [m.build() for m in self.metrics]
        step = self._val_step(ctx, stage)

        # shape buckets (ctx.eval_buckets): quantize mixed per-sample
        # resolutions onto canonical sizes and group same-bucket samples
        # into full batches — the val step then compiles at most one
        # program per bucket instead of one per distinct shape, and the
        # extended valid mask keeps padded pixels out of every masked
        # metric and loss
        buckets = getattr(ctx, "eval_buckets", None)
        input = ctx.input.apply(val.source, buckets=buckets).jax()
        data = input.loader(batch_size=val.batch_size, shuffle=False,
                            drop_last=False,
                            group_by_shape=buckets is not None,
                            **ctx.loader_args)

        desc = f"validation ({val.name}): stage {stage.index + 1}/{len(ctx.strategy.stages)}"
        if epoch is not None:
            desc += f", epoch {epoch + 1}/{stage.data.epochs}"
        desc += f", step {ctx.step}"
        samples = utils.logging.progress(data, unit="batch", leave=False, desc=desc)

        variables = ctx.train_variables()
        part = getattr(ctx, "partitioner", None)
        if jax.process_count() > 1 or (part is not None
                                       and part.model_size > 1):
            # params live as global-mesh (possibly model-sharded) arrays;
            # localize them (committed to a local device, not host numpy
            # — numpy leaves would re-upload per batch) so the
            # process-local validation jit can't trip the partitioner
            # into emitting global-mesh collectives the other processes
            # would never join, and never computes on partially
            # replicated layouts the val step's jit has no annotations
            # for
            variables = jax.device_put(jax.device_get(variables),
                                       jax.local_devices()[0])
        ctx_m = metrics.MetricContext(lr=ctx.last_lr, params=variables["params"])

        from ..evaluation import EvalRunStats
        stats = EvalRunStats(name=f"validation:{val.name}")
        # compile accounting: exact per-program counters from the
        # registered forward + loss programs (no first-seen-shape guess,
        # no overcount on warm caches)
        progs = getattr(step, "programs", ())

        def compile_count():
            return sum(p.compiles for p in progs)

        for i, (img1, img2, flow, valid, meta) in enumerate(samples):
            batch = img1.shape[0]
            pad = val.batch_size - batch if buckets is not None else 0
            if pad > 0:
                # epoch-end bucket remainder: fill up to the full batch
                # size (reusing that bucket's compiled program) with
                # repeats of the last sample whose valid mask is cleared,
                # so the masked metrics and loss provably ignore them
                img1 = np.concatenate([img1, np.repeat(img1[-1:], pad, 0)])
                img2 = np.concatenate([img2, np.repeat(img2[-1:], pad, 0)])
                flow = np.concatenate([flow, np.repeat(flow[-1:], pad, 0)])
                valid = np.concatenate(
                    [valid, np.zeros((pad,) + valid.shape[1:], bool)])

            c0 = compile_count()

            est, loss = step(
                variables, jnp.asarray(img1), jnp.asarray(img2),
                jnp.asarray(flow), jnp.asarray(valid),
            )
            est, loss = jax.device_get((est, loss))

            compiles = compile_count() - c0
            stats.add_batch(
                img1.shape[1:3], batch, pad,
                sum((m.original_extents[0][1] - m.original_extents[0][0])
                    * (m.original_extents[1][1] - m.original_extents[1][0])
                    for m in meta),
                compiles=compiles)

            for m in mtx:
                m.add(ctx_m, est, flow, valid, loss)

            for j in images:  # expected to be a small set
                j_min, j_max = i * val.batch_size, (i + 1) * val.batch_size
                if not (j_min <= j < j_max):
                    continue

                writer.set_fmtargs(dict(
                    n_stage=stage.index,
                    id_stage=stage.id.replace("/", "."),
                    n_epoch=epoch,
                    n_step=ctx.step,
                    img_idx=j,
                    id_val=val.name,
                ))
                write_images(writer, self.images.prefix, j - j_min, img1, img2,
                             flow, est, valid, meta, ctx.step)

        stats.emit()
        return mtx


class InspectorSpec:
    @classmethod
    def from_config(cls, cfg):
        return cls(
            [MetricsGroup.from_config(m) for m in cfg.get("metrics", [])],
            [Hook.from_config(h) for h in cfg.get("hooks", [])],
            ImagesSpec.from_config(cfg.get("images")),
            CheckpointSpec.from_config(cfg.get("checkpoints", {})),
            [Validation.from_config(v) for v in cfg.get("validation", [])],
            cfg.get("tensorboard", {}).get("path", "tb.{id_model}"),
        )

    def __init__(self, mtx, hooks, images, checkpoints, validation, tb_path):
        self.metrics = mtx
        self.hooks = hooks
        self.images = images
        self.checkpoints = checkpoints
        self.validation = validation
        self.tb_path = tb_path

    def get_config(self):
        return {
            "metrics": [g.get_config() for g in self.metrics],
            "hooks": [h.get_config() for h in self.hooks],
            "images": self.images.get_config() if self.images is not None else None,
            "checkpoints": self.checkpoints.get_config(),
            "validation": [v.get_config() for v in self.validation],
            "tensorboard": {"path": self.tb_path},
        }

    def build(self, id, base_path):
        base_path = Path(base_path)
        chkpts = self.checkpoints.build(id, base_path)

        args = {"id_model": id.replace("/", "_").replace("-", ".")}
        path = base_path / self.tb_path.format_map(args)
        logging.info(f"writing tensorboard summary to '{path}'")
        writer = SummaryWriter(path)

        insp = SummaryInspector(writer, self.metrics, self.hooks, self.images,
                                chkpts, self.validation)
        return insp, chkpts


class SummaryInspector(Inspector):
    def __init__(self, writer, mtx, hooks, images, checkpoints, validation):
        super().__init__()

        self.writer = writer
        self.metrics = mtx
        self.hooks = list(hooks)
        self.images = images
        self.checkpoints = checkpoints

        self.val_step = [v for v in validation if not isinstance(v.frequency, str)]
        self.val_epoch = [v for v in validation if v.frequency == "epoch"]
        self.val_stage = [v for v in validation if v.frequency == "stage"]

        self.batch_index = 0
        self._capture_fns = {}

    @property
    def wants_gradients(self):
        """The training context compiles gradients into the step's aux
        output iff observability asks for them."""
        return (
            any(g.wants_gradients for g in self.metrics)
            or any(h.needs_grads for h in self.hooks)
        )

    def wants_host_images(self, step):
        """Pixel values are only read on intermediates-capture and
        image-dump steps — the wire-format trainer skips the host decode
        everywhere else."""
        if any(h.active and h.needs_intermediates
               and step % getattr(h, "frequency", 1) == 0
               for h in self.hooks):
            return True
        return (self.images is not None
                and step % self.images.frequency == 0)

    # -- hook phase management (src/inspect/summary.py:530-562) -------------

    def setup(self, log, ctx):
        for hook in self.hooks:
            hook.active = False
        for hook in self.hooks:
            if hook.when in ("training", "all"):
                hook.register(ctx, self.writer)

    def _pre_validation(self, log, ctx):
        for hook in self.hooks:
            if hook.when == "training":
                hook.active = False
            elif not hook.active:
                hook.register(ctx, self.writer)

    def _post_validation(self, log, ctx):
        for hook in self.hooks:
            if hook.when == "validation":
                hook.active = False
            elif not hook.active:
                hook.register(ctx, self.writer)

    # -- intermediates capture ----------------------------------------------

    def _capture_fn(self, ctx, stage):
        from ..evaluation import static_args_key

        args_key = static_args_key(stage.model_args)
        key = (id(ctx.model), ctx.model.frozen_batchnorm, args_key)
        if args_key is not None and key in self._capture_fns:
            return self._capture_fns[key]

        model = ctx.model
        args = model.arguments | stage.model_args

        def fn(variables, img1, img2):
            _, mutated = model.module.apply(
                variables, img1, img2, train=False,
                frozen_bn=model.frozen_batchnorm,
                capture_intermediates=True, mutable=["intermediates"], **args,
            )
            return mutated["intermediates"]

        fn = telemetry.instrument_jit("capture_intermediates", jax.jit(fn))

        if args_key is not None:
            self._capture_fns[key] = fn
        return fn

    def _run_intermediate_hooks(self, log, ctx, stage, img1, img2):
        hooks = [
            h for h in self.hooks
            if h.active and h.needs_intermediates
            and ctx.step % getattr(h, "frequency", 1) == 0
        ]
        if not hooks:
            return

        fn = self._capture_fn(ctx, stage)
        inter = jax.device_get(
            fn(ctx.train_variables(), jnp.asarray(img1), jnp.asarray(img2))
        )
        for h in hooks:
            h.on_intermediates(log, ctx, inter)

    # -- inspector callbacks -------------------------------------------------

    def _set_fmtargs(self, ctx, stage, epoch=None):
        self.writer.set_fmtargs(dict(
            n_stage=stage.index,
            id_stage=stage.id.replace("/", "."),
            n_epoch=epoch,
            n_step=ctx.step,
        ))

    def on_batch_start(self, log, ctx, stage, epoch, i, img1, img2, target,
                       valid, meta):
        self._set_fmtargs(ctx, stage, epoch)

    def on_batch(self, log, ctx, stage, epoch, i, img1, img2, target, valid,
                 meta, result, loss):
        final = result.final()
        grads = result.aux.get("grads") if hasattr(result, "aux") else None

        ctx_m = metrics.MetricContext(
            lr=ctx.last_lr,
            params=ctx.state.params if ctx.state is not None else None,
            grads=grads,
        )

        for m in self.metrics:
            if ctx.step % m.frequency != 0:
                continue
            m.compute(ctx_m, final, target, valid, loss)

        for h in self.hooks:
            if h.active and h.needs_grads and grads is not None:
                h.on_grads(log, ctx, grads)

        # first micro-batch only: under gradient accumulation ctx.step stays
        # constant across the group, and the capture forward is expensive
        if self.batch_index == 0:
            self._run_intermediate_hooks(log, ctx, stage, img1, img2)

        # dump images (first sample, first micro-batch when accumulating)
        if (self.images is not None and ctx.step % self.images.frequency == 0
                and self.batch_index == 0):
            write_images(self.writer, self.images.prefix, 0, img1, img2,
                         target, np.asarray(final), valid, meta, ctx.step)

        self.batch_index += 1

    def on_step_start(self, log, ctx, stage, epoch, i):
        self.batch_index = 0
        for m in self.metrics:
            m.reset()

    def on_step_end(self, log, ctx, stage, epoch, i):
        for m in self.metrics:
            for k, v in m.reduce().items():
                self.writer.add_scalar(k, v, ctx.step)
            m.reset()

        # mirror the telemetry step record (emitted just before this
        # callback) into the TB scalars, so phase timings sit next to the
        # training curves without opening the JSONL
        ev = telemetry.get().last_step
        if ev is not None and ev.get("step") == ctx.step:
            for name, secs in ev["phases"].items():
                self.writer.add_scalar(f"Telemetry/Phase/{name}",
                                       secs * 1e3, ctx.step)
            self.writer.add_scalar("Telemetry/StepTimeMs",
                                   ev["step_time"] * 1e3, ctx.step)
            self.writer.add_scalar("Telemetry/StepsPerSecEma",
                                   ev["throughput_ema"], ctx.step)

        due = [v for v in self.val_step
               if ctx.step > 0 and ctx.step % v.frequency == 0]
        if due:
            self._pre_validation(log, ctx)
            for val in due:
                val.run(log, ctx, self.writer, self.checkpoints, stage, epoch)
            self._post_validation(log, ctx)

    def on_epoch_start(self, log, ctx, stage, epoch):
        self._set_fmtargs(ctx, stage, epoch)

    def on_epoch(self, log, ctx, stage, epoch):
        if self.val_epoch:
            self._pre_validation(log, ctx)
            for val in self.val_epoch:
                val.run(log, ctx, self.writer, self.checkpoints, stage, epoch)
            self._post_validation(log, ctx)

    def on_stage_start(self, log, ctx, stage):
        self._set_fmtargs(ctx, stage)

    def on_stage(self, log, ctx, stage):
        if self.val_stage:
            self._pre_validation(log, ctx)
            for val in self.val_stage:
                val.run(log, ctx, self.writer, self.checkpoints, stage, None)
            self._post_validation(log, ctx)


def write_images(writer, pfx, i, img1, img2, target, estimate, valid, meta,
                 step, occlusion=None, confidence=None):
    """Un-pad, color-code, and write one sample's images to TB
    (src/inspect/summary.py:666-705). Inputs are NHWC host arrays.

    ``occlusion``/``confidence`` are optional forwards-backwards product
    maps (NHW); when provided they are written as extra images under the
    same prefix, so existing TB mirrors see exactly the original four
    tags unless a caller opts in."""
    (h0, h1), (w0, w1) = meta[i].original_extents

    i1 = (np.asarray(img1[i]) + 1.0) / 2.0
    i2 = (np.asarray(img2[i]) + 1.0) / 2.0
    ft = np.asarray(target[i])
    fe = np.asarray(estimate[i])
    mask = np.asarray(valid[i], bool)

    i1, i2 = i1[h0:h1, w0:w1], i2[h0:h1, w0:w1]
    ft, fe = ft[h0:h1, w0:w1], fe[h0:h1, w0:w1]
    mask = mask[h0:h1, w0:w1]

    # shared motion scale across estimate and ground truth; invalid pixels
    # (masked out or non-finite, e.g. KITTI sparse-GT sentinels) must not
    # inflate or NaN the scale
    def motion_max(f, m=None):
        norm = np.linalg.norm(f, axis=-1)
        if m is not None:
            norm = norm[m]
        norm = norm[np.isfinite(norm)]
        return float(norm.max()) if norm.size else 0.0

    mrm = max(motion_max(ft, mask), motion_max(fe), 1e-5)

    ft = visual.flow_to_rgba(ft, mrm=mrm, mask=mask)
    fe = visual.flow_to_rgba(fe, mrm=mrm)

    writer.add_image(f"{pfx}img1", i1, step, dataformats="HWC")
    writer.add_image(f"{pfx}img2", i2, step, dataformats="HWC")
    writer.add_image(f"{pfx}flow-gt", ft, step, dataformats="HWC")
    writer.add_image(f"{pfx}flow-est", fe, step, dataformats="HWC")

    if occlusion is not None:
        occ = np.asarray(occlusion[i], bool)[h0:h1, w0:w1]
        rgba = visual.occlusion_overlay(i1, occ)
        writer.add_image(f"{pfx}fwbw-occlusion", rgba, step,
                         dataformats="HWC")
    if confidence is not None:
        conf = np.asarray(confidence[i])[h0:h1, w0:w1]
        rgba = visual.confidence_to_rgba(conf)
        writer.add_image(f"{pfx}fwbw-confidence", rgba, step,
                         dataformats="HWC")
