"""Inspector config loading (reference src/inspect/config.py:7)."""

from .. import utils
from . import summary


def load(cfg):
    if not isinstance(cfg, dict):
        return summary.InspectorSpec.from_config(utils.config.load(cfg))
    return summary.InspectorSpec.from_config(cfg)
