"""Anomaly detection: non-finite / absurdly large activations or gradients.

Capability parity with the reference detectors
(src/inspect/hooks/anomaly.py:16-246): on trigger, a warning names the
offending tensor and a rolling set of at most ``max-checkpoints`` debug
checkpoints is dumped. Activation checks read the captured-intermediates
tree of the auxiliary forward pass; gradient checks read the train step's
gradient pytree (compiled in when this hook is configured).
"""

from datetime import datetime

import numpy as np

from ...metrics.functional import tree_named_leaves
from .common import Hook, flatten_intermediates

_DEFAULT_CHKPT_ACTIVATION = "anomaly_in_activation-b{n_step}.ckpt"
_DEFAULT_CHKPT_GRADIENT = "anomaly_in_gradient-b{n_step}.ckpt"


class _AnomalyDetector(Hook):
    def __init__(self, large, checkpoint, checkpoint_fmt, checkpoint_max):
        super().__init__("training")
        self.large = float(large)
        self.checkpoint = bool(checkpoint)
        self.checkpoint_fmt = checkpoint_fmt
        self.checkpoint_max = int(checkpoint_max)
        self.writer = None
        self._chkpts = []
        self._dumped_step = None

    def get_config(self):
        return {
            "type": self.type,
            "large": self.large,
            "checkpoint": self.checkpoint,
            "checkpoint-fmt": self.checkpoint_fmt,
            "checkpoint-max": self.checkpoint_max,
        }

    def register(self, ctx, writer):
        self.writer = writer
        return super().register(ctx, writer)

    def _check(self, log, ctx, kind, named):
        for name, arr in named:
            arr = np.asarray(arr)
            if not np.issubdtype(arr.dtype, np.floating):
                continue

            reason = None
            if not np.all(np.isfinite(arr)):
                reason = "non-finite"
            elif np.abs(arr).max() > self.large:
                reason = "large"

            if reason is not None:
                log.warn(
                    f"{kind} anomaly detected: {reason} value detected in "
                    f"'{name}', shape {arr.shape}"
                )
                self._dump_chkpt(log, ctx)

    def _dump_chkpt(self, log, ctx):
        # at most one dump per training step, rolling retention
        if not self.checkpoint or self._dumped_step == ctx.step:
            return

        from ...strategy import checkpoint

        path = ctx.path / self.writer.fmt(self.checkpoint_fmt)
        log.info(f"saving checkpoint to {path}")

        chkpt = checkpoint.Checkpoint(
            model=ctx.model_id,
            iteration=checkpoint.Iteration(
                ctx.current_stage.index, ctx.current_epoch, ctx.step
            ),
            metrics=None,
            state=checkpoint.State(
                model=ctx.train_variables(),
                optimizer=ctx.opt_state(),
                scaler=dict(ctx.scaler or {}),
                lr_sched_inst=[s.state_dict() for s in ctx.lr_sched_inst or []],
                lr_sched_epoch=[s.state_dict() for s in ctx.lr_sched_epoch or []],
            ),
            metadata={
                "timestamp": datetime.now().isoformat(),
                "source": "training",
            },
        )
        chkpt.save(path)

        self._chkpts.append(path)
        self._dumped_step = ctx.step

        while len(self._chkpts) > self.checkpoint_max:
            self._chkpts.pop(0).unlink(missing_ok=True)


class ActivationAnomalyDetector(_AnomalyDetector):
    type = "anomalydetect-activation"
    needs_intermediates = True

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(
            cfg.get("large", 1.0e10),
            cfg.get("save-checkpoint", False),
            cfg.get("checkpoint-fmt", _DEFAULT_CHKPT_ACTIVATION),
            cfg.get("max-checkpoints", 10),
            int(cfg.get("frequency", 1)),
        )

    def __init__(self, large=1.0e10, checkpoint=False,
                 checkpoint_fmt=_DEFAULT_CHKPT_ACTIVATION, checkpoint_max=10,
                 frequency=1):
        super().__init__(large, checkpoint, checkpoint_fmt, checkpoint_max)
        self.frequency = frequency

    def get_config(self):
        return super().get_config() | {"frequency": self.frequency}

    def on_intermediates(self, log, ctx, intermediates):
        self._check(log, ctx, "activation", flatten_intermediates(intermediates))


class GradientAnomalyDetector(_AnomalyDetector):
    type = "anomalydetect-gradient"
    needs_grads = True

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(
            cfg.get("large", 1.0e10),
            cfg.get("save-checkpoint", False),
            cfg.get("checkpoint-fmt", _DEFAULT_CHKPT_GRADIENT),
            cfg.get("max-checkpoints", 10),
        )

    def __init__(self, large=1.0e10, checkpoint=False,
                 checkpoint_fmt=_DEFAULT_CHKPT_GRADIENT, checkpoint_max=10):
        super().__init__(large, checkpoint, checkpoint_fmt, checkpoint_max)

    def on_grads(self, log, ctx, grads):
        self._check(log, ctx, "gradient", tree_named_leaves(grads))
