from . import activation, anomaly, common
from .common import Handle, Hook, flatten_intermediates

__all__ = ["activation", "anomaly", "common", "Handle", "Hook",
           "flatten_intermediates"]
