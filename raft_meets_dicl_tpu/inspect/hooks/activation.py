"""Per-module activation statistics (mean/var) to TensorBoard.

Capability parity with the reference's forward-hook version
(src/inspect/hooks/activation.py:6-66); here the activations arrive as a
flax ``capture_intermediates`` tree from an auxiliary forward pass run at
``frequency`` (the torch version pays the stats on every forward; the jit
version pays a full extra forward but only when sampled).
"""

from typing import List

import numpy as np

from .common import Hook, flatten_intermediates


class ActivationStats(Hook):
    type = "activation-stats"
    needs_intermediates = True

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(
            cfg["modules"],
            cfg.get("prefix", "Train:S{n_stage}:{id_stage}/ActivationStats/"),
            int(cfg.get("frequency", 100)),
        )

    def __init__(self, modules: List[str],
                 prefix: str = "Train:S{n_stage}:{id_stage}/ActivationStats/",
                 frequency: int = 100):
        super().__init__("training")
        self.modules = list(modules)
        self.prefix = prefix
        self.frequency = frequency
        self.writer = None

    def get_config(self):
        return {
            "type": self.type,
            "prefix": self.prefix,
            "modules": self.modules,
            "frequency": self.frequency,
        }

    def register(self, ctx, writer):
        self.writer = writer
        return super().register(ctx, writer)

    def on_intermediates(self, log, ctx, intermediates):
        named = flatten_intermediates(intermediates)

        for target in self.modules:
            matches = [(n, a) for n, a in named
                       if n == target or n.startswith(target + ".")]
            for i, (_, act) in enumerate(matches):
                act = np.asarray(act)
                self.writer.add_scalar(
                    f"{self.prefix}{target}.{i}/mean", float(act.mean()), ctx.step
                )
                self.writer.add_scalar(
                    f"{self.prefix}{target}.{i}/var", float(act.var()), ctx.step
                )
