"""Hook protocol: config-constructible observers of the training process.

TPU-native redesign of the reference's torch-module hooks
(src/inspect/hooks/common.py:20-53). A pure jitted program has no place to
attach callbacks at runtime, so a hook instead *declares* what it needs and
the inspector provides it:

- ``needs_intermediates``: the inspector runs an auxiliary forward pass with
  flax ``capture_intermediates`` at the hook's frequency and hands the hook
  the captured activations tree (``on_intermediates``),
- ``needs_grads``: the train step is compiled with gradients in its aux
  output and the hook receives the pytree every step (``on_grads``).

``when`` ('training' | 'validation' | 'all') gates which phases a hook is
active in, matching the reference's register/remove swapping
(src/inspect/summary.py:530-562). ``register``/``Handle.remove`` keep the
same activation lifecycle shape.
"""


class Handle:
    def __init__(self, hook):
        self.hook = hook

    def remove(self):
        self.hook.active = False


class Hook:
    type = None
    needs_intermediates = False
    needs_grads = False

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(
                f"invalid hook type '{cfg['type']}', expected '{cls.type}'"
            )

    @classmethod
    def from_config(cls, cfg):
        from . import activation, anomaly

        types = [
            activation.ActivationStats,
            anomaly.ActivationAnomalyDetector,
            anomaly.GradientAnomalyDetector,
        ]
        types = {t.type: t for t in types}

        return types[cfg["type"]].from_config(cfg)

    def __init__(self, when):
        if when not in ("training", "validation", "all"):
            raise ValueError(f"invalid hook attribute 'when': '{when}'")
        self.when = when
        self.active = False

    def get_config(self):
        raise NotImplementedError

    def register(self, ctx, writer) -> Handle:
        self.active = True
        return Handle(self)

    def on_intermediates(self, log, ctx, intermediates):
        """Called with the captured-activations tree when active."""

    def on_grads(self, log, ctx, grads):
        """Called with the gradient pytree when active."""


def flatten_intermediates(tree, prefix=""):
    """Flatten a flax intermediates collection into [(dotted-name, array)].

    Capture entries appear as ``{module: {...: {'__call__': (value,)}}}``;
    tuple wrappers are unwrapped, tuple/list outputs enumerated.
    """
    out = []

    def walk(node, name):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{name}.{k}" if name and k != "__call__" else name or k)
        elif isinstance(node, (tuple, list)):
            if len(node) == 1:
                walk(node[0], name)
            else:
                for i, v in enumerate(node):
                    walk(v, f"{name}.{i}")
        elif node is not None and hasattr(node, "shape"):
            out.append((name, node))

    walk(tree, prefix)
    return out
