"""TensorBoard event writing with ``{key}``-templated tags.

The reference subclasses torch's SummaryWriter (src/inspect/summary.py:32-45);
here the writer sits directly on the ``tensorboard`` package's event-file
writer — scalars and PNG-encoded images, no torch in the training path.
"""

import time

import cv2
import numpy as np


class KvFormatter:
    """format_map with late-bound arguments (src/inspect/summary.py:21-29)."""

    def __init__(self, fmtargs={}):
        self.fmtargs = dict(fmtargs)

    def set_fmtargs(self, fmtargs):
        self.fmtargs = dict(fmtargs)

    def __call__(self, string):
        return string.format_map(self.fmtargs)


class SummaryWriter:
    """Writes TB event files; tags are formatted through a KvFormatter.

    Keys may contain ``{n_stage}``/``{id_stage}``/``{n_epoch}``/``{n_step}``/
    ``{id_val}``/``{img_idx}`` placeholders bound via ``set_fmtargs`` before
    each write, exactly like the reference writer.
    """

    def __init__(self, log_dir):
        from tensorboard.summary.writer.event_file_writer import EventFileWriter

        self.log_dir = str(log_dir)
        self._writer = EventFileWriter(self.log_dir)
        self.fmt = KvFormatter()

    def set_fmtargs(self, fmtargs):
        self.fmt.set_fmtargs(fmtargs)

    def _add_event(self, summary, step):
        from tensorboard.compat.proto import event_pb2

        event = event_pb2.Event(summary=summary)
        event.wall_time = time.time()
        if step is not None:
            event.step = int(step)
        self._writer.add_event(event)

    def add_scalar(self, key, value, step=None):
        from tensorboard.compat.proto import summary_pb2

        summary = summary_pb2.Summary(
            value=[summary_pb2.Summary.Value(
                tag=self.fmt(key), simple_value=float(value),
            )]
        )
        self._add_event(summary, step)

    def add_image(self, key, img, step=None, dataformats="HWC"):
        """``img``: float [0, 1] or uint8; HWC with 1/3/4 channels (or CHW
        when ``dataformats='CHW'``)."""
        from tensorboard.compat.proto import summary_pb2

        img = np.asarray(img)
        if dataformats == "CHW":
            img = np.transpose(img, (1, 2, 0))
        elif dataformats != "HWC":
            raise ValueError(f"unsupported dataformats '{dataformats}'")

        if img.ndim == 2:
            img = img[..., None]
        if img.dtype != np.uint8:
            img = (np.clip(img, 0.0, 1.0) * 255.0).astype(np.uint8)

        channels = img.shape[-1]
        if channels == 3:
            encoded = cv2.imencode(".png", img[..., ::-1])[1].tobytes()
        elif channels == 4:
            bgra = img[..., [2, 1, 0, 3]]
            encoded = cv2.imencode(".png", bgra)[1].tobytes()
        else:
            encoded = cv2.imencode(".png", img)[1].tobytes()

        summary = summary_pb2.Summary(
            value=[summary_pb2.Summary.Value(
                tag=self.fmt(key),
                image=summary_pb2.Summary.Image(
                    height=img.shape[0], width=img.shape[1],
                    colorspace=channels,
                    encoded_image_string=encoded,
                ),
            )]
        )
        self._add_event(summary, step)

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()
