from . import config, hooks, summary, writer
from .config import load
from .summary import InspectorSpec, SummaryInspector
from .writer import SummaryWriter

__all__ = ["config", "hooks", "summary", "writer", "load", "InspectorSpec",
           "SummaryInspector", "SummaryWriter"]
