"""Host→device wire format for input batches.

The input pipeline's dominant cost on remote/tunneled backends is the
host→device transfer of the batch (PERF.md). This module defines how a
batch crosses that boundary: images ship in a compact dtype (``f32`` raw
floats, ``bf16``, or quantized ``u8``), flow optionally in half precision,
and valid masks optionally bit-packed — and the clip/range normalization
that ``models.input.Input`` otherwise performs on the host moves inside
the jitted step (``decode``), so the host never materializes a second
normalized f32 copy and the device unpacks the wire format on the VPU
essentially for free.

Numerical contract (exercised by tests/test_wire.py):

- ``f32`` wire is exact up to float rounding of the normalization itself
  (same multiply/add, done by XLA instead of numpy): model outputs match
  the host-normalized path to ~1e-5.
- ``bf16`` wire quantizes image values to 8 mantissa bits (≤ 2^-9
  relative); on the mixed-precision models the first convolution casts to
  bf16 anyway, so effective numerics are unchanged. Flow targets ride in
  IEEE f16 (≤ 2^-11 relative, values clamped to ±6e4): loss values match
  to ~1e-2 relative, model outputs (which never see flow) to bf16 noise.
- ``u8`` wire quantizes images to 256 levels over the clip interval
  (≤ 1/510 of the clip span per value) — the coarsest, smallest format.

Wire dtypes per preset (bytes per pixel at the training contract of two
RGB images + 2-channel flow + valid):

    preset   images      flow   valid       B/px    vs f32
    f32      float32×6   f32×2  bool        33.0    1.0×
    bf16     bfloat16×6  f16×2  packed      16.125  2.05×
    u8       uint8×6     f16×2  packed      10.125  3.26×
"""

import numpy as np

# f16 finite range is ±65504; flow values beyond it only occur as the
# FLOW_INF clamp markers on invalid pixels — re-clamp so they stay finite
# (inf * 0-mask would poison the loss with NaNs)
_F16_FLOW_LIMIT = 6.0e4

_IMAGE_DTYPES = ("f32", "bf16", "u8")
_FLOW_DTYPES = ("f32", "f16")

PRESETS = {
    "f32": dict(images="f32", flow="f32", pack_valid=False),
    "bf16": dict(images="bf16", flow="f16", pack_valid=True),
    "u8": dict(images="u8", flow="f16", pack_valid=True),
}


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


class WireFormat:
    """Encode (host) / decode (device) contract for one batch layout.

    ``clip``/``range`` are the model's input normalization (from
    ``InputSpec``); ``decode`` applies them on device, so sources feeding
    a wire-format adapter must *not* normalize on the host
    (``InputSpec.apply(..., normalize=False)``).
    """

    @classmethod
    def from_config(cls, cfg, clip=(0.0, 1.0), range=(-1.0, 1.0)):
        """Build from a preset name ('f32'/'bf16'/'u8') or a mapping with
        explicit ``images``/``flow``/``pack-valid`` keys."""
        if cfg is None:
            return None
        if isinstance(cfg, str):
            if cfg not in PRESETS:
                raise ValueError(
                    f"unknown wire-format preset '{cfg}', "
                    f"expected one of {', '.join(PRESETS)}")
            cfg = PRESETS[cfg]
        return cls(
            images=cfg.get("images", "f32"),
            flow=cfg.get("flow", cfg.get("flow-dtype", "f32")),
            pack_valid=bool(cfg.get("pack-valid", cfg.get("pack_valid", False))),
            clip=clip, range=range,
        )

    def __init__(self, images="f32", flow="f32", pack_valid=False,
                 clip=(0.0, 1.0), range=(-1.0, 1.0)):
        if images not in _IMAGE_DTYPES:
            raise ValueError(f"invalid wire image dtype '{images}', "
                             f"expected one of {_IMAGE_DTYPES}")
        if flow not in _FLOW_DTYPES:
            raise ValueError(f"invalid wire flow dtype '{flow}', "
                             f"expected one of {_FLOW_DTYPES}")
        self.images = images
        self.flow = flow
        self.pack_valid = bool(pack_valid)
        self.clip = (float(clip[0]), float(clip[1]))
        self.range = (float(range[0]), float(range[1]))

    def get_config(self):
        return {
            "images": self.images,
            "flow": self.flow,
            "pack-valid": self.pack_valid,
        }

    def bound(self, clip, range):
        """Copy with the normalization parameters of an ``InputSpec``."""
        return WireFormat(self.images, self.flow, self.pack_valid,
                          clip=clip, range=range)

    def describe(self):
        return (f"images={self.images}, flow={self.flow}, "
                f"valid={'packed' if self.pack_valid else 'bool'}")

    def image_dtype(self):
        """The numpy dtype image arrays take on the wire (what warmup
        dummies and serving buffers must be created in)."""
        if self.images == "bf16":
            return _bf16()
        if self.images == "u8":
            return np.dtype(np.uint8)
        return np.dtype(np.float32)

    # -- host side (numpy) --------------------------------------------------

    def encode_image(self, img):
        """One un-normalized image batch → wire dtype (numpy)."""
        if self.images == "bf16":
            return np.asarray(img, _bf16())
        if self.images == "u8":
            lo, hi = self.clip
            q = (np.asarray(img, np.float32) - lo) * (255.0 / (hi - lo))
            return np.clip(np.rint(q), 0.0, 255.0).astype(np.uint8)
        return np.ascontiguousarray(img, np.float32)

    def encode_flow(self, flow):
        if flow is None or self.flow == "f32":
            return flow
        return np.clip(flow, -_F16_FLOW_LIMIT, _F16_FLOW_LIMIT).astype(
            np.float16)

    def encode_valid(self, valid):
        if valid is None or not self.pack_valid:
            return valid
        return np.packbits(np.asarray(valid, bool), axis=-1)

    def encode_batch(self, batch):
        """(img1, img2, flow, valid) with wire images → full wire tuple.

        Images are expected to already be in wire dtype (the adapter
        encodes them at decode time, inside the loader workers); this
        applies the flow/valid compression right before device placement.
        """
        img1, img2, flow, valid = batch
        return (img1, img2, self.encode_flow(flow), self.encode_valid(valid))

    def nbytes(self, batch):
        """Total bytes of a wire tuple (the per-step transfer volume)."""
        return int(sum(a.nbytes for a in batch if a is not None))

    def decode_images_host(self, img):
        """Wire image batch → normalized f32 on the *host* (numpy).

        The numpy mirror of the device-side decode, for consumers that
        need pixel values host-side (TB image dumps, eval flow images).
        """
        lo, hi = self.clip
        rmin, rmax = self.range
        if self.images == "u8":
            scale = (hi - lo) / 255.0
            x = np.asarray(img, np.float32) * scale + lo
        else:
            x = np.clip(np.asarray(img, np.float32), lo, hi)
        return (rmax - rmin) * x + rmin

    # -- device side (inside jit) -------------------------------------------

    def decode_image(self, img):
        import jax.numpy as jnp

        lo, hi = self.clip
        rmin, rmax = self.range
        if self.images == "u8":
            x = img.astype(jnp.float32) * ((hi - lo) / 255.0) + lo
        else:
            x = jnp.clip(img.astype(jnp.float32), lo, hi)
        return (rmax - rmin) * x + rmin

    def decode(self, img1, img2, flow=None, valid=None):
        """Wire tuple → (img1, img2, flow, valid) in compute dtypes.

        Runs inside the jitted train/eval step: images dequantize +
        normalize, flow widens to f32, packed valid masks unpack to bool
        at the image width.
        """
        import jax.numpy as jnp

        w = img1.shape[2]
        img1 = self.decode_image(img1)
        img2 = self.decode_image(img2)
        if flow is not None and flow.dtype != jnp.float32:
            flow = flow.astype(jnp.float32)
        if valid is not None and self.pack_valid:
            valid = jnp.unpackbits(valid, axis=-1, count=w).astype(bool)
        return img1, img2, flow, valid
