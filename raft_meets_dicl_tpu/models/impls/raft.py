"""RAFT baseline (``raft/baseline``), TPU-native.

Re-design of the reference implementation (src/models/impls/raft.py, itself
after Teed & Deng's RAFT) in Flax/JAX:

- the all-pairs correlation volume + pyramid + windowed lookup live in
  ``ops.corr`` (einsum on the MXU + vectorized gathers, raft.py:15-95),
- the iterative GRU update loop is a single ``nn.scan`` over the
  ``(hidden, coords)`` carry (raft.py:401-428's python loop) — one compiled
  step body instead of an unrolled graph,
- per-iteration gradient detaches (coords, flow input, optional corr) map
  to ``lax.stop_gradient``,
- layout is NHWC throughout; flow tensors are (B, H, W, 2) with
  channel 0 = x.

Static switches (``iterations``, ``upnet``, ``corr_flow``,
``corr_grad_stop``, ``mask_costs``, ``return_state``) are python-level
arguments: changing them recompiles, matching the per-stage argument
override model.

Iteration-ladder continuation: ``flow_init``/``hidden_init`` seed the
recurrence carry at the 1/8 grid and ``return_state=True`` returns the
final carry alongside the flow list, so ``iterations=12`` can run as
chained shorter programs (4+4+4) with ``(flow, hidden)`` handed between
them — each rung recomputes the encoders/pyramid (deterministic, same
images), and the carry re-entry is exact: the scan body's first action
is ``flow = coords1 - coords0`` with ``coords1 = coords0 + flow_init``,
an integer-grid add/subtract round-trip that is lossless in f32 for any
flow magnitude a real pair produces. The returned ``delta`` (mean-pixel
L2 of the last iteration's flow change, per sample) is the cheap
convergence probe the serving ladder reads *between* programs — no
data-dependent control flow ever enters the jit.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops import quant as quant_ops
from ...ops.corr import (
    correlation_pyramid_direct,
    lookup_pyramid_levels,
    window_delta,
)
from ...ops.upsample import convex_upsample_8x
from .. import common
from ..common.blocks.dicl import DisplacementAwareProjection
from ..common.util import ConvParams
from ..common.grid import coordinate_grid
from ..common.hsup import upsample2d_bilinear
from ..config import register_loss, register_model
from ..model import Loss, Model, ModelAdapter, Result


class SoftArgMaxFlowRegression(nn.Module):
    """Cost → flow readout: softmax-weighted displacement sum per level.

    Input: lookup output (B, H, W, L*(2r+1)²), channels (level, dx, dy).
    Returns a list of per-level flow deltas (B, H, W, 2), scaled 2^level.
    """

    num_levels: int
    radius: int
    temperature: float = 1.0
    dap: bool = False

    @nn.compact
    def __call__(self, corr):
        # ``corr`` is either the flat (B, H, W, L·K²) lookup or the
        # per-level list of (B, H, W, K, K) windows (layout-copy-free path)
        is_levels = isinstance(corr, (list, tuple))
        b, h, w = corr[0].shape[:3] if is_levels else corr.shape[:3]
        k = 2 * self.radius + 1
        dtype = corr[0].dtype if is_levels else corr.dtype
        delta = window_delta(self.radius, dtype)

        out = []
        for lvl in range(self.num_levels):
            if is_levels:
                # per-level windows are (dy, dx)-ordered; flat channels
                # (and window_delta) are dx-major
                score = corr[lvl].transpose(0, 1, 2, 4, 3)
                score = score.reshape(b, h, w, k * k)
            else:
                score = corr[..., lvl * k * k : (lvl + 1) * k * k]

            if self.dap:
                score = score.reshape(b, h, w, k, k)
                score = DisplacementAwareProjection((self.radius, self.radius))(score)
                score = score.reshape(b, h, w, k * k)

            score = jax.nn.softmax(score / self.temperature, axis=-1)
            flow = jnp.einsum(
                "bhwk,kc->bhwc", score, delta.reshape(k * k, 2) * 2**lvl
            )
            out.append(flow)

        return out


def make_flow_regression(type, num_levels, radius, **kwargs):
    if type == "softargmax":
        return SoftArgMaxFlowRegression(num_levels, radius, dap=False, **kwargs)
    if type == "softargmax+dap":
        return SoftArgMaxFlowRegression(num_levels, radius, dap=True, **kwargs)
    raise ValueError(f"unknown correlation module type '{type}'")


class _WindowConv1x1(nn.Module):
    """1x1 conv over concatenated correlation windows, without the concat.

    Parameter-identical to ``nn.Conv(features, (1, 1))`` on the flat
    (B, H, W, L·K²) lookup tensor (kernel (1, 1, L·K², features) + bias),
    but accepts the per-level list of (B, H, W, K, K) windows and contracts
    each level against its kernel slice directly — the flatten + concat the
    flat form needs costs XLA tile-padded layout copies (a (…, 9, 9) minor
    pair pads to (16, 128) tiles: 25x memory inflation, ~30 ms/step
    profiled at the bench config). Flat tensors still work (shared zoo
    callers pass them), so checkpoints are interchangeable.

    List items may mix two forms (the raft/fs hybrid dispatch produces
    both): rank-5 (B, H, W, K_dy, K_dx) window tensors, and rank-4
    already-flat (B, H, W, n·K²) chunks in the dx-major flat channel
    order (the windowed kernel's native output — contracted directly, no
    reshape/transpose/concat copies).
    """

    features: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        levels = x if isinstance(x, (list, tuple)) else None
        if levels is not None:
            in_features = sum(
                l.shape[-1] if l.ndim == 4 else l.shape[-2] * l.shape[-1]
                for l in levels)
            pdtype = levels[0].dtype
        else:
            in_features = x.shape[-1]
            pdtype = x.dtype

        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (1, 1, in_features, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))

        dt = self.dtype or jnp.promote_types(pdtype, kernel.dtype)
        k2 = kernel.reshape(in_features, self.features).astype(dt)

        if levels is None:
            y = jnp.einsum("bhwc,cf->bhwf", x.astype(dt), k2,
                           preferred_element_type=jnp.float32)
        else:
            y = 0.0
            offset = 0
            for lvl in levels:
                if lvl.ndim == 4:
                    # flat chunk, channels already in the dx-major flat
                    # contract order: plain slice of the kernel matrix
                    n = lvl.shape[-1]
                    y = y + jnp.einsum(
                        "bhwc,cf->bhwf", lvl.astype(dt),
                        k2[offset : offset + n],
                        preferred_element_type=jnp.float32)
                    offset += n
                    continue
                # level windows are (dy, dx)-ordered; the kernel slice is
                # dx-major (the flat-tensor channel contract), so reshape
                # it (dx, dy, f) and contract both axes crosswise
                kdy, kdx = lvl.shape[-2], lvl.shape[-1]
                kl = k2[offset : offset + kdy * kdx].reshape(kdx, kdy,
                                                             self.features)
                y = y + jnp.einsum("bhwka,akf->bhwf", lvl.astype(dt), kl,
                                   preferred_element_type=jnp.float32)
                offset += kdy * kdx
        return y.astype(dt) + bias.astype(dt)


class BasicMotionEncoder(nn.Module):
    """Combine correlation features and current flow into motion features.

    ``corr`` may be the flat (B, H, W, L·K²) lookup tensor or the
    per-level window list (see ``_WindowConv1x1``); parameters are
    identical either way (conv names match the reference's
    convc1/convc2/convf1/convf2/conv, chkpt_convert rules).
    """

    dtype: Any = None

    @nn.compact
    def __call__(self, flow, corr):
        dt = self.dtype
        cor = nn.relu(_WindowConv1x1(256, dtype=dt, name="Conv_0")(corr))
        cor = nn.relu(nn.Conv(192, (3, 3), dtype=dt, name="Conv_1")(cor))

        flo = nn.relu(nn.Conv(128, (7, 7), dtype=dt, name="Conv_2")(flow))
        flo = nn.relu(nn.Conv(64, (3, 3), dtype=dt, name="Conv_3")(flo))

        combined = jnp.concatenate((cor, flo), axis=-1)
        combined = nn.relu(nn.Conv(128 - 2, (3, 3), dtype=dt,
                                   name="Conv_4")(combined))

        flow = flow.astype(combined.dtype)
        return jnp.concatenate((combined, flow), axis=-1)  # 128 channels


class SepConvGru(nn.Module):
    """Separable (1x5 then 5x1) convolutional GRU.

    The z and r gates read the same (h, x) concat, so their convs run as
    one merged conv with doubled output channels (fewer, larger MXU ops:
    the scan body executes 12x per step and small-op overhead dominates
    the profile). Parameters stay per-gate (Conv_0/Conv_1 = z1/r1,
    Conv_3/Conv_4 = z2/r2 — the reference's convz1/convr1/convz2/convr2,
    chkpt_convert rules), merged only at apply time.
    """

    hidden_dim: int = 128
    dtype: Any = None

    @nn.compact
    def __call__(self, h, x):
        from jax.ad_checkpoint import checkpoint_name

        def conv(inp, w, b=None):
            out = jax.lax.conv_general_dilated(
                inp, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return out if b is None else out + b

        dt = self.dtype
        hd = self.hidden_dim
        for i, ksize in enumerate(((1, 5), (5, 1))):
            zk, zb = ConvParams(hd, ksize, name=f"Conv_{3 * i}")(
                h.shape[-1] + x.shape[-1])
            rk, rb = ConvParams(hd, ksize, name=f"Conv_{3 * i + 1}")(
                h.shape[-1] + x.shape[-1])
            qk, qb = ConvParams(hd, ksize, name=f"Conv_{3 * i + 2}")(
                h.shape[-1] + x.shape[-1])

            cdt = dt or zk.dtype
            hc = h.astype(cdt)
            xc = x.astype(cdt)

            # gate convs split along the input-channel axis: the
            # (h, x)-concat conv equals conv(h, W_h) + conv(x, W_x) by
            # linearity. The x-half outputs are checkpoint-named so the
            # remat policy saves them instead of recomputing in the
            # backward pass — the x convs are 2/3 of the gate FLOPs and
            # their saved activations are small (measured net win at the
            # bench config); it also skips the h/x concat materialization.
            zrk_h = jnp.concatenate((zk[:, :, :hd], rk[:, :, :hd]),
                                    axis=-1).astype(cdt)
            zrk_x = jnp.concatenate((zk[:, :, hd:], rk[:, :, hd:]),
                                    axis=-1).astype(cdt)
            zrb = jnp.concatenate((zb, rb)).astype(cdt)

            zr_x = checkpoint_name(conv(xc, zrk_x), "gru_gate_x")
            zr = conv(hc, zrk_h) + zr_x + zrb
            z = nn.sigmoid(zr[..., :hd])
            r = nn.sigmoid(zr[..., hd:])

            q_x = checkpoint_name(conv(xc, qk[:, :, hd:].astype(cdt)),
                                  "gru_gate_x")
            q = jnp.tanh(conv((r * h).astype(cdt), qk[:, :, :hd].astype(cdt))
                         + q_x + qb.astype(cdt))
            h = (1.0 - z) * h + z * q

        return h


class FlowHead(nn.Module):
    """Hidden state → delta flow (returned float32)."""

    hidden_dim: int = 256
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(self.hidden_dim, (3, 3), dtype=self.dtype)(x))
        return nn.Conv(2, (3, 3), dtype=self.dtype)(x).astype(jnp.float32)


class BasicUpdateBlock(nn.Module):
    """One recurrent update: motion encoding + GRU + flow head."""

    hidden_dim: int = 128
    dtype: Any = None

    @nn.compact
    def __call__(self, h, x, corr, flow):
        m = BasicMotionEncoder(dtype=self.dtype)(flow, corr)
        x = jnp.concatenate((x, m.astype(x.dtype)), axis=-1)

        h = SepConvGru(self.hidden_dim, dtype=self.dtype)(h, x)
        d = FlowHead(256, dtype=self.dtype)(h)

        return h, d


class Up8Network(nn.Module):
    """Convex 8x upsampling: per-pixel softmax over 3x3 coarse neighbors.

    Mask channels are neighbor-major (k, sub-row, sub-col) — torch RAFT's
    native layout (``view(b, 1, 9, 8, 8, h, w)``), so converted
    checkpoints import without a channel permutation. The softmax +
    convex combine run as the fused Pallas kernel
    (``ops.pallas.convex_combine_8x``) on TPU — the XLA-scheduled form
    materialized ~750 MB/step of f32 mask intermediates with layout
    copies at the bench config, the single largest cost of the training
    step. The flow window stays f32 throughout: it IS the model output,
    and bf16 ulp at 8·flow magnitudes is ~px-scale.
    """

    temperature: float = 4.0  # 4.0 = 1.0/0.25 in original RAFT
    dtype: Any = None

    @nn.compact
    def __call__(self, hidden, flow):
        mask = nn.Conv(256, (3, 3), dtype=self.dtype)(hidden)
        mask = nn.relu(mask)
        mask = nn.Conv(8 * 8 * 9, (1, 1), dtype=self.dtype)(mask)
        return convex_upsample_8x(flow, mask, temperature=self.temperature)


class _RaftStep(nn.Module):
    """One GRU iteration — the nn.scan body.

    Carry is (hidden, flow); broadcast inputs are the correlation
    pyramid, context features, and the coords0 grid. The carry is the
    *flow* (not coords1) so that a program boundary is a no-op: every
    iteration reconstructs ``coords1 = coords0 + flow`` itself, which is
    exactly what a continuation rung does with ``flow_init`` — chained
    4+4+4 is therefore bit-identical to monolithic 12 in f32 (carrying
    coords1 instead would make re-entry inexact: ``c0 + fl(c1 - c0)``
    loses ulps once |flow| exceeds the coarse coords). Produces the
    coarse-grid flow and hidden state per iteration — the convex 8x
    upsampling runs *outside* the scan, batched over all iterations (its
    full-resolution intermediates would otherwise be rematerialized per
    iteration in the backward pass; profiled as the step's largest cost).
    """

    corr_levels: int
    corr_radius: int
    recurrent_channels: int
    corr_flow: bool
    corr_grad_stop: bool
    mask_costs: Tuple[int, ...]
    corr_reg_type: str
    corr_reg_args: dict
    dtype: Any = None

    @nn.compact
    def __call__(self, carry, pyramid, x, coords0):
        h, flow = carry
        flow = jax.lax.stop_gradient(flow)
        coords1 = coords0 + flow

        # per-level list form: the flatten-to-K² + level concat the flat
        # lookup would do costs tile-padding layout copies (~30 ms/step);
        # every consumer contracts the window axes anyway
        corr = lookup_pyramid_levels(pyramid, coords1, self.corr_radius,
                                     self.mask_costs)
        # named so the remat policy can save the lookup output: recomputing
        # the windowed einsums in the backward pass costs more than the
        # (B, H/8, W/8, L·(2r+1)²) buffer per iteration it saves
        from jax.ad_checkpoint import checkpoint_name

        corr = [checkpoint_name(lvl, "corr_features") for lvl in corr]

        # always *call* the readout so its params exist regardless of the
        # static switch (per-stage overrides / checkpoint compatibility);
        # XLA dead-code-eliminates the unused branch
        reg = make_flow_regression(
            self.corr_reg_type, self.corr_levels, self.corr_radius,
            **self.corr_reg_args,
        )
        corr_flows = tuple(flow + d for d in reg(corr))
        if not self.corr_flow:
            corr_flows = ()

        if self.corr_grad_stop:
            corr = jax.lax.stop_gradient(corr)

        h, d = BasicUpdateBlock(self.recurrent_channels, dtype=self.dtype)(
            h, x, corr, flow)

        coords1 = coords1 + d
        flow = coords1 - coords0

        return (h, flow), (flow, h, corr_flows)


class RaftModule(nn.Module):
    """RAFT flow estimation network (reference RaftModule, raft.py:334-433)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    corr_channels: int = 256
    context_channels: int = 128
    recurrent_channels: int = 128
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    encoder_type: str = "raft"
    context_type: str = "raft"
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    remat: bool = True

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False, iterations=12,
                 flow_init=None, hidden_init=None, upnet=True, corr_flow=False,
                 corr_grad_stop=False, mask_costs=(), return_state=False,
                 quant=None, quant_clip=1.0):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        reg_args = self.corr_reg_args or {}

        # bf16 compute policy (the reference's autocast regions,
        # src/models/impls/raft.py:377-415): encoders, correlation volume,
        # and update block run in bf16; params, coords/flow arithmetic,
        # softmaxes, and the loss stay float32. MXU contractions accumulate
        # in float32 via preferred_element_type.
        dt = jnp.bfloat16 if self.mixed_precision else None

        fnet = common.encoders.make_encoder_s3(
            self.encoder_type, output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout, dtype=dt,
        )
        cnet = common.encoders.make_encoder_s3(
            self.context_type, output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout, dtype=dt,
        )

        fmap1, fmap2 = fnet((img1, img2), train, frozen_bn)
        if dt is None:
            fmap1 = fmap1.astype(jnp.float32)
            fmap2 = fmap2.astype(jnp.float32)

        # The all-pairs volume + einsum windowed lookup is the FASTEST
        # measured realization on-chip at training crops (the feature-space
        # alternative — ops.pallas.windowed_corr_pyramid, identical math by
        # linearity of pooling/interp in f2 — is what raft/fs uses where
        # the O(H²W²) volume cannot exist at all). Each pyramid level is a
        # direct einsum against pooled f2 (bf16 under the policy: halves
        # volume HBM traffic; lookup einsums still accumulate in f32).
        # quantized matching tier (inference-only, ops.quant): u8 stores
        # the same pyramid affinely mapped per level; i8 additionally runs
        # the correlation dots themselves in int8. Either way the lookup
        # einsums dequantize in-register, so the per-iteration HBM stream
        # is the quantized bytes. quant=None is the bit-exact default.
        qmode = quant_ops.normalize_mode(quant)
        if qmode == "i8":
            pyramid = tuple(quant_ops.correlation_pyramid_int8(
                fmap1, fmap2, self.corr_levels, clip=quant_clip))
        elif qmode == "u8":
            pyramid = tuple(quant_ops.quantize_pyramid(
                correlation_pyramid_direct(
                    fmap1, fmap2, self.corr_levels, dtype=dt),
                qmode, clip=quant_clip))
        else:
            pyramid = tuple(correlation_pyramid_direct(
                fmap1, fmap2, self.corr_levels, dtype=dt))

        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])
        if hidden_init is not None:
            # continuation rung: re-enter the recurrence with the previous
            # program's final hidden state (the context tanh is DCE'd)
            h = hidden_init.astype(h.dtype)

        b, hc, wc, _ = fmap1.shape
        coords0 = coordinate_grid(b, hc, wc)
        flow = (flow_init.astype(jnp.float32) if flow_init is not None
                else jnp.zeros((b, hc, wc, 2), jnp.float32))  # graftlint: disable=f32-literal -- flow fields are f32 by convention

        # remat the scan body: recompute iteration activations in the
        # backward pass instead of storing 12 iterations' worth in HBM —
        # this is what makes full-resolution training fit on one chip.
        # The correlation lookups are exempted (saved): their einsums are
        # the expensive part of the recompute and their outputs are small
        if self.remat:
            body = nn.remat(
                _RaftStep, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "corr_features", "gru_gate_x"),
            )
        else:
            body = _RaftStep
        step = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=nn.broadcast,
            out_axes=0,
            length=iterations,
        )(
            corr_levels=self.corr_levels,
            corr_radius=self.corr_radius,
            recurrent_channels=hdim,
            corr_flow=corr_flow,
            corr_grad_stop=corr_grad_stop,
            mask_costs=tuple(mask_costs),
            corr_reg_type=self.corr_reg_type,
            corr_reg_args=reg_args,
            dtype=dt,
        )

        (h, flow), (flows, hiddens, corr_flows) = step(
            (h, flow), pyramid, x, coords0
        )

        # convex 8x upsampling, batched over all iterations at once (one
        # large einsum + pixel shuffle instead of 12 rematerialized ones);
        # always *called* so its params exist regardless of ``upnet``
        full_shape = (img1.shape[1], img1.shape[2])
        flows_flat = flows.reshape(iterations * b, hc, wc, 2)
        hiddens_flat = hiddens.reshape(iterations * b, hc, wc, hdim)

        # remat'd: recomputing the two convs + softmax in the backward pass
        # is cheaper than saving the f32 mask residuals (66MB with layout
        # copies at the bench config)
        # explicit name: the remat wrapper would otherwise prefix the module
        # path ('CheckpointUp8Network_0'), breaking checkpoint compatibility
        up_net = nn.remat(Up8Network, prevent_cse=False)(
            dtype=dt, name="Up8Network_0")(hiddens_flat, flows_flat)
        if upnet:
            flows_up = up_net
        else:
            flows_up = 8.0 * upsample2d_bilinear(flows_flat, full_shape)
        flows_up = flows_up.reshape(iterations, b, *full_shape, 2)

        # unstack the scan axis into per-iteration lists (protocol parity)
        out = [flows_up[i] for i in range(iterations)]

        if corr_flow:
            # corr_flows is a tuple over levels of (iterations, B, H, W, 2);
            # return coarse-to-fine level lists, then the final sequence
            per_level = [
                [corr_flows[lvl][i] for i in range(iterations)]
                for lvl in range(self.corr_levels)
            ]
            out = (*reversed(per_level), out)

        if return_state:
            # ladder continuation carry + convergence probe: the coarse
            # final flow/hidden re-seed the next rung; ``delta`` is the
            # per-sample mean-pixel L2 of the last iteration's flow change
            # — the host reads it between programs to decide "converged"
            final = flows[-1]
            if iterations >= 2:
                prev = flows[-2]
            elif flow_init is not None:
                prev = flow_init.astype(jnp.float32)
            else:
                prev = jnp.zeros_like(final)
            diff = (final - prev).astype(jnp.float32)
            delta = jnp.sqrt(jnp.mean(jnp.sum(diff * diff, axis=-1),
                                      axis=(1, 2)))
            return out, {"flow": final, "hidden": h, "delta": delta}

        return out


@register_model
class Raft(Model):
    """Config wrapper for ``raft/baseline`` (reference raft.py:436-559)."""

    type = "raft/baseline"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        param_cfg = cfg["parameters"]
        return cls(
            dropout=float(param_cfg.get("dropout", 0.0)),
            mixed_precision=bool(param_cfg.get("mixed-precision", False)),
            corr_levels=param_cfg.get("corr-levels", 4),
            corr_radius=param_cfg.get("corr-radius", 4),
            corr_channels=param_cfg.get("corr-channels", 256),
            context_channels=param_cfg.get("context-channels", 128),
            recurrent_channels=param_cfg.get("recurrent-channels", 128),
            encoder_norm=param_cfg.get("encoder-norm", "instance"),
            context_norm=param_cfg.get("context-norm", "batch"),
            encoder_type=param_cfg.get("encoder-type", "raft"),
            context_type=param_cfg.get("context-type", "raft"),
            corr_reg_type=param_cfg.get("corr-reg-type", "softargmax"),
            corr_reg_args=param_cfg.get("corr-reg-args", {}),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_levels=4,
                 corr_radius=4, corr_channels=256, context_channels=128,
                 recurrent_channels=128, encoder_norm="instance",
                 context_norm="batch", encoder_type="raft", context_type="raft",
                 corr_reg_type="softargmax", corr_reg_args={}, arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_levels = corr_levels
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.encoder_type = encoder_type
        self.context_type = context_type
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = corr_reg_args

        super().__init__(
            RaftModule(
                dropout=dropout,
                mixed_precision=mixed_precision,
                corr_levels=corr_levels,
                corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels,
                encoder_norm=encoder_norm,
                context_norm=context_norm,
                encoder_type=encoder_type,
                context_type=context_type,
                corr_reg_type=corr_reg_type,
                corr_reg_args=corr_reg_args,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": 12,
            "upnet": True,
            "corr_flow": False,
            "corr_grad_stop": False,
            "mask_costs": [],
        }

        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-levels": self.corr_levels,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "encoder-type": self.encoder_type,
                "context-type": self.context_type,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)


class RaftAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return RaftResult(result)


class RaftResult(Result):
    """Sequence of per-iteration flows; nested per-level lists when the
    corr-flow readouts are enabled (reference raft.py:570-593)."""

    def __init__(self, output):
        super().__init__()
        self.result = output
        self.has_corr_flow = any(isinstance(x, (list, tuple)) for x in output)

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result

        def slice_one(x):
            return x[batch_index : batch_index + 1]

        if not self.has_corr_flow:
            return [slice_one(x) for x in self.result]
        return [[slice_one(x) for x in level] for level in self.result]

    def final(self):
        if not self.has_corr_flow:
            return self.result[-1]
        return self.result[-1][-1]

    def intermediate_flow(self):
        return self.result


@register_loss
class SequenceLoss(Loss):
    """γ-weighted distance over the iteration sequence
    (``raft/sequence``, reference raft.py:596-644)."""

    type = "raft/sequence"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {"ord": 1, "gamma": 0.8, "include_invalid": False}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                include_invalid=False):
        n = len(result)
        valid_f = valid.astype(jnp.float32)

        loss = 0.0
        for i, flow in enumerate(result):
            weight = gamma ** (n - i - 1)

            if ord == "absmean":
                dist = jnp.abs(flow - target).mean(axis=-1)
            else:
                dist = jnp.linalg.norm(flow - target, ord=ord, axis=-1)

            if include_invalid:
                # invalid pixels enter the mean as zero (original RAFT)
                loss = loss + weight * (dist * valid_f).mean()
            else:
                # mean over valid pixels only
                loss = loss + weight * (dist * valid_f).sum() / jnp.maximum(
                    valid_f.sum(), 1.0
                )

        return loss
