"""RAFT "from scratch" variant: no materialized all-pairs volume.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_fs.py:13-268: the second frame's features are
avg-pooled into a pyramid and the correlation window is computed
*on the fly* against each level via the framework's windowed-correlation
op — O(B·H·W·K²·C) per lookup instead of the O(B·H²W²) volume. This is the
framework's high-resolution memory story (SURVEY §5.7): the model of
choice when the all-pairs volume does not fit HBM.

The GRU loop is an ``nn.scan`` with rematerialization like the baseline.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.corr import correlation_pyramid_direct, lookup_pyramid_levels
from ...ops.pallas import windowed_corr_pyramid
from ...ops.pool import avg_pool2d
from ...ops.upsample import interpolate_bilinear
from ..common import encoders
from ..common.grid import coordinate_grid
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, RaftAdapter, Up8Network


class _FsStep(nn.Module):
    """One GRU iteration — nn.scan body; carry is (hidden, coords1)."""

    corr_levels: int
    corr_radius: int
    recurrent_channels: int
    upnet: bool
    mask_costs: Tuple[int, ...]
    full_shape: Tuple[int, int]
    volume: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, carry, fmap1, pyramid, x, coords0):
        h, coords1 = carry
        coords1 = jax.lax.stop_gradient(coords1)
        flow = coords1 - coords0

        if self.volume:
            # small-enough shapes: ``pyramid`` is the materialized volume
            # pyramid, amortized across iterations — same math (pooling
            # commutes with the dot product), ~4x the throughput of the
            # per-step windowed computation at training crops
            corr = lookup_pyramid_levels(pyramid, coords1,
                                         self.corr_radius,
                                         mask_costs=self.mask_costs)
        else:
            # on-the-fly windowed dot-product against the pooled feature
            # pyramid — the fused kernel (ops/pallas.py) on TPU,
            # per-level windowed correlation off it; O(B·H·W·C) memory at
            # any resolution. The reference lookup skips the sqrt(C)
            # normalization (raft_fs.py:76) in both realizations.
            corr = windowed_corr_pyramid(
                fmap1, pyramid, coords1, self.corr_radius,
                mask_costs=self.mask_costs, normalize=False,
            )

        h, d = BasicUpdateBlock(self.recurrent_channels, dtype=self.dtype)(
            h, x, corr, flow)

        coords1 = coords1 + d
        flow = coords1 - coords0

        flow_up_net = Up8Network(dtype=self.dtype)(h, flow)
        if self.upnet:
            flow_up = flow_up_net
        else:
            flow_up = 8.0 * interpolate_bilinear(flow, self.full_shape)

        return (h, coords1), flow_up


class RaftFsModule(nn.Module):
    """RAFT-fs network (reference RaftModule, raft_fs.py:92-170)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    corr_channels: int = 256
    context_channels: int = 128
    recurrent_channels: int = 128
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    remat: bool = True

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=12, flow_init=None, upnet=True, mask_costs=()):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        dt = jnp.bfloat16 if self.mixed_precision else None

        fnet = encoders.make_encoder_s3(
            "raft", output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout, dtype=dt,
        )
        cnet = encoders.make_encoder_s3(
            "raft", output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout, dtype=dt,
        )

        fmap1, fmap2 = fnet((img1, img2), train, frozen_bn)
        if dt is None:
            fmap1 = fmap1.astype(jnp.float32)
            fmap2 = fmap2.astype(jnp.float32)
        # under the bf16 policy the feature maps stay bf16: halves the
        # windowed-correlation kernel's VMEM blocks (the accumulation is
        # f32 inside the kernel)

        # strategy dispatch: the windowed computation exists so the
        # O(H²W²) volume never has to — but where the volume DOES fit,
        # materializing it once and looking it up per iteration is ~4x
        # faster at training crops (the windowed kernel is gather-bound).
        # Identical math either way (pooling/bilinear commute with the
        # dot product); the estimate charges 2x for the backward's
        # volume-gradient accumulation. RMD_FS_VOLUME_GIB tunes the
        # budget (0 forces the windowed path everywhere).
        import os

        b0, hc0, wc0, _ = fmap1.shape
        itemsize = 2 if dt is not None else 4
        vol_bytes = sum(
            b0 * hc0 * wc0 * (hc0 // 2 ** l) * (wc0 // 2 ** l) * itemsize
            for l in range(self.corr_levels)
        )
        budget = float(os.environ.get("RMD_FS_VOLUME_GIB", "2.0")) * 2 ** 30
        use_volume = 2 * vol_bytes <= budget

        if use_volume:
            pyramid = correlation_pyramid_direct(
                fmap1, fmap2, self.corr_levels, dtype=dt, normalize=False)
        else:
            # avg-pooled second-frame feature pyramid (raft_fs.py:26-31)
            pyramid = [fmap2]
            for _ in range(1, self.corr_levels):
                pyramid.append(avg_pool2d(pyramid[-1], 2))

        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])

        b, hc, wc, _ = fmap1.shape
        coords0 = coordinate_grid(b, hc, wc)
        coords1 = coords0 + flow_init if flow_init is not None else coords0

        body = nn.remat(_FsStep, prevent_cse=False) if self.remat else _FsStep
        step = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=nn.broadcast,
            out_axes=0,
            length=iterations,
        )(
            corr_levels=self.corr_levels,
            corr_radius=self.corr_radius,
            recurrent_channels=hdim,
            upnet=upnet,
            mask_costs=tuple(mask_costs),
            full_shape=(img1.shape[1], img1.shape[2]),
            volume=use_volume,
            dtype=dt,
        )

        (h, coords1), flows_up = step((h, coords1), fmap1, tuple(pyramid), x,
                                      coords0)

        return [flows_up[i] for i in range(iterations)]


@register_model
class RaftFs(Model):
    """``raft/fs`` (reference raft_fs.py:173-268)."""

    type = "raft/fs"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_levels=p.get("corr-levels", 4),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 256),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_levels=4,
                 corr_radius=4, corr_channels=256, context_channels=128,
                 recurrent_channels=128, encoder_norm="instance",
                 context_norm="batch", arguments={}, on_epoch_args={},
                 on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_levels = corr_levels
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm

        super().__init__(
            RaftFsModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_levels=corr_levels, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels,
                encoder_norm=encoder_norm, context_norm=context_norm,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {"iterations": 12, "upnet": True, "mask_costs": []}
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-levels": self.corr_levels,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
