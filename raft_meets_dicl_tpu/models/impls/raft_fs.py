"""RAFT "from scratch" variant: no materialized all-pairs volume.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_fs.py:13-268: the second frame's features are
avg-pooled into a pyramid and the correlation window is computed
*on the fly* against each level via the framework's windowed-correlation
op — O(B·H·W·K²·C) per lookup instead of the O(B·H²W²) volume. This is the
framework's high-resolution memory story (SURVEY §5.7): the model of
choice when the all-pairs volume does not fit HBM.

The GRU loop is an ``nn.scan`` with rematerialization like the baseline.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops import quant as quant_ops
from ...ops.corr import correlation_volume, lookup_pyramid_levels
from ...ops.pallas import windowed_corr_pyramid
from ...ops.pool import avg_pool2d
from ...ops.upsample import interpolate_bilinear
from ..common import encoders
from ..common.grid import coordinate_grid
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, RaftAdapter, Up8Network


def volume_level_split(coarse_shape, corr_levels, itemsize, budget_gib=None):
    """Greedy per-level dispatch decision: how many fine levels stay on
    the windowed kernel.

    Walks the pyramid from the coarsest level (each volume is 4x the
    next coarser one) and moves levels onto materialized volumes while
    twice their running total — the 2x charges the backward's
    volume-gradient accumulation — fits the ``RMD_FS_VOLUME_GIB`` budget
    (default 4 GiB; 0 forces the windowed path everywhere). Returns
    ``n_windowed``: levels ``[0, n_windowed)`` are computed on the fly.

    The budget is PER CHIP: under SPMD the trace sees the global batch
    while each chip holds only its ``1/data_axis_size`` slice of the
    batch-sharded volume, so the estimate divides by the data-parallel
    degree published by the step builders (parallel.mesh).
    """
    from ...parallel.mesh import data_axis_size
    from ...utils import env

    if budget_gib is None:
        budget_gib = env.get_float("RMD_FS_VOLUME_GIB")
    budget = budget_gib * 2 ** 30

    b0, hc0, wc0 = coarse_shape
    n_chips = data_axis_size()
    vol_bytes = [
        b0 * hc0 * wc0 * (hc0 // 2 ** l) * (wc0 // 2 ** l) * itemsize
        // n_chips
        for l in range(corr_levels)
    ]
    n_windowed = corr_levels
    total = 0
    for l in reversed(range(corr_levels)):
        if 2 * (total + vol_bytes[l]) > budget:
            break
        total += vol_bytes[l]
        n_windowed = l
    return n_windowed


class _FsStep(nn.Module):
    """One GRU iteration — nn.scan body; carry is (hidden, flow).

    The carry is the flow (reconstructing ``coords1 = coords0 + flow``
    every iteration) so a ladder-rung boundary reproduces the monolithic
    program bit-exactly — see ``raft._RaftStep``.

    ``n_windowed`` is the per-level dispatch split: pyramid levels
    ``[0, n_windowed)`` are computed on the fly by the windowed kernel
    (their volumes don't fit the budget), levels ``[n_windowed, L)`` are
    looked up from materialized volumes. The broadcast ``pyramid`` input
    carries pooled f2 maps for the windowed prefix followed by volumes
    for the coarse suffix.
    """

    corr_levels: int
    corr_radius: int
    recurrent_channels: int
    mask_costs: Tuple[int, ...]
    n_windowed: int = 0
    dtype: Any = None

    @nn.compact
    def __call__(self, carry, fmap1, pyramid, x, coords0):
        h, flow = carry
        flow = jax.lax.stop_gradient(flow)
        coords1 = coords0 + flow

        n_win = self.n_windowed
        if n_win == 0:
            # small-enough shapes: ``pyramid`` is the materialized volume
            # pyramid, amortized across iterations — same math (pooling
            # commutes with the dot product), ~4x the throughput of the
            # per-step windowed computation at training crops
            corr = lookup_pyramid_levels(pyramid, coords1,
                                         self.corr_radius,
                                         mask_costs=self.mask_costs)
        elif n_win == self.corr_levels:
            # on-the-fly windowed dot-product against the pooled feature
            # pyramid — the fused kernel (ops/pallas.py) on TPU,
            # per-level windowed correlation off it; O(B·H·W·C) memory at
            # any resolution. The reference lookup skips the sqrt(C)
            # normalization (raft_fs.py:76) in both realizations.
            corr = windowed_corr_pyramid(
                fmap1, pyramid, coords1, self.corr_radius,
                mask_costs=self.mask_costs, normalize=False,
            )
        else:
            # hybrid: the fine levels' volumes don't fit but the coarse
            # suffix's do (each level is 4x smaller than the last) —
            # kernel for the prefix, volume lookups for the suffix. The
            # mixed list goes straight to the motion encoder's
            # _WindowConv1x1: the kernel's flat (level, dx, dy) chunk
            # contracts as-is and the volume levels contract in their
            # native (dy, dx) window form — no concat, no transposes.
            corr_win = windowed_corr_pyramid(
                fmap1, pyramid[:n_win], coords1, self.corr_radius,
                mask_costs=self.mask_costs, normalize=False,
            )
            corr = [corr_win] + lookup_pyramid_levels(
                pyramid[n_win:], coords1, self.corr_radius,
                mask_costs=self.mask_costs, first_level=n_win,
            )

        # named so the remat policy saves the correlation output: without
        # it the windowed Pallas kernel's forward runs a second time in
        # the backward pass (profiled ~90 ms/step at 1080p), and the
        # volume-lookup einsums recompute likewise
        from jax.ad_checkpoint import checkpoint_name

        if isinstance(corr, list):
            corr = [checkpoint_name(lvl, "corr_features") for lvl in corr]
        else:
            corr = checkpoint_name(corr, "corr_features")

        h, d = BasicUpdateBlock(self.recurrent_channels, dtype=self.dtype)(
            h, x, corr, flow)

        coords1 = coords1 + d
        flow = coords1 - coords0

        return (h, flow), (flow, h)


class RaftFsModule(nn.Module):
    """RAFT-fs network (reference RaftModule, raft_fs.py:92-170)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    corr_channels: int = 256
    context_channels: int = 128
    recurrent_channels: int = 128
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    remat: bool = True

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=12, flow_init=None, hidden_init=None, upnet=True,
                 mask_costs=(), return_state=False, quant=None,
                 quant_clip=1.0):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        dt = jnp.bfloat16 if self.mixed_precision else None

        fnet = encoders.make_encoder_s3(
            "raft", output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout, dtype=dt,
        )
        cnet = encoders.make_encoder_s3(
            "raft", output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout, dtype=dt,
        )

        fmap1, fmap2 = fnet((img1, img2), train, frozen_bn)
        if dt is None:
            fmap1 = fmap1.astype(jnp.float32)
            fmap2 = fmap2.astype(jnp.float32)
        # under the bf16 policy the feature maps stay bf16: halves the
        # windowed-correlation kernel's VMEM blocks (the accumulation is
        # f32 inside the kernel)

        # strategy dispatch: the windowed computation exists so the
        # O(H²W²) volume never has to — but where a level's volume DOES
        # fit, materializing it once and looking it up per iteration is
        # ~4x faster (the windowed kernel is gather-bound). Identical
        # math either way (pooling/bilinear commute with the dot
        # product). The decision is PER LEVEL, greedy from the coarsest:
        # each level's volume is 4x smaller than the previous, so at
        # 1080p the coarse suffix (levels 1-3, ~1.2 GB) fits while
        # level 0 (3.7 GB) cannot — moving 3 of 4 levels off the
        # serialized kernel. The estimate charges 2x for the backward's
        # volume-gradient accumulation and is per chip (the global-batch
        # shapes seen at trace time are divided by the SPMD data-parallel
        # degree). RMD_FS_VOLUME_GIB tunes the budget (0 forces the
        # windowed path everywhere).
        b0, hc0, wc0, _ = fmap1.shape
        itemsize = 2 if dt is not None else 4
        n_windowed = volume_level_split(
            (b0, hc0, wc0), self.corr_levels, itemsize)

        # avg-pooled second-frame feature pyramid (raft_fs.py:26-31);
        # the coarse suffix becomes materialized volumes against the
        # same pooled maps (so both dispatch paths correlate against
        # bit-identical f2 levels)
        f2_pyramid = [fmap2]
        for _ in range(1, self.corr_levels):
            f2_pyramid.append(avg_pool2d(f2_pyramid[-1], 2))
        # quantized matching tier (ops.quant): the materialized coarse
        # suffix is stored at the quantized width and dequantized
        # in-register by the lookup einsums. The windowed prefix never
        # materializes a volume, so there is nothing to quantize there —
        # both modes reduce to storage quantization here (the int8
        # feature-dot construction is a RaftModule path).
        qmode = quant_ops.normalize_mode(quant)
        volumes = [
            correlation_volume(fmap1, f2, dtype=dt, normalize=False)
            for f2 in f2_pyramid[n_windowed:]
        ]
        if qmode is not None:
            volumes = quant_ops.quantize_pyramid(volumes, qmode,
                                                 clip=quant_clip)
        pyramid = f2_pyramid[:n_windowed] + volumes

        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])
        if hidden_init is not None:
            h = hidden_init.astype(h.dtype)

        b, hc, wc, _ = fmap1.shape
        coords0 = coordinate_grid(b, hc, wc)
        flow = (flow_init.astype(jnp.float32) if flow_init is not None
                else jnp.zeros((b, hc, wc, 2), jnp.float32))  # graftlint: disable=f32-literal -- flow fields are f32 by convention

        # same remat policy as raft/baseline: save the correlation lookup
        # outputs (recomputing the windowed kernel / lookup einsums in the
        # backward costs far more than the per-iteration (B, H/8, W/8,
        # L·(2r+1)²) buffers) and the GRU x-half gate convs
        if self.remat:
            body = nn.remat(
                _FsStep, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "corr_features", "gru_gate_x"),
            )
        else:
            body = _FsStep
        step = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=nn.broadcast,
            out_axes=0,
            length=iterations,
        )(
            corr_levels=self.corr_levels,
            corr_radius=self.corr_radius,
            recurrent_channels=hdim,
            mask_costs=tuple(mask_costs),
            n_windowed=n_windowed,
            dtype=dt,
        )

        (h, flow), (flows, hiddens) = step((h, flow), fmap1,
                                           tuple(pyramid), x, coords0)

        # convex 8x upsampling hoisted out of the remat'd scan and batched
        # over all iterations, exactly like raft/baseline (raft.py): inside
        # the scan its full-resolution intermediates are rematerialized
        # per iteration in the backward pass — the step's largest cost at
        # high resolution. Explicit name keeps a stable param path going
        # forward; checkpoints from before the hoist (params under the
        # scan-body subtree) are migrated at load time by
        # strategy.checkpoint._remap_legacy_model_state.
        full_shape = (img1.shape[1], img1.shape[2])
        flows_flat = flows.reshape(iterations * b, hc, wc, 2)
        hiddens_flat = hiddens.reshape(iterations * b, hc, wc, hdim)

        up_net = nn.remat(Up8Network, prevent_cse=False)(
            dtype=dt, name="Up8Network_0")(hiddens_flat, flows_flat)
        if upnet:
            flows_up = up_net
        else:
            flows_up = 8.0 * interpolate_bilinear(flows_flat, full_shape)
        flows_up = flows_up.reshape(iterations, b, *full_shape, 2)

        out = [flows_up[i] for i in range(iterations)]

        if return_state:
            final = flows[-1]
            if iterations >= 2:
                prev = flows[-2]
            elif flow_init is not None:
                prev = flow_init.astype(jnp.float32)
            else:
                prev = jnp.zeros_like(final)
            diff = (final - prev).astype(jnp.float32)
            delta = jnp.sqrt(jnp.mean(jnp.sum(diff * diff, axis=-1),
                                      axis=(1, 2)))
            return out, {"flow": final, "hidden": h, "delta": delta}

        return out


@register_model
class RaftFs(Model):
    """``raft/fs`` (reference raft_fs.py:173-268)."""

    type = "raft/fs"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_levels=p.get("corr-levels", 4),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 256),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_levels=4,
                 corr_radius=4, corr_channels=256, context_channels=128,
                 recurrent_channels=128, encoder_norm="instance",
                 context_norm="batch", arguments={}, on_epoch_args={},
                 on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_levels = corr_levels
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm

        super().__init__(
            RaftFsModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_levels=corr_levels, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels,
                encoder_norm=encoder_norm, context_norm=context_norm,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {"iterations": 12, "upnet": True, "mask_costs": []}
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-levels": self.corr_levels,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
