"""Model zoo. Importing this package registers all model/loss types."""

from . import raft

__all__ = ["raft"]
