"""Model zoo. Importing this package registers all model/loss types."""

from . import dicl, raft, raft_dicl_sl

__all__ = ["dicl", "raft", "raft_dicl_sl"]
