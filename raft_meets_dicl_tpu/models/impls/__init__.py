"""Model zoo. Importing this package registers all model/loss types."""

from ..common import loss as _common_loss  # noqa: F401 — registers mlseq
from . import (
    dicl,
    outdated,
    raft,
    raft_dicl_ctf,
    raft_dicl_ml,
    raft_dicl_sl,
    raft_fs,
    raft_sl,
    raft_sl_ctf,
)

__all__ = ["dicl", "outdated", "raft", "raft_dicl_ctf", "raft_dicl_ml",
           "raft_dicl_sl", "raft_fs", "raft_sl", "raft_sl_ctf"]
