"""RAFT+DICL coarse-to-fine hybrids — the thesis flagship family.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_dicl_ctf_l{2,3,4}.py — three hand-written variants of
one structure, realized here as a single parametric module:

- pyramid encoders (p34/p35/p36 for 2/3/4 levels),
- per-level DICL correlation modules and RAFT GRU update blocks, either
  level-shared or separate (``share_dicl`` / ``share_rnn``),
- hidden-state upsampling between levels (none/bilinear/crossattn),
- bilinear inter-level flow upsampling, convex Up8 on the finest level,
- gradient stopping between levels and iterations,
- optional per-iteration ``corr_flow`` readouts and ``prev_flow``
  intermediates (consumed by the restricted multi-level sequence loss,
  reference raft_dicl_ctf_l3.py:401-473).

Output protocol (coarse-to-fine, per reference :247-258): a list of
per-level iteration lists for the MultiLevelSequenceAdapter; with
``corr_flow`` each level contributes its readout list before its flow list;
with ``prev_flow`` entries become (prev, flow) pairs.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.upsample import interpolate_bilinear, upsample_flow_2x
from ..common import corr as corr_mod
from ..common import encoders, hsup
from ..common.adapters.mlseq import MultiLevelSequenceAdapter
from ..common.grid import coordinate_grid
from ..common.loss.mlseq import upsample_flow_to
from ..config import register_loss, register_model
from ..model import Loss, Model, ModelAdapter
from .raft import BasicUpdateBlock, Up8Network

_PYRAMIDS = {
    2: encoders.make_encoder_p34,
    3: encoders.make_encoder_p35,
    4: encoders.make_encoder_p36,
}

_DEFAULT_ITERATIONS = {2: (4, 3), 3: (4, 3, 3), 4: (3, 4, 4, 3)}


class _CtfStep(nn.Module):
    """One RAFT+DICL iteration at a fixed pyramid level — the nn.scan body.

    All parameterized submodules are passed in as shared instances created
    in the parent scope, so parameter paths (and with them checkpoints and
    the torch-importer rules) are identical to the unrolled form, and level
    sharing (``share_dicl`` / ``share_rnn``) composes freely with the scan:
    the scan only owns the loop, never the weights.
    """

    cmod: nn.Module
    reg: nn.Module
    update: nn.Module
    dap: bool
    corr_grad_stop: bool
    train: bool
    frozen_bn: bool

    @nn.compact
    def __call__(self, carry, _, f1, f2, x, coords0):
        from jax.ad_checkpoint import checkpoint_name

        # flow (not coords1) carry: program boundaries replay the same
        # ``coords0 + flow`` reconstruction, so ladder rungs chain
        # bit-exactly (see raft._RaftStep)
        h, prev = carry
        prev = jax.lax.stop_gradient(prev)
        coords1 = coords0 + prev

        corr = self.cmod(f1, f2, coords1, dap=self.dap, train=self.train,
                         frozen_bn=self.frozen_bn)
        # saved under the remat policy: recomputing the MatchingNet over
        # all (2r+1)² displacements in the backward pass costs far more
        # than the (B, H, W, (2r+1)²) cost volume it would save
        corr = checkpoint_name(corr, "corr_features")

        # readout is always computed so the regression params exist
        # regardless of the static corr_flow switch; XLA removes it when
        # the output is unused
        readout = prev + self.reg(corr)

        if self.corr_grad_stop:
            corr = jax.lax.stop_gradient(corr)

        h, d = self.update(h, x, corr, prev)
        coords1 = coords1 + d
        flow = coords1 - coords0

        return (h, flow), (flow, h, readout, prev)


class RaftPlusDiclCtfModule(nn.Module):
    """Coarse-to-fine RAFT+DICL network over ``levels`` pyramid levels
    (finest always 1/8; coarsest 1/(8·2^(levels-1)))."""

    levels: int = 3
    corr_radius: int = 4
    corr_channels: int = 32
    context_channels: int = 128
    recurrent_channels: int = 128
    dap_init: str = "identity"
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    mnet_norm: str = "batch"
    encoder_type: str = "raft"
    context_type: str = "raft"
    corr_type: str = "dicl"
    corr_args: dict = None
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    share_dicl: bool = False
    share_rnn: bool = True
    upsample_hidden: str = "none"
    mixed_precision: bool = False
    remat: bool = True
    unroll: bool = False

    def _make_cmod(self, dtype=None):
        kwargs = dict(self.corr_args or {})
        # the matching-net cmods all take a compute dtype now; "dot" has
        # no net to cast (its einsum accumulates f32 regardless)
        if dtype is not None and self.corr_type in ("dicl", "dicl-1x1",
                                                    "dicl-emb"):
            kwargs["dtype"] = dtype
        return corr_mod.make_cmod(
            self.corr_type, self.corr_channels, radius=self.corr_radius,
            dap_init=self.dap_init, norm_type=self.mnet_norm,
            **kwargs,
        )

    def _make_reg(self):
        return corr_mod.make_flow_regression(
            self.corr_type, self.corr_reg_type, self.corr_radius,
            **(self.corr_reg_args or {}),
        )

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=None, dap=True, upnet=True, corr_flow=False,
                 prev_flow=False, corr_grad_stop=False, flow_init=None,
                 hidden_init=None, return_state=False):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        b, h, w = img1.shape[0], img1.shape[1], img1.shape[2]

        # bf16 compute policy (TPU-native analog of the reference's raft
        # autocast, extended to the ctf family): encoders, matching nets,
        # and update blocks run bf16; cost volumes, coords/flow arithmetic,
        # and the Up8 flow window stay float32
        dt = jnp.bfloat16 if self.mixed_precision else None
        if dt is not None and (self.encoder_type != "raft"
                               or self.context_type != "raft"
                               or self.corr_type != "dicl"):
            # silently running parts in f32 would fake the policy
            raise ValueError(
                "mixed-precision is only plumbed through the raft encoders "
                "and the dicl correlation module; got encoder-type="
                f"'{self.encoder_type}', context-type='{self.context_type}',"
                f" corr-type='{self.corr_type}'"
            )
        enc_kw = {"dtype": dt} if dt is not None else {}
        ctx_kw = {"dtype": dt} if dt is not None else {}

        # ladder continuation: with ``hidden_init`` only the finest (1/8)
        # level runs, re-entering its recurrence from the previous rung's
        # ``(flow, hidden)``; an int ``iterations`` means the finest-level
        # count (coarse levels keep their defaults — a continuation never
        # re-runs them, so chained rungs match one longer finest loop)
        cont = hidden_init is not None
        if flow_init is not None and not cont:
            raise ValueError(
                "ctf models take flow_init only together with hidden_init "
                "(a continuation rung at the finest level); the coarse "
                "pyramid has no seeding protocol")
        if isinstance(iterations, int):
            its = list(_DEFAULT_ITERATIONS[self.levels])
            its[-1] = iterations
            iterations = tuple(its)
        else:
            iterations = tuple(iterations or _DEFAULT_ITERATIONS[self.levels])
        assert len(iterations) == self.levels

        # level ids coarse→fine, e.g. (5, 4, 3) for 3 levels; level L = 1/2^L
        level_ids = tuple(range(self.levels + 2, 2, -1))

        fnet = _PYRAMIDS[self.levels](
            self.encoder_type, output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=0, **enc_kw,
        )
        cnet = _PYRAMIDS[self.levels](
            self.context_type, output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=0, **ctx_kw,
        )

        f1, f2 = fnet((img1, img2), train, frozen_bn)  # finest-first tuples
        ctx = cnet(img1, train, frozen_bn)

        hidden = [jnp.tanh(c[..., :hdim]) for c in ctx]
        context = [nn.relu(c[..., hdim:]) for c in ctx]

        # shared-or-per-level submodules (reference :40-78); flax modules
        # created once are parameter-shared on repeated calls
        if self.share_dicl:
            shared_cmod, shared_reg = self._make_cmod(dt), self._make_reg()
            cmods = {lvl: shared_cmod for lvl in level_ids}
            regs = {lvl: shared_reg for lvl in level_ids}
        else:
            cmods = {lvl: self._make_cmod(dt) for lvl in level_ids}
            regs = {lvl: self._make_reg() for lvl in level_ids}

        if self.share_rnn:
            shared_update = BasicUpdateBlock(hdim, dtype=dt)
            shared_hup = hsup.make_hidden_state_upsampler(
                self.upsample_hidden, hdim)
            updates = {lvl: shared_update for lvl in level_ids}
            hups = {lvl: shared_hup for lvl in level_ids[1:]}
        else:
            updates = {lvl: BasicUpdateBlock(hdim, dtype=dt) for lvl in level_ids}
            hups = {
                lvl: hsup.make_hidden_state_upsampler(self.upsample_hidden, hdim)
                for lvl in level_ids[1:]
            }

        # remat'd batched convex upsampler, pinned name for checkpoint
        # stability (the wrapper would otherwise prefix the module path)
        upnet8 = nn.remat(Up8Network, prevent_cse=False)(
            dtype=dt, name="Up8Network_0")

        # the lifted scan broadcasts batch_stats read-only; when batch norm
        # actually trains (rare — stages default to freeze_batchnorm) the
        # sequential running-stat updates need the python-unrolled loop
        unrolled = self.unroll or (train and not frozen_bn)

        out = []
        flow = None
        h_state = None

        for li, lvl in enumerate(level_ids):
            finest = li == self.levels - 1
            if cont and not finest:
                continue

            scale = 2 ** lvl
            lh, lw = h // scale, w // scale
            fine_idx = lvl - 3  # index into finest-first feature tuples
            n_iter = iterations[li]

            coords0 = coordinate_grid(b, lh, lw)
            if cont:
                flow = (flow_init.astype(jnp.float32)
                        if flow_init is not None
                        else jnp.zeros((b, lh, lw, 2), jnp.float32))  # graftlint: disable=f32-literal -- flow fields are f32 by convention
                h_state = hidden_init.astype(hidden[fine_idx].dtype)
            else:
                if flow is None:
                    flow = jnp.zeros((b, lh, lw, 2), jnp.float32)  # graftlint: disable=f32-literal -- flow fields are f32 by convention
                else:
                    flow = upsample_flow_2x(flow)

                if h_state is None:
                    h_state = hidden[fine_idx]
                else:
                    h_state = hups[lvl](h_state, hidden[fine_idx])
            if finest:
                entry_flow = flow

            x = context[fine_idx]

            # one (remat-wrapped) step body serves both realizations:
            # iterations share spatial shapes within a level, and remat
            # recomputes iteration activations in the backward pass
            # instead of storing every MatchingNet intermediate (the
            # raft/baseline scan discipline, models/impls/raft.py:322-352)
            if self.remat:
                body = nn.remat(
                    _CtfStep, prevent_cse=False,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "corr_features"),
                )
            else:
                body = _CtfStep
            shared = dict(
                cmod=cmods[lvl], reg=regs[lvl], update=updates[lvl],
                dap=dap, corr_grad_stop=corr_grad_stop,
                train=train, frozen_bn=frozen_bn,
            )

            if unrolled:
                # python loop over the same step module — sequential
                # batch-stat updates, identical parameter paths
                step = body(**shared)
                carry = (h_state, flow)
                flows, hiddens, readouts, prevs = [], [], [], []
                for _ in range(n_iter):
                    carry, (fl, hi, ro, pv) = step(
                        carry, jnp.zeros((0,), dtype=jnp.bfloat16),
                        f1[fine_idx], f2[fine_idx], x, coords0,
                    )
                    flows.append(fl)
                    hiddens.append(hi)
                    readouts.append(ro)
                    prevs.append(pv)
                h_state, flow = carry

                flows = jnp.stack(flows)
                hiddens = jnp.stack(hiddens)
                readouts = jnp.stack(readouts)
                prevs = jnp.stack(prevs)
            else:
                step = nn.scan(
                    body,
                    variable_broadcast=["params", "batch_stats"],
                    split_rngs={"params": False, "dropout": True},
                    in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                             nn.broadcast),
                    out_axes=0,
                )(**shared)

                (h_state, flow), (flows, hiddens, readouts, prevs) = step(
                    (h_state, flow), jnp.zeros((n_iter, 0), dtype=jnp.bfloat16),
                    f1[fine_idx], f2[fine_idx], x, coords0,
                )

            flow = flows[-1]

            if finest:
                # convex 8x upsampling, batched over all iterations at once
                # (the raft/baseline hoist: one large einsum instead of
                # n_iter rematerialized ones); always called so its params
                # exist regardless of ``upnet``
                flows_flat = flows.reshape(n_iter * b, lh, lw, 2)
                hidden_flat = hiddens.reshape(n_iter * b, lh, lw, hdim)
                ups = upnet8(hidden_flat, flows_flat)
                if not upnet:
                    ups = 8.0 * interpolate_bilinear(flows_flat, (h, w))
                ups = ups.reshape(n_iter, b, h, w, 2)
                out_lvl = [ups[i] for i in range(n_iter)]
            else:
                out_lvl = [flows[i] for i in range(n_iter)]

            out_prev = [prevs[i] for i in range(n_iter)]
            out_corr = [readouts[i] for i in range(n_iter)]

            if prev_flow:
                out_lvl = list(zip(out_prev, out_lvl))
                if corr_flow:
                    out_corr = list(zip(out_prev, out_corr))

            if corr_flow:
                out.append(out_corr)
            out.append(out_lvl)

        if return_state:
            # finest-level (1/8) carry + convergence probe, as in raft
            final = flows[-1]
            if iterations[-1] >= 2:
                prev_f = flows[-2]
            else:
                prev_f = entry_flow
            diff = (final - prev_f).astype(jnp.float32)
            delta = jnp.sqrt(jnp.mean(jnp.sum(diff * diff, axis=-1),
                                      axis=(1, 2)))
            return out, {"flow": final, "hidden": h_state, "delta": delta}

        return out


class _CtfModel(Model):
    """Shared config wrapper for the three registered level counts."""

    levels = None

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 32),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            dap_init=p.get("dap-init", "identity"),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            mnet_norm=p.get("mnet-norm", "batch"),
            encoder_type=p.get("encoder-type", "raft"),
            context_type=p.get("context-type", "raft"),
            share_dicl=p.get("share-dicl", False),
            share_rnn=p.get("share-rnn", True),
            corr_type=p.get("corr-type", "dicl"),
            corr_args=p.get("corr-args", {}),
            corr_reg_type=p.get("corr-reg-type", "softargmax"),
            corr_reg_args=p.get("corr-reg-args", {}),
            upsample_hidden=p.get("upsample-hidden", "none"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, corr_radius=4, corr_channels=32, context_channels=128,
                 recurrent_channels=128, dap_init="identity",
                 encoder_norm="instance", context_norm="batch",
                 mnet_norm="batch", encoder_type="raft", context_type="raft",
                 share_dicl=False, share_rnn=True, corr_type="dicl",
                 corr_args={}, corr_reg_type="softargmax", corr_reg_args={},
                 upsample_hidden="none", mixed_precision=False, arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.mixed_precision = mixed_precision
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.dap_init = dap_init
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.mnet_norm = mnet_norm
        self.encoder_type = encoder_type
        self.context_type = context_type
        self.share_dicl = share_dicl
        self.share_rnn = share_rnn
        self.corr_type = corr_type
        self.corr_args = dict(corr_args)
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)
        self.upsample_hidden = upsample_hidden

        super().__init__(
            RaftPlusDiclCtfModule(
                levels=self.levels, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dap_init=dap_init,
                encoder_norm=encoder_norm, context_norm=context_norm,
                mnet_norm=mnet_norm, encoder_type=encoder_type,
                context_type=context_type, corr_type=corr_type,
                corr_args=dict(corr_args), corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args), share_dicl=share_dicl,
                share_rnn=share_rnn, upsample_hidden=upsample_hidden,
                mixed_precision=mixed_precision,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": _DEFAULT_ITERATIONS[self.levels],
            "dap": True,
            "upnet": True,
            "corr_flow": False,
            "prev_flow": False,
            "corr_grad_stop": False,
        }
        return {
            "type": self.type,
            "parameters": {
                "mixed-precision": self.mixed_precision,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "dap-init": self.dap_init,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "encoder-type": self.encoder_type,
                "context-type": self.context_type,
                "mnet-norm": self.mnet_norm,
                "share-dicl": self.share_dicl,
                "share-rnn": self.share_rnn,
                "corr-type": self.corr_type,
                "corr-args": self.corr_args,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
                "upsample-hidden": self.upsample_hidden,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return MultiLevelSequenceAdapter(self)


@register_model
class RaftPlusDiclCtfL2(_CtfModel):
    """``raft+dicl/ctf-l2`` (reference raft_dicl_ctf_l2.py)."""

    type = "raft+dicl/ctf-l2"
    levels = 2


@register_model
class RaftPlusDiclCtfL3(_CtfModel):
    """``raft+dicl/ctf-l3`` — the thesis flagship
    (reference raft_dicl_ctf_l3.py:79-260)."""

    type = "raft+dicl/ctf-l3"
    levels = 3


@register_model
class RaftPlusDiclCtfL4(_CtfModel):
    """``raft+dicl/ctf-l4`` (reference raft_dicl_ctf_l4.py)."""

    type = "raft+dicl/ctf-l4"
    levels = 4


@register_loss
class RestrictedMultiLevelSequenceLoss(Loss):
    """``raft+dicl/mlseq-restricted``: per-level loss masked by the
    displacement still representable at that level, relative to the
    previous-iterate flow (reference raft_dicl_ctf_l3.py:401-473).

    Consumes (prev, flow) pairs, i.e. the model must run with
    ``prev_flow=True``.
    """

    type = "raft+dicl/mlseq-restricted"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {
            "ord": 1,
            "gamma": 0.85,
            "alpha": (0.38, 0.6, 1.0),
            "scale": 1.0,
            "delta_range": (128, 64, 32),
            "delta_mode": "bilinear",
        }
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                alpha=(0.4, 1.0), scale=1.0, delta_range=(128, 64, 32),
                delta_mode="bilinear"):
        if delta_mode != "bilinear":
            raise ValueError(f"unsupported delta_mode '{delta_mode}'")

        th, tw = target.shape[1:3]
        valid_f = valid.astype(jnp.float32)

        loss = 0.0
        for i_level, level in enumerate(result):
            n = len(level)
            for i_seq, (flow_prev, flow) in enumerate(level):
                weight = alpha[i_level] * gamma ** (n - i_seq - 1)

                flow = upsample_flow_to(flow, (th, tw))
                flow_prev = upsample_flow_to(flow_prev, (th, tw))

                # restrict to displacements the level can still correct
                delta = jnp.abs(target - flow_prev)
                in_range = jnp.logical_and(
                    delta[..., 0] <= delta_range[i_level],
                    delta[..., 1] <= delta_range[i_level],
                )
                mask = valid_f * in_range.astype(jnp.float32)

                dist = jnp.linalg.norm(flow - target, ord=float(ord), axis=-1)
                # empty mask contributes zero (the reference skips the term)
                mean = jnp.sum(dist * mask) / jnp.maximum(jnp.sum(mask), 1.0)
                loss = loss + weight * mean

        return loss * scale
