"""wip/warp/1: coarse-to-fine warping with a recurrent level unit
(kept-registered experiment).

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/outdated/wip_warp.py: a GA-Net p26 feature pyramid, one
shared recurrent level unit (per-level cost volumes + DAP, a motion
encoder, SepConv GRU, and a soft-argmin flow head) applied coarse-to-fine
with backwards feature warping; the hidden state carries across levels
half-nearest / half-bilinear-doubled. The auxiliary multiscale corr losses
consume example costs computed by the model (``corr_loss_examples=True``),
like raft/cl.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from ....ops.sample import sample_bilinear
from ....ops.upsample import interpolate_bilinear, upsample_flow_2x
from ...common import warp
from ...common.blocks.dicl import DisplacementAwareProjection, MatchingNet
from ...common.encoders.dicl import FeatureEncoderGa
from ...config import register_loss, register_model
from ...model import Loss, Model, ModelAdapter, Result
from ..dicl import displaced_pair_volume, soft_argmin_flow
from ..raft import SepConvGru

_LEVELS = 5  # 1/4 .. 1/64


def _nearest_resize(x, size):
    b, h, w, c = x.shape
    nh, nw = size
    iy = (jnp.arange(nh) * h // nh).astype(jnp.int32)
    ix = (jnp.arange(nw) * w // nw).astype(jnp.int32)
    return x[:, iy][:, :, ix]


class _MotionEncoder(nn.Module):
    """cost volume + context features + flow → motion features
    (reference wip_warp.py:160-181)."""

    output_channels: int

    @nn.compact
    def __call__(self, cvol, cmap, flow):
        b, h, w, du, dv = cvol.shape
        x = jnp.concatenate(
            (cvol.reshape(b, h, w, du * dv), cmap, flow), axis=-1)

        x = nn.leaky_relu(nn.Conv(128, (3, 3))(x))
        x = nn.leaky_relu(nn.Conv(128, (3, 3))(x))
        return nn.Conv(self.output_channels, (3, 3))(x)


class _ScoreFlowHead(nn.Module):
    """Hidden state → displacement scores → soft-argmin delta flow
    (reference wip_warp.py:184-226)."""

    disp_range: tuple = (5, 5)

    @nn.compact
    def __call__(self, x):
        b, h, w, _ = x.shape
        du, dv = 2 * self.disp_range[0] + 1, 2 * self.disp_range[1] + 1

        score = nn.leaky_relu(nn.Conv(256, (1, 1))(x))
        score = nn.leaky_relu(nn.Conv(du * dv, (1, 1))(score))
        return soft_argmin_flow(score.reshape(b, h, w, du, dv))


class _RecurrentLevelUnit(nn.Module):
    """Warp → per-level cost volume → motion encoder → GRU → flow head
    (reference wip_warp.py:249-288). setup-style so the matching nets are
    reachable for the example-cost computation."""

    disp_range: tuple
    feat_channels: int
    hidden_dim: int

    def setup(self):
        self.cvnets = [MatchingNet() for _ in range(_LEVELS)]
        self.daps = [DisplacementAwareProjection(self.disp_range)
                     for _ in range(_LEVELS)]
        self.menet = _MotionEncoder(96 - 2)
        self.gru = SepConvGru(self.hidden_dim)
        self.fhead = _ScoreFlowHead()

    def __call__(self, fmap1, fmap2, h, flow, i, train=False, frozen_bn=False):
        fmap2, _mask = warp.warp_backwards(fmap2, jax.lax.stop_gradient(flow))

        mvol = displaced_pair_volume(fmap1, fmap2, self.disp_range)
        cvol = self.cvnets[i](mvol, train, frozen_bn)  # (B, H, W, du, dv)
        cvol = self.daps[i](cvol)

        x = self.menet(cvol, fmap1, flow)
        x = jnp.concatenate((x, flow), axis=-1)

        h = self.gru(h, x)
        d = self.fhead(h)

        return h, flow + d

    def example_costs(self, level, mvol, train=False, frozen_bn=False):
        return self.cvnets[level](mvol, train, frozen_bn)


class WipWarpModule(nn.Module):
    """Coarse-to-fine warping network (reference WipModule,
    wip_warp.py:292-385)."""

    disp_range: tuple = (5, 5)
    feat_channels: int = 32
    hidden_dim: int = 96

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 corr_loss_examples=False):
        fnet = FeatureEncoderGa(output_dim=self.feat_channels, depth=6,
                                out_levels=(1, 2, 3, 4, 5))
        f1, f2 = fnet((img1, img2), train, frozen_bn)  # finest-first, 1/4..1/64

        rlu = _RecurrentLevelUnit(self.disp_range, self.feat_channels,
                                  self.hidden_dim)

        b = img1.shape[0]
        h6, w6 = f1[-1].shape[1:3]
        flow = jnp.zeros((b, h6, w6, 2), jnp.float32)
        h = jnp.zeros((b, h6, w6, self.hidden_dim), jnp.float32)

        out = []
        for li in range(_LEVELS - 1, -1, -1):  # coarse → fine
            if f1[li].shape[1:3] != flow.shape[1:3]:
                flow = upsample_flow_2x(flow)
                size = f1[li].shape[1:3]
                c = self.hidden_dim // 2
                h = jnp.concatenate((
                    _nearest_resize(h[..., :c], size),
                    interpolate_bilinear(h[..., c:], size) * 2.0,
                ), axis=-1)

            h, flow = rlu(f1[li], f2[li], h, flow, li, train, frozen_bn)
            out.append(flow)

        result = {
            "flow": list(reversed(out)),  # finest first
            "f1": list(f1),
            "f2": list(f2),
        }

        if corr_loss_examples:
            pos, neg = [], []
            rng = (self.make_rng("permute") if self.has_rng("permute")
                   else jax.random.PRNGKey(0))
            for i, feats in enumerate(list(f1) + list(f2)):
                bb, hh, ww, cc = feats.shape
                level = i % _LEVELS

                pair = jnp.concatenate((feats, feats), axis=-1)
                pos.append(rlu.example_costs(
                    level, pair[:, None, None], train, frozen_bn))

                perm = jax.random.permutation(
                    jax.random.fold_in(rng, i), hh * ww)
                shuffled = feats.reshape(bb, hh * ww, cc)[:, perm]
                shuffled = shuffled.reshape(bb, hh, ww, cc)
                pair = jnp.concatenate((feats, shuffled), axis=-1)
                neg.append(rlu.example_costs(
                    level, pair[:, None, None], train, frozen_bn))

            result["corr_pos"] = pos
            result["corr_neg"] = neg

        return result


@register_model
class WipWarp(Model):
    """``wip/warp/1`` (reference wip_warp.py:388-427)."""

    type = "wip/warp/1"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            disp_range=tuple(p.get("disp-range", (5, 5))),
            arguments=cfg.get("arguments", {}),
        )

    def __init__(self, disp_range=(5, 5), arguments={}):
        self.disp_range = tuple(disp_range)
        super().__init__(WipWarpModule(disp_range=self.disp_range),
                         arguments=arguments)

    def get_config(self):
        return {
            "type": self.type,
            "parameters": {"disp-range": list(self.disp_range)},
            "arguments": dict(self.arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return WipAdapter(self)


class WipAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return WipResult(result, original_shape)


class WipResult(Result):
    """Dict result with finest-first flow list; final() upsamples to the
    input resolution (reference wip_warp.py:430-463)."""

    def __init__(self, output, target_shape):
        super().__init__()
        self.result = output
        self.shape = target_shape

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result
        return {
            k: [x[batch_index : batch_index + 1] for x in v]
            for k, v in self.result.items()
        }

    def final(self):
        flow = jax.lax.stop_gradient(self.result["flow"][0])

        _, fh, fw, _ = flow.shape
        th, tw = self.shape

        flow = interpolate_bilinear(flow, (th, tw))
        return flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)

    def intermediate_flow(self):
        return self.result["flow"]


@register_loss
class WipMultiscaleLoss(Loss):
    """``wip/warp/multiscale`` (reference wip_warp.py:465-522)."""

    type = "wip/warp/multiscale"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {"ord": 2, "mode": "bilinear", "alpha": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def _flow_loss(self, result, target, valid, weights, ord, mode,
                   valid_range):
        if mode != "bilinear":
            raise ValueError(f"unsupported upsampling mode '{mode}'")

        th, tw = target.shape[1:3]
        valid_f = valid.astype(jnp.float32)

        loss = 0.0
        flows = result["flow"]
        for i, flow in enumerate(flows):
            _, fh, fw, _ = flow.shape
            flow = interpolate_bilinear(flow, (th, tw))
            flow = flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)

            mask = valid_f
            if valid_range is not None:
                mask = mask * (jnp.abs(target[..., 0]) < valid_range[i][0])
                mask = mask * (jnp.abs(target[..., 1]) < valid_range[i][1])

            if ord == "robust":
                dist = (jnp.abs(flow - target).sum(axis=-1) + 1e-8) ** 0.4
            else:
                dist = jnp.linalg.norm(flow - target, ord=float(ord), axis=-1)

            mean = jnp.sum(dist * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            loss = loss + weights[i] * mean

        return loss / len(flows)

    def compute(self, model, result, target, valid, weights, ord=2,
                mode="bilinear", alpha=1.0, valid_range=None):
        # ``alpha`` is accepted (and ignored) for config round-tripping:
        # the reference's get_config advertises it on every multiscale
        # variant while only the corr-hinge/corr-mse subclasses consume
        # it (reference wip_warp.py:477,544,600) — a full config written
        # by gencfg must load back through this base class
        return self._flow_loss(result, target, valid, weights, ord, mode,
                               valid_range)


@register_loss
class WipMultiscaleCorrHingeLoss(WipMultiscaleLoss):
    """``wip/warp/multiscale+corr_hinge`` (reference wip_warp.py:525-578);
    requires the model argument ``corr_loss_examples=True``."""

    type = "wip/warp/multiscale+corr_hinge"

    def get_config(self):
        default_args = {"ord": 2, "mode": "bilinear", "margin": 1.0,
                        "alpha": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, weights, ord=2,
                mode="bilinear", margin=1.0, alpha=1.0, valid_range=None):
        flow_loss = self._flow_loss(result, target, valid, weights, ord,
                                    mode, valid_range)

        corr_loss = 0.0
        for pos in result["corr_pos"]:
            corr_loss += jnp.maximum(margin - pos, 0.0).mean()
        for neg in result["corr_neg"]:
            corr_loss += jnp.maximum(margin + neg, 0.0).mean()

        return flow_loss + alpha * corr_loss


@register_loss
class WipMultiscaleCorrMseLoss(WipMultiscaleLoss):
    """``wip/warp/multiscale+corr_mse`` (reference wip_warp.py:581-631);
    requires the model argument ``corr_loss_examples=True``."""

    type = "wip/warp/multiscale+corr_mse"

    def get_config(self):
        default_args = {"ord": 2, "mode": "bilinear", "alpha": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, weights, ord=2,
                mode="bilinear", alpha=1.0, valid_range=None):
        flow_loss = self._flow_loss(result, target, valid, weights, ord,
                                    mode, valid_range)

        corr_loss = 0.0
        for pos in result["corr_pos"]:
            corr_loss += jnp.square(pos - 1.0).mean()
        for neg in result["corr_neg"]:
            corr_loss += jnp.square(neg).mean()

        return flow_loss + alpha * corr_loss
