"""raft+dicl/sl-ca: the single-level hybrid with cross-attention embeddings.

Config wrapper (reference src/models/impls/outdated/raft_dicl_sl_ca.py)
around the raft+dicl/sl module with the ``dicl-emb`` correlation module —
pair embeddings attended by the cost softmax.
"""

from ...config import register_model
from ...model import Model, ModelAdapter
from ..raft import RaftAdapter
from ..raft_dicl_sl import RaftPlusDiclModule


@register_model
class RaftPlusDiclSlCa(Model):
    type = "raft+dicl/sl-ca"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 32),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            embedding_channels=p.get("embedding-channels", 32),
            dap_init=p.get("dap-init", "identity"),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            mnet_norm=p.get("mnet-norm", "batch"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_radius=4,
                 corr_channels=32, context_channels=128,
                 recurrent_channels=128, embedding_channels=32,
                 dap_init="identity", encoder_norm="instance",
                 context_norm="batch", mnet_norm="batch", arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.embedding_channels = embedding_channels
        self.dap_init = dap_init
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.mnet_norm = mnet_norm

        super().__init__(
            RaftPlusDiclModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_radius=corr_radius, corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dap_init=dap_init,
                encoder_norm=encoder_norm, context_norm=context_norm,
                mnet_norm=mnet_norm, corr_type="dicl-emb",
                corr_args={"embedding_dim": embedding_channels},
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {"iterations": 12, "dap": True, "upnet": True}
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "embedding-channels": self.embedding_channels,
                "dap-init": self.dap_init,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "mnet-norm": self.mnet_norm,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
