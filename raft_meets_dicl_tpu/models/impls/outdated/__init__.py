"""Kept-registered experiments (reference src/models/impls/outdated/)."""

from . import raft_cl, raft_dicl_sl_ca, wip_recwarp, wip_warp

__all__ = ["raft_cl", "raft_dicl_sl_ca", "wip_recwarp", "wip_warp"]
