"""wip/warp/2: recurrent warping units, coarse-to-fine
(kept-registered experiment).

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/outdated/wip_recwarp.py: per-level recurrent flow units —
sample the second frame's features over a displaced window around the
current coordinates ("warp with context"), run a MatchingNet + DAP, and
regress a soft-argmin delta — applied coarse-to-fine over a GA-Net p26
pyramid with coordinate upsampling between levels.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from ....ops.upsample import interpolate_bilinear
from ...common.blocks.dicl import DisplacementAwareProjection, MatchingNet
from ...common.corr.common import sample_window, stack_pair
from ...common.encoders.dicl import FeatureEncoderGa
from ...common.grid import coordinate_grid
from ...config import register_model
from ...model import Model, ModelAdapter, Result
from .wip_warp import WipAdapter  # noqa: F401  (shape parity for tooling)

_LEVELS = 5  # 1/4 .. 1/64


class _RecurrentFlowUnit(nn.Module):
    """Window-sampled cost volume → DAP → soft-argmin coordinate update
    (reference wip_recwarp.py:106-178)."""

    feature_channels: int
    disp_range: tuple

    @nn.compact
    def __call__(self, feat1, feat2, coords, dap=True, train=False,
                 frozen_bn=False):
        from ..dicl import soft_argmin_flow

        assert self.disp_range[0] == self.disp_range[1], (
            "square displacement windows only"
        )
        radius = self.disp_range[0]

        window = sample_window(feat2, coords, radius)
        feat = stack_pair(feat1, window)

        cost = MatchingNet()(feat, train, frozen_bn)  # (B, H, W, du, dv)
        if dap:
            cost = DisplacementAwareProjection(self.disp_range)(cost)

        delta = soft_argmin_flow(cost)
        return coords + delta


class WipRecWarpModule(nn.Module):
    """Coarse-to-fine recurrent warping (reference WipModule,
    wip_recwarp.py:181-236)."""

    feature_channels: int = 32
    disp: tuple = ((3, 3),) * _LEVELS

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=(1,) * _LEVELS, dap=True):
        fnet = FeatureEncoderGa(output_dim=self.feature_channels, depth=6,
                                out_levels=(1, 2, 3, 4, 5))
        f1, f2 = fnet((img1, img2), train, frozen_bn)  # finest-first

        rfus = [
            _RecurrentFlowUnit(self.feature_channels, tuple(self.disp[i]))
            for i in range(_LEVELS)
        ]

        b = img1.shape[0]
        coords = coordinate_grid(b, *f1[-1].shape[1:3])

        out = []
        for i in range(_LEVELS - 1, -1, -1):  # coarse → fine
            h2, w2 = f1[i].shape[1:3]

            if coords.shape[1:3] != (h2, w2):
                h1, w1 = coords.shape[1:3]
                coords = interpolate_bilinear(coords, (h2, w2))
                coords = coords * jnp.asarray([w2 / w1, h2 / h1],
                                              dtype=coords.dtype)

            coords0 = coordinate_grid(b, h2, w2)

            for _ in range(iterations[i]):
                coords = rfus[i](f1[i], f2[i], coords, dap=dap, train=train,
                                 frozen_bn=frozen_bn)
                out.append(coords - coords0)

        return out


@register_model
class WipRecWarp(Model):
    """``wip/warp/2`` (reference wip_recwarp.py:237-283)."""

    type = "wip/warp/2"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            feature_channels=p.get("feature-channels", 32),
            disp=p.get("disp-range", [(3, 3)] * _LEVELS),
            arguments=cfg.get("arguments", {}),
        )

    def __init__(self, feature_channels=32, disp=((3, 3),) * _LEVELS,
                 arguments={}):
        self.feature_channels = feature_channels
        self.disp = tuple(tuple(d) for d in disp)

        super().__init__(
            WipRecWarpModule(feature_channels=feature_channels,
                             disp=self.disp),
            arguments=arguments,
        )

    def get_config(self):
        default_args = {"iterations": [1] * _LEVELS, "dap": True}
        return {
            "type": self.type,
            "parameters": {
                "feature-channels": self.feature_channels,
                "disp-range": [list(d) for d in self.disp],
            },
            "arguments": default_args | self.arguments,
        }

    def get_adapter(self) -> ModelAdapter:
        return WipRecWarpAdapter(self)


class WipRecWarpAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return WipRecWarpResult(result, original_shape)


class WipRecWarpResult(Result):
    """Per-iteration flow list; stored finest-first like the reference
    (wip_recwarp.py:286-314)."""

    def __init__(self, output, shape):
        super().__init__()
        self.result = list(reversed(output))
        self.shape = shape

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result
        return [x[batch_index : batch_index + 1] for x in self.result]

    def final(self):
        flow = jax.lax.stop_gradient(self.result[0])

        _, fh, fw, _ = flow.shape
        th, tw = self.shape

        flow = interpolate_bilinear(flow, (th, tw))
        return flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)

    def intermediate_flow(self):
        return self.result
