"""raft/cl: RAFT with hierarchical cost learning (kept-registered experiment).

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/outdated/raft_cl.py: a GA-Net hourglass produces raw
ladder features; the frame-2 head builds a 1/8..1/64 pyramid, the frame-1
head lifts every level to 1/8 through learned convex 2x upsampling chains;
a per-level MatchingNet+DAP correlation module feeds the RAFT GRU.

The auxiliary correlation losses (hinge / mse over self- and permuted
feature pairs) need the matching networks' parameters, which a pure loss
function cannot reach — so here the *model* computes those example costs
when asked (``corr_loss_examples=True``, drawing the permutation from the
'permute' rng stream) and the losses consume them from the result dict.
"""

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ....ops.upsample import interpolate_bilinear
from ...common.blocks.dicl import (
    ConvBlock,
    DisplacementAwareProjection,
    MatchingNet,
)
from ...common.corr.common import sample_window, stack_pair
from ...common.encoders.dicl import FeatureEncoderGa
from ...common.encoders.raft import FeatureEncoderS3
from ...common.grid import coordinate_grid
from ...config import register_loss, register_model
from ...model import Loss, Model, ModelAdapter, Result
from ..raft import BasicUpdateBlock, Up8Network

_LEVELS = 4  # 1/8 .. 1/64
_LADDER_CHANNELS = {3: 64, 4: 96, 5: 128, 6: 160}


class _FeatureNetDown(nn.Module):
    """Frame-2 head: per-level output convs (reference raft_cl.py:87-106)."""

    output_dim: int

    @nn.compact
    def __call__(self, ladder, train=False, frozen_bn=False):
        return tuple(
            ConvBlock(self.output_dim)(x, train, frozen_bn) for x in ladder
        )


class _FeatureNetUp(nn.Module):
    """Frame-1 head: per-level output convs + learned convex 2x upsampling
    chains lifting every level to 1/8 (reference raft_cl.py:108-175)."""

    output_dim: int

    @nn.compact
    def __call__(self, ladder, train=False, frozen_bn=False):
        x3, x4, x5, x6 = ladder  # finest first, raw ladder channels

        u = [ConvBlock(self.output_dim)(x, train, frozen_bn) for x in ladder]

        def genmask(x):
            c = x.shape[-1]
            m = nn.relu(nn.Conv(c, (3, 3))(x))
            m = nn.Conv(9, (1, 1))(m)
            return nn.softmax(m, axis=-1)  # (B, h, w, 9)

        def upsample(mask, v):
            # the reference's mask-weighted 2x block upsampling
            # (raft_cl.py:135-151): coarse pixels expand into the mask's 2x2
            # sub-blocks, weighted over 9 softmax channels that sum to one
            b, h, w, _ = mask.shape
            c = v.shape[-1]
            m = mask.reshape(b, h // 2, 2, w // 2, 2, 9)
            vv = v[:, :, None, :, None, None, :]  # (B, h/2, 1, w/2, 1, 1, C)
            out = (m[..., None] * vv).sum(axis=5)  # (B, h/2, 2, w/2, 2, C)
            return out.reshape(b, h, w, c)

        m5 = genmask(x5)
        m4 = genmask(x4)
        m3 = genmask(x3)

        u6 = upsample(m3, upsample(m4, upsample(m5, u[3])))
        u5 = upsample(m3, upsample(m4, u[2]))
        u4 = upsample(m3, u[1])

        return u[0], u4, u5, u6  # all at 1/8


class _ClCorrelationModule(nn.Module):
    """Per-level MatchingNet cost over displaced windows
    (reference raft_cl.py:180-246). ``setup``-style so the example-cost
    computation for the auxiliary correlation losses runs through the SAME
    matching networks as the lookup."""

    feature_dim: int
    radius: int
    dap_init: str = "identity"

    def setup(self):
        self.mnets = [MatchingNet() for _ in range(_LEVELS)]
        self.daps = [
            DisplacementAwareProjection((self.radius, self.radius),
                                        init=self.dap_init)
            for _ in range(_LEVELS)
        ]

    def __call__(self, fmap1, fmap2, coords, dap=True, train=False,
                 frozen_bn=False):
        b, h, w, _ = coords.shape
        k = 2 * self.radius + 1

        out = []
        for i, (f1, f2) in enumerate(zip(fmap1, fmap2)):
            window = sample_window(f2, coords / 2 ** i, self.radius)
            mvol = stack_pair(f1, window)

            cost = self.mnets[i](mvol, train, frozen_bn)
            if dap:
                cost = self.daps[i](cost)

            out.append(cost.reshape(b, h, w, k * k))

        return jnp.concatenate(out, axis=-1)

    def example_costs(self, level, mvol, train=False, frozen_bn=False):
        """Level ``level``'s matching net applied to a prepared volume."""
        return self.mnets[level](mvol, train, frozen_bn)


class RaftClModule(nn.Module):
    """raft/cl network (reference RaftModule, raft_cl.py:251-339)."""

    dap_init: str = "identity"
    corr_radius: int = 3
    feature_dim: int = 32

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=12, upnet=True, flow_init=None,
                 corr_loss_examples=False):
        hdim = cdim = 128

        fnet = FeatureEncoderGa(depth=6, out_levels=(2, 3, 4, 5), heads=False)
        fnet_u = _FeatureNetUp(self.feature_dim)
        fnet_d = _FeatureNetDown(self.feature_dim)

        l1, l2 = fnet((img1, img2), train, frozen_bn)
        fmap1 = fnet_u(l1, train, frozen_bn)
        fmap2 = fnet_d(l2, train, frozen_bn)

        cnet = FeatureEncoderS3(output_dim=hdim + cdim, norm_type="batch")
        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])

        b, hc, wc, _ = fmap1[0].shape
        coords0 = coordinate_grid(b, hc, wc)
        coords1 = coords0 + flow_init if flow_init is not None else coords0

        cvol = _ClCorrelationModule(self.feature_dim, self.corr_radius,
                                    self.dap_init)
        update = BasicUpdateBlock(hdim)
        upnet8 = Up8Network()

        out = []
        for _ in range(iterations):
            coords1 = jax.lax.stop_gradient(coords1)
            flow = coords1 - coords0

            corr = cvol(fmap1, fmap2, coords1, train=train, frozen_bn=frozen_bn)

            h, d = update(h, x, corr, flow)
            coords1 = coords1 + d
            flow = coords1 - coords0

            flow_up = upnet8(h, flow)
            if not upnet:
                flow_up = 8.0 * interpolate_bilinear(
                    flow, (img1.shape[1], img1.shape[2]))
            out.append(flow_up)

        result = {"flow": out, "f1": list(fmap1), "f2": list(fmap2)}

        if corr_loss_examples:
            # self-pair and permuted-pair matching costs for the auxiliary
            # correlation losses, through the cvol's own matching nets (the
            # reference computes these inside the loss with the live module,
            # raft_cl.py:474-503)
            pos, neg = [], []
            # permutation stream; falls back to a fixed key when the caller
            # provides no 'permute' rng (the negatives are then static)
            rng = (self.make_rng("permute") if self.has_rng("permute")
                   else jax.random.PRNGKey(0))
            for i, feats in enumerate(list(fmap1) + list(fmap2)):
                bb, hh, ww, cc = feats.shape
                level = i % _LEVELS

                pair = jnp.concatenate((feats, feats), axis=-1)
                pos.append(cvol.example_costs(
                    level, pair[:, None, None], train, frozen_bn))

                perm = jax.random.permutation(
                    jax.random.fold_in(rng, i), hh * ww)
                shuffled = feats.reshape(bb, hh * ww, cc)[:, perm]
                shuffled = shuffled.reshape(bb, hh, ww, cc)
                pair = jnp.concatenate((feats, shuffled), axis=-1)
                neg.append(cvol.example_costs(
                    level, pair[:, None, None], train, frozen_bn))

            result["corr_pos"] = pos
            result["corr_neg"] = neg

        return result


@register_model
class RaftCl(Model):
    """``raft/cl`` (reference raft_cl.py:341-378)."""

    type = "raft/cl"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dap_init=p.get("dap-init", "identity"),
            corr_radius=p.get("corr-radius", 3),
            arguments=cfg.get("arguments", {}),
        )

    def __init__(self, dap_init="identity", corr_radius=3, arguments={}):
        self.dap_init = dap_init
        self.corr_radius = corr_radius

        super().__init__(
            RaftClModule(dap_init=dap_init, corr_radius=corr_radius),
            arguments=arguments,
        )

    def get_config(self):
        default_args = {"iterations": 12, "upnet": True}
        return {
            "type": self.type,
            "parameters": {
                "corr-radius": self.corr_radius,
                "dap-init": self.dap_init,
            },
            "arguments": default_args | self.arguments,
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftClAdapter(self)


class RaftClAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return RaftClResult(result)


class RaftClResult(Result):
    """Dict result: 'flow' sequence + feature lists
    (reference raft_cl.py:389-406)."""

    def __init__(self, output):
        super().__init__()
        self.result = output

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result
        return {
            k: [x[batch_index : batch_index + 1] for x in v]
            for k, v in self.result.items()
        }

    def final(self):
        return self.result["flow"][-1]

    def intermediate_flow(self):
        return self.result["flow"]


@register_loss
class ClSequenceLoss(Loss):
    """``raft/cl/sequence`` (reference raft_cl.py:408-448)."""

    type = "raft/cl/sequence"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {"ord": 1, "gamma": 0.8, "scale": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def _flow_loss(self, result, target, valid, ord, gamma):
        flows = result["flow"]
        n = len(flows)
        valid_f = valid.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(valid_f), 1.0)

        loss = 0.0
        for i, flow in enumerate(flows):
            weight = gamma ** (n - i - 1)
            dist = jnp.linalg.norm(flow - target, ord=float(ord), axis=-1)
            loss = loss + weight * jnp.sum(dist * valid_f) / denom
        return loss

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                scale=1.0):
        return self._flow_loss(result, target, valid, ord, gamma) * scale


@register_loss
class ClSequenceCorrHingeLoss(ClSequenceLoss):
    """``raft/cl/sequence+corr_hinge`` (reference raft_cl.py:452-503);
    requires the model argument ``corr_loss_examples=True``."""

    type = "raft/cl/sequence+corr_hinge"

    def get_config(self):
        default_args = {"ord": 1, "gamma": 0.8, "alpha": 1.0, "margin": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                alpha=1.0, margin=1.0):
        flow_loss = self._flow_loss(result, target, valid, ord, gamma)

        corr_loss = 0.0
        for pos in result["corr_pos"]:
            corr_loss += jnp.maximum(margin - pos, 0.0).mean()
        for neg in result["corr_neg"]:
            corr_loss += jnp.maximum(margin + neg, 0.0).mean()

        return flow_loss + alpha * corr_loss


@register_loss
class ClSequenceCorrMseLoss(ClSequenceLoss):
    """``raft/cl/sequence+corr_mse`` (reference raft_cl.py:506-554);
    requires the model argument ``corr_loss_examples=True``."""

    type = "raft/cl/sequence+corr_mse"

    def get_config(self):
        default_args = {"ord": 1, "gamma": 0.8, "alpha": 1.0}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                alpha=1.0):
        flow_loss = self._flow_loss(result, target, valid, ord, gamma)

        corr_loss = 0.0
        for pos in result["corr_pos"]:
            corr_loss += jnp.square(pos - 1.0).mean()
        for neg in result["corr_neg"]:
            corr_loss += jnp.square(neg).mean()

        return flow_loss + alpha * corr_loss
