"""RAFT single-level: the baseline with a 1-level correlation pyramid.

Thin config wrapper (reference src/models/impls/raft_sl.py:7-104) around
the RAFT module with ``corr_levels=1`` — the windowed lookup runs against
the full-resolution volume only.
"""

from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import RaftAdapter, RaftModule


@register_model
class RaftSl(Model):
    type = "raft/sl"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 256),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            corr_reg_type=p.get("corr-reg-type", "softargmax"),
            corr_reg_args=p.get("corr-reg-args", {}),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_radius=4,
                 corr_channels=256, context_channels=128,
                 recurrent_channels=128, encoder_norm="instance",
                 context_norm="batch", corr_reg_type="softargmax",
                 corr_reg_args={}, arguments={}, on_epoch_args={},
                 on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)

        super().__init__(
            RaftModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_levels=1, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels,
                encoder_norm=encoder_norm, context_norm=context_norm,
                corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args),
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {"iterations": 12, "upnet": True, "corr_flow": False}
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
