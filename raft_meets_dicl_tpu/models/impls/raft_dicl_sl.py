"""RAFT+DICL single-level hybrid: RAFT skeleton, DICL cost volume.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_dicl_sl.py:11-110 — the core hybrid of the thesis:
s3 encoders and the RAFT GRU update loop, but the correlation features come
from a learned DICL matching network evaluated on the (2r+1)² displaced
window around the current flow (``make_cmod``), optionally with a
soft-argmax corr-flow readout per iteration.

The iteration loop is an ``nn.scan`` over the shared-module step body
(``raft_dicl_ctf._CtfStep``) with rematerialization like the RAFT
baseline; when batch norm actually trains, the loop unrolls so the
sequential running-stat updates match the reference's.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.upsample import interpolate_bilinear
from ..common import corr as corr_mod
from ..common import encoders
from ..common.grid import coordinate_grid
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, RaftAdapter, Up8Network
from .raft_dicl_ctf import _CtfStep


class RaftPlusDiclModule(nn.Module):
    """RAFT+DICL single-level network (reference raft_dicl_sl.py:11-110)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_radius: int = 4
    corr_channels: int = 32
    context_channels: int = 128
    recurrent_channels: int = 128
    dap_init: str = "identity"
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    mnet_norm: str = "batch"
    corr_type: str = "dicl"
    corr_args: dict = None
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    encoder_type: str = "raft"
    context_type: str = "raft"
    remat: bool = True
    unroll: bool = False

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False, iterations=12,
                 dap=True, upnet=True, corr_flow=False, corr_grad_stop=False,
                 flow_init=None, hidden_init=None, return_state=False):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        dt = jnp.bfloat16 if self.mixed_precision else None

        fnet = encoders.make_encoder_s3(
            self.encoder_type, output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout, dtype=dt,
        )
        cnet = encoders.make_encoder_s3(
            self.context_type, output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout, dtype=dt,
        )

        fmap1, fmap2 = fnet((img1, img2), train, frozen_bn)
        fmap1 = fmap1.astype(jnp.float32)
        fmap2 = fmap2.astype(jnp.float32)

        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])
        if hidden_init is not None:
            h = hidden_init.astype(h.dtype)

        b, hc, wc, _ = fmap1.shape
        coords0 = coordinate_grid(b, hc, wc)
        flow = (flow_init.astype(jnp.float32) if flow_init is not None
                else jnp.zeros((b, hc, wc, 2), jnp.float32))  # graftlint: disable=f32-literal -- flow fields are f32 by convention

        corr_args = dict(self.corr_args or {})
        # matching nets follow the mixed policy (cost comes back f32);
        # "dot" has no net to cast
        if dt is not None and self.corr_type in ("dicl", "dicl-1x1",
                                                 "dicl-emb"):
            corr_args.setdefault("dtype", dt)
        cvol = corr_mod.make_cmod(
            self.corr_type, self.corr_channels, radius=self.corr_radius,
            dap_init=self.dap_init, norm_type=self.mnet_norm,
            **corr_args,
        )
        # always created (and called in the step) so a '+dap' readout's
        # params exist regardless of the static corr_flow switch
        reg = corr_mod.make_flow_regression(
            self.corr_type, self.corr_reg_type, self.corr_radius,
            **(self.corr_reg_args or {}),
        )
        update = BasicUpdateBlock(hdim, dtype=dt)
        upnet8 = nn.remat(Up8Network, prevent_cse=False)(
            dtype=dt, name="Up8Network_0")

        # one (remat-wrapped) step body serves both realizations; scan
        # unless batch norm is actually training (the lifted scan
        # broadcasts batch_stats read-only; see raft_dicl_ctf)
        if self.remat:
            body = nn.remat(
                _CtfStep, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "corr_features"),
            )
        else:
            body = _CtfStep
        shared = dict(
            cmod=cvol, reg=reg, update=update, dap=dap,
            corr_grad_stop=corr_grad_stop, train=train, frozen_bn=frozen_bn,
        )

        if self.unroll or (train and not frozen_bn):
            step = body(**shared)
            carry = (h, flow)
            flows, hiddens, readouts = [], [], []
            for _ in range(iterations):
                carry, (fl, hi, ro, _pv) = step(
                    carry, jnp.zeros((0,), dtype=jnp.bfloat16), fmap1, fmap2, x, coords0)
                flows.append(fl)
                hiddens.append(hi)
                readouts.append(ro)
            h, flow = carry

            flows = jnp.stack(flows)
            hiddens = jnp.stack(hiddens)
            readouts = jnp.stack(readouts)
        else:
            step = nn.scan(
                body,
                variable_broadcast=["params", "batch_stats"],
                split_rngs={"params": False, "dropout": True},
                in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast),
                out_axes=0,
            )(**shared)

            (h, flow), (flows, hiddens, readouts, _prevs) = step(
                (h, flow), jnp.zeros((iterations, 0), dtype=jnp.bfloat16),
                fmap1, fmap2, x, coords0,
            )

        # convex 8x upsampling, batched over all iterations at once
        full_shape = (img1.shape[1], img1.shape[2])
        flows_flat = flows.reshape(iterations * b, hc, wc, 2)
        hiddens_flat = hiddens.reshape(iterations * b, hc, wc, hdim)

        ups = upnet8(hiddens_flat, flows_flat)
        if not upnet:
            ups = 8.0 * interpolate_bilinear(flows_flat, full_shape)
        ups = ups.reshape(iterations, b, *full_shape, 2)

        out = [ups[i] for i in range(iterations)]

        if corr_flow:
            out = [[readouts[i] for i in range(iterations)], out]

        if return_state:
            final = flows[-1]
            if iterations >= 2:
                prev = flows[-2]
            elif flow_init is not None:
                prev = flow_init.astype(jnp.float32)
            else:
                prev = jnp.zeros_like(final)
            diff = (final - prev).astype(jnp.float32)
            delta = jnp.sqrt(jnp.mean(jnp.sum(diff * diff, axis=-1),
                                      axis=(1, 2)))
            return out, {"flow": final, "hidden": h, "delta": delta}

        return out


@register_model
class RaftPlusDicl(Model):
    """``raft+dicl/sl`` (reference raft_dicl_sl.py:113-257)."""

    type = "raft+dicl/sl"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        param_cfg = cfg["parameters"]
        return cls(
            dropout=float(param_cfg.get("dropout", 0.0)),
            mixed_precision=bool(param_cfg.get("mixed-precision", False)),
            corr_radius=param_cfg.get("corr-radius", 4),
            corr_channels=param_cfg.get("corr-channels", 32),
            context_channels=param_cfg.get("context-channels", 128),
            recurrent_channels=param_cfg.get("recurrent-channels", 128),
            dap_init=param_cfg.get("dap-init", "identity"),
            encoder_norm=param_cfg.get("encoder-norm", "instance"),
            context_norm=param_cfg.get("context-norm", "batch"),
            mnet_norm=param_cfg.get("mnet-norm", "batch"),
            corr_type=param_cfg.get("corr-type", "dicl"),
            corr_args=param_cfg.get("corr-args", {}),
            corr_reg_type=param_cfg.get("corr-reg-type", "softargmax"),
            corr_reg_args=param_cfg.get("corr-reg-args", {}),
            encoder_type=param_cfg.get("encoder-type", "raft"),
            context_type=param_cfg.get("context-type", "raft"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_radius=4,
                 corr_channels=32, context_channels=128, recurrent_channels=128,
                 dap_init="identity", encoder_norm="instance",
                 context_norm="batch", mnet_norm="batch", corr_type="dicl",
                 corr_args={}, corr_reg_type="softargmax", corr_reg_args={},
                 encoder_type="raft", context_type="raft", arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.dap_init = dap_init
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.mnet_norm = mnet_norm
        self.corr_type = corr_type
        self.corr_args = dict(corr_args)
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)
        self.encoder_type = encoder_type
        self.context_type = context_type

        super().__init__(
            RaftPlusDiclModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_radius=corr_radius, corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dap_init=dap_init,
                encoder_norm=encoder_norm, context_norm=context_norm,
                mnet_norm=mnet_norm, corr_type=corr_type,
                corr_args=dict(corr_args), corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args), encoder_type=encoder_type,
                context_type=context_type,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": 12,
            "dap": True,
            "corr_flow": False,
            "corr_grad_stop": False,
            "upnet": True,
        }
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "dap-init": self.dap_init,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "mnet-norm": self.mnet_norm,
                "corr-type": self.corr_type,
                "corr-args": self.corr_args,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
                "encoder-type": self.encoder_type,
                "context-type": self.context_type,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
