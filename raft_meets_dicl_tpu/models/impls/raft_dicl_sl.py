"""RAFT+DICL single-level hybrid: RAFT skeleton, DICL cost volume.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_dicl_sl.py:11-110 — the core hybrid of the thesis:
s3 encoders and the RAFT GRU update loop, but the correlation features come
from a learned DICL matching network evaluated on the (2r+1)² displaced
window around the current flow (``make_cmod``), optionally with a
soft-argmax corr-flow readout per iteration.

The iteration loop is an ``nn.scan`` with rematerialization like the RAFT
baseline; the matching net's batch-norm statistics ride the scan carry so
each iteration updates them exactly like the reference's sequential calls.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.upsample import interpolate_bilinear
from ..common import corr as corr_mod
from ..common import encoders
from ..common.grid import coordinate_grid
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, RaftAdapter, Up8Network


class _Step(nn.Module):
    """One GRU iteration — the nn.scan body; carry is (hidden, coords1)."""

    corr_radius: int
    recurrent_channels: int
    corr_type: str
    corr_args: dict
    corr_reg_type: str
    corr_reg_args: dict
    dap_init: str
    mnet_norm: str
    upnet: bool
    dap: bool
    corr_flow: bool
    corr_grad_stop: bool
    full_shape: Tuple[int, int]
    train: bool = False
    frozen_bn: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, carry, fmap1, fmap2, x, coords0):
        h, coords1 = carry
        coords1 = jax.lax.stop_gradient(coords1)
        flow = coords1 - coords0

        cvol = corr_mod.make_cmod(
            self.corr_type, fmap1.shape[-1], radius=self.corr_radius,
            dap_init=self.dap_init, norm_type=self.mnet_norm, **self.corr_args,
        )
        corr = cvol(fmap1, fmap2, coords1, dap=self.dap, train=self.train,
                    frozen_bn=self.frozen_bn)

        # always call the readout so its params exist regardless of the
        # static switch (a '+dap' readout has a trainable projection); XLA
        # removes the unused branch
        reg = corr_mod.make_flow_regression(
            self.corr_type, self.corr_reg_type, self.corr_radius,
            **self.corr_reg_args,
        )
        readout = flow + reg(corr)
        corr_flows = (readout,) if self.corr_flow else ()

        if self.corr_grad_stop:
            corr = jax.lax.stop_gradient(corr)

        h, d = BasicUpdateBlock(self.recurrent_channels, dtype=self.dtype)(
            h, x, corr, flow)

        coords1 = coords1 + d
        flow = coords1 - coords0

        flow_up_net = Up8Network(dtype=self.dtype)(h, flow)
        if self.upnet:
            flow_up = flow_up_net
        else:
            flow_up = 8.0 * interpolate_bilinear(flow, self.full_shape)

        return (h, coords1), (flow_up, corr_flows)


class RaftPlusDiclModule(nn.Module):
    """RAFT+DICL single-level network (reference raft_dicl_sl.py:11-110)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_radius: int = 4
    corr_channels: int = 32
    context_channels: int = 128
    recurrent_channels: int = 128
    dap_init: str = "identity"
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    mnet_norm: str = "batch"
    corr_type: str = "dicl"
    corr_args: dict = None
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    encoder_type: str = "raft"
    context_type: str = "raft"
    remat: bool = True

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False, iterations=12,
                 dap=True, upnet=True, corr_flow=False, corr_grad_stop=False,
                 flow_init=None):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        dt = jnp.bfloat16 if self.mixed_precision else None

        fnet = encoders.make_encoder_s3(
            self.encoder_type, output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout, dtype=dt,
        )
        cnet = encoders.make_encoder_s3(
            self.context_type, output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout, dtype=dt,
        )

        fmap1, fmap2 = fnet((img1, img2), train, frozen_bn)
        fmap1 = fmap1.astype(jnp.float32)
        fmap2 = fmap2.astype(jnp.float32)

        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])

        b, hc, wc, _ = fmap1.shape
        coords0 = coordinate_grid(b, hc, wc)
        coords1 = coords0 + flow_init if flow_init is not None else coords0

        # the matching net carries batch-norm statistics, which flax cannot
        # create inside an nn.scan body — so unlike the pure RAFT scan loop,
        # iterations unroll statically (iteration count is a static arg
        # anyway) with remat per step for the same activation-memory story
        body = nn.remat(_Step, prevent_cse=False) if self.remat else _Step
        step = body(
            corr_radius=self.corr_radius,
            recurrent_channels=hdim,
            corr_type=self.corr_type,
            corr_args=self.corr_args or {},
            corr_reg_type=self.corr_reg_type,
            corr_reg_args=self.corr_reg_args or {},
            dap_init=self.dap_init,
            mnet_norm=self.mnet_norm,
            upnet=upnet,
            dap=dap,
            corr_flow=corr_flow,
            corr_grad_stop=corr_grad_stop,
            full_shape=(img1.shape[1], img1.shape[2]),
            train=train,
            frozen_bn=frozen_bn,
        )

        out, out_corr = [], []
        carry = (h, coords1)
        for _ in range(iterations):
            carry, (flow_up, corr_flows) = step(carry, fmap1, fmap2, x, coords0)
            out.append(flow_up)
            if corr_flow:
                out_corr.append(corr_flows[0])

        if corr_flow:
            return [out_corr, out]

        return out


@register_model
class RaftPlusDicl(Model):
    """``raft+dicl/sl`` (reference raft_dicl_sl.py:113-257)."""

    type = "raft+dicl/sl"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        param_cfg = cfg["parameters"]
        return cls(
            dropout=float(param_cfg.get("dropout", 0.0)),
            mixed_precision=bool(param_cfg.get("mixed-precision", False)),
            corr_radius=param_cfg.get("corr-radius", 4),
            corr_channels=param_cfg.get("corr-channels", 32),
            context_channels=param_cfg.get("context-channels", 128),
            recurrent_channels=param_cfg.get("recurrent-channels", 128),
            dap_init=param_cfg.get("dap-init", "identity"),
            encoder_norm=param_cfg.get("encoder-norm", "instance"),
            context_norm=param_cfg.get("context-norm", "batch"),
            mnet_norm=param_cfg.get("mnet-norm", "batch"),
            corr_type=param_cfg.get("corr-type", "dicl"),
            corr_args=param_cfg.get("corr-args", {}),
            corr_reg_type=param_cfg.get("corr-reg-type", "softargmax"),
            corr_reg_args=param_cfg.get("corr-reg-args", {}),
            encoder_type=param_cfg.get("encoder-type", "raft"),
            context_type=param_cfg.get("context-type", "raft"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_radius=4,
                 corr_channels=32, context_channels=128, recurrent_channels=128,
                 dap_init="identity", encoder_norm="instance",
                 context_norm="batch", mnet_norm="batch", corr_type="dicl",
                 corr_args={}, corr_reg_type="softargmax", corr_reg_args={},
                 encoder_type="raft", context_type="raft", arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.dap_init = dap_init
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.mnet_norm = mnet_norm
        self.corr_type = corr_type
        self.corr_args = dict(corr_args)
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)
        self.encoder_type = encoder_type
        self.context_type = context_type

        super().__init__(
            RaftPlusDiclModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_radius=corr_radius, corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dap_init=dap_init,
                encoder_norm=encoder_norm, context_norm=context_norm,
                mnet_norm=mnet_norm, corr_type=corr_type,
                corr_args=dict(corr_args), corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args), encoder_type=encoder_type,
                context_type=context_type,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": 12,
            "dap": True,
            "corr_flow": False,
            "corr_grad_stop": False,
            "upnet": True,
        }
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "dap-init": self.dap_init,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "mnet-norm": self.mnet_norm,
                "corr-type": self.corr_type,
                "corr-args": self.corr_args,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
                "encoder-type": self.encoder_type,
                "context-type": self.context_type,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
