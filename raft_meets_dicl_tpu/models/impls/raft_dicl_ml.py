"""RAFT+DICL multi-level lookup hybrid.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_dicl_ml.py: asymmetric encoders — frame 1 as a
dilated feature *stack* at 1/8 resolution, frame 2 as a strided feature
*pyramid* (or a pooled variant for both) — and one fused correlation
module that samples every level around a single 1/8 flow estimate and
runs shared-or-per-level MatchingNets, with DAP applied per level
('separate') or across all levels at once ('full').
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.pool import avg_pool2d, max_pool2d
from ...ops.upsample import interpolate_bilinear
from ..common.blocks.dicl import DisplacementAwareProjection, MatchingNet
from ..common.blocks.raft import ResidualBlock, kaiming_normal
from ..common.corr.common import (
    dicl_fast_enabled,
    record_matching_bytes,
    sample_window,
    sample_window_fast,
)
from ..common.encoders.raft import FeatureEncoderS3
from ..common.grid import coordinate_grid
from ..common.norm import Norm2d
from ..common.util import identity_1x1_init
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, RaftAdapter, Up8Network, make_flow_regression


class _OutputNet(nn.Module):
    """Dilated 3x3 + 1x1 level head (reference raft_dicl_ml.py:18-32)."""

    output_dim: int
    dilation: int = 1
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        x = nn.Conv(128, (3, 3), kernel_dilation=self.dilation,
                    kernel_init=kaiming_normal)(x)
        x = Norm2d(self.norm_type, 8)(x, train and not frozen_bn)
        x = nn.relu(x)
        return nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal)(x)


class StackEncoder(nn.Module):
    """Frame-1 stack: all levels at 1/8, increasing dilation
    (reference raft_dicl_ml.py:35-101)."""

    output_dim: int
    levels: int = 4
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        if not 1 <= self.levels <= 4:
            raise ValueError("levels must be between 1 and 4 (inclusive)")

        outs = [_OutputNet(self.output_dim, 1, self.norm_type)(x, train, frozen_bn)]
        for lvl in range(1, self.levels):
            x = ResidualBlock(256, self.norm_type, stride=1)(x, train, frozen_bn)
            outs.append(_OutputNet(self.output_dim, 2 ** lvl, self.norm_type)(
                x, train, frozen_bn))

        return outs[0] if len(outs) == 1 else tuple(outs)


class PyramidEncoder(nn.Module):
    """Frame-2 pyramid: strided stages 384/576/864
    (reference raft_dicl_ml.py:104-170)."""

    output_dim: int
    levels: int = 4
    norm_type: str = "batch"

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        if not 1 <= self.levels <= 4:
            raise ValueError("levels must be between 1 and 4 (inclusive)")

        outs = [_OutputNet(self.output_dim, 1, self.norm_type)(x, train, frozen_bn)]
        for channels in (384, 576, 864)[: self.levels - 1]:
            x = ResidualBlock(channels, self.norm_type, stride=2)(x, train, frozen_bn)
            outs.append(_OutputNet(self.output_dim, 1, self.norm_type)(
                x, train, frozen_bn))

        return outs[0] if len(outs) == 1 else tuple(outs)


class MlCorrelationModule(nn.Module):
    """Fused multi-level DICL lookup around one 1/8 flow estimate
    (reference raft_dicl_ml.py:236-345).

    Matching runs through the shared fast path by default: the fused
    window sampler, the unstacked ``(f1, window)`` MatchingNet form (the
    stacked (B, du, dv, H, W, 2C) volume never materializes), matching in
    ``dtype`` when set, and ONE batched MatchingNet evaluation per GRU
    iteration instead of a python loop of ``levels`` hourglass calls —
    all levels share the 1/8 output resolution and channel count, so they
    concatenate along the batch when ``share=True`` and ride a
    stacked-params ``vmap`` when ``share=False``. Parameter paths and
    checkpoints are unchanged: the per-level modules below own the
    parameters in both paths; the vmap only *reads* their subtrees.

    The reference per-level loop remains the fallback (``fast=False``,
    the ``RMD_DICL_FAST=0`` escape hatch, initialization, live-BN
    training — whose sequential running-stat updates the batched call
    cannot reproduce — and, for ``share=False``, non-TPU backends by
    default, where CPU XLA's grouped-conv backward is pathological).
    """

    feature_dim: int
    levels: int
    radius: int
    dap_init: str = "identity"
    dap_type: str = "separate"
    norm_type: str = "batch"
    share: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, fmap1, fmap2, coords, dap=True, mask_costs=(),
                 train=False, frozen_bn=False, fast=None):
        if self.dap_type not in ("full", "separate"):
            raise ValueError(f"DAP type '{self.dap_type}' not supported")

        b, h, w, _ = coords.shape
        k = 2 * self.radius + 1

        if fast is None:
            # share=False batches via stacked-params vmap → grouped convs,
            # whose backward is pathological on CPU XLA (~6x the loop) but
            # MXU-native on TPU: off-TPU the default stays on the loop
            # (explicit fast=True still forces the batched path)
            fast = dicl_fast_enabled() and (
                self.share or jax.default_backend() == "tpu")
        # live batch norm computes per-level statistics sequentially (the
        # shared-params case updates running stats levels-times per call);
        # only the reference loop reproduces that
        live_bn = train and not frozen_bn and self.norm_type == "batch"
        fast = fast and not live_bn and not self.is_initializing()

        if self.share:
            shared_mnet = MatchingNet(norm_type=self.norm_type,
                                      dtype=self.dtype)
            mnets = [shared_mnet] * self.levels
            if self.dap_type == "separate":
                shared_dap = DisplacementAwareProjection(
                    (self.radius, self.radius), init=self.dap_init)
                daps = [shared_dap] * self.levels
        else:
            mnets = [MatchingNet(norm_type=self.norm_type, dtype=self.dtype)
                     for _ in range(self.levels)]
            if self.dap_type == "separate":
                daps = [DisplacementAwareProjection(
                            (self.radius, self.radius), init=self.dap_init)
                        for _ in range(self.levels)]

        sample = sample_window_fast if fast else sample_window
        windows = [sample(f2, coords / 2 ** i, self.radius)
                   for i, f2 in enumerate(fmap2)]
        fmap1 = list(fmap1)
        if self.dtype is not None:
            fmap1 = [f1.astype(self.dtype) for f1 in fmap1]
            windows = [win.astype(self.dtype) for win in windows]
        if not self.is_initializing():
            record_matching_bytes(*fmap1, *windows)

        if fast:
            costs = self._batched_costs(mnets, fmap1, windows, train,
                                        frozen_bn)
        else:
            # reference per-level loop (also the init path: creates the
            # per-level parameters at their checkpoint paths)
            costs = [mnets[i]((f1, win), train, frozen_bn)
                     for i, (f1, win) in enumerate(zip(fmap1, windows))]

        out = []
        for i, cost in enumerate(costs):       # cost: (B, H, W, du, dv)
            if i + 3 in mask_costs:
                cost = jnp.zeros_like(cost)

            if dap and self.dap_type == "separate":
                cost = daps[i](cost)

            out.append(cost.reshape(b, h, w, k * k))

        out = jnp.concatenate(out, axis=-1)

        if self.dap_type == "full":
            # always create the full-DAP params for config stability
            full = nn.Conv(
                self.levels * k * k, (1, 1), use_bias=False,
                kernel_init=(identity_1x1_init if self.dap_init == "identity"
                             else nn.initializers.lecun_normal()),
            )
            projected = full(out)
            if dap:
                out = projected

        return out

    def _batched_costs(self, mnets, fmap1, windows, train, frozen_bn):
        """One MatchingNet evaluation for all levels.

        ``share=True``: the levels concatenate along the batch axis into
        the single shared net — identical parameters, identical per-element
        math (norms are frozen/stat-free on this path).

        ``share=False``: the per-level parameter subtrees created by the
        reference loop are read from this module's scope, stacked along a
        level axis, and the net runs under ``jax.vmap`` — XLA sees one
        grouped convolution per layer instead of ``levels`` separate
        hourglasses, while the checkpoint keeps its per-level
        ``MatchingNet_i`` layout (the stacking is a trace-time view).
        """
        if self.share:
            f1a = jnp.concatenate(fmap1, axis=0)
            wina = jnp.concatenate(windows, axis=0)
            cost = mnets[0]((f1a, wina), train, frozen_bn)  # (L·B, H, W, k, k)
            return [cost[i * fmap1[0].shape[0]:(i + 1) * fmap1[0].shape[0]]
                    for i in range(self.levels)]

        variables = []
        for i in range(self.levels):
            vs = {"params": self.scope.get_variable(
                "params", f"MatchingNet_{i}")}
            if self.has_variable("batch_stats", f"MatchingNet_{i}"):
                vs["batch_stats"] = self.scope.get_variable(
                    "batch_stats", f"MatchingNet_{i}")
            variables.append(vs)
        stacked = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *variables)

        template = MatchingNet(norm_type=self.norm_type, dtype=self.dtype,
                               parent=None)

        def one(vs, f1, win):
            return template.apply(vs, (f1, win), train, frozen_bn)

        costs = jax.vmap(one)(stacked, jnp.stack(fmap1), jnp.stack(windows))
        return [costs[i] for i in range(self.levels)]


class _MlStep(nn.Module):
    """One GRU iteration — the nn.scan body. Parameterized submodules are
    shared instances from the parent scope (see raft_dicl_ctf._CtfStep for
    why: identical parameter paths to the unrolled loop)."""

    cvol: nn.Module
    reg: nn.Module
    update: nn.Module
    dap: bool
    mask_costs: tuple
    corr_grad_stop: bool
    train: bool
    frozen_bn: bool

    @nn.compact
    def __call__(self, carry, _, fmap1, fmap2, x, coords0):
        from jax.ad_checkpoint import checkpoint_name

        # flow (not coords1) carry: program boundaries replay the same
        # ``coords0 + flow`` reconstruction, so ladder rungs chain
        # bit-exactly (see raft._RaftStep)
        h, flow = carry
        flow = jax.lax.stop_gradient(flow)
        coords1 = coords0 + flow

        corr = self.cvol(fmap1, fmap2, coords1, dap=self.dap,
                         mask_costs=self.mask_costs, train=self.train,
                         frozen_bn=self.frozen_bn)
        corr = checkpoint_name(corr, "corr_features")

        corr_flows = tuple(flow + d for d in self.reg(corr))

        if self.corr_grad_stop:
            corr = jax.lax.stop_gradient(corr)

        h, d = self.update(h, x, corr, flow)
        coords1 = coords1 + d
        flow = coords1 - coords0

        return (h, flow), (flow, h, corr_flows)


class RaftPlusDiclMlModule(nn.Module):
    """RAFT+DICL multi-level network (reference raft_dicl_ml.py:350-470)."""

    dropout: float = 0.0
    mixed_precision: bool = False
    corr_levels: int = 4
    corr_radius: int = 4
    corr_channels: int = 32
    context_channels: int = 128
    recurrent_channels: int = 128
    dap_init: str = "identity"
    dap_type: str = "separate"
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    mnet_norm: str = "batch"
    encoder_type: str = "raft-cnn"
    share_dicl: bool = False
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    remat: bool = True
    unroll: bool = False

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False, iterations=12,
                 dap=True, upnet=True, corr_flow=False, corr_grad_stop=False,
                 flow_init=None, hidden_init=None, mask_costs=(),
                 return_state=False):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        dt = jnp.bfloat16 if self.mixed_precision else None

        # asymmetric encoders (reference :173-236)
        if self.encoder_type == "raft-cnn":
            base = FeatureEncoderS3(output_dim=256, norm_type=self.encoder_norm,
                                    dropout=0, dtype=dt)
            b1, b2 = base((img1, img2), train, frozen_bn)
            b1 = b1.astype(jnp.float32)
            b2 = b2.astype(jnp.float32)

            fmap1 = StackEncoder(self.corr_channels, self.corr_levels,
                                 self.encoder_norm)(b1, train, frozen_bn)
            fmap2 = PyramidEncoder(self.corr_channels, self.corr_levels,
                                   self.encoder_norm)(b2, train, frozen_bn)
            fmap1 = (fmap1,) if self.corr_levels == 1 else fmap1
            fmap2 = (fmap2,) if self.corr_levels == 1 else fmap2
        elif self.encoder_type in ("raft-avgpool", "raft-maxpool"):
            pool = avg_pool2d if self.encoder_type.endswith("avgpool") else max_pool2d
            base = FeatureEncoderS3(output_dim=self.corr_channels,
                                    norm_type=self.encoder_norm, dropout=0,
                                    dtype=dt)
            f1, f2 = base((img1, img2), train, frozen_bn)
            f1 = f1.astype(jnp.float32)
            f2 = f2.astype(jnp.float32)

            fmap1 = tuple([f1] * self.corr_levels)
            pyramid = [f2]
            for _ in range(1, self.corr_levels):
                pyramid.append(pool(pyramid[-1], 2))
            fmap2 = tuple(pyramid)
        else:
            raise ValueError(f"unknown encoder type: '{self.encoder_type}'")

        cnet = FeatureEncoderS3(output_dim=hdim + cdim,
                                norm_type=self.context_norm,
                                dropout=self.dropout, dtype=dt)
        ctx = cnet(img1, train, frozen_bn)
        h = jnp.tanh(ctx[..., :hdim])
        x = nn.relu(ctx[..., hdim:])
        if hidden_init is not None:
            h = hidden_init.astype(h.dtype)

        b, hc, wc, _ = fmap1[0].shape
        coords0 = coordinate_grid(b, hc, wc)
        flow = (flow_init.astype(jnp.float32) if flow_init is not None
                else jnp.zeros((b, hc, wc, 2), jnp.float32))  # graftlint: disable=f32-literal -- flow fields are f32 by convention

        # the matching nets follow the model's mixed policy (the reference
        # autocast covers them too; cost volumes come back f32 regardless)
        cvol = MlCorrelationModule(
            feature_dim=self.corr_channels, levels=self.corr_levels,
            radius=self.corr_radius, dap_init=self.dap_init,
            dap_type=self.dap_type, norm_type=self.mnet_norm,
            share=self.share_dicl, dtype=dt,
        )
        reg = make_flow_regression(self.corr_reg_type, self.corr_levels,
                                   self.corr_radius,
                                   **(self.corr_reg_args or {}))
        update = BasicUpdateBlock(hdim, dtype=dt)
        # remat'd, pinned name (the wrapper would otherwise prefix the path)
        upnet8 = nn.remat(Up8Network, prevent_cse=False)(
            dtype=dt, name="Up8Network_0")

        # one (remat-wrapped) step body serves both realizations: the
        # lax.scan (default) or a python-unrolled loop (`unroll=True`,
        # kept as a debugging escape hatch)
        if self.remat:
            body = nn.remat(
                _MlStep, prevent_cse=False,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "corr_features"),
            )
        else:
            body = _MlStep
        shared = dict(
            cvol=cvol, reg=reg, update=update, dap=dap,
            mask_costs=tuple(mask_costs), corr_grad_stop=corr_grad_stop,
            train=train, frozen_bn=frozen_bn,
        )

        if self.unroll:
            step = body(**shared)
            carry = (h, flow)
            flows, hiddens, corr_flows = [], [], []
            for _ in range(iterations):
                carry, (fl, hi, cf) = step(
                    carry, jnp.zeros((0,), dtype=jnp.bfloat16), fmap1, fmap2, x, coords0)
                flows.append(fl)
                hiddens.append(hi)
                corr_flows.append(cf)
            h, flow = carry

            flows = jnp.stack(flows)
            hiddens = jnp.stack(hiddens)
            corr_flows = tuple(
                jnp.stack([cf[lvl] for cf in corr_flows])
                for lvl in range(self.corr_levels)
            )
        else:
            # train-mode batch norm mutates running stats every iteration;
            # carrying the batch_stats collection through the scan keeps
            # the sequential-update semantics of the unrolled loop while
            # compiling ONE step body — the 12x-unrolled train graph of
            # this model (12 iterations x 4 MatchingNets) is what crashed
            # the TPU compiler service at the reference Things config
            # (b6/384x704; see PERF.md round 5)
            live_bn = train and not frozen_bn
            step = nn.scan(
                body,
                variable_broadcast=(["params"] if live_bn
                                    else ["params", "batch_stats"]),
                variable_carry=["batch_stats"] if live_bn else [],
                split_rngs={"params": False, "dropout": True},
                in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast),
                out_axes=0,
            )(**shared)

            (h, flow), (flows, hiddens, corr_flows) = step(
                (h, flow), jnp.zeros((iterations, 0), dtype=jnp.bfloat16),
                fmap1, fmap2, x, coords0,
            )

        # convex 8x upsampling, batched over all iterations at once
        full_shape = (img1.shape[1], img1.shape[2])
        flows_flat = flows.reshape(iterations * b, hc, wc, 2)
        hiddens_flat = hiddens.reshape(iterations * b, hc, wc, hdim)

        ups = upnet8(hiddens_flat, flows_flat)
        if not upnet:
            ups = 8.0 * interpolate_bilinear(flows_flat, full_shape)
        ups = ups.reshape(iterations, b, *full_shape, 2)

        out = [ups[i] for i in range(iterations)]

        if corr_flow:
            out_corr = [
                [corr_flows[lvl][i] for i in range(iterations)]
                for lvl in range(self.corr_levels)
            ]
            out = [*reversed(out_corr), out]  # coarse-to-fine, then final

        if return_state:
            final = flows[-1]
            if iterations >= 2:
                prev = flows[-2]
            elif flow_init is not None:
                prev = flow_init.astype(jnp.float32)
            else:
                prev = jnp.zeros_like(final)
            diff = (final - prev).astype(jnp.float32)
            delta = jnp.sqrt(jnp.mean(jnp.sum(diff * diff, axis=-1),
                                      axis=(1, 2)))
            return out, {"flow": final, "hidden": h, "delta": delta}

        return out


@register_model
class RaftPlusDiclMl(Model):
    """``raft+dicl/ml`` (reference raft_dicl_ml.py:448-582)."""

    type = "raft+dicl/ml"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            mixed_precision=bool(p.get("mixed-precision", False)),
            corr_levels=p.get("corr-levels", 4),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 32),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            dap_init=p.get("dap-init", "identity"),
            dap_type=p.get("dap-type", "separate"),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            mnet_norm=p.get("mnet-norm", "batch"),
            encoder_type=p.get("encoder-type", "raft-cnn"),
            share_dicl=p.get("share-dicl", False),
            corr_reg_type=p.get("corr-reg-type", "softargmax"),
            corr_reg_args=p.get("corr-reg-args", {}),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, mixed_precision=False, corr_levels=4,
                 corr_radius=4, corr_channels=32, context_channels=128,
                 recurrent_channels=128, dap_init="identity",
                 dap_type="separate", encoder_norm="instance",
                 context_norm="batch", mnet_norm="batch",
                 encoder_type="raft-cnn", share_dicl=False,
                 corr_reg_type="softargmax", corr_reg_args={}, arguments={},
                 on_epoch_args={}, on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.mixed_precision = mixed_precision
        self.corr_levels = corr_levels
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.dap_init = dap_init
        self.dap_type = dap_type
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.mnet_norm = mnet_norm
        self.encoder_type = encoder_type
        self.share_dicl = share_dicl
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)

        super().__init__(
            RaftPlusDiclMlModule(
                dropout=dropout, mixed_precision=mixed_precision,
                corr_levels=corr_levels, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dap_init=dap_init,
                dap_type=dap_type, encoder_norm=encoder_norm,
                context_norm=context_norm, mnet_norm=mnet_norm,
                encoder_type=encoder_type, share_dicl=share_dicl,
                corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args),
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": 12,
            "dap": True,
            "upnet": True,
            "corr_flow": False,
            "corr_grad_stop": False,
            "mask_costs": [],
        }
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "mixed-precision": self.mixed_precision,
                "corr-levels": self.corr_levels,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "dap-init": self.dap_init,
                "dap-type": self.dap_type,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "mnet-norm": self.mnet_norm,
                "encoder-type": self.encoder_type,
                "share-dicl": self.share_dicl,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return RaftAdapter(self)
