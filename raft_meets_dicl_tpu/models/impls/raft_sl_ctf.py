"""Coarse-to-fine RAFT (single-level correlation per pyramid level).

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/raft_sl_ctf_l{2,3,4}.py — one parametric module instead of
three hand-written variants: pyramid encoders, a per-level all-pairs
correlation volume with ``corr_levels=1`` (einsum volume + MXU-friendly
windowed lookup from ops.corr), shared-or-separate update blocks, hidden-
state upsampling, bilinear inter-level flow upsampling, and convex Up8 on
the finest level.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...ops.corr import all_pairs_correlation, lookup_pyramid
from ...ops.upsample import interpolate_bilinear, upsample_flow_2x
from ..common import encoders, hsup
from ..common.adapters.mlseq import MultiLevelSequenceAdapter
from ..common.grid import coordinate_grid
from ..config import register_model
from ..model import Model, ModelAdapter
from .raft import BasicUpdateBlock, Up8Network, make_flow_regression
from .raft_dicl_ctf import _DEFAULT_ITERATIONS, _PYRAMIDS


class _SlCtfStep(nn.Module):
    """One GRU iteration at a fixed pyramid level — the nn.scan body.

    Parameterized submodules (regression, update block) are shared
    instances from the parent scope so parameter paths are identical to
    the unrolled loop and level sharing composes with the scan."""

    reg: nn.Module
    update: nn.Module
    corr_radius: int
    corr_grad_stop: bool

    @nn.compact
    def __call__(self, carry, _, pyramid, x, coords0):
        from jax.ad_checkpoint import checkpoint_name

        h, coords1 = carry
        coords1 = jax.lax.stop_gradient(coords1)
        flow = coords1 - coords0

        corr = lookup_pyramid(pyramid, coords1, self.corr_radius)
        corr = checkpoint_name(corr, "corr_features")

        # always called so a '+dap' readout's params exist regardless of
        # the static switch; XLA removes the unused branch
        readout = flow + self.reg(corr)[0]

        if self.corr_grad_stop:
            corr = jax.lax.stop_gradient(corr)

        h, d = self.update(h, x, corr, flow)
        coords1 = coords1 + d

        return (h, coords1), (coords1 - coords0, h, readout)


class RaftSlCtfModule(nn.Module):
    """Coarse-to-fine RAFT over ``levels`` pyramid levels, single-level
    all-pairs correlation per level."""

    levels: int = 3
    corr_radius: int = 4
    corr_channels: int = 256
    context_channels: int = 128
    recurrent_channels: int = 128
    dropout: float = 0.0
    encoder_norm: str = "instance"
    context_norm: str = "batch"
    encoder_type: str = "raft"
    context_type: str = "raft"
    corr_reg_type: str = "softargmax"
    corr_reg_args: dict = None
    share_rnn: bool = True
    upsample_hidden: str = "none"
    remat: bool = True
    unroll: bool = False

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False,
                 iterations=None, upnet=True, corr_flow=False,
                 corr_grad_stop=False):
        hdim = self.recurrent_channels
        cdim = self.context_channels
        b, h, w = img1.shape[0], img1.shape[1], img1.shape[2]

        iterations = tuple(iterations or _DEFAULT_ITERATIONS[self.levels])
        assert len(iterations) == self.levels

        level_ids = tuple(range(self.levels + 2, 2, -1))  # coarse→fine

        fnet = _PYRAMIDS[self.levels](
            self.encoder_type, output_dim=self.corr_channels,
            norm_type=self.encoder_norm, dropout=self.dropout,
        )
        cnet = _PYRAMIDS[self.levels](
            self.context_type, output_dim=hdim + cdim,
            norm_type=self.context_norm, dropout=self.dropout,
        )

        f1, f2 = fnet((img1, img2), train, frozen_bn)
        ctx = cnet(img1, train, frozen_bn)

        hidden = [jnp.tanh(c[..., :hdim]) for c in ctx]
        context = [nn.relu(c[..., hdim:]) for c in ctx]

        if self.share_rnn:
            shared_update = BasicUpdateBlock(hdim)
            shared_hup = hsup.make_hidden_state_upsampler(
                self.upsample_hidden, hdim)
            updates = {lvl: shared_update for lvl in level_ids}
            hups = {lvl: shared_hup for lvl in level_ids[1:]}
        else:
            updates = {lvl: BasicUpdateBlock(hdim) for lvl in level_ids}
            hups = {
                lvl: hsup.make_hidden_state_upsampler(self.upsample_hidden, hdim)
                for lvl in level_ids[1:]
            }

        regs = {
            lvl: make_flow_regression(
                self.corr_reg_type, 1, self.corr_radius,
                **(self.corr_reg_args or {}),
            )
            for lvl in level_ids
        }
        # remat'd batched convex upsampler, pinned name for checkpoint
        # stability
        upnet8 = nn.remat(Up8Network, prevent_cse=False)(name="Up8Network_0")

        out = []
        flow = None
        h_state = None

        for li, lvl in enumerate(level_ids):
            scale = 2 ** lvl
            lh, lw = h // scale, w // scale
            fine_idx = lvl - 3
            n_iter = iterations[li]

            coords0 = coordinate_grid(b, lh, lw)
            if flow is None:
                coords1 = coords0
            else:
                flow = upsample_flow_2x(flow)
                coords1 = coords0 + flow

            if h_state is None:
                h_state = hidden[fine_idx]
            else:
                h_state = hups[lvl](h_state, hidden[fine_idx])

            x = context[fine_idx]
            finest = li == self.levels - 1

            # single-level all-pairs volume for this pyramid level
            pyramid = (all_pairs_correlation(f1[fine_idx], f2[fine_idx]),)

            # one nn.scan per level with remat — the raft/baseline
            # iteration discipline (models/impls/raft.py:322-352); the
            # body is batch-norm-free, so the scan covers training too
            if self.remat:
                body = nn.remat(
                    _SlCtfStep, prevent_cse=False,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "corr_features"),
                )
            else:
                body = _SlCtfStep
            shared = dict(
                reg=regs[lvl], update=updates[lvl],
                corr_radius=self.corr_radius,
                corr_grad_stop=corr_grad_stop,
            )

            if self.unroll:
                step = body(**shared)
                carry = (h_state, coords1)
                flows, hiddens, readouts = [], [], []
                for _ in range(n_iter):
                    carry, (fl, hi, ro) = step(
                        carry, jnp.zeros((0,)), pyramid, x, coords0)
                    flows.append(fl)
                    hiddens.append(hi)
                    readouts.append(ro)
                h_state, coords1 = carry

                flows = jnp.stack(flows)
                hiddens = jnp.stack(hiddens)
                readouts = jnp.stack(readouts)
            else:
                step = nn.scan(
                    body,
                    variable_broadcast="params",
                    split_rngs={"params": False, "dropout": True},
                    in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast),
                    out_axes=0,
                )(**shared)

                (h_state, coords1), (flows, hiddens, readouts) = step(
                    (h_state, coords1), jnp.zeros((n_iter, 0)),
                    pyramid, x, coords0,
                )

            flow = flows[-1]

            if finest:
                # convex 8x upsampling, batched over all iterations at once
                flows_flat = flows.reshape(n_iter * b, lh, lw, 2)
                hidden_flat = hiddens.reshape(n_iter * b, lh, lw, hdim)
                ups = upnet8(hidden_flat, flows_flat)
                if not upnet:
                    ups = 8.0 * interpolate_bilinear(flows_flat, (h, w))
                ups = ups.reshape(n_iter, b, h, w, 2)
                out_lvl = [ups[i] for i in range(n_iter)]
            else:
                out_lvl = [flows[i] for i in range(n_iter)]

            if corr_flow:
                out.append([readouts[i] for i in range(n_iter)])
            out.append(out_lvl)

        return out


class _SlCtfModel(Model):
    """Shared config wrapper for the three registered level counts."""

    levels = None

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        p = cfg["parameters"]
        return cls(
            dropout=float(p.get("dropout", 0.0)),
            corr_radius=p.get("corr-radius", 4),
            corr_channels=p.get("corr-channels", 256),
            context_channels=p.get("context-channels", 128),
            recurrent_channels=p.get("recurrent-channels", 128),
            encoder_norm=p.get("encoder-norm", "instance"),
            context_norm=p.get("context-norm", "batch"),
            encoder_type=p.get("encoder-type", "raft"),
            context_type=p.get("context-type", "raft"),
            share_rnn=p.get("share-rnn", True),
            corr_reg_type=p.get("corr-reg-type", "softargmax"),
            corr_reg_args=p.get("corr-reg-args", {}),
            upsample_hidden=p.get("upsample-hidden", "none"),
            arguments=cfg.get("arguments", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": True}),
            on_epoch_args=cfg.get("on-epoch", {}),
        )

    def __init__(self, dropout=0.0, corr_radius=4, corr_channels=256,
                 context_channels=128, recurrent_channels=128,
                 encoder_norm="instance", context_norm="batch",
                 encoder_type="raft", context_type="raft", share_rnn=True,
                 corr_reg_type="softargmax", corr_reg_args={},
                 upsample_hidden="none", arguments={}, on_epoch_args={},
                 on_stage_args={"freeze_batchnorm": True}):
        self.dropout = dropout
        self.corr_radius = corr_radius
        self.corr_channels = corr_channels
        self.context_channels = context_channels
        self.recurrent_channels = recurrent_channels
        self.encoder_norm = encoder_norm
        self.context_norm = context_norm
        self.encoder_type = encoder_type
        self.context_type = context_type
        self.share_rnn = share_rnn
        self.corr_reg_type = corr_reg_type
        self.corr_reg_args = dict(corr_reg_args)
        self.upsample_hidden = upsample_hidden

        super().__init__(
            RaftSlCtfModule(
                levels=self.levels, corr_radius=corr_radius,
                corr_channels=corr_channels,
                context_channels=context_channels,
                recurrent_channels=recurrent_channels, dropout=dropout,
                encoder_norm=encoder_norm, context_norm=context_norm,
                encoder_type=encoder_type, context_type=context_type,
                corr_reg_type=corr_reg_type,
                corr_reg_args=dict(corr_reg_args), share_rnn=share_rnn,
                upsample_hidden=upsample_hidden,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "iterations": _DEFAULT_ITERATIONS[self.levels],
            "upnet": True,
            "corr_flow": False,
            "corr_grad_stop": False,
        }
        return {
            "type": self.type,
            "parameters": {
                "dropout": self.dropout,
                "corr-radius": self.corr_radius,
                "corr-channels": self.corr_channels,
                "context-channels": self.context_channels,
                "recurrent-channels": self.recurrent_channels,
                "encoder-norm": self.encoder_norm,
                "context-norm": self.context_norm,
                "encoder-type": self.encoder_type,
                "context-type": self.context_type,
                "share-rnn": self.share_rnn,
                "corr-reg-type": self.corr_reg_type,
                "corr-reg-args": self.corr_reg_args,
                "upsample-hidden": self.upsample_hidden,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": True} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return MultiLevelSequenceAdapter(self)


@register_model
class RaftSlCtfL2(_SlCtfModel):
    """``raft/sl-ctf-l2`` (reference raft_sl_ctf_l2.py)."""

    type = "raft/sl-ctf-l2"
    levels = 2


@register_model
class RaftSlCtfL3(_SlCtfModel):
    """``raft/sl-ctf-l3`` (reference raft_sl_ctf_l3.py:11-210)."""

    type = "raft/sl-ctf-l3"
    levels = 3


@register_model
class RaftSlCtfL4(_SlCtfModel):
    """``raft/sl-ctf-l4`` (reference raft_sl_ctf_l4.py)."""

    type = "raft/sl-ctf-l4"
    levels = 4
