"""DICL baseline: displacement-invariant cost learning, coarse-to-fine.

TPU-native (Flax, NHWC) implementation of the capabilities of reference
src/models/impls/dicl.py ("Displacement-Invariant Matching Cost Learning
for Accurate Optical Flow Estimation", Wang et al.; upstream
jytime/DICL-Flow):

- the full displacement-shifted matching volume is built from *static*
  integer shifts — a pad + (2r+1)² slice stack XLA folds into cheap copies
  (the reference fills a zero tensor per displacement in a python loop,
  dicl.py:212-241),
- cost volumes are (B, H, W, du, dv) channels-last, so the DAP is one MXU
  1x1 conv and soft-argmin/entropy are trailing-axis reductions,
- the coarse-to-fine ladder (levels 6..2, GA-Net p26 features) warps the
  second frame's features by the upsampled coarse flow and refines with
  dilated context networks exactly like the reference (dicl.py:150-297).
"""

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ...ops.upsample import interpolate_bilinear, upsample_flow_2x
from ..common import warp
from ..common.blocks.dicl import (
    ConvBlock,
    DisplacementAwareProjection,
    MatchingNet,
)
from ..common.encoders import dicl as dicl_encoders
from ..config import register_loss, register_model
from ..model import Loss, Model, ModelAdapter, Result

_DEFAULT_CONTEXT_SCALE = {
    "level-6": 1.0,
    "level-5": 1.0,
    "level-4": 1.0,
    "level-3": 1.0,
    "level-2": 1.0,
}


def flow_entropy(cost, eps=1e-9):
    """Normalized entropy of the displacement distribution
    (reference FlowEntropy, dicl.py:31-50). cost: (B, H, W, du, dv) →
    (B, H, W, 1)."""
    b, h, w, du, dv = cost.shape

    p = nn.softmax(cost.reshape(b, h, w, du * dv), axis=-1)
    plogp = -p * jnp.log(jnp.clip(p, eps, 1.0 - eps))
    entropy = plogp.sum(axis=-1) / np.log(du * dv)
    return entropy[..., None]


def soft_argmin_flow(cost):
    """Soft-argmin flow regression (reference FlowRegression, dicl.py:53-85).

    cost: (B, H, W, du, dv) — du indexes x-displacement, dv indexes y.
    Returns (B, H, W, 2) flow (u, v).
    """
    b, h, w, du, dv = cost.shape
    ru, rv = (du - 1) // 2, (dv - 1) // 2

    prob = nn.softmax(cost.reshape(b, h, w, du * dv), axis=-1)
    prob = prob.reshape(b, h, w, du, dv)

    disp_u = jnp.arange(-ru, ru + 1, dtype=cost.dtype)
    disp_v = jnp.arange(-rv, rv + 1, dtype=cost.dtype)

    u = jnp.einsum("bhwuv,u->bhw", prob, disp_u)
    v = jnp.einsum("bhwuv,v->bhw", prob, disp_v)
    return jnp.stack((u, v), axis=-1)


def displaced_pair_volume(feat1, feat2, disp_range):
    """Stack feature pairs for every integer displacement in the range.

    Returns (B, du, dv, H, W, 2C): at displacement d, the second half of
    the channels holds ``feat2[p + d]`` (zeros outside), and hypotheses
    whose displaced features are all-zero (out of bounds / holes) are
    zeroed entirely — reference compute_cost semantics (dicl.py:212-241),
    realized as static pad + slice instead of per-displacement copies.
    """
    b, h, w, c = feat1.shape
    ru, rv = disp_range
    du, dv = 2 * ru + 1, 2 * rv + 1

    f2p = jnp.pad(feat2, ((0, 0), (rv, rv), (ru, ru), (0, 0)))

    rows = []
    for i in range(du):  # x-displacement di = i - ru
        cols = []
        for j in range(dv):  # y-displacement dj = j - rv
            cols.append(f2p[:, j : j + h, i : i + w, :])
        rows.append(jnp.stack(cols, axis=1))
    shifted = jnp.stack(rows, axis=1)  # (B, du, dv, H, W, C)

    # zero out occluded / out-of-bounds hypotheses
    valid = jax.lax.stop_gradient(shifted).sum(axis=-1, keepdims=True) != 0

    f1 = jnp.broadcast_to(feat1[:, None, None], shifted.shape)
    return jnp.concatenate((f1 * valid, shifted * valid), axis=-1)


class CtfContextNet(nn.Module):
    """Dilated context network; level 2/3 depth by default, levels 4/5/6
    use progressively fewer layers (reference dicl.py:88-147)."""

    level: int = 3

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        plans = {
            # (channels, dilation) per layer; final 3x3 conv to 2 channels
            3: ((64, 1), (128, 2), (128, 4), (96, 8), (64, 16), (32, 1)),
            4: ((64, 1), (128, 2), (128, 4), (64, 8), (32, 1)),
            5: ((64, 1), (128, 2), (64, 4), (32, 1)),
            6: ((64, 1), (64, 2), (32, 1)),
        }
        plan = plans[min(max(self.level, 3), 6)]

        for ch, dil in plan:
            x = ConvBlock(ch, dilation=dil)(x, train, frozen_bn)
        return nn.Conv(2, (3, 3))(x)  # with bias, like the reference


class FlowLevel(nn.Module):
    """One coarse-to-fine level: cost volume → DAP → soft-argmin (+ coarse
    flow) → context refinement (reference FlowLevel, dicl.py:150-241)."""

    feature_channels: int
    level: int
    maxdisp: tuple
    dap_init: str = "identity"

    @nn.compact
    def __call__(self, img1, feat1, feat2, flow_coarse, raw=False, dap=True,
                 ctx=True, scale=1.0, train=False, frozen_bn=False):
        b, h, w, _ = feat1.shape

        flow_up = None
        if flow_coarse is not None:
            flow_up = jax.lax.stop_gradient(upsample_flow_2x(flow_coarse))
            feat2, _mask = warp.warp_backwards(feat2, flow_up)

        # matching cost
        mvol = displaced_pair_volume(feat1, feat2, self.maxdisp)
        cost = MatchingNet()(mvol, train, frozen_bn)  # (B, H, W, du, dv)
        if dap:
            cost = DisplacementAwareProjection(self.maxdisp, init=self.dap_init)(cost)

        # raw flow via soft-argmin, plus the coarse estimate
        flow = soft_argmin_flow(cost)
        flow = flow + flow_up if flow_up is not None else flow
        flow_raw = flow if raw else None

        if ctx:
            img1 = interpolate_bilinear(img1, (h, w))
            entr = jax.lax.stop_gradient(flow_entropy(cost))
            ctxf = jnp.concatenate(
                (jax.lax.stop_gradient(flow), entr, feat1, img1), axis=-1
            )
            flow = flow + CtfContextNet(self.level)(ctxf, train, frozen_bn) * scale

        return flow, flow_raw


class DiclModule(nn.Module):
    """Coarse-to-fine DICL stack over GA-Net features.

    ``levels`` picks the refinement ladder: (6..2) with p26 features is the
    baseline (reference DiclModule, dicl.py:244-297), (6..3) with a
    p36-shaped encoder is the 64to8 variant (reference dicl_64to8.py:102-151
    — its hand-written FeatureNet is the same hourglass minus the final
    1/4-level head).
    """

    disp_ranges: Dict[str, Any]
    dap_init: str = "identity"
    feature_channels: int = 32
    levels: tuple = (6, 5, 4, 3, 2)

    @nn.compact
    def __call__(self, img1, img2, train=False, frozen_bn=False, raw=False,
                 dap=True, ctx=True, context_scale=None):
        context_scale = context_scale or {
            f"level-{lvl}": 1.0 for lvl in self.levels
        }
        finest = min(self.levels)

        # encoder heads at exactly the levels the ladder consumes
        # (encoder level i is H/2^(i+1): flow level L sits at encoder level L-1)
        feature = dicl_encoders.FeatureEncoderGa(
            output_dim=self.feature_channels, depth=6,
            out_levels=tuple(lvl - 1 for lvl in sorted(self.levels)),
        )
        f1, f2 = feature((img1, img2), train, frozen_bn)  # finest-first

        flow = None
        out = []
        for lvl in sorted(self.levels, reverse=True):
            level = FlowLevel(
                self.feature_channels, lvl,
                tuple(self.disp_ranges[f"level-{lvl}"]), self.dap_init,
            )
            flow, flow_raw = level(
                img1, f1[lvl - finest], f2[lvl - finest], flow, raw=raw,
                dap=dap, ctx=ctx, scale=context_scale[f"level-{lvl}"],
                train=train, frozen_bn=frozen_bn,
            )
            out = [flow, flow_raw] + out

        # finest first: [flow_f, flow_f_raw, ..., flow6, flow6_raw]
        return [f for f in out if f is not None]


@register_model
class Dicl(Model):
    """``dicl/baseline`` (reference dicl.py:300-375)."""

    type = "dicl/baseline"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        param_cfg = cfg["parameters"]
        return cls(
            disp_ranges=param_cfg["displacement-range"],
            dap_init=param_cfg.get("dap-init", "identity"),
            feature_channels=param_cfg.get("feature-channels", 32),
            arguments=cfg.get("arguments", {}),
            on_epoch_args=cfg.get("on-epoch", {}),
            on_stage_args=cfg.get("on-stage", {"freeze_batchnorm": False}),
        )

    def __init__(self, disp_ranges, dap_init="identity", feature_channels=32,
                 arguments={}, on_epoch_args={},
                 on_stage_args={"freeze_batchnorm": False}):
        self.disp_ranges = dict(disp_ranges)
        self.dap_init = dap_init
        self.feature_channels = feature_channels

        super().__init__(
            DiclModule(
                disp_ranges=dict(disp_ranges), dap_init=dap_init,
                feature_channels=feature_channels,
            ),
            arguments=arguments,
            on_epoch_arguments=on_epoch_args,
            on_stage_arguments=on_stage_args,
        )

    def get_config(self):
        default_args = {
            "raw": False,
            "dap": True,
            "context_scale": _DEFAULT_CONTEXT_SCALE,
        }
        return {
            "type": self.type,
            "parameters": {
                "feature-channels": self.feature_channels,
                "displacement-range": self.disp_ranges,
                "dap-init": self.dap_init,
            },
            "arguments": default_args | self.arguments,
            "on-stage": {"freeze_batchnorm": False} | self.on_stage_arguments,
            "on-epoch": dict(self.on_epoch_arguments),
        }

    def get_adapter(self) -> ModelAdapter:
        return DiclAdapter(self)


@register_model
class Dicl64to8(Model):
    """``dicl/64to8``: the DICL ladder stopped at 1/8 resolution, levels
    6..3 (reference dicl_64to8.py:154-202)."""

    type = "dicl/64to8"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        param_cfg = cfg["parameters"]
        return cls(
            disp_ranges=param_cfg["displacement-range"],
            dap_init=param_cfg.get("dap-init", "identity"),
            feature_channels=param_cfg.get("feature-channels", 32),
            arguments=cfg.get("arguments", {}),
        )

    def __init__(self, disp_ranges, dap_init="identity", feature_channels=32,
                 arguments={}):
        self.disp_ranges = dict(disp_ranges)
        self.dap_init = dap_init
        self.feature_channels = feature_channels

        super().__init__(
            DiclModule(
                disp_ranges=dict(disp_ranges), dap_init=dap_init,
                feature_channels=feature_channels, levels=(6, 5, 4, 3),
            ),
            arguments=arguments,
        )

    def get_config(self):
        default_args = {
            "raw": False,
            "dap": True,
            "context_scale": {f"level-{lvl}": 1.0 for lvl in (6, 5, 4, 3)},
        }
        return {
            "type": self.type,
            "parameters": {
                "feature-channels": self.feature_channels,
                "displacement-range": self.disp_ranges,
                "dap-init": self.dap_init,
            },
            "arguments": default_args | self.arguments,
        }

    def get_adapter(self) -> ModelAdapter:
        return DiclAdapter(self)


class DiclAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return DiclResult(result, original_shape)


class DiclResult(Result):
    """List of per-level flows, finest (1/4 resolution) first
    (reference dicl.py:386-413)."""

    def __init__(self, output, target_shape):
        super().__init__()
        self.result = output
        self.shape = target_shape  # (H, W) of the input images

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result
        return [x[batch_index : batch_index + 1] for x in self.result]

    def final(self):
        flow = jax.lax.stop_gradient(self.result[0])

        _, fh, fw, _ = flow.shape
        th, tw = self.shape

        flow = interpolate_bilinear(flow, (th, tw))
        return flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)

    def intermediate_flow(self):
        return self.result


@register_loss
class MultiscaleLoss(Loss):
    """``dicl/multiscale``: weighted per-level distances on upsampled flow
    (reference dicl.py:416-472)."""

    type = "dicl/multiscale"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {"ord": 2, "mode": "bilinear"}
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, weights, ord=2,
                mode="bilinear", valid_range=None):
        if mode != "bilinear":
            raise ValueError(f"unsupported upsampling mode '{mode}'")

        th, tw = target.shape[1:3]
        valid_f = valid.astype(jnp.float32)

        loss = 0.0
        for i, flow in enumerate(result):
            _, fh, fw, _ = flow.shape
            flow = interpolate_bilinear(flow, (th, tw))
            flow = flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)

            mask = valid_f
            if valid_range is not None:
                mask = mask * (jnp.abs(target[..., 0]) < valid_range[i][0])
                mask = mask * (jnp.abs(target[..., 1]) < valid_range[i][1])

            if ord == "robust":
                # robust norm of the original DICL implementation
                dist = (jnp.abs(flow - target).sum(axis=-1) + 1e-8) ** 0.4
            else:
                dist = jnp.linalg.norm(flow - target, ord=float(ord), axis=-1)

            mean = jnp.sum(dist * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            loss = loss + weights[i] * mean

        return loss / len(result)
