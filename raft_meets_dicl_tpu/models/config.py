"""Model spec loading: the string-typed model/loss registries.

Mirrors the reference registry surface (src/models/config.py:9-94): a model
config file carries name/id plus typed ``model``, ``loss``, and ``input``
sections. Model and loss implementations self-register via
``register_model``/``register_loss`` when their module is imported, so the
registry grows with the zoo without a central edit point.
"""

from .. import utils
from . import input as input_mod
from . import model as model_mod

_MODELS = {}
_LOSSES = {}


def register_model(cls):
    """Class decorator: add a Model subclass to the type registry."""
    if cls.type is None:
        raise ValueError(f"model class {cls.__name__} has no type id")
    _MODELS[cls.type] = cls
    return cls


def register_loss(cls):
    """Class decorator: add a Loss subclass to the type registry."""
    if cls.type is None:
        raise ValueError(f"loss class {cls.__name__} has no type id")
    _LOSSES[cls.type] = cls
    return cls


def model_types():
    from . import impls  # noqa: F401 — triggers registration

    return sorted(_MODELS)


def loss_types():
    from . import impls  # noqa: F401 — triggers registration

    return sorted(_LOSSES)


class ModelSpec:
    """name/id + model + loss + input — one loadable model definition."""

    @classmethod
    def from_config(cls, cfg):
        return cls(
            cfg["name"],
            cfg["id"],
            load_model(cfg["model"]),
            load_loss(cfg["loss"]),
            load_input(cfg.get("input")),
        )

    def __init__(self, name, id, model, loss, input):
        self.name = name
        self.id = id
        self.model = model
        self.loss = loss
        self.input = input

    def get_config(self):
        return {
            "name": self.name,
            "id": self.id,
            "model": self.model.get_config(),
            "loss": self.loss.get_config(),
            "input": self.input.get_config(),
        }


def load_input(cfg) -> input_mod.InputSpec:
    return input_mod.InputSpec.from_config(cfg)


def load_loss(cfg) -> model_mod.Loss:
    from . import impls  # noqa: F401 — triggers registration

    ty = cfg["type"]
    if ty not in _LOSSES:
        raise ValueError(f"unknown loss type '{ty}'")
    return _LOSSES[ty].from_config(cfg)


def load_model(cfg) -> model_mod.Model:
    from . import impls  # noqa: F401 — triggers registration

    ty = cfg["type"]
    if ty not in _MODELS:
        raise ValueError(f"unknown model type '{ty}'")
    return _MODELS[ty].from_config(cfg)


def load(cfg) -> ModelSpec:
    if not isinstance(cfg, dict):
        cfg = utils.config.load(cfg)

    return ModelSpec.from_config(cfg)
