"""Multiprocess sample decode with shared-memory array transport.

The thread-pooled loader overlaps I/O and the GIL-releasing parts of
cv2/numpy, but the pure-Python decode path (dataset indexing, augmentation
glue, per-sample validation) stays single-core. This pool forks worker
processes that run ``source[index]`` and hand the resulting arrays back
through POSIX shared memory — one segment per sample, written once by the
worker, read zero-copy by the consumer (``collate`` is the single copy),
then unlinked. Only the metadata list travels through the result queue's
pickle channel.

Fork start method by default (the source pipeline is inherited, nothing
is pickled); override with ``RMD_LOADER_MP=spawn`` for sources that hold
fork-unsafe state. Workers never touch jax.

Self-healing: ``result()`` polls the queue with a timeout instead of
blocking forever, so a worker that died (OOM-killed, segfaulted in a
native decode, fault-injected) is detected, respawned with backoff, and
its lost in-flight work resubmitted — bounded by ``RMD_LOADER_RESPAWNS``
(then the pool gives up loudly). ``RMD_LOADER_TIMEOUT`` bounds the total
wait per sample so a wedged-but-alive worker can't hang the run.
"""

import multiprocessing as mp
import os
import pickle
import queue as _queue
import time
from multiprocessing import shared_memory

import numpy as np

from ..testing import faults
from ..utils import env


class PoolBroken(RuntimeError):
    """The decode pool itself is unusable (respawn budget exhausted) —
    not a per-sample failure, so the loader's retry path must not
    swallow it."""


def _unregister_tracker(name):
    """Detach a segment from the creating process's resource tracker.

    SharedMemory(create=True) registers with the *worker's* tracker; the
    consumer unlinks explicitly, so tracker cleanup at worker exit would
    only race it and log spurious leak warnings.
    """
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name.lstrip("/"), "shared_memory")
    except Exception:  # noqa: BLE001 - tracker APIs are version-dependent
        pass


def encode_sample(sample):
    """Sample → (shm_name, array descriptors, meta); arrays in one segment."""
    img1, img2, flow, valid, meta = sample
    arrays = [img1, img2, flow, valid]
    total = sum(a.nbytes for a in arrays if a is not None)
    shm = shared_memory.SharedMemory(create=True, size=max(1, total))
    _unregister_tracker(shm.name)

    descr = []
    offset = 0
    for a in arrays:
        if a is None:
            descr.append(None)
            continue
        a = np.ascontiguousarray(a)
        dst = np.ndarray(a.shape, a.dtype, buffer=shm.buf, offset=offset)
        dst[...] = a
        descr.append((offset, a.shape, a.dtype))
        offset += a.nbytes

    name = shm.name
    shm.close()
    return name, descr, meta


def decode_sample(payload):
    """Payload → ((img1, img2, flow, valid, meta), shm handle).

    The arrays are views into the segment: the caller must keep ``shm``
    open until it has copied them out (collate does), then
    ``shm.close(); shm.unlink()``.
    """
    name, descr, meta = payload
    shm = shared_memory.SharedMemory(name=name)
    arrays = []
    for d in descr:
        if d is None:
            arrays.append(None)
            continue
        offset, shape, dtype = d
        arrays.append(np.ndarray(shape, dtype, buffer=shm.buf, offset=offset))
    img1, img2, flow, valid = arrays
    return (img1, img2, flow, valid, meta), shm


def _discard_payload(payload):
    """Unlink a result segment the consumer will never read."""
    if payload is None:
        return
    try:
        shm = shared_memory.SharedMemory(name=payload[0])
        shm.close()
        shm.unlink()
    except Exception:  # noqa: BLE001 - best-effort cleanup
        pass


def _worker(source, tasks, results):
    while True:
        task = tasks.get()
        if task is None:
            return
        seq, index = task
        try:
            if faults.fire("kill_worker", index=index) is not None:
                os._exit(17)  # injected hard death: no result, no cleanup
            results.put((seq, encode_sample(source[index]), None))
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            try:
                pickle.dumps(e)
            except Exception:  # noqa: BLE001
                e = RuntimeError(f"{type(e).__name__}: {e}")
            results.put((seq, None, e))


class DecodePool:
    """Fixed pool of decode processes with in-order result retrieval.

    Dead workers are respawned (with backoff) and their lost in-flight
    tasks resubmitted; duplicate results from a resubmission race are
    detected by sequence number and their segments discarded.
    """

    def __init__(self, source, procs, start_method=None,
                 timeout=None, poll=None, max_respawns=None):
        method = start_method or env.get_str("RMD_LOADER_MP")
        self._ctx = mp.get_context(method)
        self._source = source
        self._tasks = self._ctx.Queue()
        self._results = self._ctx.Queue()
        self._received = {}
        self._inflight = {}   # seq -> index, until the result is received
        self._done = set()    # delivered seqs (duplicate-result guard)
        self._seq = 0
        self._respawns = 0
        self._backoff = 0.0

        # total wait per sample before the pool declares the pipeline
        # wedged; poll interval bounds dead-worker detection latency
        self._timeout = (timeout if timeout is not None
                         else env.get_float("RMD_LOADER_TIMEOUT"))
        self._poll = (poll if poll is not None
                      else env.get_float("RMD_LOADER_POLL"))
        self._max_respawns = int(max_respawns if max_respawns is not None
                                 else env.get_int("RMD_LOADER_RESPAWNS"))

        self._workers = [self._spawn() for _ in range(max(1, int(procs)))]

    def _spawn(self):
        w = self._ctx.Process(
            target=_worker, args=(self._source, self._tasks, self._results),
            daemon=True)
        w.start()
        return w

    def submit(self, index):
        """Queue one sample decode; returns its sequence token."""
        seq = self._seq
        self._seq += 1
        self._inflight[seq] = int(index)
        self._tasks.put((seq, int(index)))
        return seq

    def _heal(self):
        """Respawn dead workers and resubmit their lost in-flight tasks.

        A worker that died mid-decode took its task with it; since the
        queue doesn't say which, every unreceived in-flight task is
        resubmitted — tasks that were merely queued get decoded twice,
        and the duplicate result is dropped by sequence number.
        """
        from .. import telemetry, utils

        dead = [(i, w) for i, w in enumerate(self._workers)
                if not w.is_alive()]
        if not dead:
            return

        log = utils.logging.Logger("data:mpdecode")
        for i, w in dead:
            self._respawns += 1
            if self._respawns > self._max_respawns:
                raise PoolBroken(
                    f"decode worker died (exit code {w.exitcode}) and the "
                    f"respawn budget ({self._max_respawns}) is exhausted — "
                    "the input pipeline is persistently failing")
            log.warn(
                f"decode worker {i} died (exit code {w.exitcode}): "
                f"respawning ({self._respawns}/{self._max_respawns})")
            telemetry.get().emit(
                "respawn", worker=i, exitcode=w.exitcode,
                respawns=self._respawns)
            if self._backoff:
                time.sleep(self._backoff)
            self._backoff = min(max(0.1, self._backoff * 2), 10.0)
            self._workers[i] = self._spawn()

        for seq, index in list(self._inflight.items()):
            if seq not in self._received:
                self._tasks.put((seq, index))

    def result(self, seq):
        """Block until sample ``seq`` is decoded; returns (sample, shm)."""
        deadline = time.monotonic() + self._timeout
        while seq not in self._received:
            try:
                s, payload, err = self._results.get(
                    timeout=max(0.01, self._poll))
            except _queue.Empty:
                self._heal()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"decode pool produced no result for "
                        f"{self._timeout:.0f}s (sample seq {seq}) — input "
                        "pipeline wedged") from None
                continue
            if s in self._done or s in self._received:
                # duplicate from a resubmission race: keep the first
                _discard_payload(payload)
                continue
            self._received[s] = (payload, err)
            self._inflight.pop(s, None)
        payload, err = self._received.pop(seq)
        self._done.add(seq)
        if err is not None:
            raise err
        return decode_sample(payload)

    def shutdown(self):
        for _ in self._workers:
            self._tasks.put(None)
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        # drop any undelivered segments (consumer bailed mid-epoch)
        for payload, err in self._received.values():
            _discard_payload(payload)
        self._received.clear()
        self._inflight.clear()
        while True:
            try:
                s, payload, err = self._results.get_nowait()
            except Exception:  # noqa: BLE001 - queue empty
                break
            _discard_payload(payload)
