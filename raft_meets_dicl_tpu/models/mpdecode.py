"""Multiprocess sample decode with shared-memory array transport.

The thread-pooled loader overlaps I/O and the GIL-releasing parts of
cv2/numpy, but the pure-Python decode path (dataset indexing, augmentation
glue, per-sample validation) stays single-core. This pool forks worker
processes that run ``source[index]`` and hand the resulting arrays back
through POSIX shared memory — one segment per sample, written once by the
worker, read zero-copy by the consumer (``collate`` is the single copy),
then unlinked. Only the metadata list travels through the result queue's
pickle channel.

Fork start method by default (the source pipeline is inherited, nothing
is pickled); override with ``RMD_LOADER_MP=spawn`` for sources that hold
fork-unsafe state. Workers never touch jax.
"""

import multiprocessing as mp
import os
import pickle
from multiprocessing import shared_memory

import numpy as np


def _unregister_tracker(name):
    """Detach a segment from the creating process's resource tracker.

    SharedMemory(create=True) registers with the *worker's* tracker; the
    consumer unlinks explicitly, so tracker cleanup at worker exit would
    only race it and log spurious leak warnings.
    """
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name.lstrip("/"), "shared_memory")
    except Exception:  # noqa: BLE001 - tracker APIs are version-dependent
        pass


def encode_sample(sample):
    """Sample → (shm_name, array descriptors, meta); arrays in one segment."""
    img1, img2, flow, valid, meta = sample
    arrays = [img1, img2, flow, valid]
    total = sum(a.nbytes for a in arrays if a is not None)
    shm = shared_memory.SharedMemory(create=True, size=max(1, total))
    _unregister_tracker(shm.name)

    descr = []
    offset = 0
    for a in arrays:
        if a is None:
            descr.append(None)
            continue
        a = np.ascontiguousarray(a)
        dst = np.ndarray(a.shape, a.dtype, buffer=shm.buf, offset=offset)
        dst[...] = a
        descr.append((offset, a.shape, a.dtype))
        offset += a.nbytes

    name = shm.name
    shm.close()
    return name, descr, meta


def decode_sample(payload):
    """Payload → ((img1, img2, flow, valid, meta), shm handle).

    The arrays are views into the segment: the caller must keep ``shm``
    open until it has copied them out (collate does), then
    ``shm.close(); shm.unlink()``.
    """
    name, descr, meta = payload
    shm = shared_memory.SharedMemory(name=name)
    arrays = []
    for d in descr:
        if d is None:
            arrays.append(None)
            continue
        offset, shape, dtype = d
        arrays.append(np.ndarray(shape, dtype, buffer=shm.buf, offset=offset))
    img1, img2, flow, valid = arrays
    return (img1, img2, flow, valid, meta), shm


def _worker(source, tasks, results):
    while True:
        task = tasks.get()
        if task is None:
            return
        seq, index = task
        try:
            results.put((seq, encode_sample(source[index]), None))
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            try:
                pickle.dumps(e)
            except Exception:  # noqa: BLE001
                e = RuntimeError(f"{type(e).__name__}: {e}")
            results.put((seq, None, e))


class DecodePool:
    """Fixed pool of decode processes with in-order result retrieval."""

    def __init__(self, source, procs, start_method=None):
        method = start_method or os.environ.get("RMD_LOADER_MP", "fork")
        ctx = mp.get_context(method)
        self._tasks = ctx.Queue()
        self._results = ctx.Queue()
        self._received = {}
        self._seq = 0
        self._workers = [
            ctx.Process(target=_worker, args=(source, self._tasks, self._results),
                        daemon=True)
            for _ in range(max(1, int(procs)))
        ]
        for w in self._workers:
            w.start()

    def submit(self, index):
        """Queue one sample decode; returns its sequence token."""
        seq = self._seq
        self._seq += 1
        self._tasks.put((seq, int(index)))
        return seq

    def result(self, seq):
        """Block until sample ``seq`` is decoded; returns (sample, shm)."""
        while seq not in self._received:
            s, payload, err = self._results.get()
            self._received[s] = (payload, err)
        payload, err = self._received.pop(seq)
        if err is not None:
            raise err
        return decode_sample(payload)

    def shutdown(self):
        for _ in self._workers:
            self._tasks.put(None)
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        # drop any undelivered segments (consumer bailed mid-epoch)
        for payload, err in self._received.values():
            if payload is None:
                continue
            try:
                shm = shared_memory.SharedMemory(name=payload[0])
                shm.close()
                shm.unlink()
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
        self._received.clear()
        while True:
            try:
                s, payload, err = self._results.get_nowait()
            except Exception:  # noqa: BLE001 - queue empty
                break
            if payload is not None:
                try:
                    shm = shared_memory.SharedMemory(name=payload[0])
                    shm.close()
                    shm.unlink()
                except Exception:  # noqa: BLE001
                    pass
