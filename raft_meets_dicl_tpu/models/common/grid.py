"""Coordinate grids, NHWC layout.

Reference returns (B, 2, H, W) (src/models/common/grid.py:4-12); here grids
are (B, H, W, 2) with channel 0 = x, 1 = y — the TPU-native
channels-last convention used across this framework.
"""

import jax.numpy as jnp


def coordinate_grid(batch, h, w, dtype=jnp.float32):
    """(B, H, W, 2) pixel-position grid; [..., 0] = x, [..., 1] = y."""
    ys, xs = jnp.meshgrid(
        jnp.arange(h, dtype=dtype), jnp.arange(w, dtype=dtype), indexing="ij"
    )
    grid = jnp.stack((xs, ys), axis=-1)
    return jnp.broadcast_to(grid, (batch, h, w, 2))
