"""Small shared helpers for the NN layer."""

import flax.linen as nn
import jax.numpy as jnp


def unfold3x3(x):
    """(B, H, W, C) → (B, H, W, 9, C) zero-padded 3x3 neighborhoods,
    window ordered row-major (dy, dx) like torch ``F.unfold``."""
    b, h, w, c = x.shape
    x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    patches = [x[:, dy : dy + h, dx : dx + w] for dy in range(3) for dx in range(3)]
    return jnp.stack(patches, axis=3)


def identity_1x1_init(key, shape, dtype=jnp.float32):
    """(1, 1, C, C) identity kernel — identity-initialized 1x1 convs."""
    return jnp.eye(shape[-1], dtype=dtype).reshape(shape)


class ConvParams(nn.Module):
    """Holds an ``nn.Conv``-compatible kernel (+ optional bias) without
    applying them: parameter names, shapes, and initializers match what
    ``nn.Conv`` would create, so checkpoint trees stay identical when
    sibling convolutions are merged into one call or one conv is applied
    as split partial convolutions (linearity)."""

    features: int
    kernel_size: tuple
    use_bias: bool = True

    @nn.compact
    def __call__(self, in_features):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (*self.kernel_size, in_features, self.features))
        if not self.use_bias:
            return kernel
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        return kernel, bias
