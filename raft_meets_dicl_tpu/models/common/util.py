"""Small shared helpers for the NN layer."""

import jax.numpy as jnp


def unfold3x3(x):
    """(B, H, W, C) → (B, H, W, 9, C) zero-padded 3x3 neighborhoods,
    window ordered row-major (dy, dx) like torch ``F.unfold``."""
    b, h, w, c = x.shape
    x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    patches = [x[:, dy : dy + h, dx : dx + w] for dy in range(3) for dx in range(3)]
    return jnp.stack(patches, axis=3)


def identity_1x1_init(key, shape, dtype=jnp.float32):
    """(1, 1, C, C) identity kernel — identity-initialized 1x1 convs."""
    return jnp.eye(shape[-1], dtype=dtype).reshape(shape)
