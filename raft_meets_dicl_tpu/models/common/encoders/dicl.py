"""GA-Net feature encoders for the DICL family (Flax, NHWC).

The reference ships five hand-unrolled variants of the same hourglass
(src/models/common/encoders/dicl/{s3,p26,p34,p35,p36}.py — "Guided
Aggregation Net for End-to-end Stereo Matching"): a strided conv ladder
down to depth D, a transposed-conv ladder back up, a second strided ladder
(each rung fused with the previous ladder's same-resolution features), and
a final up-ladder emitting output heads at the requested levels. Here that
is ONE parametric module; the variants are (depth, out_levels) instances.

Level numbering: level 0 is H/2 (the stem output), level i is H/2^(i+1) —
so the reference's s3 output (H/8) is level 2, p26's outputs (H/4..H/64)
are levels 1..5.
"""

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..blocks.dicl import ConvBlock, GaConv2xBlock, GaConv2xBlockTransposed

# channels per level: stem = 32 (H/2), then one stage per downsample
_CHANNELS = (32, 48, 64, 96, 128, 160, 192)


class FeatureEncoderGa(nn.Module):
    """Parametric GA-Net hourglass: down D, up, down, up-with-heads.

    Returns a tuple of features finest-first at ``out_levels`` (or a single
    array when only one level is requested). Accepts an ``(img1, img2)``
    tuple for the shared-batch pair trick like the RAFT encoders.
    """

    output_dim: int = 32
    depth: int = 3
    out_levels: Tuple[int, ...] = (2,)
    norm_type: str = "batch"
    heads: bool = True  # False: raw ladder features (varying channels)

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        depth = self.depth
        out_levels = sorted(self.out_levels)
        assert 1 <= min(out_levels) and max(out_levels) < depth

        paired = isinstance(x, (tuple, list))
        if paired:
            n = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        nt = self.norm_type
        # paired inputs fold (img1, img2) into one 2N batch for conv
        # efficiency, but the REFERENCE runs the two images through
        # separate encoder calls (src/models/impls/dicl.py:277-278) —
        # live batch-norm statistics must therefore be per-image
        sp = 2 if paired else 1

        # stem: three 3x3 convs, middle one strided (→ level 0, H/2)
        x = ConvBlock(_CHANNELS[0], norm_type=nt, bn_splits=sp)(x, train, frozen_bn)
        x = ConvBlock(_CHANNELS[0], stride=2, norm_type=nt, bn_splits=sp)(x, train, frozen_bn)
        x = ConvBlock(_CHANNELS[0], norm_type=nt, bn_splits=sp)(x, train, frozen_bn)

        res = {0: x}

        # first down-ladder
        for i in range(1, depth + 1):
            x = ConvBlock(_CHANNELS[i], stride=2, norm_type=nt, bn_splits=sp)(x, train, frozen_bn)
            res[i] = x

        # up-ladder, refreshing the skip features
        for i in range(depth, 0, -1):
            x = GaConv2xBlockTransposed(_CHANNELS[i - 1], norm_type=nt, bn_splits=sp)(
                x, res[i - 1], train, frozen_bn
            )
            res[i - 1] = x

        # second down-ladder, fusing the refreshed skips
        for i in range(1, depth + 1):
            x = GaConv2xBlock(_CHANNELS[i], norm_type=nt, bn_splits=sp)(x, res[i], train, frozen_bn)
            res[i] = x

        # final up-ladder with output heads at the requested levels
        outputs = {}
        for i in range(depth, min(out_levels), -1):
            x = GaConv2xBlockTransposed(_CHANNELS[i - 1], norm_type=nt, bn_splits=sp)(
                x, res[i - 1], train, frozen_bn
            )
            if i - 1 in out_levels:
                if self.heads:
                    outputs[i - 1] = ConvBlock(self.output_dim, norm_type=nt, bn_splits=sp)(
                        x, train, frozen_bn
                    )
                else:
                    outputs[i - 1] = x

        outs = tuple(outputs[lvl] for lvl in out_levels)  # finest first

        if paired:
            if len(outs) == 1:
                return outs[0][:n], outs[0][n:]
            return tuple(o[:n] for o in outs), tuple(o[n:] for o in outs)
        return outs[0] if len(outs) == 1 else outs


def s3(output_dim, norm_type="batch", **kwargs):
    """Single-scale 1/8 (reference dicl/s3.py)."""
    return FeatureEncoderGa(output_dim=output_dim, depth=3, out_levels=(2,),
                            norm_type=norm_type, **kwargs)


def p26(output_dim, norm_type="batch", **kwargs):
    """1/4 .. 1/64 pyramid for the DICL baseline (reference dicl/p26.py)."""
    return FeatureEncoderGa(output_dim=output_dim, depth=6,
                            out_levels=(1, 2, 3, 4, 5), norm_type=norm_type,
                            **kwargs)


def pyramid(levels, output_dim, norm_type="batch", **kwargs):
    """1/8 .. 1/(8·2^(levels-1)) pyramids: levels 2/3/4 ≈ p34/p35/p36."""
    out_levels = tuple(range(2, 2 + levels))
    return FeatureEncoderGa(output_dim=output_dim, depth=max(out_levels) + 1,
                            out_levels=out_levels, norm_type=norm_type,
                            **kwargs)
