"""RFPM encoders: residual feature pyramid modules (Flax, NHWC).

Behavioral equivalent of reference src/models/common/encoders/rfpm/* —
"Detail Preserving Residual Feature Pyramid Modules for Optical Flow"
(Long & Lang 2021, arXiv:2107.10990) on the RAFT encoder base: three
parallel pyramids (left: plain residual stages; center: residual-feature
downsampling with max-pool shortcuts; right: plain residual), repair-mask
corrections chaining left→center→right at every stage, and per-level
output nets over the three concatenated pyramids. The reference's four
hand-written variants (s3, p34, p35, p36) are instances of one parametric
module.
"""

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from ....ops.pool import max_pool2d
from ..blocks.raft import ResidualBlock, kaiming_normal
from ..norm import Norm2d

_STAGE_CHANNELS = (64, 96, 128, 160, 192, 224, 256)


class RfpmRfdBlock(nn.Module):
    """Residual feature downsampling with a max-pool shortcut
    (reference rfpm/common.py:10-45)."""

    c_out: int
    norm_type: str = "group"
    stride: int = 2

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        groups = max(self.c_out // 8, 1)

        # explicit padding: flax 'SAME' shifts strided convs by one pixel
        y = nn.Conv(self.c_out, (3, 3), strides=self.stride, padding=1,
                    kernel_init=kaiming_normal)(x)
        y = Norm2d(self.norm_type, groups)(y, train and not frozen_bn)
        y = nn.relu(y)
        y = nn.Conv(self.c_out, (3, 3), kernel_init=kaiming_normal)(y)
        y = Norm2d(self.norm_type, groups)(y, train and not frozen_bn)
        y = nn.relu(y)

        if self.stride > 1:
            x = max_pool2d(x, 2, self.stride)
            x = nn.Conv(self.c_out, (1, 1), kernel_init=kaiming_normal)(x)
            x = Norm2d(self.norm_type, groups)(x, train and not frozen_bn)

        return nn.relu(x + y)


class RfpmRepairMaskNet(nn.Module):
    """Mask-and-bias correction between pyramids
    (reference rfpm/common.py:48-67): x · sigmoid(conv(left)) + tanh(conv(left))."""

    @nn.compact
    def __call__(self, left, x):
        c = x.shape[-1]
        a = nn.sigmoid(nn.Conv(c, (3, 3), kernel_init=kaiming_normal)(left))
        b = jnp.tanh(nn.Conv(c, (3, 3), kernel_init=kaiming_normal)(left))
        return x * a + b


class RfpmOutputNet(nn.Module):
    """Per-level output head (reference rfpm/common.py:70-87)."""

    output_dim: int
    hidden_dim: int = 128
    norm_type: str = "batch"
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        x = nn.Conv(self.hidden_dim, (1, 1), kernel_init=kaiming_normal)(x)
        x = Norm2d(self.norm_type, 8)(x, train and not frozen_bn)
        x = nn.relu(x)
        x = nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal)(x)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2),
                           deterministic=not train)(x)
        return x


class _Stage(nn.Module):
    """One pyramid stage across left/center/right + repair masks."""

    c_out: int
    stride: int
    norm_type: str

    @nn.compact
    def __call__(self, xl, xc, xr, train=False, frozen_bn=False):
        def res_pair(first_rfd):
            def run(x):
                if first_rfd and self.stride > 1:
                    x = RfpmRfdBlock(self.c_out, self.norm_type,
                                     self.stride)(x, train, frozen_bn)
                else:
                    x = ResidualBlock(self.c_out, self.norm_type,
                                      stride=self.stride)(x, train, frozen_bn)
                return ResidualBlock(self.c_out, self.norm_type,
                                     stride=1)(x, train, frozen_bn)
            return run

        xl = res_pair(False)(xl)
        xc = res_pair(True)(xc)
        xr = res_pair(False)(xr)

        xc = RfpmRepairMaskNet()(xl, xc)
        xr = RfpmRepairMaskNet()(xc, xr)
        return xl, xc, xr


class FeatureEncoderRfpm(nn.Module):
    """RFPM encoder; ``levels=1`` is the reference s3 (single 1/8 output),
    2/3/4 are p34/p35/p36 (heads at 1/8 .. 1/(8·2^(levels-1)))."""

    output_dim: int = 32
    levels: int = 1
    norm_type: str = "batch"
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        paired = isinstance(x, (tuple, list))
        if paired:
            n = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        x = nn.Conv(64, (7, 7), strides=2, padding=3,
                    kernel_init=kaiming_normal)(x)
        x = Norm2d(self.norm_type, 8)(x, train and not frozen_bn)
        x = nn.relu(x)

        xl = xc = xr = x
        n_stages = self.levels + 2  # heads start after stage 3 (1/8)

        outputs = []
        for stage in range(1, n_stages + 1):
            xl, xc, xr = _Stage(
                _STAGE_CHANNELS[stage - 1], 1 if stage == 1 else 2,
                self.norm_type,
            )(xl, xc, xr, train, frozen_bn)

            if stage >= 3:
                head = RfpmOutputNet(
                    self.output_dim, hidden_dim=3 * _STAGE_CHANNELS[stage],
                    norm_type=self.norm_type, dropout=self.dropout,
                )
                outputs.append(head(
                    jnp.concatenate((xl, xc, xr), axis=-1), train, frozen_bn
                ))

        outs = tuple(outputs)
        if paired:
            if len(outs) == 1:
                return outs[0][:n], outs[0][n:]
            return tuple(o[:n] for o in outs), tuple(o[n:] for o in outs)
        return outs[0] if len(outs) == 1 else outs
