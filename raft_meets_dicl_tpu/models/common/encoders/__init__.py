"""Encoder factories: family × output shape.

Mirrors the reference factory surface (src/models/common/encoders/
__init__.py:7-61): families ``raft``, ``dicl``, ``raft-avgpool``,
``raft-maxpool``, ``rfpm-raft`` over shapes ``s3`` (single-scale 1/8) and
``p34``/``p35``/``p36`` (pyramids 1/8..1/16, 1/8..1/32, 1/8..1/64).
Families are filled in as the model zoo grows; unknown names raise.
"""

from . import dicl, pool, raft, rfpm

# families are registered here as their modules get built; each entry is a
# builder (output_dim, norm_type, dropout, **kwargs) → module, pyramid
# builders additionally take ``levels`` first
_S3_FAMILIES = {
    "raft": lambda output_dim, norm_type, dropout, **kw:
        raft.FeatureEncoderS3(output_dim=output_dim, norm_type=norm_type,
                              dropout=dropout, **kw),
    "dicl": lambda output_dim, norm_type, dropout, **kw:
        dicl.s3(output_dim=output_dim, norm_type=norm_type,
                **_reject_dropout(dropout, kw)),
    "rfpm-raft": lambda output_dim, norm_type, dropout, **kw:
        rfpm.FeatureEncoderRfpm(output_dim=output_dim, levels=1,
                                norm_type=norm_type, dropout=dropout, **kw),
}
_PYRAMID_FAMILIES = {
    "raft": lambda levels, output_dim, norm_type, dropout, **kw:
        raft.FeatureEncoderPyramid(output_dim=output_dim, levels=levels,
                                   norm_type=norm_type, dropout=dropout, **kw),
    "dicl": lambda levels, output_dim, norm_type, dropout, **kw:
        dicl.pyramid(levels, output_dim=output_dim, norm_type=norm_type,
                     **_reject_dropout(dropout, kw)),
    "raft-avgpool": lambda levels, output_dim, norm_type, dropout, **kw:
        pool.FeatureEncoderPool(output_dim=output_dim, levels=levels,
                                norm_type=norm_type, dropout=dropout, **kw),
    "raft-maxpool": lambda levels, output_dim, norm_type, dropout, **kw:
        pool.FeatureEncoderPool(output_dim=output_dim, levels=levels,
                                norm_type=norm_type, dropout=dropout, **kw),
    "rfpm-raft": lambda levels, output_dim, norm_type, dropout, **kw:
        rfpm.FeatureEncoderRfpm(output_dim=output_dim, levels=levels,
                                norm_type=norm_type, dropout=dropout, **kw),
}

_KNOWN_FAMILIES = ("raft", "raft-avgpool", "raft-maxpool", "dicl", "rfpm-raft")


def _reject_dropout(dropout, kwargs):
    """GA-Net encoders have no dropout (reference dicl/*.py take none) —
    silently ignoring a configured rate would fake regularization."""
    if dropout:
        raise ValueError("the 'dicl' encoder family does not support dropout")
    return kwargs


def _resolve(families, encoder_type):
    if encoder_type in families:
        return families[encoder_type]
    if encoder_type in _KNOWN_FAMILIES:
        raise NotImplementedError(
            f"encoder family '{encoder_type}' is not implemented yet"
        )
    raise ValueError(f"unsupported feature encoder type: '{encoder_type}'")


def make_encoder_s3(encoder_type, output_dim, norm_type, dropout, **kwargs):
    build = _resolve(_S3_FAMILIES, encoder_type)
    return build(output_dim, norm_type, dropout, **kwargs)


def _make_pyramid(encoder_type, levels, output_dim, norm_type, dropout, **kwargs):
    if encoder_type in ("raft-avgpool", "raft-maxpool"):
        kwargs = {"pool_type": encoder_type.removeprefix("raft-")[:-4], **kwargs}
    build = _resolve(_PYRAMID_FAMILIES, encoder_type)
    return build(levels, output_dim, norm_type, dropout, **kwargs)


def make_encoder_p34(encoder_type, output_dim, norm_type, dropout, **kwargs):
    return _make_pyramid(encoder_type, 2, output_dim, norm_type, dropout, **kwargs)


def make_encoder_p35(encoder_type, output_dim, norm_type, dropout, **kwargs):
    return _make_pyramid(encoder_type, 3, output_dim, norm_type, dropout, **kwargs)


def make_encoder_p36(encoder_type, output_dim, norm_type, dropout, **kwargs):
    return _make_pyramid(encoder_type, 4, output_dim, norm_type, dropout, **kwargs)
