"""Pooled pyramid encoders: a single-scale RAFT encoding, avg/max-pooled
for the coarser levels (Flax, NHWC).

Behavioral equivalent of reference src/models/common/encoders/pool/p3*.py —
three hand-written variants of one structure: the s3 trunk produces the
1/8 features, every coarser level is a 2x pool of the previous one, with
per-level channel dropout.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ....ops.pool import avg_pool2d, max_pool2d
from .raft import _Stem, _drop2d
from ..blocks.raft import kaiming_normal


class FeatureEncoderPool(nn.Module):
    """(B, H, W, 3) → tuple of features at 1/8 .. 1/(8·2^(levels-1))."""

    output_dim: int = 128
    levels: int = 2
    norm_type: str = "batch"
    dropout: float = 0.0
    pool_type: str = "avg"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False) -> Tuple:
        if self.pool_type not in ("avg", "max"):
            raise ValueError(f"invalid pool_type value: '{self.pool_type}'")
        pool = avg_pool2d if self.pool_type == "avg" else max_pool2d

        paired = isinstance(x, (tuple, list))
        if paired:
            n = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        x = _Stem(self.norm_type, dtype=self.dtype)(x, train, frozen_bn)
        x = nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal,
                    dtype=self.dtype)(x)

        outputs = []
        for i in range(self.levels):
            if i > 0:
                x = pool(x, 2)
            out = _drop2d(x, self.dropout, train) if self.dropout > 0 else x
            outputs.append(out)

        if paired:
            return (
                tuple(o[:n] for o in outputs),
                tuple(o[n:] for o in outputs),
            )
        return tuple(outputs)
