"""RAFT feature/context encoders (Flax, NHWC).

Single-scale s3 (1/8 resolution) after the reference
(src/models/common/encoders/raft/s3.py): 7x7 stride-2 input conv, three
residual stages (64/96/128), 1x1 output conv, optional 2D dropout.

The reference's shared-batch trick for image pairs (s3.py:53-57) is kept:
pass a tuple ``(img1, img2)`` and both are encoded in one batched pass.

Pyramid variants (p34/p35/p36) extend the residual stack with 160/192
channel stages and per-level output heads (reference raft/p36.py,
raft/common.py) returning features at 1/8..1/64.
"""

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..blocks.raft import ResidualBlock, kaiming_normal
from ..norm import Norm2d


class _Stem(nn.Module):
    """Input conv + the first three residual stages (to 1/8, 128ch)."""

    norm_type: str = "instance"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        dt = self.dtype
        x = nn.Conv(64, (7, 7), strides=2, padding=3, kernel_init=kaiming_normal,
                    dtype=dt)(x)
        x = Norm2d(self.norm_type, 8, dtype=dt)(x, train and not frozen_bn)
        x = nn.relu(x)

        x = ResidualBlock(64, self.norm_type, stride=1, dtype=dt)(x, train, frozen_bn)
        x = ResidualBlock(64, self.norm_type, stride=1, dtype=dt)(x, train, frozen_bn)

        x = ResidualBlock(96, self.norm_type, stride=2, dtype=dt)(x, train, frozen_bn)
        x = ResidualBlock(96, self.norm_type, stride=1, dtype=dt)(x, train, frozen_bn)

        x = ResidualBlock(128, self.norm_type, stride=2, dtype=dt)(x, train, frozen_bn)
        x = ResidualBlock(128, self.norm_type, stride=1, dtype=dt)(x, train, frozen_bn)

        return x


def _drop2d(x, rate, train):
    """Channel dropout (torch Dropout2d): broadcast over spatial dims."""
    return nn.Dropout(rate, broadcast_dims=(1, 2), deterministic=not train)(x)


class FeatureEncoderS3(nn.Module):
    """Single-scale encoder: (B, H, W, 3) → (B, H/8, W/8, output_dim)."""

    output_dim: int = 128
    norm_type: str = "instance"
    dropout: float = 0.0
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        paired = isinstance(x, (tuple, list))
        if paired:
            n = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        x = _Stem(self.norm_type, dtype=self.dtype)(x, train, frozen_bn)
        x = nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal,
                    dtype=self.dtype)(x)
        if self.dropout > 0:
            x = _drop2d(x, self.dropout, train)

        if paired:
            return x[:n], x[n:]
        return x


class EncoderOutputNet(nn.Module):
    """Per-level output head: 3x3 conv + norm + relu + 1x1 conv
    (reference raft/common.py:6-29)."""

    output_dim: int
    intermediate_dim: int = 128
    norm_type: str = "batch"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        x = nn.Conv(self.intermediate_dim, (3, 3), kernel_init=kaiming_normal,
                    dtype=self.dtype)(x)
        x = Norm2d(self.norm_type, 8, dtype=self.dtype)(x, train and not frozen_bn)
        x = nn.relu(x)
        x = nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal,
                    dtype=self.dtype)(x)
        return x


class FeatureEncoderPyramid(nn.Module):
    """Pyramid encoder returning features at 1/8 .. 1/(8*2^(levels-1)).

    ``levels=2`` ≈ reference p34 (1/8, 1/16), ``3`` ≈ p35, ``4`` ≈ p36.
    Extra residual stages use 160/192/224 channels like the reference
    (raft/p36.py:9-61); each level gets its own output head.
    """

    output_dim: int = 128
    levels: int = 3
    norm_type: str = "instance"
    dropout: float = 0.0
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False) -> Tuple:
        dt = self.dtype
        paired = isinstance(x, (tuple, list))
        if paired:
            n = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        x = _Stem(self.norm_type, dtype=dt)(x, train, frozen_bn)  # 1/8, 128ch

        stage_channels = (160, 192, 224)
        # per-level head widths grow with the pyramid: out3..out6 use
        # 160/192/224/256 intermediates (reference raft/p35.py:47-49,
        # p36.py:52-55)
        outputs = []
        for i in range(self.levels):
            out = EncoderOutputNet(self.output_dim,
                                   intermediate_dim=160 + 32 * i,
                                   norm_type=self.norm_type,
                                   dtype=dt)(x, train, frozen_bn)
            if self.dropout > 0:
                out = _drop2d(out, self.dropout, train)
            outputs.append(out)

            if i + 1 < self.levels:
                ch = stage_channels[min(i, len(stage_channels) - 1)]
                x = ResidualBlock(ch, self.norm_type, stride=2, dtype=dt)(x, train, frozen_bn)
                x = ResidualBlock(ch, self.norm_type, stride=1, dtype=dt)(x, train, frozen_bn)

        if paired:
            return (
                tuple(o[:n] for o in outputs),
                tuple(o[n:] for o in outputs),
            )
        return tuple(outputs)
