"""2D normalization with a string-typed factory, Flax edition.

Mirrors the reference factory (src/models/common/norm.py:4-16) with torch
hyperparameters (eps 1e-5, BN momentum 0.1 → flax momentum 0.9; instance
norm non-affine). Batchnorm freezing is not implemented by module surgery
like the reference (norm.py:18-32) — it's an apply-time switch: the model
wrapper passes ``train=False``-equivalent ``use_running_average`` into
``Norm2d.__call__`` (see models/model.py ``Model.apply``).
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

NORM_TYPES = ("group", "batch", "instance", "none")


class Norm2d(nn.Module):
    """Dispatches to group/batch/instance/no normalization over NHWC maps.

    ``train`` only affects batch norm (running-stats update vs. use).
    ``dtype`` is the return/compute dtype; flax norm layers compute the
    statistics in float32 internally regardless.
    """

    ty: str
    num_groups: int = 8
    dtype: Any = None
    # batch norm only: compute live statistics over `splits` equal
    # leading-axis chunks instead of the whole batch. Encoders that fold
    # an (img1, img2) pair into one 2N batch for conv efficiency set
    # splits=2 when the REFERENCE runs the two images through separate
    # calls (per-image stats, sequential running-stat updates) — only
    # the norm couples the pair, so only the norm needs to split
    # (reference src/models/impls/dicl.py:277-278).
    splits: int = 1

    @nn.compact
    def __call__(self, x, train=False):
        if self.ty == "group":
            return nn.GroupNorm(
                num_groups=self.num_groups, epsilon=1e-5, dtype=self.dtype
            )(x)
        if self.ty == "batch":
            bn = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.dtype,
            )
            if train and self.splits > 1:
                # one shared BatchNorm instance applied per chunk: same
                # parameter tree, per-chunk statistics, and the second
                # call's running-stat update reads the first's result —
                # exactly the reference's sequential per-image calls
                n = x.shape[0] // self.splits
                return jnp.concatenate(
                    [bn(x[i * n:(i + 1) * n]) for i in range(self.splits)],
                    axis=0)
            return bn(x)
        if self.ty == "instance":
            # per-sample, per-channel over spatial dims; non-affine like torch
            return nn.GroupNorm(
                num_groups=None, group_size=1, epsilon=1e-5,
                use_scale=False, use_bias=False, dtype=self.dtype,
            )(x)
        if self.ty == "none":
            return x
        raise ValueError(f"unknown norm type '{self.ty}'")


def make_norm2d(ty, num_channels=None, num_groups=8):
    """Factory matching the reference signature; ``num_channels`` is implied
    by the input in flax and kept only for call-site compatibility."""
    if ty not in NORM_TYPES:
        raise ValueError(f"unknown norm type '{ty}'")
    return Norm2d(ty=ty, num_groups=num_groups)
