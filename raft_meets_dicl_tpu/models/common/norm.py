"""2D normalization with a string-typed factory, Flax edition.

Mirrors the reference factory (src/models/common/norm.py:4-16) with torch
hyperparameters (eps 1e-5, BN momentum 0.1 → flax momentum 0.9; instance
norm non-affine). Batchnorm freezing is not implemented by module surgery
like the reference (norm.py:18-32) — it's an apply-time switch: the model
wrapper passes ``train=False``-equivalent ``use_running_average`` into
``Norm2d.__call__`` (see models/model.py ``Model.apply``).
"""

from typing import Any

import flax.linen as nn

NORM_TYPES = ("group", "batch", "instance", "none")


class Norm2d(nn.Module):
    """Dispatches to group/batch/instance/no normalization over NHWC maps.

    ``train`` only affects batch norm (running-stats update vs. use).
    ``dtype`` is the return/compute dtype; flax norm layers compute the
    statistics in float32 internally regardless.
    """

    ty: str
    num_groups: int = 8
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False):
        if self.ty == "group":
            return nn.GroupNorm(
                num_groups=self.num_groups, epsilon=1e-5, dtype=self.dtype
            )(x)
        if self.ty == "batch":
            return nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.dtype,
            )(x)
        if self.ty == "instance":
            # per-sample, per-channel over spatial dims; non-affine like torch
            return nn.GroupNorm(
                num_groups=None, group_size=1, epsilon=1e-5,
                use_scale=False, use_bias=False, dtype=self.dtype,
            )(x)
        if self.ty == "none":
            return x
        raise ValueError(f"unknown norm type '{self.ty}'")


def make_norm2d(ty, num_channels=None, num_groups=8):
    """Factory matching the reference signature; ``num_channels`` is implied
    by the input in flax and kept only for call-site compatibility."""
    if ty not in NORM_TYPES:
        raise ValueError(f"unknown norm type '{ty}'")
    return Norm2d(ty=ty, num_groups=num_groups)
