"""Multi-level sequence result adapter (reference
src/models/common/adapters/mlseq.py:4-33).

Model output is a list of per-level lists ordered coarse-to-fine, each
level a sequence of per-iteration flows; entries may be (prev, flow)
tuples when the model emits previous-flow intermediates.
"""

from ...model import ModelAdapter, Result


class MultiLevelSequenceAdapter(ModelAdapter):
    def wrap_result(self, result, original_shape) -> Result:
        return MultiLevelSequenceResult(result, original_shape)


class MultiLevelSequenceResult(Result):
    def __init__(self, output, shape):
        super().__init__()
        self.result = output  # list of lists: (level, iteration)
        self.shape = shape

    def output(self, batch_index=None):
        if batch_index is None:
            return self.result

        def sl(x):
            return x[batch_index : batch_index + 1]

        if not isinstance(self.result[0][0], (tuple, list)):
            return [[sl(x) for x in level] for level in self.result]
        return [[[sl(x) for x in tp] for tp in level] for level in self.result]

    def final(self):
        final = self.result[-1][-1]
        return final[-1] if isinstance(final, (list, tuple)) else final

    def intermediate_flow(self):
        return self.result
