from . import mlseq
from .mlseq import MultiLevelSequenceAdapter, MultiLevelSequenceResult

__all__ = ["mlseq", "MultiLevelSequenceAdapter", "MultiLevelSequenceResult"]
