from . import blocks, encoders, grid, hsup, norm, warp

__all__ = ["blocks", "encoders", "grid", "hsup", "norm", "warp"]
