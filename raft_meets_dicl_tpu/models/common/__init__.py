from . import blocks, corr, encoders, grid, hsup, norm, warp

__all__ = ["blocks", "corr", "encoders", "grid", "hsup", "norm", "warp"]
