from . import adapters, blocks, corr, encoders, grid, hsup, loss, norm, warp

__all__ = ["adapters", "blocks", "corr", "encoders", "grid", "hsup", "loss",
           "norm", "warp"]
