"""RAFT encoder building blocks (Flax, NHWC).

Behavioral equivalent of the reference blocks (src/models/common/blocks/
raft.py:13-46) with kaiming-normal conv init like the reference encoders.

``dtype`` is the compute dtype (bf16 under the mixed-precision policy —
the TPU analog of the reference's autocast regions,
src/models/impls/raft.py:377-415); params stay float32, norm statistics
are computed in float32 inside the flax norm layers.
"""

from typing import Any

import flax.linen as nn

from ..norm import Norm2d

kaiming_normal = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class ResidualBlock(nn.Module):
    """Two 3x3 convs with norm + residual; strided 1x1 downsample path."""

    out_planes: int
    norm_type: str = "group"
    stride: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        groups = self.out_planes // 8
        norm_train = train and not frozen_bn

        # explicit symmetric padding: flax 'SAME' pads (0, 1) on strided
        # convs over even inputs where torch pads (1, 1) — one-pixel shift
        y = nn.Conv(self.out_planes, (3, 3), strides=self.stride, padding=1,
                    kernel_init=kaiming_normal, dtype=self.dtype)(x)
        y = Norm2d(self.norm_type, groups, dtype=self.dtype)(y, norm_train)
        y = nn.relu(y)

        y = nn.Conv(self.out_planes, (3, 3), kernel_init=kaiming_normal,
                    dtype=self.dtype)(y)
        y = Norm2d(self.norm_type, groups, dtype=self.dtype)(y, norm_train)
        y = nn.relu(y)

        if self.stride > 1:
            x = nn.Conv(self.out_planes, (1, 1), strides=self.stride,
                        kernel_init=kaiming_normal, dtype=self.dtype)(x)
            x = Norm2d(self.norm_type, groups, dtype=self.dtype)(x, norm_train)

        return nn.relu(x + y)
