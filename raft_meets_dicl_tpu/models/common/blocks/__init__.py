from . import dicl, raft

__all__ = ["dicl", "raft"]
