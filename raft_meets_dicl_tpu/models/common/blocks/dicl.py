"""DICL / GA-Net building blocks (Flax, NHWC).

Behavioral equivalents of the reference blocks (src/models/common/blocks/
dicl.py): conv blocks, GA-Net 2x up/down fusion blocks, the per-displacement
MatchingNet, and the displacement-aware projection (DAP).

TPU-native layout decisions:
- Matching volumes are ``(B, du, dv, H, W, C)``; MatchingNet folds the
  displacement axes into the batch so XLA sees one big conv over
  ``B*du*dv`` maps (the reference does the same reshape trick with NCHW,
  dicl.py:93-118).
- Cost volumes are ``(B, H, W, du, dv)``; DAP flattens (du, dv) into the
  trailing channel axis, making it a plain 1x1 conv — the ideal layout for
  the TPU MXU (channels-last matmul over du*dv).
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..norm import Norm2d
from ..util import ConvParams, identity_1x1_init


class ConvBlock(nn.Module):
    """conv → norm → relu (no conv bias, like the reference).

    Input may also be a pair ``(shared, per_item)`` with shared (B, H, W,
    C1) and per_item (B·N, H, W, C2): the conv then splits along its input
    channels — conv(concat) = conv(shared) broadcast over N + conv(per_item)
    by linearity — computing the shared half once instead of N times.
    Parameters are identical to the concatenated form (kernel channels
    ordered shared-first).
    """

    c_out: int
    kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    norm_type: str = "batch"
    num_groups: int = 8
    dtype: Any = None
    bn_splits: int = 1

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        if isinstance(x, tuple):
            shared, per_item = x
            c1 = shared.shape[-1]
            kernel = ConvParams(
                self.c_out, (self.kernel_size, self.kernel_size),
                use_bias=False, name="Conv_0")(c1 + per_item.shape[-1])

            dt = self.dtype or kernel.dtype
            pad = self.dilation * (self.kernel_size // 2)

            def conv(inp, kk):
                return jax.lax.conv_general_dilated(
                    inp.astype(dt), kk.astype(dt),
                    (self.stride, self.stride), [(pad, pad), (pad, pad)],
                    rhs_dilation=(self.dilation, self.dilation),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))

            ys = conv(shared, kernel[:, :, :c1])       # (B, h', w', c_out)
            yp = conv(per_item, kernel[:, :, c1:])     # (B·N, h', w', c_out)
            n = yp.shape[0] // ys.shape[0]
            x = (yp.reshape(ys.shape[0], n, *yp.shape[1:])
                 + ys[:, None]).reshape(yp.shape)
        else:
            # explicit torch-convention padding (flax 'SAME' shifts strided
            # convs by one pixel on even inputs)
            x = nn.Conv(
                self.c_out,
                (self.kernel_size, self.kernel_size),
                strides=self.stride,
                kernel_dilation=self.dilation,
                padding=self.dilation * (self.kernel_size // 2),
                use_bias=False,
                dtype=self.dtype,
            )(x)
        x = Norm2d(self.norm_type, self.num_groups, dtype=self.dtype,
                   splits=self.bn_splits)(x, train and not frozen_bn)
        return nn.relu(x)


class ConvBlockTransposed(nn.Module):
    """transposed conv (2x up, k=4 s=2 p=1 torch geometry) → norm → relu.

    flax ``padding='SAME'`` reproduces torch's k4/s2/p1 exactly (out = 2·in,
    same border alignment — verified bit-exact in f64 against
    ``F.conv_transpose2d``); explicit pair padding in flax means something
    different and loses pixels.
    """

    c_out: int
    norm_type: str = "batch"
    num_groups: int = 8
    dtype: Any = None
    bn_splits: int = 1

    @nn.compact
    def __call__(self, x, train=False, frozen_bn=False):
        x = nn.ConvTranspose(
            self.c_out, (4, 4), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype,
        )(x)
        x = Norm2d(self.norm_type, self.num_groups, dtype=self.dtype,
                   splits=self.bn_splits)(x, train and not frozen_bn)
        return nn.relu(x)


class GaConv2xBlock(nn.Module):
    """Strided 3x3 downsample fused with a same-resolution skip input."""

    c_out: int
    norm_type: str = "batch"
    bn_splits: int = 1

    @nn.compact
    def __call__(self, x, res, train=False, frozen_bn=False):
        x = nn.Conv(self.c_out, (3, 3), strides=2, padding=1,
                    use_bias=False)(x)
        x = nn.relu(x)

        assert x.shape == res.shape
        x = jnp.concatenate((x, res), axis=-1)

        x = nn.Conv(self.c_out, (3, 3), use_bias=False)(x)
        x = Norm2d(self.norm_type, 8, splits=self.bn_splits)(
            x, train and not frozen_bn)
        return nn.relu(x)


class GaConv2xBlockTransposed(nn.Module):
    """2x transposed-conv upsample fused with a same-resolution skip input."""

    c_out: int
    norm_type: str = "batch"
    bn_splits: int = 1

    @nn.compact
    def __call__(self, x, res, train=False, frozen_bn=False):
        # 'SAME' = torch k4/s2/p1 geometry (see ConvBlockTransposed)
        x = nn.ConvTranspose(
            self.c_out, (4, 4), strides=(2, 2), padding="SAME", use_bias=False,
        )(x)
        x = nn.relu(x)

        assert x.shape == res.shape
        x = jnp.concatenate((x, res), axis=-1)

        x = nn.Conv(self.c_out, (3, 3), use_bias=False)(x)
        x = Norm2d(self.norm_type, 8, splits=self.bn_splits)(
            x, train and not frozen_bn)
        return nn.relu(x)


class MatchingNet(nn.Module):
    """6-layer conv hourglass applied per displacement candidate.

    Input ``(B, du, dv, H, W, C)`` (stacked feature pairs), output cost
    ``(B, H, W, du, dv)``. The displacement axes ride the batch dimension
    through the convs — one large batched conv instead of du*dv small ones.

    Alternatively input may be the pair ``(f1, window)`` with f1
    (B, H, W, C) and window (B, du, dv, H, W, C) *unstacked*: the first
    conv then splits along its input channels — the f1 half is computed
    once and broadcast over displacements instead of convolving the same
    f1 values du·dv times (half the first conv's FLOPs, and the
    (B, du, dv, H, W, C) f1 broadcast never materializes). Parameters are
    identical to the stacked form.
    """

    norm_type: str = "batch"
    scale: float = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, mvol, train=False, frozen_bn=False):
        dt = self.dtype
        c1 = int(self.scale * 96)
        c2 = int(self.scale * 128)
        c3 = int(self.scale * 64)
        c4 = int(self.scale * 32)

        if isinstance(mvol, tuple):
            f1, window = mvol
            b, du, dv, h, w, c = window.shape
            x = ConvBlock(c1, norm_type=self.norm_type, dtype=dt)(
                (f1, window.reshape(b * du * dv, h, w, c)), train, frozen_bn)
        else:
            b, du, dv, h, w, c = mvol.shape
            x = mvol.reshape(b * du * dv, h, w, c)
            x = ConvBlock(c1, norm_type=self.norm_type, dtype=dt)(
                x, train, frozen_bn)
        x = ConvBlock(c2, stride=2, norm_type=self.norm_type, dtype=dt)(x, train, frozen_bn)
        x = ConvBlock(c2, norm_type=self.norm_type, dtype=dt)(x, train, frozen_bn)
        x = ConvBlock(c3, norm_type=self.norm_type, dtype=dt)(x, train, frozen_bn)
        x = ConvBlockTransposed(c4, norm_type=self.norm_type, num_groups=4, dtype=dt)(x, train, frozen_bn)
        x = nn.Conv(1, (3, 3), dtype=dt)(x)  # with bias, like the reference

        # the cost volume is the readout surface (softargmax/DAP): f32
        cost = x.reshape(b, du, dv, h, w).astype(jnp.float32)
        return cost.transpose(0, 3, 4, 1, 2)  # (B, H, W, du, dv)


class DisplacementAwareProjection(nn.Module):
    """1x1 conv mixing the du*dv displacement channels of a cost volume.

    Input/output ``(B, H, W, du, dv)``. ``init='identity'`` starts as a
    no-op projection (reference dicl.py:121-150).
    """

    disp_range: tuple
    init: str = "identity"

    @nn.compact
    def __call__(self, x):
        if self.init not in ("identity", "standard"):
            raise ValueError(f"unknown init value '{self.init}'")

        b, h, w, du, dv = x.shape
        assert (du, dv) == (2 * self.disp_range[0] + 1, 2 * self.disp_range[1] + 1)

        kernel_init = (
            identity_1x1_init if self.init == "identity" else nn.initializers.lecun_normal()
        )

        x = x.reshape(b, h, w, du * dv)
        x = nn.Conv(du * dv, (1, 1), use_bias=False, kernel_init=kernel_init)(x)
        return x.reshape(b, h, w, du, dv)
