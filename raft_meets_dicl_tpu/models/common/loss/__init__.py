from . import mlseq
from .mlseq import MultiLevelSequenceLoss, upsample_flow_to

__all__ = ["mlseq", "MultiLevelSequenceLoss", "upsample_flow_to"]
