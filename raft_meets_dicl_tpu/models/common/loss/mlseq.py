"""Multi-level sequence loss (reference src/models/common/loss/mlseq.py:7-69).

Per-level weight α × per-iteration weight γ^(n−i−1), each flow upsampled to
the target resolution (align-corners bilinear with displacement rescaling)
and penalized by an L-ord distance over valid pixels.
"""

import jax.numpy as jnp

from ....ops.upsample import interpolate_bilinear
from ...config import register_loss
from ...model import Loss


def upsample_flow_to(flow, shape):
    """align-corners bilinear resize of a flow field to (H, W), rescaling
    the displacement values by the size ratio."""
    _, fh, fw, _ = flow.shape
    th, tw = shape
    if (fh, fw) == (th, tw):
        return flow

    flow = interpolate_bilinear(flow, (th, tw))
    return flow * jnp.asarray([tw / fw, th / fh], dtype=flow.dtype)


@register_loss
class MultiLevelSequenceLoss(Loss):
    type = "raft+dicl/mlseq"

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)
        return cls(cfg.get("arguments", {}))

    def __init__(self, arguments={}):
        super().__init__(arguments)

    def get_config(self):
        default_args = {
            "ord": 1,
            "gamma": 0.8,
            "alpha": (1.0, 0.5),
            "scale": 1.0,
        }
        return {"type": self.type, "arguments": default_args | self.arguments}

    def compute(self, model, result, target, valid, ord=1, gamma=0.8,
                alpha=(0.4, 1.0), scale=1.0):
        th, tw = target.shape[1:3]
        valid_f = valid.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(valid_f), 1.0)

        loss = 0.0
        for i_level, level in enumerate(result):
            n = len(level)
            for i_seq, flow in enumerate(level):
                weight = alpha[i_level] * gamma ** (n - i_seq - 1)

                flow = upsample_flow_to(flow, (th, tw))
                dist = jnp.linalg.norm(flow - target, ord=float(ord), axis=-1)
                loss = loss + weight * jnp.sum(dist * valid_f) / denom

        return loss * scale
