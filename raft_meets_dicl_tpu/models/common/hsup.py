"""Hidden-state upsamplers between coarse-to-fine pyramid levels.

Reference: src/models/common/hsup.py — carries the GRU hidden state from a
coarse level into the next finer level's initialization. Three variants:
``none`` (use the fine init), ``bilinear`` (identity-init 1x1 conv +
bilinear 2x + add), ``crossattn`` (3x3-window cross-attention with Q from
the fine init and K/V unfolded from the coarse state).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from .util import identity_1x1_init


def upsample2d_bilinear(x, size):
    """align_corners=True bilinear resize to ``size`` = (H, W), NHWC
    (static-matrix contraction form — no gather, see
    ops.upsample.interpolate_bilinear)."""
    from ...ops.upsample import interpolate_bilinear

    return interpolate_bilinear(x, size)


class HUpNone(nn.Module):
    recurrent_channels: int

    def __call__(self, h_prev, h_init):
        return h_init


class HUpBilinear(nn.Module):
    """Identity-init 1x1 conv on the coarse state, 2x bilinear, add."""

    recurrent_channels: int

    @nn.compact
    def __call__(self, h_prev, h_init):
        b, h, w, c = h_init.shape

        h_prev = nn.Conv(self.recurrent_channels, (1, 1),
                         kernel_init=identity_1x1_init)(h_prev)
        h_prev = upsample2d_bilinear(h_prev, (h, w))

        return h_init + h_prev


class HUpCrossAttn(nn.Module):
    """Local 3x3-window cross-attention from fine init to coarse state."""

    recurrent_channels: int
    key_channels: int = 64

    @nn.compact
    def __call__(self, h_prev, h_init):
        b, h, w, _ = h_init.shape
        _, h2, w2, _ = h_prev.shape
        ck, cv = self.key_channels, self.recurrent_channels

        q = nn.Conv(ck, (1, 1))(h_init)       # (B, h, w, ck)
        k = nn.Conv(ck, (1, 1))(h_prev)       # (B, h2, w2, ck)
        v = nn.Conv(cv, (1, 1))(h_prev)       # (B, h2, w2, cv)

        def unfold3x3(t):
            # (B, h2, w2, 9, C): zero-padded 3x3 neighborhoods
            t = jnp.pad(t, ((0, 0), (1, 1), (1, 1), (0, 0)))
            patches = [
                t[:, dy : dy + h2, dx : dx + w2]
                for dy in range(3)
                for dx in range(3)
            ]
            return jnp.stack(patches, axis=3)

        def expand_to_fine(t):
            # nearest-repeat each coarse cell onto its fine-level block
            ry, rx = h // h2, w // w2
            t = jnp.repeat(t, ry, axis=1)
            return jnp.repeat(t, rx, axis=2)

        k_win = expand_to_fine(unfold3x3(k))  # (B, h, w, 9, ck)
        v_win = expand_to_fine(unfold3x3(v))  # (B, h, w, 9, cv)

        attn = jnp.einsum("bhwc,bhwkc->bhwk", q, k_win)
        attn = jax.nn.softmax(attn, axis=-1)

        x = jnp.einsum("bhwk,bhwkc->bhwc", attn, v_win)

        v_init = nn.Conv(cv, (1, 1))(h_init)
        return nn.Conv(self.recurrent_channels, (1, 1))(v_init + x)


def make_hidden_state_upsampler(type, recurrent_channels):
    if type == "none":
        return HUpNone(recurrent_channels)
    if type == "bilinear":
        return HUpBilinear(recurrent_channels)
    if type == "crossattn":
        return HUpCrossAttn(recurrent_channels)
    raise ValueError(f"unknown hidden state upsampler type '{type}'")
