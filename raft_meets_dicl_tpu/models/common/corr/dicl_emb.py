"""DICL correlation module with pair embeddings.

Behavioral equivalent of reference src/models/common/corr/dicl_emb.py: the
matching volume gains the window offsets as positional-encoding channels, a
pointwise pair-embedding net produces per-displacement embeddings, and the
(DAP-weighted) cost softmax attends over them — the module outputs the cost
volume concatenated with the attended embedding.
"""

import flax.linen as nn
import jax.numpy as jnp

from ....ops.corr import window_delta
from ..blocks.dicl import DisplacementAwareProjection, MatchingNet
from .common import soft_argmax_flow, sample_window, stack_pair

__all__ = ["CorrelationModule", "PairEmbedding", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class PairEmbedding(nn.Module):
    """Pointwise embedding of stacked feature pairs
    (reference dicl_emb.py:8-29)."""

    output_dim: int = 32

    @nn.compact
    def __call__(self, fstack):
        b, du, dv, h, w, c = fstack.shape

        x = fstack.reshape(b * du * dv, h, w, c)
        x = nn.relu(nn.Conv(48, (1, 1))(x))
        x = nn.relu(nn.Conv(64, (1, 1))(x))
        x = nn.Conv(self.output_dim, (1, 1))(x)

        return x.reshape(b, du, dv, h, w, self.output_dim)


class CorrelationModule(nn.Module):
    feature_dim: int
    radius: int
    embedding_dim: int = 32
    dap_init: str = "identity"
    norm_type: str = "batch"

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2 + self.embedding_dim

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape
        k = 2 * self.radius + 1

        window = sample_window(f2, coords, self.radius)
        mvol = stack_pair(f1, window)  # (B, du, dv, H, W, 2C)

        # window offsets as positional encodings (dicl_emb.py:78-83)
        delta = window_delta(self.radius, mvol.dtype)  # (K, K, 2)
        delta = jnp.broadcast_to(
            delta[None, :, :, None, None, :], (b, k, k, h, w, 2)
        )
        mvol = jnp.concatenate((mvol, delta), axis=-1)

        cost = MatchingNet(norm_type=self.norm_type)(mvol, train, frozen_bn)
        emb = PairEmbedding(self.embedding_dim)(mvol)  # (B, du, dv, H, W, E)

        score = cost
        if dap:
            score = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(cost)

        # attention over the displacement candidates
        score = nn.softmax(score.reshape(b, h, w, k * k), axis=-1)
        emb = emb.transpose(0, 3, 4, 1, 2, 5).reshape(b, h, w, k * k, -1)
        attended = jnp.einsum("bhwd,bhwde->bhwe", score, emb)

        return jnp.concatenate(
            (cost.reshape(b, h, w, k * k), attended), axis=-1
        )


class SoftArgMaxFlowRegression(nn.Module):
    """Readout over the cost slice of the (cost ++ embedding) output.

    The reference version (dicl_emb.py:107-135) slices then regresses; the
    embedding channels are ignored for flow.
    """

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, out):
        k2 = (2 * self.radius + 1) ** 2
        return soft_argmax_flow(out[..., :k2], self.radius, self.temperature)


class SoftArgMaxFlowRegressionWithDap(nn.Module):
    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, out):
        b, h, w, _ = out.shape
        k = 2 * self.radius + 1
        k2 = k * k

        vol = out[..., :k2].reshape(b, h, w, k, k)
        vol = DisplacementAwareProjection((self.radius, self.radius))(vol)
        return soft_argmax_flow(vol.reshape(b, h, w, k2), self.radius,
                                self.temperature)
