"""DICL correlation module with pair embeddings.

Behavioral equivalent of reference src/models/common/corr/dicl_emb.py: the
matching volume gains the window offsets as positional-encoding channels, a
pointwise pair-embedding net produces per-displacement embeddings, and the
(DAP-weighted) cost softmax attends over them — the module outputs the cost
volume concatenated with the attended embedding.

Both nets consume the unstacked ``(f1, window ++ delta)`` pair: their first
convs split along the input channels (f1 half computed once, broadcast over
displacements), so the stacked (B, du, dv, H, W, 2C+2) volume's f1 copies
never materialize. Parameters are identical to the stacked form
(``stack_pair`` remains the parity reference for tests).
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ....ops.corr import window_delta
from ..blocks.dicl import DisplacementAwareProjection, MatchingNet
from ..util import ConvParams
from .common import (
    record_matching_bytes,
    sample_window_fast,
    soft_argmax_flow,
)

__all__ = ["CorrelationModule", "PairEmbedding", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class PairEmbedding(nn.Module):
    """Pointwise embedding of stacked feature pairs
    (reference dicl_emb.py:8-29).

    Accepts the stacked ``(B, du, dv, H, W, C)`` volume or the unstacked
    ``(shared, per_item)`` pair — the first conv then splits along its
    input channels by linearity (shared-first kernel order, parameters
    identical to the stacked form).
    """

    output_dim: int = 32
    dtype: Any = None

    @nn.compact
    def __call__(self, fstack):
        if isinstance(fstack, tuple):
            shared, per_item = fstack
            b, du, dv, h, w, c = per_item.shape
            x = per_item.reshape(b * du * dv, h, w, c)

            c1 = shared.shape[-1]
            kernel, bias = ConvParams(48, (1, 1), name="Conv_0")(c1 + c)
            dt = self.dtype or kernel.dtype

            def conv(inp, kk):
                return jax.lax.conv_general_dilated(
                    inp.astype(dt), kk.astype(dt), (1, 1),
                    [(0, 0), (0, 0)],
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))

            ys = conv(shared, kernel[:, :, :c1])       # (B, H, W, 48)
            yp = conv(x, kernel[:, :, c1:])            # (B·N, H, W, 48)
            n = yp.shape[0] // ys.shape[0]
            x = (yp.reshape(ys.shape[0], n, *yp.shape[1:])
                 + ys[:, None]).reshape(yp.shape)
            x = nn.relu(x + bias.astype(dt))
        else:
            b, du, dv, h, w, c = fstack.shape
            x = fstack.reshape(b * du * dv, h, w, c)
            x = nn.relu(nn.Conv(48, (1, 1), dtype=self.dtype,
                                name="Conv_0")(x))

        x = nn.relu(nn.Conv(64, (1, 1), dtype=self.dtype, name="Conv_1")(x))
        x = nn.Conv(self.output_dim, (1, 1), dtype=self.dtype,
                    name="Conv_2")(x)

        # embeddings feed the (f32) attention readout
        x = x.astype(jnp.float32)
        return x.reshape(b, du, dv, h, w, self.output_dim)


class CorrelationModule(nn.Module):
    feature_dim: int
    radius: int
    embedding_dim: int = 32
    dap_init: str = "identity"
    norm_type: str = "batch"
    dtype: Any = None

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2 + self.embedding_dim

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape
        k = 2 * self.radius + 1

        window = sample_window_fast(f2, coords, self.radius)

        # window offsets as positional encodings (dicl_emb.py:78-83),
        # riding the per-displacement half of the unstacked pair so the
        # kernel channel order matches the stacked [f1 | window | delta]
        delta = window_delta(self.radius, window.dtype)  # (K, K, 2)
        delta = jnp.broadcast_to(
            delta[None, :, :, None, None, :], (b, k, k, h, w, 2)
        )
        if self.dtype is not None:
            f1 = f1.astype(self.dtype)
            window = window.astype(self.dtype)
            delta = delta.astype(self.dtype)
        per_item = jnp.concatenate((window, delta), axis=-1)
        if not self.is_initializing():
            record_matching_bytes(f1, per_item)

        cost = MatchingNet(norm_type=self.norm_type, dtype=self.dtype)(
            (f1, per_item), train, frozen_bn)
        emb = PairEmbedding(self.embedding_dim, dtype=self.dtype)(
            (f1, per_item))  # (B, du, dv, H, W, E)

        score = cost
        if dap:
            score = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(cost)

        # attention over the displacement candidates
        score = nn.softmax(score.reshape(b, h, w, k * k), axis=-1)
        emb = emb.transpose(0, 3, 4, 1, 2, 5).reshape(b, h, w, k * k, -1)
        attended = jnp.einsum("bhwd,bhwde->bhwe", score, emb)

        return jnp.concatenate(
            (cost.reshape(b, h, w, k * k), attended), axis=-1
        )


class SoftArgMaxFlowRegression(nn.Module):
    """Readout over the cost slice of the (cost ++ embedding) output.

    The reference version (dicl_emb.py:107-135) slices then regresses; the
    embedding channels are ignored for flow.
    """

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, out):
        k2 = (2 * self.radius + 1) ** 2
        return soft_argmax_flow(out[..., :k2], self.radius, self.temperature)


class SoftArgMaxFlowRegressionWithDap(nn.Module):
    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, out):
        b, h, w, _ = out.shape
        k = 2 * self.radius + 1
        k2 = k * k

        vol = out[..., :k2].reshape(b, h, w, k, k)
        vol = DisplacementAwareProjection((self.radius, self.radius))(vol)
        return soft_argmax_flow(vol.reshape(b, h, w, k2), self.radius,
                                self.temperature)
