"""DICL correlation module with a 1x1-conv MatchingNet.

Behavioral equivalent of reference src/models/common/corr/dicl_1x1.py: same
lookup as the full DICL module but the cost net is three 1x1 conv blocks +
a biased 1x1 head — per-pixel cost, no spatial context.
"""

import flax.linen as nn

from ..blocks.dicl import ConvBlock, DisplacementAwareProjection
from .common import (
    SoftArgMaxFlowRegression,
    SoftArgMaxFlowRegressionWithDap,
    sample_window,
    stack_pair,
)

__all__ = ["CorrelationModule", "MatchingNet1x1", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class MatchingNet1x1(nn.Module):
    """Pointwise matching net (reference dicl_1x1.py:8-30): displacement
    axes ride the batch through 1x1 convs."""

    norm_type: str = "batch"
    scale: float = 1

    @nn.compact
    def __call__(self, mvol, train=False, frozen_bn=False):
        b, du, dv, h, w, c = mvol.shape
        c1 = int(self.scale * 96)
        c2 = int(self.scale * 128)
        c3 = int(self.scale * 64)

        x = mvol.reshape(b * du * dv, h, w, c)

        x = ConvBlock(c1, kernel_size=1, norm_type=self.norm_type)(x, train, frozen_bn)
        x = ConvBlock(c2, kernel_size=1, norm_type=self.norm_type)(x, train, frozen_bn)
        x = ConvBlock(c3, kernel_size=1, norm_type=self.norm_type)(x, train, frozen_bn)
        x = nn.Conv(1, (1, 1))(x)  # with bias, like the reference

        cost = x.reshape(b, du, dv, h, w)
        return cost.transpose(0, 3, 4, 1, 2)  # (B, H, W, du, dv)


class CorrelationModule(nn.Module):
    feature_dim: int
    radius: int
    dap_init: str = "identity"
    norm_type: str = "batch"
    mnet_scale: float = 1

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape

        window = sample_window(f2, coords, self.radius)
        mvol = stack_pair(f1, window)

        cost = MatchingNet1x1(norm_type=self.norm_type, scale=self.mnet_scale)(
            mvol, train, frozen_bn
        )

        if dap:
            cost = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(cost)

        return cost.reshape(b, h, w, self.output_dim)
