"""DICL correlation module with a 1x1-conv MatchingNet.

Behavioral equivalent of reference src/models/common/corr/dicl_1x1.py: same
lookup as the full DICL module but the cost net is three 1x1 conv blocks +
a biased 1x1 head — per-pixel cost, no spatial context.

Runs the unstacked ``(f1, window)`` matching form (the f1 half of the
first conv computes once instead of per displacement, and the stacked
(B, du, dv, H, W, 2C) volume never materializes); ``stack_pair`` remains
the parity reference for tests.
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..blocks.dicl import ConvBlock, DisplacementAwareProjection
from .common import (
    SoftArgMaxFlowRegression,
    SoftArgMaxFlowRegressionWithDap,
    record_matching_bytes,
    sample_window_fast,
)

__all__ = ["CorrelationModule", "MatchingNet1x1", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class MatchingNet1x1(nn.Module):
    """Pointwise matching net (reference dicl_1x1.py:8-30): displacement
    axes ride the batch through 1x1 convs.

    Input is the stacked ``(B, du, dv, H, W, 2C)`` volume or the unstacked
    pair ``(f1, window)`` — the first conv then splits along its input
    channels exactly like ``MatchingNet`` (parameters identical to the
    stacked form, f1-first channel order).
    """

    norm_type: str = "batch"
    scale: float = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, mvol, train=False, frozen_bn=False):
        dt = self.dtype
        c1 = int(self.scale * 96)
        c2 = int(self.scale * 128)
        c3 = int(self.scale * 64)

        if isinstance(mvol, tuple):
            f1, window = mvol
            b, du, dv, h, w, c = window.shape
            x = ConvBlock(c1, kernel_size=1, norm_type=self.norm_type,
                          dtype=dt)(
                (f1, window.reshape(b * du * dv, h, w, c)), train, frozen_bn)
        else:
            b, du, dv, h, w, c = mvol.shape
            x = mvol.reshape(b * du * dv, h, w, c)
            x = ConvBlock(c1, kernel_size=1, norm_type=self.norm_type,
                          dtype=dt)(x, train, frozen_bn)

        x = ConvBlock(c2, kernel_size=1, norm_type=self.norm_type, dtype=dt)(
            x, train, frozen_bn)
        x = ConvBlock(c3, kernel_size=1, norm_type=self.norm_type, dtype=dt)(
            x, train, frozen_bn)
        x = nn.Conv(1, (1, 1), dtype=dt)(x)  # with bias, like the reference

        # the cost volume is the readout surface (softargmax/DAP): f32
        cost = x.reshape(b, du, dv, h, w).astype(jnp.float32)
        return cost.transpose(0, 3, 4, 1, 2)  # (B, H, W, du, dv)


class CorrelationModule(nn.Module):
    feature_dim: int
    radius: int
    dap_init: str = "identity"
    norm_type: str = "batch"
    mnet_scale: float = 1
    dtype: Any = None

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape

        window = sample_window_fast(f2, coords, self.radius)
        if self.dtype is not None:
            f1 = f1.astype(self.dtype)
            window = window.astype(self.dtype)
        if not self.is_initializing():
            record_matching_bytes(f1, window)

        cost = MatchingNet1x1(norm_type=self.norm_type, scale=self.mnet_scale,
                              dtype=self.dtype)(
            (f1, window), train, frozen_bn
        )

        if dap:
            cost = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(cost)

        return cost.reshape(b, h, w, self.output_dim)
