"""Shared pieces of the correlation modules: window sampling + soft-argmax.

The reference samples the (2r+1)² displaced feature windows with
``F.grid_sample`` per module (src/models/common/corr/dicl.py:26-61 and
siblings); here one helper owns that lookup, built on the framework's
bilinear-sample op, with windows ordered by ``ops.corr.window_delta``
(axis 0 varies dx) so every cost volume in the framework shares one channel
layout.
"""

import flax.linen as nn
import jax.numpy as jnp

from ....ops.corr import window_delta
from ..blocks.dicl import DisplacementAwareProjection


def sample_window(f2, coords, radius):
    """Sample f2 at the (2r+1)² displaced positions around each coordinate.

    f2: (B, H2, W2, C) features; coords: (B, H, W, 2) pixel positions *into
    f2's grid* — the two resolutions may differ (multi-level lookups pass
    coarser feature maps with rescaled coordinates). Returns
    (B, du, dv, H, W, C) with zero padding outside — du varies dx.

    All (2r+1)² displacements are integer offsets from one center, so they
    share the center's bilinear fractions: instead of 4 corner gathers per
    displacement (4K² rows per position through ``sample_bilinear``), one
    (K+1)² integer patch is gathered per position and the displaced values
    come from two static-shift lerps over the patch — 3.2x fewer gather
    rows, the dominant cost of the DICL models' training step. Zero padding
    falls out of masking OOB patch entries (every sampled value is a convex
    combination of patch entries, exactly the grid_sample corner terms).
    """
    b, h, w = coords.shape[:3]
    h2, w2, c = f2.shape[-3:]
    k = 2 * radius + 1
    t = k + 1

    # patch base = top-left corner of the displacement window
    cx = coords[..., 0].reshape(b, -1) - radius      # (B, P)
    cy = coords[..., 1].reshape(b, -1) - radius
    x0f = jnp.floor(cx)
    y0f = jnp.floor(cy)
    fx = (cx - x0f)[:, None, None, :, None]          # (B, 1, 1, P, 1)
    fy = (cy - y0f)[:, None, None, :, None]

    # tap axes ordered (tx, ty) so the lerped output is (dx, dy)-major,
    # matching window_delta's du-varies-dx channel layout
    tx = jnp.arange(t, dtype=jnp.int32)[None, :, None, None]
    ty = jnp.arange(t, dtype=jnp.int32)[None, None, :, None]
    ix = x0f.astype(jnp.int32)[:, None, None, :] + tx   # (B, T, T, P)
    iy = y0f.astype(jnp.int32)[:, None, None, :] + ty
    inb = (ix >= 0) & (ix <= w2 - 1) & (iy >= 0) & (iy <= h2 - 1)
    idx = (jnp.clip(iy, 0, h2 - 1) * w2 + jnp.clip(ix, 0, w2 - 1))

    flat = f2.reshape(b, h2 * w2, c)
    patch = jnp.take_along_axis(flat, idx.reshape(b, -1)[..., None], axis=1)
    patch = patch.reshape(b, t, t, h * w, c) * inb[..., None]

    # separable lerp over the shared fractions (static shifts only)
    ylerp = (1.0 - fy) * patch[:, :, 0:k] + fy * patch[:, :, 1:t]
    win = (1.0 - fx) * ylerp[:, 0:k] + fx * ylerp[:, 1:t]
    return win.reshape(b, k, k, h, w, c)


def stack_pair(f1, f2_window):
    """Broadcast f1 against the sampled window and stack channels:
    (B, du, dv, H, W, 2C) matching volume (reference corr/dicl.py:50-55)."""
    b, du, dv, h, w, c = f2_window.shape
    f1 = jnp.broadcast_to(f1[:, None, None], (b, du, dv, h, w, c))
    return jnp.concatenate((f1, f2_window), axis=-1)


def soft_argmax_flow(cost, radius, temperature=1.0):
    """Softmax-weighted displacement readout: cost (B, H, W, (2r+1)²) →
    flow (B, H, W, 2)."""
    b, h, w, _ = cost.shape
    k = 2 * radius + 1

    score = nn.softmax(cost / temperature, axis=-1)
    delta = window_delta(radius, cost.dtype).reshape(k * k, 2)
    return jnp.einsum("bhwd,dc->bhwc", score, delta)


class SoftArgMaxFlowRegression(nn.Module):
    """Flow readout from a cost volume (reference corr/dicl.py:64-89)."""

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, cost):
        return soft_argmax_flow(cost, self.radius, self.temperature)


class SoftArgMaxFlowRegressionWithDap(nn.Module):
    """Flow readout with its own (trained) DAP applied first
    (reference corr/dicl.py:92-119)."""

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, cost):
        b, h, w, kk = cost.shape
        k = 2 * self.radius + 1

        vol = cost.reshape(b, h, w, k, k)
        vol = DisplacementAwareProjection((self.radius, self.radius))(vol)
        return soft_argmax_flow(vol.reshape(b, h, w, kk), self.radius,
                                self.temperature)
