"""Shared pieces of the correlation modules: window sampling + soft-argmax.

The reference samples the (2r+1)² displaced feature windows with
``F.grid_sample`` per module (src/models/common/corr/dicl.py:26-61 and
siblings); here one helper owns that lookup, built on the framework's
bilinear-sample op, with windows ordered by ``ops.corr.window_delta``
(axis 0 varies dx) so every cost volume in the framework shares one channel
layout.

The XLA sampler lives in ``ops.sample.sample_window`` (re-exported here
for the corr modules and parity tests); ``sample_window_fast`` dispatches
to the fused Pallas kernel on TPU unless the ``RMD_DICL_FAST=0`` escape
hatch forces the reference path.
"""


import flax.linen as nn
import jax.numpy as jnp

from ....ops.corr import window_delta
from ....ops.sample import sample_window  # noqa: F401  (re-export)
from ..blocks.dicl import DisplacementAwareProjection


def dicl_fast_enabled():
    """DICL fast-path switch, read at trace time: ``RMD_DICL_FAST=0``
    restores the reference XLA sampler + per-level matching loops."""
    from ....utils import env

    return env.get_bool("RMD_DICL_FAST")


def sample_window_fast(f2, coords, radius):
    """``sample_window`` through the fused Pallas kernel when enabled.

    Semantics and layout match ``sample_window`` exactly; the fused path
    treats ``coords`` as non-differentiable (every caller sits behind the
    RAFT iteration's stop_gradient on the lookup centers).
    """
    if not dicl_fast_enabled():
        return sample_window(f2, coords, radius)
    from ....ops.pallas import sample_window_fused

    return sample_window_fused(f2, coords, radius)


def record_matching_bytes(*arrays):
    """Trace-time accounting of the matching volumes fed to the cost nets.

    Called while the model traces (once per compile): the byte count lands
    in the next ``step`` event's counters as ``matching_volume_bytes``, so
    events.jsonl shows the window/volume footprint the matching path moves
    per step — and the drop when the unstacked/bf16 fast path is active.
    """
    from .... import telemetry

    n = sum(int(a.size) * a.dtype.itemsize for a in arrays)
    telemetry.get().add_count("matching_volume_bytes", n)
    return n


def stack_pair(f1, f2_window):
    """Broadcast f1 against the sampled window and stack channels:
    (B, du, dv, H, W, 2C) matching volume (reference corr/dicl.py:50-55)."""
    b, du, dv, h, w, c = f2_window.shape
    f1 = jnp.broadcast_to(f1[:, None, None], (b, du, dv, h, w, c))
    return jnp.concatenate((f1, f2_window), axis=-1)


def soft_argmax_flow(cost, radius, temperature=1.0):
    """Softmax-weighted displacement readout: cost (B, H, W, (2r+1)²) →
    flow (B, H, W, 2)."""
    b, h, w, _ = cost.shape
    k = 2 * radius + 1

    score = nn.softmax(cost / temperature, axis=-1)
    delta = window_delta(radius, cost.dtype).reshape(k * k, 2)
    return jnp.einsum("bhwd,dc->bhwc", score, delta)


class SoftArgMaxFlowRegression(nn.Module):
    """Flow readout from a cost volume (reference corr/dicl.py:64-89)."""

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, cost):
        return soft_argmax_flow(cost, self.radius, self.temperature)


class SoftArgMaxFlowRegressionWithDap(nn.Module):
    """Flow readout with its own (trained) DAP applied first
    (reference corr/dicl.py:92-119)."""

    radius: int
    temperature: float = 1.0

    @nn.compact
    def __call__(self, cost):
        b, h, w, kk = cost.shape
        k = 2 * self.radius + 1

        vol = cost.reshape(b, h, w, k, k)
        vol = DisplacementAwareProjection((self.radius, self.radius))(vol)
        return soft_argmax_flow(vol.reshape(b, h, w, kk), self.radius,
                                self.temperature)
