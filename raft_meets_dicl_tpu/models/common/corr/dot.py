"""Dot-product correlation module (single-level windowed, à la RAFT).

Behavioral equivalent of reference src/models/common/corr/dot.py:8-66 in
NHWC: instead of a learned MatchingNet cost, the displaced-window cost is
the normalized dot product of the feature vectors — computed by the
framework's on-the-fly windowed-correlation op (no materialized volume),
then passed through the DAP.
"""

import flax.linen as nn

from ....ops.corr import windowed_correlation
from ..blocks.dicl import DisplacementAwareProjection
from .common import (
    SoftArgMaxFlowRegression,
    SoftArgMaxFlowRegressionWithDap,
)

__all__ = ["CorrelationModule", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class CorrelationModule(nn.Module):
    radius: int
    dap_init: str = "identity"

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape
        k = 2 * self.radius + 1

        # dot(f1[p], f2[c + d]) / sqrt(C) over the window, channels (dx, dy)
        cost = windowed_correlation(f1, f2, coords, self.radius, scale=1.0)

        if dap:
            vol = cost.reshape(b, h, w, k, k)
            vol = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(vol)
            cost = vol.reshape(b, h, w, k * k)

        return cost
