"""DICL correlation module: MatchingNet cost over displaced feature pairs.

Behavioral equivalent of reference src/models/common/corr/dicl.py:8-61 in
NHWC: sample the second frame's features at the (2r+1)² displaced positions
around the current flow, stack with frame-1 features, run the MatchingNet
per displacement (displacements ride the batch axis through the convs), and
apply the displacement-aware projection.
"""

from typing import Any

import flax.linen as nn

from ..blocks.dicl import DisplacementAwareProjection, MatchingNet
from .common import (
    SoftArgMaxFlowRegression,
    SoftArgMaxFlowRegressionWithDap,
    record_matching_bytes,
    sample_window_fast,
)

__all__ = ["CorrelationModule", "SoftArgMaxFlowRegression",
           "SoftArgMaxFlowRegressionWithDap"]


class CorrelationModule(nn.Module):
    feature_dim: int
    radius: int
    dap_init: str = "identity"
    norm_type: str = "batch"
    mnet_scale: float = 1
    dtype: Any = None

    @property
    def output_dim(self):
        return (2 * self.radius + 1) ** 2

    @nn.compact
    def __call__(self, f1, f2, coords, dap=True, train=False, frozen_bn=False):
        b, h, w, _ = f1.shape

        window = sample_window_fast(f2, coords, self.radius)
        # unstacked pair: MatchingNet's first conv computes the f1 half
        # once and broadcasts it over the (2r+1)² displacements — the
        # (B, du, dv, H, W, 2C) stacked volume's f1 copies never exist
        # (channel order f1-first matches ``stack_pair``, so parameters
        # and checkpoints are unchanged)
        if self.dtype is not None:
            f1 = f1.astype(self.dtype)
            window = window.astype(self.dtype)
        if not self.is_initializing():
            record_matching_bytes(f1, window)

        cost = MatchingNet(norm_type=self.norm_type, scale=self.mnet_scale,
                           dtype=self.dtype)(
            (f1, window), train, frozen_bn
        )  # (B, H, W, du, dv) float32

        if dap:
            cost = DisplacementAwareProjection(
                (self.radius, self.radius), init=self.dap_init
            )(cost)

        return cost.reshape(b, h, w, self.output_dim)
