"""Correlation-module factory (reference src/models/common/corr/__init__.py:7-50).

``make_cmod`` builds the cost-volume module for the hybrid models; all
modules share the call signature ``(f1, f2, coords, dap=True, train=...,
frozen_bn=...) → (B, H, W, output_dim)`` in NHWC, with window channels
ordered by ``ops.corr.window_delta``.
"""

from . import common, dicl, dicl_1x1, dicl_emb, dot

_CMODS = {
    "dicl": dicl.CorrelationModule,
    "dicl-1x1": dicl_1x1.CorrelationModule,
    "dicl-emb": dicl_emb.CorrelationModule,
    "dot": dot.CorrelationModule,
}

_REGRESSIONS = {
    "dicl": (dicl.SoftArgMaxFlowRegression, dicl.SoftArgMaxFlowRegressionWithDap),
    "dicl-1x1": (dicl_1x1.SoftArgMaxFlowRegression,
                 dicl_1x1.SoftArgMaxFlowRegressionWithDap),
    "dicl-emb": (dicl_emb.SoftArgMaxFlowRegression,
                 dicl_emb.SoftArgMaxFlowRegressionWithDap),
    "dot": (dot.SoftArgMaxFlowRegression, dot.SoftArgMaxFlowRegressionWithDap),
}


def make_cmod(type, feature_dim, radius, dap_init="identity",
              norm_type="batch", **kwargs):
    if type == "dot":
        return dot.CorrelationModule(radius=radius, dap_init=dap_init, **kwargs)
    if type not in _CMODS:
        raise ValueError(f"unknown correlation module type '{type}'")

    return _CMODS[type](feature_dim=feature_dim, radius=radius,
                        dap_init=dap_init, norm_type=norm_type, **kwargs)


def make_flow_regression(cmod_type, type, radius, **kwargs):
    if cmod_type not in _REGRESSIONS:
        raise ValueError(
            f"unknown correlation module type '{cmod_type}' for flow regression"
        )

    softargmax, with_dap = _REGRESSIONS[cmod_type]
    if type == "softargmax":
        return softargmax(radius=radius, **kwargs)
    if type == "softargmax+dap":
        return with_dap(radius=radius, **kwargs)

    raise ValueError(
        f"unknown flow regression type '{type}' for correlation module "
        f"'{cmod_type}'"
    )


__all__ = ["common", "dicl", "dicl_1x1", "dicl_emb", "dot", "make_cmod",
           "make_flow_regression"]
