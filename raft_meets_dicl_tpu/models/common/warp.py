"""Backwards warping via flow, NHWC.

Reference: src/models/common/warp.py:5-33 (grid_sample with
align_corners=True + sampled validity mask). Built on the shared
torch-parity bilinear gather in ops.sample.
"""

import jax.numpy as jnp

from ...ops.sample import sample_bilinear
from .grid import coordinate_grid


def warp_backwards(img2, flow, eps=1e-5):
    """Warp ``img2`` back to frame 1 by sampling at ``grid + flow``.

    img2: (B, H, W, C); flow: (B, H, W, 2). Returns (est1, mask) where mask
    is True for pixels whose sample window lies fully inside the image.
    """
    b, h, w, c = img2.shape

    pos = coordinate_grid(b, h, w, dtype=flow.dtype) + flow
    x, y = pos[..., 0], pos[..., 1]

    est1 = sample_bilinear(img2, x, y)
    mask = sample_bilinear(jnp.ones_like(img2), x, y) > (1.0 - eps)

    return est1 * mask, mask
