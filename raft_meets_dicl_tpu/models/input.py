"""Model input pipeline: range scaling, modulo padding, batching, loading.

Reference behavior (src/models/input.py) with a jax-native adapter: batches
stay NHWC numpy float32 on the host (TPU-native layout — no NCHW transpose
anywhere), validation marks bad batches via ``meta.valid`` instead of
raising, and the loader is a thread-pooled iterator (cv2/numpy release the
GIL) rather than a torch DataLoader with worker processes.
"""

import concurrent.futures
import copy
import os
from dataclasses import replace

import numpy as np

from .. import utils
from ..data.collection import Metadata, SampleArgs, SampleId

# Technical flow-magnitude limit (not an optimization knob): non-finite flow
# values are clamped here so error magnitudes stay computable before masking.
FLOW_INF = 1e10


class Padding:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(f"invalid padding type '{cfg['type']}', expected '{cls.type}'")

    def get_config(self):
        raise NotImplementedError

    def apply(self, img1, img2, flow, valid, meta):
        raise NotImplementedError

    def __call__(self, img1, img2, flow, valid, meta):
        return self.apply(img1, img2, flow, valid, meta)

    def raw_variant(self, clip, range):
        """Variant for un-normalized (wire-format) pipelines.

        Constant padding values are defined in *normalized* space
        ("zeros" pads with normalized 0); when normalization moves into
        the jitted step, the host pads raw values, so constants must be
        mapped through the inverse normalization. Non-constant modes
        (edge/reflect/...) are value-independent and pass through.
        """
        return self


class ModuloPadding(Padding):
    """Pad images to a multiple of ``size`` with configurable alignment.

    Flow/valid are always zero-padded (padded pixels are invalid);
    ``meta.original_extents`` shifts so outputs can be cropped back.
    ``torch.replicate``/``torch.reflect``/``torch.circular`` mode aliases
    from reference configs map onto the equivalent numpy modes.
    """

    type = "modulo"

    _NUMPY_MODES = (
        "edge", "maximum", "mean", "median", "minimum", "reflect",
        "symmetric", "wrap",
    )
    _ALIASES = {
        "zeros": ("constant", {"constant_values": 0.0}),
        "ones": ("constant", {"constant_values": 1.0}),
        "torch.replicate": ("edge", {}),
        "torch.reflect": ("reflect", {}),
        "torch.circular": ("wrap", {}),
    }

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        size = [int(x) for x in cfg["size"]]
        if len(size) != 2:
            raise ValueError("expected list/tuple of 2 integers for attribute 'size'")

        return cls(
            cfg["mode"],
            size,
            align_hz=cfg.get("align-horizontal", "left"),
            align_vt=cfg.get("align-vertical", "top"),
        )

    def __init__(self, mode, size, align_hz="left", align_vt="top"):
        super().__init__()

        if mode not in self._NUMPY_MODES and mode not in self._ALIASES:
            raise ValueError(f"invalid padding mode: {mode}")
        if align_hz not in ("left", "center", "right"):
            raise ValueError(f"invalid horizontal alignment for padding: {align_hz}")
        if align_vt not in ("bottom", "center", "top"):
            raise ValueError(f"invalid vertical alignment for padding: {align_vt}")

        self.mode = mode
        self.size = size
        self.align_hz = align_hz
        self.align_vt = align_vt

    def get_config(self):
        return {
            "type": self.type,
            "mode": self.mode,
            "size": self.size,
            "align-horizontal": self.align_hz,
            "align-vertical": self.align_vt,
        }

    def _split(self, total, align_lo_name, align):
        if align == align_lo_name:
            return 0, total
        if align == "center":
            return total // 2, total - total // 2
        return total, 0

    def raw_variant(self, clip, range):
        mode, args = self._ALIASES.get(self.mode, (self.mode, {}))
        if "constant_values" not in args:
            return self
        rmin, rmax = range
        lo, hi = clip
        c = (args["constant_values"] - rmin) / (rmax - rmin)
        out = copy.copy(self)
        # raw-space constant, clipped into the clip interval so the
        # device-side clip+scale maps it back to the normalized constant
        out._raw_constant = float(min(max(c, lo), hi))
        return out

    def apply(self, img1, img2, flow, valid, meta):
        mode, args = self._ALIASES.get(self.mode, (self.mode, {}))
        raw = getattr(self, "_raw_constant", None)
        if raw is not None and "constant_values" in args:
            args = dict(args, constant_values=raw)

        _, h, w, _ = img1.shape
        new_h = -(-h // self.size[1]) * self.size[1]
        new_w = -(-w // self.size[0]) * self.size[0]
        if (new_h, new_w) == (h, w):
            # already aligned: np.pad with zero widths still copies every
            # array — measured ~10 ms/sample of pure memcpy in the loader
            return img1, img2, flow, valid, meta

        ph1, ph2 = self._split(new_h - h, "top", self.align_vt)
        pw1, pw2 = self._split(new_w - w, "left", self.align_hz)

        pad4 = ((0, 0), (ph1, ph2), (pw1, pw2), (0, 0))
        pad3 = ((0, 0), (ph1, ph2), (pw1, pw2))

        img1 = np.pad(img1, pad4, mode=mode, **args)
        img2 = np.pad(img2, pad4, mode=mode, **args)

        if flow is not None:
            flow = np.pad(flow, pad4, mode="constant", constant_values=0)
            valid = np.pad(valid, pad3, mode="constant", constant_values=False)

        # new Metadata objects — sources may hand out the same instances on
        # every access (e.g. wrap_single), so in-place shifts would accumulate
        meta = [
            replace(
                m,
                original_extents=(
                    (m.original_extents[0][0] + ph1, m.original_extents[0][1] + ph1),
                    (m.original_extents[1][0] + pw1, m.original_extents[1][1] + pw1),
                ),
            )
            for m in meta
        ]

        return img1, img2, flow, valid, meta


_PADDINGS = {ModuloPadding.type: ModuloPadding}


def _build_padding(cfg):
    if cfg is None:
        return None
    return _PADDINGS[cfg["type"]].from_config(cfg)


class InputSpec:
    """Model input contract: clip range, value range, optional padding."""

    @classmethod
    def from_config(cls, cfg):
        cfg = cfg if cfg is not None else {}

        clip = [float(x) for x in cfg.get("clip", (0, 1))]
        if len(clip) != 2:
            raise ValueError("invalid value for 'clip', expected list/tuple of two floats")

        range_ = cfg.get("range", (-1, 1))
        if len(range_) != 2:
            raise ValueError("invalid value for 'range', expected list/tuple of two floats")

        return cls(clip, range_, _build_padding(cfg.get("padding")))

    def __init__(self, clip=(0.0, 1.0), range=(-1.0, 1.0), padding=None):
        self.clip = clip
        self.range = range
        self.padding = padding

    def get_config(self):
        return {
            "clip": self.clip,
            "range": self.range,
            "padding": self.padding.get_config() if self.padding is not None else None,
        }

    def apply(self, source, normalize=True):
        """Wrap ``source``; ``normalize=False`` defers the clip/range
        scaling to the device (wire-format pipelines)."""
        return Input(source, self.clip, self.range, self.padding,
                     normalize=normalize)

    def wrap_single(self, img1, img2, flow=None, valid=None, seq=0, dsid="custom"):
        """Wrap one unbatched image pair as a one-sample input source."""
        img1 = img1[None]
        img2 = img2[None]
        if flow is not None:
            flow = flow[None]
            valid = valid[None]

        meta = [
            Metadata(
                valid=True,
                dataset_id=dsid,
                sample_id=SampleId(
                    format="{dsid}/{seq}/{id}",
                    img1=SampleArgs([], {"dsid": dsid, "seq": seq, "id": 1}),
                    img2=SampleArgs([], {"dsid": dsid, "seq": seq, "id": 2}),
                ),
                original_extents=((0, img1.shape[1]), (0, img1.shape[2])),
            )
        ]

        return self.apply([(img1, img2, flow, valid, meta)])


class Input:
    """Applies clip + range scaling + padding over a Collection.

    With ``normalize=False`` the clip/range scaling is skipped — the
    wire-format path applies it inside the jitted step instead
    (``models.wire.WireFormat.decode``) — and constant padding values
    are translated into raw space so device-side normalization maps the
    padding back onto the configured normalized constant.
    """

    def __init__(self, source, clip=(0.0, 1.0), range=(-1.0, 1.0),
                 padding=None, normalize=True):
        self.source = source
        self.clip = clip
        self.range = range
        self.normalize = normalize
        self.padding = padding
        if padding is not None and not normalize:
            self.padding = padding.raw_variant(clip, range)

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.source[index]

        if self.normalize:
            lo, hi = self.clip
            rmin, rmax = self.range

            img1 = (rmax - rmin) * np.clip(img1, lo, hi) + rmin
            img2 = (rmax - rmin) * np.clip(img2, lo, hi) + rmin

        if self.padding is not None:
            img1, img2, flow, valid, meta = self.padding(img1, img2, flow, valid, meta)

        return img1, img2, flow, valid, meta

    def __len__(self):
        return len(self.source)

    def jax(self, flow=True, wire=None):
        return JaxAdapter(self, flow, wire=wire)

    # alias so call sites written against the reference's `.torch()` read
    # naturally during porting
    def adapter(self, flow=True):
        return JaxAdapter(self, flow)


class JaxAdapter:
    """Validates batches and normalizes them to NHWC float32 numpy.

    Device placement happens later (in the train/eval step or loader
    prefetch), so this stays a pure host-side transform. Non-finite images
    or flow, or empty valid masks, mark the whole sample batch invalid via
    ``meta.valid`` — the trainer skips those batches with a warning, exactly
    like the reference (src/models/input.py:252-299).
    """

    def __init__(self, source, flow=True, validate=True, wire=None):
        self.source = source
        self.flow = flow
        self.validate = validate
        self.wire = wire
        self.log = utils.logging.Logger("data:jax-adapter")

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.source[index]

        if self.validate:
            self._validate_images(img1, img2, meta)

        if self.wire is not None:
            # wire compression of the images happens here, inside the
            # loader workers: the compact form is what crosses thread /
            # process / device boundaries. Flow and valid stay exact for
            # host consumers (metrics, inspector); their wire compression
            # is applied at device-put time (WireFormat.encode_batch).
            img1 = self.wire.encode_image(img1)
            img2 = self.wire.encode_image(img2)
        else:
            img1 = np.ascontiguousarray(img1, dtype=np.float32)
            img2 = np.ascontiguousarray(img2, dtype=np.float32)

        if not self.flow:
            return img1, img2, None, None, meta

        assert flow is not None and valid is not None

        if self.validate:
            self._validate_flow(flow, valid, meta)

        flow = np.nan_to_num(flow, nan=0.0, posinf=FLOW_INF, neginf=-FLOW_INF)
        flow = np.clip(flow, -FLOW_INF, FLOW_INF)

        flow = np.ascontiguousarray(flow, dtype=np.float32)
        valid = np.ascontiguousarray(valid, dtype=bool)

        return img1, img2, flow, valid, meta

    def _mark_invalid(self, meta, which, bad_mask):
        for i, bad in enumerate(bad_mask):
            if bad:
                self.log.warn(f"{which}: {meta[i].sample_id}")
        for m in meta:
            m.valid = False

    def _validate_images(self, img1, img2, meta):
        bad1 = ~np.all(np.isfinite(img1), axis=(1, 2, 3))
        if bad1.any():
            self._mark_invalid(meta, "non-finite values in img1 detected", bad1)

        bad2 = ~np.all(np.isfinite(img2), axis=(1, 2, 3))
        if bad2.any():
            self._mark_invalid(meta, "non-finite values in img2 detected", bad2)

    def _validate_flow(self, flow, valid, meta):
        no_valid = ~np.any(valid, axis=(1, 2))
        if no_valid.any():
            self._mark_invalid(meta, "sample contains no valid flow pixels", no_valid)

        nonfinite = np.array(
            [not np.all(np.isfinite(flow[b][valid[b]])) for b in range(flow.shape[0])]
        )
        if nonfinite.any():
            self._mark_invalid(meta, "non-finite values in flow detected", nonfinite)

    def __len__(self):
        return len(self.source)

    def loader(self, batch_size=1, shuffle=False, num_workers=4, drop_last=False,
               seed=None, shard=None, procs=None):
        # no **kwargs catch-all: unknown loader arguments (typos in env
        # configs) must fail loudly instead of being silently dropped
        return Loader(self, batch_size, shuffle, num_workers, drop_last, seed,
                      shard, procs)


def collate(samples, shuffle=False, rng=None):
    """Concatenate pre-batched samples into one global batch.

    Sources may return more than one sample each (fw/bw pairing); the global
    batch is the concatenation, optionally shuffled within the batch so
    paired samples don't always sit next to each other.
    """
    img1 = np.concatenate([s[0] for s in samples], axis=0)
    img2 = np.concatenate([s[1] for s in samples], axis=0)

    if samples[0][2] is not None:
        flow = np.concatenate([s[2] for s in samples], axis=0)
        valid = np.concatenate([s[3] for s in samples], axis=0)
    else:
        flow, valid = None, None

    meta = [m for s in samples for m in s[4]]

    if shuffle and img1.shape[0] > 1:
        rng = rng if rng is not None else np.random
        perm = rng.permutation(img1.shape[0])
        img1, img2 = img1[perm], img2[perm]
        if flow is not None:
            flow, valid = flow[perm], valid[perm]
        meta = [meta[i] for i in perm]

    return img1, img2, flow, valid, meta


class Loader:
    """Batching iterator over an adapter: threads or decode processes.

    Epoch order reshuffles on every ``__iter__`` when ``shuffle`` is set;
    within-batch shuffle mixes samples from pre-batched sources. The
    default transport is a thread pool (cv2/numpy release the GIL for the
    heavy work); ``procs > 0`` switches to a decode-process pool with
    shared-memory array transport (models.mpdecode) for pipelines whose
    pure-Python decode path is the bottleneck. ``procs=None`` reads
    ``RMD_LOADER_PROCS`` (0 or unset = thread pool).

    Shuffling uses an own Generator. Without an explicit ``seed`` it is
    derived from the global numpy RNG so run-level seeding
    (utils.seeds) still makes data order reproducible.

    ``shard=(index, count)`` restricts the loader to every count-th
    sample of the (shared-seed) epoch order — the per-process slice in
    multi-host training. All shards see the same number of batches
    (processes must step in lockstep), so ``batch_size`` here is the
    per-process size.
    """

    def __init__(self, source, batch_size=1, shuffle=False, num_workers=4,
                 drop_last=False, seed=None, shard=None, procs=None):
        self.source = source
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.shard = shard
        if procs is None:
            procs = int(os.environ.get("RMD_LOADER_PROCS", "0"))
        self.procs = max(0, int(procs))
        if seed is None:
            seed = int(np.random.randint(0, 2**31 - 1))
        self.rng = np.random.default_rng(seed)

    def _shard_len(self):
        n = len(self.source)
        if self.shard is None:
            return n
        index, count = self.shard
        # every shard gets the same length: floor, so trailing samples
        # that not all shards have are dropped
        return n // count

    def __len__(self):
        n = self._shard_len()
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _batches(self):
        order = self.rng.permutation(len(self.source)) if self.shuffle \
            else np.arange(len(self.source))

        if self.shard is not None:
            index, count = self.shard
            order = order[index::count][: self._shard_len()]

        for start in range(0, len(order), self.batch_size):
            chunk = order[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield chunk

    def __iter__(self):
        if self.procs > 0:
            yield from self._iter_procs()
            return

        if self.num_workers <= 0:
            for chunk in self._batches():
                samples = [self.source[i] for i in chunk]
                yield collate(samples, self.shuffle, self.rng)
            return

        with concurrent.futures.ThreadPoolExecutor(self.num_workers) as pool:
            # pipeline: submit the next batch while the consumer works
            pending = []
            batches = self._batches()

            def submit_next():
                chunk = next(batches, None)
                if chunk is not None:
                    pending.append([pool.submit(self.source.__getitem__, i) for i in chunk])

            submit_next()
            submit_next()
            while pending:
                futures = pending.pop(0)
                samples = [f.result() for f in futures]
                submit_next()
                yield collate(samples, self.shuffle, self.rng)

    def _iter_procs(self):
        """Decode-process path: same two-batch pipelining as the thread
        pool, with samples crossing back through shared memory. Segments
        are released right after collate copies out of them."""
        from . import mpdecode

        pool = mpdecode.DecodePool(self.source, self.procs)
        try:
            pending = []
            batches = self._batches()

            def submit_next():
                chunk = next(batches, None)
                if chunk is not None:
                    pending.append([pool.submit(i) for i in chunk])

            submit_next()
            submit_next()
            while pending:
                seqs = pending.pop(0)
                samples, segments = [], []
                for seq in seqs:
                    sample, shm = pool.result(seq)
                    samples.append(sample)
                    segments.append(shm)
                submit_next()
                batch = collate(samples, self.shuffle, self.rng)
                for shm in segments:
                    shm.close()
                    shm.unlink()
                yield batch
        finally:
            pool.shutdown()
