"""Model input pipeline: range scaling, modulo padding, batching, loading.

Reference behavior (src/models/input.py) with a jax-native adapter: batches
stay NHWC numpy float32 on the host (TPU-native layout — no NCHW transpose
anywhere), validation marks bad batches via ``meta.valid`` instead of
raising, and the loader is a thread-pooled iterator (cv2/numpy release the
GIL) rather than a torch DataLoader with worker processes.
"""

import concurrent.futures
import copy
import threading
from dataclasses import replace

import numpy as np

from .. import utils
from ..data.collection import Metadata, SampleArgs, SampleId

# Technical flow-magnitude limit (not an optimization knob): non-finite flow
# values are clamped here so error magnitudes stay computable before masking.
FLOW_INF = 1e10


# numpy pad modes shared by every padding flavor; the aliases map the
# reference configs' torch-style names onto the equivalent numpy modes
_NUMPY_PAD_MODES = (
    "edge", "maximum", "mean", "median", "minimum", "reflect",
    "symmetric", "wrap",
)
_PAD_MODE_ALIASES = {
    "zeros": ("constant", {"constant_values": 0.0}),
    "ones": ("constant", {"constant_values": 1.0}),
    "torch.replicate": ("edge", {}),
    "torch.reflect": ("reflect", {}),
    "torch.circular": ("wrap", {}),
}


def _raw_pad_constant(value, clip, range):
    """Map a *normalized-space* constant padding value into raw space.

    Wire-format pipelines pad un-normalized values on the host; the
    device-side clip+scale must map the padding back onto the configured
    normalized constant, so the raw constant is the inverse normalization
    (clamped into the clip interval, which the normalization saturates
    anyway)."""
    rmin, rmax = range
    lo, hi = clip
    c = (value - rmin) / (rmax - rmin)
    return float(min(max(c, lo), hi))


def _pad_arrays(img1, img2, flow, valid, meta, pad_h, pad_w, mode, args):
    """Pad one NHWC sample batch by ``pad_h=(top, bottom)`` /
    ``pad_w=(left, right)``: images with ``mode``, flow/valid always
    zero-padded (padded pixels are invalid), metadata extents shifted."""
    ph1, ph2 = pad_h
    pw1, pw2 = pad_w

    pad4 = ((0, 0), (ph1, ph2), (pw1, pw2), (0, 0))
    pad3 = ((0, 0), (ph1, ph2), (pw1, pw2))

    img1 = np.pad(img1, pad4, mode=mode, **args)
    img2 = np.pad(img2, pad4, mode=mode, **args)

    if flow is not None:
        flow = np.pad(flow, pad4, mode="constant", constant_values=0)
        valid = np.pad(valid, pad3, mode="constant", constant_values=False)

    # new Metadata objects — sources may hand out the same instances on
    # every access (e.g. wrap_single), so in-place shifts would accumulate
    meta = [
        replace(
            m,
            original_extents=(
                (m.original_extents[0][0] + ph1, m.original_extents[0][1] + ph1),
                (m.original_extents[1][0] + pw1, m.original_extents[1][1] + pw1),
            ),
        )
        for m in meta
    ]

    return img1, img2, flow, valid, meta


class Padding:
    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(f"invalid padding type '{cfg['type']}', expected '{cls.type}'")

    def get_config(self):
        raise NotImplementedError

    def apply(self, img1, img2, flow, valid, meta):
        raise NotImplementedError

    def __call__(self, img1, img2, flow, valid, meta):
        return self.apply(img1, img2, flow, valid, meta)

    def raw_variant(self, clip, range):
        """Variant for un-normalized (wire-format) pipelines.

        Constant padding values are defined in *normalized* space
        ("zeros" pads with normalized 0); when normalization moves into
        the jitted step, the host pads raw values, so constants must be
        mapped through the inverse normalization. Non-constant modes
        (edge/reflect/...) are value-independent and pass through.
        """
        return self


class ModuloPadding(Padding):
    """Pad images to a multiple of ``size`` with configurable alignment.

    Flow/valid are always zero-padded (padded pixels are invalid);
    ``meta.original_extents`` shifts so outputs can be cropped back.
    ``torch.replicate``/``torch.reflect``/``torch.circular`` mode aliases
    from reference configs map onto the equivalent numpy modes.
    """

    type = "modulo"

    _NUMPY_MODES = _NUMPY_PAD_MODES
    _ALIASES = _PAD_MODE_ALIASES

    @classmethod
    def from_config(cls, cfg):
        cls._typecheck(cfg)

        size = [int(x) for x in cfg["size"]]
        if len(size) != 2:
            raise ValueError("expected list/tuple of 2 integers for attribute 'size'")

        return cls(
            cfg["mode"],
            size,
            align_hz=cfg.get("align-horizontal", "left"),
            align_vt=cfg.get("align-vertical", "top"),
        )

    def __init__(self, mode, size, align_hz="left", align_vt="top"):
        super().__init__()

        if mode not in self._NUMPY_MODES and mode not in self._ALIASES:
            raise ValueError(f"invalid padding mode: {mode}")
        if align_hz not in ("left", "center", "right"):
            raise ValueError(f"invalid horizontal alignment for padding: {align_hz}")
        if align_vt not in ("bottom", "center", "top"):
            raise ValueError(f"invalid vertical alignment for padding: {align_vt}")

        self.mode = mode
        self.size = size
        self.align_hz = align_hz
        self.align_vt = align_vt

    def get_config(self):
        return {
            "type": self.type,
            "mode": self.mode,
            "size": self.size,
            "align-horizontal": self.align_hz,
            "align-vertical": self.align_vt,
        }

    def _split(self, total, align_lo_name, align):
        if align == align_lo_name:
            return 0, total
        if align == "center":
            return total // 2, total - total // 2
        return total, 0

    def raw_variant(self, clip, range):
        mode, args = self._ALIASES.get(self.mode, (self.mode, {}))
        if "constant_values" not in args:
            return self
        out = copy.copy(self)
        # raw-space constant, clipped into the clip interval so the
        # device-side clip+scale maps it back to the normalized constant
        out._raw_constant = _raw_pad_constant(
            args["constant_values"], clip, range)
        return out

    def apply(self, img1, img2, flow, valid, meta):
        mode, args = self._ALIASES.get(self.mode, (self.mode, {}))
        raw = getattr(self, "_raw_constant", None)
        if raw is not None and "constant_values" in args:
            args = dict(args, constant_values=raw)

        _, h, w, _ = img1.shape
        new_h = -(-h // self.size[1]) * self.size[1]
        new_w = -(-w // self.size[0]) * self.size[0]
        if (new_h, new_w) == (h, w):
            # already aligned: np.pad with zero widths still copies every
            # array — measured ~10 ms/sample of pure memcpy in the loader
            return img1, img2, flow, valid, meta

        pad_h = self._split(new_h - h, "top", self.align_vt)
        pad_w = self._split(new_w - w, "left", self.align_hz)

        return _pad_arrays(img1, img2, flow, valid, meta, pad_h, pad_w,
                           mode, args)


_PADDINGS = {ModuloPadding.type: ModuloPadding}


def _build_padding(cfg):
    if cfg is None:
        return None
    return _PADDINGS[cfg["type"]].from_config(cfg)


class ShapeBuckets:
    """Canonical evaluation shapes: quantize mixed per-sample resolutions
    up to a small fixed set so a whole benchmark sweep compiles at most
    ``len(sizes)`` programs instead of one per distinct padded shape.

    Each sample is padded (bottom/right, so ``meta.original_extents``
    stays put) from its modulo-padded size up to the smallest configured
    bucket that fits; the ``valid`` mask is extended with ``False`` over
    the padded pixels, so masked metrics (EPE, Fl-all, the masked losses)
    provably never see them. An empty ``sizes`` list is the pure
    *grouping* policy: no quantization pad, the loader still groups
    same-shape samples into full batches (``Loader(group_by_shape=True)``)
    so mixed-resolution sets stop degrading to batch 1.

    Assignment is deterministic: buckets are ordered by (area, height,
    width) and the first one that fits both dimensions wins; samples
    larger than every bucket keep their own shape (they batch among
    themselves and compile their own program, like before).
    """

    def __init__(self, sizes=(), mode="zeros"):
        if mode not in _NUMPY_PAD_MODES and mode not in _PAD_MODE_ALIASES:
            raise ValueError(f"invalid bucket padding mode: {mode}")

        parsed = []
        for hw in sizes:
            h, w = (int(x) for x in hw)
            if h <= 0 or w <= 0:
                raise ValueError(f"invalid bucket size {hw!r}")
            parsed.append((h, w))

        self.sizes = sorted(set(parsed), key=lambda s: (s[0] * s[1], s))
        self.mode = mode

    @classmethod
    def from_config(cls, cfg):
        """``None`` | spec string (see :meth:`parse`) | mapping with
        ``sizes`` (list of [H, W]) and optional ``mode``."""
        if cfg is None:
            return None
        if isinstance(cfg, str):
            return cls.parse(cfg)
        if isinstance(cfg, (list, tuple)):
            return cls(cfg)
        return cls(cfg.get("sizes", ()), cfg.get("mode", "zeros"))

    @classmethod
    def parse(cls, spec):
        """CLI/env spec: ``'group'`` (shape grouping only) or a
        comma-separated ``HxW`` list, e.g. ``'384x1280,448x1024'``."""
        spec = spec.strip()
        if not spec:
            return None
        if spec in ("group", "shape"):
            return cls(())
        sizes = []
        for part in spec.split(","):
            try:
                h, w = part.strip().lower().split("x")
                sizes.append((int(h), int(w)))
            except ValueError:
                raise ValueError(
                    f"invalid bucket spec '{part.strip()}' in '{spec}': "
                    "expected 'group' or a comma-separated HxW list "
                    "like '384x1280,448x1024'") from None
        return cls(sizes)

    def get_config(self):
        return {"sizes": [list(s) for s in self.sizes], "mode": self.mode}

    def describe(self):
        if not self.sizes:
            return "group-by-shape (no canonical sizes)"
        return ", ".join(f"{h}x{w}" for h, w in self.sizes)

    def assign(self, h, w):
        """Smallest-area bucket fitting an (h, w) sample, or None when no
        bucket fits (the sample keeps its own shape)."""
        for bh, bw in self.sizes:
            if bh >= h and bw >= w:
                return bh, bw
        return None

    def check_compatible(self, padding):
        """Every bucket must satisfy the model's modulo constraint, else
        the quantized shapes would be rejected by the network's pyramid —
        fail at config time with the offending bucket named."""
        if padding is None or not isinstance(padding, ModuloPadding):
            return
        mw, mh = padding.size  # config order: (w multiple, h multiple)
        for bh, bw in self.sizes:
            if bh % mh or bw % mw:
                raise ValueError(
                    f"bucket {bh}x{bw} is not a multiple of the input "
                    f"padding size {mh}x{mw} (h x w): the model would "
                    "reject the quantized shape")

    def raw_variant(self, clip, range):
        """Variant for un-normalized (wire-format) pipelines: constant
        padding values translate into raw space (see ModuloPadding)."""
        mode, args = _PAD_MODE_ALIASES.get(self.mode, (self.mode, {}))
        if "constant_values" not in args:
            return self
        out = ShapeBuckets(self.sizes, self.mode)
        out._raw_constant = _raw_pad_constant(
            args["constant_values"], clip, range)
        return out

    def pad_image(self, img, bucket):
        """Pad a single HWC (or NHWC) image up to ``bucket`` bottom/right.

        The serving admission path pads each request's images directly to
        their assigned bucket (``check_compatible`` guarantees buckets
        satisfy the model's modulo constraint, so no intermediate modulo
        pad is needed); on a ``raw_variant`` the constant translates into
        raw space exactly like the batch path.
        """
        h, w = img.shape[-3], img.shape[-2]
        bh, bw = bucket
        if (h, w) == (bh, bw):
            return img

        mode, args = _PAD_MODE_ALIASES.get(self.mode, (self.mode, {}))
        raw = getattr(self, "_raw_constant", None)
        if raw is not None and "constant_values" in args:
            args = dict(args, constant_values=raw)

        pad = [(0, 0)] * (img.ndim - 3) + [(0, bh - h), (0, bw - w), (0, 0)]
        return np.pad(img, pad, mode=mode, **args)

    def pad(self, img1, img2, flow, valid, meta):
        """Pad one sample batch up to its bucket (no-op when no bucket
        fits or the sample already sits on one)."""
        _, h, w, _ = img1.shape
        bucket = self.assign(h, w)
        if bucket is None or bucket == (h, w):
            return img1, img2, flow, valid, meta

        mode, args = _PAD_MODE_ALIASES.get(self.mode, (self.mode, {}))
        raw = getattr(self, "_raw_constant", None)
        if raw is not None and "constant_values" in args:
            args = dict(args, constant_values=raw)

        bh, bw = bucket
        return _pad_arrays(img1, img2, flow, valid, meta,
                           (0, bh - h), (0, bw - w), mode, args)

    def __call__(self, img1, img2, flow, valid, meta):
        return self.pad(img1, img2, flow, valid, meta)


class InputSpec:
    """Model input contract: clip range, value range, optional padding."""

    @classmethod
    def from_config(cls, cfg):
        cfg = cfg if cfg is not None else {}

        clip = [float(x) for x in cfg.get("clip", (0, 1))]
        if len(clip) != 2:
            raise ValueError("invalid value for 'clip', expected list/tuple of two floats")

        range_ = cfg.get("range", (-1, 1))
        if len(range_) != 2:
            raise ValueError("invalid value for 'range', expected list/tuple of two floats")

        return cls(clip, range_, _build_padding(cfg.get("padding")))

    def __init__(self, clip=(0.0, 1.0), range=(-1.0, 1.0), padding=None):
        self.clip = clip
        self.range = range
        self.padding = padding

    def get_config(self):
        return {
            "clip": self.clip,
            "range": self.range,
            "padding": self.padding.get_config() if self.padding is not None else None,
        }

    def apply(self, source, normalize=True, buckets=None):
        """Wrap ``source``; ``normalize=False`` defers the clip/range
        scaling to the device (wire-format pipelines). ``buckets`` (a
        ShapeBuckets) quantizes each sample's padded size up to a
        canonical bucket for recompile-free mixed-resolution batching."""
        return Input(source, self.clip, self.range, self.padding,
                     normalize=normalize, buckets=buckets)

    def wrap_single(self, img1, img2, flow=None, valid=None, seq=0, dsid="custom"):
        """Wrap one unbatched image pair as a one-sample input source."""
        img1 = img1[None]
        img2 = img2[None]
        if flow is not None:
            flow = flow[None]
            valid = valid[None]

        meta = [
            Metadata(
                valid=True,
                dataset_id=dsid,
                sample_id=SampleId(
                    format="{dsid}/{seq}/{id}",
                    img1=SampleArgs([], {"dsid": dsid, "seq": seq, "id": 1}),
                    img2=SampleArgs([], {"dsid": dsid, "seq": seq, "id": 2}),
                ),
                original_extents=((0, img1.shape[1]), (0, img1.shape[2])),
            )
        ]

        return self.apply([(img1, img2, flow, valid, meta)])


class Input:
    """Applies clip + range scaling + padding over a Collection.

    With ``normalize=False`` the clip/range scaling is skipped — the
    wire-format path applies it inside the jitted step instead
    (``models.wire.WireFormat.decode``) — and constant padding values
    are translated into raw space so device-side normalization maps the
    padding back onto the configured normalized constant.
    """

    def __init__(self, source, clip=(0.0, 1.0), range=(-1.0, 1.0),
                 padding=None, normalize=True, buckets=None):
        self.source = source
        self.clip = clip
        self.range = range
        self.normalize = normalize
        self.padding = padding
        if padding is not None and not normalize:
            self.padding = padding.raw_variant(clip, range)
        if buckets is not None:
            buckets.check_compatible(padding)
            if not normalize:
                buckets = buckets.raw_variant(clip, range)
        self.buckets = buckets

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.source[index]

        if self.normalize:
            lo, hi = self.clip
            rmin, rmax = self.range

            img1 = (rmax - rmin) * np.clip(img1, lo, hi) + rmin
            img2 = (rmax - rmin) * np.clip(img2, lo, hi) + rmin

        if self.padding is not None:
            img1, img2, flow, valid, meta = self.padding(img1, img2, flow, valid, meta)

        if self.buckets is not None:
            img1, img2, flow, valid, meta = self.buckets(img1, img2, flow, valid, meta)

        return img1, img2, flow, valid, meta

    def __len__(self):
        return len(self.source)

    def jax(self, flow=True, wire=None):
        return JaxAdapter(self, flow, wire=wire)

    # alias so call sites written against the reference's `.torch()` read
    # naturally during porting
    def adapter(self, flow=True):
        return JaxAdapter(self, flow)


class JaxAdapter:
    """Validates batches and normalizes them to NHWC float32 numpy.

    Device placement happens later (in the train/eval step or loader
    prefetch), so this stays a pure host-side transform. Non-finite images
    or flow, or empty valid masks, mark the whole sample batch invalid via
    ``meta.valid`` — the trainer skips those batches with a warning, exactly
    like the reference (src/models/input.py:252-299).
    """

    def __init__(self, source, flow=True, validate=True, wire=None):
        self.source = source
        self.flow = flow
        self.validate = validate
        self.wire = wire
        self.log = utils.logging.Logger("data:jax-adapter")

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.source[index]

        if self.validate:
            self._validate_images(img1, img2, meta)

        if self.wire is not None:
            # wire compression of the images happens here, inside the
            # loader workers: the compact form is what crosses thread /
            # process / device boundaries. Flow and valid stay exact for
            # host consumers (metrics, inspector); their wire compression
            # is applied at device-put time (WireFormat.encode_batch).
            img1 = self.wire.encode_image(img1)
            img2 = self.wire.encode_image(img2)
        else:
            img1 = np.ascontiguousarray(img1, dtype=np.float32)
            img2 = np.ascontiguousarray(img2, dtype=np.float32)

        if not self.flow:
            return img1, img2, None, None, meta

        assert flow is not None and valid is not None

        if self.validate:
            self._validate_flow(flow, valid, meta)

        flow = np.nan_to_num(flow, nan=0.0, posinf=FLOW_INF, neginf=-FLOW_INF)
        flow = np.clip(flow, -FLOW_INF, FLOW_INF)

        flow = np.ascontiguousarray(flow, dtype=np.float32)
        valid = np.ascontiguousarray(valid, dtype=bool)

        return img1, img2, flow, valid, meta

    def _mark_invalid(self, meta, which, bad_mask):
        for i, bad in enumerate(bad_mask):
            if bad:
                self.log.warn(f"{which}: {meta[i].sample_id}")
        for m in meta:
            m.valid = False

    def _validate_images(self, img1, img2, meta):
        bad1 = ~np.all(np.isfinite(img1), axis=(1, 2, 3))
        if bad1.any():
            self._mark_invalid(meta, "non-finite values in img1 detected", bad1)

        bad2 = ~np.all(np.isfinite(img2), axis=(1, 2, 3))
        if bad2.any():
            self._mark_invalid(meta, "non-finite values in img2 detected", bad2)

    def _validate_flow(self, flow, valid, meta):
        no_valid = ~np.any(valid, axis=(1, 2))
        if no_valid.any():
            self._mark_invalid(meta, "sample contains no valid flow pixels", no_valid)

        nonfinite = np.array(
            [not np.all(np.isfinite(flow[b][valid[b]])) for b in range(flow.shape[0])]
        )
        if nonfinite.any():
            self._mark_invalid(meta, "non-finite values in flow detected", nonfinite)

    def __len__(self):
        return len(self.source)

    def loader(self, batch_size=1, shuffle=False, num_workers=4, drop_last=False,
               seed=None, shard=None, procs=None, group_by_shape=False,
               retries=None, bad_sample_budget=None):
        # no **kwargs catch-all: unknown loader arguments (typos in env
        # configs) must fail loudly instead of being silently dropped
        return Loader(self, batch_size, shuffle, num_workers, drop_last, seed,
                      shard, procs, group_by_shape, retries,
                      bad_sample_budget)


def collate(samples, shuffle=False, rng=None):
    """Concatenate pre-batched samples into one global batch.

    Sources may return more than one sample each (fw/bw pairing); the global
    batch is the concatenation, optionally shuffled within the batch so
    paired samples don't always sit next to each other.
    """
    base = samples[0][0].shape[1:]
    for s in samples[1:]:
        if s[0].shape[1:] != base:
            def describe(smp, shape):
                meta = smp[4]
                ds = meta[0].dataset_id if meta and hasattr(
                    meta[0], "dataset_id") else "<unknown dataset>"
                return f"{shape[0]}x{shape[1]} (dataset '{ds}')"
            raise ValueError(
                "cannot batch samples of mixed shapes: "
                f"{describe(samples[0], base)} vs "
                f"{describe(s, s[0].shape[1:])} — use shape buckets "
                "(--buckets / RMD_EVAL_BUCKETS / loader "
                "group_by_shape=True) or batch size 1 for "
                "mixed-resolution datasets")

    img1 = np.concatenate([s[0] for s in samples], axis=0)
    img2 = np.concatenate([s[1] for s in samples], axis=0)

    if samples[0][2] is not None:
        flow = np.concatenate([s[2] for s in samples], axis=0)
        valid = np.concatenate([s[3] for s in samples], axis=0)
    else:
        flow, valid = None, None

    meta = [m for s in samples for m in s[4]]

    if shuffle and img1.shape[0] > 1:
        rng = rng if rng is not None else np.random
        perm = rng.permutation(img1.shape[0])
        img1, img2 = img1[perm], img2[perm]
        if flow is not None:
            flow, valid = flow[perm], valid[perm]
        meta = [meta[i] for i in perm]

    return img1, img2, flow, valid, meta


class _DecodeFailed(Exception):
    """Wrapper distinguishing per-sample decode errors (retryable) from
    pool-level failures (fatal) on the decode-process path."""


class Loader:
    """Batching iterator over an adapter: threads or decode processes.

    Epoch order reshuffles on every ``__iter__`` when ``shuffle`` is set;
    within-batch shuffle mixes samples from pre-batched sources. The
    default transport is a thread pool (cv2/numpy release the GIL for the
    heavy work); ``procs > 0`` switches to a decode-process pool with
    shared-memory array transport (models.mpdecode) for pipelines whose
    pure-Python decode path is the bottleneck. ``procs=None`` reads
    ``RMD_LOADER_PROCS`` (0 or unset = thread pool).

    Shuffling uses an own Generator. Without an explicit ``seed`` it is
    derived from the global numpy RNG so run-level seeding
    (utils.seeds) still makes data order reproducible.

    ``shard=(index, count)`` restricts the loader to every count-th
    sample of the (shared-seed) epoch order — the per-process slice in
    multi-host training. All shards see the same number of batches
    (processes must step in lockstep), so ``batch_size`` here is the
    per-process size.

    ``group_by_shape`` reorders the epoch into full same-shape batches:
    samples are fetched in epoch order but buffered per (H, W) shape key
    and a batch is emitted whenever one shape's buffer fills (partial
    buffers flush at epoch end, first-seen shape first). Within a batch
    the epoch order — and with it the per-sample ``meta`` order — is
    preserved. Combined with ShapeBuckets quantization this turns a
    mixed-resolution evaluation epoch into at most ``n_buckets`` distinct
    batch shapes instead of one tiny ragged batch per resolution.
    """

    def __init__(self, source, batch_size=1, shuffle=False, num_workers=4,
                 drop_last=False, seed=None, shard=None, procs=None,
                 group_by_shape=False, retries=None, bad_sample_budget=None):
        self.source = source
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.shard = shard
        self.group_by_shape = bool(group_by_shape)
        if procs is None:
            procs = utils.env.get_int("RMD_LOADER_PROCS")
        self.procs = max(0, int(procs))
        if seed is None:
            seed = int(np.random.randint(0, 2**31 - 1))
        self.rng = np.random.default_rng(seed)

        # self-healing fetch: a failing sample decode is retried
        # ``retries`` times, then a neighboring sample is substituted in
        # its place (batch shapes — and with them the compiled step
        # programs — stay stable). Every substitution burns one unit of
        # the bad-sample budget; exceeding it aborts the epoch: at that
        # point the data (or its storage) is broken, not flaky.
        if retries is None:
            retries = utils.env.get_int("RMD_LOADER_RETRIES")
        self.retries = max(0, int(retries))
        if bad_sample_budget is None:
            bad_sample_budget = utils.env.get_int("RMD_BAD_SAMPLE_BUDGET")
        self.bad_sample_budget = max(0, int(bad_sample_budget))
        self._bad_samples = 0
        self._bad_lock = threading.Lock()

    def _note_bad_sample(self, index, error):
        from .. import telemetry, utils

        if isinstance(error, _DecodeFailed):
            error = error.__cause__
        if self.bad_sample_budget <= 0:
            # budget 0 = healing off: the original error propagates as-is
            raise error
        with self._bad_lock:
            self._bad_samples += 1
            bad = self._bad_samples
        utils.logging.Logger("data:loader").warn(
            f"sample {index} failed to decode after {self.retries + 1} "
            f"attempt(s) ({type(error).__name__}: {error}); substituting a "
            f"neighbor ({bad}/{self.bad_sample_budget} bad-sample budget)")
        telemetry.get().emit("bad_sample", index=int(index),
                             error=f"{type(error).__name__}: {error}",
                             bad_samples=bad)
        if bad > self.bad_sample_budget:
            raise RuntimeError(
                f"bad-sample budget exceeded ({bad} > "
                f"{self.bad_sample_budget}): the input data is "
                "persistently failing to decode") from error

    def _fetch(self, index, fetch=None, retry_on=Exception):
        """``source[index]`` with bounded retry, then substitution.

        ``fetch`` overrides the raw per-index fetch (the decode-process
        path goes through the pool); only ``retry_on`` exceptions count
        as per-sample decode failures — anything else (pool breakage,
        timeouts) propagates immediately. Deterministic neighbor
        substitution keeps batch shapes (and compiled programs) stable;
        repeated samples are harmless to training, unlike a mid-run
        crash.
        """
        index = int(index)
        fetch = fetch if fetch is not None else self.source.__getitem__
        last = None
        for _ in range(self.retries + 1):
            try:
                return fetch(index)
            except retry_on as e:  # injected/IO decode failures
                last = e
        self._note_bad_sample(index, last)

        n = len(self.source)
        for k in range(1, min(n, 8)):
            sub = (index + k) % n
            try:
                return fetch(sub)
            except retry_on as e:
                self._note_bad_sample(sub, e)
        raise RuntimeError(
            f"sample {index} and every substitution candidate failed to "
            "decode") from last

    def _pool_result(self, pool, seq, index):
        """Decode-pool result with the same retry/substitute discipline.

        The first attempt consumes the already-pipelined result; retries
        and substitutions go through a blocking submit+result round trip
        (only the failing sample loses pipelining). Pool-level failures
        (worker respawn exhaustion, wedged-pipeline timeouts) are not
        per-sample problems and propagate unretried.
        """
        from .mpdecode import PoolBroken

        state = {"first": True}

        def once(i):
            s = seq if state.pop("first", False) and i == index \
                else pool.submit(i)
            try:
                return pool.result(s)
            except (TimeoutError, PoolBroken):
                raise
            except Exception as e:  # noqa: BLE001 - worker decode error
                raise _DecodeFailed(e) from e

        try:
            return self._fetch(index, fetch=once, retry_on=_DecodeFailed)
        except _DecodeFailed as e:  # pragma: no cover - unwrapped below
            raise e.__cause__

    def _shard_len(self):
        n = len(self.source)
        if self.shard is None:
            return n
        index, count = self.shard
        # every shard gets the same length: floor, so trailing samples
        # that not all shards have are dropped
        return n // count

    def __len__(self):
        n = self._shard_len()
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _order(self):
        order = self.rng.permutation(len(self.source)) if self.shuffle \
            else np.arange(len(self.source))

        if self.shard is not None:
            index, count = self.shard
            order = order[index::count][: self._shard_len()]
        return order

    def _batches(self):
        order = self._order()

        for start in range(0, len(order), self.batch_size):
            chunk = order[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield chunk

    def __iter__(self):
        if self.group_by_shape:
            yield from self._iter_grouped()
            return

        if self.procs > 0:
            yield from self._iter_procs()
            return

        if self.num_workers <= 0:
            for chunk in self._batches():
                samples = [self._fetch(i) for i in chunk]
                yield collate(samples, self.shuffle, self.rng)
            return

        with concurrent.futures.ThreadPoolExecutor(self.num_workers) as pool:
            # pipeline: submit the next batch while the consumer works
            pending = []
            batches = self._batches()

            def submit_next():
                chunk = next(batches, None)
                if chunk is not None:
                    pending.append([pool.submit(self._fetch, i) for i in chunk])

            submit_next()
            submit_next()
            while pending:
                futures = pending.pop(0)
                samples = [f.result() for f in futures]
                submit_next()
                yield collate(samples, self.shuffle, self.rng)

    def _iter_samples(self):
        """Single samples in epoch order, decode pipelined a window ahead
        (threads, decode processes, or synchronous per ``procs`` /
        ``num_workers`` — same transports as the batch path)."""
        order = self._order()

        if self.procs > 0:
            from . import mpdecode

            pool = mpdecode.DecodePool(self.source, self.procs)
            try:
                it = iter(order)
                pending = []

                def submit_next():
                    i = next(it, None)
                    if i is not None:
                        pending.append((pool.submit(int(i)), int(i)))

                for _ in range(max(2 * self.procs, 4)):
                    submit_next()
                while pending:
                    sample, shm = self._pool_result(pool, *pending.pop(0))
                    # copy out of shared memory immediately: grouped
                    # samples can sit in a bucket buffer for a while, and
                    # segments must not pile up until the batch flushes
                    img1, img2, flow, valid, meta = sample
                    sample = (np.copy(img1), np.copy(img2),
                              None if flow is None else np.copy(flow),
                              None if valid is None else np.copy(valid),
                              meta)
                    shm.close()
                    shm.unlink()
                    submit_next()
                    yield sample
            finally:
                pool.shutdown()
            return

        if self.num_workers <= 0:
            for i in order:
                yield self._fetch(i)
            return

        with concurrent.futures.ThreadPoolExecutor(self.num_workers) as pool:
            it = iter(order)
            pending = []

            def submit_next():
                i = next(it, None)
                if i is not None:
                    pending.append(pool.submit(self._fetch, int(i)))

            for _ in range(max(2 * self.num_workers, 2 * self.batch_size)):
                submit_next()
            while pending:
                sample = pending.pop(0).result()
                submit_next()
                yield sample

    def _iter_grouped(self):
        """Shape-grouping mode: buffer fetched samples per (H, W) key and
        emit a full batch whenever one shape's buffer fills; partial
        buffers flush at epoch end in first-seen order (dropped under
        ``drop_last``). Epoch order is preserved within each group, so
        per-sample ``meta`` order within a batch is stable."""
        groups = {}
        seen = []

        for sample in self._iter_samples():
            key = sample[0].shape[1:3]
            if key not in groups:
                groups[key] = []
                seen.append(key)
            buf = groups[key]
            buf.append(sample)
            if sum(s[0].shape[0] for s in buf) >= self.batch_size:
                groups[key] = []
                yield collate(buf, self.shuffle, self.rng)

        if not self.drop_last:
            for key in seen:
                if groups[key]:
                    yield collate(groups[key], self.shuffle, self.rng)

    def _iter_procs(self):
        """Decode-process path: same two-batch pipelining as the thread
        pool, with samples crossing back through shared memory. Segments
        are released right after collate copies out of them."""
        from . import mpdecode

        pool = mpdecode.DecodePool(self.source, self.procs)
        try:
            pending = []
            batches = self._batches()

            def submit_next():
                chunk = next(batches, None)
                if chunk is not None:
                    pending.append([(pool.submit(i), int(i)) for i in chunk])

            submit_next()
            submit_next()
            while pending:
                seqs = pending.pop(0)
                samples, segments = [], []
                for seq, index in seqs:
                    sample, shm = self._pool_result(pool, seq, index)
                    samples.append(sample)
                    segments.append(shm)
                submit_next()
                batch = collate(samples, self.shuffle, self.rng)
                for shm in segments:
                    shm.close()
                    shm.unlink()
                yield batch
        finally:
            pool.shutdown()
