"""Model framework: wrappers, registry, input pipeline, zoo."""

from . import common, config, input, model
from .config import ModelSpec, load, load_input, load_loss, load_model
from .input import InputSpec
from .model import Loss, Model, ModelAdapter, Result

__all__ = [
    "common", "config", "input", "model",
    "ModelSpec", "load", "load_input", "load_loss", "load_model",
    "InputSpec", "Loss", "Model", "ModelAdapter", "Result",
]
