"""Model framework core: Model/Loss wrappers, adapters, results.

TPU-native redesign of the reference framework classes
(src/models/model.py:5-82). The key difference from the torch original: a
model here is a *pure function* — a Flax linen module whose parameters live
in an explicit variables pytree — so the wrapper exposes ``init``/``apply``
instead of owning state. Per-stage behavior switches (forward arguments,
batchnorm freezing) are python-side static configuration that is threaded
into ``apply`` as static arguments; changing them across stages triggers an
XLA recompile, which is expected and cheap relative to a training stage.

The config-facing surface is identical to the reference: every Model/Loss is
built ``from_config`` and round-trips ``get_config``; per-stage ``model_args``
and ``loss_args`` merge over the config defaults at call time.
"""


class Result:
    """Wraps a model's raw forward output behind a uniform interface.

    ``output()`` is what the loss consumes (model-specific structure),
    ``final()`` is the finest full-resolution flow estimate,
    ``intermediate_flow()`` exposes per-level/iteration flows for inspection.
    """

    def output(self, batch_index=None):
        raise NotImplementedError

    def final(self):
        raise NotImplementedError

    def intermediate_flow(self):
        raise NotImplementedError


class ModelAdapter:
    """Decouples the trainer/evaluator from model-specific output shapes.

    Also relays stage/epoch lifecycle events to the model with config-bound
    default arguments merged in.
    """

    def __init__(self, model):
        self.model = model

    def wrap_result(self, result, original_shape) -> Result:
        raise NotImplementedError

    def on_stage(self, stage, **kwargs):
        self.model.on_stage(stage, **(self.model.on_stage_arguments | kwargs))

    def on_epoch(self, stage, epoch, **kwargs):
        self.model.on_epoch(stage, epoch, **(self.model.on_epoch_arguments | kwargs))


class Model:
    """Config-constructible wrapper around a Flax module.

    Holds the module definition, default forward arguments (merged with
    per-stage overrides at apply time), and lifecycle-event argument sets.
    Parameters are *not* stored here — they are created by ``init`` and
    passed to ``apply`` explicitly, so the same Model object can serve any
    number of parameter sets (e.g. across pmap replicas).
    """

    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(f"invalid model type '{cfg['type']}', expected '{cls.type}'")

    def __init__(self, module, arguments, on_epoch_arguments={}, on_stage_arguments={}):
        self.module = module
        self.arguments = dict(arguments)
        self.on_epoch_arguments = dict(on_epoch_arguments)
        self.on_stage_arguments = dict(on_stage_arguments)
        self.frozen_batchnorm = False

    def get_config(self):
        raise NotImplementedError

    def get_adapter(self) -> ModelAdapter:
        raise NotImplementedError

    def init(self, rng, img1, img2, **kwargs):
        """Create the variables pytree (params + batch_stats) for tracing shapes."""
        args = self.arguments | kwargs
        return self.module.init(rng, img1, img2, train=False, **args)

    def apply(self, variables, img1, img2, train=False, rngs=None, **kwargs):
        """Run the forward pass.

        In training mode (unless batchnorm is frozen for the stage) batch
        statistics are mutable and the updated collection is returned
        alongside the output: ``(output, updated_batch_stats)``. In eval
        mode just the output is returned.

        Framework convention: module ``__call__`` signatures take
        ``(img1, img2, train, frozen_bn, **model_args)`` — ``train`` drives
        stochastic layers (dropout), ``frozen_bn`` only switches batch norm
        to running statistics, matching the reference's selective
        ``freeze_batchnorm`` (src/models/common/norm.py:18-32).

        Ladder continuation protocol: every impl accepts ``flow_init`` and
        ``hidden_init`` (traced arrays seeding the recurrence carry at the
        coarse grid) and a static ``return_state`` switch. With
        ``return_state=True`` the raw output becomes ``(output, state)``
        where ``state`` is ``{"flow", "hidden", "delta"}`` — the carry to
        hand to the next rung program plus a per-sample convergence norm.
        The tuple passes through here untouched; rung programs
        (``evaluation.make_rung_fn``) unpack it themselves.
        """
        args = self.arguments | kwargs
        frozen = self.frozen_batchnorm

        if train and not frozen and "batch_stats" in variables:
            out, mutated = self.module.apply(
                variables, img1, img2, train=True, frozen_bn=False, rngs=rngs,
                mutable=["batch_stats"], **args,
            )
            return out, mutated["batch_stats"]

        out = self.module.apply(
            variables, img1, img2, train=train, frozen_bn=frozen, rngs=rngs, **args
        )
        if train:
            return out, variables.get("batch_stats", {})
        return out

    def on_stage(self, stage, **kwargs):
        """Default stage hook: support ``freeze_batchnorm`` like the reference
        (src/models/common/norm.py:18-32) via an apply-time switch."""
        self.frozen_batchnorm = bool(kwargs.get("freeze_batchnorm", False))

    def on_epoch(self, stage, epoch, **kwargs):
        pass

    def __call__(self, variables, img1, img2, train=False, rngs=None, **kwargs):
        return self.apply(variables, img1, img2, train=train, rngs=rngs, **kwargs)


class Loss:
    """Config-constructible loss with default-argument merging.

    ``compute`` is a pure jnp function of (result-output, target, valid) and
    must be traceable under jit; the ``model`` argument carries the wrapper
    for losses that regularize parameters.
    """

    type = None

    @classmethod
    def _typecheck(cls, cfg):
        if cfg["type"] != cls.type:
            raise ValueError(f"invalid loss type '{cfg['type']}', expected '{cls.type}'")

    def __init__(self, arguments):
        self.arguments = dict(arguments)

    def get_config(self):
        raise NotImplementedError

    def compute(self, model, result, target, valid, **kwargs):
        raise NotImplementedError

    def __call__(self, model, result, target, valid, **kwargs):
        return self.compute(model, result, target, valid, **(self.arguments | kwargs))
