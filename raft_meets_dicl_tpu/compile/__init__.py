"""Compiled-program registry + AOT export (ROADMAP item 5).

Public surface:

- :class:`ProgramKey` / :class:`Program` / :class:`ProgramRegistry`,
  ``registry()``, ``reset()``, ``register_step()`` — one constructor for
  every jitted train/eval step in the system (``registry`` module);
- ``enable_aot()`` / ``disable_aot()`` / ``aot_enabled()`` /
  ``programs_dir()`` — the serialized-executable store that lets a
  repeat boot of the same config start stepping with zero compiles
  (``aot`` module). CLI and bench entry points call ``enable_aot()``;
  ``RMD_AOT=0`` opts out, ``RMD_AOT_DIR`` relocates the store.
"""

from . import aot
from .aot import (
    aot_enabled, artifact_path, disable_aot, enable_aot, fetch, fingerprint,
    publish,
    programs_dir,
)
from .registry import (
    Program, ProgramKey, ProgramRegistry, flag_items, register_step,
    registry, reset, shape_signature, unstable,
)

__all__ = [
    "aot",
    "Program", "ProgramKey", "ProgramRegistry",
    "flag_items", "register_step", "registry", "reset",
    "shape_signature", "unstable",
    "aot_enabled", "artifact_path", "disable_aot", "enable_aot",
    "fetch", "publish",
    "fingerprint", "programs_dir",
]
