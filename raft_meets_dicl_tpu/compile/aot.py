"""AOT program artifacts: serialized compiled executables on disk.

The persistent XLA cache (utils.compcache) removes the *compile* cost of
a repeat boot but still pays tracing + cache lookup per program; this
layer removes the whole warmup. A compiled step is serialized via
``jax.experimental.serialize_executable`` into a content-addressed file
under the ``programs/`` directory — keyed by the ProgramKey digest plus
the concrete input shape signature — and a later boot (same config, same
topology) deserializes it directly: zero traces, zero backend compiles.
That is what makes fleet-style replicas cheap: compile once, ship the
artifact (ROADMAP item 1), and what makes a resumed run start stepping
immediately (item 3's warmup budget).

Everything here is best-effort: a missing, corrupt, or version-mismatched
artifact degrades to the normal JIT path (the registry records the
fallback in telemetry), never to an error.
"""

import hashlib
import io
import os
import pickle
import time
import zlib

from ..utils import env

_MAGIC = "RMDP1"
# bump to invalidate every existing artifact when the program contract
# changes (arg order, aux layout, ...)
_LAYOUT_VERSION = 1

_state = {"on": False, "dir": None}


def default_dir():
    """``programs/`` next to the persistent compile cache."""
    from ..utils import compcache

    base = compcache.effective_dir() or compcache.DEFAULT_DIR
    return os.path.join(base, "programs")


def enable_aot(path=None):
    """Turn the AOT program store on (CLI/bench boots call this, mirroring
    ``compcache.enable_persistent_cache``); ``RMD_AOT=0`` wins. Returns
    the effective programs directory, or None when disabled."""
    if not env.get_bool("RMD_AOT"):
        _state["on"] = False
        return None
    _state["on"] = True
    _state["dir"] = path or env.raw("RMD_AOT_DIR") or None
    return programs_dir()


def disable_aot():
    _state["on"] = False


def aot_enabled():
    return _state["on"]


def programs_dir():
    return _state["dir"] or default_dir()


_fingerprint = None


def fingerprint():
    """Version string an artifact must match to be loadable: jax/jaxlib,
    the artifact layout version, and the backend topology (a serialized
    executable references concrete devices)."""
    global _fingerprint
    if _fingerprint is None:
        import jax
        import jaxlib

        dev = jax.devices()[0]
        _fingerprint = (
            f"jax={jax.__version__} jaxlib={jaxlib.__version__} "
            f"layout={_LAYOUT_VERSION} "
            f"backend={dev.platform}:{getattr(dev, 'device_kind', '?')} "
            f"n={jax.device_count()}")
    return _fingerprint


def artifact_path(key, sig):
    digest = hashlib.sha256(
        (key.canonical() + "\0" + repr(sig)).encode()).hexdigest()
    return os.path.join(programs_dir(), f"{digest}.rmdp")


def tombstone(path):
    """Mark a (key, sig) as not AOT-loadable under the current
    fingerprint: some executables serialize but fail to load back (e.g.
    XLA-CPU fusions with unexported symbols). The marker suppresses
    save/fail churn on every later boot — the program just runs through
    the normal JIT path (+ persistent compile cache). A jax/backend
    upgrade changes the fingerprint and retries."""
    try:
        with open(path + ".noaot", "w") as fd:
            fd.write(fingerprint() + "\n")
    except OSError:
        pass


def tombstoned(path):
    try:
        with open(path + ".noaot") as fd:
            return fd.readline().strip() == fingerprint()
    except OSError:
        return False


def _validate_artifact(path):
    """Cheap record validation without deserializing the executable:
    magic, runtime-fingerprint match (same jax/backend/topology), CRC.
    Returns (ok, reason)."""
    try:
        with open(path, "rb") as fd:
            record = pickle.loads(fd.read())
    except Exception as e:  # noqa: BLE001 - any decode failure
        return False, f"unpickle: {type(e).__name__}"
    if not isinstance(record, dict) or record.get("magic") != _MAGIC:
        return False, "bad magic"
    if record.get("fingerprint") != fingerprint():
        return False, (f"fingerprint '{record.get('fingerprint')}' vs "
                       f"runtime '{fingerprint()}'")
    payload = record.get("payload")
    if payload is None or zlib.crc32(payload) != record.get("crc"):
        return False, "crc mismatch"
    return True, "ok"


def _copy_artifacts(src, dest, event):
    """Validated artifact transfer between program stores (the fleet
    distribution primitive): every ``*.rmdp`` whose record passes
    :func:`_validate_artifact` is copied atomically; invalid or
    version-mismatched artifacts are skipped (never raising), existing
    destination files are left alone (content-addressed names — same
    name means same program). Tombstones stay local: they record a
    host-specific load failure, not a property of the artifact.
    Returns ``{copied, present, invalid, artifacts}``.
    """
    import glob as _glob

    from .. import telemetry

    os.makedirs(dest, exist_ok=True)
    copied, present, invalid = [], 0, {}
    for path in sorted(_glob.glob(os.path.join(src, "*.rmdp"))):
        name = os.path.basename(path)
        target = os.path.join(dest, name)
        if os.path.exists(target):
            present += 1
            continue
        ok, reason = _validate_artifact(path)
        if not ok:
            invalid[name] = reason
            continue
        tmp = f"{target}.tmp.{os.getpid()}"
        with open(path, "rb") as sfd, open(tmp, "wb") as dfd:
            dfd.write(sfd.read())
        os.replace(tmp, target)
        copied.append(name)
    out = {"copied": len(copied), "present": present,
           "invalid": len(invalid), "artifacts": copied}
    telemetry.get().emit("aot", event=event, src=str(src), dest=str(dest),
                         **{k: out[k] for k in
                            ("copied", "present", "invalid")})
    return out


def publish(dest, src=None):
    """Publish the local program store into a shared fleet store: one
    ``serve --prebuild`` host exports its compiled executables, every
    replica fetches them. Only artifacts matching the *current* runtime
    fingerprint travel — that is the same-topology portability check."""
    return _copy_artifacts(src or programs_dir(), dest, "publish")


def fetch(src, dest=None):
    """Pull published artifacts into the local program store (replica
    boot): validated against the local runtime fingerprint, so an
    artifact built on a different jax/backend/topology is skipped and
    that program simply JIT-compiles."""
    return _copy_artifacts(src, dest or programs_dir(), "fetch")


def save(path, key, sig, compiled):
    """Serialize ``compiled`` (a jax.stages.Compiled) to ``path``
    atomically. Returns (nbytes, seconds); raises on failure — callers
    treat a failed save as cosmetic."""
    from jax.experimental import serialize_executable

    t0 = time.perf_counter()
    payload, in_tree, out_tree = serialize_executable.serialize(compiled)
    record = {
        "magic": _MAGIC,
        "fingerprint": fingerprint(),
        "key": key.canonical(),
        "sig": repr(sig),
        "crc": zlib.crc32(payload),
        "payload": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
    }
    buf = io.BytesIO()
    pickle.dump(record, buf, protocol=pickle.HIGHEST_PROTOCOL)
    data = buf.getvalue()

    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fd:
        fd.write(data)
    os.replace(tmp, path)
    return len(data), time.perf_counter() - t0


def load(path, key, sig):
    """Deserialize an artifact back into a callable Compiled.

    Returns ``(compiled, status, info)`` where status is one of
    ``hit`` (compiled is live), ``missing``, ``corrupt``, ``version``
    (fingerprint mismatch — stale jax/backend), or ``error``; ``info``
    carries {bytes, seconds} on a hit and a reason string otherwise.
    Never raises.
    """
    t0 = time.perf_counter()
    try:
        try:
            with open(path, "rb") as fd:
                data = fd.read()
        except FileNotFoundError:
            return None, "missing", "no artifact"

        try:
            record = pickle.loads(data)
        except Exception as e:  # noqa: BLE001 - any decode failure
            return None, "corrupt", f"unpickle: {type(e).__name__}"

        if not isinstance(record, dict) or record.get("magic") != _MAGIC:
            return None, "corrupt", "bad magic"
        if record.get("fingerprint") != fingerprint():
            return None, "version", (
                f"artifact '{record.get('fingerprint')}' vs "
                f"runtime '{fingerprint()}'")
        if record.get("key") != key.canonical() or record.get("sig") != repr(sig):
            # hash collision or a hand-moved file: treat as absent
            return None, "corrupt", "key mismatch"
        payload = record["payload"]
        if zlib.crc32(payload) != record.get("crc"):
            return None, "corrupt", "crc mismatch"

        from jax.experimental import serialize_executable

        compiled = serialize_executable.deserialize_and_load(
            payload, record["in_tree"], record["out_tree"])
        return compiled, "hit", {
            "bytes": len(data),
            "seconds": time.perf_counter() - t0,
        }
    except Exception as e:  # noqa: BLE001 - artifacts must never break boot
        return None, "error", f"{type(e).__name__}: {str(e)[:160]}"
