"""Compiled-program registry: the single constructor for jitted steps.

Before PR 7 every layer built its jitted step ad hoc — the training loop
through ``parallel.make_train_step``, evaluation through ``make_eval_fn``
plus its module-level cache, training-validation through a private jit in
``inspect/summary.py`` — so the same (model, shape bucket, wire) triple
could compile more than once per process and *always* recompiled per
boot. The registry gives every step program one identity
(:class:`ProgramKey`), one owner (:class:`Program` — lowering,
compilation, AOT artifacts, warmup, per-program compile counters), and
one dedupe point (:class:`ProgramRegistry`).

Key discipline: a ProgramKey built only from *stable* configuration
(model id string, config reprs, shapes) is content-addressable — equal
across boots, so its programs can round-trip through the AOT artifact
store (``aot.py``). Callers that cannot name their configuration exactly
mark the key with a ``pyid:`` component (process-local object identity):
such programs still dedupe within the process and still count compiles,
but never touch the artifact store.
"""

import os
import threading
from dataclasses import dataclass, field
from typing import Tuple

from .. import telemetry
from . import aot

_UNSTABLE = "pyid:"

# sentinel: this shape signature cannot use an AOT executable; stay on JIT
_FALLBACK = object()


def unstable(obj):
    """Process-local identity marker for a key component that has no
    stable serialization (keeps dedupe, disables AOT)."""
    return f"{_UNSTABLE}{id(obj)}"


def flag_items(**kwargs):
    """Normalize keyword policy flags into the sorted (name, repr) tuple
    a ProgramKey stores. Values must repr deterministically — the
    ``evaluation.static_args_key`` discipline; callers pass
    ``unstable(obj)`` for anything that doesn't."""
    return tuple(sorted((k, repr(v)) for k, v in kwargs.items()))


@dataclass(frozen=True)
class ProgramKey:
    """Identity of one compiled step program.

    ``kind`` is the program family ('train_step', 'eval_step',
    'val_loss', ...) — it doubles as the telemetry compile label.
    ``model`` is the stable model id (or a ``pyid:`` marker). ``flags``
    carries every policy that changes the traced computation: wire
    format, mesh spec, nonfinite guard, accumulation, donation, static
    model/loss args, stage config. Concrete input shapes are *not* part
    of the key — one Program owns all shape buckets of its computation,
    and the AOT store addresses artifacts by (key digest, shape
    signature).
    """

    kind: str
    model: str
    flags: Tuple[Tuple[str, str], ...] = field(default=())

    @property
    def stable(self):
        """Whether the key survives across processes (AOT-addressable)."""
        if self.model.startswith(_UNSTABLE):
            return False
        return not any(_UNSTABLE in v for _, v in self.flags)

    def canonical(self):
        return repr((self.kind, self.model, self.flags))

    def describe(self):
        return f"{self.kind}[{self.model}]"


def shape_signature(args):
    """Concrete (shape, dtype) tuple over every array leaf of ``args`` —
    the per-call index into a Program's compiled-executable family."""
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(args):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            parts.append(type(leaf).__name__)
    return tuple(parts)


class Program:
    """One registered step program: a jitted callable plus its identity,
    compile counters, and (for stable keys) its AOT executable family.

    Calls route through a per-shape-signature compiled executable when
    the AOT store is enabled — loaded from disk when an artifact exists
    (zero compiles), otherwise compiled ahead of time once and saved for
    the next boot. Any mismatch (corrupt artifact, stale version,
    incompatible input placement) falls back to the plain JIT path for
    that signature, permanently and silently for the caller; the
    telemetry trail records why.

    ``compiles``/``compile_seconds`` count actual backend compiles
    attributed to this program via the jax.monitoring listener — they
    increment even when the telemetry sink is disabled, which is what
    lets eval warmup report 0 compiles on a warm cache instead of
    guessing 1 per shape (the pre-PR-7 overcount).
    """

    def __init__(self, key, fn, label=None):
        self.key = key
        self.label = label or key.kind
        self._fn = fn
        # compat with instrument_jit's wrapper contract
        self.__wrapped__ = fn
        self.telemetry_label = self.label
        self.compiles = 0
        self.compile_seconds = 0.0
        self.aot_hits = 0
        self.aot_misses = 0
        self.aot_saves = 0
        self.aot_fallbacks = 0
        self._compiled = {}
        self._lock = threading.Lock()
        # callers may pin objects their pyid: key components reference so
        # the ids stay unique for the program's lifetime
        self._refs = ()

    # -- counters (jax.monitoring listener callback) -----------------------

    def record_compile(self, seconds):
        self.compiles += 1
        self.compile_seconds += seconds

    # -- call paths --------------------------------------------------------

    def lower(self, *args, **kwargs):
        with telemetry.jit_label(self.label, self):
            return self._fn.lower(*args, **kwargs)

    def __call__(self, *args):
        if self.key.stable and aot.aot_enabled():
            sig = shape_signature(args)
            entry = self._compiled.get(sig)
            if entry is None:
                entry = self._ensure(sig, args)
            if entry is not _FALLBACK:
                try:
                    return entry(*args)
                except Exception as e:  # noqa: BLE001 - input mismatch
                    # argument checks run before execution, so the args
                    # (donated included) are intact; pin this signature
                    # to the JIT path and carry on
                    self._compiled[sig] = _FALLBACK
                    self.aot_fallbacks += 1
                    self._emit("fallback",
                               reason=f"call: {type(e).__name__}: "
                                      f"{str(e)[:160]}")
        with telemetry.jit_label(self.label, self):
            return self._fn(*args)

    def _ensure(self, sig, args):
        """Resolve one shape signature: load its artifact, or compile
        ahead of time and save one. Called once per (program, sig)."""
        with self._lock:
            entry = self._compiled.get(sig)
            if entry is not None:
                return entry

            path = aot.artifact_path(self.key, sig)
            if aot.tombstoned(path):
                # a previous boot proved this executable doesn't survive
                # serialization on this backend: plain JIT, no churn
                self._compiled[sig] = _FALLBACK
                return _FALLBACK
            compiled, status, info = aot.load(path, self.key, sig)
            if compiled is not None:
                self.aot_hits += 1
                self._emit("hit", bytes=info["bytes"],
                           seconds=round(info["seconds"], 4))
                self._compiled[sig] = compiled
                return compiled

            if status == "missing":
                self.aot_misses += 1
                self._emit("miss")
            else:
                # an artifact existed but was unusable: this boot pays a
                # cold JIT it expected to skip — the anomaly the report
                # flags
                self.aot_fallbacks += 1
                self._emit("fallback", reason=f"{status}: {info}")
                if status == "error":
                    # the artifact deserialized on save but not on load:
                    # this executable doesn't round-trip on this backend
                    # (e.g. XLA-CPU fusion symbol collisions). Tombstone
                    # it so later boots take the JIT path silently
                    # instead of re-saving and re-failing forever; the
                    # marker is fingerprint-scoped, so a jax/backend
                    # upgrade retries.
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    aot.tombstone(path)

            lower = getattr(self._fn, "lower", None)
            if lower is None:
                self._compiled[sig] = _FALLBACK
                return _FALLBACK

            c0 = self.compiles
            try:
                with telemetry.jit_label(self.label, self):
                    compiled = lower(*args).compile()
            except Exception as e:  # noqa: BLE001 - fall back to plain jit
                self.aot_fallbacks += 1
                self._emit("fallback",
                           reason=f"compile: {type(e).__name__}: "
                                  f"{str(e)[:160]}")
                self._compiled[sig] = _FALLBACK
                return _FALLBACK

            if self.compiles == c0:
                # the compile was served from the persistent XLA cache:
                # no backend compile ran, and (on some backends) such
                # executables serialize without their object code —
                # writing them would poison the next boot. This boot is
                # already warm through the cache; the artifact gets
                # written by whichever boot pays the real compile.
                self._emit("skip_save",
                           reason="compile served from persistent cache")
            else:
                try:
                    nbytes, seconds = aot.save(path, self.key, sig,
                                               compiled)
                    self.aot_saves += 1
                    self._emit("save", bytes=nbytes,
                               seconds=round(seconds, 4))
                except Exception as e:  # noqa: BLE001 - save is cosmetic
                    self._emit("fallback",
                               reason=f"save: {type(e).__name__}: "
                                      f"{str(e)[:160]}")

            self._compiled[sig] = compiled
            return compiled

    def _emit(self, event, **fields):
        telemetry.get().emit(
            "aot", event=event, program=self.key.kind,
            model=self.key.model, **fields)

    def stats(self):
        return {
            "kind": self.key.kind,
            "model": self.key.model,
            "stable": self.key.stable,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 3),
            "aot_hits": self.aot_hits,
            "aot_misses": self.aot_misses,
            "aot_saves": self.aot_saves,
            "aot_fallbacks": self.aot_fallbacks,
            "signatures": len(self._compiled),
        }


class ProgramRegistry:
    """Process-wide Program store: dedupe by key, bounded FIFO.

    Evicting an entry only drops the registry's reference — callers
    holding the Program keep a fully working step (same contract as the
    old evaluation fn cache)."""

    def __init__(self, max_programs=64):
        self.max_programs = max_programs
        self._programs = {}
        self._anonymous = []
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            return self._programs.get(key)

    def register(self, key, fn, label=None, dedupe=True):
        telemetry.install_listeners()
        with self._lock:
            if dedupe:
                existing = self._programs.get(key)
                if existing is not None:
                    return existing
            program = Program(key, fn, label)
            if dedupe:
                while len(self._programs) >= self.max_programs:
                    self._programs.pop(next(iter(self._programs)))
                self._programs[key] = program
            else:
                self._anonymous.append(program)
                del self._anonymous[:-self.max_programs]
            return program

    def programs(self):
        with self._lock:
            return list(self._programs.values()) + list(self._anonymous)

    def stats(self):
        return [p.stats() for p in self.programs()]

    def clear(self):
        with self._lock:
            self._programs.clear()
            self._anonymous.clear()


_registry = ProgramRegistry()


def registry():
    """The process-wide registry."""
    return _registry


def reset():
    """Drop every registered program (tests / bench cold runs)."""
    _registry.clear()


def register_step(kind, fn, key=None, label=None):
    """Route one freshly built jitted step through the registry.

    With a ``key`` the program dedupes (a second build of the same key
    returns the first Program, jit closure discarded — check
    ``registry().get(key)`` first to skip the build). Without one the
    program is anonymous: tracked for stats and compile attribution,
    never shared, never AOT'd — the safe default for callers whose
    closures (optimizer, loss) have no stable identity.
    """
    if key is None:
        key = ProgramKey(kind=kind, model=unstable(fn))
        return _registry.register(key, fn, label or kind, dedupe=False)
    return _registry.register(key, fn, label or key.kind, dedupe=True)
