#!/usr/bin/env python3
"""Per-op profile of the bench training step on the attached accelerator.

Captures a jax.profiler trace of the same step bench.py measures, parses
the .xplane.pb directly (tensorboard's converter is broken against the
installed TF), and prints the top XLA ops by self time plus a category
rollup. Usage:

    python scripts/profile_bench.py [N]   # N = ops to list (default 30)
"""

import glob
import os
import sys
import time
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def capture(trace_dir):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import parallel

    batch = int(os.environ.get("BENCH_BATCH", "6"))
    height = int(os.environ.get("BENCH_HEIGHT", "400"))
    width = int(os.environ.get("BENCH_WIDTH", "720"))
    iters = int(os.environ.get("BENCH_ITERS", "12"))
    model_ty = os.environ.get("BENCH_MODEL", "raft/baseline")
    # profile what bench.py measures: bf16 policy on the bench models
    model_params = {"mixed-precision": True} \
        if model_ty in ("raft/baseline", "raft/fs") or \
        model_ty.startswith("raft+dicl/ctf") else {}
    if model_ty.startswith("raft+dicl/ctf"):
        levels = int(model_ty[-1])
        model_args = {"iterations": (iters,) * levels}
        # corpus level weights, finest-last (cfg/model/raft+dicl-*.yaml)
        loss_cfg = {"type": "raft+dicl/mlseq",
                    "arguments": {"alpha": [0.23, 0.38, 0.6, 1.0][-levels:]}}
    else:
        model_args = {"iterations": iters}
        loss_cfg = {"type": "raft/sequence"}
    spec = models.load({
        "name": "bench", "id": "bench",
        "model": {"type": model_ty, "parameters": model_params},
        "loss": loss_cfg,
        "input": None,
    })

    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    img2 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(batch, height, width, 2), jnp.float32)
    valid = jnp.ones((batch, height, width), bool)

    init_args = dict(model_args)
    init_args["iterations"] = (
        (1,) * len(model_args["iterations"])
        if isinstance(model_args["iterations"], tuple) else 1)
    variables = spec.model.init(jax.random.PRNGKey(0), img1[:1], img2[:1],
                                **init_args)

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(4e-4))
    state = parallel.TrainState.create(variables, tx)
    step = parallel.make_train_step(spec.model, spec.loss, tx,
                                    model_args=model_args)

    state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])  # sync (block_until_ready unreliable on the tunnel)

    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    for _ in range(3):
        state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])
    dt = (time.perf_counter() - t0) / 3
    jax.profiler.stop_trace()
    print(f"step time: {dt * 1e3:.1f} ms")
    return dt


def parse(trace_dir, top_n=30):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    assert files, f"no xplane under {trace_dir}"
    newest = max(files, key=os.path.getmtime)
    xspace = xplane_pb2.XSpace()
    xspace.ParseFromString(open(newest, "rb").read())

    ops = defaultdict(float)
    for plane in xspace.planes:
        if "TPU" not in plane.name and "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            evmeta = plane.event_metadata
            for event in line.events:
                name = evmeta[event.metadata_id].name
                # container events double-count their children
                if name.startswith(("%while", "jit_", "%tuple")):
                    continue
                ops[name] += event.duration_ps / 1e9  # ms

    total = sum(ops.values())
    print(f"\ndevice op time: {total:.1f} ms over {len(ops)} ops")

    cats = defaultdict(float)
    for name, ms in ops.items():
        if "fusion" in name:
            cats["fusion"] += ms
        elif "convolution" in name or "conv" in name:
            cats["convolution"] += ms
        elif "dot" in name or "einsum" in name:
            cats["dot"] += ms
        elif "copy" in name or "transpose" in name or "bitcast" in name:
            cats["copy/transpose"] += ms
        elif "reduce" in name:
            cats["reduce"] += ms
        elif "all-reduce" in name or "all-gather" in name:
            cats["collective"] += ms
        else:
            cats["other"] += ms
    print("\ncategory rollup:")
    for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:16s} {ms:8.1f} ms  {100 * ms / total:5.1f}%")

    print(f"\ntop {top_n} ops by total time (3 steps):")
    for name, ms in sorted(ops.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"  {ms:8.2f} ms  {name[:110]}")


if __name__ == "__main__":
    top_n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    trace_dir = os.environ.get("TRACE_DIR", "/tmp/bench_trace")
    os.makedirs(trace_dir, exist_ok=True)
    capture(trace_dir)
    parse(trace_dir, top_n)
