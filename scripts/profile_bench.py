#!/usr/bin/env python3
"""Per-op profile of the bench training step on the attached accelerator.

Captures a jax.profiler trace of the same step bench.py measures and
attributes it through graftprof (``analysis.profile``) — the one
trace-reading code path shared with ``scripts/graftprof.py``,
``/profilez`` and the telemetry report. Prints the per-module op-class
attribution plus the top XLA ops by self time. Usage:

    python scripts/profile_bench.py [N]   # N = ops to list (default 30)
"""

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.analysis import profile as prof  # noqa: E402


def capture(trace_dir):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import raft_meets_dicl_tpu.models as models
    from raft_meets_dicl_tpu import parallel

    batch = int(os.environ.get("BENCH_BATCH", "6"))
    height = int(os.environ.get("BENCH_HEIGHT", "400"))
    width = int(os.environ.get("BENCH_WIDTH", "720"))
    iters = int(os.environ.get("BENCH_ITERS", "12"))
    model_ty = os.environ.get("BENCH_MODEL", "raft/baseline")
    # profile what bench.py measures: bf16 policy on the bench models
    model_params = {"mixed-precision": True} \
        if model_ty in ("raft/baseline", "raft/fs") or \
        model_ty.startswith("raft+dicl/ctf") else {}
    if model_ty.startswith("raft+dicl/ctf"):
        levels = int(model_ty[-1])
        model_args = {"iterations": (iters,) * levels}
        # corpus level weights, finest-last (cfg/model/raft+dicl-*.yaml)
        loss_cfg = {"type": "raft+dicl/mlseq",
                    "arguments": {"alpha": [0.23, 0.38, 0.6, 1.0][-levels:]}}
    else:
        model_args = {"iterations": iters}
        loss_cfg = {"type": "raft/sequence"}
    spec = models.load({
        "name": "bench", "id": "bench",
        "model": {"type": model_ty, "parameters": model_params},
        "loss": loss_cfg,
        "input": None,
    })

    rng = np.random.RandomState(0)
    img1 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    img2 = jnp.asarray(rng.rand(batch, height, width, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(batch, height, width, 2), jnp.float32)
    valid = jnp.ones((batch, height, width), bool)

    init_args = dict(model_args)
    init_args["iterations"] = (
        (1,) * len(model_args["iterations"])
        if isinstance(model_args["iterations"], tuple) else 1)
    variables = spec.model.init(jax.random.PRNGKey(0), img1[:1], img2[:1],
                                **init_args)

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(4e-4))
    state = parallel.TrainState.create(variables, tx)
    step = parallel.make_train_step(spec.model, spec.loss, tx,
                                    model_args=model_args)

    state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])  # sync (block_until_ready unreliable on the tunnel)

    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    for _ in range(3):
        state, aux = step(state, img1, img2, flow, valid)
    float(aux["loss"])
    dt = (time.perf_counter() - t0) / 3
    jax.profiler.stop_trace()
    print(f"step time: {dt * 1e3:.1f} ms")
    return dt


def parse(trace_dir, top_n=30):
    """Attribute the capture through graftprof and print the rollup."""
    summary = prof.attribute_trace(trace_dir, top_ops=top_n)
    print()
    print(prof.render_attribution(summary))

    ops = {}
    for m in summary["modules"]:
        for o in m["top_ops"]:
            ops[o["op"]] = ops.get(o["op"], 0.0) + o["seconds"]
    print(f"\ntop {top_n} ops by total time (3 steps):")
    for name, s in sorted(ops.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"  {s * 1e3:8.2f} ms  {name[:110]}")


if __name__ == "__main__":
    top_n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    trace_dir = os.environ.get("TRACE_DIR", "/tmp/bench_trace")
    os.makedirs(trace_dir, exist_ok=True)
    capture(trace_dir)
    parse(trace_dir, top_n)
