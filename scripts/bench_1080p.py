#!/usr/bin/env python3
"""High-resolution (HD1K 1080p) training-step stress benchmark.

BASELINE configs[4] — the KITTI/HD1K fine-tune at native resolution is
the high-res correlation stress case (SURVEY §5.7): at 2560x1072 the
1/8-scale all-pairs volume is (320*134)^2 elements ~= 3.4 GB in bf16
per sample before gradients, so ``raft/baseline`` cannot train there.
``raft/fs`` computes the correlation windows on the fly instead:
O(B*H*W*C) memory at any resolution. This benchmark runs one-sample
training steps of raft/fs at the cfg/strategy/highres recipe's crop,
reports throughput and peak HBM, and (optionally) demonstrates the
baseline's behavior at the same config.

Each measurement runs in its own subprocess: peak_bytes_in_use is a
process-lifetime high-water mark, and a parent holding the chip would
block the child on single-client TPU runtimes.

    python scripts/bench_1080p.py [--try-baseline]
"""

import argparse
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).parent.parent


def measure_subprocess(model_cfg, height, width, iters, steps):
    """bench._measure in a fresh process; returns (pairs/s, peak_bytes)
    or raises RuntimeError with the child's last error line."""
    code = (
        "import sys, json; sys.path.insert(0, {repo!r}); import bench; "
        "print(json.dumps(bench._measure({model!r}, "
        "{{'type': 'raft/sequence'}}, 1, {h}, {w}, "
        "{{'iterations': {it}}}, {st})))"
    ).format(repo=str(REPO), model=model_cfg, h=height, w=width,
             it=iters, st=steps)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        tail = proc.stderr.strip().splitlines()
        err = next((ln for ln in reversed(tail)
                    if "Error" in ln or "RESOURCE" in ln),
                   tail[-1] if tail else "unknown")
        raise RuntimeError(err[:160])
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--try-baseline", action="store_true",
                    help="also attempt raft/baseline at 1080p")
    ap.add_argument("--height", type=int, default=1072)
    ap.add_argument("--width", type=int, default=2560)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--steps", type=int, default=2)
    args = ap.parse_args()

    result = {
        "metric": "train-throughput-raft-fs-1080p",
        "config": f"{args.width}x{args.height} batch 1, "
                  f"{args.iters} iterations, bf16",
        "unit": "image-pairs/sec/chip",
    }

    pairs, peak = measure_subprocess(
        {"type": "raft/fs", "parameters": {"mixed-precision": True}},
        args.height, args.width, args.iters, args.steps)
    result["value"] = round(pairs, 4)
    result["peak_hbm_gib"] = round(peak / 2**30, 2)

    if args.try_baseline:
        try:
            pairs_b, peak_b = measure_subprocess(
                {"type": "raft/baseline",
                 "parameters": {"mixed-precision": True}},
                args.height, args.width, args.iters, args.steps)
            result["baseline_value"] = round(pairs_b, 4)
            result["baseline_peak_hbm_gib"] = round(peak_b / 2**30, 2)
        except RuntimeError as e:
            # the failure IS the datum (OOM expected at 1080p)
            result["baseline_error"] = str(e)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
