#!/usr/bin/env python3
"""High-resolution (HD1K 1080p) training-step stress benchmark.

BASELINE configs[4] — the KITTI/HD1K fine-tune at native resolution is
the high-res correlation stress case (SURVEY §5.7): at 2560x1072 the
1/8-scale all-pairs volume is (320*134)^2 elements ~= 3.4 GB in bf16
per sample before gradients, so ``raft/baseline`` cannot train there.
``raft/fs`` computes the correlation windows on the fly instead:
O(B*H*W*C) memory at any resolution. This benchmark runs one-sample
training steps of raft/fs at the cfg/strategy/highres recipe's crop,
reports throughput and peak HBM, and (optionally) demonstrates the
baseline's behavior at the same config.

    python scripts/bench_1080p.py [--try-baseline]
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import bench  # noqa: E402  (the shared train-step measurement harness)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--try-baseline", action="store_true",
                    help="also attempt raft/baseline at 1080p")
    ap.add_argument("--height", type=int, default=1072)
    ap.add_argument("--width", type=int, default=2560)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--steps", type=int, default=2)
    args = ap.parse_args()

    result = {
        "metric": "train-throughput-raft-fs-1080p",
        "config": f"{args.width}x{args.height} batch 1, "
                  f"{args.iters} iterations, bf16",
        "unit": "image-pairs/sec/chip",
    }

    pairs, peak = bench._measure(
        {"type": "raft/fs", "parameters": {"mixed-precision": True}},
        {"type": "raft/sequence"},
        1, args.height, args.width, {"iterations": args.iters}, args.steps)
    result["value"] = round(pairs, 4)
    result["peak_hbm_gib"] = round(peak / 2**30, 2)

    if args.try_baseline:
        # separate process: peak_bytes_in_use is a process-lifetime
        # high-water mark, so measuring in-process would report
        # max(fs_peak, baseline_peak)
        import subprocess

        code = (
            "import sys, json; sys.path.insert(0, {repo!r}); import bench; "
            "print(json.dumps(bench._measure("
            "{{'type': 'raft/baseline', "
            "'parameters': {{'mixed-precision': True}}}}, "
            "{{'type': 'raft/sequence'}}, 1, {h}, {w}, "
            "{{'iterations': {it}}}, {st})))"
        ).format(repo=str(Path(__file__).parent.parent), h=args.height,
                 w=args.width, it=args.iters, st=args.steps)
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True)
        if proc.returncode == 0:
            pairs_b, peak_b = json.loads(proc.stdout.strip().splitlines()[-1])
            result["baseline_value"] = round(pairs_b, 4)
            result["baseline_peak_hbm_gib"] = round(peak_b / 2**30, 2)
        else:
            # the failure IS the datum (OOM expected at 1080p)
            tail = proc.stderr.strip().splitlines()
            err = next((ln for ln in reversed(tail)
                        if "Error" in ln or "RESOURCE" in ln),
                       tail[-1] if tail else "unknown")
            result["baseline_error"] = err[:160]

    print(json.dumps(result))


if __name__ == "__main__":
    main()
