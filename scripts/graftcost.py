#!/usr/bin/env python3
"""graftcost CLI: static HLO cost model + sharding-contract audit,
gated against the pinned per-program budgets in ``hlo-budget.json``.

Walks every registered audit program (flagship train/eval, the (4, 2)-
mesh SPMD variant, the iteration-ladder rungs), computes deterministic
per-op-class FLOP/byte totals from the lowered StableHLO, diffs the
compiled collective schedule against the partitioner-derived
expectation, and enforces the pinned budgets: flops/bytes/collective
bytes within tolerance, hazard and resharding counts no worse than
grandfathered, no unpinned programs, stale pins reported.

    python scripts/graftcost.py                    # audit vs hlo-budget.json
    python scripts/graftcost.py --update           # re-pin after a deliberate change
    python scripts/graftcost.py --format json      # machine-readable report
    python scripts/graftcost.py --no-mesh2d        # skip the 8-device SPMD variant
    python scripts/graftcost.py --events out.jsonl # per-program 'cost' telemetry

Exit codes: 0 — every audited program within budget (stale pins alone
don't fail; prune them with --update); 1 — findings (budget drift,
hazard growth, contract violation, unpinned program); 2 — usage error.
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.analysis import cost  # noqa: E402


def json_report(report):
    """Stable machine-readable schema (see also graftlint --format json):
    bump ``schema`` on any incompatible change."""
    out = report.to_dict()
    out["schema"] = 1
    out["exit_code"] = 0 if report.ok else 1
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="exit codes: 0 within budget; 1 findings; 2 usage error")
    ap.add_argument("--budget", default=None, metavar="FILE",
                    help=f"pinned budget JSON (default: <repo>/"
                         f"{cost.BUDGET_NAME})")
    ap.add_argument("--update", action="store_true",
                    help="re-pin the budget file from this run's numbers "
                         "(drops stale entries) instead of gating")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format (default: text)")
    ap.add_argument("--no-mesh2d", action="store_true",
                    help="skip the 8-device (4, 2)-mesh SPMD variant "
                         "(faster; its pins then report stale)")
    ap.add_argument("--events", default=None, metavar="FILE",
                    help="append per-program 'cost' telemetry events")
    args = ap.parse_args(argv)

    budget_path = Path(args.budget) if args.budget else \
        Path(__file__).parent.parent / cost.BUDGET_NAME
    budget = (cost.Budget.load(budget_path) if budget_path.exists()
              else cost.Budget.empty())

    entries = cost.build_entries(include_mesh2d=not args.no_mesh2d)
    report = cost.audit_costs(entries=entries, budget=budget)

    if args.events:
        from raft_meets_dicl_tpu import telemetry

        tele = telemetry.Telemetry(args.events)
        try:
            cost.emit_events(report, tele)
        finally:
            tele.close()

    if args.update:
        budget.path = str(budget_path)
        budget_path.write_text(
            json.dumps(budget.pinned_data(report.reports), indent=2)
            + "\n")
        print(f"pinned {len(report.reports)} program budget(s) -> "
              f"{budget_path}")
        dropped = [k for k in report.stale]
        for k in dropped:
            print(f"  dropped stale entry: {k}")
        return 0

    if args.format == "json":
        json.dump(json_report(report), sys.stdout, indent=2)
        print()
    else:
        print(cost.render_reports(report))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
