#!/usr/bin/env bash
# Prepare every host of a TPU pod slice: sync the repo and install deps.
# (reference scripts/cluster/setup-env.sh, TPU edition)
#
# Usage: TPU_NAME=my-pod ZONE=us-central2-b ./scripts/cluster/setup-env.sh
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to the TPU pod/VM name}"
ZONE="${ZONE:?set ZONE to the TPU zone}"
REPO_DIR="${REPO_DIR:-\$HOME/raft_meets_dicl_tpu}"
SRC_DIR="${SRC_DIR:-$(cd "$(dirname "$0")/../.." && pwd)}"

# sync the framework to all workers
gcloud compute tpus tpu-vm scp --recurse --zone "$ZONE" --worker=all \
    "$SRC_DIR" "$TPU_NAME:$REPO_DIR"

# install python dependencies (jax[tpu] ships with TPU VM images)
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command "pip install --quiet flax optax chex einops opencv-python-headless pyyaml tqdm pandas matplotlib tensorboard"
