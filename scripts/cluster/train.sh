#!/usr/bin/env bash
# Launch training on a TPU pod slice (all hosts).
#
# TPU-native equivalent of the reference's SLURM submission script
# (reference scripts/cluster/train.sh:1-31): instead of sbatch + CUDA env
# modules, this drives `gcloud compute tpus tpu-vm ssh --worker=all` so the
# same SPMD program runs on every host of the slice. `--distributed` joins
# the multi-process runtime (jax.distributed.initialize; coordinator and
# rank are auto-discovered on TPU pods), the data mesh then spans all
# chips (ICI within the slice), each host loads its per-process batch
# shard, and only worker 0 writes logs/checkpoints
# (raft_meets_dicl_tpu/parallel/distributed.py; exercised end-to-end on a
# 2-process virtual cluster by tests/test_distributed.py).
#
# Usage:
#   TPU_NAME=my-pod ZONE=us-central2-b ./scripts/cluster/train.sh \
#       -d cfg/strategy/baseline/raft/s0-chairs.yaml \
#       -m cfg/model/raft-baseline.yaml
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to the TPU pod/VM name}"
ZONE="${ZONE:?set ZONE to the TPU zone}"
REPO_DIR="${REPO_DIR:-\$HOME/raft_meets_dicl_tpu}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
    --command "cd $REPO_DIR && python3 main.py train --distributed $*"
