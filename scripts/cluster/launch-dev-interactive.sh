#!/usr/bin/env bash
# Open an interactive shell on worker 0 of a TPU pod/VM with the repo
# on PYTHONPATH (reference scripts/cluster/launch-dev-interactive.sh).
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to the TPU pod/VM name}"
ZONE="${ZONE:?set ZONE to the TPU zone}"
REPO_DIR="${REPO_DIR:-\$HOME/raft_meets_dicl_tpu}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=0 \
    -- -t "cd $REPO_DIR && PYTHONPATH=$REPO_DIR exec bash -l"
