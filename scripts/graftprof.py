#!/usr/bin/env python3
"""graftprof CLI: measured device-time attribution, gated against the
machine-scoped calibration pins in ``prof-budget.json``.

Two modes:

- **capture-and-attribute** (default): runs every graftcost audit
  program (the exact set ``hlo-budget.json`` pins) inside its own
  profiler trace segment, attributes measured device time per op class,
  diffs it against the roofline-predicted seconds, and gates the
  measured/predicted ratio per program against the pins for *this*
  machine (``platform:device_kind``).
- **attribute-only** (``--trace-dir DIR``): parses an existing capture
  (a ``/profilez`` artifact, a ``train --profile`` dir, a
  ``profile_bench`` trace) and prints the per-module attribution —
  no gating, module→program matching is best-effort.

    python scripts/graftprof.py                     # audit vs prof-budget.json
    python scripts/graftprof.py --update            # re-pin this machine
    python scripts/graftprof.py --format json       # machine-readable report
    python scripts/graftprof.py --trace-dir /tmp/t  # attribute a capture
    python scripts/graftprof.py --events out.jsonl  # 'profile' telemetry

Exit codes: 0 — every profiled program within its calibration band
(stale pins alone don't fail; prune them with --update); 1 — findings
(calibration drift, unpinned program); 2 — usage error.
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.analysis import profile as prof  # noqa: E402


def json_report(report):
    """Stable machine-readable schema (graftcost discipline): bump
    ``schema`` on any incompatible change."""
    out = report.to_dict()
    out["schema"] = 1
    out["exit_code"] = 0 if report.ok else 1
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="exit codes: 0 within calibration; 1 findings; "
               "2 usage error")
    ap.add_argument("--budget", default=None, metavar="FILE",
                    help=f"pinned calibration JSON (default: <repo>/"
                         f"{prof.BUDGET_NAME})")
    ap.add_argument("--update", action="store_true",
                    help="re-pin this machine's calibration from this "
                         "run's ratios (other machines' pins are "
                         "preserved) instead of gating")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="report format (default: text)")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="attribute an existing capture directory "
                         "instead of capturing (no gating)")
    ap.add_argument("--no-mesh2d", action="store_true",
                    help="skip the 8-device (4, 2)-mesh SPMD variant "
                         "(faster; its pins then report stale)")
    ap.add_argument("--repeats", type=int, default=2, metavar="N",
                    help="traced executions per program (default: 2)")
    ap.add_argument("--keep-trace", default=None, metavar="DIR",
                    help="keep the segmented capture under DIR instead "
                         "of a deleted tempdir")
    ap.add_argument("--events", default=None, metavar="FILE",
                    help="append per-program 'profile' telemetry "
                         "events")
    args = ap.parse_args(argv)

    if args.trace_dir:
        try:
            summary = prof.attribute_trace(args.trace_dir)
        except prof.TraceError as e:
            print(f"graftprof: {e}", file=sys.stderr)
            return 2
        if args.format == "json":
            summary["schema"] = 1
            json.dump(summary, sys.stdout, indent=2)
            print()
        else:
            print(prof.render_attribution(summary))
        return 0

    budget_path = Path(args.budget) if args.budget else \
        Path(__file__).parent.parent / prof.BUDGET_NAME
    budget = (prof.ProfBudget.load(budget_path) if budget_path.exists()
              else prof.ProfBudget.empty())

    from raft_meets_dicl_tpu.analysis import cost

    entries = cost.build_entries(include_mesh2d=not args.no_mesh2d)
    report = prof.audit_profiles(entries=entries, budget=budget,
                                 out_dir=args.keep_trace,
                                 repeats=args.repeats)

    if args.events:
        from raft_meets_dicl_tpu import telemetry

        tele = telemetry.Telemetry(args.events)
        try:
            prof.emit_events(report, tele)
        finally:
            tele.close()

    if args.update:
        machine_id = report.machine["machine_id"]
        budget.path = str(budget_path)
        budget_path.write_text(
            json.dumps(budget.pinned_data(report.reports, machine_id),
                       indent=2) + "\n")
        print(f"pinned {len(report.reports)} calibration(s) for "
              f"{machine_id} -> {budget_path}")
        for k in report.stale:
            print(f"  dropped stale entry: {k}")
        return 0

    if args.format == "json":
        json.dump(json_report(report), sys.stdout, indent=2)
        print()
    else:
        print(prof.render_reports(report))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
