#!/usr/bin/env python3
"""Flow-image matrix dump: models × training stages × cost-mask variants.

Capability parity with reference scripts/eval/multi-flow.py: for each
configured (model, checkpoint) pair and each ``mask_costs`` variant
(zeroing correlation-cost levels by pyramid id), run the evaluation
command with flow-image output — the qualitative matrix used to study
what each correlation level contributes.

Edit the ``models`` / ``mask`` / ``data`` tables below for your runs,
then:  ./scripts/eval/multi-flow.py
"""

import json
import sys
import tempfile
import types
from dataclasses import dataclass
from pathlib import Path
from typing import Dict

sys.path.insert(0, str(Path(__file__).parent.parent.parent))

import raft_meets_dicl_tpu as fw  # noqa: E402
import raft_meets_dicl_tpu.cmd.eval as cmd_eval  # noqa: E402

DIR_OUT = Path("out/flow")

mask = {
    "base": (),
    "mask-3": (3,),
    "mask-34": (3, 4),
    "mask-4": (4,),
}

data = "cfg/data/mpi-sintel-clean.visual.yaml"


@dataclass
class Stage:
    model: str
    checkpoint: str


@dataclass
class Model:
    stages: Dict[str, Stage]


# fill in run artifacts: model = a model yaml or a run's config.json,
# checkpoint = the matching .ckpt
models = {
    "raft-baseline": Model(
        stages={
            "chairs": Stage(
                model="cfg/model/raft-baseline.yaml",
                checkpoint="runs/<run>/checkpoints/<chkpt>.ckpt",
            ),
        }
    ),
}


def do_evaluate(model, checkpoint, data_path, flow_out):
    args = types.SimpleNamespace(
        device=None,
        device_ids=None,
        batch_size=1,
        model=model,
        checkpoint=checkpoint,
        data=data_path,
        output=None,
        metrics=None,
        flow=str(flow_out),
        flow_only=True,
        flow_format="visual:flow",
        flow_mrm=60,
        flow_gamma=None,
        flow_transform=None,
        epe_max=None,
        epe_cmap=None,
    )
    cmd_eval.evaluate(args)


def path_validate(path):
    if not Path(path).is_file():
        raise RuntimeError(f"path does not exist: '{path}'")


def update_model(model_file, model_src, mask_costs):
    cfg = fw.utils.config.load(model_src)
    if "model" in cfg and "strategy" in cfg:  # frozen full config
        cfg = cfg["model"]
    model = fw.models.load(cfg)

    model.model.arguments["mask_costs"] = list(mask_costs)
    model_cfg = json.dumps(model.get_config())

    model_file.seek(0)
    model_file.truncate(0)
    model_file.write(model_cfg.encode("utf-8"))
    model_file.flush()


def main():
    for model in models.values():
        for stage in model.stages.values():
            path_validate(stage.model)
            path_validate(stage.checkpoint)

    with tempfile.NamedTemporaryFile(suffix=".json") as model_file:
        for model_name, model in models.items():
            for stage_name, stage in model.stages.items():
                for mask_name, ms in mask.items():
                    output = DIR_OUT / model_name / stage_name / mask_name

                    update_model(model_file, stage.model, ms)
                    do_evaluate(model_file.name, stage.checkpoint, data,
                                output)


if __name__ == "__main__":
    main()
