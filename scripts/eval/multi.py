#!/usr/bin/env python3
"""Batch evaluation over a model × stage × dataset matrix.

Capability parity with reference scripts/eval/multi.py:29-70 — but the
matrix is a config file instead of hard-coded paths:

```yaml
output: multieval
batch-size: 2
models:
  raft-baseline:
    stages:
      things:
        model: runs/<ts>/config.json
        checkpoint: runs/<ts>/checkpoints/best.ckpt
        data:
          sintel-clean: cfg/data/mpi-sintel-clean.train-full.yaml
          sintel-final: cfg/data/mpi-sintel-final.train-full.yaml
```

Writes one JSON report per (model, stage, dataset) into the output
directory, plus a combined summary.
"""

import argparse
import json
import sys
import types
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent.parent))

from raft_meets_dicl_tpu import cmd, utils  # noqa: E402


def evaluate_one(model_cfg, checkpoint, data_cfg, output, batch_size):
    args = types.SimpleNamespace(
        data=str(data_cfg), model=str(model_cfg), checkpoint=str(checkpoint),
        batch_size=batch_size, metrics=None, output=str(output), flow=None,
        flow_format="visual:flow", flow_mrm=None, flow_gamma=None,
        flow_transform=None, flow_only=False, epe_cmap="gray", epe_max=None,
        device=None, device_ids=None,
    )
    cmd.evaluate(args)


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Batch-evaluate a model/stage/dataset matrix",
        formatter_class=fmtcls)
    parser.add_argument("-c", "--config", required=True,
                        help="matrix specification (yaml/json)")
    parser.add_argument("-o", "--output",
                        help="output directory (overrides the spec)")

    args = parser.parse_args()

    spec = utils.config.load(args.config)
    out_dir = Path(args.output or spec.get("output", "multieval"))
    out_dir.mkdir(parents=True, exist_ok=True)
    batch_size = int(spec.get("batch-size", 1))

    summary = {}
    for model_name, model_spec in spec["models"].items():
        for stage_name, stage in model_spec["stages"].items():
            for data_name, data_cfg in stage["data"].items():
                report = out_dir / f"{model_name}-{stage_name}-{data_name}.json"
                print(f"==> {model_name} / {stage_name} / {data_name}")

                evaluate_one(stage["model"], stage["checkpoint"], data_cfg,
                             report, batch_size)

                with open(report) as fd:
                    result = json.load(fd)
                summary[f"{model_name}/{stage_name}/{data_name}"] = \
                    result["summary"]

    with open(out_dir / "summary.json", "w") as fd:
        json.dump(summary, fd, indent=2)
    print(f"wrote combined summary to '{out_dir / 'summary.json'}'")


if __name__ == "__main__":
    main()
