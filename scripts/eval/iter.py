#!/usr/bin/env python3
"""Evaluate checkpoints across recurrent iteration counts.

Capability parity with reference scripts/eval/iter.py:18-50 with a
config-driven matrix:

```yaml
output: itereval
iterations: [1, 2, 4, 8, 12, 16, 24]
models:
  raft-baseline:
    model: runs/<ts>/config.json
    checkpoint: runs/<ts>/checkpoints/best.ckpt
    data:
      sintel-clean: cfg/data/mpi-sintel-clean.train-full.yaml
```
"""

import argparse
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent.parent))

from raft_meets_dicl_tpu import utils  # noqa: E402

from multi import evaluate_one  # noqa: E402


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Evaluate across iteration counts", formatter_class=fmtcls)
    parser.add_argument("-c", "--config", required=True,
                        help="matrix specification (yaml/json)")
    parser.add_argument("-o", "--output",
                        help="output directory (overrides the spec)")

    args = parser.parse_args()

    spec = utils.config.load(args.config)
    out_dir = Path(args.output or spec.get("output", "itereval"))
    out_dir.mkdir(parents=True, exist_ok=True)
    batch_size = int(spec.get("batch-size", 1))
    iterations = spec["iterations"]

    summary = {}
    for model_name, model_spec in spec["models"].items():
        model_cfg = utils.config.load(model_spec["model"])
        if "strategy" in model_cfg:
            model_cfg = model_cfg["model"]

        for n_iter in iterations:
            # bake the iteration count into the model arguments
            cfg = dict(model_cfg)
            cfg["model"] = dict(cfg["model"])
            cfg["model"]["arguments"] = dict(
                cfg["model"].get("arguments", {})) | {"iterations": n_iter}

            with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False) as fd:
                utils.config.store(fd.name, cfg)
                tmp_model = fd.name

            for data_name, data_cfg in model_spec["data"].items():
                report = out_dir / f"{model_name}-i{n_iter}-{data_name}.json"
                print(f"==> {model_name} / iterations={n_iter} / {data_name}")

                evaluate_one(tmp_model, model_spec["checkpoint"], data_cfg,
                             report, batch_size)

                with open(report) as fd:
                    result = json.load(fd)
                summary[f"{model_name}/i{n_iter}/{data_name}"] = \
                    result["summary"]

    with open(out_dir / "summary.json", "w") as fd:
        json.dump(summary, fd, indent=2)


if __name__ == "__main__":
    main()
