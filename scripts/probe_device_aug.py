#!/usr/bin/env python3
"""Measure the goodput delta of moving augmentation off the host
(ISSUE PR-19 deliverable: a smoke-train ``data_starved``/``data_wait``
delta from the goodput ledger).

Two arms over the same synthetic source and the same tiny raft/baseline
strategy loop (CPU-safe shapes, the PR-14 harness idiom):

  A. host augmentation — the classic ``data.augment.Augment`` stack
     (color jitter, flip, gaussian noise, occlusion eraser) applied
     per sample on the loader path; its cost lands in the step trace's
     ``data_wait`` phase and the ledger's ``data_starved`` class.
  B. device augmentation — the same transform family compiled into the
     registered train step (``data.device_augment.DeviceAugment``); the
     loader ships raw samples, augmentation rides the device program,
     and ``data_wait`` collapses to queue-pull overhead.

Both arms run with the goodput ledger active and print the per-arm
ledger classes plus the steptrace ``data_wait`` mean/share so the delta
is read from the same instruments a production run reports.

    python scripts/probe_device_aug.py [--steps 24] [--shape 96 128]
"""

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu import models, strategy, telemetry  # noqa: E402
from raft_meets_dicl_tpu.data import augment as haug  # noqa: E402
from raft_meets_dicl_tpu.data.collection import (  # noqa: E402
    Collection, Metadata, SampleArgs, SampleId)
from raft_meets_dicl_tpu.data.device_augment import DeviceAugment  # noqa: E402
from raft_meets_dicl_tpu.strategy.spec import (  # noqa: E402
    ClipGradientNorm, DataSpec, GradientSpec, MultiSchedulerSpec,
    OptimizerSpec, SchedulerSpec, Stage)
from raft_meets_dicl_tpu.telemetry import goodput  # noqa: E402
from raft_meets_dicl_tpu.utils.logging import Logger  # noqa: E402

TINY_MODEL = {
    "name": "tiny", "id": "tiny-augprobe",
    "model": {
        "type": "raft/baseline",
        "parameters": {"corr-levels": 2, "corr-radius": 2,
                       "corr-channels": 32, "context-channels": 16,
                       "recurrent-channels": 16},
        "arguments": {"iterations": 2},
    },
    "loss": {"type": "raft/sequence"},
    "input": None,
}


class Source(Collection):
    """Deterministic constant-translation pairs at a probe-sized shape."""

    type = "probe-flow"

    def __init__(self, n, h, w):
        self.n, self.h, self.w = n, h, w

    def __getitem__(self, index):
        rng = np.random.RandomState(index)
        base = rng.rand(self.h, self.w + 8, 3).astype(np.float32)
        img1, img2 = base[:, :-8], base[:, 8:]
        flow = np.zeros((self.h, self.w, 2), np.float32)
        flow[..., 0] = 8.0
        valid = np.ones((self.h, self.w), bool)
        meta = Metadata(True, "probe",
                        SampleId("s", SampleArgs([], {"i": index}),
                                 SampleArgs([], {"i": index + 1})),
                        ((0, self.h), (0, self.w)))
        return img1[None], img2[None], flow[None], valid[None], [meta]

    def __len__(self):
        return self.n

    def get_config(self):
        return {"type": self.type, "n": self.n}

    def description(self):
        return "probe flow"


def _host_stack():
    return [haug.ColorJitter(1.0, 0.4, 0.4, 0.4, 0.1),
            haug.Flip([0.5, 0.1]),
            haug.NoiseNormal([0.0, 0.02]),
            haug.OcclusionForward(0.5, [1, 3], [10, 10], [30, 30])]


def _device_stack():
    return DeviceAugment(scale=(0.0, 0.0), stretch=0.0, rotate=0.0,
                         translate=0.0, jitter=0.0, flip=(0.5, 0.1),
                         brightness=0.4, contrast=0.4, saturation=0.4,
                         hue=0.1, noise=(0.0, 0.02), occlusion=0.5,
                         occlusion_num=(1, 3), occlusion_size=(10, 30),
                         seed=0)


def _stage(source, epochs, batch):
    return Stage(
        name="s0", id="probe/s0",
        data=DataSpec(source, epochs=epochs, batch_size=batch),
        validation=[],
        optimizer=OptimizerSpec("adam", {"lr": 1e-4}),
        gradient=GradientSpec(accumulate=1, clip=ClipGradientNorm(1.0)),
        scheduler=MultiSchedulerSpec(instance=[SchedulerSpec("one-cycle", {
            "max_lr": 1e-4, "total_steps": "{n_batches} * {n_epochs}",
            "pct_start": 0.3, "cycle_momentum": False})]),
    )


def run_arm(name, source, augment, workdir, epochs, batch):
    sink = telemetry.activate(telemetry.Telemetry())
    led = goodput.activate()
    try:
        spec = models.load(TINY_MODEL)
        mgr = strategy.CheckpointManager(
            "tiny", Path(workdir) / "checkpoints",
            "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt",
            compare=["{m_loss}"], keep_best=1, keep_latest=1)
        ctx = strategy.TrainingContext(
            Logger(f"probe-{name}"), workdir,
            strategy.Strategy("continuous", [_stage(source, epochs, batch)]),
            "tiny", spec.model, spec.model.get_adapter(), spec.loss,
            spec.input, strategy.Inspector(), mgr,
            loader_args={"num_workers": 0}, augment=augment)
        t0 = time.perf_counter()
        ctx.run()
        wall = time.perf_counter() - t0
        snap = led.snapshot()
        traces = [e for e in sink.events if e["kind"] == "steptrace"]
        # exact per-step sums from the bounded ring (capacity 512 >>
        # this smoke run); the sink events carry windowed p50s only
        records = list(ctx.steptraces._records)
        waits = [r["phases"].get("data_wait", 0.0) for r in records]
        totals = [r["total"] for r in records]
        return {
            "arm": name,
            "steps": ctx.steps_completed,
            "wall_s": round(wall, 3),
            "data_wait_ms_per_step": round(
                1e3 * sum(waits) / max(1, len(waits)), 2),
            "data_wait_share": round(sum(waits) / max(1e-9, sum(totals)), 4),
            "data_starved_windows": sum(
                1 for e in traces if e.get("data_starved")),
            "windows": len(traces),
            "goodput": {k: round(v, 3)
                        for k, v in snap["classes"].items() if v > 0.0},
        }
    finally:
        telemetry.deactivate()
        goodput.deactivate()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--samples", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--shape", type=int, nargs=2, default=(96, 128),
                    metavar=("H", "W"))
    ap.add_argument("--workdir", default="/tmp/probe_device_aug")
    args = ap.parse_args(argv)

    import os

    # pin the finite-check cadence: one steptrace record per step, so
    # both arms sample data_wait at identical granularity
    os.environ["RMD_FINITE_CHECK_EVERY"] = "1"

    h, w = args.shape
    rows = []
    for name, source, augment in (
        ("host-augment",
         haug.Augment(_host_stack(), Source(args.samples, h, w),
                      sync=True, seed=0),
         None),
        ("device-augment", Source(args.samples, h, w), _device_stack()),
    ):
        workdir = Path(args.workdir) / name
        workdir.mkdir(parents=True, exist_ok=True)
        rows.append(run_arm(name, source, augment, workdir,
                            args.epochs, args.batch))
        print(rows[-1], flush=True)

    a, b = rows
    print(f"\ndata_wait {a['data_wait_ms_per_step']} -> "
          f"{b['data_wait_ms_per_step']} ms/step "
          f"(share {a['data_wait_share']:.3f} -> "
          f"{b['data_wait_share']:.3f}), "
          f"starved windows {a['data_starved_windows']}/{a['windows']} -> "
          f"{b['data_starved_windows']}/{b['windows']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
