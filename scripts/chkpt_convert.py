#!/usr/bin/env python3
"""Convert original-implementation torch checkpoints to this framework.

Capability parity with reference scripts/chkpt_convert.py:22-120: imports
princeton-vl/RAFT checkpoints (and the reference framework's own
``raft/baseline`` .pth files, whose renamed prefixes are normalized first)
into the framework's msgpack checkpoint format — the practical route to
validating EPE parity against trained weights without retraining.

Unlike the reference (a torch-key rename), this conversion crosses
frameworks: torch module paths map onto the flax variable tree and weight
layouts are transposed (conv OIHW → HWIO, BN weight/bias →
scale/bias + batch_stats).

Usage:
    ./scripts/chkpt_convert.py -i raft-things.pth -o raft-things.ckpt -f raft
"""

import argparse
import logging
import sys
from datetime import datetime
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

import raft_meets_dicl_tpu.models as models  # noqa: E402
from raft_meets_dicl_tpu import utils  # noqa: E402
from raft_meets_dicl_tpu.strategy.checkpoint import (  # noqa: E402
    Checkpoint,
    Iteration,
    State,
)

# prefix normalization: the reference framework renames some upstream RAFT
# modules (reference chkpt_convert.py:43-51); accept either spelling
_RAFT_PFX = [
    ("module.", ""),
    ("update_block.enc.", "update_block.encoder."),
    ("update_block.flow.", "update_block.flow_head."),
    ("upnet.conv1.", "update_block.mask.0."),
    ("upnet.conv2.", "update_block.mask.2."),
]


def _normalize(state, sub):
    out = {}
    for k, v in state.items():
        for old, new in sub:
            if k.startswith(old):
                k = new + k[len(old):]
        out[k] = np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach")
                            else v)
    return out


def _conv(torch_w):
    """torch conv weight (O, I, kh, kw) → flax kernel (kh, kw, I, O)."""
    return np.transpose(torch_w, (2, 3, 1, 0))


def _conv_t(torch_w):
    """torch ConvTranspose2d weight (I, O, kh, kw) → flax ConvTranspose
    kernel (kh, kw, I, O) with ``transpose_kernel=False`` semantics —
    spatial flip + axis moves (verified bit-exact in f64 against
    ``F.conv_transpose2d`` k4/s2/p1 vs flax 'SAME')."""
    return np.transpose(torch_w[:, :, ::-1, ::-1], (2, 3, 0, 1))


def _stem_rules(src):
    """flax _Stem path fragment → torch fnet/cnet path fragment."""
    rules = {
        "Conv_0": f"{src}.conv1",
        "Norm2d_0.BatchNorm_0": f"{src}.norm1",
    }
    for i in range(6):
        tgt = f"{src}.layer{i // 2 + 1}.{i % 2}"
        rules[f"ResidualBlock_{i}.Conv_0"] = f"{tgt}.conv1"
        rules[f"ResidualBlock_{i}.Conv_1"] = f"{tgt}.conv2"
        rules[f"ResidualBlock_{i}.Conv_2"] = f"{tgt}.downsample.0"
        rules[f"ResidualBlock_{i}.Norm2d_0.BatchNorm_0"] = f"{tgt}.norm1"
        rules[f"ResidualBlock_{i}.Norm2d_1.BatchNorm_0"] = f"{tgt}.norm2"
        rules[f"ResidualBlock_{i}.Norm2d_2.BatchNorm_0"] = f"{tgt}.downsample.1"
    return rules


def _raft_rules():
    """flax module path (dotted) → torch module path for raft/baseline."""
    rules = {}

    for flax_enc, torch_enc in (("FeatureEncoderS3_0", "fnet"),
                                ("FeatureEncoderS3_1", "cnet")):
        for flax_frag, torch_frag in _stem_rules(torch_enc).items():
            rules[f"{flax_enc}._Stem_0.{flax_frag}"] = torch_frag
        rules[f"{flax_enc}.Conv_0"] = f"{torch_enc}.conv2"

    step = "ScanCheckpoint_RaftStep_0"
    rules |= _update_block_rules(f"{step}.BasicUpdateBlock_0", "update_block")

    # the upsampling network lives outside the scan (batched application)
    rules["Up8Network_0.Conv_0"] = "update_block.mask.0"
    rules["Up8Network_0.Conv_1"] = "update_block.mask.2"

    return rules


def _fill_variables(variables, torch_state, rules):
    """Walk the flax tree, pulling each leaf from the torch state dict."""
    from raft_meets_dicl_tpu.metrics.functional import tree_named_leaves

    used = set()
    filled = {"params": {}, "batch_stats": {}}

    def assign(col, path, value, expect_shape):
        if value.shape != tuple(expect_shape):
            raise ValueError(
                f"shape mismatch at {'.'.join(path)}: torch {value.shape} "
                f"vs flax {tuple(expect_shape)}"
            )
        node = filled[col]
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = value.astype(np.float32)

    for name, leaf in tree_named_leaves(variables):
        col, *path = name.split(".")
        module_path = ".".join(path[:-1])
        leaf_name = path[-1]

        if module_path not in rules:
            raise KeyError(f"no conversion rule for flax module '{module_path}'")
        torch_mod = rules[module_path]

        if col == "params":
            if leaf_name == "kernel":
                src = f"{torch_mod}.weight"
                transform = (_conv_t if path[-2].startswith("ConvTranspose")
                             else _conv)
                value = transform(torch_state[src])
            elif leaf_name == "bias":
                src = f"{torch_mod}.bias"
                value = torch_state[src]
            elif leaf_name == "scale":
                src = f"{torch_mod}.weight"
                value = torch_state[src]
            else:
                raise KeyError(f"unhandled param leaf '{leaf_name}'")
        else:  # batch_stats
            src = f"{torch_mod}.running_mean" if leaf_name == "mean" \
                else f"{torch_mod}.running_var"
            value = torch_state[src]

        used.add(src)
        assign(col, path, value, leaf.shape)

    unused = {
        k for k in torch_state
        if k not in used and not k.endswith("num_batches_tracked")
    }
    return filled, unused


def _make_checkpoint(model_id, filled, metadata):
    from flax import serialization

    return Checkpoint(
        model=model_id,
        iteration=Iteration(0, 0, 0),
        metrics={},
        state=State(
            model=serialization.to_state_dict(filled),
            optimizer=None, scaler=None, lr_sched_inst=[], lr_sched_epoch=[],
        ),
        metadata=metadata,
    )


def convert_raft(torch_state, metadata):
    """princeton-vl RAFT (or reference raft/baseline) → ``raft/baseline``."""
    import jax
    import jax.numpy as jnp

    state = _normalize(torch_state, _RAFT_PFX)

    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros", "size": [8, 8]}},
    })
    img = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(0), img, img, iterations=1)

    filled, unused = _fill_variables(variables, state, _raft_rules())
    if unused:
        logging.warning(f"unused torch keys: {sorted(unused)}")

    return _make_checkpoint("raft/baseline", filled, metadata)


# jytime/DICL-Flow naming → canonical prefixes (the same renames the
# reference applies, chkpt_convert.py:54-90, minus its torch-side block
# internals which the flax rules below absorb)
_DICL_PFX = [
    ("module.", ""),
    ("feature.conv_start.", "feature.conv0."),
] + [
    (f"dap_layer{x}.dap_layer.conv.", f"dap{x}.") for x in range(2, 7)
]


def _dicl_block_rules(flax_path, torch_block, transposed=False, bias=False):
    """Leaf rules for one ConvBlock-style block (jytime blocks name their
    children .conv / .bn; plain final convs carry weight+bias directly)."""
    if bias:
        return {f"{flax_path}.Conv_0": torch_block}

    conv_child = "ConvTranspose_0" if transposed else "Conv_0"
    return {
        f"{flax_path}.{conv_child}": f"{torch_block}.conv",
        f"{flax_path}.Norm2d_0.BatchNorm_0": f"{torch_block}.bn",
    }


def _dicl_rules():
    """flax module path → canonical torch path for ``dicl/baseline``.

    The GA-Net hourglass is parametric here (one FeatureEncoderGa) while
    jytime unrolls it — creation order fixes the suffix correspondence:
    stem ConvBlock_0..2, down ladder ConvBlock_3..8 = conv1a..6a, first up
    ladder GaT_0..5 = deconv6a..1a, second down GaConv_0..5 = conv1b..6b,
    final up GaT_6..10 = deconv6b..2b with heads ConvBlock_9..13 =
    outconv6..2. FlowLevel_0..4 = lvl6..lvl2 (coarse→fine creation).
    """
    enc = "FeatureEncoderGa_0"
    rules = {}

    for i in range(3):  # stem
        rules |= _dicl_block_rules(f"{enc}.ConvBlock_{i}", f"feature.conv0.{i}")

    for i in range(1, 7):  # first down ladder
        rules |= _dicl_block_rules(f"{enc}.ConvBlock_{i + 2}",
                                   f"feature.conv{i}a")

    def ga_rules(flax_path, torch_block, transposed):
        first = "ConvTranspose_0" if transposed else "Conv_0"
        second = "Conv_0" if transposed else "Conv_1"
        return {
            f"{flax_path}.{first}": f"{torch_block}.conv1.conv",
            f"{flax_path}.{second}": f"{torch_block}.conv2.conv",
            f"{flax_path}.Norm2d_0.BatchNorm_0": f"{torch_block}.conv2.bn",
        }

    for n, i in enumerate(range(6, 0, -1)):  # first up ladder
        rules |= ga_rules(f"{enc}.GaConv2xBlockTransposed_{n}",
                          f"feature.deconv{i}a", True)

    for i in range(1, 7):  # second down ladder
        rules |= ga_rules(f"{enc}.GaConv2xBlock_{i - 1}",
                          f"feature.conv{i}b", False)

    for n, i in enumerate(range(6, 1, -1)):  # final up ladder + heads
        rules |= ga_rules(f"{enc}.GaConv2xBlockTransposed_{n + 6}",
                          f"feature.deconv{i}b", True)
        rules |= _dicl_block_rules(f"{enc}.ConvBlock_{n + 9}",
                                   f"feature.outconv{i}")

    # flow levels, coarse→fine: FlowLevel_0 = lvl 6 ... FlowLevel_4 = lvl 2
    ctx_layers = {6: 3, 5: 4, 4: 5, 3: 6, 2: 6}
    for idx, lvl in enumerate(range(6, 1, -1)):
        fl = f"FlowLevel_{idx}"
        mnet = f"matching{lvl}.match"

        for i in range(4):
            rules |= _dicl_block_rules(f"{fl}.MatchingNet_0.ConvBlock_{i}",
                                       f"{mnet}.{i}")
        rules |= _dicl_block_rules(f"{fl}.MatchingNet_0.ConvBlockTransposed_0",
                                   f"{mnet}.4", transposed=True)
        rules |= _dicl_block_rules(f"{fl}.MatchingNet_0", f"{mnet}.5",
                                   bias=True)

        rules[f"{fl}.DisplacementAwareProjection_0.Conv_0"] = f"dap{lvl}"

        n_ctx = ctx_layers[lvl]
        for i in range(n_ctx):
            rules |= _dicl_block_rules(f"{fl}.CtfContextNet_0.ConvBlock_{i}",
                                       f"context_net{lvl}.{i}")
        rules |= _dicl_block_rules(f"{fl}.CtfContextNet_0",
                                   f"context_net{lvl}.{n_ctx}", bias=True)

    return rules


def convert_dicl(torch_state, metadata):
    """jytime/DICL-Flow checkpoint → ``dicl/baseline``."""
    import jax
    import jax.numpy as jnp

    state = _normalize(torch_state, _DICL_PFX)

    spec = models.load({
        "name": "DICL baseline", "id": "dicl/baseline",
        "model": {
            "type": "dicl/baseline",
            "parameters": {
                "displacement-range": {
                    f"level-{lvl}": [3, 3] for lvl in range(2, 7)
                },
            },
        },
        "loss": {"type": "dicl/multiscale",
                 "arguments": {"weights": [1.0] * 10}},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [128, 128]}},
    })
    img = jnp.zeros((1, 128, 128, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(0), img, img)

    filled, unused = _fill_variables(variables, state, _dicl_rules())
    if unused:
        logging.warning(f"unused torch keys: {sorted(unused)}")

    return _make_checkpoint("dicl/baseline", filled, metadata)


# ---------------------------------------------------------------------------
# raft+dicl coarse-to-fine (reference raft_dicl_ctf_l{2,3,4}.py) — these
# checkpoints only come from the reference framework itself, so the source
# naming is its module tree (fnet/cnet pyramid, corr_{lvl}, update_block,
# upnet, upnet_h).

# reference BasicUpdateBlock children are .enc/.gru/.flow (raft.py:283-285);
# normalize the shared and the per-level spellings alike
_CTF_PFX = [
    ("module.", ""),
    ("update_block.enc.", "update_block.encoder."),
    ("update_block.flow.", "update_block.flow_head."),
] + [
    (f"update_block_{lvl}.{old}", f"update_block_{lvl}.{new}")
    for lvl in range(3, 7)
    for old, new in (("enc.", "encoder."), ("flow.", "flow_head."))
]


def _pyramid_rules(flax_enc, torch_enc, levels):
    """Rules for one FeatureEncoderPyramid against a reference p3x encoder
    (p34/p35/p36: stem layer1-3, heads out3..out6 with growing widths,
    inter-level stages layer4..layer6)."""
    rules = {}
    for frag, tgt in _stem_rules(torch_enc).items():
        rules[f"{flax_enc}._Stem_0.{frag}"] = tgt

    for i in range(levels):
        head = f"{flax_enc}.EncoderOutputNet_{i}"
        out = f"{torch_enc}.out{i + 3}"
        rules[f"{head}.Conv_0"] = f"{out}.conv1"
        rules[f"{head}.Norm2d_0.BatchNorm_0"] = f"{out}.norm1"
        rules[f"{head}.Conv_1"] = f"{out}.conv2"

    for j in range(levels - 1):
        for k in range(2):
            blk = f"{flax_enc}.ResidualBlock_{2 * j + k}"
            tgt = f"{torch_enc}.layer{4 + j}.{k}"
            rules[f"{blk}.Conv_0"] = f"{tgt}.conv1"
            rules[f"{blk}.Conv_1"] = f"{tgt}.conv2"
            rules[f"{blk}.Conv_2"] = f"{tgt}.downsample.0"
            rules[f"{blk}.Norm2d_0.BatchNorm_0"] = f"{tgt}.norm1"
            rules[f"{blk}.Norm2d_1.BatchNorm_0"] = f"{tgt}.norm2"
            rules[f"{blk}.Norm2d_2.BatchNorm_0"] = f"{tgt}.downsample.1"
    return rules


def _cmod_rules(flax_path, torch_path):
    """Rules for one DICL CorrelationModule (MatchingNet hourglass + DAP)."""
    rules = {}
    mnet = f"{flax_path}.MatchingNet_0"
    for i in range(4):
        rules[f"{mnet}.ConvBlock_{i}.Conv_0"] = f"{torch_path}.mnet.{i}.0"
        rules[f"{mnet}.ConvBlock_{i}.Norm2d_0.BatchNorm_0"] = \
            f"{torch_path}.mnet.{i}.1"
    rules[f"{mnet}.ConvBlockTransposed_0.ConvTranspose_0"] = \
        f"{torch_path}.mnet.4.0"
    rules[f"{mnet}.ConvBlockTransposed_0.Norm2d_0.BatchNorm_0"] = \
        f"{torch_path}.mnet.4.1"
    rules[f"{mnet}.Conv_0"] = f"{torch_path}.mnet.5"
    rules[f"{flax_path}.DisplacementAwareProjection_0.Conv_0"] = \
        f"{torch_path}.dap.conv1"
    return rules


def _update_block_rules(flax_path, torch_path):
    """Rules for one (normalized) BasicUpdateBlock."""
    rules = {}
    enc = f"{flax_path}.BasicMotionEncoder_0"
    for i, name in enumerate(("convc1", "convc2", "convf1", "convf2", "conv")):
        rules[f"{enc}.Conv_{i}"] = f"{torch_path}.encoder.{name}"
    gru = f"{flax_path}.SepConvGru_0"
    for i, name in enumerate(("convz1", "convr1", "convq1",
                              "convz2", "convr2", "convq2")):
        rules[f"{gru}.Conv_{i}"] = f"{torch_path}.gru.{name}"
    head = f"{flax_path}.FlowHead_0"
    rules[f"{head}.Conv_0"] = f"{torch_path}.flow_head.conv1"
    rules[f"{head}.Conv_1"] = f"{torch_path}.flow_head.conv2"
    return rules


def _ctf_rules(levels, share_dicl, share_rnn, upsample_hidden):
    """flax module path → (normalized) torch path for raft+dicl/ctf-l*.

    Flax submodule suffixes follow creation order in
    RaftPlusDiclCtfModule.__call__ — coarse→fine over
    ``level_ids = (levels+2 .. 3)``, so suffix i corresponds to reference
    ``corr_{level_ids[i]}`` / ``update_block_{level_ids[i]}``.
    """
    level_ids = tuple(range(levels + 2, 2, -1))
    rules = {}

    rules |= _pyramid_rules("FeatureEncoderPyramid_0", "fnet", levels)
    rules |= _pyramid_rules("FeatureEncoderPyramid_1", "cnet", levels)

    for i, lvl in enumerate(level_ids):
        rules |= _cmod_rules(
            f"CorrelationModule_{0 if share_dicl else i}",
            "corr" if share_dicl else f"corr_{lvl}",
        )
        rules |= _update_block_rules(
            f"BasicUpdateBlock_{0 if share_rnn else i}",
            "update_block" if share_rnn else f"update_block_{lvl}",
        )

    for i, lvl in enumerate(level_ids[1:]):
        flax_h = 0 if share_rnn else i
        # the reference l2 variant has a single transition and names its
        # upsampler 'upnet_h' regardless of sharing (raft_dicl_ctf_l2.py:68)
        torch_h = "upnet_h" if share_rnn or levels == 2 else f"upnet_h_{lvl}"
        if upsample_hidden == "bilinear":
            rules[f"HUpBilinear_{flax_h}.Conv_0"] = f"{torch_h}.conv1"
        elif upsample_hidden == "crossattn":
            for j, name in enumerate(("conv_q", "conv_k", "conv_v_prev",
                                      "conv_v_init", "conv_out")):
                rules[f"HUpCrossAttn_{flax_h}.Conv_{j}"] = f"{torch_h}.{name}"

    rules["Up8Network_0.Conv_0"] = "upnet.conv1"
    rules["Up8Network_0.Conv_1"] = "upnet.conv2"
    return rules


def convert_raft_dicl(torch_state, metadata):
    """Reference raft+dicl/ctf-l{2,3,4} checkpoint → same model id here.

    Pyramid depth, module sharing, and the hidden-state upsampler are
    auto-detected from the state-dict key set.
    """
    import jax
    import jax.numpy as jnp

    state = _normalize(torch_state, _CTF_PFX)

    # p34/p35/p36 carry heads out3..out{levels+2}
    levels = max(
        lvl for lvl in (4, 5, 6)
        if any(k.startswith(f"fnet.out{lvl}.") for k in state)
    ) - 2
    share_dicl = any(k.startswith("corr.") for k in state)
    share_rnn = any(k.startswith("update_block.") for k in state)
    if any(k.startswith("upnet_h.conv_q") for k in state) or \
            any(k.startswith("upnet_h_4.conv_q") for k in state):
        upsample_hidden = "crossattn"
    elif any(k.startswith(("upnet_h.", "upnet_h_4.")) for k in state):
        upsample_hidden = "bilinear"
    else:
        upsample_hidden = "none"

    model_id = f"raft+dicl/ctf-l{levels}"
    pad = 8 * 2 ** (levels - 1)

    spec = models.load({
        "name": f"RAFT+DICL ctf-l{levels}", "id": model_id,
        "model": {
            "type": model_id,
            "parameters": {
                "share-dicl": share_dicl,
                "share-rnn": share_rnn,
                "upsample-hidden": upsample_hidden,
            },
        },
        "loss": {"type": "raft+dicl/mlseq"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [pad, pad]}},
    })
    # the coarsest-level maps must have even extent (MatchingNet's
    # stride-2 + 2x-transposed round trip), so trace at 2·pad multiples
    img = jnp.zeros((1, 2 * pad, 4 * pad, 3), jnp.float32)
    variables = spec.model.init(
        jax.random.PRNGKey(0), img, img, iterations=(1,) * levels)

    filled, unused = _fill_variables(
        variables, state,
        _ctf_rules(levels, share_dicl, share_rnn, upsample_hidden))
    if unused:
        logging.warning(f"unused torch keys: {sorted(unused)}")

    return _make_checkpoint(model_id, filled, metadata)


CONVERTERS = {
    "raft": convert_raft,
    "dicl": convert_dicl,
    "raft+dicl": convert_raft_dicl,
}


def main():
    utils.logging.setup()

    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Convert model checkpoint formats", formatter_class=fmtcls)
    parser.add_argument("-i", "--input", required=True,
                        help="input torch checkpoint file")
    parser.add_argument("-o", "--output", required=True,
                        help="output checkpoint file")
    parser.add_argument("-f", "--format", required=True,
                        choices=sorted(CONVERTERS), help="input format")

    args = parser.parse_args()

    metadata = {
        "timestamp": datetime.now().isoformat(),
        "source": f"file://{Path(args.input).resolve()}",
    }

    logging.info(f"loading checkpoint, file: '{args.input}'")
    import torch

    state = torch.load(args.input, map_location="cpu", weights_only=True)
    if "state_dict" in state:
        state = state["state_dict"]

    logging.info("converting...")
    chkpt = CONVERTERS[args.format](state, metadata)

    logging.info(f"saving checkpoint, file: '{args.output}'")
    chkpt.save(args.output)


if __name__ == "__main__":
    main()
