#!/usr/bin/env python3
"""graftlint CLI: the TPU-hazard lint pass + HLO program auditor.

Static pass (default) — AST rules over the repo's Python surface
(host-sync, tracer-branch, f32-literal, env-knob, env-docs), with
line-level ``# graftlint: disable=<rule> -- <reason>`` suppressions and
the committed ``graftlint-baseline.json`` of grandfathered findings.
Exit code is 0 iff no finding is *open* (suppressed/baselined don't
fail) — so CI stays green on the committed tree and goes red the moment
a new hazard lands without a justification.

HLO pass (``--hlo``) — lowers the registered flagship step programs
twice each and audits fingerprint stability, collective counts
(post-GSPMD), f32 convolutions, and baked-in constants. Needs jax; the
static pass does not. (The quantitative cost/budget gate lives in
``scripts/graftcost.py``.)

    python scripts/graftlint.py                  # lint, human-readable
    python scripts/graftlint.py --format json    # machine-readable
    python scripts/graftlint.py --baseline b.json --root /path/to/repo
    python scripts/graftlint.py --prune          # drop stale baseline entries
    python scripts/graftlint.py --fix-knob-table # regenerate README table
    python scripts/graftlint.py --hlo            # add the program audit
    python scripts/graftlint.py --events out.jsonl  # findings as telemetry

Exit codes: 0 — no open findings (suppressed/baselined/stale don't
fail); 1 — at least one open finding; 2 — usage or config error
(unreadable baseline, bad flags).
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.analysis import lint  # noqa: E402


def fix_knob_table(root):
    from raft_meets_dicl_tpu.utils import env

    readme = Path(root) / "README.md"
    text = readme.read_text()
    new = env.splice_readme(text)
    if new == text:
        print("README knob table already up to date")
        return 0
    readme.write_text(new)
    print("README knob table regenerated from utils.env.KNOBS")
    return 0


def prune_baseline(root, baseline_path):
    """Rewrite the baseline with this run's unused entries removed.

    The run itself decides staleness (an entry is stale iff it matched
    no finding), so pruning is always relative to the *current* tree.
    The file's header comment and version ride through untouched.
    """
    path = Path(baseline_path) if baseline_path else \
        Path(root) / lint.BASELINE_NAME
    if not path.exists():
        print(f"no baseline at {path}; nothing to prune")
        return 0
    baseline = lint.Baseline.load(path)
    lint.run(root, baseline=baseline)
    stale = baseline.unused_entries()
    if not stale:
        print(f"{path}: no stale entries; baseline unchanged")
        return 0
    data = json.loads(path.read_text())
    keep = [e for e in baseline.entries if e not in stale]
    data["entries"] = keep
    path.write_text(json.dumps(data, indent=2) + "\n")
    print(f"{path}: pruned {len(stale)} stale entr"
          f"{'y' if len(stale) == 1 else 'ies'}, {len(keep)} kept")
    for e in stale:
        print(f"  dropped: {e['rule']} @ {e['glob']}")
    return 0


def json_report(report, hlo_reports=None):
    """Stable machine-readable schema for CI consumers. Contract:
    ``schema`` bumps on any incompatible change; ``exit_code`` mirrors
    the process exit code (0 iff no open finding); findings carry
    rule/path/line/severity/status/message (+justification when
    suppressed or baselined); ``stale_baseline_entries`` lists baseline
    entries that matched nothing."""
    out = report.to_dict()
    out["schema"] = 1
    out["exit_code"] = 0 if report.ok else 1
    if hlo_reports is not None:
        out["hlo"] = hlo_reports
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="exit codes: 0 no open findings; 1 open findings; "
               "2 usage/config error")
    ap.add_argument("--root", default=str(Path(__file__).parent.parent),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: <root>/"
                         f"{lint.BASELINE_NAME} if present)")
    ap.add_argument("--format", choices=("text", "json"), default=None,
                    help="report format (default: text)")
    ap.add_argument("--json", action="store_true",
                    help="shorthand for --format json")
    ap.add_argument("--prune", action="store_true",
                    help="rewrite the baseline without stale entries "
                         "(those matching nothing on this tree) and exit")
    ap.add_argument("--fix-knob-table", action="store_true",
                    help="regenerate the README env-knob table and exit")
    ap.add_argument("--hlo", action="store_true",
                    help="also lower + audit the registered flagship "
                         "programs (requires jax)")
    ap.add_argument("--events", default=None, metavar="FILE",
                    help="append findings as 'lint' telemetry events")
    args = ap.parse_args(argv)

    if args.fix_knob_table:
        return fix_knob_table(args.root)
    if args.prune:
        return prune_baseline(args.root, args.baseline)

    baseline = (lint.Baseline.load(args.baseline)
                if args.baseline else None)
    report = lint.run(args.root, baseline=baseline)

    hlo_reports, hlo_findings = [], []
    if args.hlo:
        from raft_meets_dicl_tpu.analysis import hlo

        hlo_reports, hlo_findings = hlo.audit_registry()
        report.findings.extend(hlo_findings)

    if args.events:
        from raft_meets_dicl_tpu import telemetry

        tele = telemetry.Telemetry(args.events)
        try:
            lint.emit_events(report, tele)
        finally:
            tele.close()

    fmt = args.format or ("json" if args.json else "text")
    if fmt == "json":
        json.dump(json_report(report, hlo_reports if args.hlo else None),
                  sys.stdout, indent=2)
        print()
    else:
        print(lint.render_text(report))
        if args.hlo:
            from raft_meets_dicl_tpu.analysis import hlo

            print(hlo.render_reports(hlo_reports))

    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
