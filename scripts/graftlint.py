#!/usr/bin/env python3
"""graftlint CLI: the TPU-hazard lint pass + HLO program auditor.

Static pass (default) — AST rules over the repo's Python surface
(host-sync, tracer-branch, f32-literal, env-knob, env-docs), with
line-level ``# graftlint: disable=<rule> -- <reason>`` suppressions and
the committed ``graftlint-baseline.json`` of grandfathered findings.
Exit code is 0 iff no finding is *open* (suppressed/baselined don't
fail) — so CI stays green on the committed tree and goes red the moment
a new hazard lands without a justification.

HLO pass (``--hlo``) — lowers the registered flagship step programs
twice each and audits fingerprint stability, collective counts
(post-GSPMD), f32 convolutions, and baked-in constants. Needs jax; the
static pass does not.

    python scripts/graftlint.py                  # lint, human-readable
    python scripts/graftlint.py --json           # machine-readable
    python scripts/graftlint.py --baseline b.json --root /path/to/repo
    python scripts/graftlint.py --fix-knob-table # regenerate README table
    python scripts/graftlint.py --hlo            # add the program audit
    python scripts/graftlint.py --events out.jsonl  # findings as telemetry
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.analysis import lint  # noqa: E402


def fix_knob_table(root):
    from raft_meets_dicl_tpu.utils import env

    readme = Path(root) / "README.md"
    text = readme.read_text()
    new = env.splice_readme(text)
    if new == text:
        print("README knob table already up to date")
        return 0
    readme.write_text(new)
    print("README knob table regenerated from utils.env.KNOBS")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=str(Path(__file__).parent.parent),
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: <root>/"
                         f"{lint.BASELINE_NAME} if present)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    ap.add_argument("--fix-knob-table", action="store_true",
                    help="regenerate the README env-knob table and exit")
    ap.add_argument("--hlo", action="store_true",
                    help="also lower + audit the registered flagship "
                         "programs (requires jax)")
    ap.add_argument("--events", default=None, metavar="FILE",
                    help="append findings as 'lint' telemetry events")
    args = ap.parse_args(argv)

    if args.fix_knob_table:
        return fix_knob_table(args.root)

    baseline = (lint.Baseline.load(args.baseline)
                if args.baseline else None)
    report = lint.run(args.root, baseline=baseline)

    hlo_reports, hlo_findings = [], []
    if args.hlo:
        from raft_meets_dicl_tpu.analysis import hlo

        hlo_reports, hlo_findings = hlo.audit_registry()
        report.findings.extend(hlo_findings)

    if args.events:
        from raft_meets_dicl_tpu import telemetry

        tele = telemetry.Telemetry(args.events)
        try:
            lint.emit_events(report, tele)
        finally:
            tele.close()

    if args.json:
        out = report.to_dict()
        if args.hlo:
            out["hlo"] = hlo_reports
        json.dump(out, sys.stdout, indent=2)
        print()
    else:
        print(lint.render_text(report))
        if args.hlo:
            from raft_meets_dicl_tpu.analysis import hlo

            print(hlo.render_reports(hlo_reports))

    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
