#!/usr/bin/env python3
"""Export TensorBoard scalar series to CSV
(reference scripts/tfdata_to_csv.py)."""

import argparse
import sys
from pathlib import Path

import pandas as pd

sys.path.insert(0, str(Path(__file__).parent.parent))
from raft_meets_dicl_tpu.utils import tfdata  # noqa: E402


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Convert tensorboard scalar data to CSV",
        formatter_class=fmtcls)
    parser.add_argument("-d", "--data", required=True,
                        help="the tensorboard log file")
    parser.add_argument("-t", "--tag", required=True, action="append",
                        help="the tag to export")
    parser.add_argument("-o", "--output", required=True, action="append",
                        help="output file")
    parser.add_argument("-a", "--ewm", type=float,
                        help="alpha for exponential weighted moving average")

    args = parser.parse_args()

    if len(args.output) != len(args.tag):
        raise ValueError("must have one output file per tag")

    print("loading data...")
    df = tfdata.tfdata_scalars_to_pandas(args.data, args.tag)

    print("converting...")
    for output, tag in zip(args.output, args.tag):
        out = pd.DataFrame()
        out["step"] = df.loc[df.tag == tag].step
        out["value"] = df.loc[df.tag == tag].value

        if args.ewm is not None:
            ewm = out["value"].ewm(alpha=args.ewm)
            out["value"] = ewm.mean()
            out["std"] = ewm.std().fillna(value=0.0)

        print(f"writing CSV data to '{output}'")
        out.to_csv(output, index=False)


if __name__ == "__main__":
    main()
