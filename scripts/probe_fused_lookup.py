#!/usr/bin/env python3
"""Measure whether a hand-scheduled Pallas kernel can beat XLA's batched
einsum on the RAFT lookup contraction (PERF.md round-4 "fused lookup+GRU"
spec, VERDICT item 7).

The windowed bilinear lookup is mathematically a batched (K, H2) x
(H2, W2) contraction per source position (ops/corr.py:_lookup_level).
The fused-kernel estimate (>=25 pairs/s for raft/baseline) assumed
hand-scheduling could lift this off the measured ~5 TFLOP/s batched-
tiny-matmul floor. This probe times the exact level-0 contraction at the
bench config three ways:

  A. XLA batched einsum (what the model runs today)
  B. Pallas, per-position serial dots from VMEM-resident rows
  C. Pallas, both lookup stages fused per position (t = wy @ corr,
     out = t @ wx^T) so the intermediate never leaves VMEM
  D. XLA einsum over the u8-quantized volume, dequantized in-register
     as the stage-1 operand (the ops/corr.py quantized-tier branch) —
     same contraction, 1/4 (f32) or 1/2 (bf16) of the volume bytes
     streamed from HBM

If B/C do not beat A, the contraction is MXU-shape-bound — the 9-row
operand uses 9/128 of the systolic array regardless of who schedules
it — and no fused realization can reach the estimate; together with the
VMEM capacity argument (the b6 volume pyramid is ~54 MB/image vs
~16 MB/core VMEM, so an in-VMEM fused loop cannot hold its operand)
this closes the spec with a measured negative result.

    python scripts/probe_fused_lookup.py [--dtype bf16] [--steps 20]
"""

import argparse
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# bench config, level 0: b6 @ 400x720 -> 50x90 coarse grid
B, NI, NJ = 6, 50, 90
K, H2, W2 = 9, 50, 90


def _xla_lookup(wy, corr, wx):
    t = jnp.einsum("bijkh,bijhw->bijkw", wy, corr,
                   preferred_element_type=jnp.float32)
    t = t.astype(wy.dtype)
    return jnp.einsum("bijkw,bijaw->bijka", t, wx,
                      preferred_element_type=jnp.float32)


def _xla_lookup_u8(wy, qvals, scale, wx):
    # the ops/corr.py quantized-tier branch: u8 rows stream from HBM and
    # dequantize in-register as the stage-1 einsum operand (zero point
    # 128); the per-sample scale lands once on the (K, K) output
    deq = qvals.astype(wy.dtype) - jnp.asarray(128, wy.dtype)
    t = jnp.einsum("bijkh,bijhw->bijkw", wy, deq,
                   preferred_element_type=jnp.float32)
    t = t.astype(wy.dtype)
    out = jnp.einsum("bijkw,bijaw->bijka", t, wx,
                     preferred_element_type=jnp.float32)
    return out * scale


def _stage1_kernel(wy_ref, corr_ref, out_ref):
    # one (b, i) row per grid cell: NJ serial (K, H2) x (H2, W2) dots
    for j in range(NJ):
        out_ref[0, 0, j] = jax.lax.dot_general(
            wy_ref[0, 0, j], corr_ref[0, 0, j], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _fused_kernel(wy_ref, corr_ref, wx_ref, out_ref):
    # both lookup stages per position; the (K, W2) intermediate stays in
    # registers/VMEM instead of round-tripping HBM between einsums
    for j in range(NJ):
        t = jax.lax.dot_general(
            wy_ref[0, 0, j], corr_ref[0, 0, j], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[0, 0, j] = jax.lax.dot_general(
            t.astype(wx_ref.dtype), wx_ref[0, 0, j], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)


def _pallas_stage1(wy, corr):
    return pl.pallas_call(
        _stage1_kernel,
        out_shape=jax.ShapeDtypeStruct((B, NI, NJ, K, W2), jnp.float32),
        grid=(B, NI),
        in_specs=[
            pl.BlockSpec((1, 1, NJ, K, H2), lambda b, i: (b, i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, NJ, H2, W2), lambda b, i: (b, i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, NJ, K, W2),
                               lambda b, i: (b, i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(wy.reshape(B, NI, NJ, K, H2), corr)


def _pallas_fused(wy, corr, wx):
    return pl.pallas_call(
        _fused_kernel,
        out_shape=jax.ShapeDtypeStruct((B, NI, NJ, K, K), jnp.float32),
        grid=(B, NI),
        in_specs=[
            pl.BlockSpec((1, 1, NJ, K, H2), lambda b, i: (b, i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, NJ, H2, W2), lambda b, i: (b, i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, NJ, K, W2), lambda b, i: (b, i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, NJ, K, K),
                               lambda b, i: (b, i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(wy.reshape(B, NI, NJ, K, H2), corr, wx.reshape(B, NI, NJ, K, W2))


def _sync(out):
    # on the tunneled axon backend block_until_ready does not reliably
    # wait; a scalar value transfer does (same workaround as bench.py)
    return float(out.ravel()[0])


def _time(fn, *args, steps=20):
    out = fn(*args)  # compile
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / steps, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()
    dt = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32

    rs = np.random.RandomState(0)
    # realistic hat-matrix sparsity: windows around random in-range centers
    cy = rs.rand(B, NI, NJ, 1) * (H2 - 10) + 5
    cx = rs.rand(B, NI, NJ, 1) * (W2 - 10) + 5
    d = np.arange(-4, 5)
    wy = np.maximum(
        0.0, 1.0 - np.abs((cy + d)[..., None] - np.arange(H2))).astype("f4")
    wx = np.maximum(
        0.0, 1.0 - np.abs((cx + d)[..., None] - np.arange(W2))).astype("f4")
    corr = rs.randn(B, NI, NJ, H2, W2).astype("f4")

    wy, wx, corr = (jnp.asarray(a, dt) for a in (wy, wx, corr))

    flops_s1 = 2 * B * NI * NJ * K * H2 * W2
    flops_full = flops_s1 + 2 * B * NI * NJ * K * W2 * K

    xla = jax.jit(_xla_lookup)
    t_a, out_a = _time(xla, wy, corr, wx, steps=args.steps)
    print(f"A  XLA batched einsum (both stages): {t_a * 1e3:8.3f} ms"
          f"  ({flops_full / t_a / 1e12:.2f} TFLOP/s)")

    try:
        p1 = jax.jit(_pallas_stage1)
        t_b, out_b = _time(p1, wy, corr, steps=args.steps)
        print(f"B  Pallas stage-1 dots:              {t_b * 1e3:8.3f} ms"
              f"  ({flops_s1 / t_b / 1e12:.2f} TFLOP/s)")
        # bit-exactness of B is part of the PERF.md claim, so verify it
        # against the same stage-1 contraction XLA runs (f32 accumulate),
        # not just C's end-to-end output
        ref_s1 = jax.jit(lambda w, c: jnp.einsum(
            "bijkh,bijhw->bijkw", w, c,
            preferred_element_type=jnp.float32))(wy, corr)
        err_b = float(jnp.max(jnp.abs(out_b - ref_s1)))
        print(f"   max |B - A| = {err_b:.3e}  (stage-1 intermediate)")
    except Exception as e:  # pragma: no cover - probe reporting
        print(f"B  Pallas stage-1 dots: FAILED ({type(e).__name__}: "
              f"{str(e)[:140]})")

    try:
        pf = jax.jit(_pallas_fused)
        t_c, out_c = _time(pf, wy, corr, wx, steps=args.steps)
        print(f"C  Pallas fused both stages:         {t_c * 1e3:8.3f} ms"
              f"  ({flops_full / t_c / 1e12:.2f} TFLOP/s)")
        err = float(jnp.max(jnp.abs(
            out_c - out_a.reshape(B, NI, NJ, K, K))))
        print(f"   max |C - A| = {err:.3e}")
    except Exception as e:  # pragma: no cover - probe reporting
        print(f"C  Pallas fused both stages: FAILED ({type(e).__name__}: "
              f"{str(e)[:140]})")

    # D answers a byte-bound question, not a FLOP-bound one: the lookup
    # reads the whole volume row set every iteration, so streaming u8
    # moves 1/4 (f32) or 1/2 (bf16) of arm A's bytes. Quantization is
    # a one-time cost at pyramid build, so it stays outside the timer.
    from raft_meets_dicl_tpu.ops import quant as rmq

    level = rmq.quantize_level(jnp.asarray(corr, jnp.float32), "u8")
    scale = level.scale.astype(jnp.float32)
    t_d, out_d = _time(jax.jit(_xla_lookup_u8), wy, level.values, scale,
                       wx, steps=args.steps)
    err_d = float(jnp.max(jnp.abs(out_d - out_a)))
    ratio = jnp.dtype(dt).itemsize  # u8 volume is 1 B/element
    print(f"D  XLA u8 volume, in-reg dequant:    {t_d * 1e3:8.3f} ms"
          f"  ({flops_full / t_d / 1e12:.2f} TFLOP/s)")
    print(f"   max |D - A| = {err_d:.3e}  (step "
          f"{float(jnp.max(level.scale)):.3e}); volume bytes 1/{ratio} "
          f"of arm A")


if __name__ == "__main__":
    main()
