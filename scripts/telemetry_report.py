#!/usr/bin/env python3
"""Render a run's telemetry events into a phase-breakdown report.

Reads the ``events.jsonl`` a training run writes by default (or any file
produced by ``raft_meets_dicl_tpu.telemetry``), validates every record
against the versioned schema, prints per-phase step timing stats
(mean/p95/max/share), compile + persistent-cache counts, the
compiled-programs section (boot cache/AOT directories, per-program AOT
hit/miss/save/fallback counts with bytes and serialize/load ms), SPMD
sharding placement (mesh shape, per-chip vs replicated param/opt bytes),
memory watermarks, and flags anomalies: step-time spikes, recompiles
after warmup, non-finite-guard events, and boots that fell back from an
AOT artifact to a cold JIT.

    python scripts/telemetry_report.py runs/<ts>/events.jsonl
    python scripts/telemetry_report.py runs/<ts>          # finds the file
    python scripts/telemetry_report.py events.jsonl --strict

Given several paths (one per host / restart), each stream is tagged by
its run id and rendered as a merged report instead: a per-host table
(start skew vs the earliest host, median step time, straggler delta vs
the fastest host, goodput) and a merged landmark timeline on the shared
wall clock:

    python scripts/telemetry_report.py runs/host0 runs/host1 runs/host2
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from raft_meets_dicl_tpu.telemetry import report  # noqa: E402


def resolve(path):
    p = Path(path)
    if p.is_dir():
        candidate = p / "events.jsonl"
        if not candidate.exists():
            raise SystemExit(f"no events.jsonl under '{p}'")
        return candidate
    if not p.exists():
        raise SystemExit(f"no such file: '{p}'")
    return p


def run_label(path, used):
    """Tag a stream by its run id: the run directory name (the parent,
    for an events.jsonl path), deduplicated across identical names."""
    p = Path(path)
    base = p.parent.name if p.name == "events.jsonl" else p.stem
    if p.is_dir():
        base = p.name
    label, n = base or str(p), 2
    while label in used:
        label = f"{base}#{n}"
        n += 1
    used.add(label)
    return label


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render a telemetry events.jsonl into a report")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="events.jsonl file or run directory; several "
                         "paths (one per host) render a merged report")
    ap.add_argument("--warmup-steps", type=int,
                    default=report.DEFAULT_WARMUP_STEPS,
                    help="compiles after this many in-stage steps are "
                         "flagged as recompiles [default: %(default)s]")
    ap.add_argument("--spike-factor", type=float,
                    default=report.DEFAULT_SPIKE_FACTOR,
                    help="flag steps slower than this multiple of the "
                         "stage median [default: %(default)s]")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on schema errors or anomalies")
    args = ap.parse_args(argv)

    if len(args.paths) > 1:
        # multi-run merge: tag each stream by run id, render the
        # cross-host table + merged timeline
        runs, all_errors, all_flags = [], [], []
        used = set()
        for path in args.paths:
            label = run_label(path, used)
            events, errors = report.load_events(resolve(path))
            runs.append({"label": label, "events": events})
            all_errors.extend((label, n, msg) for n, msg in errors)
            all_flags.extend(
                (label, f) for f in report.find_anomalies(
                    events, warmup_steps=args.warmup_steps,
                    spike_factor=args.spike_factor))
        print(report.render_merged(runs))
        if all_flags:
            print(f"\n== anomalies ({len(all_flags)}) ==")
            for label, flag in all_flags:
                print(f"  ! [{label}] {flag}")
        for label, n, msg in all_errors:
            print(f"  schema error [{label}] line {n}: {msg}")
        if args.strict and (all_errors or all_flags):
            return 1
        return 0

    skipped = []
    events, errors = report.load_events(resolve(args.paths[0]),
                                        skipped=skipped)
    print(report.render(events, errors, warmup_steps=args.warmup_steps,
                        spike_factor=args.spike_factor))
    if skipped:
        # forward compat, not corruption: records from a newer producer
        # (unknown kind / newer schema minor) — never fails --strict
        print(f"\nskipped {len(skipped)} record(s) from a newer producer "
              f"(first: line {skipped[0][0]}: {skipped[0][1]})")

    flags = report.find_anomalies(events, warmup_steps=args.warmup_steps,
                                  spike_factor=args.spike_factor)
    if args.strict and (errors or flags):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
