#!/usr/bin/env python3
"""Generate a synthetic FlyingChairs-like optical-flow dataset + run configs.

No real dataset ships with this environment, so the trained-quality
evidence (QUALITY.md) uses this generator: textured objects moving with
independent affine transforms over an affinely-moving background, with
the exact forward flow composited by z-order — the same construction
idea as FlyingChairs (objects + affine motions, dense ground truth),
procedurally textured. The mapping image-pair -> flow is fully learnable,
so a correct training stack must drive validation EPE down by orders of
magnitude; random-noise data (as used in the CLI smoke tests) cannot
show that.

Writes, under --out (default /tmp/synth-chairs):
  data/{train,val}/{seq:05d}-img_{1,2}.png  + -flow.flo
  dataset.yaml / train.yaml / val.yaml / strategy.yaml / inspect.yaml

then prints the main.py train invocation.

Reference analogue: the FlyingChairs stage of the baseline schedule
(reference cfg/strategy/baseline/raft/s0-chairs2.yaml; dataset layout
src/data/dataset.py generic layout).
"""

import argparse
import os
import sys

import cv2
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from raft_meets_dicl_tpu.data import io  # noqa: E402

H, W = 384, 512
PAD = 96  # texture canvas margin; must exceed max |displacement|


def _smooth_texture(rs, h, w, cells):
    """Colorful band-limited texture: low-res noise upsampled cubically."""
    small = rs.rand(max(2, h // cells), max(2, w // cells), 3).astype(np.float32)
    tex = cv2.resize(small, (w, h), interpolation=cv2.INTER_CUBIC)
    return np.clip(tex, 0.0, 1.0)


def _rand_affine(rs, max_t, max_rot_deg, scale_lo, scale_hi, cx, cy):
    """2x3 forward map about (cx, cy): p2 = A @ p1 + b."""
    ang = np.deg2rad(rs.uniform(-max_rot_deg, max_rot_deg))
    s = rs.uniform(scale_lo, scale_hi)
    ca, sa = np.cos(ang) * s, np.sin(ang) * s
    A = np.array([[ca, -sa], [sa, ca]], np.float64)
    t = rs.uniform(-max_t, max_t, size=2)
    c = np.array([cx, cy], np.float64)
    b = c - A @ c + t
    return np.hstack([A, b[:, None]]).astype(np.float64)


def _flow_of(M, xs, ys):
    """Forward flow of affine M evaluated at pixel coords (xs, ys)."""
    fx = (M[0, 0] - 1.0) * xs + M[0, 1] * ys + M[0, 2]
    fy = M[1, 0] * xs + (M[1, 1] - 1.0) * ys + M[1, 2]
    return np.stack([fx, fy], axis=-1).astype(np.float32)


def _object_mask(rs, h, w):
    """Random filled convex polygon or ellipse, anywhere in frame."""
    mask = np.zeros((h, w), np.uint8)
    cx, cy = rs.uniform(0.15, 0.85) * w, rs.uniform(0.15, 0.85) * h
    size = rs.uniform(30, 90)
    if rs.rand() < 0.5:
        axes = (int(size), int(size * rs.uniform(0.4, 1.0)))
        cv2.ellipse(mask, (int(cx), int(cy)), axes,
                    rs.uniform(0, 180), 0, 360, 1, -1)
    else:
        k = rs.randint(3, 7)
        ang = np.sort(rs.uniform(0, 2 * np.pi, k))
        r = size * rs.uniform(0.5, 1.0, k)
        pts = np.stack([cx + r * np.cos(ang), cy + r * np.sin(ang)], -1)
        cv2.fillPoly(mask, [pts.astype(np.int32)], 1)
    return mask.astype(bool)


def make_pair(seed):
    rs = np.random.RandomState(seed)
    ch, cw = H + 2 * PAD, W + 2 * PAD

    # background: moving texture on an oversized canvas so no border
    # content ever enters the frame (keeps the affine flow exact)
    tex = _smooth_texture(rs, ch, cw, cells=rs.randint(16, 48))
    m_bg = _rand_affine(rs, max_t=16, max_rot_deg=4,
                        scale_lo=0.95, scale_hi=1.05,
                        cx=cw / 2, cy=ch / 2)
    bg2 = cv2.warpAffine(tex, m_bg[:2], (cw, ch), flags=cv2.INTER_LINEAR)

    img1 = tex[PAD:PAD + H, PAD:PAD + W].copy()
    img2 = bg2[PAD:PAD + H, PAD:PAD + W].copy()

    ys, xs = np.mgrid[0:H, 0:W].astype(np.float64)
    # canvas coords of frame pixels (affine flow is coord-dependent)
    flow = _flow_of(m_bg, xs + PAD, ys + PAD)

    for _ in range(rs.randint(2, 5)):
        mask1 = _object_mask(rs, H, W)
        if mask1.sum() < 64:
            continue
        otex = _smooth_texture(rs, H, W, cells=rs.randint(6, 24))
        m_obj = _rand_affine(rs, max_t=28, max_rot_deg=12,
                             scale_lo=0.9, scale_hi=1.12,
                             cx=W / 2, cy=H / 2)
        layer2 = cv2.warpAffine(otex, m_obj[:2], (W, H))
        mask2 = cv2.warpAffine(mask1.astype(np.float32), m_obj[:2],
                               (W, H)) > 0.5
        img1[mask1] = otex[mask1]
        img2[mask2] = layer2[mask2]
        flow[mask1] = _flow_of(m_obj, xs, ys)[mask1]

    to8 = lambda im: (np.clip(im, 0, 1) * 255).astype(np.uint8)  # noqa: E731
    return to8(img1), to8(img2), flow


DATASET_YAML = """\
name: Synthetic Chairs
id: synth-chairs
path: ./data

layout:
  type: generic
  images: '{split}/{seq:05d}-img_{idx:d}.png'
  flows: '{split}/{seq:05d}-flow.flo'
  key: '{split}/{seq:05d}'

parameters:
  split:
    values: [train, val]
    sub: split
"""

SOURCE_YAML = """\
type: augment

augmentations:
  - type: crop
    size: [{cw}, {ch}]

source:
  type: cache
  source:
    type: dataset
    spec: ./dataset.yaml
    parameters:
      split: {split}
"""

VAL_YAML = """\
type: cache
source:
  type: dataset
  spec: ./dataset.yaml
  parameters:
    split: val
"""

STRATEGY_YAML = """\
name: synth-chairs quality run
id: dev/synth-chairs

mode: continuous

stages:
  - name: "Synthetic Chairs ({epochs} epochs)"
    id: train/synth-chairs-0

    data:
      epochs: {epochs}
      batch-size: {batch}
      source: ./train.yaml

    validation:
      source: ./val.yaml
      batch-size: 2
      images: [0, 1, 2, 3]

    optimizer:
      type: adam-w
      parameters:
        lr: &lr {lr}
        weight_decay: 1.0e-4
        eps: 1.0e-8

    lr-scheduler:
      instance:
        - type: one-cycle
          parameters:
            max_lr: *lr
            total_steps: '{{n_epochs}} * {{n_batches}} + 10'
            pct_start: 0.05
            cycle_momentum: false
            anneal_strategy: linear

    gradient:
      clip:
        type: norm
        value: 1.0
"""

INSPECT_YAML = """\
metrics:
  - prefix: 'Train:S{n_stage}:{id_stage}/'
    frequency: 10
    metrics:
      - type: epe
      - type: loss
      - type: learning-rate

checkpoints:
  path: checkpoints/
  name: 'synth-chairs-s{n_stage}_e{n_epoch}_b{n_steps}-epe{m_EndPointError_mean:.4f}.ckpt'
  compare: ['{m_EndPointError_mean}']
  keep:
    latest: 2
    best: 2

validation:
  - type: strategy
    frequency: epoch
    checkpoint: true
    tb-metrics-prefix: 'Validation:S{n_stage}:{id_stage}:{id_val}/'
    metrics:
      - reduce: mean
        metric:
          type: epe
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/synth-chairs")
    ap.add_argument("--train", type=int, default=1000)
    ap.add_argument("--val", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--batch", type=int, default=6)
    ap.add_argument("--lr", type=float, default=4.0e-4)
    args = ap.parse_args()

    for split, n, base in (("train", args.train, 0),
                           ("val", args.val, 10_000_000)):
        d = os.path.join(args.out, "data", split)
        os.makedirs(d, exist_ok=True)
        for i in range(n):
            img1, img2, flow = make_pair(base + i)
            cv2.imwrite(os.path.join(d, f"{i:05d}-img_1.png"), img1[..., ::-1])
            cv2.imwrite(os.path.join(d, f"{i:05d}-img_2.png"), img2[..., ::-1])
            io.write_flow_mb(os.path.join(d, f"{i:05d}-flow.flo"), flow)
            if i % 200 == 0:
                print(f"{split}: {i}/{n}", flush=True)

    cfg = {
        "dataset.yaml": DATASET_YAML,
        "train.yaml": SOURCE_YAML.format(cw=496, ch=368, split="train"),
        "val.yaml": VAL_YAML,
        "strategy.yaml": STRATEGY_YAML.format(
            epochs=args.epochs, batch=args.batch, lr=args.lr),
        "inspect.yaml": INSPECT_YAML,
    }
    for name, text in cfg.items():
        with open(os.path.join(args.out, name), "w") as f:
            f.write(text)

    print("dataset ready. train with:")
    print(f"  python main.py train -d {args.out}/strategy.yaml "
          f"-m cfg/model/raft-baseline.yaml -i {args.out}/inspect.yaml "
          f"-o runs-quality")


if __name__ == "__main__":
    main()
