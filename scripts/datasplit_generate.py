#!/usr/bin/env python3
"""Generate dataset split files (values 0/1)
(reference scripts/datasplit_generate.py:14-57)."""

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))
import raft_meets_dicl_tpu.data as data  # noqa: E402


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Generate split files (values: 0/1)",
        formatter_class=fmtcls)
    parser.add_argument("-d", "--data", required=True,
                        help="the data source spec to generate the split for")
    parser.add_argument("-o", "--output", required=True, help="output file")
    parser.add_argument("-n", "--number", type=int, metavar="N",
                        help="select exactly N elements at random")
    parser.add_argument("-p", "--probability", type=float, metavar="P",
                        help="select elements with probability P")
    parser.add_argument("-k", "--key", metavar="K",
                        help="select elements whose sample id contains K "
                             "(comma-separated alternatives)")

    args = parser.parse_args()

    n_methods = sum(map(bool, (args.number, args.probability, args.key)))
    if n_methods > 1:
        raise ValueError("cannot set multiple methods at the same time")
    if n_methods == 0:
        raise ValueError("one of --number/--probability/--key must be set")

    source = data.load(args.data)
    n = len(source)

    if args.number:
        choices = np.random.choice(np.arange(n), args.number, replace=False)
        split = np.zeros(n, dtype=bool)
        split[choices] = True
    elif args.probability:
        split = np.random.rand(n) < args.probability
    else:
        keys = args.key.split(",")
        split = [
            any(k in str(m.sample_id) for k in keys for m in meta)
            for _, _, _, _, meta in source
        ]

    with open(args.output, "w") as fd:
        for x in split:
            fd.write(f"{'1' if x else '0'}\n")


if __name__ == "__main__":
    main()
