#!/usr/bin/env python3
"""Dump correlation cost-volume visualizations as tiled images.

Capability parity with reference scripts/visualize_costs.py:27-70+, jit
edition: instead of registering torch forward hooks on the corr modules,
the forward pass runs with flax ``capture_intermediates`` and every
captured (B, H, W, du, dv) cost volume is rendered as a (dy·H, dx·W) tiled
image through a matplotlib colormap.

Usage:
    ./scripts/visualize_costs.py -d data.yaml -m model.yaml -c chkpt.ckpt \
        -o costs/ [--filter DisplacementAwareProjection]
"""

import argparse
import sys
from pathlib import Path

import matplotlib
import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

import raft_meets_dicl_tpu.data as data  # noqa: E402
import raft_meets_dicl_tpu.models as models  # noqa: E402
from raft_meets_dicl_tpu import strategy, utils  # noqa: E402

UPSAMPLE = 4


def save_cvol(cv, path, cmap="viridis"):
    """cv: (H, W, du, dv) → tiled image with one (du, dv) block per pixel."""
    import cv2

    h, w, dx, dy = cv.shape
    cv = np.transpose(cv, (3, 0, 2, 1))  # dy, h, dx, w
    cv = np.transpose(cv, (1, 0, 3, 2))  # h, dy, w, dx

    lo, hi = cv.min(), cv.max()
    cv = (cv - lo) / max(hi - lo, 1e-12)

    img = matplotlib.colormaps[cmap](cv)  # (h, dy, w, dx, 4)
    img = np.repeat(np.repeat(img, UPSAMPLE, axis=1), UPSAMPLE, axis=3)
    dyu, dxu = dy * UPSAMPLE, dx * UPSAMPLE

    # spacing between pixels
    framed = np.zeros((h, dyu + 1, w, dxu + 1, 4))
    framed[:, :dyu, :, :dxu, :] = img
    img = framed.reshape((dyu + 1) * h, (dxu + 1) * w, 4)[:-1, :-1]

    bgra = (np.clip(img[..., [2, 1, 0, 3]], 0, 1) * 255).astype(np.uint8)
    cv2.imwrite(str(path), bgra)


def main():
    def fmtcls(prog):
        return argparse.HelpFormatter(prog, max_help_position=42)

    parser = argparse.ArgumentParser(
        description="Visualize correlation cost volumes", formatter_class=fmtcls)
    parser.add_argument("-d", "--data", required=True, help="dataset spec")
    parser.add_argument("-m", "--model", required=True, help="model spec")
    parser.add_argument("-c", "--checkpoint", required=True, help="checkpoint")
    parser.add_argument("-o", "--output", required=True, help="output directory")
    parser.add_argument("--filter", default="",
                        help="substring filter on captured module paths")
    parser.add_argument("--limit", type=int, default=1,
                        help="number of samples to visualize")
    parser.add_argument("--cmap", default="viridis", help="colormap")

    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    utils.logging.setup()

    model_cfg = utils.config.load(args.model)
    if "strategy" in model_cfg:
        model_cfg = model_cfg["model"]
    spec = models.load(model_cfg)
    model, input = spec.model, spec.input

    chkpt = strategy.Checkpoint.load(args.checkpoint)

    dataset = data.load(args.data)
    loader = input.apply(dataset).jax().loader(batch_size=1, shuffle=False)

    img1, img2, *_ = loader.source[0]
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])
    variables, _, _ = chkpt.apply(variables=variables)

    out_dir = Path(args.output)
    out_dir.mkdir(parents=True, exist_ok=True)

    @jax.jit
    def capture(variables, img1, img2):
        _, mutated = model.module.apply(
            variables, img1, img2, train=False, frozen_bn=False,
            capture_intermediates=True, mutable=["intermediates"],
            **model.arguments,
        )
        return mutated["intermediates"]

    from raft_meets_dicl_tpu.inspect.hooks import flatten_intermediates

    for i, (img1, img2, flow, valid, meta) in enumerate(loader):
        if i >= args.limit:
            break

        inter = jax.device_get(
            capture(variables, jnp.asarray(img1), jnp.asarray(img2)))

        n_saved = 0
        for name, arr in flatten_intermediates(inter):
            if args.filter and args.filter not in name:
                continue
            if arr.ndim != 5:  # cost volumes are (B, H, W, du, dv)
                continue

            sid = str(meta[0].sample_id).replace("/", "_")
            path = out_dir / f"{sid}-{n_saved:03d}-{name.replace('.', '_')}.png"
            save_cvol(np.asarray(arr[0]), path, args.cmap)
            print(f"saved '{path}'")
            n_saved += 1

        if n_saved == 0:
            print("no cost volumes captured — check --filter "
                  "(cost volumes must be 5-D (B, H, W, du, dv))")


if __name__ == "__main__":
    main()
