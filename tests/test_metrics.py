"""Metric registry tests: torch-golden parity for EPE/Fl-all, config
round-trips, collectors, tree statistics, and the evaluation generator."""

import numpy as np
import pytest
import torch

import raft_meets_dicl_tpu.metrics as metrics
from raft_meets_dicl_tpu.metrics import MetricContext, functional as F


def _random_flow(seed, b=2, h=13, w=17):
    rng = np.random.RandomState(seed)
    est = rng.randn(b, h, w, 2).astype(np.float32) * 3
    tgt = rng.randn(b, h, w, 2).astype(np.float32) * 3
    valid = rng.rand(b, h, w) > 0.3
    return est, tgt, valid


def _torch_epe(est, tgt, valid, distances=(1, 3, 5)):
    # reference semantics (src/metrics/epe.py:36-52), NCHW with dim=-3
    est_t = torch.from_numpy(est).permute(0, 3, 1, 2)
    tgt_t = torch.from_numpy(tgt).permute(0, 3, 1, 2)
    epe = torch.linalg.vector_norm(est_t - tgt_t, ord=2, dim=-3)
    epe = epe[torch.from_numpy(valid)]
    out = {"mean": epe.mean().item()}
    for d in distances:
        out[f"{d}px"] = (epe <= d).float().mean().item()
    return out


def test_epe_torch_parity():
    est, tgt, valid = _random_flow(0)
    golden = _torch_epe(est, tgt, valid)

    vals = F.end_point_error(est, tgt, valid)
    assert float(vals["mean"]) == pytest.approx(golden["mean"], rel=1e-5)
    for d in (1, 3, 5):
        assert float(vals[f"{d}px"]) == pytest.approx(golden[f"{d}px"], rel=1e-5)

    m = metrics.Metric.from_config({"type": "epe"})
    res = m(MetricContext(), est, tgt, valid, loss=0.0)
    assert res["EndPointError/mean"] == pytest.approx(golden["mean"], rel=1e-5)
    assert res["EndPointError/3px"] == pytest.approx(golden["3px"], rel=1e-5)


def test_fl_all_torch_parity():
    est, tgt, valid = _random_flow(1)

    est_t = torch.from_numpy(est).permute(0, 3, 1, 2)
    tgt_t = torch.from_numpy(tgt).permute(0, 3, 1, 2)
    epe = torch.linalg.vector_norm(est_t - tgt_t, ord=2, dim=-3)
    mag = torch.linalg.vector_norm(tgt_t, ord=2, dim=-3)
    v = torch.from_numpy(valid)
    golden = torch.logical_and(epe[v] > 3, epe[v] > 0.05 * mag[v]).float().mean().item()

    assert float(F.fl_all(est, tgt, valid)) == pytest.approx(golden, rel=1e-5)

    m = metrics.Metric.from_config({"type": "fl-all"})
    res = m(MetricContext(), est, tgt, valid, loss=0.0)
    assert res["Fl-all"] == pytest.approx(golden, rel=1e-5)


def test_aae_and_magnitude():
    est, tgt, valid = _random_flow(2)

    # published AAE definition (Barron et al.): angle between unit-extended
    # spatio-temporal vectors (u, v, 1)
    ext_e = np.concatenate([est, np.ones_like(est[..., :1])], axis=-1)
    ext_t = np.concatenate([tgt, np.ones_like(tgt[..., :1])], axis=-1)
    cos = (ext_e * ext_t).sum(-1) / (
        np.linalg.norm(ext_e, axis=-1) * np.linalg.norm(ext_t, axis=-1))
    golden = np.degrees(np.arccos(np.clip(cos, -1, 1)).mean())

    assert float(F.average_angular_error(est, tgt)) == pytest.approx(golden, rel=1e-4)

    golden_mag = np.linalg.norm(est, axis=-1).mean()
    assert float(F.flow_magnitude(est)) == pytest.approx(golden_mag, rel=1e-5)


def test_epe_empty_valid_is_finite():
    est, tgt, valid = _random_flow(3)
    vals = F.end_point_error(est, tgt, np.zeros_like(valid))
    assert np.isfinite(float(vals["mean"]))


def test_config_roundtrip_all_types():
    cfgs = [
        {"type": "epe", "key": "EndPointError/", "distances": [1, 3, 5]},
        {"type": "fl-all", "key": "Fl-all"},
        {"type": "aae", "key": "AverageAngularError"},
        {"type": "flow-magnitude", "key": "FlowMagnitude", "ord": 2},
        {"type": "loss", "key": "Loss"},
        {"type": "learning-rate", "key": "LearningRate"},
        {"type": "grad-norm", "key": "GradientNorm/", "parameters": "total", "ord": 2.0},
        {"type": "grad-mean", "key": "GradientMean/", "parameters": "total"},
        {"type": "grad-minmax", "key": "GradientMinMax/", "parameters": "total"},
        {"type": "param-norm", "key": "ParameterNorm/", "parameters": "total", "ord": 2.0},
        {"type": "param-mean", "key": "ParameterMean/", "parameters": "total"},
        {"type": "param-minmax", "key": "ParameterMinMax/", "parameters": "total"},
    ]
    for cfg in cfgs:
        m = metrics.Metric.from_config(cfg)
        cfg2 = m.get_config()
        m2 = metrics.Metric.from_config(cfg2)
        assert m2.get_config() == cfg2


def test_tree_stats_against_torch():
    rng = np.random.RandomState(4)
    tree = {
        "enc": {"kernel": rng.randn(3, 3, 8).astype(np.float32)},
        "head": {"bias": rng.randn(8).astype(np.float32)},
    }

    norms = F.tree_norm(tree)
    t_enc = torch.from_numpy(tree["enc"]["kernel"]).norm(p=2).item()
    t_head = torch.from_numpy(tree["head"]["bias"]).norm(p=2).item()
    assert norms["enc.kernel"] == pytest.approx(t_enc, rel=1e-5)
    t_total = torch.tensor([t_enc, t_head]).norm(p=2).item()
    assert norms["total"] == pytest.approx(t_total, rel=1e-5)

    mean = F.tree_mean(tree)
    n1, m1 = mean["enc.kernel"]
    assert n1 == tree["enc"]["kernel"].size
    assert m1 == pytest.approx(tree["enc"]["kernel"].mean(), rel=1e-4)
    n_tot, m_tot = mean["total"]
    exp = (tree["enc"]["kernel"].sum() + tree["head"]["bias"].sum()) / n_tot
    assert m_tot == pytest.approx(exp, rel=1e-4)

    mm = F.tree_minmax(tree)
    assert mm["total"][0] == pytest.approx(
        min(tree["enc"]["kernel"].min(), tree["head"]["bias"].min()), rel=1e-5)


def test_grad_param_metrics_selection():
    rng = np.random.RandomState(5)
    grads = {"enc": {"k": rng.randn(4, 4).astype(np.float32)},
             "head": {"b": rng.randn(4).astype(np.float32)}}
    ctx = MetricContext(lr=1e-4, params=grads, grads=grads)

    m = metrics.Metric.from_config({"type": "grad-norm", "parameters": "all"})
    out = m(ctx, None, None, None, 0.0)
    assert "GradientNorm/enc.k" in out and "GradientNorm/total" in out

    m = metrics.Metric.from_config(
        {"type": "grad-norm", "parameters": {"encoder": ["enc."]}})
    out = m(ctx, None, None, None, 0.0)
    assert set(out) == {"GradientNorm/encoder"}

    m = metrics.Metric.from_config({"type": "param-minmax", "parameters": "total"})
    out = m(ctx, None, None, None, 0.0)
    assert "ParameterMinMax/total/min" in out

    m = metrics.Metric.from_config({"type": "learning-rate"})
    assert m(ctx, None, None, None, 0.0)["LearningRate"] == pytest.approx(1e-4)


def test_metrics_group_and_collectors():
    est, tgt, valid = _random_flow(6)
    ms = metrics.Metrics.from_config(
        [{"type": "epe"}, {"type": "fl-all"}, {"type": "loss"}])
    res = ms(MetricContext(), est, tgt, valid, loss=1.25)
    assert res["Loss"] == 1.25
    assert "EndPointError/mean" in res and "Fl-all" in res

    cs = metrics.Collectors.from_config([{"type": "mean"}])
    cs.collect({"a": 1.0, "b": float("nan")})
    cs.collect({"a": 3.0, "b": 2.0})
    out = cs.results()["mean"]
    assert out["a"] == pytest.approx(2.0)
    assert out["b"] == pytest.approx(2.0)  # NaN skipped


def test_evaluator_end_to_end():
    """Random-init raft/baseline → EPE computed end-to-end per sample."""
    import jax

    import raft_meets_dicl_tpu.evaluation as evaluation
    import raft_meets_dicl_tpu.models as models

    spec = models.load({
        "name": "RAFT", "id": "raft-eval-test",
        "model": {"type": "raft/baseline",
                  "parameters": {"iterations": 2}},
        "loss": {"type": "raft/sequence"},
        "input": {},
    })
    model = spec.model

    rng = np.random.RandomState(7)
    img1 = rng.rand(2, 64, 96, 3).astype(np.float32)
    img2 = rng.rand(2, 64, 96, 3).astype(np.float32)
    flow = rng.randn(2, 64, 96, 2).astype(np.float32)
    valid = np.ones((2, 64, 96), bool)

    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])

    loader = spec.input.apply([(img1, img2, flow, valid, [
        _meta(i) for i in range(2)])]).jax().loader(batch_size=1)

    ms = metrics.Metrics.from_config([{"type": "epe"}, {"type": "fl-all"}])
    collectors = metrics.Collectors.from_config([{"type": "mean"}])

    n = 0
    for sample in evaluation.evaluate(model, variables, loader,
                                      show_progress=False):
        assert sample.final.shape == (64, 96, 2)
        assert np.all(np.isfinite(sample.final))
        res = ms(MetricContext(), sample.final, sample.target, sample.valid,
                 loss=0.0)
        assert np.isfinite(res["EndPointError/mean"])
        collectors.collect(res)
        n += 1

    assert n == 2
    summary = collectors.results()["mean"]
    assert np.isfinite(summary["EndPointError/mean"])


def _meta(i):
    from raft_meets_dicl_tpu.data.collection import Metadata, SampleArgs, SampleId

    return Metadata(
        valid=True,
        dataset_id="test",
        sample_id=SampleId(format="test/{id}",
                           img1=SampleArgs([], {"id": i}),
                           img2=SampleArgs([], {"id": i + 1})),
        original_extents=((0, 64), (0, 96)),
    )
