"""graftprof: the trace-event parser, op-class bucketing, the
measured-vs-predicted calibration table, the machine-scoped
prof-budget.json drift gate, the /profilez retention fix, and the
telemetry/report/metrics round-trip — plus one real segmented CPU
capture of a toy registered program end to end."""

import json
from pathlib import Path

import pytest

from raft_meets_dicl_tpu import telemetry
from raft_meets_dicl_tpu.analysis import profile as prof
from raft_meets_dicl_tpu.telemetry import metrics as metrics_mod
from raft_meets_dicl_tpu.telemetry import sidecar

pytestmark = pytest.mark.obs

REPO = Path(__file__).parent.parent
CANNED = Path(__file__).parent / "data" / "graftprof"
MACHINE = "cpu:test"


# -- op-class bucketing -------------------------------------------------------


def test_op_class_bucketing():
    # both HLO (hyphens) and StableHLO (underscores) spellings, fused
    # names, leading % and instance suffixes
    assert prof.op_class("dot.42") == "dot"
    assert prof.op_class("%dot_general.3") == "dot"
    assert prof.op_class("convolution.2") == "conv"
    assert prof.op_class("convolution_fusion") == "conv"
    assert prof.op_class("gather.4") == "gather"
    assert prof.op_class("dynamic-update-slice.8") == "gather"
    assert prof.op_class("dynamic_slice.1") == "gather"
    assert prof.op_class("reduce.7") == "reduce"
    assert prof.op_class("reduce_window.1") == "reduce"
    # collectives win over their substrings (all-REDUCE, reduce-SCATTER)
    assert prof.op_class("all-reduce.3") == "collective"
    assert prof.op_class("reduce-scatter.1") == "collective"
    assert prof.op_class("all_gather.9") == "collective"
    assert prof.op_class("collective-permute.1") == "collective"
    assert prof.op_class("infeed.6") == "infeed"
    assert prof.op_class("outfeed.1") == "infeed"
    assert prof.op_class("add_rsqrt_fusion.5") == "elementwise"
    assert prof.op_class("copy.1") == "elementwise"
    assert prof.op_class("convert_convert_fusion") == "elementwise"


# -- trace parsing (canned fixture) ------------------------------------------


def test_collect_trace_canned_fixture():
    collected = prof.collect_trace(CANNED)
    assert collected["source"] == "trace-json"
    assert len(collected["ops"]) == 9  # host events without hlo_op skip
    by_module = {}
    for module, _, s in collected["ops"]:
        by_module[module] = by_module.get(module, 0.0) + s
    assert by_module["jit_step"] == pytest.approx(4040e-6)
    assert by_module["jit_eval_step"] == pytest.approx(300e-6)
    classes = prof.class_seconds(
        [o for o in collected["ops"] if o[0] == "jit_step"])
    assert classes["dot"] == pytest.approx(1000e-6)
    assert classes["conv"] == pytest.approx(2000e-6)
    assert classes["collective"] == pytest.approx(500e-6)
    assert classes["gather"] == pytest.approx(290e-6)  # gather + dus
    assert classes["elementwise"] == pytest.approx(125e-6)
    assert classes["infeed"] == pytest.approx(75e-6)
    assert classes["reduce"] == pytest.approx(50e-6)


def test_attribute_trace_canned_fixture():
    summary = prof.attribute_trace(CANNED)
    assert summary["source"] == "trace-json"
    assert summary["op_events"] == 9
    assert summary["device_seconds"] == pytest.approx(4340e-6)
    assert [m["module"] for m in summary["modules"]] == \
        ["jit_step", "jit_eval_step"]  # sorted by device time
    step = summary["modules"][0]
    assert step["classes"]["conv"] == pytest.approx(2000e-6)
    assert step["top_ops"][0]["op"] == "convolution.2"
    text = prof.render_attribution(summary)
    assert "jit_step" in text and "conv" in text


def test_trace_errors_are_clean(tmp_path):
    # empty dir: no capture at all
    with pytest.raises(prof.TraceError, match="no profiler capture"):
        prof.collect_trace(tmp_path)
    # malformed JSON
    bad = tmp_path / "host.trace.json"
    bad.write_text("{not json")
    with pytest.raises(prof.TraceError, match="unreadable trace file"):
        prof.collect_trace(tmp_path)
    # valid JSON without traceEvents
    bad.write_text(json.dumps({"foo": 1}))
    with pytest.raises(prof.TraceError, match="no traceEvents"):
        prof.collect_trace(tmp_path)
    # a trace with only host events: nothing to attribute
    bad.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "ts": 0, "dur": 5, "name": "PyCall", "args": {}}]}))
    with pytest.raises(prof.TraceError, match="no device op events"):
        prof.collect_trace(tmp_path)


# -- calibration budget -------------------------------------------------------


def _report(key="('train_step', 'm', ())", ratio=1.5, fp="abc",
            classes=None):
    classes = classes if classes is not None else {
        "dot": {"seconds": 0.006, "predicted_seconds": 0.004,
                "ratio": 1.5},
        "elementwise": {"seconds": 0.0001,
                        "predicted_seconds": 0.0001, "ratio": 1.0},
    }
    predicted = sum(c.get("predicted_seconds", 0.0)
                    for c in classes.values())
    measured = sum(c.get("seconds", 0.0) for c in classes.values())
    return {"key": key, "label": "t", "kind": "train_step",
            "fingerprint": fp, "repeats": 2, "source": "trace-json",
            "device_seconds": measured, "predicted_seconds": predicted,
            "ratio": ratio, "classes": classes,
            "flops": 10**9, "bytes": 10**8}


def _budget(ratio=1.5, fp="abc", classes=None):
    entry = {"ratio": ratio, "fingerprint": fp, "device_seconds": 0.006,
             "classes": classes or {"dot": {"ratio": 1.5}}}
    return prof.ProfBudget({
        "version": 1,
        "machines": {MACHINE: {"entries": {_report()["key"]: entry}}},
    }, path="prof-budget.json")


def test_budget_ratio_band_and_drift():
    b = _budget(ratio=1.5)
    assert b.check(_report(ratio=1.5), MACHINE) == []
    # multiplicative band [r/(1+tol), r*(1+tol)], tol=1.5 -> [0.6, 3.75]
    assert b.check(_report(ratio=3.7), MACHINE) == []
    drift = b.check(_report(ratio=4.0), MACHINE)
    assert [f.rule for f in drift] == ["prof-calibration"]
    assert "graftprof.py --update" in drift[0].message
    slow = _budget(ratio=1.5).check(_report(ratio=0.5), MACHINE)
    assert [f.rule for f in slow] == ["prof-calibration"]


def test_budget_unpinned_and_machine_scoping():
    b = _budget()
    unpinned = b.check(_report(key="('other', 'm', ())"), MACHINE)
    assert [f.rule for f in unpinned] == ["prof-unpinned"]
    # same program on a different machine: unpinned there, never gated
    # against this machine's ratio
    other = b.check(_report(ratio=99.0), "tpu:v4")
    assert [f.rule for f in other] == ["prof-unpinned"]


def test_budget_class_ratio_gates_only_visible_classes():
    classes = {
        "dot": {"seconds": 0.04, "predicted_seconds": 0.004,
                "ratio": 10.0},  # pinned 1.5, tol 3.0 -> band hi 6.0
        "elementwise": {"seconds": 0.01,
                        "predicted_seconds": 0.00001, "ratio": 1000.0},
    }
    b = _budget(ratio=1.5)
    rep = _report(ratio=1.5, classes=classes)
    findings = b.check(rep, MACHINE)
    msgs = [f.message for f in findings]
    # dot (>=5% of predicted step, pinned) gates; elementwise's wild
    # ratio is below the share floor and has no pin — silent
    assert len(findings) == 1 and "dot ratio 10.00" in msgs[0]


def test_budget_fingerprint_mismatch_is_note_not_finding():
    b = _budget(fp="abc")
    rep = _report(fp="DIFFERENT")
    assert b.check(rep, MACHINE) == []
    assert rep["stale_fingerprint"] is True
    text = prof.render_reports(prof.ProfReport(
        reports=[rep], machine={"machine_id": MACHINE}))
    assert "[stale fingerprint]" in text


def test_budget_stale_entries_and_version_gate(tmp_path):
    b = _budget()
    b.check(_report(), MACHINE)
    assert b.unused_entries(MACHINE) == []
    b2 = _budget()
    assert b2.unused_entries(MACHINE) == [_report()["key"]]
    with pytest.raises(ValueError, match="unsupported prof-budget"):
        prof.ProfBudget({"version": 99, "machines": {}})


def test_budget_pin_roundtrip_preserves_other_machines(tmp_path):
    b = _budget()
    rep = _report(ratio=2.0, fp="new")
    data = b.pinned_data([rep], "tpu:v4")
    path = tmp_path / "prof-budget.json"
    path.write_text(json.dumps(data))
    b2 = prof.ProfBudget.load(path)
    # the old machine's pin survived, the new machine got pinned
    assert b2.check(_report(), MACHINE) == []
    assert b2.check(_report(ratio=2.0, fp="new"), "tpu:v4") == []
    entry = b2.entries_for("tpu:v4")[rep["key"]]
    assert entry["ratio"] == 2.0 and entry["fingerprint"] == "new"


# -- real segmented capture (toy program) ------------------------------------


@pytest.fixture(scope="module")
def toy_audit(tmp_path_factory):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_meets_dicl_tpu import compile as programs

    def toy_prof_step(x, w):
        y = jnp.tanh(x @ w)
        return jnp.sum(y * y)

    key = programs.ProgramKey(
        kind="toy_prof_step", model="toy",
        flags=programs.flag_items(shape=(192, 192)))
    p = programs.register_step("toy_prof_step", jax.jit(toy_prof_step),
                               key=key)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(192, 192), jnp.float32)
    w = jnp.asarray(rng.rand(192, 192), jnp.float32)
    out_dir = tmp_path_factory.mktemp("graftprof-capture")
    rep = prof.audit_profiles(entries=[(p, (x, w), {})],
                              out_dir=out_dir, repeats=2)
    return rep, out_dir


def test_toy_capture_produces_calibration_row(toy_audit):
    rep, _ = toy_audit
    assert rep.ok and len(rep.reports) == 1
    r = rep.reports[0]
    assert r["kind"] == "toy_prof_step"
    assert r["device_seconds"] > 0
    assert r["predicted_seconds"] > 0
    assert r["ratio"] > 0
    assert r["achieved_flops"] > 0
    # the matmul dominates and must be attributed to the dot class
    assert r["classes"]["dot"]["seconds"] > 0
    assert r["fingerprint"] and len(r["fingerprint"]) == 64
    assert rep.machine["machine_id"].startswith("cpu:")


def test_toy_capture_segment_manifest_and_pin_roundtrip(toy_audit,
                                                        tmp_path):
    rep, out_dir = toy_audit
    manifest = json.loads((out_dir / prof.MANIFEST_NAME).read_text())
    assert manifest["segments"][0]["key"] == rep.reports[0]["key"]
    # re-attribute the kept capture from disk: identical measurement
    reports = prof.attribute_segments(out_dir)
    assert reports[0]["device_seconds"] == \
        rep.reports[0]["device_seconds"]
    # pin this machine, re-check the same run: green, no stale entries
    mid = rep.machine["machine_id"]
    b = prof.ProfBudget(
        prof.ProfBudget.empty().pinned_data(rep.reports, mid))
    b.path = "x"
    assert b.check(rep.reports[0], mid) == []
    assert b.unused_entries(mid) == []


# -- telemetry / report / metrics round-trip ---------------------------------


def _prof_report(drift=False):
    from raft_meets_dicl_tpu.analysis.lint import Finding

    rep = prof.ProfReport(reports=[_report()],
                          machine={"machine_id": MACHINE,
                                   "n_devices": 1,
                                   "peak_flops": 1e11,
                                   "peak_bytes_per_s": 2e10})
    if drift:
        rep.findings.append(Finding(
            rule="prof-calibration", path="analysis/profile", line=1,
            message=f"{_report()['key']}: measured/predicted ratio "
                    f"4.00 vs pinned 1.50"))
    return rep


def test_profile_events_flow_into_telemetry_report():
    rep = _prof_report(drift=True)
    tele = telemetry.Telemetry()          # in-memory sink
    prof.emit_events(rep, tele)
    from raft_meets_dicl_tpu.telemetry import report as trep

    stats = trep.prof_stats(tele.events)
    assert len(stats["programs"]) == 1
    assert len(stats["drifted"]) == 1
    text = trep.render(tele.events)
    assert "== profiling" in text
    assert _report()["key"][:72] in text
    assert "[drift]" in text
    flags = trep.find_anomalies(tele.events)
    assert any("calibration drift" in f for f in flags)


def test_profile_events_clean_run_has_no_anomaly():
    tele = telemetry.Telemetry()
    prof.emit_events(_prof_report(drift=False), tele)
    from raft_meets_dicl_tpu.telemetry import report as trep

    assert not any("calibration drift" in f
                   for f in trep.find_anomalies(tele.events))


def test_publish_metrics_roundtrip():
    reg = metrics_mod.MetricsRegistry()
    prof.publish_metrics(_prof_report(), reg)
    parsed = metrics_mod.parse_text(reg.render())
    sec = parsed["rmd_prof_device_seconds"]
    assert sec[tuple(sorted([("program", "train_step")]))] == \
        pytest.approx(0.0061)
    ratio = parsed["rmd_prof_calibration_ratio"]
    assert ratio[tuple(sorted([("program", "train_step")]))] == 1.5
    cls = parsed["rmd_prof_class_seconds"]
    assert cls[tuple(sorted([("klass", "dot")]))] == \
        pytest.approx(0.006)


def test_publish_attribution_metrics_roundtrip(monkeypatch):
    # pin the registry guess empty: earlier test files may have left a
    # live program named `step`, which would relabel the jit_step row
    monkeypatch.setattr(prof, "_module_map", lambda: {})
    reg = metrics_mod.MetricsRegistry()
    summary = prof.attribute_trace(CANNED)
    prof.publish_attribution_metrics(summary, reg)
    parsed = metrics_mod.parse_text(reg.render())
    sec = parsed["rmd_prof_device_seconds"]
    assert sec[tuple(sorted([("program", "jit_step")]))] == \
        pytest.approx(4040e-6)
    cls = parsed["rmd_prof_class_seconds"]
    assert cls[tuple(sorted([("klass", "conv")]))] == \
        pytest.approx(2000e-6)


def test_profile_event_kind_is_registered():
    from raft_meets_dicl_tpu.telemetry.core import SCHEMA

    assert "profile" in SCHEMA
    assert SCHEMA["profile"] == {"program", "seconds"}


# -- /profilez retention + inline attribution --------------------------------


def test_evict_captures_bounded_retention(tmp_path):
    import os
    import time as time_mod

    dirs = []
    for i in range(5):
        d = tmp_path / f"rmd-profilez-{i}"
        d.mkdir()
        ts = time_mod.time() - (5 - i) * 60
        os.utime(d, (ts, ts))
        dirs.append(d)
    evicted = sidecar.evict_captures(keep=2, tmp_root=str(tmp_path))
    assert sorted(evicted) == sorted(str(d) for d in dirs[:3])
    assert sorted(p.name for p in tmp_path.iterdir()) == \
        ["rmd-profilez-3", "rmd-profilez-4"]
    # keep is floored at 1: a zero knob never deletes the capture the
    # caller is about to return
    sidecar.evict_captures(keep=0, tmp_root=str(tmp_path))
    assert [p.name for p in tmp_path.iterdir()] == ["rmd-profilez-4"]


def test_capture_profile_attribution_and_eviction(monkeypatch, tmp_path):
    import threading

    monkeypatch.setattr("tempfile.tempdir", str(tmp_path))
    canned = {"source": "trace-json", "device_seconds": 0.004,
              "op_events": 9, "modules": [
                  {"module": "jit_step", "program": None, "candidates": 0,
                   "seconds": 0.004, "classes": {"conv": 0.002},
                   "top_ops": []}]}
    monkeypatch.setattr(prof, "attribute_trace", lambda d: canned)
    reg = metrics_mod.MetricsRegistry()
    payload = sidecar.capture_profile(threading.Lock(), 0.1,
                                      registry=reg)
    assert payload["dir"].startswith(str(tmp_path))
    assert payload["attribution"] is canned
    parsed = metrics_mod.parse_text(reg.render())
    assert parsed["rmd_prof_device_seconds"][
        tuple(sorted([("program", "jit_step")]))] == \
        pytest.approx(0.004)
    # the capture dir itself survives the eviction pass
    assert Path(payload["dir"]).is_dir()


def test_capture_profile_attribution_failure_is_advisory(monkeypatch,
                                                         tmp_path):
    import threading

    monkeypatch.setattr("tempfile.tempdir", str(tmp_path))

    def boom(d):
        raise prof.TraceError("nothing executed")

    monkeypatch.setattr(prof, "attribute_trace", boom)
    payload = sidecar.capture_profile(threading.Lock(), 0.1)
    assert "attribution" not in payload
    assert "nothing executed" in payload["attribution_error"]
    assert Path(payload["dir"]).is_dir()


def test_capture_profile_attribution_knob_off(monkeypatch, tmp_path):
    import threading

    monkeypatch.setattr("tempfile.tempdir", str(tmp_path))
    monkeypatch.setenv("RMD_PROFILE_ATTRIBUTION", "0")
    called = []
    monkeypatch.setattr(prof, "attribute_trace",
                        lambda d: called.append(d))
    payload = sidecar.capture_profile(threading.Lock(), 0.1)
    assert "attribution" not in payload and not called


# -- CLI contract -------------------------------------------------------------


def _cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graftprof_cli", REPO / "scripts" / "graftprof.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_graftprof_cli_json_schema():
    mod = _cli()
    payload = mod.json_report(_prof_report())
    assert payload["schema"] == 1
    assert payload["ok"] is True and payload["exit_code"] == 0
    json.dumps(payload)
    bad = mod.json_report(_prof_report(drift=True))
    assert bad["ok"] is False and bad["exit_code"] == 1


def test_graftprof_cli_trace_dir_mode(capsys, tmp_path):
    mod = _cli()
    assert mod.main(["--trace-dir", str(CANNED)]) == 0
    out = capsys.readouterr().out
    assert "jit_step" in out and "device op time" in out
    assert mod.main(["--trace-dir", str(CANNED),
                     "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["schema"] == 1 and payload["op_events"] == 9
    # an unusable dir is a usage error (exit 2), not a traceback
    assert mod.main(["--trace-dir", str(tmp_path)]) == 2
    assert "no profiler capture" in capsys.readouterr().err
