"""SPMD layer tests on the 8-device virtual CPU mesh."""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import raft_meets_dicl_tpu.models as models
from raft_meets_dicl_tpu import parallel

pytestmark = pytest.mark.slow

TINY = {
    "name": "tiny", "id": "tiny",
    "model": {
        "type": "raft/baseline",
        "parameters": {
            "corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
            "context-channels": 16, "recurrent-channels": 16,
        },
        "arguments": {"iterations": 2},
    },
    "loss": {"type": "raft/sequence"},
    "input": None,
}


def _batch(b, h=16, w=24):
    rng = np.random.RandomState(0)
    return (
        jnp.asarray(rng.rand(b, h, w, 3), jnp.float32),
        jnp.asarray(rng.rand(b, h, w, 3), jnp.float32),
        jnp.asarray(rng.randn(b, h, w, 2), jnp.float32),
        jnp.ones((b, h, w), bool),
    )


def test_mesh_has_8_devices():
    mesh = parallel.data_mesh()
    assert mesh.devices.size == 8


def test_mesh_too_many_devices():
    with pytest.raises(ValueError, match="requested"):
        parallel.data_mesh(99)


def test_sharded_train_step_matches_single_device():
    spec = models.load(TINY)
    model, loss = spec.model, spec.loss

    img1, img2, flow, valid = _batch(8)
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])

    # SGD so updates are proportional to gradients (adam's first step is
    # ~sign(g)*lr, which amplifies reduction-order noise into lr-sized
    # param differences)
    tx = optax.sgd(1e-2)

    # single-device reference
    state1 = parallel.TrainState.create(variables, tx)
    step1 = parallel.make_train_step(model, loss, tx, donate=False, with_grads=True)
    state1, aux1 = step1(state1, img1, img2, flow, valid)

    # 8-device mesh
    mesh = parallel.data_mesh(8)
    state8 = parallel.TrainState.create(variables, tx)
    state8 = parallel.replicate(state8, mesh)
    step8 = parallel.make_train_step(model, loss, tx, mesh=mesh, donate=False, with_grads=True)
    batch = parallel.shard_batch((img1, img2, flow, valid), mesh)
    state8, aux8 = step8(state8, *batch)

    # same loss, same gradients (up to reduction order), same updated params
    np.testing.assert_allclose(
        float(aux1["loss"]), float(aux8["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(aux1["grads"]), jax.tree.leaves(aux8["grads"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
    for a, b in zip(jax.tree.leaves(state1.params), jax.tree.leaves(state8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_eval_step_sharded():
    spec = models.load(TINY)
    model = spec.model

    img1, img2, *_ = _batch(8)
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])

    mesh = parallel.data_mesh(8)
    step = parallel.make_eval_step(model, mesh=mesh, model_args={"iterations": 2})
    out = step(parallel.replicate(variables, mesh),
               *parallel.shard_batch((img1, img2), mesh))
    assert out.shape == (8, 16, 24, 2)
    assert np.isfinite(np.asarray(out)).all()


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_forward_compiles():
    import __graft_entry__ as ge

    fn, (variables, img1, img2) = ge.entry()
    # compile-check on a tiny override instead of the full 368x496 (slow on CPU)
    small1 = img1[:, :64, :96]
    small2 = img2[:, :64, :96]
    out = jax.jit(fn)(variables, small1, small2)
    assert out.shape == (1, 64, 96, 2)


def test_evaluation_mesh_matches_single_device():
    """evaluation.evaluate over an 8-device data mesh yields the same
    per-sample finals/outputs as the single-device path, including a
    short (non-divisible) final batch that needs padding."""
    from raft_meets_dicl_tpu import evaluation

    spec = models.load(TINY)
    model = spec.model

    img1, img2, flow, valid = _batch(6)  # 6 % 8 != 0: exercises padding
    variables = model.init(jax.random.PRNGKey(0), img1[:1], img2[:1])

    meta = [{"sample_id": i} for i in range(6)]
    batches = [(np.asarray(img1[:4]), np.asarray(img2[:4]),
                np.asarray(flow[:4]), np.asarray(valid[:4]), meta[:4]),
               (np.asarray(img1[4:]), np.asarray(img2[4:]),
                np.asarray(flow[4:]), np.asarray(valid[4:]), meta[4:])]

    args = {"iterations": 2}
    ref = list(evaluation.evaluate(model, variables, batches,
                                   model_args=args, show_progress=False))

    mesh = parallel.data_mesh(8)
    got = list(evaluation.evaluate(model, variables, batches,
                                   model_args=args, show_progress=False,
                                   mesh=mesh))

    assert len(ref) == len(got) == 6
    for r, g in zip(ref, got):
        assert r.meta == g.meta
        np.testing.assert_allclose(r.final, g.final, atol=1e-5)
        for a, b in zip(r.output, g.output):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


# -- SPMD reach across the zoo ----------------------------------------------

_FULL_DIR = Path(__file__).resolve().parent.parent / "cfg" / "full" / "baseline"


def _every_model_id():
    """One frozen full config per registered model id (the reference wraps
    EVERY model in DataParallel identically — src/cmd/train.py:183-184 —
    so every id must at least trace + shard over the mesh)."""
    import json

    seen = {}
    for f in sorted(_FULL_DIR.glob("*.json")):
        cfg = json.load(open(f))["model"]
        seen.setdefault(cfg["id"], cfg)
    return [pytest.param(cfg, id=mid) for mid, cfg in sorted(seen.items())]


@pytest.mark.parametrize("mcfg", _every_model_id())
def test_spmd_train_step_lowers_for_every_model_id(mcfg):
    """Abstractly trace + lower the full SPMD training step for every
    registered model id at its published (full-channel) configuration on
    the 8-device mesh. eval_shape keeps this a pure tracing check — the
    compile+run proof per model family lives in the driver dryrun
    (__graft_entry__.dryrun_multichip) and the tests above; this one
    catches per-id shape, adapter, loss, or sharding-annotation breaks."""
    spec = models.load(mcfg)
    model, loss = spec.model, spec.loss

    margs = dict(mcfg["model"].get("arguments", {}))
    iters = margs.get("iterations")
    if isinstance(iters, (tuple, list)):
        margs["iterations"] = (1,) * len(iters)
    elif iters is not None:
        margs["iterations"] = 1
    margs.pop("prev_flow", None)  # loss-pairing variant, not a step knob

    mesh = parallel.data_mesh(8)
    b, h, w = 8, 128, 128
    img = jnp.zeros((b, h, w, 3), jnp.float32)
    flow = jnp.zeros((b, h, w, 2), jnp.float32)
    valid = jnp.zeros((b, h, w), bool)

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-4))

    def abstract_state():
        variables = model.init(jax.random.PRNGKey(0), img[:1], img[:1],
                               **margs)
        return parallel.TrainState.create(variables, tx)

    state_shape = jax.eval_shape(abstract_state)
    step = parallel.make_train_step(model, loss, tx, mesh=mesh,
                                    model_args=margs)
    lowered = step.lower(state_shape, img, img, flow, valid)
    assert lowered is not None
