"""Live observability plane tests: tracing, metrics, SLO, endpoints.

The trace decomposition is pinned as *exact* (phases telescope to the
end-to-end total — nothing hides between phases), the metrics registry
round-trips through its own Prometheus text parser, the SLO burn-rate
math matches the SRE definitions, and the HTTP plane is exercised over
a real socket: /healthz readiness flips with the warm pool, /metrics
parses with nonzero request counters. Scheduler propagation runs on the
host-only fake session; one real tiny-model test covers span propagation
through an actual pad-tiled partial batch.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
from raft_meets_dicl_tpu import serve, telemetry
from raft_meets_dicl_tpu.analysis import telemetrykinds
from raft_meets_dicl_tpu.analysis.lint import Module
from raft_meets_dicl_tpu.models.input import ShapeBuckets
from raft_meets_dicl_tpu.models.wire import WireFormat
from raft_meets_dicl_tpu.serve import Scheduler, ServeSession, observe
from raft_meets_dicl_tpu.telemetry import (
    core, metrics as metrics_mod, report as treport, slo as slo_mod,
    trace as trace_mod,
)
from raft_meets_dicl_tpu.testing import faults

pytestmark = pytest.mark.obs

TINY_OBS_MODEL = {
    "name": "obs tiny", "id": "obs-tiny",
    "model": {"type": "raft/baseline",
              "parameters": {"corr-levels": 2, "corr-radius": 2,
                             "corr-channels": 32, "context-channels": 16,
                             "recurrent-channels": 16},
              "arguments": {"iterations": 2}},
    "loss": {"type": "raft/sequence"},
    "input": {"padding": {"type": "modulo", "mode": "zeros",
                          "size": [8, 8]}},
}


@pytest.fixture(autouse=True)
def _obs_hygiene(monkeypatch):
    """Fresh in-memory sink + fresh default metrics registry per test."""
    monkeypatch.delenv("RMD_FAULT", raising=False)
    monkeypatch.delenv("RMD_FAULT_STATE", raising=False)
    faults.reset()
    metrics_mod.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    yield sink
    telemetry.deactivate()
    metrics_mod.reset()
    faults.reset()


def _pair(shape, seed=0):
    rng = np.random.default_rng(seed)
    h, w = shape
    return (rng.random((h, w, 3), dtype=np.float32),
            rng.random((h, w, 3), dtype=np.float32))


class FakeSession:
    def __init__(self, buckets, batch_size=4, delay_s=0.0):
        self.buckets = buckets
        self.batch_size = batch_size
        self.delay_s = delay_s

    def encode_image(self, img):
        return np.asarray(img, np.float32) * 2.0 - 1.0

    def compiles(self):
        return 0

    def run(self, img1, img2):
        if self.delay_s:
            time.sleep(self.delay_s)
        return (img1 + img2)[..., :2]

    def fetch(self, flow):
        return np.asarray(flow)


def _fake_scheduler(batch_size=2, max_wait_ms=2.0, queue_limit=64):
    buckets = ShapeBuckets([(16, 24), (32, 48)])
    session = FakeSession(buckets, batch_size=batch_size)
    return Scheduler(session, batch_size=batch_size,
                     max_wait_ms=max_wait_ms, queue_limit=queue_limit)


def _trace_events(sink, event):
    return [e for e in sink.events
            if e["kind"] == "trace" and e["event"] == event]


def _get(url):
    """(status, parsed JSON or text) for a GET, 4xx/5xx included."""
    try:
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            body = resp.read().decode()
            code = resp.status
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        code = e.code
    try:
        return code, json.loads(body)
    except json.JSONDecodeError:
        return code, body


# -- trace decomposition ------------------------------------------------------


def test_phases_telescope_exactly():
    rt = trace_mod.RequestTrace(klass="fast", bucket=(16, 24))
    for i, mark in enumerate(trace_mod.MARKS):
        rt.mark(mark, t=10.0 + i * 0.25)
    phases = rt.phases()
    assert set(phases) == set(trace_mod.PHASES)
    # exact telescoping: the phases are differences of one clock at
    # consecutive marks, so they sum to total with no residual
    assert sum(phases.values()) == rt.total() == pytest.approx(1.25)
    rec = rt.record()
    assert rec["klass"] == "fast" and rec["bucket"] == "16x24"
    assert sum(rec["phases"].values()) == pytest.approx(rec["total"],
                                                        abs=1e-5)


def test_phases_skip_unhit_marks():
    rt = trace_mod.RequestTrace()
    rt.mark("submit", t=1.0)
    rt.mark("dispatch", t=3.0)   # enqueue never hit
    rt.mark("released", t=4.0)
    phases = rt.phases()
    # gaps bridge the missing marks, attribution still covers everything
    assert phases == {"admission": 2.0, "batch_form": 1.0}
    assert sum(phases.values()) == rt.total() == 3.0


def test_unknown_mark_rejected():
    with pytest.raises(ValueError, match="unknown trace mark"):
        trace_mod.RequestTrace().mark("teleport")


def test_batch_trace_links_members():
    bt = trace_mod.BatchTrace((32, 48), "quality", program="prog@abc")
    members = [trace_mod.RequestTrace(klass="quality") for _ in range(3)]
    for rt in members:
        bt.link(rt)
    bt.fill = 4
    rec = bt.finish().record()
    assert rec["size"] == 3 and rec["fill"] == 4
    assert rec["bucket"] == "32x48" and rec["program"] == "prog@abc"
    assert rec["members"] == [rt.trace_id for rt in members]
    assert all(rt.batch_id == bt.batch_id for rt in members)
    assert rec["seconds"] >= 0


def test_trace_summary_snapshot_and_tail():
    ts = trace_mod.TraceSummary()
    # 9 fast requests at 10ms, one slow one queue-dominated at 100ms
    for _ in range(9):
        ts.add({"klass": "fast", "total": 0.010,
                "phases": {"queue": 0.002, "device": 0.008}})
    ts.add({"klass": "fast", "total": 0.100,
            "phases": {"queue": 0.090, "device": 0.010}})
    snap = ts.snapshot()
    assert snap["count"] == 10
    fast = snap["classes"]["fast"]
    assert fast["count"] == 10
    assert fast["p50_ms"] == pytest.approx(10.0)
    assert fast["p99_ms"] == pytest.approx(100.0)
    tail = snap["tail"]
    assert tail["count"] == 1
    assert tail["dominant"] == "queue" and tail["queue_dominated"]
    assert tail["phases_ms"]["queue"] == pytest.approx(90.0)


def test_trace_summary_bounded():
    ts = trace_mod.TraceSummary(capacity=8)
    for i in range(50):
        ts.add({"klass": "", "total": float(i), "phases": {}})
    assert len(ts) == 8
    assert ts.snapshot()["classes"][""]["count"] == 8


# -- metrics registry + exposition --------------------------------------------


def test_metric_name_convention_enforced():
    reg = metrics_mod.MetricsRegistry()
    with pytest.raises(ValueError, match="rmd_<subsystem>_<name>"):
        reg.gauge("queue_depth", "no rmd_ prefix")
    with pytest.raises(ValueError, match="rmd_<subsystem>_<name>"):
        reg.gauge("rmd_depth", "too few segments")
    with pytest.raises(ValueError, match="must end in _total"):
        reg.counter("rmd_serve_requests", "counter suffix")
    with pytest.raises(ValueError, match="bad label name"):
        reg.gauge("rmd_serve_depth", "bad label", ("Klass!",))


def test_counter_only_goes_up():
    reg = metrics_mod.MetricsRegistry()
    c = reg.counter("rmd_test_ops_total", "ops")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)


def test_labels_checked_and_rendered():
    reg = metrics_mod.MetricsRegistry()
    c = reg.counter("rmd_test_reqs_total", "reqs", ("klass", "bucket"))
    c.labels(klass="fast", bucket="16x24").inc(3)
    with pytest.raises(ValueError, match="takes labels"):
        c.labels(klass="fast")
    with pytest.raises(ValueError, match="needs .labels"):
        c.inc()
    parsed = metrics_mod.parse_text(reg.render())
    key = (("bucket", "16x24"), ("klass", "fast"))
    assert parsed["rmd_test_reqs_total"][key] == 3.0


def test_histogram_cumulative_buckets():
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("rmd_test_lat_seconds", "latency",
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    parsed = metrics_mod.parse_text(reg.render())
    buckets = parsed["rmd_test_lat_seconds_bucket"]
    assert buckets[(("le", "0.01"),)] == 1.0
    assert buckets[(("le", "0.1"),)] == 2.0
    assert buckets[(("le", "1"),)] == 3.0
    assert buckets[(("le", "+Inf"),)] == 4.0
    assert parsed["rmd_test_lat_seconds_count"][()] == 4.0
    assert parsed["rmd_test_lat_seconds_sum"][()] == pytest.approx(5.555)


def test_registry_reregistration_idempotent_or_loud():
    reg = metrics_mod.MetricsRegistry()
    g1 = reg.gauge("rmd_test_depth_now", "depth")
    assert reg.gauge("rmd_test_depth_now", "depth") is g1
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("rmd_test_depth_now_total", "ok")  # different name: fine
        reg.counter("rmd_test_depth_now", "clash")


def test_render_parses_as_prometheus_text():
    reg = metrics_mod.MetricsRegistry()
    reg.gauge("rmd_test_ready_flag", 'docs with "quotes" and\nnewline').set(1)
    reg.counter("rmd_test_n_total", "n").inc(7)
    text = reg.render()
    assert "# HELP rmd_test_ready_flag" in text
    assert "# TYPE rmd_test_n_total counter" in text
    parsed = metrics_mod.parse_text(text)
    assert parsed["rmd_test_ready_flag"][()] == 1.0
    assert parsed["rmd_test_n_total"][()] == 7.0


# -- SLO burn-rate windows ----------------------------------------------------


def test_class_slo_burn_math():
    s = slo_mod.ClassSLO("fast", target_ms=50.0, objective=0.9,
                         window_s=60.0)
    for _ in range(8):
        assert s.record(0.010, now=100.0)       # good: 10ms <= 50ms
    for _ in range(2):
        assert not s.record(0.200, now=100.0)   # bad
    snap = s.snapshot(now=100.0)
    assert snap["good"] == 8 and snap["bad"] == 2
    assert snap["attainment"] == pytest.approx(0.8)
    # burn = (1 - 0.8) / (1 - 0.9): missing the objective 2x over budget
    assert snap["burn_rate"] == pytest.approx(2.0)


def test_class_slo_window_prunes():
    s = slo_mod.ClassSLO("fast", target_ms=50.0, window_s=10.0)
    s.record(0.200, now=100.0)  # bad, but ages out below
    s.record(0.010, now=111.0)
    snap = s.snapshot(now=111.0)
    assert snap["good"] == 1 and snap["bad"] == 0
    assert snap["attainment"] == 1.0 and snap["burn_rate"] == 0.0


def test_class_slo_validates_config():
    with pytest.raises(ValueError, match="target_ms"):
        slo_mod.ClassSLO("x", target_ms=0.0)
    with pytest.raises(ValueError, match="objective"):
        slo_mod.ClassSLO("x", target_ms=1.0, objective=1.0)


def test_slo_tracker_default_fallback_and_untracked():
    tracker = slo_mod.SLOTracker(
        class_targets={"fast": 20.0, "balanced": 0.0, "": 80.0},
        objective=0.99, window_s=60.0)
    # balanced had no target of its own: inherits the "" default
    assert tracker.classes() == ["", "balanced", "fast"]
    assert tracker
    snap = tracker.snapshot(now=10.0)
    assert snap["balanced"]["target_ms"] == 80.0
    assert tracker.record("quality", 0.001) is None  # untracked: ignored
    empty = slo_mod.SLOTracker(class_targets={"fast": 0.0, "": 0.0})
    assert not empty


def test_slo_tracker_emits_valid_rate_limited_events(_obs_hygiene):
    tracker = slo_mod.SLOTracker(class_targets={"fast": 50.0},
                                 objective=0.99, window_s=60.0,
                                 emit_interval_s=30.0)
    tracker.record("fast", 0.010, now=100.0)
    assert len(tracker.maybe_emit(_obs_hygiene, now=100.0)) == 1
    assert tracker.maybe_emit(_obs_hygiene, now=110.0) == []   # interval
    assert len(tracker.maybe_emit(_obs_hygiene, now=131.0)) == 1
    events = [e for e in _obs_hygiene.events if e["kind"] == "slo"]
    assert len(events) == 2
    for ev in events:
        core.validate_event(ev)  # slo events honor their SCHEMA entry
        assert ev["klass"] == "fast" and ev["target_ms"] == 50.0


# -- scheduler propagation (host-only fake session) ---------------------------


def test_scheduler_emits_linked_trace_events(_obs_hygiene):
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    try:
        img1, img2 = _pair((14, 20))
        res = sched.submit(img1, img2).result(timeout=10.0)
    finally:
        sched.stop(drain=True)
    # legacy spans stay untouched alongside the new decomposition
    for span in ("admission", "queue", "dispatch", "device", "total"):
        assert span in res.spans

    reqs = _trace_events(_obs_hygiene, "request")
    batches = _trace_events(_obs_hygiene, "batch")
    assert len(reqs) == 1 and len(batches) == 1
    for ev in reqs + batches:
        core.validate_event(ev)
    req, batch = reqs[0], batches[0]
    # fan-in linkage: the batch span names its member request spans
    assert req["trace"] in batch["members"]
    assert req["batch"] == batch["batch"]
    assert req["bucket"] == batch["bucket"] == "16x24"
    # exact critical-path decomposition: phases sum to end-to-end total
    assert set(req["phases"]) == set(trace_mod.PHASES)
    assert sum(req["phases"].values()) == pytest.approx(req["total"],
                                                        abs=1e-5)
    assert req["total"] * 1e3 <= res.spans["total"] * 1e3 + 1.0

    # the live aggregate saw the same record
    snap = sched.trace_summary.snapshot()
    assert snap["count"] == 1 and snap["tail"]["count"] == 1


def test_scheduler_metrics_counters(_obs_hygiene):
    reg = metrics_mod.registry()
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    try:
        for seed in range(3):
            sched.submit(*_pair((14, 20), seed=seed)).result(timeout=10.0)
    finally:
        sched.stop(drain=True)
    parsed = metrics_mod.parse_text(reg.render())
    key = (("bucket", "16x24"), ("klass", ""))
    assert parsed["rmd_serve_requests_total"][key] == 3.0
    assert parsed["rmd_serve_request_latency_seconds_count"][
        (("klass", ""),)] == 3.0
    assert sum(parsed["rmd_serve_batches_total"].values()) >= 1.0


def test_scheduler_heartbeat_and_queue_depths():
    sched = _fake_scheduler(batch_size=4, max_wait_ms=1e4)  # not started
    img1, img2 = _pair((14, 20))
    sched.submit(img1, img2)
    sched.submit(*_pair((30, 40)))
    depths = sched.queue_depths()
    assert depths == {"16x24": 1, "32x48": 1}
    assert sched.heartbeat_age() < 10.0
    sched.start()
    sched.stop(drain=True)
    time.sleep(0.01)
    assert sched.heartbeat_age() >= 0.0


# -- HTTP plane ---------------------------------------------------------------


def test_endpoints_over_real_socket(_obs_hygiene):
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    server = serve.serve_observer(sched.session, sched, port=0,
                                  sink=_obs_hygiene)
    try:
        # readiness gates /healthz: FakeSession has no ready attr -> 503
        code, health = _get(server.url + "/healthz")
        assert code == 503
        assert health["ready"] is False and health["live"] is True

        sched.session.ready = True  # what warm_pool() flips on the real one
        code, health = _get(server.url + "/healthz")
        assert code == 200 and health["ready"] is True

        for seed in range(4):
            sched.submit(*_pair((14, 20), seed=seed)).result(timeout=10.0)

        code, text = _get(server.url + "/metrics")
        assert code == 200
        parsed = metrics_mod.parse_text(text)
        key = (("bucket", "16x24"), ("klass", ""))
        assert parsed["rmd_serve_requests_total"][key] == 4.0
        assert parsed["rmd_serve_ready"][()] == 1.0
        assert parsed["rmd_telemetry_dropped_total"][()] == 0.0

        code, status = _get(server.url + "/statusz")
        assert code == 200
        assert status["requests"] == 4 and status["pending"] == 0
        assert status["classes"][""]["count"] == 4
        assert status["tail"]["count"] >= 1

        code, err = _get(server.url + "/nope")
        assert code == 404 and "no route" in err["error"]
    finally:
        server.close()
        sched.stop(drain=True)


def test_observer_liveness_goes_stale():
    sched = _fake_scheduler()  # never started: heartbeat only from init
    obs = observe.Observer(FakeSession(ShapeBuckets([(16, 24)])), sched,
                           registry=metrics_mod.MetricsRegistry(),
                           stale_heartbeat_s=1e-9)
    payload, code = obs.health()
    assert code == 503 and payload["live"] is False


# -- real tiny model: spans through a pad-tiled partial batch -----------------


@pytest.fixture(scope="module")
def tiny_session():
    spec = models.load(TINY_OBS_MODEL)
    return ServeSession(spec, ShapeBuckets([(32, 48)]),
                        wire=WireFormat.from_config("u8"), batch_size=2)


def test_readiness_flips_with_warm_pool_and_traces_flow(tiny_session,
                                                        _obs_hygiene):
    session = tiny_session
    if not session.ready:  # module fixture: first test in pays the warm-up
        obs = observe.Observer(session, _fake_scheduler(),
                               registry=metrics_mod.MetricsRegistry())
        assert not obs.ready()
        session.warm_pool()
    assert session.ready

    sched = Scheduler(session, max_wait_ms=1.0).start()
    server = serve.serve_observer(session, sched, port=0, sink=_obs_hygiene)
    try:
        code, health = _get(server.url + "/healthz")
        assert code == 200 and health["ready"] is True

        # partial batch (1 of 2) off-bucket: pad + tile to the full
        # program, the trace still decomposes exactly
        res = sched.submit(*_pair((28, 40), seed=7)).result(timeout=60.0)
        assert res.flow.shape == (28, 40, 2)

        reqs = _trace_events(_obs_hygiene, "request")
        batches = _trace_events(_obs_hygiene, "batch")
        assert len(reqs) == 1 and len(batches) == 1
        assert reqs[0]["trace"] in batches[0]["members"]
        assert batches[0]["fill"] == 1  # one live request, one pad slot
        assert batches[0]["program"]   # compiled-program fingerprint
        assert sum(reqs[0]["phases"].values()) == pytest.approx(
            reqs[0]["total"], abs=1e-5)
    finally:
        server.close()
        sched.stop(drain=True)


# -- forward compatibility (report reader) ------------------------------------


def test_load_events_skips_newer_producer_records(tmp_path):
    path = tmp_path / "events.jsonl"
    lines = [
        {"v": 1, "t": 1.0, "kind": "run_end"},                    # fine
        {"v": 1, "t": 2.0, "kind": "hologram", "x": 1},           # newer kind
        {"v": 1.5, "t": 3.0, "kind": "run_end"},                  # newer minor
        {"v": 99, "t": 4.0, "kind": "run_end"},                   # alien major
        {"v": 1, "t": 5.0, "kind": "cache", "event": "nope"},     # corrupt
    ]
    path.write_text("".join(json.dumps(e) + "\n" for e in lines))
    skipped = []
    events, errors = treport.load_events(path, skipped=skipped)
    assert [e["kind"] for e in events] == ["run_end"]
    # unknown kind + newer minor are warn-and-skip, not errors
    assert [n for n, _ in skipped] == [2, 3]
    # an alien major version and a corrupt record stay hard errors
    assert [n for n, _ in errors] == [4, 5]


def test_trace_and_slo_report_sections(_obs_hygiene):
    sink = _obs_hygiene
    for total, queue in ((0.010, 0.001), (0.012, 0.002), (0.200, 0.190)):
        sink.emit("trace", event="request", trace="req-x", batch="b-x",
                  klass="fast", bucket="16x24", total=total,
                  phases={"queue": queue, "device": total - queue})
    sink.emit("trace", event="batch", batch="b-x", bucket="16x24",
              klass="fast", size=3, fill=3, members=["req-x"],
              seconds=0.01, program="p@1")
    sink.emit("slo", klass="fast", target_ms=50.0, objective=0.99,
              window_s=60.0, good=2, bad=1, attainment=0.6667,
              burn_rate=33.33)

    tstats = treport.trace_stats(sink.events)
    assert tstats["requests"] == 3 and tstats["batches"] == 1
    assert tstats["classes"]["fast"]["count"] == 3
    assert tstats["tail"]["dominant"] == "queue"
    assert tstats["tail"]["queue_dominated"]

    sstats = treport.slo_stats(sink.events)
    assert sstats["classes"]["fast"]["worst_burn_rate"] == 33.33

    text = treport.render(sink.events)
    assert "== tracing ==" in text and "== slo ==" in text
    anomalies = treport.find_anomalies(sink.events)
    assert any("burn" in a for a in anomalies)
    assert any("queue-dominated" in a for a in anomalies)


# -- non-blocking bounded sink ------------------------------------------------


def test_nonblocking_sink_drops_and_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("RMD_TELEMETRY_BUFFER", "4")
    path = tmp_path / "events.jsonl"
    sink = telemetry.Telemetry(path, nonblocking=True)
    # jam the disk: the writer thread blocks on the io lock, the bounded
    # queue fills, further emits are shed and counted -- never blocking
    with sink._io_lock:
        for i in range(100):
            sink.emit("cache", event="hit", n=i)
        time.sleep(0.05)  # emit() returned instantly every time
        dropped = sink.dropped()
        assert dropped >= 100 - 2 * 4  # at most 2 batches escaped the queue
    sink.close()
    written = sum(1 for _ in open(path))
    assert written + sink.dropped() == 100
    assert sink.dropped() >= dropped


def test_blocking_and_null_sinks_never_drop():
    assert telemetry.Telemetry().dropped() == 0
    assert telemetry.NullTelemetry().dropped() == 0


def test_rotation_caps_file_size(tmp_path, monkeypatch):
    monkeypatch.setenv("RMD_TELEMETRY_MAX_MB", "0.0002")  # ~200 bytes
    path = tmp_path / "events.jsonl"
    sink = telemetry.Telemetry(path)
    for i in range(40):
        # an unbuffered kind: every emit is its own write batch, so the
        # size check runs (buffered kinds only rotate at flush points)
        sink.emit("run_end", n=i)
    sink.close()
    rotated = tmp_path / "events.jsonl.1"
    assert rotated.exists()
    max_bytes = int(0.0002 * 2 ** 20)
    assert path.stat().st_size <= max_bytes + 200
    # both generations still parse line-by-line
    for f in (path, rotated):
        for line in f.read_text().splitlines():
            assert json.loads(line)["kind"] == "run_end"


def test_rotation_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("RMD_TELEMETRY_MAX_MB", raising=False)
    path = tmp_path / "events.jsonl"
    sink = telemetry.Telemetry(path)
    for i in range(40):
        sink.emit("cache", event="hit", n=i)
    sink.close()
    assert not (tmp_path / "events.jsonl.1").exists()
    assert sum(1 for _ in open(path)) == 40


# -- graftlint: telemetry-unregistered-kind -----------------------------------


def mk(source, rel="raft_meets_dicl_tpu/serve/fixture.py"):
    import textwrap
    return Module(rel, rel, textwrap.dedent(source))


def test_lint_flags_unregistered_emit_kind():
    findings = telemetrykinds.check(mk("""
        tele.emit("run_end")
        tele.emit("telport", step=3)
        tele.emit(kind="hologram")
        tele.emit(kind)          # computed: runtime's problem
        queue.emit("not telemetry")
    """))
    msgs = [f.message for f in findings]
    assert len(findings) == 3
    assert any("'telport'" in m for m in msgs)
    assert any("'hologram'" in m for m in msgs)
    assert any("'not telemetry'" in m for m in msgs)


def test_lint_enforces_metric_name_convention():
    findings = telemetrykinds.check(mk("""
        reg.counter("rmd_serve_requests_total", "ok")
        reg.gauge("rmd_serve_queue_depth", "ok")
        reg.histogram("serve_latency_seconds", "no prefix")
        reg.counter("rmd_serve_shed", "no _total suffix")
        reg.gauge(name_var, "computed: skipped")
        histogram("rmd_bad_but_bare", "numpy import, not the registry")
    """))
    assert len(findings) == 2
    assert "breaks the" in findings[0].message
    assert "must end in _total" in findings[1].message


def test_lint_rule_registered_in_default_set():
    from raft_meets_dicl_tpu.analysis import lint as lint_mod
    names = {r.name for r in lint_mod.default_rules()}
    assert telemetrykinds.RULE in names
