"""Strategy layer tests: specs, schedulers (torch parity), optimizers,
checkpoints, and the full TrainingContext loop."""

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
import raft_meets_dicl_tpu.strategy as strategy
from raft_meets_dicl_tpu.data.collection import (
    Collection, Metadata, SampleArgs, SampleId,
)
from raft_meets_dicl_tpu.strategy.spec import (
    MultiStepLr, OneCycleLr, OptimizerSpec, SchedulerSpec,
)
from raft_meets_dicl_tpu.utils.logging import Logger


class FlowSource(Collection):
    """Synthetic constant-translation flow dataset."""

    type = "fake-flow"

    def __init__(self, n=4, h=32, w=48):
        self.n, self.h, self.w = n, h, w

    def __getitem__(self, index):
        rng = np.random.RandomState(index)
        base = rng.rand(self.h, self.w + 8, 3).astype(np.float32)
        img1 = base[:, :-8]
        img2 = base[:, 8:]
        flow = np.zeros((self.h, self.w, 2), np.float32)
        flow[..., 0] = 8.0
        valid = np.ones((self.h, self.w), bool)
        meta = Metadata(True, "fake", SampleId("s{i}", SampleArgs([], {"i": index}),
                                               SampleArgs([], {"i": index + 1})),
                        ((0, self.h), (0, self.w)))
        return img1[None], img2[None], flow[None], valid[None], [meta]

    def __len__(self):
        return self.n

    def get_config(self):
        return {"type": self.type, "n": self.n}

    def description(self):
        return "fake flow"


def test_one_cycle_matches_torch():
    import torch

    total, max_lr = 50, 4e-4
    params = [torch.nn.Parameter(torch.zeros(1))]
    opt = torch.optim.SGD(params, lr=max_lr)
    tsched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr=max_lr, total_steps=total, pct_start=0.05,
        anneal_strategy="linear", cycle_momentum=False,
    )

    ours = OneCycleLr(max_lr, max_lr=max_lr, total_steps=total, pct_start=0.05,
                      anneal_strategy="linear", cycle_momentum=False)

    for step in range(total):
        torch_lr = opt.param_groups[0]["lr"]
        np.testing.assert_allclose(ours.lr(), torch_lr, rtol=1e-6,
                                   err_msg=f"step {step}")
        opt.step()
        tsched.step()
        ours.step()


def test_one_cycle_cos_matches_torch():
    import torch

    total, max_lr = 40, 1e-3
    params = [torch.nn.Parameter(torch.zeros(1))]
    opt = torch.optim.SGD(params, lr=max_lr)
    tsched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr=max_lr, total_steps=total, pct_start=0.3,
        cycle_momentum=False,
    )

    ours = OneCycleLr(max_lr, max_lr=max_lr, total_steps=total, pct_start=0.3,
                      cycle_momentum=False)

    for step in range(total):
        np.testing.assert_allclose(ours.lr(), opt.param_groups[0]["lr"],
                                   rtol=1e-5, err_msg=f"step {step}")
        opt.step()
        tsched.step()
        ours.step()


def test_multi_step_lr():
    s = MultiStepLr(1.0, milestones=[3, 6], gamma=0.1)
    lrs = []
    for _ in range(8):
        lrs.append(s.lr())
        s.step()
    np.testing.assert_allclose(lrs[:3], 1.0)
    np.testing.assert_allclose(lrs[3:6], 0.1)
    np.testing.assert_allclose(lrs[6:], 0.01)


def test_scheduler_expression_params():
    spec = SchedulerSpec.from_config({
        "type": "one-cycle",
        "parameters": {"max_lr": 4e-4, "total_steps": "{n_batches} * {n_epochs} + 100",
                       "pct_start": 0.05, "cycle_momentum": False,
                       "anneal_strategy": "linear"},
    })
    sched = spec.build(4e-4, {"n_batches": 100, "n_epochs": 10, "n_samples": 1000,
                              "n_accum": 1, "batch_size": 10})
    assert sched.total_steps == 1100


def test_adamw_single_step_matches_torch():
    import jax.numpy as jnp
    import optax
    import torch

    w0 = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    g = np.random.RandomState(1).randn(5, 3).astype(np.float32)
    lr, wd = 1e-3, 0.05

    # torch
    p = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.AdamW([p], lr=lr, weight_decay=wd, eps=1e-8)
    p.grad = torch.from_numpy(g.copy())
    opt.step()

    # ours
    spec = OptimizerSpec("adam-w", {"lr": lr, "weight_decay": wd, "eps": 1e-8})
    tx, base_lr = spec.build()
    assert base_lr == lr
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.asarray(g)}, state, params)
    new = optax.apply_updates(params, {"w": -lr * updates["w"]})

    np.testing.assert_allclose(np.asarray(new["w"]), p.detach().numpy(),
                               atol=1e-6)


def test_adam_l2_single_step_matches_torch():
    import jax.numpy as jnp
    import optax
    import torch

    w0 = np.random.RandomState(2).randn(4, 4).astype(np.float32)
    g = np.random.RandomState(3).randn(4, 4).astype(np.float32)
    lr, wd = 1e-3, 0.1

    p = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = torch.optim.Adam([p], lr=lr, weight_decay=wd, eps=1e-8)
    p.grad = torch.from_numpy(g.copy())
    opt.step()

    spec = OptimizerSpec("adam", {"lr": lr, "weight_decay": wd, "eps": 1e-8})
    tx, _ = spec.build()
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.asarray(g)}, state, params)
    new = optax.apply_updates(params, {"w": -lr * updates["w"]})

    np.testing.assert_allclose(np.asarray(new["w"]), p.detach().numpy(),
                               atol=1e-6)


def test_stage_config_roundtrip(tmp_path):
    cfg = {
        "name": "test stage", "id": "test/s0",
        "data": {"epochs": 2, "batch-size": 2,
                 "source": {"type": "fake-flow", "n": 4}},
        "optimizer": {"type": "adam-w", "parameters": {"lr": 4e-4}},
        "lr-scheduler": {"instance": [{"type": "one-cycle", "parameters": {
            "max_lr": 4e-4, "total_steps": "100", "pct_start": 0.05,
            "cycle_momentum": False, "anneal_strategy": "linear"}}]},
        "gradient": {"clip": {"type": "norm", "value": 1.0}},
    }

    # fake-flow isn't a registered data type; patch the registry for the test
    import raft_meets_dicl_tpu.data.config as dc

    dc._TYPES["fake-flow"] = type(
        "F", (), {"from_config": staticmethod(lambda path, c: FlowSource(c["n"]))}
    )
    try:
        stage = strategy.spec.Stage.from_config(tmp_path, cfg)
        out = stage.get_config()
        assert out["id"] == "test/s0"
        assert out["gradient"]["clip"]["value"] == 1.0
        assert out["optimizer"]["parameters"]["lr"] == 4e-4
    finally:
        del dc._TYPES["fake-flow"]


TINY_MODEL = {
    "name": "tiny", "id": "tiny",
    "model": {
        "type": "raft/baseline",
        "parameters": {"corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
                       "context-channels": 16, "recurrent-channels": 16},
        "arguments": {"iterations": 2},
    },
    "loss": {"type": "raft/sequence"},
    "input": None,
}


def _make_stage(epochs=1, accumulate=1):
    return strategy.spec.Stage(
        name="s0", id="test/s0",
        data=strategy.spec.DataSpec(FlowSource(4), epochs=epochs, batch_size=2),
        validation=[],
        optimizer=strategy.spec.OptimizerSpec("adam", {"lr": 1e-3}),
        gradient=strategy.spec.GradientSpec(
            accumulate=accumulate,
            clip=strategy.spec.ClipGradientNorm(1.0),
        ),
        scheduler=strategy.spec.MultiSchedulerSpec(
            instance=[SchedulerSpec("one-cycle", {
                "max_lr": 1e-3, "total_steps": "{n_batches} * {n_epochs}",
                "pct_start": 0.3, "cycle_momentum": False})],
        ),
    )


def _make_context(tmp_path, stages, mode="continuous", step_limit=None):
    spec = models.load(TINY_MODEL)
    mgr = strategy.CheckpointManager(
        "tiny", tmp_path / "checkpoints",
        "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt",
        compare=["{m_loss}"], keep_best=2, keep_latest=2,
    )
    log = Logger("test")
    ctx = strategy.TrainingContext(
        log, tmp_path, strategy.Strategy(mode, stages), "tiny",
        spec.model, spec.model.get_adapter(), spec.loss, spec.input,
        strategy.Inspector(), mgr, step_limit=step_limit,
        loader_args={"num_workers": 0},
    )
    return ctx, mgr


@pytest.mark.slow
def test_training_context_runs(tmp_path):
    ctx, _ = _make_context(tmp_path, [_make_stage(epochs=1)])
    ctx.run()
    assert ctx.step == 2  # 4 samples / batch 2
    assert ctx.variables is not None


@pytest.mark.slow
def test_training_context_grad_accum(tmp_path):
    ctx, _ = _make_context(tmp_path, [_make_stage(epochs=1, accumulate=2)])
    ctx.run()
    assert ctx.step == 1  # 2 batches, accumulate 2 → 1 optimizer step


@pytest.mark.slow
def test_training_context_step_limit(tmp_path):
    ctx, _ = _make_context(tmp_path, [_make_stage(epochs=3)], step_limit=3)
    ctx.run()
    assert ctx.step == 3


@pytest.mark.slow
def test_checkpoint_roundtrip(tmp_path):
    ctx, mgr = _make_context(tmp_path, [_make_stage(epochs=1)])
    ctx.run()

    stage = ctx.current_stage
    mgr.create(ctx.log, ctx, stage, epoch=0, step=ctx.step,
               metrics={"loss": 1.5})
    assert len(mgr.checkpoints) == 1

    entry = mgr.get_latest()
    chkpt = entry.load()
    assert chkpt.model == "tiny"
    assert chkpt.iteration.step == ctx.step
    assert chkpt.metrics == {"loss": 1.5}

    # weights restore into a fresh context
    ctx2, _ = _make_context(tmp_path, [_make_stage(epochs=1)])
    ctx2._ensure_variables(ctx2.strategy.stages[0])
    restored, _, _ = chkpt.apply(variables=ctx2.variables)

    import jax

    a = jax.tree.leaves(restored["params"])
    b = jax.tree.leaves(ctx.variables["params"])
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-7)


def test_checkpoint_manager_trim(tmp_path):
    mgr = strategy.CheckpointManager(
        "m", tmp_path, "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt",
        compare=["{m_epe}"],
    )

    # fabricate entries with files
    for step, epe in [(1, 3.0), (2, 1.0), (3, 2.0), (4, 5.0)]:
        p = tmp_path / f"m-s0_e0_b{step}.ckpt"
        p.write_bytes(b"RMDT1\nx")
        mgr.checkpoints.append(
            strategy.checkpoint.CheckpointEntry("m", 0, 0, step, {"epe": epe}, p)
        )

    mgr.trim(n_best=1, n_latest=1)
    steps = sorted(c.idx_step for c in mgr.checkpoints)
    assert steps == [2, 4]  # best (epe 1.0) + latest
    assert not (tmp_path / "m-s0_e0_b1.ckpt").exists()


@pytest.mark.slow
def test_training_resume_mid_stage(tmp_path):
    # train one epoch of two, checkpoint, then resume epoch 2
    ctx, mgr = _make_context(tmp_path, [_make_stage(epochs=2)], step_limit=2)
    ctx.run()
    assert ctx.step == 2

    mgr.create(ctx.log, ctx, ctx.current_stage, epoch=0, step=ctx.step,
               metrics={"loss": 1.0})
    chkpt = mgr.get_latest().load()

    ctx2, _ = _make_context(tmp_path, [_make_stage(epochs=2)])
    ctx2.run(checkpoint=chkpt)
    # resumed from epoch 1: 2 more batches
    assert ctx2.step == 4
