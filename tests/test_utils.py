import json

import numpy as np
import pytest

from raft_meets_dicl_tpu.utils import config, expr, seeds


class TestConfig:
    def test_yaml_roundtrip(self, tmp_path):
        cfg = {"b": 1, "a": {"nested": [1, 2, 3]}, "c": "str"}
        p = tmp_path / "cfg.yaml"
        config.store(p, cfg)
        assert config.load(p) == cfg

    def test_json_roundtrip(self, tmp_path):
        cfg = {"x": 1.5, "y": [{"z": None}]}
        p = tmp_path / "cfg.json"
        config.store(p, cfg)
        assert config.load(p) == cfg

    def test_yaml_preserves_order(self, tmp_path):
        cfg = {"zeta": 1, "alpha": 2, "mid": 3}
        p = tmp_path / "cfg.yaml"
        config.store(p, cfg)
        text = p.read_text()
        assert text.index("zeta") < text.index("alpha") < text.index("mid")

    def test_resolve_path(self, tmp_path):
        base = tmp_path / "strategy" / "main.yaml"
        assert config.resolve_path(base, "../data/chairs.yaml") == (tmp_path / "data" / "chairs.yaml").resolve()
        assert config.resolve_path(base, "/abs/x.yaml") == config.resolve_path(base, "/abs/x.yaml")


class TestExpr:
    def test_plain_number_passthrough(self):
        assert expr.eval_math_expr(42) == 42
        assert expr.eval_math_expr(1.5) == 1.5

    def test_arithmetic(self):
        assert expr.eval_math_expr("100000 + 100") == 100100
        assert expr.eval_math_expr("2 ** 10") == 1024
        assert expr.eval_math_expr("7 // 2 + 7 % 2") == 4

    def test_variables(self):
        assert expr.eval_math_expr("{n_epochs} * {n_batches}", n_epochs=2, n_batches=50) == 100
        assert expr.eval_math_expr("{batch_size} / {n_accum}", batch_size=8, n_accum=2) == 4.0

    def test_functions(self):
        assert expr.eval_math_expr("min(3, 5)") == 3
        assert expr.eval_math_expr("round(2.6)") == 3

    def test_rejects_unsafe(self):
        with pytest.raises(Exception):
            expr.eval_math_expr("__import__('os').system('true')")
        with pytest.raises(Exception):
            expr.eval_math_expr("open('/etc/passwd')")


class TestSeeds:
    def test_roundtrip(self):
        s = seeds.Seeds(python=1, numpy=2, jax=3)
        s2 = seeds.Seeds.from_config(s.get_config())
        assert s2.get_config() == s.get_config()

    def test_apply_deterministic(self):
        s = seeds.Seeds(python=1, numpy=2, jax=3)
        key1 = s.apply()
        a = np.random.rand(3)
        key2 = s.apply()
        b = np.random.rand(3)
        assert np.allclose(a, b)
        assert (np.asarray(key1) == np.asarray(key2)).all()

    def test_new_random_distinct(self):
        assert seeds.random_seeds().get_config() != seeds.random_seeds().get_config()
