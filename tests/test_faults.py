"""Fault-injection and recovery-path tests (testing.faults harness).

Every recovery path the fault-tolerance layer promises is proven end to
end here, CPU-only: checkpoint CRC verify + quarantine + fallback,
non-finite skip/rollback policies (including bit-identical params across
a skipped update), decode-worker respawn, per-sample retry/substitute,
and the SIGTERM emergency save + auto-resume round trip.
"""

from pathlib import Path

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
import raft_meets_dicl_tpu.strategy as strategy
from raft_meets_dicl_tpu import telemetry
from raft_meets_dicl_tpu.data.collection import (
    Metadata, SampleArgs, SampleId,
)
from raft_meets_dicl_tpu.strategy.checkpoint import (
    Checkpoint, CheckpointCorrupt, CheckpointEntry, Iteration, State,
    find_auto_resume, quarantine,
)
from raft_meets_dicl_tpu.testing import faults
from raft_meets_dicl_tpu.utils.logging import Logger
from test_strategy import TINY_MODEL, _make_stage

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _fault_hygiene(monkeypatch):
    """Every test starts unarmed, with a fresh memory telemetry sink and
    the finite check at every step (deterministic trip detection)."""
    monkeypatch.delenv("RMD_FAULT", raising=False)
    monkeypatch.delenv("RMD_FAULT_STATE", raising=False)
    monkeypatch.setenv("RMD_FINITE_CHECK_EVERY", "1")
    faults.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    yield sink
    telemetry.deactivate()
    faults.reset()


def _events(sink, kind):
    return [e for e in sink.events if e["kind"] == kind]


# -- fixtures ----------------------------------------------------------------


def _tiny_checkpoint(step=1, stage=0, epoch=0, model="tiny"):
    rng = np.random.RandomState(step)
    return Checkpoint(
        model=model,
        iteration=Iteration(stage, epoch, step),
        metrics={"loss": float(step)},
        state=State(
            model={"params": {"w": rng.randn(8).astype(np.float32)}},
            optimizer={},
            scaler={},
            lr_sched_inst=[],
            lr_sched_epoch=[],
        ),
        metadata={"source": "test"},
    )


class SynthFlow:
    """Tiny flow samples; consults the decode_error fault directive."""

    def __init__(self, n=4, h=32, w=48):
        self.n, self.h, self.w = n, h, w

    def __getitem__(self, index):
        if faults.fire("decode_error", index=index) is not None:
            raise IOError(f"injected decode failure on sample {index}")
        rng = np.random.RandomState(index)
        base = rng.rand(self.h, self.w + 8, 3).astype(np.float32)
        img1, img2 = base[:, :-8], base[:, 8:]
        flow = np.zeros((self.h, self.w, 2), np.float32)
        flow[..., 0] = 8.0
        valid = np.ones((self.h, self.w), bool)
        meta = Metadata(True, "synth",
                        SampleId("s{i}", SampleArgs([], {"i": index}),
                                 SampleArgs([], {"i": index + 1})),
                        ((0, self.h), (0, self.w)))
        return img1[None], img2[None], flow[None], valid[None], [meta]

    def __len__(self):
        return self.n

    def get_config(self):
        return {"type": "synth-flow", "n": self.n}

    def description(self):
        return "synth flow"


def _make_context(tmp_path, nonfinite=None, epochs=1, step_limit=None,
                  keep=2):
    tmp_path = Path(tmp_path)
    tmp_path.mkdir(parents=True, exist_ok=True)
    spec = models.load(TINY_MODEL)
    mgr = strategy.CheckpointManager(
        "tiny", tmp_path / "checkpoints",
        "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt",
        compare=["{m_loss}"], keep_best=keep, keep_latest=keep,
    )
    ctx = strategy.TrainingContext(
        Logger("test"), tmp_path, strategy.Strategy(
            "continuous", [_make_stage(epochs=epochs)]),
        "tiny", spec.model, spec.model.get_adapter(), spec.loss, spec.input,
        strategy.Inspector(), mgr, step_limit=step_limit,
        loader_args={"num_workers": 0}, nonfinite=nonfinite,
    )
    return ctx, mgr


# -- checkpoint integrity ----------------------------------------------------


def test_checkpoint_crc_roundtrip(tmp_path):
    ck = _tiny_checkpoint(step=5)
    ck.save(tmp_path / "a.ckpt")
    ld = Checkpoint.load(tmp_path / "a.ckpt")
    assert ld.iteration.step == 5
    np.testing.assert_array_equal(ld.state.model["params"]["w"],
                                  ck.state.model["params"]["w"])


def test_checkpoint_legacy_v1_still_loads(tmp_path):
    from flax import serialization

    from raft_meets_dicl_tpu.strategy import checkpoint as chk

    ck = _tiny_checkpoint(step=3)
    payload = serialization.msgpack_serialize(chk._to_host(ck.to_dict()))
    (tmp_path / "v1.ckpt").write_bytes(chk._MAGIC_V1 + payload)
    ld = Checkpoint.load(tmp_path / "v1.ckpt")
    assert ld.iteration.step == 3


def test_checkpoint_bitflip_detected_and_quarantined(tmp_path, _fault_hygiene):
    p = tmp_path / "a.ckpt"
    _tiny_checkpoint().save(p)
    faults.corrupt_file(p)
    with pytest.raises(CheckpointCorrupt):
        Checkpoint.load(p)
    moved = quarantine(p)
    assert not p.exists()
    assert moved.name == "a.ckpt.corrupt" and moved.exists()
    ev = _events(_fault_hygiene, "quarantine")
    assert ev and ev[0]["path"].endswith("a.ckpt")


def test_checkpoint_truncation_detected(tmp_path):
    p = tmp_path / "a.ckpt"
    _tiny_checkpoint().save(p)
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) // 2])
    with pytest.raises(CheckpointCorrupt):
        Checkpoint.load(p)


def test_corrupt_checkpoint_fault_directive(tmp_path, monkeypatch):
    monkeypatch.setenv("RMD_FAULT", "corrupt_checkpoint@nth=2")
    faults.reset()
    from raft_meets_dicl_tpu.strategy import checkpoint as chk

    monkeypatch.setattr(chk, "_SAVES", 0)
    _tiny_checkpoint(step=1).save(tmp_path / "a.ckpt")
    _tiny_checkpoint(step=2).save(tmp_path / "b.ckpt")
    Checkpoint.load(tmp_path / "a.ckpt")  # untouched
    with pytest.raises(CheckpointCorrupt):
        Checkpoint.load(tmp_path / "b.ckpt")


def test_manager_falls_back_to_next_valid(tmp_path, _fault_hygiene):
    mgr = strategy.CheckpointManager(
        "m", tmp_path, "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}.ckpt",
        compare=["{m_loss}"])
    for step in (1, 2):
        p = tmp_path / f"m-s0_e0_b{step}.ckpt"
        _tiny_checkpoint(step=step, model="m").save(p)
        mgr.checkpoints.append(
            CheckpointEntry("m", 0, 0, step, {"loss": 1.0}, p))
    faults.corrupt_file(tmp_path / "m-s0_e0_b2.ckpt")

    entry, chkpt = mgr.load_valid(sort="latest", log=Logger("test"))
    assert chkpt.iteration.step == 1
    assert (tmp_path / "m-s0_e0_b2.ckpt.corrupt").exists()
    assert len(mgr.checkpoints) == 1
    assert _events(_fault_hygiene, "quarantine")


def test_find_auto_resume_picks_furthest_valid(tmp_path):
    (tmp_path / "runA").mkdir()
    _tiny_checkpoint(step=2).save(tmp_path / "runA" / "x.ckpt")
    (tmp_path / "runB").mkdir()
    _tiny_checkpoint(step=7, epoch=1).save(tmp_path / "runB" / "y.ckpt")
    # poisoned post-mortem dumps are never resume candidates
    _tiny_checkpoint(step=99).save(tmp_path / "runB" / "failed.ckpt")

    file, chkpt = find_auto_resume(tmp_path)
    assert file.name == "y.ckpt"
    assert chkpt.iteration.step == 7


def test_find_auto_resume_quarantines_and_falls_back(tmp_path):
    _tiny_checkpoint(step=3).save(tmp_path / "old.ckpt")
    _tiny_checkpoint(step=9).save(tmp_path / "new.ckpt")
    faults.corrupt_file(tmp_path / "new.ckpt")

    file, chkpt = find_auto_resume(tmp_path)
    assert file.name == "old.ckpt"
    assert chkpt.iteration.step == 3
    assert (tmp_path / "new.ckpt.corrupt").exists()
    assert find_auto_resume(tmp_path / "does-not-exist") is None


def test_background_write_failure_surfaces(tmp_path, monkeypatch):
    """A writer-thread exception must mark the entry failed and re-raise
    at the next wait()/create() instead of vanishing with the Future."""
    import time

    from raft_meets_dicl_tpu.strategy import checkpoint as chk

    ctx, mgr = _make_context(tmp_path)
    ctx._ensure_variables(ctx.strategy.stages[0])
    stage = ctx.strategy.stages[0]
    stage.index = 0

    orig_write = chk._write_atomic

    def boom(path, payload):
        raise OSError("disk full (injected)")

    # first create: write fails on the background thread
    monkeypatch.setattr(chk, "_write_atomic", boom)
    monkeypatch.setenv("RMD_ASYNC_CHECKPOINT", "1")
    mgr.create(Logger("test"), ctx, stage, 0, 1, {"loss": 1.0})
    failed = mgr.checkpoints[-1]
    for _ in range(100):  # let the writer thread resolve the future
        if failed.pending is None or failed.pending.done():
            break
        time.sleep(0.05)

    # wait() surfaces it, marks the entry failed, queries skip it
    with pytest.raises(RuntimeError, match="background checkpoint write"):
        failed.wait()
    assert failed.failed
    assert mgr.get_latest() is None

    # a fresh failed pending surfaces at the next create() instead
    monkeypatch.setattr(chk, "_write_atomic", boom)
    mgr.checkpoints = []
    mgr.create(Logger("test"), ctx, stage, 0, 2, {"loss": 1.0})
    entry = mgr.checkpoints[-1]
    for _ in range(100):
        if entry.pending is None or entry.pending.done():
            break
        time.sleep(0.05)
    monkeypatch.setattr(chk, "_write_atomic", orig_write)
    with pytest.raises(RuntimeError, match="background checkpoint write"):
        mgr.create(Logger("test"), ctx, stage, 0, 3, {"loss": 1.0})
    assert entry not in mgr.checkpoints


# -- non-finite step recovery ------------------------------------------------


def test_skip_guard_leaves_params_bit_identical():
    """A poisoned update under the skip guard must not move a single bit
    of params/opt state, and the device trip counter must advance."""
    import jax
    import optax

    from raft_meets_dicl_tpu import parallel

    spec = models.load(TINY_MODEL)
    model, loss = spec.model, spec.loss
    src = SynthFlow(1)
    img1, img2, flow, valid, _ = src[0]

    variables = model.init(jax.random.PRNGKey(0), img1, img2)
    tx = optax.adam(1e-3)
    state = parallel.TrainState.create(variables, tx)
    step = parallel.make_train_step(model, loss, tx, external_lr=True,
                                    donate=False, nonfinite="skip")

    before = jax.device_get(state.params)
    state, aux = step(state, float("nan"), img1, img2, flow, valid)
    assert not bool(aux["finite"])
    assert int(aux["nonfinite_count"]) == 1
    after = jax.device_get(state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)

    # a clean step still applies and the counter holds
    state, aux = step(state, 1e-3, img1, img2, flow, valid)
    assert bool(aux["finite"])
    assert int(aux["nonfinite_count"]) == 1
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(before),
                        jax.tree.leaves(jax.device_get(state.params))))
    assert changed


def test_training_skip_policy_continues(tmp_path, monkeypatch,
                                        _fault_hygiene):
    monkeypatch.setenv("RMD_FAULT", "nan_update@step=1")
    faults.reset()
    ctx, _ = _make_context(tmp_path, nonfinite="skip")
    ctx.run()
    assert ctx.step == 2  # the run completed despite the poisoned step

    evs = [e for e in _events(_fault_hygiene, "nonfinite")
           if e.get("action") == "skip"]
    assert evs and evs[0]["trips"] == 1
    # offending batch reproducible offline: sample ids attached
    assert any(s["samples"] for s in evs[0]["samples"])


def test_training_skip_policy_escalates(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "RMD_FAULT", ",".join(f"nan_update@step={i}" for i in range(8)))
    faults.reset()
    ctx, _ = _make_context(
        tmp_path, nonfinite={"policy": "skip", "max-consecutive": 2},
        epochs=3)
    with pytest.raises(RuntimeError, match="persist"):
        ctx.run()
    assert (Path(tmp_path) / "failed.ckpt").exists()


def test_training_rollback_restores_checkpoint(tmp_path, monkeypatch,
                                               _fault_hygiene):
    monkeypatch.setenv("RMD_FAULT", "nan_update@step=2,nan_update@step=3")
    faults.reset()
    ctx, mgr = _make_context(
        tmp_path,
        nonfinite={"policy": "rollback", "max-consecutive": 2},
        epochs=3)

    # checkpoint after the first (clean) epoch, like a validation pass
    orig = strategy.TrainingContext.run_epoch

    def run_epoch(self, log, stage, epoch):
        orig(self, log, stage, epoch)
        if epoch == 0:
            mgr.create(log, self, stage, epoch, self.step, {"loss": 1.0})

    monkeypatch.setattr(strategy.TrainingContext, "run_epoch", run_epoch)
    ctx.run()

    rb = [e for e in _events(_fault_hygiene, "nonfinite")
          if e.get("action") == "rollback"]
    assert rb, "rollback must fire after persistent trips"
    assert rb[0]["to_step"] == 2 and rb[0]["from_step"] >= rb[0]["to_step"]


# -- preemption + auto-resume ------------------------------------------------


def test_sigterm_emergency_save_and_auto_resume(tmp_path, monkeypatch,
                                                _fault_hygiene):
    monkeypatch.setenv("RMD_FAULT", "sigterm@step=1")
    faults.reset()
    ctx, _ = _make_context(tmp_path, epochs=2)
    assert ctx.install_signal_handlers()
    ctx.run()

    # the in-flight step finished, then the run stopped cleanly
    assert ctx._stop == "SIGTERM"
    saved_step = ctx.step
    assert saved_step < 4  # 2 epochs x 2 batches would be 4: stopped early

    preempts = _events(_fault_hygiene, "preempt")
    assert preempts and preempts[0]["signal"] == "SIGTERM"
    emergency = [e for e in _events(_fault_hygiene, "checkpoint")
                 if e.get("source") == "emergency"]
    assert emergency

    # --resume auto discovers the emergency save and resumes at its step
    found = find_auto_resume(tmp_path, model="tiny")
    assert found is not None
    file, chkpt = found
    assert "emergency" in file.name
    assert chkpt.iteration.step == saved_step

    ctx2, _ = _make_context(tmp_path, epochs=2)
    ctx2.run(checkpoint=chkpt)
    assert ctx2.step > saved_step  # continued from, not restarted


def test_request_stop_without_signal(tmp_path):
    ctx, _ = _make_context(tmp_path)
    ctx.request_stop("TEST")
    assert ctx._stop == "TEST"


# -- self-healing input pipeline ---------------------------------------------


def test_loader_retry_absorbs_transient_failure(monkeypatch,
                                                _fault_hygiene):
    from raft_meets_dicl_tpu.models.input import Loader

    monkeypatch.setenv("RMD_FAULT", "decode_error@index=2;times=1")
    faults.reset()
    ld = Loader(SynthFlow(4, 8, 8), batch_size=2, num_workers=0, retries=2)
    batches = list(ld)
    assert sum(b[0].shape[0] for b in batches) == 4
    assert ld._bad_samples == 0  # retry succeeded, no substitution


def test_loader_substitutes_persistent_bad_sample(monkeypatch,
                                                  _fault_hygiene):
    from raft_meets_dicl_tpu.models.input import Loader

    monkeypatch.setenv("RMD_FAULT", "decode_error@index=3;times=5")
    faults.reset()
    ld = Loader(SynthFlow(4, 8, 8), batch_size=2, num_workers=2, retries=1,
                bad_sample_budget=4)
    batches = list(ld)
    # batch count and shapes unchanged: the bad sample was substituted
    assert sum(b[0].shape[0] for b in batches) == 4
    assert ld._bad_samples == 1
    ev = _events(_fault_hygiene, "bad_sample")
    assert ev and ev[0]["index"] == 3


def test_loader_bad_sample_budget_aborts(monkeypatch):
    from raft_meets_dicl_tpu.models.input import Loader

    monkeypatch.setenv(
        "RMD_FAULT",
        ",".join(f"decode_error@index={i};times=99" for i in range(4)))
    faults.reset()
    ld = Loader(SynthFlow(4, 8, 8), batch_size=2, num_workers=0, retries=0,
                bad_sample_budget=1)
    with pytest.raises(RuntimeError, match="bad-sample budget"):
        list(ld)


def test_decode_pool_respawns_dead_worker(tmp_path, monkeypatch,
                                          _fault_hygiene):
    from raft_meets_dicl_tpu.models.input import Loader

    monkeypatch.setenv("RMD_FAULT", "kill_worker@index=2")
    monkeypatch.setenv("RMD_FAULT_STATE", str(tmp_path))
    monkeypatch.setenv("RMD_LOADER_POLL", "0.2")
    monkeypatch.setenv("RMD_LOADER_TIMEOUT", "60")
    faults.reset()

    ld = Loader(SynthFlow(6, 8, 8), batch_size=2, procs=2)
    batches = list(ld)
    assert sum(b[0].shape[0] for b in batches) == 6
    ev = _events(_fault_hygiene, "respawn")
    assert ev and ev[0]["exitcode"] == 17


def test_decode_pool_worker_error_retried(tmp_path, monkeypatch):
    from raft_meets_dicl_tpu.models.input import Loader

    monkeypatch.setenv("RMD_FAULT", "decode_error@index=1;times=1")
    monkeypatch.setenv("RMD_FAULT_STATE", str(tmp_path))
    faults.reset()

    ld = Loader(SynthFlow(4, 8, 8), batch_size=2, procs=2, retries=2)
    batches = list(ld)
    assert sum(b[0].shape[0] for b in batches) == 4
    assert ld._bad_samples == 0


def test_decode_pool_respawn_budget_exhausts(tmp_path, monkeypatch):
    """max_respawns=0: the first worker death immediately exhausts the
    budget and surfaces as PoolBroken (not a hang, not a retry)."""
    from raft_meets_dicl_tpu.models.mpdecode import DecodePool, PoolBroken

    monkeypatch.setenv("RMD_FAULT", "kill_worker@index=0")
    monkeypatch.setenv("RMD_FAULT_STATE", str(tmp_path))
    faults.reset()

    pool = DecodePool(SynthFlow(6, 8, 8), procs=1, poll=0.2, timeout=60,
                      max_respawns=0)
    try:
        with pytest.raises(PoolBroken, match="respawn budget"):
            pool.result(pool.submit(0))
    finally:
        pool.shutdown()


# -- telemetry schema + report -----------------------------------------------


def test_fault_event_schema():
    import time

    from raft_meets_dicl_tpu.telemetry.core import (
        SCHEMA_VERSION, validate_event,
    )

    def base(kind, **f):
        return {"v": SCHEMA_VERSION, "t": time.time(), "kind": kind, **f}

    validate_event(base("preempt", signal="SIGTERM", step=3))
    validate_event(base("resume", path="a.ckpt", step=3))
    validate_event(base("quarantine", path="a.ckpt"))
    validate_event(base("respawn", worker=0, exitcode=17))
    validate_event(base("bad_sample", index=3, error="IOError"))
    validate_event(base("nonfinite", step=1, action="skip", trips=2))
    with pytest.raises(ValueError):
        validate_event(base("preempt", signal="SIGTERM"))  # missing step
    with pytest.raises(ValueError):
        validate_event(base("quarantine"))


def test_report_renders_fault_events():
    import time

    from raft_meets_dicl_tpu.telemetry import report
    from raft_meets_dicl_tpu.telemetry.core import SCHEMA_VERSION

    def base(kind, **f):
        return {"v": SCHEMA_VERSION, "t": time.time(), "kind": kind, **f}

    events = [
        base("nonfinite", step=4, action="skip", trips=1, window_trips=1),
        base("nonfinite", step=6, action="rollback", from_step=6,
             to_step=2, path="c.ckpt"),
        base("preempt", signal="SIGTERM", step=8),
        base("resume", path="e.ckpt", step=8),
        base("quarantine", path="bad.ckpt"),
        base("respawn", worker=1, exitcode=9),
        base("bad_sample", index=5, error="IOError: nope"),
    ]
    text = report.render(events)
    assert "fault tolerance" in text
    for frag in ("skip at step 4", "rollback at step 6",
                 "preempt (SIGTERM)", "resume from 'e.ckpt'",
                 "quarantined 'bad.ckpt'", "respawned decode worker 1",
                 "substituted bad sample 5"):
        assert frag in text, frag

    flags = report.find_anomalies(events)
    assert any("quarantined" in f for f in flags)
    assert any("respawned" in f for f in flags)
    assert any("preempted" in f for f in flags)


def test_nonfinite_policy_config_roundtrip():
    from raft_meets_dicl_tpu.strategy.training import NonFinitePolicy

    p = NonFinitePolicy.from_config(
        {"policy": "rollback", "max-consecutive": 5, "window": 100})
    assert (p.policy, p.max_consecutive, p.window) == ("rollback", 5, 100)
    assert NonFinitePolicy.from_config(None).policy == "raise"
    assert NonFinitePolicy.from_config("skip").policy == "skip"
    assert p.get_config()["max-consecutive"] == 5
    with pytest.raises(ValueError):
        NonFinitePolicy("explode")


def test_fault_directive_parsing(monkeypatch):
    monkeypatch.setenv(
        "RMD_FAULT", "nan_update@step=3,decode_error@index=2;times=2")
    faults.reset()
    assert faults.active()
    assert faults.fire("nan_update", step=1) is None   # wrong step
    assert faults.fire("nan_update", step=3) is not None
    assert faults.fire("nan_update", step=3) is None   # consumed
    assert faults.fire("decode_error", index=2) is not None
    assert faults.fire("decode_error", index=2) is not None  # times=2
    assert faults.fire("decode_error", index=2) is None


def test_fault_marker_state_shared(tmp_path, monkeypatch):
    monkeypatch.setenv("RMD_FAULT", "kill_worker@index=1")
    monkeypatch.setenv("RMD_FAULT_STATE", str(tmp_path))
    faults.reset()
    assert faults.fire("kill_worker", index=1) is not None
    faults.reset()  # a "new process" still sees the marker file
    assert faults.fire("kill_worker", index=1) is None


# -- CLI round trip (slow) ---------------------------------------------------


@pytest.mark.slow
def test_cli_sigterm_then_resume_auto(tmp_path):
    """Full-process proof: SIGTERM mid-run exits cleanly with an
    emergency checkpoint; a second invocation with --resume auto resumes
    at the saved step."""
    import json
    import subprocess
    import sys

    repo = Path(__file__).parent.parent
    ws = tmp_path

    # minimal in-place workspace (one stage, no validation)
    import cv2

    from raft_meets_dicl_tpu.data import io as dio

    scene = ws / "data/training/clean/alley_1"
    flows = ws / "data/training/flow/alley_1"
    scene.mkdir(parents=True)
    flows.mkdir(parents=True)
    rs = np.random.RandomState(0)
    for i in range(1, 5):
        cv2.imwrite(str(scene / f"frame_{i:04d}.png"),
                    (rs.rand(64, 96, 3) * 255).astype(np.uint8))
    for i in range(1, 4):
        dio.write_flow_mb(str(flows / f"frame_{i:04d}.flo"),
                          rs.randn(64, 96, 2).astype(np.float32))
    (ws / "dsspec.yaml").write_text("""
name: Fake Sintel
id: fake-sintel
path: ./data
layout:
  type: generic
  images: 'training/{pass}/{scene}/frame_{idx:04d}.png'
  flows: 'training/flow/{scene}/frame_{idx:04d}.flo'
  key: '{scene}/frame_{idx:04d}'
parameters:
  pass:
    values: [clean]
    sub: pass
""")
    (ws / "data.yaml").write_text("type: dataset\nspec: ./dsspec.yaml\n")
    (ws / "model.yaml").write_text("""
name: RAFT tiny
id: raft/tiny
model:
  type: raft/baseline
  parameters: {corr-levels: 2, corr-radius: 2, corr-channels: 32,
               context-channels: 16, recurrent-channels: 16}
  arguments: {iterations: 2}
loss:
  type: raft/sequence
input:
  padding: {type: modulo, mode: zeros, size: [8, 8]}
""")
    (ws / "strategy.yaml").write_text("""
mode: continuous
stages:
  - name: Stage 0
    id: fake/s0
    data: {epochs: 2, batch-size: 1, source: ./data.yaml}
    optimizer: {type: adam-w, parameters: {lr: 0.0004}}
""")

    from test_cli import _cli_env

    env = dict(_cli_env(), RMD_FAULT="sigterm@step=1",
               RMD_FINITE_CHECK_EVERY="1")
    proc = subprocess.run(
        [sys.executable, str(repo / "main.py"), "train",
         "-d", str(ws / "strategy.yaml"), "-m", str(ws / "model.yaml"),
         "-o", str(ws / "runs")],
        cwd=ws, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]

    emergency = list((ws / "runs").rglob("emergency-*.ckpt"))
    assert emergency, "SIGTERM must leave an emergency checkpoint"
    saved = Checkpoint.load(emergency[0])

    env2 = dict(_cli_env(), RMD_FINITE_CHECK_EVERY="1")
    proc = subprocess.run(
        [sys.executable, str(repo / "main.py"), "train",
         "-d", str(ws / "strategy.yaml"), "-m", str(ws / "model.yaml"),
         "-o", str(ws / "runs"), "--resume", "auto", "--limit-steps",
         str(saved.iteration.step + 1)],
        cwd=ws, env=env2, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]

    # the resumed run's telemetry shows the resume event at the exact step
    run_dirs = sorted((ws / "runs").iterdir())
    evs = [json.loads(line)
           for line in (run_dirs[-1] / "events.jsonl").read_text().splitlines()]
    resumes = [e for e in evs if e["kind"] == "resume"]
    assert resumes and resumes[0]["step"] == saved.iteration.step
    starts = [e for e in evs if e["kind"] == "stage_start"]
    assert starts and starts[0]["step"] == saved.iteration.step
