"""Full-model forward parity against the reference torch implementation.

The reference source tree is importable at /root/reference (package
``src``) and torch is installed in this environment. Each test
instantiates the reference torch module with default hyperparameters,
randomizes its weights and batch-norm statistics, maps the state dict
onto the flax variable tree through the scripts/chkpt_convert rules, and
asserts both frameworks compute the same function on identical inputs.

This is what makes the EPE-parity goal falsifiable without datasets:
op-level parity (tests/test_ops_parity.py) and weight-mapping round
trips (tests/test_chkpt_convert.py) are necessary but not sufficient — a
misplaced norm, padding mode, or channel-order mismatch composes
individually-correct ops and still diverges. A full forward catches it.

Covers: raft/baseline (reference src/models/impls/raft.py:372-433),
dicl/baseline (dicl.py:150-300), raft+dicl/ctf-l3 (the thesis flagship,
raft_dicl_ctf_l3.py:79-260).
"""

import sys
from pathlib import Path

import numpy as np
import pytest
import torch

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))
sys.path.insert(0, "/root/reference")

# importing src.models pulls in src/__init__ → src.data, which imports
# dataset-pipeline deps not installed here; the model code never touches
# them, so satisfy the imports with empty stubs
import types  # noqa: E402

for _name in ("torchvision", "torchvision.transforms", "parse", "git"):
    if _name not in sys.modules:
        try:
            __import__(_name)
        except ImportError:
            sys.modules[_name] = types.ModuleType(_name)

import chkpt_convert as cc  # noqa: E402

pytestmark = pytest.mark.slow


def _randomize_batchnorm(module, seed):
    """Fresh torch models carry degenerate BN state (mean 0, var 1,
    scale 1, bias 0) — a wrong stats mapping would be invisible.
    Randomize so the batch_stats transfer is actually exercised."""
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for m in module.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.running_mean.uniform_(-0.5, 0.5, generator=g)
                m.running_var.uniform_(0.5, 1.5, generator=g)
                m.weight.uniform_(0.5, 1.5, generator=g)
                m.bias.uniform_(-0.5, 0.5, generator=g)


def _images(shape, seed):
    rng = np.random.default_rng(seed)
    img1 = rng.uniform(-1.0, 1.0, size=shape).astype(np.float32)
    img2 = rng.uniform(-1.0, 1.0, size=shape).astype(np.float32)
    return img1, img2


def _nchw(x):
    return torch.from_numpy(np.transpose(x, (0, 3, 1, 2))).contiguous()


def _nhwc(t):
    return np.transpose(t.detach().numpy(), (0, 2, 3, 1))


def _restore(spec, chkpt, img_shape, **init_kwargs):
    """Init the flax variables and load the converted checkpoint into them."""
    import jax
    import jax.numpy as jnp
    from flax import serialization

    img = jnp.zeros(img_shape, jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(0), img, img, **init_kwargs)
    return serialization.from_state_dict(variables, chkpt.state.model)


def _assert_flow_lists_match(torch_flows, flax_flows, atol, label):
    assert len(torch_flows) == len(flax_flows), (
        f"{label}: {len(torch_flows)} torch outputs "
        f"vs {len(flax_flows)} flax outputs"
    )
    for i, (tf, ff) in enumerate(zip(torch_flows, flax_flows)):
        t = _nhwc(tf)
        f = np.asarray(ff)
        assert t.shape == f.shape, f"{label}[{i}]: {t.shape} vs {f.shape}"
        diff = np.abs(t - f).max()
        assert diff <= atol, f"{label}[{i}]: max |Δflow| = {diff:.2e} > {atol}"


def test_raft_baseline_forward_parity():
    import raft_meets_dicl_tpu.models as models
    from src.models.impls import raft as ref_raft

    torch.manual_seed(7)
    tmod = ref_raft.RaftModule()
    _randomize_batchnorm(tmod, 70)
    tmod.eval()

    chkpt = cc.convert_raft(dict(tmod.state_dict()), {})

    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [8, 8]}},
    })

    # the reference corr pyramid needs the 1/8 map ≥ 16 px per side — a
    # coarsest level of width 1 makes grid_sample divide by (w-1) = 0
    img1, img2 = _images((1, 128, 160, 3), 170)
    variables = _restore(spec, chkpt, (1, 128, 160, 3), iterations=1)

    with torch.no_grad():
        t_out = tmod(_nchw(img1), _nchw(img2), iterations=12)
    f_out = spec.model.apply(variables, img1, img2, iterations=12)

    _assert_flow_lists_match(t_out, f_out, 1e-3, "raft flow")


def test_raft_dicl_ctf_l3_forward_parity():
    import raft_meets_dicl_tpu.models as models
    from src.models.impls import raft_dicl_ctf_l3 as ref_ctf

    torch.manual_seed(8)
    tmod = ref_ctf.RaftPlusDiclModule()
    _randomize_batchnorm(tmod, 80)
    tmod.eval()

    chkpt = cc.convert_raft_dicl(dict(tmod.state_dict()), {})
    assert chkpt.model == "raft+dicl/ctf-l3"

    spec = models.load({
        "name": "RAFT+DICL ctf-l3", "id": "raft+dicl/ctf-l3",
        "model": {"type": "raft+dicl/ctf-l3", "parameters": {}},
        "loss": {"type": "raft+dicl/mlseq"},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [32, 32]}},
    })

    # multiples of 64: the 1/32-scale maps must have even extent
    # (MatchingNet downsamples by 2 and upsamples back)
    img1, img2 = _images((1, 128, 192, 3), 180)
    variables = _restore(spec, chkpt, (1, 128, 192, 3),
                         iterations=(1, 1, 1))

    with torch.no_grad():
        t_out = tmod(_nchw(img1), _nchw(img2), iterations=(4, 3, 3))
    f_out = spec.model.apply(variables, img1, img2, iterations=(4, 3, 3))

    # reference returns (out_5, out_4, out_3) iteration lists; ours is the
    # same structure as a list
    assert len(t_out) == len(f_out) == 3
    for lvl, (t_lvl, f_lvl) in enumerate(zip(t_out, f_out)):
        _assert_flow_lists_match(t_lvl, f_lvl, 1e-3, f"ctf-l3 level {lvl}")


def _ref_dicl_state_to_jytime(state):
    """Rename the reference DiclModule's own state-dict keys to the jytime
    naming that convert_dicl consumes (inverse of the renames in reference
    scripts/chkpt_convert.py:53-90)."""
    sub = []

    blocks = [f"conv0.{x}" for x in range(3)]
    blocks += [f"conv{x}a" for x in range(1, 7)]
    blocks += [f"outconv{x}" for x in range(2, 7)]
    for b in blocks:
        sub += [(f"feature.{b}.0.", f"feature.{b}.conv."),
                (f"feature.{b}.1.", f"feature.{b}.bn.")]

    ga = [f"deconv{x}a" for x in range(1, 7)]
    ga += [f"deconv{x}b" for x in range(2, 7)]
    ga += [f"conv{x}b" for x in range(1, 7)]
    for c in ga:
        sub += [(f"feature.{c}.conv1.", f"feature.{c}.conv1.conv."),
                (f"feature.{c}.conv2.", f"feature.{c}.conv2.conv."),
                (f"feature.{c}.bn2.", f"feature.{c}.conv2.bn.")]

    for lvl in range(2, 7):
        sub.append((f"lvl{lvl}.mnet.5.", f"matching{lvl}.match.5."))
        for x in range(5):
            sub += [(f"lvl{lvl}.mnet.{x}.0.", f"matching{lvl}.match.{x}.conv."),
                    (f"lvl{lvl}.mnet.{x}.1.", f"matching{lvl}.match.{x}.bn.")]
        sub.append((f"lvl{lvl}.dap.conv1.", f"dap{lvl}."))
        for x in range(7):
            sub += [(f"lvl{lvl}.ctxnet.{x}.0.", f"context_net{lvl}.{x}.conv."),
                    (f"lvl{lvl}.ctxnet.{x}.1.", f"context_net{lvl}.{x}.bn.")]
        # final plain conv (carries weight+bias directly)
        sub.append((f"lvl{lvl}.ctxnet.", f"context_net{lvl}."))

    out = {}
    for k, v in state.items():
        for old, new in sub:
            if k.startswith(old):
                k = new + k[len(old):]
        out[k] = v
    return out


def test_dicl_baseline_forward_parity():
    import raft_meets_dicl_tpu.models as models
    from src.models.impls import dicl as ref_dicl

    disp_ranges = {f"level-{lvl}": [3, 3] for lvl in range(2, 7)}

    torch.manual_seed(9)
    tmod = ref_dicl.DiclModule(disp_ranges=disp_ranges)
    _randomize_batchnorm(tmod, 90)
    tmod.eval()

    state = _ref_dicl_state_to_jytime(dict(tmod.state_dict()))
    chkpt = cc.convert_dicl(state, {})

    spec = models.load({
        "name": "DICL baseline", "id": "dicl/baseline",
        "model": {
            "type": "dicl/baseline",
            "parameters": {"displacement-range": disp_ranges},
        },
        "loss": {"type": "dicl/multiscale",
                 "arguments": {"weights": [1.0] * 10}},
        "input": {"padding": {"type": "modulo", "mode": "zeros",
                              "size": [128, 128]}},
    })

    # multiples of 128 (the GA-Net hourglass reaches 1/128), and the
    # 1/64 maps must exceed the ±3 displacement range
    img1, img2 = _images((1, 256, 384, 3), 190)
    variables = _restore(spec, chkpt, (1, 256, 384, 3))

    with torch.no_grad():
        t_out = tmod(_nchw(img1), _nchw(img2), raw=True)
    f_out = spec.model.apply(variables, img1, img2, raw=True)

    # coarse-to-fine warping amplifies f32 rounding ~4-6x per level: the
    # measured drift is 6e-6 at level 6 growing monotonically to ~1e-2 at
    # level 2 — numerical accumulation, not structure (any structural
    # mismatch shows up as O(1) at the level it happens)
    _assert_flow_lists_match(t_out, f_out, 2e-2, "dicl flow")


def _torch_grads_as_tree(tmod, convert_fn):
    """Run the model's gradients through the same weight-conversion rules
    as the checkpoint import: the converter's reshapes/transposes are
    linear, so the converted gradient dict is directly comparable
    leaf-by-leaf with the flax gradient tree. Buffers (BN running stats)
    carry no gradient and enter as zeros."""
    # remove_duplicate=False: the reference registers some norms both as
    # attributes and inside downsample Sequentials — state_dict lists both
    # names, named_parameters() would dedupe and lose one alias
    params = dict(tmod.named_parameters(remove_duplicate=False))
    state = {}
    for k, v in tmod.state_dict().items():
        g = params[k].grad if k in params else None
        state[k] = g.detach().clone() if g is not None else torch.zeros_like(v)
    return convert_fn(state, {}).state.model["params"]


def _flat_norms(tree, prefix=""):
    """Flatten a nested dict of arrays into {dotted-path: l2-norm}."""
    out = {}
    for k, v in tree.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            out |= _flat_norms(v, path + ".")
        else:
            out[path] = float(np.linalg.norm(np.asarray(v, np.float64).ravel()))
    return out


def _assert_grad_norms_match(torch_tree, flax_tree, rtol, label,
                             rtol_weak=None):
    """Per-tensor gradient-norm comparison, optionally two-tier.

    With ``rtol_weak``, leaves whose norm is below 10% of the median
    only need to meet the weak bound: in deep coarse-to-fine models the
    smallest-norm leaves (late-ladder batch-norm biases, norms ~1% of
    typical) are dominated by the same fp chaos that grows ~4-6x per
    warp level — their *relative* error is meaningless while the
    signal-carrying gradients still match tightly.
    """
    tn = _flat_norms(torch_tree)
    fn = _flat_norms(flax_tree)
    assert set(tn) == set(fn), (
        f"{label}: gradient trees differ: only-torch="
        f"{sorted(set(tn) - set(fn))[:5]} only-flax={sorted(set(fn) - set(tn))[:5]}"
    )
    median = float(np.median(list(tn.values())))
    worst = ("", 0.0, rtol)
    for k in tn:
        # floor 1e-5: conv biases directly followed by train-mode batch
        # norm have mathematically-zero gradients that both frameworks
        # realize as ~1e-8 fp noise — relative comparison is meaningless
        # there
        rel = abs(tn[k] - fn[k]) / max(tn[k], fn[k], 1e-5)
        bound = (rtol_weak if rtol_weak is not None
                 and tn[k] < 0.1 * median else rtol)
        if rel / bound > worst[1] / worst[2]:
            worst = (k, rel, bound)
    assert worst[1] <= worst[2], (
        f"{label}: grad-norm mismatch at '{worst[0]}': rel diff "
        f"{worst[1]:.2e} > {worst[2]} (torch {tn[worst[0]]:.6g} vs "
        f"flax {fn[worst[0]]:.6g}; median norm {median:.4g})"
    )


def test_raft_baseline_train_step_gradient_parity():
    """One training step, train-mode batch norm: loss values and
    per-tensor gradient norms agree torch-vs-flax — through the
    scan+remat iteration path and the sequence loss."""
    import jax
    import jax.numpy as jnp

    import raft_meets_dicl_tpu.models as models
    from src.models.impls import raft as ref_raft

    torch.manual_seed(17)
    tmod = ref_raft.RaftModule()
    _randomize_batchnorm(tmod, 171)
    tmod.train()

    chkpt = cc.convert_raft(dict(tmod.state_dict()), {})

    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })

    shape = (2, 128, 160, 3)
    img1, img2 = _images(shape, 172)
    rng = np.random.default_rng(173)
    target = rng.normal(0.0, 3.0, size=shape[:3] + (2,)).astype(np.float32)
    valid = np.ones(shape[:3], bool)
    iters = 6

    variables = _restore(spec, chkpt, shape, iterations=1)

    # --- torch step
    t1, t2 = _nchw(img1), _nchw(img2)
    t_out = tmod(t1, t2, iterations=iters)
    t_target = _nchw(target)
    ref_loss_mod = ref_raft.SequenceLoss()
    t_loss = ref_loss_mod.compute(tmod, t_out, t_target,
                                  torch.from_numpy(valid))
    t_loss.backward()

    # --- flax step (train-mode BN, scan + remat backward)
    def loss_fn(params):
        out, _new_bs = spec.model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.asarray(img1), jnp.asarray(img2), train=True,
            iterations=iters, rngs={"dropout": jax.random.PRNGKey(0)},
        )
        return spec.loss(spec.model, out, jnp.asarray(target),
                         jnp.asarray(valid))

    f_loss, f_grads = jax.value_and_grad(loss_fn)(variables["params"])

    rel = abs(float(t_loss) - float(f_loss)) / max(abs(float(t_loss)), 1e-8)
    assert rel <= 1e-4, (
        f"loss mismatch: torch {float(t_loss):.6f} vs flax "
        f"{float(f_loss):.6f} (rel {rel:.2e})"
    )

    t_grads = _torch_grads_as_tree(tmod, cc.convert_raft)
    # 1% on per-tensor l2 norms: f32 accumulation over 6 iterations of
    # backward (measured headroom ~5x)
    _assert_grad_norms_match(t_grads, f_grads, 1e-2, "raft grads")


def test_dicl_baseline_train_step_gradient_parity():
    """DICL training step: train-mode BN through the GA-Net encoder and
    MatchingNets, the soft-argmin flow regression, DAP, and the
    10-output (raw + refined per level) multiscale loss — the path where
    a subtly-wrong entropy/soft-argmin backward would hide (reference
    src/models/impls/dicl.py:31-86,416-472)."""
    import jax
    import jax.numpy as jnp

    import raft_meets_dicl_tpu.models as models
    from src.models.impls import dicl as ref_dicl

    disp_ranges = {f"level-{lvl}": [3, 3] for lvl in range(2, 7)}

    torch.manual_seed(19)
    tmod = ref_dicl.DiclModule(disp_ranges=disp_ranges)
    _randomize_batchnorm(tmod, 191)
    tmod.train()

    state = _ref_dicl_state_to_jytime(dict(tmod.state_dict()))
    chkpt = cc.convert_dicl(state, {})

    loss_args = {"weights": [1.0, 0.8, 0.75, 0.6, 0.5,
                             0.4, 0.5, 0.4, 0.5, 0.4], "ord": 2}
    spec = models.load({
        "name": "DICL baseline", "id": "dicl/baseline",
        "model": {"type": "dicl/baseline",
                  "parameters": {"displacement-range": disp_ranges}},
        "loss": {"type": "dicl/multiscale", "arguments": dict(loss_args)},
        "input": None,
    })

    shape = (2, 256, 384, 3)
    img1, img2 = _images(shape, 192)
    rng = np.random.default_rng(193)
    target = rng.normal(0.0, 3.0, size=shape[:3] + (2,)).astype(np.float32)
    valid = np.ones(shape[:3], bool)

    variables = _restore(spec, chkpt, shape)

    # --- torch step
    t_out = tmod(_nchw(img1), _nchw(img2), raw=True)
    ref_loss_mod = ref_dicl.MultiscaleLoss()
    t_loss = ref_loss_mod.compute(tmod, t_out, _nchw(target),
                                  torch.from_numpy(valid), **loss_args)
    t_loss.backward()

    # --- flax step
    def loss_fn(params):
        out, _new_bs = spec.model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.asarray(img1), jnp.asarray(img2), train=True, raw=True,
        )
        result = spec.model.get_adapter().wrap_result(out, shape[1:3])
        return spec.loss(spec.model, result.output(), jnp.asarray(target),
                         jnp.asarray(valid), **loss_args)

    f_loss, f_grads = jax.value_and_grad(loss_fn)(variables["params"])

    rel = abs(float(t_loss) - float(f_loss)) / max(abs(float(t_loss)), 1e-8)
    assert rel <= 1e-4, (
        f"loss mismatch: torch {float(t_loss):.6f} vs flax "
        f"{float(f_loss):.6f} (rel {rel:.2e})"
    )

    def convert(state_dict, loose):
        return cc.convert_dicl(_ref_dicl_state_to_jytime(state_dict), loose)

    t_grads = _torch_grads_as_tree(tmod, convert)
    # 6% for signal-carrying gradients: the coarse-to-fine ladder is 5
    # warp levels deep (vs ctf-l3's 3 at 2%), and forward drift measured
    # at 1e-5 (coarsest) growing ~4-6x per level to 3e-3 (finest)
    # amplifies into finest-level MatchingNet gradients at ~3.6%; a
    # structural break shows as O(1) at the level it happens, far above
    # this. Small-norm leaves (<10% of the median, late-ladder BN biases
    # at ~1% of typical norms) are chaos-dominated — measured ~17% on
    # norms of ~0.02 — and only need the 30% sanity bound.
    _assert_grad_norms_match(t_grads, f_grads, 6e-2, "dicl grads",
                             rtol_weak=0.3)


def test_raft_dicl_ctf_l3_train_step_gradient_parity():
    """Flagship training step: train-mode BN through the MatchingNets,
    the restricted multi-level sequence loss over (prev, flow) pairs, and
    the per-level scan+remat backward."""
    import jax
    import jax.numpy as jnp

    import raft_meets_dicl_tpu.models as models
    from src.models.impls import raft_dicl_ctf_l3 as ref_ctf

    torch.manual_seed(18)
    tmod = ref_ctf.RaftPlusDiclModule()
    _randomize_batchnorm(tmod, 181)
    tmod.train()

    chkpt = cc.convert_raft_dicl(dict(tmod.state_dict()), {})

    loss_args = {"ord": 1, "gamma": 0.85, "alpha": (0.38, 0.6, 1.0),
                 "delta_range": (128, 64, 32), "delta_mode": "bilinear"}
    spec = models.load({
        "name": "RAFT+DICL ctf-l3", "id": "raft+dicl/ctf-l3",
        "model": {"type": "raft+dicl/ctf-l3", "parameters": {}},
        "loss": {"type": "raft+dicl/mlseq-restricted",
                 "arguments": dict(loss_args, alpha=list(loss_args["alpha"]),
                                   delta_range=list(loss_args["delta_range"]))},
        "input": None,
    })

    shape = (1, 128, 192, 3)
    img1, img2 = _images(shape, 182)
    rng = np.random.default_rng(183)
    target = rng.normal(0.0, 3.0, size=shape[:3] + (2,)).astype(np.float32)
    valid = np.ones(shape[:3], bool)
    iters = (2, 2, 2)

    variables = _restore(spec, chkpt, shape, iterations=(1, 1, 1))

    # --- torch step
    t_out = tmod(_nchw(img1), _nchw(img2), iterations=iters, prev_flow=True)
    ref_loss_mod = ref_ctf.RestrictedMultiLevelSequenceLoss()
    t_loss = ref_loss_mod.compute(tmod, t_out, _nchw(target),
                                  torch.from_numpy(valid), **loss_args)
    t_loss.backward()

    # --- flax step
    def loss_fn(params):
        out, _new_bs = spec.model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.asarray(img1), jnp.asarray(img2), train=True,
            iterations=iters, prev_flow=True,
            rngs={"dropout": jax.random.PRNGKey(0)},
        )
        result = spec.model.get_adapter().wrap_result(out, shape[1:3])
        return spec.loss(spec.model, result.output(), jnp.asarray(target),
                         jnp.asarray(valid), **loss_args)

    f_loss, f_grads = jax.value_and_grad(loss_fn)(variables["params"])

    rel = abs(float(t_loss) - float(f_loss)) / max(abs(float(t_loss)), 1e-8)
    assert rel <= 1e-4, (
        f"loss mismatch: torch {float(t_loss):.6f} vs flax "
        f"{float(f_loss):.6f} (rel {rel:.2e})"
    )

    t_grads = _torch_grads_as_tree(tmod, cc.convert_raft_dicl)
    # 2%: the ctf backward stacks MatchingNet/BN trains across three
    # levels; coarse-level grads are small and accumulate relative error
    _assert_grad_norms_match(t_grads, f_grads, 2e-2, "ctf-l3 grads")
