"""Observability-layer tests: TB writer, SummaryInspector, validation-driven
checkpoints, hooks, and the grad-accum skip realignment."""

import numpy as np

import raft_meets_dicl_tpu.inspect as inspect_
import raft_meets_dicl_tpu.models as models
import raft_meets_dicl_tpu.strategy as strategy
from raft_meets_dicl_tpu.data.collection import Collection
from raft_meets_dicl_tpu.data.dataset import Metadata, SampleArgs, SampleId
from raft_meets_dicl_tpu.utils.logging import Logger

from test_strategy import TINY_MODEL, FlowSource, _make_stage


def _read_events(tb_dir):
    """All (tag, step, value|'img') tuples from every event file in a dir."""
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )

    out = []
    for f in sorted(tb_dir.glob("events.out.tfevents.*")):
        for event in EventFileLoader(str(f)).Load():
            for value in event.summary.value:
                # the event writer migrates both scalars and images to the
                # generic tensor representation; the plugin name tells them
                # apart
                plugin = value.metadata.plugin_data.plugin_name
                if value.HasField("simple_value"):
                    out.append((value.tag, event.step, value.simple_value))
                elif plugin == "scalars" and value.HasField("tensor"):
                    out.append((value.tag, event.step,
                                float(value.tensor.float_val[0])))
                elif plugin == "images" or value.HasField("image"):
                    out.append((value.tag, event.step, "img"))
    return out


def test_summary_writer_scalars_and_images(tmp_path):
    w = inspect_.SummaryWriter(tmp_path / "tb")
    w.set_fmtargs({"n_stage": 0, "id_stage": "test.s0"})
    w.add_scalar("Train:S{n_stage}:{id_stage}/Loss", 0.5, 3)
    w.add_image("Train:S{n_stage}:{id_stage}/img1",
                np.random.rand(8, 12, 3).astype(np.float32), 3)
    w.add_image("rgba", np.random.rand(8, 12, 4), 4)
    w.close()

    events = _read_events(tmp_path / "tb")
    assert ("Train:S0:test.s0/Loss", 3, 0.5) in events
    assert ("Train:S0:test.s0/img1", 3, "img") in events
    assert ("rgba", 4, "img") in events


INSPECT_CFG = {
    "metrics": [{
        "prefix": "Train:S{n_stage}:{id_stage}/",
        "frequency": 1,
        "metrics": [
            {"type": "epe"},
            {"type": "loss"},
            {"type": "learning-rate"},
            {"type": "grad-norm"},
        ],
    }],
    "images": {"frequency": 1, "prefix": "Train:S{n_stage}:{id_stage}/"},
    "checkpoints": {
        "path": "checkpoints",
        "name": "{id_model}-s{n_stage}_e{n_epoch}_b{n_steps}"
                "-epe{m_EndPointError_mean:.4f}.ckpt",
        "compare": ["{m_EndPointError_mean}"],
        "keep": {"latest": 2, "best": 2},
    },
    "validation": [{
        "type": "strategy",
        "frequency": "epoch",
        "checkpoint": True,
        "tb-metrics-prefix": "Validation:S{n_stage}:{id_stage}:{id_val}/",
        "metrics": [
            {"reduce": "mean", "metric": {"type": "epe"}},
            {"reduce": "mean", "metric": {"type": "loss"}},
        ],
        "images": {"prefix": "Validation:S{n_stage}:{id_stage}:{id_val}/i{img_idx}/"},
    }],
    "tensorboard": {"path": "tb.{id_model}"},
}


def test_inspector_spec_roundtrip():
    spec = inspect_.load(INSPECT_CFG)
    cfg = spec.get_config()
    spec2 = inspect_.load(cfg)
    assert spec2.get_config() == cfg


def _make_inspected_context(tmp_path, stages, inspect_cfg):
    spec = models.load(TINY_MODEL)
    insp_spec = inspect_.load(inspect_cfg)
    inspector, mgr = insp_spec.build("tiny", tmp_path)

    log = Logger("test")
    ctx = strategy.TrainingContext(
        log, tmp_path, strategy.Strategy("continuous", stages), "tiny",
        spec.model, spec.model.get_adapter(), spec.loss, spec.input,
        inspector, mgr, loader_args={"num_workers": 0},
    )
    return ctx, mgr, inspector


def _stage_with_validation(epochs=1, accumulate=1):
    stage = _make_stage(epochs=epochs, accumulate=accumulate)
    stage.validation = [strategy.spec.ValidationSpec(
        name="fake", source=FlowSource(2), batch_size=1, images={0},
    )]
    return stage


def test_summary_inspector_end_to_end(tmp_path):
    """One epoch with the full inspector: train metrics + images to TB,
    epoch validation computes EPE and creates a checkpoint."""
    ctx, mgr, _ = _make_inspected_context(
        tmp_path, [_stage_with_validation()], INSPECT_CFG
    )
    ctx.run()
    assert ctx.step == 2

    # validation created checkpoints with the EPE metric in name + entry
    assert len(mgr.checkpoints) == 1
    entry = mgr.checkpoints[0]
    assert "EndPointError/mean" in entry.metrics
    entry.wait()  # the save's serialize+write runs on a background thread
    assert entry.path.exists()
    assert "-epe" in entry.path.name

    # checkpoint loads back
    chkpt = entry.load()
    assert chkpt.metrics["EndPointError/mean"] == entry.metrics["EndPointError/mean"]

    ctx.inspector.writer.close()
    events = _read_events(tmp_path / "tb.tiny")
    tags = {t for t, _, _ in events}

    assert "Train:S0:test.s0/Loss" in tags
    assert "Train:S0:test.s0/EndPointError/mean" in tags
    assert "Train:S0:test.s0/LearningRate" in tags
    assert "Train:S0:test.s0/GradientNorm/total" in tags
    assert "Train:S0:test.s0/img1" in tags
    assert "Train:S0:test.s0/flow-est" in tags
    assert "Validation:S0:test.s0:fake/EndPointError/mean" in tags
    assert "Validation:S0:test.s0:fake/i0/flow-est" in tags


class SometimesInvalidSource(Collection):
    """FlowSource variant where selected sample indices are invalid."""

    type = "fake-flow-invalid"

    def __init__(self, n=6, invalid=(2,), h=32, w=48):
        self.inner = FlowSource(n, h, w)
        self.invalid = set(invalid)

    def __getitem__(self, index):
        img1, img2, flow, valid, meta = self.inner[index]
        if index in self.invalid:
            meta = [Metadata(False, m.dataset_id, m.sample_id,
                             m.original_extents) for m in meta]
        return img1, img2, flow, valid, meta

    def __len__(self):
        return len(self.inner)

    def get_config(self):
        return {"type": self.type}

    def description(self):
        return "fake flow with invalid samples"


def test_grad_accum_skip_stays_aligned(tmp_path):
    """An invalid batch mid-accumulation must cost one micro-batch, not
    desync the host step counter from optax.MultiSteps (VERDICT weak #4)."""
    from test_strategy import _make_context

    stage = _make_stage(epochs=1, accumulate=2)
    stage.data = strategy.spec.DataSpec(
        SometimesInvalidSource(n=5, invalid=(1,)), epochs=1, batch_size=1,
        shuffle=False,
    )

    ctx, _ = _make_context(tmp_path, [stage])
    ctx.run()

    # 5 batches, 1 skipped → 4 executed micro-batches → 2 optimizer steps;
    # the old (i+1)%accum boundary would have counted only 1
    assert ctx.step == 2

    # MultiSteps agrees: no partial accumulation left pending
    from raft_meets_dicl_tpu.strategy.training import TrainingContext  # noqa: F401
    mini_step = ctx.state.opt_state.mini_step
    assert int(np.asarray(mini_step)) == 0


def test_hooks_activation_and_gradient(tmp_path):
    """Activation-stats writes mean/var scalars via capture_intermediates;
    gradient anomaly hook sees grads (and stays silent on healthy ones)."""
    cfg = dict(INSPECT_CFG)
    cfg = {k: v for k, v in cfg.items() if k != "validation"}
    cfg["hooks"] = [
        {"type": "activation-stats", "modules": ["FeatureEncoderS3_0._Stem_0"],
         "prefix": "Train/ActivationStats/", "frequency": 1},
        {"type": "anomalydetect-gradient", "save-checkpoint": True,
         "checkpoint-fmt": "anomaly-b{n_step}.ckpt"},
    ]

    ctx, _, inspector = _make_inspected_context(
        tmp_path, [_make_stage(epochs=1)], cfg
    )
    assert inspector.wants_gradients  # grad-norm metric + gradient hook
    ctx.run()

    ctx.inspector.writer.close()
    events = _read_events(tmp_path / "tb.tiny")
    tags = {t for t, _, _ in events}

    act_tags = [t for t in tags
                if t.startswith("Train/ActivationStats/FeatureEncoderS3_0")]
    assert act_tags, f"no activation stats written; tags: {sorted(tags)[:20]}"
    assert any(t.endswith("/mean") for t in act_tags)
    assert any(t.endswith("/var") for t in act_tags)

    # healthy training: no anomaly checkpoints dumped
    assert not list(tmp_path.glob("anomaly-*.ckpt"))


def test_gradient_anomaly_dumps_checkpoint(tmp_path):
    """A non-finite gradient triggers the rolling debug checkpoint dump."""
    import jax.numpy as jnp

    from raft_meets_dicl_tpu.inspect.hooks.anomaly import GradientAnomalyDetector

    ctx, _, inspector = _make_inspected_context(
        tmp_path, [_make_stage(epochs=1)], INSPECT_CFG
    )
    # minimal live context for the dump
    ctx._ensure_variables(ctx.strategy.stages[0])
    ctx.current_stage = ctx.strategy.stages[0]
    ctx.current_stage.index = 0
    ctx.current_epoch = 0
    ctx.lr_sched_inst, ctx.lr_sched_epoch = [], []

    hook = GradientAnomalyDetector(checkpoint=True)
    writer = inspector.writer
    writer.set_fmtargs({"n_step": 0})
    hook.register(ctx, writer)

    log = Logger("test")
    hook.on_grads(log, ctx, {"w": jnp.array([1.0, float("nan")])})

    dumps = list(tmp_path.glob("anomaly_in_gradient-*.ckpt"))
    assert len(dumps) == 1
    # the dump is a loadable checkpoint
    chkpt = strategy.Checkpoint.load(dumps[0])
    assert chkpt.model == "tiny"


def test_tfdata_reads_back_writer_scalars(tmp_path):
    """utils.tfdata round-trips scalars written by our SummaryWriter."""
    from raft_meets_dicl_tpu.utils import tfdata

    w = inspect_.SummaryWriter(tmp_path / "tb")
    for step, value in enumerate((0.5, 0.25, 0.125)):
        w.add_scalar("Loss", value, step)
    w.add_scalar("Other", 1.0, 0)
    w.close()

    events = sorted((tmp_path / "tb").glob("events.out.tfevents.*"))
    df = tfdata.tfdata_scalars_to_pandas(events[0])
    loss = df[df.tag == "Loss"].sort_values("step")
    assert list(loss.step) == [0, 1, 2]
    assert list(loss.value) == [0.5, 0.25, 0.125]

    filtered = tfdata.tfdata_scalars_to_pandas(events[0], tags={"Other"})
    assert set(filtered.tag) == {"Other"}
