"""Torch-checkpoint import: the flax-tree mapping must be complete and
lossless for princeton-vl-style RAFT state dicts."""

import sys
from pathlib import Path

import pytest
import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))

import chkpt_convert  # noqa: E402

import raft_meets_dicl_tpu.models as models  # noqa: E402
from raft_meets_dicl_tpu.metrics.functional import tree_named_leaves  # noqa: E402
from raft_meets_dicl_tpu.strategy.checkpoint import Checkpoint  # noqa: E402

pytestmark = pytest.mark.slow


def _fabricate_torch_state(variables):
    """Inverse of the converter's mapping: build a princeton-vl-style torch
    state dict from a flax variables tree (tests the mapping bijectively)."""
    import torch

    rules = chkpt_convert._raft_rules()
    state = {}

    # no mask-head channel permutation: the flax Up8 head uses torch
    # RAFT's neighbor-major channel layout natively

    for name, leaf in tree_named_leaves(variables):
        col, *path = name.split(".")
        module_path = ".".join(path[:-1])
        leaf_name = path[-1]
        torch_mod = rules[module_path]

        value = np.asarray(leaf)
        if col == "params":
            if leaf_name == "kernel":
                key = f"{torch_mod}.weight"
                value = np.transpose(value, (3, 2, 0, 1))  # HWIO → OIHW
            elif leaf_name == "bias":
                key = f"{torch_mod}.bias"
            else:  # scale
                key = f"{torch_mod}.weight"
        else:
            key = (f"{torch_mod}.running_mean" if leaf_name == "mean"
                   else f"{torch_mod}.running_var")

        state[f"module.{key}"] = torch.from_numpy(value.copy())

    return state


def test_raft_conversion_roundtrip(tmp_path):
    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })
    img = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(7), img, img, iterations=1)

    torch_state = _fabricate_torch_state(variables)
    state = chkpt_convert._normalize(torch_state, chkpt_convert._RAFT_PFX)

    filled, unused = chkpt_convert._fill_variables(
        variables, state, chkpt_convert._raft_rules())
    assert not unused, f"unmapped torch keys: {sorted(unused)[:5]}"

    # lossless: every leaf returns bit-identical
    orig = dict(tree_named_leaves(variables))
    conv = dict(tree_named_leaves(filled))
    assert orig.keys() == conv.keys()
    for k in orig:
        assert np.array_equal(np.asarray(orig[k]), conv[k]), k


def test_raft_conversion_end_to_end(tmp_path):
    """torch.save → converter → Checkpoint.load → apply → forward."""
    import torch

    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })
    img = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(3), img, img, iterations=1)

    pth = tmp_path / "raft-synth.pth"
    torch.save(_fabricate_torch_state(variables), pth)

    state = torch.load(pth, map_location="cpu", weights_only=True)
    chkpt = chkpt_convert.convert_raft(state, {"source": str(pth)})

    out = tmp_path / "raft-synth.ckpt"
    chkpt.save(out)

    loaded = Checkpoint.load(out)
    assert loaded.model == "raft/baseline"

    restored, _, _ = loaded.apply(variables=variables)

    rimg = jnp.asarray(np.random.RandomState(0).rand(1, 64, 96, 3), jnp.float32)
    flows = jax.jit(
        lambda v: spec.model.apply(v, rimg, rimg, iterations=2)
    )(restored)
    assert flows[-1].shape == (1, 64, 96, 2)
    assert bool(jnp.all(jnp.isfinite(flows[-1])))


def test_convex_combine_pallas_matches_reference():
    """The fused Pallas mask-combine kernel (fwd + custom VJP, run in
    interpreter mode off-TPU) must match the XLA reference semantics the
    torch-parity tests validate."""
    from raft_meets_dicl_tpu.ops import pallas as pk

    rs = np.random.RandomState(11)
    m = 700  # not a multiple of the row tile: exercises padding
    logits = jnp.asarray(rs.randn(m, 576), jnp.float32)
    win = jnp.asarray(rs.randn(m, 9 * 2), jnp.float32)

    expected = pk._combine_reference(logits, win, 0.25)
    actual = pk._run_fwd_interpret(logits, win, 0.25)
    assert np.allclose(np.asarray(actual), np.asarray(expected), atol=1e-5)

    # backward: compare the pallas bwd kernel against autodiff of the
    # reference
    dout = jnp.asarray(rs.randn(m, 128), jnp.float32)
    _, vjp = jax.vjp(lambda lg, wn: pk._combine_reference(lg, wn, 0.25),
                     logits, win)
    dl_ref, dw_ref = vjp(dout)
    dl, dw = pk._run_bwd_interpret(logits, win, dout, 0.25)
    assert np.allclose(np.asarray(dl), np.asarray(dl_ref), atol=1e-5)
    assert np.allclose(np.asarray(dw), np.asarray(dw_ref), atol=1e-5)


def test_dicl_conversion_roundtrip():
    """The dicl/baseline mapping must cover the whole tree losslessly for
    jytime-style state dicts (incl. the ConvTranspose flip transform)."""
    import torch

    spec = models.load({
        "name": "DICL baseline", "id": "dicl/baseline",
        "model": {
            "type": "dicl/baseline",
            "parameters": {
                "displacement-range": {f"level-{l}": [3, 3]
                                       for l in range(2, 7)},
            },
        },
        "loss": {"type": "dicl/multiscale", "arguments": {"weights": [1.0] * 10}},
        "input": None,
    })
    img = jnp.zeros((1, 128, 128, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(2), img, img)

    rules = chkpt_convert._dicl_rules()

    # fabricate a jytime-style torch state dict (inverse transforms)
    state = {}
    for name, leaf in tree_named_leaves(variables):
        col, *path = name.split(".")
        module_path = ".".join(path[:-1])
        leaf_name = path[-1]
        torch_mod = rules[module_path]

        value = np.asarray(leaf)
        if col == "params":
            if leaf_name == "kernel":
                key = f"{torch_mod}.weight"
                if path[-2].startswith("ConvTranspose"):
                    # inverse of _conv_t: HWIO → IOHW, then spatial flip
                    value = np.transpose(value, (2, 3, 0, 1))[:, :, ::-1, ::-1]
                else:
                    value = np.transpose(value, (3, 2, 0, 1))
            elif leaf_name == "bias":
                key = f"{torch_mod}.bias"
            else:
                key = f"{torch_mod}.weight"
        else:
            key = (f"{torch_mod}.running_mean" if leaf_name == "mean"
                   else f"{torch_mod}.running_var")
        state[key] = torch.from_numpy(value.copy())

    # back through jytime naming, then through the converter
    jytime = {}
    for k, v in state.items():
        k = k.replace("feature.conv0.", "feature.conv_start.")
        for x in range(2, 7):
            k = k.replace(f"dap{x}.", f"dap_layer{x}.dap_layer.conv.")
        jytime[f"module.{k}"] = v

    norm = chkpt_convert._normalize(jytime, chkpt_convert._DICL_PFX)
    filled, unused = chkpt_convert._fill_variables(variables, norm, rules)
    assert not unused, f"unmapped torch keys: {sorted(unused)[:5]}"

    orig = dict(tree_named_leaves(variables))
    conv = dict(tree_named_leaves(filled))
    assert orig.keys() == conv.keys()
    for k in orig:
        assert np.array_equal(np.asarray(orig[k]), conv[k]), k


def test_conv_transpose_import_transform_matches_torch():
    """_conv_t must make flax ConvTranspose (SAME, unflipped kernel)
    reproduce torch ConvTranspose2d(k4, s2, p1) bit-for-bit in f64."""
    import torch
    from flax import linen as fnn

    rs = np.random.RandomState(4)
    x = rs.randn(1, 4, 6, 3)
    wt = rs.randn(3, 5, 4, 4)  # torch (I, O, kh, kw)

    expected = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)), torch.from_numpy(wt),
        stride=2, padding=1,
    ).numpy().transpose(0, 2, 3, 1)

    prior_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        mod = fnn.ConvTranspose(5, (4, 4), strides=(2, 2), padding="SAME",
                                use_bias=False)
        out = np.asarray(mod.apply(
            {"params": {"kernel": jnp.asarray(chkpt_convert._conv_t(wt))}},
            jnp.asarray(x)))
    finally:
        jax.config.update("jax_enable_x64", prior_x64)

    assert np.abs(out - expected).max() < 1e-10
