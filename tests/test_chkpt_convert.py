"""Torch-checkpoint import: the flax-tree mapping must be complete and
lossless for princeton-vl-style RAFT state dicts."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))

import chkpt_convert  # noqa: E402

import raft_meets_dicl_tpu.models as models  # noqa: E402
from raft_meets_dicl_tpu.metrics.functional import tree_named_leaves  # noqa: E402
from raft_meets_dicl_tpu.strategy.checkpoint import Checkpoint  # noqa: E402


def _fabricate_torch_state(variables):
    """Inverse of the converter's mapping: build a princeton-vl-style torch
    state dict from a flax variables tree (tests the mapping bijectively)."""
    import torch

    rules = chkpt_convert._raft_rules()
    state = {}

    for name, leaf in tree_named_leaves(variables):
        col, *path = name.split(".")
        module_path = ".".join(path[:-1])
        leaf_name = path[-1]
        torch_mod = rules[module_path]

        value = np.asarray(leaf)
        if col == "params":
            if leaf_name == "kernel":
                key = f"{torch_mod}.weight"
                value = np.transpose(value, (3, 2, 0, 1))  # HWIO → OIHW
            elif leaf_name == "bias":
                key = f"{torch_mod}.bias"
            else:  # scale
                key = f"{torch_mod}.weight"
        else:
            key = (f"{torch_mod}.running_mean" if leaf_name == "mean"
                   else f"{torch_mod}.running_var")

        state[f"module.{key}"] = torch.from_numpy(value.copy())

    return state


def test_raft_conversion_roundtrip(tmp_path):
    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })
    img = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(7), img, img, iterations=1)

    torch_state = _fabricate_torch_state(variables)
    state = chkpt_convert._normalize(torch_state, chkpt_convert._RAFT_PFX)

    filled, unused = chkpt_convert._fill_variables(
        variables, state, chkpt_convert._raft_rules())
    assert not unused, f"unmapped torch keys: {sorted(unused)[:5]}"

    # lossless: every leaf returns bit-identical
    orig = dict(tree_named_leaves(variables))
    conv = dict(tree_named_leaves(filled))
    assert orig.keys() == conv.keys()
    for k in orig:
        assert np.array_equal(np.asarray(orig[k]), conv[k]), k


def test_raft_conversion_end_to_end(tmp_path):
    """torch.save → converter → Checkpoint.load → apply → forward."""
    import torch

    spec = models.load({
        "name": "RAFT baseline", "id": "raft/baseline",
        "model": {"type": "raft/baseline", "parameters": {}},
        "loss": {"type": "raft/sequence"},
        "input": None,
    })
    img = jnp.zeros((1, 64, 96, 3), jnp.float32)
    variables = spec.model.init(jax.random.PRNGKey(3), img, img, iterations=1)

    pth = tmp_path / "raft-synth.pth"
    torch.save(_fabricate_torch_state(variables), pth)

    state = torch.load(pth, map_location="cpu", weights_only=True)
    chkpt = chkpt_convert.convert_raft(state, {"source": str(pth)})

    out = tmp_path / "raft-synth.ckpt"
    chkpt.save(out)

    loaded = Checkpoint.load(out)
    assert loaded.model == "raft/baseline"

    restored, _, _ = loaded.apply(variables=variables)

    rimg = jnp.asarray(np.random.RandomState(0).rand(1, 64, 96, 3), jnp.float32)
    flows = jax.jit(
        lambda v: spec.model.apply(v, rimg, rimg, iterations=2)
    )(restored)
    assert flows[-1].shape == (1, 64, 96, 2)
    assert bool(jnp.all(jnp.isfinite(flows[-1])))
