"""Model-zoo wave 2 tests: raft/sl, raft/fs, coarse-to-fine families,
and the multi-level sequence losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models

pytestmark = pytest.mark.slow
from raft_meets_dicl_tpu.models.config import load_loss

RNG = jax.random.PRNGKey(0)


def _img(h=64, w=96, b=1, seed=0):
    return jnp.asarray(np.random.RandomState(seed).rand(b, h, w, 3), jnp.float32)


def test_registry_covers_wave2():
    types = models.config.model_types()
    for ty in ("raft/baseline", "raft/sl", "raft/fs", "raft/sl-ctf-l2",
               "raft/sl-ctf-l3", "raft/sl-ctf-l4", "raft+dicl/sl",
               "raft+dicl/ctf-l2", "raft+dicl/ctf-l3", "raft+dicl/ctf-l4",
               "dicl/baseline", "dicl/64to8"):
        assert ty in types, ty

    losses = models.config.loss_types()
    for ty in ("raft/sequence", "dicl/multiscale", "raft+dicl/mlseq",
               "raft+dicl/mlseq-restricted"):
        assert ty in losses, ty


def test_raft_sl_forward():
    m = models.config.load_model({
        "type": "raft/sl",
        "parameters": {"corr-radius": 2, "corr-channels": 16,
                       "context-channels": 8, "recurrent-channels": 8},
    })
    img = _img()
    v = jax.jit(lambda: m.init(RNG, img, img, iterations=1))()
    out = jax.jit(lambda v: m.apply(v, img, img, iterations=2))(v)
    assert len(out) == 2 and out[0].shape == (1, 64, 96, 2)
    assert m.get_config()["type"] == "raft/sl"

    cfg = m.get_config()
    assert models.config.load_model(cfg).get_config() == cfg


@pytest.mark.parametrize("volume_gib", ["0", "2.0"])
def test_raft_fs_forward(volume_gib, monkeypatch):
    """Both correlation strategies of the adaptive dispatch: '0' forces
    the windowed/Pallas-path _FsStep branch, '2.0' takes the
    materialized-volume branch at this toy shape."""
    monkeypatch.setenv("RMD_FS_VOLUME_GIB", volume_gib)
    m = models.config.load_model({
        "type": "raft/fs",
        "parameters": {"corr-levels": 3, "corr-radius": 2, "corr-channels": 16,
                       "context-channels": 8, "recurrent-channels": 8},
    })
    img = _img()
    v = jax.jit(lambda: m.init(RNG, img, img, iterations=1))()
    out = jax.jit(lambda v: m.apply(v, img, img, iterations=2))(v)
    assert len(out) == 2 and out[0].shape == (1, 64, 96, 2)

    # mask_costs zeroes a level but keeps shapes
    out = jax.jit(
        lambda v: m.apply(v, img, img, iterations=1, mask_costs=(3,))
    )(v)
    assert out[0].shape == (1, 64, 96, 2)

    cfg = m.get_config()
    assert models.config.load_model(cfg).get_config() == cfg


def test_raft_fs_volume_level_split():
    """The greedy per-level dispatch moves coarse levels onto volumes
    one at a time as the budget grows (shape: the toy test config's
    8x12 f32 coarse grid — per-level volumes 36864/9216/2304 bytes)."""
    from raft_meets_dicl_tpu.models.impls.raft_fs import volume_level_split

    split = lambda gib: volume_level_split((1, 8, 12), 3, 4, budget_gib=gib)
    assert split(0.0) == 3        # nothing fits: pure windowed
    assert split(1e-5) == 2       # level 2 only
    assert split(5e-5) == 1       # levels 1-2
    assert split(2.0) == 0        # everything: pure volume
    # the 2x backward charge: a budget of exactly 2x the coarsest level
    # admits it, one byte less does not
    assert volume_level_split((1, 8, 12), 3, 4, budget_gib=4608 / 2**30) == 2
    assert volume_level_split((1, 8, 12), 3, 4, budget_gib=4607 / 2**30) == 3


@pytest.mark.parametrize("volume_gib,n_windowed", [
    ("2.0", 0),   # every level fits: pure materialized-volume path
    ("5e-5", 1),  # levels 1-2 fit: hybrid, kernel level 0 + volumes 1-2
    ("1e-5", 2),  # level 2 fits: hybrid, kernel levels 0-1 + volume 2
])
def test_raft_fs_volume_dispatch_matches_windowed(volume_gib, n_windowed,
                                                  monkeypatch):
    """Every dispatch split computes the same model function as the pure
    windowed path (pooling/bilinear interpolation commute with the dot
    product); the per-level greedy budget moves coarse levels onto
    materialized volumes one at a time."""
    cfg = {
        "type": "raft/fs",
        "parameters": {"corr-levels": 3, "corr-radius": 2, "corr-channels": 16,
                       "context-channels": 8, "recurrent-channels": 8},
    }
    img = _img()

    # the budget must produce the split this case claims to exercise
    from raft_meets_dicl_tpu.models.impls.raft_fs import volume_level_split

    assert volume_level_split((1, 8, 12), 3, 4,
                              budget_gib=float(volume_gib)) == n_windowed

    monkeypatch.setenv("RMD_FS_VOLUME_GIB", volume_gib)
    m_vol = models.config.load_model(cfg)
    v = m_vol.init(RNG, img, img, iterations=1)
    out_vol = m_vol.apply(v, img, img, iterations=3)

    monkeypatch.setenv("RMD_FS_VOLUME_GIB", "0")
    out_win = models.config.load_model(cfg).apply(v, img, img, iterations=3)

    for a, b in zip(out_vol, out_win):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_raft_fs_matches_windowed_lookup_semantics():
    """fs on-the-fly lookup == unnormalized dot product at grid coords."""
    from raft_meets_dicl_tpu.ops.corr import windowed_correlation
    from raft_meets_dicl_tpu.ops.warp import coordinate_grid

    rs = np.random.RandomState(1)
    f1 = jnp.asarray(rs.randn(1, 6, 8, 4), jnp.float32)
    f2 = jnp.asarray(rs.randn(1, 6, 8, 4), jnp.float32)
    coords = coordinate_grid(1, 6, 8)

    corr = np.asarray(windowed_correlation(f1, f2, coords, 1, 1.0,
                                           normalize=False))
    y, x = 3, 4
    for i, (dx, dy) in enumerate((dx, dy) for dx in (-1, 0, 1)
                                 for dy in (-1, 0, 1)):
        expect = float(np.dot(np.asarray(f1)[0, y, x],
                              np.asarray(f2)[0, y + dy, x + dx]))
        assert corr[0, y, x, i] == pytest.approx(expect, abs=1e-4)


SL_CTF_PARAMS = {"corr-radius": 2, "corr-channels": 16, "context-channels": 8,
                 "recurrent-channels": 8}


@pytest.mark.parametrize("levels,ty,iters,size", [
    (2, "raft/sl-ctf-l2", (2, 1), (64, 96)),
    (3, "raft/sl-ctf-l3", (1, 1, 1), (64, 96)),
])
def test_raft_sl_ctf_forward(levels, ty, iters, size):
    m = models.config.load_model({"type": ty, "parameters": SL_CTF_PARAMS})
    h, w = size
    img = _img(h, w)

    v = jax.jit(lambda: m.init(RNG, img, img,
                               iterations=tuple(1 for _ in range(levels))))()
    out = jax.jit(lambda v: m.apply(v, img, img, iterations=iters))(v)

    assert len(out) == levels  # coarse→fine level lists
    assert [len(lv) for lv in out] == list(iters)
    assert out[-1][-1].shape == (1, h, w, 2)  # finest is Up8-upsampled
    coarsest = 2 ** (levels + 2)
    assert out[0][0].shape == (1, h // coarsest, w // coarsest, 2)

    res = m.get_adapter().wrap_result(out, (h, w))
    assert res.final().shape == (1, h, w, 2)

    loss = load_loss({"type": "raft+dicl/mlseq",
                      "arguments": {"alpha": [0.4] * (levels - 1) + [1.0]}})
    l = loss(m, res.output(), jnp.zeros((1, h, w, 2)),
             jnp.ones((1, h, w), bool))
    assert np.isfinite(float(l))

    cfg = m.get_config()
    assert models.config.load_model(cfg).get_config() == cfg


CTF_PARAMS = {"corr-radius": 2, "corr-channels": 8, "context-channels": 8,
              "recurrent-channels": 8, "corr-args": {"mnet_scale": 0.125}}


def test_raft_dicl_ctf_l2_share_variants():
    img = _img(64, 96)

    for share_dicl, share_rnn in ((False, True), (True, False)):
        m = models.config.load_model({
            "type": "raft+dicl/ctf-l2",
            "parameters": CTF_PARAMS | {"share-dicl": share_dicl,
                                        "share-rnn": share_rnn,
                                        "upsample-hidden": "bilinear"},
        })
        v = jax.jit(lambda m=m: m.init(RNG, img, img, iterations=(1, 1)))()
        out = jax.jit(
            lambda v, m=m: m.apply(v, img, img, iterations=(2, 1))
        )(v)
        assert [len(lv) for lv in out] == [2, 1]
        assert out[-1][-1].shape == (1, 64, 96, 2)


def test_raft_dicl_ctf_l3_flagship_with_restricted_loss():
    m = models.config.load_model({
        "type": "raft+dicl/ctf-l3",
        "parameters": CTF_PARAMS | {"upsample-hidden": "bilinear"},
    })
    img = _img(128, 128)
    target = jnp.zeros((1, 128, 128, 2))
    valid = jnp.ones((1, 128, 128), bool)

    v = jax.jit(lambda: m.init(RNG, img, img, iterations=(1, 1, 1)))()

    @jax.jit
    def fwd(v):
        out = m.apply(v, img, img, iterations=(2, 1, 1), prev_flow=True)
        res = m.get_adapter().wrap_result(out, (128, 128))
        loss = load_loss({"type": "raft+dicl/mlseq-restricted",
                          "arguments": {"alpha": [0.38, 0.6, 1.0],
                                        "delta_range": [128, 64, 32]}})
        return res.final(), loss(m, res.output(), target, valid)

    final, l = fwd(v)
    assert final.shape == (1, 128, 128, 2)
    assert np.isfinite(float(l))

    # prev_flow entries are (prev, flow) pairs; per-sample slicing keeps them
    out = jax.jit(
        lambda v: m.apply(v, img, img, iterations=(1, 1, 1), prev_flow=True)
    )(v)
    res = m.get_adapter().wrap_result(out, (128, 128))
    sliced = res.output(0)
    assert isinstance(sliced[0][0], list) and len(sliced[0][0]) == 2

    cfg = m.get_config()
    assert cfg["type"] == "raft+dicl/ctf-l3"
    assert models.config.load_model(cfg).get_config() == cfg


def test_raft_dicl_ctf_l3_corr_flow_output_structure():
    m = models.config.load_model({
        "type": "raft+dicl/ctf-l3",
        "parameters": CTF_PARAMS,
    })
    img = _img(128, 128)
    v = jax.jit(lambda: m.init(RNG, img, img, iterations=(1, 1, 1)))()

    out = jax.jit(
        lambda v: m.apply(v, img, img, iterations=(1, 1, 1), corr_flow=True)
    )(v)
    # per level: corr-readout list then flow list (reference :254-256)
    assert len(out) == 6
    res = m.get_adapter().wrap_result(out, (128, 128))
    assert res.final().shape == (1, 128, 128, 2)


def test_mlseq_loss_weighting():
    """Level/iteration weighting matches the α·γ^(n-i-1) formula."""
    loss = load_loss({"type": "raft+dicl/mlseq"})

    target = jnp.zeros((1, 8, 8, 2))
    valid = jnp.ones((1, 8, 8), bool)
    one = jnp.ones((1, 8, 8, 2))  # unit flow → L1 dist = 2 everywhere

    result = [[one], [one, one]]
    # level 0: α=0.4, n=1 → 0.4·γ⁰·2 ; level 1: α=1.0 → (γ·2 + 2)
    got = float(loss(None, result, target, valid,
                     ord=1, gamma=0.5, alpha=(0.4, 1.0)))
    expect = 0.4 * 2 + (0.5 * 2 + 2)
    assert got == pytest.approx(expect, rel=1e-5)


def test_raft_dicl_ml_forward():
    img = _img()
    for params in (
        {"corr-levels": 2, "corr-radius": 2, "corr-channels": 8,
         "context-channels": 8, "recurrent-channels": 8},
        {"corr-levels": 2, "corr-radius": 2, "corr-channels": 8,
         "context-channels": 8, "recurrent-channels": 8,
         "encoder-type": "raft-maxpool", "dap-type": "full",
         "share-dicl": True},
    ):
        m = models.config.load_model({"type": "raft+dicl/ml",
                                      "parameters": params})
        v = jax.jit(lambda m=m: m.init(RNG, img, img, iterations=1))()
        out = jax.jit(lambda v, m=m: m.apply(v, img, img, iterations=2))(v)
        assert len(out) == 2 and out[0].shape == (1, 64, 96, 2)

        out = jax.jit(
            lambda v, m=m: m.apply(v, img, img, iterations=1, corr_flow=True)
        )(v)
        assert len(out) == 3  # 2 corr levels (coarse→fine) + final sequence

        res = m.get_adapter().wrap_result(out, (64, 96))
        assert res.final().shape == (1, 64, 96, 2)

        cfg = m.get_config()
        assert models.config.load_model(cfg).get_config() == cfg


def test_pool_and_rfpm_encoder_families():
    from raft_meets_dicl_tpu.models.common import encoders

    x = jnp.zeros((1, 64, 96, 3))
    for fam in ("raft-avgpool", "raft-maxpool"):
        enc = encoders.make_encoder_p34(fam, output_dim=16, norm_type="batch",
                                        dropout=0)
        outs = enc.apply(enc.init(RNG, x), x)
        assert [o.shape[1] for o in outs] == [8, 4]

    enc = encoders.make_encoder_s3("rfpm-raft", output_dim=16,
                                   norm_type="batch", dropout=0)
    out = enc.apply(enc.init(RNG, x), x)
    assert out.shape == (1, 8, 12, 16)

    enc = encoders.make_encoder_p34("rfpm-raft", output_dim=16,
                                    norm_type="batch", dropout=0)
    outs = enc.apply(enc.init(RNG, x), x)
    assert [o.shape[1] for o in outs] == [8, 4]


def test_ctf_scan_matches_unrolled():
    """The nn.scan iteration path computes the same function (outputs and
    gradients) as the python-unrolled loop, with identical variables —
    parameter paths must not depend on the loop realization."""
    from raft_meets_dicl_tpu.models.impls.raft_dicl_ctf import (
        RaftPlusDiclCtfModule,
    )

    kw = dict(levels=2, corr_radius=2, corr_channels=8, context_channels=16,
              recurrent_channels=16)
    m_scan = RaftPlusDiclCtfModule(**kw)
    m_unroll = RaftPlusDiclCtfModule(**kw, unroll=True)

    rng = np.random.default_rng(12)
    img1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 128, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 128, 3)), jnp.float32)

    v = jax.jit(
        lambda: m_scan.init(RNG, img1, img2, iterations=(1, 1))
    )()
    v2 = jax.jit(
        lambda: m_unroll.init(RNG, img1, img2, iterations=(1, 1))
    )()
    assert jax.tree.structure(v) == jax.tree.structure(v2)

    args = dict(iterations=(2, 2), corr_flow=True, prev_flow=True)
    o_scan = m_scan.apply(v, img1, img2, **args)
    o_unroll = m_unroll.apply(v, img1, img2, **args)

    flat_s = jax.tree.leaves(o_scan)
    flat_u = jax.tree.leaves(o_unroll)
    assert len(flat_s) == len(flat_u)
    for a, b in zip(flat_s, flat_u):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)

    def loss(variables, mod):
        out = mod.apply(variables, img1, img2, iterations=(2, 1))
        return sum(jnp.abs(f).mean() for lvl in out for f in lvl)

    g_scan = jax.grad(lambda vv: loss(vv, m_scan))(v)
    g_unroll = jax.grad(lambda vv: loss(vv, m_unroll))(v)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_scan)[0],
        jax.tree_util.tree_flatten_with_path(g_unroll)[0],
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3, err_msg=str(path))


@pytest.mark.parametrize("which", ["ml", "sl", "sl-ctf"])
def test_scan_matches_unrolled_variants(which):
    """Scan and unrolled loop realizations agree for the ml/sl/sl-ctf
    hybrids (same variables — parameter paths are loop-independent)."""
    from raft_meets_dicl_tpu.models.impls.raft_dicl_ml import (
        RaftPlusDiclMlModule,
    )
    from raft_meets_dicl_tpu.models.impls.raft_dicl_sl import (
        RaftPlusDiclModule as SlModule,
    )
    from raft_meets_dicl_tpu.models.impls.raft_sl_ctf import RaftSlCtfModule

    rng = np.random.default_rng(21)

    if which == "ml":
        kw = dict(corr_levels=2, corr_radius=2, corr_channels=8,
                  context_channels=16, recurrent_channels=16)
        mods = (RaftPlusDiclMlModule(**kw),
                RaftPlusDiclMlModule(**kw, unroll=True))
        args = dict(iterations=2, corr_flow=True)
        shape = (1, 64, 96, 3)
    elif which == "sl":
        kw = dict(corr_radius=2, corr_channels=8, context_channels=16,
                  recurrent_channels=16)
        mods = (SlModule(**kw), SlModule(**kw, unroll=True))
        args = dict(iterations=2, corr_flow=True)
        shape = (1, 64, 96, 3)
    else:
        kw = dict(levels=2, corr_radius=2, corr_channels=16,
                  context_channels=16, recurrent_channels=16)
        mods = (RaftSlCtfModule(**kw), RaftSlCtfModule(**kw, unroll=True))
        args = dict(iterations=(2, 2), corr_flow=True)
        shape = (1, 64, 128, 3)

    img1 = jnp.asarray(rng.uniform(-1, 1, shape), jnp.float32)
    img2 = jnp.asarray(rng.uniform(-1, 1, shape), jnp.float32)

    init_iters = (dict(iterations=(1, 1))
                  if which == "sl-ctf" else dict(iterations=1))
    v = jax.jit(lambda: mods[0].init(RNG, img1, img2, **init_iters))()
    v2 = jax.jit(lambda: mods[1].init(RNG, img1, img2, **init_iters))()
    assert jax.tree.structure(v) == jax.tree.structure(v2)

    o_scan = mods[0].apply(v, img1, img2, **args)
    o_unroll = mods[1].apply(v, img1, img2, **args)
    for a, b in zip(jax.tree.leaves(o_scan), jax.tree.leaves(o_unroll)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
