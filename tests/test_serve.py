"""Serving-path tests: scheduler core, backpressure, request faults.

The scheduler/batcher mechanics (bucket coalescing determinism, partial
padding, bounded-queue sheds, sticky per-client ordering, request-level
fault degradation) run against a host-only fake session — no jax, so the
invariants are pinned fast and in isolation. The device half (partial
batches bit-exactly riding the full batch's compiled program, the warm
pool's zero-compile AOT contract) runs a real tiny model.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
from raft_meets_dicl_tpu import evaluation, serve, telemetry
from raft_meets_dicl_tpu import compile as programs
from raft_meets_dicl_tpu.models.input import ShapeBuckets
from raft_meets_dicl_tpu.models.wire import WireFormat
from raft_meets_dicl_tpu.serve import (
    BucketBatcher, ServeError, ServeRejected, ServeSession, Scheduler,
)
from raft_meets_dicl_tpu.telemetry import report as treport
from raft_meets_dicl_tpu.testing import faults

pytestmark = pytest.mark.serve

REPO = Path(__file__).parent.parent

TINY_SERVE_MODEL = {
    "name": "serve tiny", "id": "serve-tiny",
    "model": {"type": "raft/baseline",
              "parameters": {"corr-levels": 2, "corr-radius": 2,
                             "corr-channels": 32, "context-channels": 16,
                             "recurrent-channels": 16},
              "arguments": {"iterations": 2}},
    "loss": {"type": "raft/sequence"},
    "input": {"padding": {"type": "modulo", "mode": "zeros",
                          "size": [8, 8]}},
}


@pytest.fixture(autouse=True)
def _serve_hygiene(monkeypatch):
    """Every test starts unarmed with a fresh memory telemetry sink."""
    monkeypatch.delenv("RMD_FAULT", raising=False)
    monkeypatch.delenv("RMD_FAULT_STATE", raising=False)
    faults.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    yield sink
    telemetry.deactivate()
    faults.reset()


def _serve_events(sink, event):
    return [e for e in sink.events
            if e["kind"] == "serve" and e["event"] == event]


def _pair(shape, seed=0):
    rng = np.random.default_rng(seed)
    h, w = shape
    return (rng.random((h, w, 3), dtype=np.float32),
            rng.random((h, w, 3), dtype=np.float32))


class FakeSession:
    """Host-only stand-in for ServeSession: the 'flow' is a deterministic
    numpy function of the encoded inputs, so scheduler mechanics are
    testable without any device work."""

    def __init__(self, buckets, batch_size=4, delay_s=0.0):
        self.buckets = buckets
        self.batch_size = batch_size
        self.delay_s = delay_s
        self.batch_shapes = []

    def encode_image(self, img):
        return np.asarray(img, np.float32) * 2.0 - 1.0

    def compiles(self):
        return 0

    def run(self, img1, img2):
        self.batch_shapes.append(img1.shape)
        if self.delay_s:
            time.sleep(self.delay_s)
        return (img1 + img2)[..., :2]

    def fetch(self, flow):
        return np.asarray(flow)


def _fake_scheduler(batch_size=2, max_wait_ms=5.0, queue_limit=64,
                    delay_s=0.0):
    buckets = ShapeBuckets([(16, 24), (32, 48)])
    session = FakeSession(buckets, batch_size=batch_size, delay_s=delay_s)
    return Scheduler(session, batch_size=batch_size,
                     max_wait_ms=max_wait_ms, queue_limit=queue_limit)


def _offer(batcher, rid, bucket, client="c"):
    h, w = bucket
    img = np.zeros((h, w, 3), np.float32)
    req = serve.FlowRequest(rid=rid, client=client, seq=rid, bucket=bucket,
                            shape=(h, w), img1=img, img2=img, ticket=None,
                            t_submit=time.perf_counter())
    assert batcher.offer(req)
    return req


# -- batcher core -------------------------------------------------------------


def test_bucket_assignment_smallest_fit():
    buckets = ShapeBuckets([(32, 48), (16, 24), (32, 32)])
    b = BucketBatcher(buckets, batch_size=2, queue_limit=8)
    assert b.assign(10, 20) == (16, 24)    # smallest area that fits
    assert b.assign(16, 24) == (16, 24)    # exact fit
    assert b.assign(20, 30) == (32, 32)    # skips too-small buckets
    assert b.assign(30, 40) == (32, 48)
    assert b.assign(33, 20) is None        # oversized
    assert b.assign(20, 60) is None


def test_take_full_batches_first_then_fifo():
    buckets = ShapeBuckets([(16, 24), (32, 48)])
    b = BucketBatcher(buckets, batch_size=2, queue_limit=8)
    # older partial in the small bucket, then a full batch in the big one
    r0 = _offer(b, 0, (16, 24))
    r1 = _offer(b, 1, (32, 48))
    r2 = _offer(b, 2, (32, 48))
    now = time.perf_counter()
    bucket, batch = b.take(now, max_wait_s=60.0)
    assert bucket == (32, 48)              # full beats older partial
    assert [r.rid for r in batch] == [1, 2]  # strict FIFO within bucket
    # the partial hasn't expired: take reports its wake-up deadline
    bucket, deadline = b.take(now, max_wait_s=60.0)
    assert bucket is None
    assert deadline == pytest.approx(r0.t_enqueue + 60.0)
    # expired (or drained) partials dispatch
    bucket, batch = b.take(r0.t_enqueue + 61.0, max_wait_s=60.0)
    assert bucket == (16, 24) and [r.rid for r in batch] == [0]


def test_take_is_deterministic_for_a_submission_sequence():
    def coalesce():
        buckets = ShapeBuckets([(16, 24), (32, 48)])
        b = BucketBatcher(buckets, batch_size=2, queue_limit=16)
        order = [(16, 24), (32, 48), (16, 24), (32, 48), (16, 24)]
        for rid, bucket in enumerate(order):
            _offer(b, rid, bucket)
        batches = []
        while True:
            bucket, batch = b.take(time.perf_counter() + 1e6,
                                   max_wait_s=0.0, drain=True)
            if bucket is None:
                break
            batches.append((bucket, [r.rid for r in batch]))
        return batches

    assert coalesce() == coalesce()
    assert coalesce() == [((16, 24), [0, 2]), ((32, 48), [1, 3]),
                          ((16, 24), [4])]


def test_assemble_fills_partial_by_tiling_last():
    buckets = ShapeBuckets([(16, 24)])
    b = BucketBatcher(buckets, batch_size=3, queue_limit=8)
    r = _offer(b, 0, (16, 24))
    r.img1 = np.random.default_rng(0).random((16, 24, 3)).astype(np.float32)
    r.img2 = r.img1 + 1.0
    img1, img2, fill = b.assemble([r])
    assert fill == 2
    assert img1.shape == (3, 16, 24, 3)
    np.testing.assert_array_equal(img1[1], img1[0])
    np.testing.assert_array_equal(img1[2], img1[0])
    np.testing.assert_array_equal(img2[1], img2[0])


# -- scheduler: admission, backpressure, ordering, faults ---------------------


def test_scheduler_round_trip_and_spans(_serve_hygiene):
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    try:
        img1, img2 = _pair((14, 20))
        t = sched.submit(img1, img2)
        res = t.result(timeout=10.0)
    finally:
        sched.stop(drain=True)
    assert res.bucket == (16, 24)
    assert res.shape == (14, 20)
    assert res.flow.shape == (14, 20, 2)
    # the fake 'flow' is encode(img1)+encode(img2), cropped to the raw
    # extent — padding never leaks into the response
    want = (img1 * 2 - 1) + (img2 * 2 - 1)
    np.testing.assert_allclose(res.flow, want[..., :2], rtol=1e-6)
    for span in ("admission", "queue", "dispatch", "device", "total"):
        assert span in res.spans
    ev = _serve_events(_serve_hygiene, "request")
    assert len(ev) == 1 and ev[0]["rid"] == 0
    assert ev[0]["bucket"] == "16x24"
    bev = _serve_events(_serve_hygiene, "batch")
    assert len(bev) == 1 and bev[0]["size"] == 1 and bev[0]["fill"] == 1


def test_backpressure_sheds_at_queue_bound(_serve_hygiene):
    # not started: nothing drains the queues, so the bound is reachable
    sched = _fake_scheduler(batch_size=4, queue_limit=2, max_wait_ms=1e4)
    img1, img2 = _pair((14, 20))
    sched.submit(img1, img2)
    sched.submit(img1, img2)
    with pytest.raises(ServeRejected) as exc:
        sched.submit(img1, img2)
    assert exc.value.reason == "queue_full"
    ev = _serve_events(_serve_hygiene, "reject")
    assert len(ev) == 1
    assert ev[0]["reason"] == "queue_full" and ev[0]["bucket"] == "16x24"
    # the shed request never consumed a sequence slot: draining the two
    # admitted ones still releases both
    sched.start()
    sched.stop(drain=True)
    assert len(_serve_events(_serve_hygiene, "request")) == 2


def test_sticky_per_client_release_order():
    sched = _fake_scheduler(batch_size=1, max_wait_ms=1e4)  # never started
    img1, img2 = _pair((14, 20))
    tickets = [sched.submit(img1, img2, client="a") for _ in range(3)]
    batches = []
    for _ in range(3):
        bucket, batch = sched.batcher.take(time.perf_counter(), 0.0,
                                           drain=True)
        batches.append((bucket, batch))
    # complete out of order: 2 first — it must be held until 0 and 1 land
    sched._dispatch(*batches[2])
    assert not tickets[2].done()
    sched._dispatch(*batches[0])
    assert tickets[0].done() and not tickets[2].done()
    sched._dispatch(*batches[1])
    assert tickets[1].done() and tickets[2].done()
    rids = [t.result(timeout=1.0).rid for t in tickets]
    assert rids == [0, 1, 2]


def test_malformed_and_oversized_are_typed_at_admission(_serve_hygiene,
                                                        monkeypatch):
    sched = _fake_scheduler()
    img1, img2 = _pair((14, 20))
    with pytest.raises(ServeError) as exc:
        sched.submit(np.zeros((14, 20), np.float32), img2)
    assert exc.value.kind == "malformed"
    with pytest.raises(ServeError) as exc:
        sched.submit(img1, _pair((16, 20))[1])
    assert exc.value.kind == "malformed"
    with pytest.raises(ServeError) as exc:
        sched.submit(*_pair((64, 64)))  # fits no bucket
    assert exc.value.kind == "oversized"
    # fault-injected variants (the request-level faults harness)
    monkeypatch.setenv("RMD_FAULT",
                       "serve_malformed@index=3,serve_oversized@index=4")
    with pytest.raises(ServeError) as exc:
        sched.submit(img1, img2)
    assert exc.value.kind == "malformed"
    with pytest.raises(ServeError) as exc:
        sched.submit(img1, img2)
    assert exc.value.kind == "oversized"
    kinds = [e["error"] for e in _serve_events(_serve_hygiene, "error")]
    assert kinds == ["malformed", "malformed", "oversized", "malformed",
                     "oversized"]
    assert sched.pending() == 0  # nothing ever queued


def test_decode_fault_degrades_without_poisoning(_serve_hygiene,
                                                 monkeypatch):
    # rid 1 fails during batch preparation; rid 0 (same batch) must still
    # serve, and the dispatch loop must keep taking work afterwards
    monkeypatch.setenv("RMD_FAULT", "serve_decode_error@index=1")
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    try:
        img1, img2 = _pair((14, 20))
        t0 = sched.submit(img1, img2)
        t1 = sched.submit(img1, img2)
        res0 = t0.result(timeout=10.0)
        with pytest.raises(ServeError) as exc:
            t1.result(timeout=10.0)
        assert exc.value.kind == "decode"
        assert res0.flow.shape == (14, 20, 2)
        # loop alive: a later request still round-trips
        t2 = sched.submit(img1, img2)
        assert t2.result(timeout=10.0).rid == 2
    finally:
        sched.stop(drain=True)
    bev = _serve_events(_serve_hygiene, "batch")
    # the poisoned request was removed before assembly: first batch
    # dispatched size 1 (refilled by tiling), second size 1
    assert [e["size"] for e in bev] == [1, 1]
    errs = _serve_events(_serve_hygiene, "error")
    assert len(errs) == 1 and errs[0]["error"] == "decode"


def test_stop_without_drain_fails_queued_typed():
    sched = _fake_scheduler(batch_size=4, max_wait_ms=1e4).start()
    img1, img2 = _pair((14, 20))
    t = sched.submit(img1, img2)
    sched.stop(drain=False)
    with pytest.raises(ServeError) as exc:
        t.result(timeout=5.0)
    assert exc.value.kind == "internal"
    with pytest.raises(ServeRejected) as exc:
        sched.submit(img1, img2)
    assert exc.value.reason == "shutdown"


def test_loadgen_open_loop_summary():
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0).start()
    try:
        report = serve.loadgen.run_open_loop(
            sched, [(14, 20), (16, 24), (30, 40)], requests=9,
            rate_hz=500.0)
    finally:
        sched.stop(drain=True)
    assert report["requests"] == 9 and report["completed"] == 9
    assert report["rejected"] == {} and report["errors"] == {}
    assert report["p50_ms"] <= report["p99_ms"]
    assert report["pairs_per_sec"] > 0
    for span in ("admission", "queue", "dispatch", "device", "total"):
        assert span in report["spans_ms"]


def test_serve_report_section_renders(_serve_hygiene):
    monkeypatch_events = _serve_hygiene
    sched = _fake_scheduler(batch_size=2, max_wait_ms=2.0, queue_limit=1)
    img1, img2 = _pair((14, 20))
    t = sched.submit(img1, img2)
    with pytest.raises(ServeRejected):
        sched.submit(img1, img2)  # queue bound 1: typed shed
    sched.start()
    sched.stop(drain=True)
    t.result(timeout=5.0)
    stats = treport.serve_stats(monkeypatch_events.events)
    assert stats["requests"] == 1
    assert stats["rejects"] == {"queue_full": 1}
    assert stats["buckets"]["16x24"]["requests"] == 1
    text = treport.render(monkeypatch_events.events)
    assert "== serving ==" in text
    assert "queue_full" in text
    assert "bucket 16x24" in text


# -- device half: real tiny model --------------------------------------------


@pytest.fixture(scope="module")
def tiny_session():
    spec = models.load(TINY_SERVE_MODEL)
    return ServeSession(spec, ShapeBuckets([(32, 48)]),
                        wire=WireFormat.from_config("u8"), batch_size=2)


def test_partial_batch_rides_full_batch_program(tiny_session):
    session = tiny_session
    session.warm_pool()
    c0 = session.compiles()
    sched = Scheduler(session, max_wait_ms=1.0).start()
    try:
        img1, img2 = _pair((28, 40), seed=7)
        res = sched.submit(img1, img2).result(timeout=60.0)
    finally:
        sched.stop(drain=True)
    assert res.flow.shape == (28, 40, 2)
    # serving — including the partial batch — compiled nothing new
    assert session.compiles() == c0

    # bit-exact: the same pair tiled to the full batch size through the
    # program directly must produce the identical cropped flow
    e1, e2 = sched.batcher.encode_pair(img1, img2, (32, 48),
                                       session.encode_image)
    b1 = np.stack([e1, e1])
    b2 = np.stack([e2, e2])
    flow = session.fetch(session.run(b1, b2))
    np.testing.assert_array_equal(res.flow, flow[0, :28, :40, :])


def test_warm_pool_prebuild_then_zero_compile_replica(tmp_path,
                                                      _serve_hygiene):
    cfg = dict(TINY_SERVE_MODEL, id="serve-aot", name="serve aot")
    buckets = [(32, 48)]
    programs.enable_aot(str(tmp_path))
    try:
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        s1 = ServeSession(models.load(cfg), ShapeBuckets(buckets),
                          wire=WireFormat.from_config("u8"), batch_size=2)
        out1 = s1.warm_pool()
        assert [o["compiles"] for o in out1] == [1]
        assert [o["aot_saves"] for o in out1] == [1]

        # "new replica": drop every in-process program and model object;
        # only the exported artifacts remain
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        s2 = ServeSession(models.load(cfg), ShapeBuckets(buckets),
                          wire=WireFormat.from_config("u8"), batch_size=2)
        out2 = s2.warm_pool()
        assert [o["compiles"] for o in out2] == [0]
        assert [o["aot_hits"] for o in out2] == [1]

        # and it actually serves
        sched = Scheduler(s2, max_wait_ms=1.0).start()
        try:
            res = sched.submit(*_pair((30, 44))).result(timeout=60.0)
        finally:
            sched.stop(drain=True)
        assert res.flow.shape == (30, 44, 2)
        assert s2.compiles() == 0
    finally:
        programs.disable_aot()
    warm = _serve_events(_serve_hygiene, "warmup")
    assert len(warm) == 2
    assert warm[0]["aot_saves"] == 1 and warm[1]["aot_hits"] == 1


@pytest.mark.slow
def test_cli_serve_smoke(tmp_path):
    import yaml

    (tmp_path / "model.yaml").write_text(yaml.safe_dump(TINY_SERVE_MODEL))
    (tmp_path / "serve.yaml").write_text(yaml.safe_dump({
        "serve": {
            "model": "./model.yaml",
            "buckets": "32x48",
            "wire-format": "u8",
            "batch-size": 2,
            "max-wait-ms": 5,
            "requests": 6,
            "rate": 50,
        }
    }))
    import os
    import re

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    env["RMD_AOT_DIR"] = str(tmp_path / "programs")
    env["RMD_COMPILE_CACHE"] = str(tmp_path / "xla-cache")

    pre = subprocess.run(
        [sys.executable, str(REPO / "main.py"), "serve", "-c", "serve.yaml",
         "--prebuild"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=600)
    assert pre.returncode == 0, pre.stderr[-2000:]
    built = json.loads(pre.stdout.strip().splitlines()[-1])
    assert built["prebuild"][0]["aot_saves"] >= 0

    proc = subprocess.run(
        [sys.executable, str(REPO / "main.py"), "serve", "-c", "serve.yaml"],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["completed"] == 6
    assert report["p50_ms"] <= report["p99_ms"]
