"""Training goodput plane tests: step traces, ledger, sidecar, blackbox.

The trainer's observability contract, pinned end to end on CPU: the
per-step trace phases telescope *exactly* to the step total with zero
added host syncs (steptrace events ride the amortized finite-check
cadence), the goodput ledger classifies every wall-clock second into
exactly one class (classes sum to total by construction, resume-replay
attributed across a SIGTERM → auto-resume drill), the trainer sidecar
serves /metrics //healthz //statusz over a real socket, and the flight
recorder dumps a postmortem bundle under both fault drills.
"""

import json
from pathlib import Path

import pytest

from raft_meets_dicl_tpu import telemetry
from raft_meets_dicl_tpu.analysis import lint as lint_mod
from raft_meets_dicl_tpu.analysis import telemetrykinds
from raft_meets_dicl_tpu.analysis.lint import Module, ProjectContext
from raft_meets_dicl_tpu.strategy.checkpoint import find_auto_resume
from raft_meets_dicl_tpu.telemetry import (
    blackbox, core, goodput, metrics as metrics_mod, report as treport,
    sidecar, steptrace,
)
from raft_meets_dicl_tpu.testing import faults
from test_faults import _make_context
from test_trace import _get

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _goodput_hygiene(monkeypatch):
    """Fresh sink/registry/ledger/recorder per test; finite check every
    step so traces and syncs are deterministic."""
    monkeypatch.delenv("RMD_FAULT", raising=False)
    monkeypatch.delenv("RMD_FAULT_STATE", raising=False)
    monkeypatch.setenv("RMD_FINITE_CHECK_EVERY", "1")
    faults.reset()
    metrics_mod.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    yield sink
    telemetry.deactivate()
    goodput.deactivate()
    blackbox.deactivate()
    metrics_mod.reset()
    faults.reset()


def _events(sink, kind, **match):
    return [e for e in sink.events if e["kind"] == kind
            and all(e.get(k) == v for k, v in match.items())]


# -- step-trace decomposition -------------------------------------------------


def test_steptrace_phases_telescope_exactly():
    st = steptrace.StepTrace(step=7)
    for i, mark in enumerate(steptrace.MARKS):
        st.mark(mark, t=100.0 + i * 0.125)
    phases = st.phases()
    assert set(phases) == set(steptrace.PHASES)
    # exact telescoping: differences of one clock at consecutive marks
    # sum to the total with no residual
    assert sum(phases.values()) == st.total() == pytest.approx(0.75)
    rec = st.record()
    assert rec["step"] == 7
    assert sum(rec["phases"].values()) == pytest.approx(rec["total"],
                                                        abs=1e-5)


def test_steptrace_skipped_marks_still_cover_the_step():
    # a step without a finite-check fetch never hits "synced"; the span
    # is attributed to the phase named by its left mark, so coverage
    # stays exact
    st = steptrace.StepTrace(step=0)
    st.mark("start", t=1.0).mark("data", t=1.5).mark("prep", t=1.6)
    st.mark("dispatched", t=1.9).mark("done", t=2.25)
    phases = st.phases()
    assert sum(phases.values()) == st.total() == pytest.approx(1.25)
    assert phases["device"] == pytest.approx(0.35)  # dispatched→done
    assert phases["device_put"] == pytest.approx(0.3)  # prep→dispatched


def test_steptrace_unknown_mark_rejected():
    with pytest.raises(ValueError, match="unknown step mark"):
        steptrace.StepTrace().mark("teleport")


def _rec(step, total, data_wait=0.0):
    return {"step": step, "total": total,
            "phases": {"data_wait": data_wait,
                       "device": total - data_wait}}


def test_steptrace_summary_bounded_and_flags():
    s = steptrace.StepTraceSummary(capacity=8)
    for i in range(32):
        s.add(_rec(i, 0.1))
    assert len(s) == 8  # bounded: old records fall off
    snap = s.snapshot()
    assert snap["count"] == 8 and not snap["straggler"]
    assert snap["total_ms"]["p50"] == pytest.approx(100.0)

    s.add(_rec(32, 0.5))  # 5x the median: the last step is a straggler
    assert s.snapshot()["straggler"]

    starved = steptrace.StepTraceSummary(capacity=8)
    for i in range(8):
        starved.add(_rec(i, 0.1, data_wait=0.08))
    assert starved.snapshot()["data_starved"]


def test_steptrace_summary_event_windows():
    s = steptrace.StepTraceSummary()
    assert s.event(step=0) is None  # empty window emits nothing
    s.add(_rec(0, 0.1))
    s.add(_rec(1, 0.2))
    ev = s.event(step=2)
    assert ev["window"] == 2 and ev["step"] == 2
    assert s.event(step=2) is None  # drained


# -- goodput ledger -----------------------------------------------------------


def test_goodput_classes_sum_to_total():
    led = goodput.GoodputLedger().start(t=0.0)
    led.charge("compile", 2.0)
    led.charge("checkpoint", 0.5)
    led.charge("eval", 1.0)
    snap = led.snapshot(t=10.0)
    assert snap["classes"]["compile"] == 2.0
    assert snap["classes"]["productive"] == pytest.approx(6.5)
    assert sum(snap["classes"].values()) == pytest.approx(snap["total"],
                                                          abs=1e-9)
    assert snap["goodput"] == pytest.approx(0.65)


def test_goodput_overcharge_clamps_productive():
    led = goodput.GoodputLedger().start(t=0.0)
    led.charge("compile", 20.0)  # charged more than elapsed: clamp at 0
    snap = led.snapshot(t=10.0)
    assert snap["classes"]["productive"] == 0.0
    assert sum(snap["classes"].values()) == pytest.approx(snap["total"],
                                                          abs=1e-9)


def test_goodput_unknown_class_rejected():
    with pytest.raises(ValueError, match="unknown goodput class"):
        goodput.GoodputLedger().start().charge("coffee", 1.0)


def test_goodput_tap_classifies_telemetry_events(_goodput_hygiene):
    led = goodput.activate()
    tele = telemetry.get()
    tele.emit("compile", label="step", seconds=0.25)
    tele.emit("checkpoint", path="x.ckpt", step=1, seconds=0.125)
    tele.emit("eval", name="val", samples=4, batches=2, seconds=0.5)
    tele.emit("step", step=1, phases={"data_wait": 0.0625}, step_time=0.1,
              throughput_ema=1.0)
    snap = led.snapshot()
    assert snap["classes"]["compile"] == pytest.approx(0.25)
    assert snap["classes"]["checkpoint"] == pytest.approx(0.125)
    assert snap["classes"]["eval"] == pytest.approx(0.5)
    assert snap["classes"]["data_starved"] == pytest.approx(0.0625)


def test_goodput_resume_replay_window_settles():
    led = goodput.GoodputLedger().start()
    led.resume_from(5)
    led.step_completed(4)  # still behind the restored step: window open
    assert led._replay is not None
    led.step_completed(7)
    assert led._replay is None
    assert led.replayed_steps == 2
    snap = led.snapshot()
    assert snap["classes"]["resume_replay"] >= 0.0
    assert snap["replayed_steps"] == 2


def test_goodput_close_pins_total_and_settles_preempt():
    import time

    led = goodput.GoodputLedger().start()
    led.observe("preempt", {"signal": "SIGTERM", "step": 3})
    time.sleep(0.01)  # teardown wall clock the preemption burns
    snap = led.close()
    assert snap["classes"]["preempted"] > 0.0
    time.sleep(0.01)
    later = led.snapshot()  # closed: the total stops growing
    assert later["total"] == snap["total"]


def test_null_ledger_and_recorder_are_inert(tmp_path):
    led = goodput.get()
    assert not led.enabled and led.snapshot() == {}
    rec = blackbox.get()
    assert not rec.enabled
    assert rec.dump(tmp_path, "whatever") is None
    assert not list(Path(tmp_path).glob("postmortem-*"))


# -- schema -------------------------------------------------------------------


def test_schema_validates_new_kinds():
    def base(kind, **fields):
        return {"v": core.SCHEMA_VERSION, "t": 0.0, "kind": kind, **fields}

    core.validate_event(base("steptrace", step=3, phases={}))
    core.validate_event(base("goodput", total=1.0, classes={}))
    core.validate_event(base("postmortem", reason="crash", path="x.json"))
    with pytest.raises(ValueError):
        core.validate_event(base("steptrace", step=3))  # missing phases
    with pytest.raises(ValueError):
        core.validate_event(base("goodput", total=1.0))
    with pytest.raises(ValueError):
        core.validate_event(base("postmortem", reason="crash"))


# -- training loop integration ------------------------------------------------


def test_training_emits_steptraces_at_sync_cadence(tmp_path,
                                                   _goodput_hygiene):
    led = goodput.activate()
    ctx, _ = _make_context(tmp_path)
    ctx.run()
    assert ctx.steps_completed == 2

    straces = _events(_goodput_hygiene, "steptrace")
    syncs = _events(_goodput_hygiene, "device_sync")
    assert straces, "the loop must emit steptrace events"
    # zero added host syncs: steptrace windows ride the existing
    # finite-check cadence, so there is one event per device_sync sample
    assert len(straces) == len(syncs)
    assert sum(e["window"] for e in straces) == ctx.steps_completed
    # every record's phases telescope to its total (float precision
    # before rounding is pinned above; records carry 6-decimal rounding)
    for rec in ctx.steptraces._records:
        assert sum(rec["phases"].values()) == pytest.approx(rec["total"],
                                                            abs=1e-5)
    # in-step norms rode the finite fetch: no extra sync, values present
    assert ctx.last_norms is not None
    grad, update = ctx.last_norms
    assert grad is not None and grad >= 0.0
    assert update is not None and update >= 0.0

    snap = led.snapshot()
    assert sum(snap["classes"].values()) == pytest.approx(snap["total"],
                                                          abs=1e-6)


def test_trainer_sidecar_endpoints_over_real_socket(tmp_path,
                                                    _goodput_hygiene):
    led = goodput.activate()
    ctx, _ = _make_context(tmp_path)
    server = sidecar.train_observer(ctx, 0, sink=_goodput_hygiene,
                                    ledger=led)
    try:
        # before the first step: alive but not ready -> 503
        code, payload = _get(server.url + "/healthz")
        assert code == 503 and payload["ready"] is False

        ctx.run()

        code, payload = _get(server.url + "/healthz")
        assert code == 200
        assert payload["ready"] is True and payload["live"] is True

        code, text = _get(server.url + "/metrics")
        assert code == 200
        assert "rmd_train_ready 1" in text
        assert "rmd_train_goodput_seconds" in text
        assert "rmd_train_step_phase_p50_seconds" in text
        assert "rmd_train_grad_norm" in text

        code, status = _get(server.url + "/statusz")
        assert code == 200
        assert status["steps_completed"] == ctx.steps_completed
        assert status["steps"]["count"] == ctx.steps_completed
        assert set(status["goodput"]["classes"]) == set(goodput.CLASSES)
        assert status["nonfinite"]["count"] == 0

        code, _ = _get(server.url + "/bogus")
        assert code == 404
    finally:
        server.close()


# -- postmortem drills --------------------------------------------------------


def test_postmortem_bundle_on_nonfinite_escalation(tmp_path, monkeypatch,
                                                   _goodput_hygiene):
    monkeypatch.setenv(
        "RMD_FAULT", ",".join(f"nan_update@step={i}" for i in range(8)))
    faults.reset()
    blackbox.activate(capacity=8, registry=metrics_mod.registry())
    ctx, _ = _make_context(
        tmp_path, nonfinite={"policy": "skip", "max-consecutive": 2},
        epochs=3)
    with pytest.raises(RuntimeError, match="persist"):
        ctx.run()

    path = Path(tmp_path) / "postmortem-nonfinite.json"
    assert blackbox.get().dumped == path and path.exists()
    bundle = json.loads(path.read_text())
    assert bundle["reason"] == "nonfinite"
    assert bundle["steps"], "the step-trace ring must be in the bundle"
    assert bundle["knobs"]["RMD_FINITE_CHECK_EVERY"]["set"] is True
    # the bundle references the failure dump written next to it
    assert Path(bundle["checkpoint"]).name == "failed.ckpt"
    assert Path(bundle["checkpoint"]).exists()
    posts = _events(_goodput_hygiene, "postmortem")
    assert posts and posts[0]["path"] == str(path)


def test_postmortem_bundle_on_sigterm_references_emergency_ckpt(
        tmp_path, monkeypatch, _goodput_hygiene):
    monkeypatch.setenv("RMD_FAULT", "sigterm@step=1")
    faults.reset()
    blackbox.activate(capacity=8)
    led = goodput.activate()
    ctx, _ = _make_context(tmp_path, epochs=2)
    assert ctx.install_signal_handlers()
    ctx.run()
    assert ctx._stop == "SIGTERM"
    saved_step = ctx.step

    dumped = blackbox.get().dumped
    assert dumped is not None and dumped.exists()
    bundle = json.loads(dumped.read_text())
    assert bundle["reason"].startswith("preempt")
    # the ring survived the signal path and the bundle sits next to the
    # emergency checkpoint it references
    assert bundle["steps"]
    ckpt = Path(bundle["checkpoint"])
    assert ckpt.exists() and "emergency" in ckpt.name
    assert ckpt.parent == dumped.parent
    assert any(e["kind"] == "preempt" for e in bundle["events"])
    snap1 = led.close()

    # --resume auto drill: the replay window between the resume event and
    # the first step past the restored one lands in resume_replay
    found = find_auto_resume(tmp_path, model="tiny")
    assert found is not None
    file, chkpt = found
    blackbox.deactivate()
    led2 = goodput.activate()
    telemetry.get().emit("resume", path=str(file), step=saved_step)
    ctx2, _ = _make_context(tmp_path, epochs=2)
    ctx2.run(checkpoint=chkpt)
    assert ctx2.step > saved_step
    snap2 = led2.close()
    assert snap2["classes"]["resume_replay"] > 0.0
    # the emergency save restored the exact step it stopped at, so the
    # drill replays no optimizer steps — the replay cost is the window
    # seconds above (restore, rebuild, re-warm), not repeated work
    assert snap2["replayed_steps"] == 0
    for snap in (snap1, snap2):
        assert sum(snap["classes"].values()) == pytest.approx(
            snap["total"], abs=1e-6)


# -- lint: sidecar-route ------------------------------------------------------

SIDECAR_SRC = Path(sidecar.__file__)


def _sidecar_ctx(tmp_path, readme):
    (tmp_path / "README.md").write_text(readme)
    mod = Module(SIDECAR_SRC, telemetrykinds.SIDECAR_MODULE,
                 SIDECAR_SRC.read_text())
    return ProjectContext(tmp_path, [mod])


def test_lint_sidecar_route_rule(tmp_path):
    documented = " ".join(sidecar.ROUTES)
    assert not telemetrykinds.check_sidecar_routes(
        _sidecar_ctx(tmp_path, f"# obs\n{documented}\n"))

    findings = telemetrykinds.check_sidecar_routes(
        _sidecar_ctx(tmp_path, "# obs\n/metrics /healthz /statusz\n"))
    assert len(findings) == 1
    assert "/profilez" in findings[0].message


def test_lint_sidecar_route_requires_routes_tuple(tmp_path):
    (tmp_path / "README.md").write_text("/metrics")
    mod = Module(SIDECAR_SRC, telemetrykinds.SIDECAR_MODULE,
                 "x = 1\n")
    findings = telemetrykinds.check_sidecar_routes(
        ProjectContext(tmp_path, [mod]))
    assert findings and "ROUTES" in findings[0].message


def test_lint_sidecar_rule_registered_in_default_set():
    names = {r.name for r in lint_mod.default_rules()}
    assert telemetrykinds.SIDECAR_RULE in names


def test_repo_readme_documents_every_sidecar_route():
    root = Path(__file__).resolve().parent.parent
    mod = Module(SIDECAR_SRC, telemetrykinds.SIDECAR_MODULE,
                 SIDECAR_SRC.read_text())
    assert not telemetrykinds.check_sidecar_routes(
        ProjectContext(root, [mod]))


# -- report -------------------------------------------------------------------


def _ev(kind, t=0.0, **fields):
    return core.validate_event(
        {"v": core.SCHEMA_VERSION, "t": t, "kind": kind, **fields})


def test_report_renders_goodput_plane_sections():
    events = [
        _ev("steptrace", t=1.0, step=4, window=4,
            phases={"data_wait": {"p50_ms": 1.0, "p99_ms": 2.0},
                    "device": {"p50_ms": 90.0, "p99_ms": 120.0}},
            total_ms={"p50": 100.0, "p99": 130.0},
            straggler=False, data_starved=False),
        _ev("steptrace", t=2.0, step=2, scope="eval", name="val",
            bucket="32x48", window=2, samples=4,
            phases={"dispatch": 0.2}, total=0.25),
        _ev("goodput", t=3.0, total=10.0, wall=10.0, goodput=0.8,
            replayed_steps=1,
            classes={"productive": 8.0, "compile": 1.5,
                     "checkpoint": 0.5}),
        _ev("postmortem", t=4.0, reason="nonfinite",
            path="out/postmortem-nonfinite.json", steps=8, events=12,
            checkpoint="out/failed.ckpt"),
    ]
    text = treport.render(events)
    assert "== step traces" in text and "data_wait" in text
    assert "== eval progress" in text and "32x48" in text
    assert "== goodput ==" in text and "80.0" in text
    assert "== postmortem" in text and "failed.ckpt" in text

    flags = treport.find_anomalies(events)
    assert any("postmortem" in f for f in flags)


def test_report_flags_data_starved_windows():
    events = [_ev("steptrace", t=1.0, step=4, window=4, phases={},
                  total_ms={}, straggler=False, data_starved=True)]
    flags = treport.find_anomalies(events)
    assert any("data-starved" in f for f in flags)


def test_report_merged_runs_skew_and_stragglers():
    def step(t, i, wall):
        return _ev("step", t=t, step=i, phases={}, step_time=wall,
                   throughput_ema=1.0)

    fast = {"label": "host0", "events": [
        _ev("run_start", t=100.0, dir="runs/a"),
        *[step(100.0 + i, i, 0.1) for i in range(5)],
    ]}
    slow = {"label": "host1", "events": [
        _ev("run_start", t=105.0, dir="runs/b"),
        *[step(105.0 + i, i, 0.4) for i in range(5)],
        _ev("preempt", t=112.0, signal="SIGTERM", step=4),
    ]}
    merged = treport.merge_stats([fast, slow])
    rows = {r["label"]: r for r in merged["rows"]}
    assert rows["host0"]["skew_s"] == pytest.approx(0.0)
    assert rows["host1"]["skew_s"] == pytest.approx(5.0)
    assert rows["host1"]["straggler_x"] == pytest.approx(4.0)
    # landmarks from both hosts interleave on the shared clock
    kinds = [e["kind"] for _, _, e in merged["timeline"]]
    assert kinds == ["run_start", "run_start", "preempt"]

    text = treport.render_merged([fast, slow])
    assert "host0" in text and "host1" in text
    assert "straggler" in text and "merged timeline" in text


# -- eval progress heartbeat --------------------------------------------------


def test_eval_emits_per_bucket_progress(_goodput_hygiene):
    import jax
    import numpy as np

    from raft_meets_dicl_tpu import evaluation
    from raft_meets_dicl_tpu.models import input as minput
    from raft_meets_dicl_tpu.models.input import ShapeBuckets
    from test_eval_buckets import _local_model, _mixed_source

    model = _local_model()
    source = _mixed_source([(30, 44), (17, 25)], per_shape=2)
    spec = minput.InputSpec(padding=minput.ModuloPadding("zeros", [8, 8]))
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 32, 48, 3), np.float32),
                           np.zeros((1, 32, 48, 3), np.float32))
    buckets = ShapeBuckets([(32, 48), (24, 32)])
    loader = spec.apply(source, buckets=buckets).jax().loader(
        batch_size=2, shuffle=False, num_workers=0, group_by_shape=True)

    stats = evaluation.EvalRunStats(name="val")
    list(evaluation.evaluate(model, variables, loader, stats=stats,
                             show_progress=False))

    progress = _events(_goodput_hygiene, "steptrace", scope="eval")
    # the fix under test: a heartbeat lands per finished bucket, not one
    # silent gap from warmup to completion
    assert len(progress) == len(buckets.sizes)
    assert sum(e["window"] for e in progress) == stats.batches
    assert sum(e["samples"] for e in progress) == stats.samples
    assert {e["bucket"] for e in progress} == {"32x48", "24x32"}
    for e in progress:
        core.validate_event(dict(e))
