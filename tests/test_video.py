"""Streaming-video engine tests: warm-start programs, fw/bw products,
sticky serve sessions, sequence runner.

The host half pins the session-cache policy (bounded LRU + TTL + shape
check with an injectable clock), the forwards-backwards consistency
math on analytic flows (constant translation, layered motion), and the
report/visual plumbing — no jax. The device half runs a real tiny
model: the zero-init warm program must be bit-exact with its plain rung
twin, the sequence runner must spend fewer iterations on warm frames,
and the serve path must stay zero-compile while sticking warm state to
clients.
"""

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
from raft_meets_dicl_tpu import evaluation, serve, telemetry, visual
from raft_meets_dicl_tpu import compile as programs
from raft_meets_dicl_tpu.models.input import ShapeBuckets
from raft_meets_dicl_tpu.serve import (
    LadderSpec, Scheduler, ServeError, ServeSession,
)
from raft_meets_dicl_tpu.telemetry import report as treport
from raft_meets_dicl_tpu.video import (
    SequenceRunner, SessionCache, fw_bw_flows, fw_bw_products,
    fw_bw_products_batch, warp_flow,
)

pytestmark = pytest.mark.video

TINY_VIDEO_MODEL = {
    "name": "video tiny", "id": "video-tiny",
    "model": {"type": "raft/baseline",
              "parameters": {"corr-levels": 2, "corr-radius": 2,
                             "corr-channels": 32, "context-channels": 16,
                             "recurrent-channels": 16},
              "arguments": {"iterations": 2}},
    "loss": {"type": "raft/sequence"},
    "input": {"padding": {"type": "modulo", "mode": "zeros",
                          "size": [8, 8]}},
}


@pytest.fixture(autouse=True)
def _video_hygiene():
    """Every test runs against a fresh in-memory telemetry sink."""
    sink = telemetry.activate(telemetry.Telemetry())
    yield sink
    telemetry.deactivate()


def _events(sink, kind, event=None):
    return [e for e in sink.events if e["kind"] == kind
            and (event is None or e.get("event") == event)]


class _Clock:
    """Injectable monotonic clock for TTL tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- session cache: bounded, TTL-evicted, shape-checked ----------------------


def test_session_cache_hit_miss_and_shape_check(_video_hygiene):
    cache = SessionCache(capacity=4, ttl_s=10.0, clock=_Clock())
    flow = np.ones((4, 6, 2), np.float32)

    assert cache.get("cam0") is None            # cold: nothing stored
    cache.put("cam0", flow)
    assert len(cache) == 1
    np.testing.assert_array_equal(cache.get("cam0"), flow)
    np.testing.assert_array_equal(cache.get("cam0", shape=(4, 6, 2)), flow)

    # resolution switch: the old carry is useless and must be dropped
    assert cache.get("cam0", shape=(8, 12, 2)) is None
    assert cache.get("cam0") is None

    ev = [(e["event"], e["client"]) for e in _events(_video_hygiene,
                                                     "session")]
    assert ev == [("miss", "cam0"), ("hit", "cam0"), ("hit", "cam0"),
                  ("miss", "cam0"), ("miss", "cam0")]


def test_session_cache_ttl_eviction(_video_hygiene):
    clock = _Clock()
    cache = SessionCache(capacity=4, ttl_s=5.0, clock=clock)
    cache.put("cam0", np.zeros((2, 3, 2), np.float32))

    clock.t = 4.0
    assert cache.get("cam0") is not None        # within TTL: refreshed
    clock.t = 8.5
    assert cache.get("cam0") is not None        # touch at 4.0 reset the TTL
    clock.t = 15.0
    assert cache.get("cam0") is None            # stalled past TTL: cold
    assert len(cache) == 0

    evicts = _events(_video_hygiene, "session", "evict")
    assert len(evicts) == 1 and evicts[0]["reason"] == "ttl"


def test_session_cache_capacity_lru(_video_hygiene):
    cache = SessionCache(capacity=2, ttl_s=100.0, clock=_Clock())
    row = np.zeros((2, 3, 2), np.float32)
    cache.put("a", row)
    cache.put("b", row)
    cache.get("a")                              # touch: 'b' is now LRU
    cache.put("c", row)                         # bound 2: evicts 'b'
    assert cache.get("a") is not None
    assert cache.get("b") is None
    assert cache.get("c") is not None

    evicts = _events(_video_hygiene, "session", "evict")
    assert [(e["client"], e["reason"]) for e in evicts] == [
        ("b", "capacity")]


def test_session_cache_drop_and_validation():
    cache = SessionCache(capacity=2, ttl_s=1.0, clock=_Clock())
    cache.put("a", np.zeros((2, 3, 2), np.float32))
    assert cache.drop("a") is True              # stream closed
    assert cache.drop("a") is False
    assert len(cache) == 0

    with pytest.raises(ValueError):
        SessionCache(capacity=0, ttl_s=1.0)
    with pytest.raises(ValueError):
        SessionCache(capacity=1, ttl_s=0.0)


# -- forwards-backwards products ---------------------------------------------


def test_warp_flow_zero_is_identity():
    rng = np.random.default_rng(0)
    flow_b = rng.normal(size=(6, 8, 2)).astype(np.float32)
    warped, inside = warp_flow(flow_b, np.zeros((6, 8, 2), np.float32))
    np.testing.assert_allclose(warped, flow_b, rtol=1e-6)
    assert inside.all()


def test_fw_bw_products_constant_translation():
    h, w, d = 16, 20, 3.0
    flow_fw = np.zeros((h, w, 2), np.float32)
    flow_fw[..., 0] = d
    flow_bw = -flow_fw

    occ, conf = fw_bw_products(flow_fw, flow_bw)
    assert occ.shape == (h, w) and occ.dtype == bool
    assert conf.shape == (h, w) and conf.dtype == np.float32

    # consistent interior: round trip returns home, confidence ~= 1
    assert not occ[:, : w - 3].any()
    np.testing.assert_allclose(conf[:, : w - 3], 1.0, atol=1e-5)
    # pixels whose forward flow leaves the image are occluded by
    # definition, with zero confidence
    assert occ[:, w - 2 :].all()
    np.testing.assert_array_equal(conf[:, w - 2 :], 0.0)


def test_fw_bw_products_layered_motion_occlusion():
    # a foreground square moves right by d over a static background: the
    # background band it covers is occluded in frame 2, everything else
    # is consistent
    h, w, d = 24, 32, 4
    r0, r1, c0, c1 = 8, 16, 8, 16
    flow_fw = np.zeros((h, w, 2), np.float32)
    flow_fw[r0:r1, c0:c1, 0] = d
    flow_bw = np.zeros((h, w, 2), np.float32)
    flow_bw[r0:r1, c0 + d : c1 + d, 0] = -d

    occ, conf = fw_bw_products(flow_fw, flow_bw)

    covered = np.zeros((h, w), bool)
    covered[r0:r1, c1 : c1 + d] = True
    assert occ[covered].all()                  # the covered band is flagged
    assert not occ[~covered].any()             # fg + far bg are consistent
    assert conf[covered].max() < conf[~covered].min()


def test_fw_bw_products_batch_and_shape_check():
    flow = np.zeros((2, 8, 10, 2), np.float32)
    occ, conf = fw_bw_products_batch(flow, flow)
    assert occ.shape == (2, 8, 10) and conf.shape == (2, 8, 10)

    with pytest.raises(ValueError):
        fw_bw_products(np.zeros((8, 10, 2)), np.zeros((8, 12, 2)))


def test_fw_bw_flows_splits_doubled_batch():
    def step(variables, a, b):
        return (np.asarray(a) - np.asarray(b))[..., :2], None

    rng = np.random.default_rng(1)
    img1 = rng.random((2, 6, 8, 3), dtype=np.float32)
    img2 = rng.random((2, 6, 8, 3), dtype=np.float32)
    fw, bw = fw_bw_flows(step, None, img1, img2)
    np.testing.assert_allclose(fw, (img1 - img2)[..., :2], rtol=1e-6)
    np.testing.assert_allclose(bw, (img2 - img1)[..., :2], rtol=1e-6)


# -- visual + inspect plumbing -----------------------------------------------


def test_occlusion_overlay_contract():
    img = np.full((6, 8, 3), 0.5)
    occ = np.zeros((6, 8), bool)
    occ[2, 3] = True
    rgba = visual.occlusion_overlay(img, occ)
    assert rgba.shape == (6, 8, 4)
    assert rgba.min() >= 0.0 and rgba.max() <= 1.0
    np.testing.assert_array_equal(rgba[..., 3], 1.0)
    # occluded pixel is tinted red, the rest keep the image
    assert rgba[2, 3, 0] > rgba[0, 0, 0]
    np.testing.assert_allclose(rgba[0, 0, :3], 0.5)
    # mask-only render works without an image
    assert visual.occlusion_overlay(None, occ).shape == (6, 8, 4)


def test_confidence_to_rgba_contract():
    conf = np.linspace(0.0, 1.0, 48, dtype=np.float32).reshape(6, 8)
    rgba = visual.confidence_to_rgba(conf)
    assert rgba.shape == (6, 8, 4)
    assert rgba.min() >= 0.0 and rgba.max() <= 1.0
    # NaNs (never produced, but defensive) must not poison the render
    conf[0, 0] = np.nan
    assert np.isfinite(visual.confidence_to_rgba(conf)).all()


class _Writer:
    def __init__(self):
        self.tags = {}

    def add_image(self, tag, img, step, dataformats=None):
        self.tags[tag] = np.asarray(img)


def test_write_images_accepts_fwbw_products():
    from raft_meets_dicl_tpu.data.collection import Metadata
    from raft_meets_dicl_tpu.inspect import summary

    rng = np.random.default_rng(2)
    img = rng.random((1, 8, 10, 3), dtype=np.float32) * 2.0 - 1.0
    flow = rng.normal(size=(1, 8, 10, 2)).astype(np.float32)
    valid = np.ones((1, 8, 10), bool)
    meta = [Metadata(True, "d", None, ((0, 8), (0, 10)))]

    # default call: exactly the four existing TB tags, mirrors unchanged
    writer = _Writer()
    summary.write_images(writer, "p/", 0, img, img, flow, flow, valid,
                         meta, step=0)
    assert sorted(writer.tags) == ["p/flow-est", "p/flow-gt", "p/img1",
                                   "p/img2"]

    writer = _Writer()
    occ = np.zeros((1, 8, 10), bool)
    conf = np.ones((1, 8, 10), np.float32)
    summary.write_images(writer, "p/", 0, img, img, flow, flow, valid,
                         meta, step=0, occlusion=occ, confidence=conf)
    assert "p/fwbw-occlusion" in writer.tags
    assert "p/fwbw-confidence" in writer.tags
    assert writer.tags["p/fwbw-occlusion"].shape == (8, 10, 4)
    assert writer.tags["p/fwbw-confidence"].shape == (8, 10, 4)


# -- telemetry report --------------------------------------------------------


def test_video_stats_and_report_section():
    events = [
        {"kind": "video", "event": "frame", "frame": 0, "warm": False,
         "iterations": 12, "rungs": 1, "seconds": 0.5, "epe": 1.5},
        {"kind": "video", "event": "frame", "frame": 1, "warm": True,
         "iterations": 4, "rungs": 1, "seconds": 0.2, "epe": 1.6},
        {"kind": "video", "event": "frame", "frame": 2, "warm": True,
         "iterations": 4, "rungs": 1, "seconds": 0.2, "epe": 1.4},
        {"kind": "video", "event": "sequence", "frames": 3,
         "warm_frames": 2, "mean_iterations": 6.67, "frames_per_sec": 3.3,
         "seconds": 0.9, "mean_epe": 1.5},
        {"kind": "session", "event": "miss", "client": "a"},
        {"kind": "session", "event": "hit", "client": "a"},
        {"kind": "session", "event": "evict", "client": "a",
         "reason": "ttl"},
        {"kind": "serve", "event": "batch", "bucket": "32x48", "size": 2,
         "fill": 0, "compiles": 0, "seconds": 0.1, "video": True,
         "warm_members": 1, "products": True},
    ]
    stats = treport.video_stats(events)
    assert stats["cold"]["frames"] == 1
    assert stats["cold"]["mean_iterations"] == 12.0
    assert stats["warm"]["frames"] == 2
    assert stats["warm"]["mean_iterations"] == 4.0
    assert stats["warm"]["mean_epe"] == pytest.approx(1.5)
    assert stats["sequences"][0]["warm_frames"] == 2
    assert stats["sessions"] == {"hits": 1, "misses": 1,
                                 "evictions": {"ttl": 1}}
    assert stats["batches"] == {"batches": 1, "requests": 2, "warm": 1,
                                "products": 1}

    text = treport.render(events)
    assert "== video ==" in text
    assert "cold frames: 1" in text and "warm frames: 2" in text
    assert "1 warm hits / 2 lookups (50%)" in text
    assert "evictions ttl=1" in text
    assert "1 video batches" in text

    assert treport.video_stats([]) is None
    assert "== video ==" not in treport.render([])


# -- scheduler admission: sequence requests need a video session --------------


class _PlainFakeSession:
    """Minimal non-video stand-in (mirrors test_serve.FakeSession)."""

    def __init__(self, buckets, batch_size=4):
        self.buckets = buckets
        self.batch_size = batch_size

    def encode_image(self, img):
        return np.asarray(img, np.float32)

    def compiles(self):
        return 0

    def run(self, img1, img2):
        return (img1 + img2)[..., :2]

    def fetch(self, flow):
        return np.asarray(flow)


def test_sequence_requests_need_video_session():
    session = _PlainFakeSession(ShapeBuckets([(16, 24)]))
    sched = Scheduler(session, batch_size=2)
    img = np.zeros((16, 24, 3), np.float32)
    with pytest.raises(ServeError) as exc:
        sched.submit(img, img, sequence=True)
    assert exc.value.kind == "no_video"


# -- loadgen: sticky streams --------------------------------------------------


class FakeVideoSession:
    """Host-only video session: deterministic flow + a 2x-coarse carry."""

    def __init__(self, buckets, batch_size=1):
        self.buckets = buckets
        self.batch_size = batch_size
        self.video = True

    def encode_image(self, img):
        return np.asarray(img, np.float32)

    def compiles(self):
        return 0

    def fetch(self, flow):
        return np.asarray(flow)

    def run(self, img1, img2):
        return (img1 + img2)[..., :2]

    def run_video(self, img1, img2, carry=None):
        b, h, w = img1.shape[:3]
        flow = (img1 + img2)[..., :2]
        state = {"flow": np.zeros((b, h // 2, w // 2, 2), np.float32),
                 "hidden": np.zeros((b, h // 2, w // 2, 4), np.float32),
                 "delta": np.zeros((b,), np.float32)}
        return flow, state, {"rungs": 1, "iterations": 4,
                             "warm": carry is not None}


def test_loadgen_sequence_streams_report_warm_split(_video_hygiene):
    session = FakeVideoSession(ShapeBuckets([(16, 24)]))
    sched = Scheduler(session, batch_size=1, max_wait_ms=2.0).start()
    try:
        report = serve.loadgen.run_open_loop(
            sched, [(16, 24)], requests=6, rate_hz=500.0, sequence=True,
            streams=2)
    finally:
        sched.stop(drain=True)
    assert report["completed"] == 6
    # 2 sticky streams: each pays exactly one cold first frame
    assert report["video"] == {"warm": 4, "cold": 2}
    batches = _events(_video_hygiene, "serve", "batch")
    assert all(b["video"] for b in batches)
    assert sum(b["warm_members"] for b in batches) == 4


# -- device half: real tiny model ---------------------------------------------


@pytest.fixture(scope="module")
def tiny_video():
    import jax
    import jax.numpy as jnp

    spec = models.load(TINY_VIDEO_MODEL)
    rng = np.random.default_rng(4)
    img1 = rng.random((1, 32, 48, 3), dtype=np.float32)
    img2 = rng.random((1, 32, 48, 3), dtype=np.float32)
    variables = spec.model.init(jax.random.PRNGKey(0), jnp.asarray(img1),
                                jnp.asarray(img2), iterations=1)
    return spec, variables, jnp.asarray(img1), jnp.asarray(img2)


def test_warm_program_zero_init_bit_parity(tiny_video):
    import jax.numpy as jnp

    spec, variables, img1, img2 = tiny_video
    plain = evaluation.make_rung_fn(spec.model, 2, model_id=spec.id)
    warm = evaluation.make_warm_fn(spec.model, 2, model_id=spec.id)

    # the warm flag keys its own program — one per (rung, warm) pair
    assert warm.key != plain.key
    assert "warm" in dict(warm.key.flags)
    assert "warm" not in dict(plain.key.flags)
    assert warm is evaluation.make_warm_fn(spec.model, 2, model_id=spec.id)

    flow_p, state_p = plain(variables, img1, img2)
    zeros = jnp.zeros_like(state_p["flow"])
    flow_w, state_w = warm(variables, img1, img2, zeros)

    # zero carry == cold start, bit for bit: warm-start can never be a
    # correctness hazard, only an optimization
    np.testing.assert_array_equal(np.asarray(flow_w), np.asarray(flow_p))
    np.testing.assert_array_equal(np.asarray(state_w["flow"]),
                                  np.asarray(state_p["flow"]))
    np.testing.assert_array_equal(np.asarray(state_w["hidden"]),
                                  np.asarray(state_p["hidden"]))


def _constant_motion_frames(n=4, shift=2, shape=(32, 48), seed=5):
    rng = np.random.default_rng(seed)
    base = rng.random((shape[0], shape[1], 3), dtype=np.float32)
    frames = [np.roll(base, i * shift, axis=1)[None] for i in range(n)]
    target = np.zeros((1, shape[0], shape[1], 2), np.float32)
    target[..., 0] = shift
    return frames, [target] * (n - 1)


def test_sequence_runner_warm_spends_fewer_iterations(tiny_video,
                                                      _video_hygiene):
    spec, variables, _, _ = tiny_video
    runner = SequenceRunner(
        spec.model, variables, model_id=spec.id,
        ladder=LadderSpec(rungs=(1, 2), threshold=float("inf")))
    frames, targets = _constant_motion_frames()

    cold = runner.run(frames, targets=targets, warm=False)
    assert [f.warm for f in cold.frames] == [False, False, False]
    assert [f.iterations for f in cold.frames] == [2, 2, 2]
    assert cold.mean_iterations() == 2.0
    assert cold.warm_frames() == 0

    res = runner.run(frames, targets=targets)
    assert [f.warm for f in res.frames] == [False, True, True]
    # warm frames stop at the bottom rung (threshold inf: no escalation)
    assert [f.iterations for f in res.frames] == [2, 1, 1]
    assert [f.rungs for f in res.frames] == [1, 1, 1]
    assert res.mean_iterations() < cold.mean_iterations()
    assert res.warm_frames() == 2
    assert res.mean_epe() is not None and res.mean_epe() >= 0.0
    assert res.frames_per_sec() > 0.0
    assert res.frames[0].flow.shape == (1, 32, 48, 2)

    # a second pass reuses every program: recompile-free by construction
    c0 = runner.compiles()
    runner.run(frames, warm=True, keep_flows=False)
    assert runner.compiles() == c0

    frame_ev = _events(_video_hygiene, "video", "frame")
    seq_ev = _events(_video_hygiene, "video", "sequence")
    assert len(frame_ev) == 9 and len(seq_ev) == 3
    assert frame_ev[3]["warm"] is False and frame_ev[4]["warm"] is True
    assert "epe" in frame_ev[3] and "epe" not in frame_ev[6]
    assert seq_ev[1]["warm_frames"] == 2

    with pytest.raises(ValueError):
        runner.run(frames[:1])


def test_sequence_runner_escalates_under_tight_threshold(tiny_video):
    spec, variables, _, _ = tiny_video
    runner = SequenceRunner(
        spec.model, variables, model_id=spec.id,
        ladder=LadderSpec(rungs=(1, 2), threshold=1e-12))
    frames, _ = _constant_motion_frames(n=3)
    res = runner.run(frames)
    # a random-init model never converges below 1e-12: every warm frame
    # escalates through the +1 continuation up to the full budget (3
    # frames = 2 pairs: one cold, one warm-escalated)
    assert [f.iterations for f in res.frames] == [2, 2]
    assert [f.rungs for f in res.frames] == [1, 2]
    assert [f.warm for f in res.frames] == [False, True]


def test_serve_video_sticky_sessions_zero_compile(monkeypatch,
                                                  _video_hygiene):
    monkeypatch.setenv("RMD_VIDEO_WARM_ITERATIONS", "2")
    spec = models.load(TINY_VIDEO_MODEL)
    session = ServeSession(spec, ShapeBuckets([(32, 48)]), batch_size=1,
                           video=True)
    outcomes = session.warm_pool()
    rungs = sorted(o["rung"] for o in outcomes if "rung" in o)
    assert rungs == ["base:2", "warm:2"]

    c0 = session.compiles()
    clock = _Clock()
    sched = Scheduler(session, batch_size=1, max_wait_ms=2.0).start()
    sched.sessions = SessionCache(capacity=4, ttl_s=30.0, clock=clock)
    try:
        rng = np.random.default_rng(6)
        base = rng.random((30, 44, 3), dtype=np.float32)
        frames = [np.roll(base, 2 * i, axis=1) for i in range(4)]

        results = []
        for i in range(3):
            t = sched.submit(frames[i], frames[i + 1], client="cam0",
                             sequence=True, products=(i == 2))
            results.append(t.result(timeout=120.0))

        # sticky: the first frame is cold, every later one warm-starts
        assert [r.warm for r in results] == [False, True, True]
        assert all(r.iterations == 2 for r in results)
        assert all(r.flow.shape == (30, 44, 2) for r in results)
        assert len(sched.sessions) == 1

        # fw/bw products ride the same programs and crop to the request
        assert results[2].occlusion is not None
        assert results[2].occlusion.shape == (30, 44)
        assert results[2].occlusion.dtype == bool
        assert results[2].confidence.shape == (30, 44)

        # an unrelated client never sees cam0's carry
        other = sched.submit(frames[0], frames[1], client="cam1",
                             sequence=True).result(timeout=120.0)
        assert other.warm is False
        assert len(sched.sessions) == 2

        # a stream that stalls past the TTL restarts cold
        clock.t = 31.0
        stale = sched.submit(frames[0], frames[1], client="cam0",
                             sequence=True).result(timeout=120.0)
        assert stale.warm is False
    finally:
        sched.stop(drain=True)

    # the whole exercise — warm starts, reversed products pair, TTL
    # restart — rode the prebuilt program pool
    assert session.compiles() == c0

    batches = _events(_video_hygiene, "serve", "batch")
    assert [b["warm_members"] for b in batches] == [0, 1, 1, 0, 0]
    assert all(b["video"] for b in batches)
    assert sum(1 for b in batches if b.get("products")) == 1


def test_video_warm_pool_prebuild_then_zero_compile_replica(tmp_path,
                                                            monkeypatch):
    monkeypatch.setenv("RMD_VIDEO_WARM_ITERATIONS", "2")
    cfg = dict(TINY_VIDEO_MODEL, id="video-aot", name="video aot")
    buckets = [(32, 48)]
    programs.enable_aot(str(tmp_path))
    try:
        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        s1 = ServeSession(models.load(cfg), ShapeBuckets(buckets),
                          batch_size=1, video=True)
        out1 = s1.warm_pool()
        # eval + plain twin + warm variant all exported
        assert sum(o["aot_saves"] for o in out1) == 3

        programs.reset()
        evaluation._EVAL_FN_CACHE.clear()
        s2 = ServeSession(models.load(cfg), ShapeBuckets(buckets),
                          batch_size=1, video=True)
        out2 = s2.warm_pool()
        assert sum(o["compiles"] for o in out2) == 0
        assert sum(o["aot_hits"] for o in out2) == 3

        # and the replica actually serves warm frames without compiling
        sched = Scheduler(s2, batch_size=1, max_wait_ms=2.0).start()
        try:
            img = np.random.default_rng(7).random((30, 44, 3),
                                                  dtype=np.float32)
            r0 = sched.submit(img, img, client="c", sequence=True)
            r0.result(timeout=120.0)
            r1 = sched.submit(img, img, client="c", sequence=True)
            assert r1.result(timeout=120.0).warm is True
        finally:
            sched.stop(drain=True)
        assert s2.compiles() == 0
    finally:
        programs.disable_aot()
