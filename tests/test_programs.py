"""Compiled-program registry + AOT export + prefetch (PR 7).

Covers: ProgramKey identity/stability, registry dedupe across the
train-validation and eval-CLI paths, AOT save→reload roundtrips
(bit-identical outputs, zero second-boot compiles), corrupted and
version-mismatched artifacts falling back cleanly, per-program compile
attribution (the warm-cache overcount bugfix), the configurable
persistent-cache directory, the boot/aot telemetry schema + report
section, and the RMD_PREFETCH on/off parity of the training loop.
"""

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_meets_dicl_tpu import compile as programs
from raft_meets_dicl_tpu import evaluation, parallel, telemetry
import raft_meets_dicl_tpu.models as models


@pytest.fixture
def aot_store(tmp_path, monkeypatch):
    """AOT program store enabled against a temp dir; clean registry."""
    monkeypatch.delenv("RMD_AOT", raising=False)
    monkeypatch.delenv("RMD_AOT_DIR", raising=False)
    programs.reset()
    d = tmp_path / "programs"
    programs.enable_aot(str(d))
    yield d
    programs.disable_aot()
    programs.reset()


TINY_EVAL_MODEL = {
    "name": "tiny-prog", "id": "tiny-prog",
    "model": {
        "type": "raft/baseline",
        "parameters": {"corr-levels": 2, "corr-radius": 2,
                       "corr-channels": 32, "context-channels": 16,
                       "recurrent-channels": 16},
        "arguments": {"iterations": 2},
    },
    "loss": {"type": "raft/sequence"},
    "input": None,
}


# -- ProgramKey -----------------------------------------------------------


def test_program_key_identity():
    k1 = programs.ProgramKey("train_step", "m",
                             programs.flag_items(a=1, wire="u8"))
    k2 = programs.ProgramKey("train_step", "m",
                             programs.flag_items(wire="u8", a=1))
    assert k1 == k2  # flag order normalized
    assert hash(k1) == hash(k2)
    assert k1.canonical() == k2.canonical()

    assert k1 != programs.ProgramKey("eval_step", "m", k1.flags)
    assert k1 != programs.ProgramKey("train_step", "m2", k1.flags)
    assert k1 != programs.ProgramKey(
        "train_step", "m", programs.flag_items(a=2, wire="u8"))


def test_program_key_stability():
    stable = programs.ProgramKey("eval_step", "model-id",
                                 programs.flag_items(wire=None))
    assert stable.stable

    by_object = programs.ProgramKey("eval_step", programs.unstable(object()))
    assert not by_object.stable

    # an unstable flag component also pins the key to the process
    pinned = programs.ProgramKey(
        "val_loss", "model-id",
        programs.flag_items(loss=programs.unstable(object())))
    assert not pinned.stable


def test_shape_signature_over_pytrees():
    sig = programs.shape_signature(
        (({"a": jnp.zeros((2, 3)), "b": jnp.zeros((4,), jnp.int32)},),
         1.5, True))
    assert ((2, 3), "float32") in sig
    assert ((4,), "int32") in sig
    assert "float" in sig and "bool" in sig
    # identical structure, different shape -> different signature
    sig2 = programs.shape_signature(
        (({"a": jnp.zeros((2, 4)), "b": jnp.zeros((4,), jnp.int32)},),
         1.5, True))
    assert sig != sig2


# -- registry dedupe + compile attribution --------------------------------


def test_registry_dedupe_and_anonymous():
    programs.reset()
    key = programs.ProgramKey("eval_step", "dedupe-model")
    f1, f2 = jax.jit(lambda x: x + 1), jax.jit(lambda x: x + 1)
    a = programs.register_step("eval_step", f1, key=key)
    b = programs.register_step("eval_step", f2, key=key)
    assert a is b  # same key: second build returns the first program

    c = programs.register_step("eval_step", f1)
    d = programs.register_step("eval_step", f1)
    assert c is not d  # anonymous: never shared
    programs.reset()


def test_program_counts_compiles_without_telemetry_sink():
    """Per-program compile counters come from the jax.monitoring
    listener and work with the null sink — the basis of the warm-cache
    accounting fix."""
    programs.reset()
    prog = programs.register_step("eval_step", jax.jit(lambda x: x * 2))
    assert isinstance(telemetry.get(), telemetry.NullTelemetry)
    assert prog.compiles == 0
    prog(jnp.ones((3,)))
    assert prog.compiles == 1
    assert prog.compile_seconds > 0.0
    prog(jnp.ones((3,)))
    assert prog.compiles == 1  # jit cache hit: no new compile
    prog(jnp.ones((4,)))
    assert prog.compiles == 2  # new shape retraces
    programs.reset()


def test_eval_fn_dedupes_across_validation_and_cli_paths():
    """The same (model, bucket, wire) triple builds ONE program whether
    it is requested through the eval-CLI path or the training-validation
    path — both name the model by its stable config id."""
    programs.reset()
    evaluation._EVAL_FN_CACHE.clear()
    m_cli = models.load(TINY_EVAL_MODEL).model
    m_val = models.load(TINY_EVAL_MODEL).model  # a distinct object
    assert m_cli is not m_val

    cli = evaluation.make_eval_fn(m_cli, {"iterations": 2},
                                  model_id="tiny-prog")
    evaluation._EVAL_FN_CACHE.clear()  # module cache out of the way
    val = evaluation.make_eval_fn(m_val, {"iterations": 2},
                                  model_id="tiny-prog")
    assert cli is val

    # and the validation step builder reuses exactly that program as its
    # forward pass
    from types import SimpleNamespace

    from raft_meets_dicl_tpu.inspect.summary import StrategyValidation

    sv = StrategyValidation(1, False, "", [], None)
    ctx = SimpleNamespace(model=m_val, loss=models.load(TINY_EVAL_MODEL).loss,
                          model_id="tiny-prog")
    stage = SimpleNamespace(model_args={"iterations": 2}, loss_args={})
    step = sv._val_step(ctx, stage)
    assert step.programs[0] is cli
    assert step.programs[1].key.kind == "val_loss"
    programs.reset()


def test_val_step_matches_fused_reference():
    """The split validation step (shared forward program + loss program)
    must produce the same numbers as the pre-PR-7 fused jit."""
    from types import SimpleNamespace

    from raft_meets_dicl_tpu.inspect.summary import StrategyValidation

    programs.reset()
    evaluation._EVAL_FN_CACHE.clear()
    spec = models.load(TINY_EVAL_MODEL)
    model, loss_fn = spec.model, spec.loss
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 48, 3)),
                           jnp.zeros((1, 32, 48, 3)), iterations=1)

    rng = np.random.RandomState(7)
    img1 = jnp.asarray(rng.rand(2, 32, 48, 3), jnp.float32)
    img2 = jnp.asarray(rng.rand(2, 32, 48, 3), jnp.float32)
    flow = jnp.asarray(rng.randn(2, 32, 48, 2), jnp.float32)
    valid = jnp.ones((2, 32, 48), bool)

    sv = StrategyValidation(1, False, "", [], None)
    ctx = SimpleNamespace(model=model, loss=loss_fn, model_id="tiny-prog")
    stage = SimpleNamespace(model_args={"iterations": 2}, loss_args={})
    step = sv._val_step(ctx, stage)
    assert sv._val_step(ctx, stage) is step  # memoized
    est, loss = step(variables, img1, img2, flow, valid)

    out = model.apply(variables, img1, img2, train=False, iterations=2)
    result = model.get_adapter().wrap_result(out, (32, 48))
    ref_est = result.final()
    ref_loss = loss_fn(model, result.output(), flow, valid)

    np.testing.assert_allclose(np.asarray(est), np.asarray(ref_est),
                               atol=1e-5, rtol=1e-5)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    programs.reset()


# -- AOT roundtrip --------------------------------------------------------


def _toy_step_fn():
    def fn(state, x):
        return {"w": state["w"] + x.sum()}, {"y": x * state["w"]}

    return jax.jit(fn)


def test_aot_roundtrip_bit_identical(aot_store):
    key = programs.ProgramKey("train_step", "toy-roundtrip")
    prog = programs.register_step("train_step", _toy_step_fn(), key=key)
    state = {"w": jnp.asarray(2.0)}
    x = jnp.arange(6, dtype=jnp.float32)

    s1, aux1 = prog(state, x)
    assert prog.aot_misses == 1 and prog.aot_saves == 1
    assert len(list(aot_store.glob("*.rmdp"))) == 1

    # "second boot": fresh registry, fresh jit closure, same key
    programs.reset()
    prog2 = programs.register_step("train_step", _toy_step_fn(), key=key)
    s2, aux2 = prog2(state, x)
    assert prog2.aot_hits == 1
    assert prog2.compiles == 0  # the acceptance bar: zero compiles
    assert np.array_equal(np.asarray(aux1["y"]), np.asarray(aux2["y"]))
    assert np.array_equal(np.asarray(s1["w"]), np.asarray(s2["w"]))


def test_aot_second_boot_emits_no_compile_events(aot_store):
    """With artifacts present, a registered program records 0 compile
    events in the telemetry sink on the next boot."""
    key = programs.ProgramKey("train_step", "toy-events")
    prog = programs.register_step("train_step", _toy_step_fn(), key=key)
    prog({"w": jnp.asarray(1.0)}, jnp.ones((4,)))
    assert prog.aot_saves == 1

    programs.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    try:
        prog2 = programs.register_step("train_step", _toy_step_fn(),
                                       key=key)
        prog2({"w": jnp.asarray(1.0)}, jnp.ones((4,)))
        compiles = [e for e in sink.events
                    if e["kind"] == "compile"
                    and e["label"] == "train_step"]
        assert compiles == []
        aot_events = [e for e in sink.events if e["kind"] == "aot"]
        assert [e["event"] for e in aot_events] == ["hit"]
        assert aot_events[0]["program"] == "train_step"
    finally:
        telemetry.deactivate()


def test_aot_artifact_per_shape_signature(aot_store):
    key = programs.ProgramKey("eval_step", "toy-shapes")
    prog = programs.register_step("eval_step", jax.jit(lambda x: x + 1),
                                  key=key)
    prog(jnp.ones((2, 3)))
    prog(jnp.ones((4, 5)))
    assert prog.aot_saves == 2
    assert len(list(aot_store.glob("*.rmdp"))) == 2


def test_aot_corrupt_artifact_falls_back(aot_store):
    key = programs.ProgramKey("train_step", "toy-corrupt")
    prog = programs.register_step("train_step", _toy_step_fn(), key=key)
    state, x = {"w": jnp.asarray(3.0)}, jnp.ones((5,))
    _, aux_ref = prog(state, x)

    artifact = next(aot_store.glob("*.rmdp"))
    blob = bytearray(artifact.read_bytes())
    blob[len(blob) // 2] ^= 0xFF  # flip a payload byte
    artifact.write_bytes(bytes(blob))

    programs.reset()
    sink = telemetry.activate(telemetry.Telemetry())
    try:
        prog2 = programs.register_step("train_step", _toy_step_fn(),
                                       key=key)
        _, aux2 = prog2(state, x)  # must not raise
        assert np.array_equal(np.asarray(aux_ref["y"]),
                              np.asarray(aux2["y"]))
        assert prog2.aot_hits == 0
        assert prog2.aot_fallbacks >= 1
        events = [e["event"] for e in sink.events if e["kind"] == "aot"]
        assert "fallback" in events
    finally:
        telemetry.deactivate()

    # truncation is also absorbed
    artifact = next(aot_store.glob("*.rmdp"))
    artifact.write_bytes(artifact.read_bytes()[:64])
    programs.reset()
    prog3 = programs.register_step("train_step", _toy_step_fn(), key=key)
    _, aux3 = prog3(state, x)
    assert np.array_equal(np.asarray(aux_ref["y"]), np.asarray(aux3["y"]))
    assert prog3.aot_hits == 0


def test_aot_version_mismatch_falls_back(aot_store):
    key = programs.ProgramKey("train_step", "toy-version")
    prog = programs.register_step("train_step", _toy_step_fn(), key=key)
    state, x = {"w": jnp.asarray(1.0)}, jnp.ones((3,))
    _, aux_ref = prog(state, x)

    artifact = next(aot_store.glob("*.rmdp"))
    record = pickle.loads(artifact.read_bytes())
    record["fingerprint"] = "jax=0.0.0 stale"
    artifact.write_bytes(pickle.dumps(record))

    programs.reset()
    prog2 = programs.register_step("train_step", _toy_step_fn(), key=key)
    _, aux2 = prog2(state, x)
    assert np.array_equal(np.asarray(aux_ref["y"]), np.asarray(aux2["y"]))
    assert prog2.aot_hits == 0 and prog2.aot_fallbacks >= 1
    # the cold compile re-saved a loadable artifact for the next boot
    assert prog2.aot_saves == 1


def test_aot_train_step_roundtrip_through_builder(aot_store):
    """End-to-end through parallel.make_train_step: a keyed tiny train
    step saves its executable; a fresh build reloads it with zero
    compiles and bit-identical parameter updates."""
    import optax

    spec = models.load(TINY_EVAL_MODEL)
    model, loss = spec.model, spec.loss
    variables = jax.tree.map(np.asarray, model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 48, 3)),
        jnp.zeros((1, 32, 48, 3)), iterations=1))
    tx = optax.adam(1e-3)

    rng = np.random.RandomState(0)
    batch = tuple(jnp.asarray(v) for v in (
        rng.rand(2, 32, 48, 3).astype(np.float32),
        rng.rand(2, 32, 48, 3).astype(np.float32),
        rng.randn(2, 32, 48, 2).astype(np.float32),
        np.ones((2, 32, 48), bool)))
    key = programs.ProgramKey(
        "train_step", "tiny-prog",
        programs.flag_items(shape=(2, 32, 48), iterations=2))

    def build_and_step():
        state = parallel.TrainState.create(
            jax.tree.map(jnp.asarray, variables), tx)
        step = parallel.make_train_step(model, loss, tx,
                                        model_args={"iterations": 2},
                                        key=key)
        new_state, aux = step(state, *batch)
        return step, new_state, float(aux["loss"])

    step1, state1, loss1 = build_and_step()
    assert step1.aot_saves == 1

    programs.reset()
    step2, state2, loss2 = build_and_step()
    assert step2.aot_hits == 1 and step2.compiles == 0
    assert loss1 == loss2
    for a, b in zip(jax.tree.leaves(state1.params),
                    jax.tree.leaves(state2.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# -- warm-cache compile accounting (overcount bugfix) ---------------------


def test_warmup_compiles_not_overcounted_when_warm(aot_store):
    """Second warmup over the same shapes reports 0 compiles — with the
    telemetry sink disabled, where the pre-PR-7 fallback guessed 1 per
    shape."""
    evaluation._EVAL_FN_CACHE.clear()
    model = models.load(TINY_EVAL_MODEL).model
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 48, 3)),
                           jnp.zeros((1, 32, 48, 3)), iterations=1)
    fn = evaluation.make_eval_fn(model, {"iterations": 2},
                                 model_id="tiny-prog-warm")
    assert isinstance(telemetry.get(), telemetry.NullTelemetry)

    cold = evaluation.EvalRunStats(name="cold")
    evaluation.warmup_eval_fn(fn, variables, [(32, 48), (24, 40)], 2,
                              stats=cold)
    assert cold.compiles == 2

    warm = evaluation.EvalRunStats(name="warm")
    evaluation.warmup_eval_fn(fn, variables, [(32, 48), (24, 40)], 2,
                              stats=warm)
    assert warm.compiles == 0
    assert warm.phases.get("warmup", 0.0) > 0.0
    programs.reset()


# -- compcache satellite --------------------------------------------------


def test_compile_cache_dir_configurable(tmp_path, monkeypatch):
    from raft_meets_dicl_tpu.utils import compcache

    orig_dir = jax.config.jax_compilation_cache_dir
    orig_entry = jax.config.jax_persistent_cache_min_entry_size_bytes
    orig_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        monkeypatch.delenv("RMD_NO_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("RMD_COMPILE_CACHE", str(tmp_path / "env-cache"))
        got = compcache.enable_persistent_cache()
        assert got == str(tmp_path / "env-cache")
        assert compcache.effective_dir() == got
        assert os.path.isdir(got)

        # an explicit path (the --compile-cache flag) wins over the env
        got = compcache.enable_persistent_cache(str(tmp_path / "cli-cache"))
        assert got == str(tmp_path / "cli-cache")
        assert compcache.effective_dir() == got

        # kill switch
        monkeypatch.setenv("RMD_NO_COMPILE_CACHE", "1")
        assert compcache.enable_persistent_cache() is None
        assert compcache.effective_dir() is None
    finally:
        jax.config.update("jax_compilation_cache_dir", orig_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          orig_entry)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          orig_secs)
        compcache._effective = None


def test_aot_dir_defaults_next_to_compile_cache(tmp_path, monkeypatch):
    from raft_meets_dicl_tpu.utils import compcache

    monkeypatch.delenv("RMD_AOT", raising=False)
    monkeypatch.delenv("RMD_AOT_DIR", raising=False)
    monkeypatch.setattr(compcache, "_effective", str(tmp_path / "cc"))
    try:
        got = programs.enable_aot()
        assert got == os.path.join(str(tmp_path / "cc"), "programs")
        assert programs.aot_enabled()
        # RMD_AOT=0 wins
        monkeypatch.setenv("RMD_AOT", "0")
        assert programs.enable_aot() is None
        assert not programs.aot_enabled()
    finally:
        programs.disable_aot()


# -- telemetry schema + report --------------------------------------------


def test_boot_and_aot_event_schema():
    def ev(kind, **f):
        return {"v": telemetry.SCHEMA_VERSION, "t": 0.0, "kind": kind, **f}

    telemetry.validate_event(ev("boot", compile_cache=None, aot_dir=None,
                                aot=False, prefetch=True))
    telemetry.validate_event(ev("aot", event="hit", program="train_step",
                                model="m", bytes=10, seconds=0.1))
    with pytest.raises(ValueError):
        telemetry.validate_event(ev("aot"))  # event field required
    with pytest.raises(ValueError):
        telemetry.validate_event(ev("boot"))


def test_report_compiled_programs_section_and_anomaly():
    from raft_meets_dicl_tpu.telemetry import report

    def ev(kind, **f):
        return {"v": telemetry.SCHEMA_VERSION, "t": 0.0, "kind": kind, **f}

    events = [
        ev("boot", compile_cache="/tmp/cc", aot_dir="/tmp/cc/programs",
           aot=True, prefetch=True),
        ev("aot", event="save", program="train_step", model="m",
           bytes=2 ** 20, seconds=0.2),
        ev("aot", event="hit", program="eval_step", model="m",
           bytes=2 ** 19, seconds=0.05),
        ev("aot", event="fallback", program="eval_step", model="m",
           reason="corrupt: crc mismatch"),
    ]
    stats = report.aot_stats(events)
    assert stats["boot"]["compile_cache"] == "/tmp/cc"
    assert stats["programs"][("train_step", "m")]["save"] == 1
    assert stats["programs"][("eval_step", "m")]["hit"] == 1
    assert stats["programs"][("eval_step", "m")]["fallback"] == 1

    text = report.render(events)
    assert "compiled programs" in text
    assert "/tmp/cc/programs" in text
    assert "1 AOT hits" in text

    flags = report.find_anomalies(events)
    assert any("AOT fallback to cold JIT" in f for f in flags)
    # a clean boot (no fallback) raises no AOT flag
    clean = [e for e in events if e.get("event") != "fallback"]
    assert not any("AOT" in f for f in report.find_anomalies(clean))


# -- prefetch -------------------------------------------------------------


def _run_tiny_training(tmp_path, monkeypatch, prefetch):
    from test_strategy import _make_context, _make_stage

    monkeypatch.setenv("RMD_PREFETCH", "1" if prefetch else "0")
    np.random.seed(1234)  # init seed + epoch order identical across runs
    ctx, _ = _make_context(tmp_path, [_make_stage(epochs=1)])
    ctx.run()
    assert ctx.step == 2
    return jax.tree.map(np.asarray, ctx.variables)


def test_prefetch_on_off_bit_identical(tmp_path, monkeypatch):
    """RMD_PREFETCH only moves the device_put off the critical path —
    training results are bit-identical with it on or off, and telemetry
    records the device_put phase either way."""
    sink_on = telemetry.activate(telemetry.Telemetry())
    try:
        v_on = _run_tiny_training(tmp_path / "on", monkeypatch, True)
    finally:
        telemetry.deactivate()

    sink_off = telemetry.activate(telemetry.Telemetry())
    try:
        v_off = _run_tiny_training(tmp_path / "off", monkeypatch, False)
    finally:
        telemetry.deactivate()

    leaves_on = jax.tree.leaves(v_on)
    leaves_off = jax.tree.leaves(v_off)
    assert len(leaves_on) == len(leaves_off)
    for a, b in zip(leaves_on, leaves_off):
        assert np.array_equal(a, b)

    for sink in (sink_on, sink_off):
        steps = [e for e in sink.events if e["kind"] == "step"]
        phases = set().union(*(e["phases"] for e in steps))
        assert {"data_wait", "device_put", "dispatch"} <= phases


def test_prefetch_depth_knob(monkeypatch):
    """The prefetch generator respects depth and re-raises loader
    errors at the consumption point."""
    from raft_meets_dicl_tpu.strategy.training import (
        _device_prefetch, _sync_transfer,
    )

    items = [(np.full((1,), i), np.full((1,), i), None, None, [i])
             for i in range(4)]
    got = list(_device_prefetch(iter(items), lambda b: ("dev",) + b,
                                depth=1, tele=telemetry.get()))
    assert [m for *_, m in got] == [[0], [1], [2], [3]]
    assert all(dev[0] == "dev" for _, dev, _ in got)

    got = list(_sync_transfer(iter(items), lambda b: ("dev",) + b,
                              tele=telemetry.get()))
    assert [m for *_, m in got] == [[0], [1], [2], [3]]

    def boom():
        yield items[0]
        raise RuntimeError("loader died")

    it = _device_prefetch(boom(), lambda b: b, tele=telemetry.get())
    next(it)
    with pytest.raises(RuntimeError, match="loader died"):
        next(it)
