"""Wire-format input pipeline tests: encode/decode contracts, the
numerical-parity guarantee on the jitted train step, and the
multiprocess decode loader.

Tolerances assert the contract documented in models/wire.py: f32 wire is
exact up to the normalization moving from numpy to XLA (~1e-5), bf16
quantizes images to 8 mantissa bits and flow to IEEE f16, u8 quantizes
images to 256 levels over the clip interval.
"""

import numpy as np
import pytest

import raft_meets_dicl_tpu.models as models
from raft_meets_dicl_tpu.data.collection import (
    Metadata, SampleArgs, SampleId,
)
from raft_meets_dicl_tpu.models import input as minput
from raft_meets_dicl_tpu.models import mpdecode
from raft_meets_dicl_tpu.models.wire import PRESETS, WireFormat

TINY = {
    "name": "tiny", "id": "tiny",
    "model": {
        "type": "raft/baseline",
        "parameters": {
            "corr-levels": 2, "corr-radius": 2, "corr-channels": 32,
            "context-channels": 16, "recurrent-channels": 16,
        },
        "arguments": {"iterations": 2},
    },
    "loss": {"type": "raft/sequence"},
    "input": None,
}


def _meta(h, w, b=1):
    return [
        Metadata(True, "t", SampleId("s", SampleArgs(), SampleArgs()),
                 ((0, h), (0, w)))
        for _ in range(b)
    ]


def _raw_sample(h=16, w=24, b=1, seed=0):
    rng = np.random.RandomState(seed)
    img1 = rng.rand(b, h, w, 3).astype(np.float32)
    img2 = rng.rand(b, h, w, 3).astype(np.float32)
    flow = rng.randn(b, h, w, 2).astype(np.float32)
    valid = rng.rand(b, h, w) > 0.1
    return img1, img2, flow, valid, _meta(h, w, b)


# -- format-level contracts ---------------------------------------------------


def test_wire_from_config_presets_and_errors():
    for name in PRESETS:
        wire = WireFormat.from_config(name)
        assert wire.get_config()["images"] == PRESETS[name]["images"]
    assert WireFormat.from_config(None) is None
    # mapping form with explicit keys
    wire = WireFormat.from_config({"images": "u8", "flow": "f16",
                                   "pack-valid": True})
    assert wire.images == "u8" and wire.flow == "f16" and wire.pack_valid
    with pytest.raises(ValueError, match="preset"):
        WireFormat.from_config("f64")
    with pytest.raises(ValueError, match="image dtype"):
        WireFormat(images="i4")
    with pytest.raises(ValueError, match="flow dtype"):
        WireFormat(flow="u8")


def test_wire_image_roundtrip_host():
    img = np.random.RandomState(1).rand(2, 8, 10, 3).astype(np.float32)
    norm = 2.0 * np.clip(img, 0.0, 1.0) - 1.0  # clip (0,1), range (-1,1)

    f32 = WireFormat.from_config("f32")
    np.testing.assert_allclose(
        f32.decode_images_host(f32.encode_image(img)), norm, atol=1e-6)

    bf16 = WireFormat.from_config("bf16")
    enc = bf16.encode_image(img)
    assert enc.dtype.itemsize == 2
    # 8 mantissa bits => <= 2^-9 relative on [0,1], x2 for the range scale
    np.testing.assert_allclose(
        bf16.decode_images_host(enc), norm, atol=2 ** -8)

    u8 = WireFormat.from_config("u8")
    enc = u8.encode_image(img)
    assert enc.dtype == np.uint8
    # 256 levels over the clip span, x2 for the range scale
    np.testing.assert_allclose(
        u8.decode_images_host(enc), norm, atol=1.01 / 255.0)


def test_wire_flow_f16_finite_and_close():
    wire = WireFormat.from_config("bf16")
    flow = np.random.RandomState(2).randn(1, 6, 7, 2).astype(np.float32) * 30
    # FLOW_INF clamp markers (1e10) must re-clamp to a finite f16 value
    flow[0, 0, 0, 0] = minput.FLOW_INF
    enc = wire.encode_flow(flow)
    assert enc.dtype == np.float16
    assert np.isfinite(enc.astype(np.float32)).all()
    np.testing.assert_allclose(enc[0, 1:].astype(np.float32), flow[0, 1:],
                               rtol=2 ** -10, atol=1e-2)


def test_wire_valid_packing_roundtrip_non_multiple_width():
    import jax.numpy as jnp

    wire = WireFormat.from_config("bf16")
    h, w = 5, 23  # width deliberately not a multiple of 8
    rng = np.random.RandomState(3)
    valid = rng.rand(1, h, w) > 0.5
    img = wire.encode_image(rng.rand(1, h, w, 3).astype(np.float32))
    packed = wire.encode_valid(valid)
    assert packed.shape == (1, h, -(-w // 8))

    _, _, _, dec = wire.decode(jnp.asarray(img), jnp.asarray(img),
                               valid=jnp.asarray(packed))
    assert dec.dtype == bool and dec.shape == (1, h, w)
    np.testing.assert_array_equal(np.asarray(dec), valid)


def test_wire_bytes_reduction():
    """The acceptance contract: bf16 wire ships >= 2x fewer bytes than
    f32, u8 >= 3x, on the training batch layout."""
    img1, img2, flow, valid, _ = _raw_sample(h=32, w=48, b=2)

    def volume(preset):
        if preset is None:
            batch = (np.float32(img1), np.float32(img2), flow, valid)
            return sum(a.nbytes for a in batch)
        wire = WireFormat.from_config(preset)
        batch = wire.encode_batch(
            (wire.encode_image(img1), wire.encode_image(img2), flow, valid))
        return wire.nbytes(batch)

    f32 = volume(None)
    assert volume("f32") == f32
    assert f32 / volume("bf16") >= 2.0
    assert f32 / volume("u8") >= 3.0


def test_input_spec_raw_mode_matches_normalized_after_decode():
    """InputSpec.apply(normalize=False) + host decode == the normalized
    path — including constant ('zeros') modulo padding, whose pad value
    is translated into raw space."""
    spec = minput.InputSpec(
        clip=(0, 1), range=(-1, 1),
        padding=minput.ModuloPadding("zeros", [8, 8]))
    src = [_raw_sample(h=6, w=10)]
    wire = WireFormat.from_config("f32", clip=spec.clip, range=spec.range)

    img1_n, *_ = spec.apply(src)[0]
    img1_r, *_ = spec.apply(src, normalize=False)[0]
    assert img1_r.shape == img1_n.shape  # padded to (8, 16)
    np.testing.assert_allclose(wire.decode_images_host(img1_r), img1_n,
                               atol=1e-6)


# -- jitted train-step parity -------------------------------------------------


def test_train_step_parity_wire_vs_f32():
    """The hard numerical contract from ISSUE 2: bf16-wire and u8-wire
    batches match the host-normalized f32 path on a jitted train step
    (loss + final flow) within the tolerances documented in
    models/wire.py; f32-wire matches to float rounding."""
    import jax
    import optax

    from raft_meets_dicl_tpu import parallel

    spec = models.load(TINY)
    model, loss = spec.model, spec.loss

    rng = np.random.RandomState(0)
    b, h, w = 2, 16, 24
    raw1 = rng.rand(b, h, w, 3).astype(np.float32)
    raw2 = rng.rand(b, h, w, 3).astype(np.float32)
    flow = rng.randn(b, h, w, 2).astype(np.float32)
    valid = rng.rand(b, h, w) > 0.1

    norm1 = 2.0 * np.clip(raw1, 0, 1) - 1.0
    norm2 = 2.0 * np.clip(raw2, 0, 1) - 1.0

    variables = model.init(jax.random.PRNGKey(0), norm1[:1], norm2[:1])
    # SGD: adam's first step is ~sign(g)*lr, which would amplify
    # quantization noise into lr-sized param differences
    tx = optax.sgd(1e-2)
    state0 = parallel.TrainState.create(variables, tx)

    step = parallel.make_train_step(model, loss, tx, donate=False)
    _, aux_ref = step(state0, norm1, norm2, flow, valid)
    loss_ref = float(aux_ref["loss"])
    final_ref = np.asarray(aux_ref["final"])

    # (preset, loss rtol, final-flow atol): f32 is XLA-vs-numpy rounding
    # only; bf16 feeds ~2^-9-relative image noise and f16 flow targets
    # through 2 GRU iterations; u8 feeds ~1/255 image noise
    cases = [("f32", 1e-5, 1e-4), ("bf16", 2e-2, 0.1), ("u8", 5e-2, 0.25)]
    for preset, loss_rtol, flow_atol in cases:
        wire = WireFormat.from_config(preset, clip=(0, 1), range=(-1, 1))
        w1 = wire.encode_image(raw1)
        w2 = wire.encode_image(raw2)
        _, _, wf, wv = wire.encode_batch((w1, w2, flow, valid))

        wstep = parallel.make_train_step(model, loss, tx, donate=False,
                                         wire=wire)
        wstate, aux = wstep(state0, w1, w2, wf, wv)

        assert abs(float(aux["loss"]) - loss_ref) <= loss_rtol * abs(loss_ref), \
            f"{preset}: loss {float(aux['loss'])} vs {loss_ref}"
        np.testing.assert_allclose(np.asarray(aux["final"]), final_ref,
                                   atol=flow_atol, err_msg=preset)
        # the updated params must stay finite and close to the reference
        for a, r in zip(jax.tree.leaves(wstate.params),
                        jax.tree.leaves(state0.params)):
            assert np.isfinite(np.asarray(a)).all()


def test_eval_step_parity_wire_vs_f32():
    import jax

    from raft_meets_dicl_tpu import parallel

    spec = models.load(TINY)
    model = spec.model

    rng = np.random.RandomState(1)
    raw1 = rng.rand(1, 16, 24, 3).astype(np.float32)
    raw2 = rng.rand(1, 16, 24, 3).astype(np.float32)
    norm1 = 2.0 * np.clip(raw1, 0, 1) - 1.0
    norm2 = 2.0 * np.clip(raw2, 0, 1) - 1.0

    variables = model.init(jax.random.PRNGKey(0), norm1, norm2)
    ref = np.asarray(parallel.make_eval_step(model)(variables, norm1, norm2))

    wire = WireFormat.from_config("bf16", clip=(0, 1), range=(-1, 1))
    got = np.asarray(parallel.make_eval_step(model, wire=wire)(
        variables, wire.encode_image(raw1), wire.encode_image(raw2)))
    np.testing.assert_allclose(got, ref, atol=0.1)


# -- adapter / loader integration ---------------------------------------------


def test_adapter_wire_emits_compact_images_exact_flow():
    sample = _raw_sample()
    wire = WireFormat.from_config("u8")
    adapter = minput.JaxAdapter([sample], wire=wire)
    img1, img2, flow, valid, meta = adapter[0]
    assert img1.dtype == np.uint8 and img2.dtype == np.uint8
    # flow/valid stay exact host-side; compression happens at device put
    assert flow.dtype == np.float32 and valid.dtype == bool
    assert meta[0].valid


def test_loader_rejects_unknown_kwargs():
    adapter = minput.JaxAdapter([_raw_sample()])
    with pytest.raises(TypeError):
        adapter.loader(batch_size=1, prefetch_factor=2)


def test_mpdecode_shared_memory_roundtrip():
    sample = _raw_sample(h=9, w=13)
    payload = mpdecode.encode_sample(sample)
    (img1, img2, flow, valid, meta), shm = mpdecode.decode_sample(payload)
    try:
        np.testing.assert_array_equal(img1, sample[0])
        np.testing.assert_array_equal(img2, sample[1])
        np.testing.assert_array_equal(flow, sample[2])
        np.testing.assert_array_equal(valid, sample[3])
        assert meta[0].valid
    finally:
        shm.close()
        shm.unlink()


def test_mpdecode_none_arrays():
    img1, img2, _, _, meta = _raw_sample()
    payload = mpdecode.encode_sample((img1, img2, None, None, meta))
    (d1, d2, flow, valid, _), shm = mpdecode.decode_sample(payload)
    try:
        np.testing.assert_array_equal(d1, img1)
        assert flow is None and valid is None
    finally:
        shm.close()
        shm.unlink()


def test_loader_procs_matches_thread_pool():
    """The decode-process loader yields the same batches as the thread
    pool (shuffle off), and releases its shared-memory segments."""
    source = [_raw_sample(seed=i) for i in range(5)]
    adapter = minput.JaxAdapter(source)

    ref = list(adapter.loader(batch_size=2, shuffle=False, num_workers=0))
    got = list(adapter.loader(batch_size=2, shuffle=False, procs=2))

    assert len(got) == len(ref) == 3
    for (r1, r2, rf, rv, rm), (g1, g2, gf, gv, gm) in zip(ref, got):
        np.testing.assert_array_equal(g1, r1)
        np.testing.assert_array_equal(gf, rf)
        np.testing.assert_array_equal(gv, rv)
        assert len(gm) == len(rm)
        # collate copied out of the segments: the arrays must own their
        # memory (the segments are unlinked by the time we read them)
        assert g1.flags.owndata or g1.base is None


def test_loader_procs_env_default(monkeypatch):
    monkeypatch.setenv("RMD_LOADER_PROCS", "0")
    loader = minput.JaxAdapter([_raw_sample()]).loader(batch_size=1)
    assert loader.procs == 0
    monkeypatch.setenv("RMD_LOADER_PROCS", "3")
    loader = minput.JaxAdapter([_raw_sample()]).loader(batch_size=1)
    assert loader.procs == 3


def test_loader_procs_worker_error_propagates():
    class Boom:
        def __len__(self):
            return 2

        def __getitem__(self, index):
            if index == 1:
                raise ValueError("bad sample")
            return _raw_sample()

    # bad_sample_budget=0 disables the self-healing retry/substitute
    # layer (tests/test_faults.py covers it): the worker's error must
    # propagate to the consumer as-is
    loader = minput.Loader(Boom(), batch_size=1, procs=1, retries=0,
                           bad_sample_budget=0)
    with pytest.raises(ValueError, match="bad sample"):
        list(loader)
