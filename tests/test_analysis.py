"""graftlint: lint-rule fixtures, baseline/suppression machinery, the
HLO program auditor, partition-rule coverage, and the repo-stays-clean
regression gate (this is the tier-1 lint gate itself)."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import jax.numpy as jnp
import pytest

from raft_meets_dicl_tpu import parallel
from raft_meets_dicl_tpu.analysis import (
    envknobs, hlo, hostsync, lint, precision, tracerflow,
)

pytestmark = pytest.mark.lint

REPO = Path(__file__).parent.parent


def mk(source, rel="raft_meets_dicl_tpu/models/fixture.py"):
    src = textwrap.dedent(source)
    return lint.Module(rel, rel, src)


def run_fixture(tmp_path, source, baseline=None):
    """Run the full lint pipeline over a one-file tree (suppression +
    baseline resolution included, unlike calling a rule check directly)."""
    (tmp_path / "main.py").write_text(textwrap.dedent(source))
    return lint.run(tmp_path, baseline=baseline, targets=("main.py",))


# -- host-sync ---------------------------------------------------------------


def test_hostsync_error_when_jit_reachable():
    m = mk("""
        import jax

        @jax.jit
        def step(state, batch):
            loss = state.apply(batch)
            return float(loss)
        """)
    found = hostsync.check(m)
    assert [f.severity for f in found] == ["error"]
    assert "jit-reachable" in found[0].message


def test_hostsync_reachable_through_helper_and_scan_body():
    m = mk("""
        import jax
        from jax import lax

        def fetch(x):
            return x.item()

        def body(carry, x):
            return carry, fetch(x)

        @jax.jit
        def step(xs):
            return lax.scan(body, 0, xs)
        """)
    errors = [f for f in hostsync.check(m) if f.severity == "error"]
    assert len(errors) == 1 and ".item()" in errors[0].message


def test_hostsync_warn_off_hot_path_and_registry_roots():
    m = mk("""
        import jax

        def summary(metrics):
            return float(metrics)

        def _train(state, batch):
            return state.apply(batch).item()

        register_step("train_step", _train)
        """)
    found = {f.severity for f in hostsync.check(m)}
    assert found == {"warn", "error"}  # summary warns, _train errors


def test_hostsync_skips_jax_free_modules_and_literals():
    clean = mk("""
        import numpy as np

        def parse(cfg):
            return float(cfg), float("nan"), np.asarray(cfg)
        """)
    assert hostsync.check(clean) == []
    jaxy = mk("""
        import jax

        def parse(args):
            return float(args.lr), int("3")
        """)
    assert hostsync.check(jaxy) == []  # attr chains + literals pass


def test_hostsync_suppression_resolves(tmp_path):
    rep = run_fixture(tmp_path, """
        import jax

        def summary(x):
            return float(x)  # graftlint: disable=host-sync -- eval table, post-step
        """)
    assert rep.ok
    assert [f.status for f in rep.findings] == ["suppressed"]
    assert rep.findings[0].justification == "eval table, post-step"


# -- tracer-branch -----------------------------------------------------------


def test_tracerbranch_flags_data_dependent_if():
    m = mk("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.abs(x)
            if y > 0:
                return y
            return x
        """)
    found = tracerflow.check(m)
    assert len(found) == 1 and found[0].rule == "tracer-branch"


def test_tracerbranch_static_attrs_and_shields_pass():
    m = mk("""
        import jax

        @jax.jit
        def f(x, mode=None):
            if x.ndim > 3:
                x = x[0]
            if isinstance(mode, str):
                x = x + 1
            if mode is None:
                x = x * 2
            while x.shape[0] > 4:
                x = x[::2]
            return x
        """)
    assert tracerflow.check(m) == []


def test_tracerbranch_ignores_host_functions():
    m = mk("""
        import jax

        def config(v):
            if v > 0:
                return v
            return -v
        """)
    assert tracerflow.check(m) == []


# -- f32-literal -------------------------------------------------------------


def test_precision_flags_dtypeless_and_explicit_f32():
    m = mk("""
        import flax.linen as nn
        import jax.numpy as jnp

        class Net(nn.Module):
            mixed_precision: bool = False

            def __call__(self, x):
                a = jnp.zeros((4,))
                b = jnp.ones((4,), dtype=jnp.float32)
                c = jnp.zeros((4,), dtype=jnp.bfloat16)
                return a + b + c
        """)
    found = precision.check(m)
    assert len(found) == 2
    assert "dtype-less" in found[0].message
    assert "jnp.float32" in found[1].message


def test_precision_scope_needs_policy_and_models_path():
    src = """
        import flax.linen as nn
        import jax.numpy as jnp

        class Net(nn.Module):
            features: int = 8

            def __call__(self, x):
                return x + jnp.zeros((4,))
        """
    assert precision.check(mk(src)) == []  # no policy field
    m = mk(src.replace("features: int = 8", "dtype: str = None"),
           rel="raft_meets_dicl_tpu/ops/fixture.py")
    assert precision.check(m) == []  # not under models/


# -- env-knob / env-docs -----------------------------------------------------


def test_envknob_flags_reads_not_writes():
    m = mk("""
        import os

        v = os.environ.get("RMD_TELEMETRY")
        w = os.environ["RMD_PREFETCH"]
        armed = "RMD_FAULT" in os.environ
        os.environ["RMD_FAULT"] = "decode:1"   # write: legal
        del os.environ["RMD_FAULT"]            # delete: legal
        """)
    found = envknobs.check(m)
    assert len(found) == 3
    msgs = " ".join(f.message for f in found)
    for name in ("RMD_TELEMETRY", "RMD_PREFETCH", "RMD_FAULT"):
        assert name in msgs
    assert all("utils.env" in f.message for f in found)


def _env_module_stub():
    # the project checks only engage when the linted tree contains the
    # knob registry itself
    return mk("KNOBS = {}\n", rel=envknobs.ENV_MODULE)


def test_envknob_project_catches_typo_and_stale(tmp_path):
    m = mk("""
        from raft_meets_dicl_tpu.utils import env

        x = env.get_bool("RMD_PREFTCH")
        """)
    ctx = lint.ProjectContext(tmp_path, [m, _env_module_stub()])
    found = envknobs.check_project(ctx)
    typos = [f for f in found if "RMD_PREFTCH" in f.message]
    assert len(typos) == 1 and "unregistered" in typos[0].message
    # with only this module in scope, real knobs are unreferenced = stale
    assert any("stale knob" in f.message for f in found)


def test_envknob_dead_rule_needs_an_accessor_read(tmp_path):
    # a knob that is written, saved/restored, and name-dropped in a
    # docstring is still *dead* until something reads it through a
    # typed accessor — this is what separates env-dead-knob from the
    # reference check in check_project
    knob = next(iter(_real_knobs()))
    mentions_only = mk(f"""
        import os

        def save_restore():
            '''round-trips {knob} around a fault drill'''
            old = os.environ.pop("{knob}", None)
            os.environ["{knob}"] = "1"
        """)
    ctx = lint.ProjectContext(tmp_path, [mentions_only, _env_module_stub()])
    dead = {f.message.split(":")[0] for f in envknobs.check_dead_knobs(ctx)}
    assert f"dead knob {knob}" in dead

    reader = mk(f"""
        from raft_meets_dicl_tpu.utils import env

        flag = env.get_bool("{knob}")
        """, rel="raft_meets_dicl_tpu/models/reader.py")
    ctx = lint.ProjectContext(
        tmp_path, [mentions_only, reader, _env_module_stub()])
    dead = {f.message.split(":")[0] for f in envknobs.check_dead_knobs(ctx)}
    assert f"dead knob {knob}" not in dead
    # every finding names the registry module, not the mentioning file
    for f in envknobs.check_dead_knobs(ctx):
        assert f.path == envknobs.ENV_MODULE

    # a direct environ read keeps the knob live too (it already draws
    # its own env-knob finding; no double jeopardy)
    env_reader = mk(f"""
        import os

        raw = os.environ.get("{knob}")
        """, rel="raft_meets_dicl_tpu/models/envreader.py")
    ctx = lint.ProjectContext(
        tmp_path, [mentions_only, env_reader, _env_module_stub()])
    dead = {f.message.split(":")[0] for f in envknobs.check_dead_knobs(ctx)}
    assert f"dead knob {knob}" not in dead


def _real_knobs():
    from raft_meets_dicl_tpu.utils import env
    return env.KNOBS


def test_envdocs_detects_missing_and_stale_table(tmp_path):
    from raft_meets_dicl_tpu.utils import env

    ctx = lint.ProjectContext(tmp_path, [_env_module_stub()])
    readme = tmp_path / "README.md"
    readme.write_text("# no markers\n")
    assert any("markers missing" in f.message
               for f in envknobs.check_docs(ctx))
    readme.write_text(f"{env.TABLE_BEGIN}\nstale\n{env.TABLE_END}\n")
    assert any("stale" in f.message for f in envknobs.check_docs(ctx))
    readme.write_text(
        f"{env.TABLE_BEGIN}\n{env.readme_table()}\n{env.TABLE_END}\n")
    assert envknobs.check_docs(ctx) == []


# -- framework: suppressions, baseline, report -------------------------------


def test_bad_suppression_missing_reason_and_unknown_rule(tmp_path):
    rep = run_fixture(tmp_path, """
        import jax

        def f(x):
            return float(x)  # graftlint: disable=host-sync

        y = 1  # graftlint: disable=no-such-rule -- because
        """)
    # the reason-less pragma still suppresses its line, but the gate
    # fails anyway: bad-suppression findings are never suppressible
    assert sorted(f.rule for f in rep.open) == ["bad-suppression",
                                               "bad-suppression"]
    assert not rep.ok
    assert [f.status for f in rep.findings
            if f.rule == "host-sync"] == ["suppressed"]


def test_baseline_requires_justification_and_reports_stale(tmp_path):
    with pytest.raises(ValueError, match="justification"):
        lint.Baseline([{"rule": "host-sync", "glob": "*"}])
    bl = lint.Baseline([
        {"rule": "host-sync", "glob": "main.py",
         "justification": "grandfathered"},
        {"rule": "host-sync", "glob": "never/*",
         "justification": "matches nothing"},
    ])
    rep = run_fixture(tmp_path, """
        import jax

        def f(x):
            return float(x)
        """, baseline=bl)
    assert rep.ok
    assert [f.status for f in rep.findings] == ["baselined"]
    assert [e["glob"] for e in rep.stale_baseline] == ["never/*"]


def test_baseline_version_gate(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(ValueError, match="version"):
        lint.Baseline.load(p)


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "main.py").write_text("def broken(:\n")
    rep = lint.run(tmp_path, baseline=lint.Baseline.empty(),
                   targets=("main.py",))
    assert [f.rule for f in rep.findings] == ["parse-error"]


def test_emit_events_schema(tmp_path):
    from raft_meets_dicl_tpu import telemetry
    from raft_meets_dicl_tpu.telemetry import report as trep

    rep = run_fixture(tmp_path, """
        import jax

        def f(x):
            return float(x)
        """)
    tele = telemetry.Telemetry()   # in-memory
    lint.emit_events(rep, tele)
    assert [e["kind"] for e in tele.events] == ["lint"]
    stats = trep.lint_stats(tele.events)
    assert stats["per_rule"]["host-sync"]["open"] == 1
    assert stats["open"][0]["path"] == "main.py"


# -- the repo gate -----------------------------------------------------------


def test_repo_is_lint_clean_with_committed_baseline():
    rep = lint.run(REPO)
    assert rep.n_modules > 100
    open_ = [f.location + " " + f.rule for f in rep.open]
    assert open_ == [], f"new lint findings: {open_}"
    stale = [(e["rule"], e["glob"]) for e in rep.stale_baseline]
    assert stale == [], f"stale baseline entries: {stale}"


@pytest.mark.slow
def test_cli_exit_codes(tmp_path):
    script = REPO / "scripts" / "graftlint.py"
    (tmp_path / "main.py").write_text(
        "import jax\n\ndef f(x):\n    return float(x)\n")
    bad = subprocess.run(
        [sys.executable, str(script), "--root", str(tmp_path), "--json"],
        capture_output=True, text=True)
    assert bad.returncode == 1
    payload = json.loads(bad.stdout)
    assert payload["ok"] is False and payload["open"] >= 1
    (tmp_path / "main.py").write_text("x = 1\n")
    good = subprocess.run(
        [sys.executable, str(script), "--root", str(tmp_path)],
        capture_output=True, text=True)
    assert good.returncode == 0, good.stdout + good.stderr


def _graftlint_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graftlint_cli", REPO / "scripts" / "graftlint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


HOTSYNC_SRC = "import jax\n\ndef f(x):\n    return float(x)\n"


def test_prune_drops_only_stale_baseline_entries(tmp_path):
    cli = _graftlint_cli()
    (tmp_path / "main.py").write_text(HOTSYNC_SRC)
    path = tmp_path / lint.BASELINE_NAME
    path.write_text(json.dumps({
        "version": 1,
        "comment": "header note that must survive the rewrite",
        "entries": [
            {"rule": "host-sync", "glob": "main.py",
             "justification": "grandfathered"},
            {"rule": "host-sync", "glob": "gone/*.py",
             "justification": "module deleted two PRs ago"},
        ],
    }))
    assert cli.prune_baseline(tmp_path, str(path)) == 0
    data = json.loads(path.read_text())
    # only the entry that matched nothing is gone; header rides through
    assert [e["glob"] for e in data["entries"]] == ["main.py"]
    assert data["comment"] == "header note that must survive the rewrite"
    assert data["version"] == 1
    # idempotent: a second prune is a no-op
    before = path.read_text()
    assert cli.prune_baseline(tmp_path, str(path)) == 0
    assert path.read_text() == before
    # the pruned baseline still fully suppresses the tree
    rep = lint.run(tmp_path, baseline=lint.Baseline.load(path))
    assert rep.ok and len(rep.baselined) == 1 and not rep.stale_baseline


def test_json_report_schema_and_exit_code_contract(tmp_path):
    cli = _graftlint_cli()
    bad = run_fixture(tmp_path, HOTSYNC_SRC)
    payload = cli.json_report(bad)
    assert payload["schema"] == 1
    assert payload["ok"] is False and payload["exit_code"] == 1
    assert payload["open"] >= 1
    f = payload["findings"][0]
    assert {"rule", "path", "line", "severity", "status",
            "message"} <= set(f)
    json.dumps(payload)  # must be serializable as-is

    good = run_fixture(tmp_path, "x = 1\n")
    payload = cli.json_report(good)
    assert payload["ok"] is True and payload["exit_code"] == 0
    assert payload["stale_baseline_entries"] == []
    # --hlo attaches program reports under a dedicated key
    payload = cli.json_report(good, hlo_reports=[{"program": "p"}])
    assert payload["hlo"] == [{"program": "p"}]


# -- HLO auditor -------------------------------------------------------------


STABLEHLO_FIXTURE = """
module @jit_step {
  func.func public @main(%arg0: tensor<8x16xf32>) -> tensor<8x16xf32> {
    %0 = stablehlo.constant dense<1.0> : tensor<1024x1024xf32> loc("x")
    %1 = stablehlo.all_reduce(%arg0) : tensor<8x16xf32> loc("y")
    %2 = stablehlo.convolution(%arg0, %arg0) : (tensor<8x16xf32>,
         tensor<8x16xf32>) -> tensor<8x16xf32>
    return %2 : tensor<8x16xf32>
  }
}
#loc = loc("step")
"""


def test_audit_stablehlo_counts():
    out = hlo.audit_stablehlo(STABLEHLO_FIXTURE)
    assert out["collectives"] == {"all-reduce": 1}
    assert out["f32_convolutions"] == 1
    assert out["large_constants"] == [
        {"type": "tensor<1024x1024xf32>", "bytes": 4 * 1024 * 1024}]


def test_fingerprint_ignores_locations_only():
    moved = STABLEHLO_FIXTURE.replace('loc("x")', 'loc("elsewhere")')
    assert hlo.fingerprint(STABLEHLO_FIXTURE) == hlo.fingerprint(moved)
    changed = STABLEHLO_FIXTURE.replace("dense<1.0>", "dense<2.0>")
    assert hlo.fingerprint(STABLEHLO_FIXTURE) != hlo.fingerprint(changed)


def test_audit_compiled_counts_rhs_ops_only():
    text = textwrap.dedent("""
        %ar = f32[8] all-reduce(%x), replica_groups={}
        %ag = f32[16] all-gather(%y), dimensions={0}
        ROOT %t = (f32[8]) tuple(%ar)
        all-reduce-free comment line
        """)
    assert hlo.audit_compiled(text) == {"all-reduce": 1, "all-gather": 1}


def test_audit_registry_flagship_programs():
    """The acceptance gate: every registered flagship program lowers with
    a stable fingerprint and sane collective counts, and the audit emits
    zero findings."""
    reports, findings = hlo.audit_registry(n_devices=2, shape=(48, 64))
    assert findings == []
    assert len(reports) == 2
    train = next(r for r in reports if "train_step" in r["key"])
    next(r for r in reports if "eval_step" in r["key"])
    for r in reports:
        assert r["fingerprint_stable"], r["key"]
        assert r["large_constants"] == []
    # 2-device data-parallel train step must sync gradients
    assert sum(train["compiled_collectives"].values()) > 0
    rendered = hlo.render_reports(reports)
    assert "hlo audit" in rendered and "stable" in rendered


# -- partition-rule coverage (satellite of the lint PR) ----------------------


@pytest.mark.spmd
def test_partitioner_coverage_flags_dead_rules():
    from jax.sharding import PartitionSpec as P

    mesh = parallel.make_mesh((4, 2))
    params = {"FeatureEncoder_0": {"Conv_0": {"kernel": jnp.zeros((3, 3, 8, 16)),
                                              "bias": jnp.zeros((16,))}}}
    part = parallel.Partitioner(mesh)
    cov = part.coverage(params)
    assert cov["n_paths"] == 2
    assert cov["unmatched"] == []
    # encoder rule matches; the dead ones are update/flow-head rules that
    # this toy tree never instantiates
    matches = dict(cov["rule_matches"])
    assert matches[r"(FeatureEncoder|StackEncoder|PoolEncoder|Rfpm)"
                   r"[^/]*/.*kernel$"] == 1
    assert len(cov["dead_rules"]) == 2

    bogus = parallel.Partitioner(
        mesh, rules=((r"NoSuchModule/.*kernel$", P("model")), (r".*", P())))
    cov = bogus.coverage(params)
    assert cov["dead_rules"] == [r"NoSuchModule/.*kernel$"]
    assert cov["unmatched"] == []


@pytest.mark.spmd
def test_shard_state_warns_on_dead_rules():
    import optax
    from jax.sharding import PartitionSpec as P

    mesh = parallel.make_mesh((4, 2))
    variables = {"params": {"Dense_0": {"kernel": jnp.zeros((8, 8))}}}
    tx = optax.sgd(1e-3)
    state = parallel.TrainState.create(variables, tx)
    part = parallel.Partitioner(
        mesh, rules=((r"Ghost/.*kernel$", P("model")), (r".*", P())))
    with pytest.warns(UserWarning, match="dead rules"):
        part.shard_state(state)
