"""Test configuration: force an 8-device virtual CPU backend.

Multi-device sharding/collective tests run on a virtual CPU mesh (JAX's
standard fake-backend trick) so the full SPMD path is exercised without TPU
pod hardware. The environment may pre-import jax with a TPU platform
(sitecustomize), so we both set the env vars and force the platform via
jax.config — the latter works as long as no backend has been used yet.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# XLA's CPU backend routes f32 convs/matmuls through oneDNN at reduced
# precision by default (~2e-3 relative error) — numerical-parity tests
# against torch need true f32
jax.config.update("jax_default_matmul_precision", "highest")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
