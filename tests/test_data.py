"""Data-layer tests: I/O round-trips, pattern engine, dataset layouts,
combinators, augmentations, and backward-flow estimation."""

import numpy as np
import pytest

from raft_meets_dicl_tpu.data import augment, combinators, fw_bw, io, patterns
from raft_meets_dicl_tpu.data import config as data_config
from raft_meets_dicl_tpu.data.collection import Collection, Metadata, SampleArgs, SampleId


# -- io ---------------------------------------------------------------------


def test_flo_roundtrip(tmp_path):
    uv = np.random.randn(13, 17, 2).astype(np.float32)
    io.write_flow_mb(tmp_path / "t.flo", uv)
    out = io.read_flow_mb(tmp_path / "t.flo")
    np.testing.assert_array_equal(out, uv)


def test_kitti_roundtrip(tmp_path):
    uv = np.round(np.random.uniform(-100, 100, (11, 7, 2)) * 64) / 64
    valid = np.random.rand(11, 7) > 0.3
    io.write_flow_kitti(tmp_path / "t.png", uv.astype(np.float32), valid)
    flow, v = io.read_flow_kitti(tmp_path / "t.png")
    np.testing.assert_allclose(flow[v], uv[v].astype(np.float32), atol=1 / 64)
    np.testing.assert_array_equal(v, valid)


def test_pfm_read(tmp_path):
    data = np.random.rand(5, 4, 3).astype(np.float32)
    with open(tmp_path / "t.pfm", "wb") as fd:
        fd.write(b"PF\n4 5\n-1.0\n")
        data[::-1].astype("<f4").tofile(fd)
    out = io.read_pfm(tmp_path / "t.pfm")
    np.testing.assert_allclose(out, data)


# -- patterns ---------------------------------------------------------------


def test_pattern_glob():
    assert patterns.to_glob("{type}/{pass}/frame_{idx:04d}.png") == "*/*/frame_*.png"


def test_pattern_match_types():
    p = patterns.FormatPattern("clean/{scene}/frame_{idx:04d}.png")
    m = p.match("clean/alley_1/frame_0012.png")
    assert m == {"scene": "alley_1", "idx": 12}
    assert p.match("final/alley_1/frame_0012.png") is None


def test_pattern_match_plain_int():
    p = patterns.FormatPattern("{seq:05d}_img{idx:d}.ppm")
    assert p.match("00001_img2.ppm") == {"seq": 1, "idx": 2}


def test_pattern_format_is_str_format():
    pat = "{seq:05d}_img{idx:d}.ppm"
    assert pat.format(seq=3, idx=1) == "00003_img1.ppm"


# -- dataset ----------------------------------------------------------------


def _make_sintel_like(root, scenes=("alley_1", "market_2"), frames=4):
    """Synthetic dataset tree shaped like Sintel with two passes."""
    for pass_ in ("clean", "final"):
        for scene in scenes:
            d = root / "training" / pass_ / scene
            d.mkdir(parents=True, exist_ok=True)
            for i in range(1, frames + 1):
                img = (np.random.rand(8, 12, 3) * 255).astype(np.uint8)
                import cv2

                cv2.imwrite(str(d / f"frame_{i:04d}.png"), img)
    for scene in scenes:
        d = root / "training" / "flow" / scene
        d.mkdir(parents=True, exist_ok=True)
        for i in range(1, frames):  # last frame has no flow
            io.write_flow_mb(d / f"frame_{i:04d}.flo", np.random.randn(8, 12, 2).astype(np.float32))


SPEC = {
    "id": "synthetic-sintel",
    "name": "Synthetic Sintel",
    "path": ".",
    "layout": {
        "type": "generic",
        "images": "training/{pass}/{scene}/frame_{idx:04d}.png",
        "flows": "training/flow/{scene}/frame_{idx:04d}.flo",
        "key": "{pass}/{scene}/frame_{idx:04d}",
    },
    "parameters": {"pass": {"values": ["clean", "final"], "sub": "pass"}},
}


def test_dataset_generic_layout(tmp_path):
    _make_sintel_like(tmp_path)

    cfg = {"type": "dataset", "spec": SPEC, "parameters": {"pass": "clean"}}
    ds = data_config.load(tmp_path, cfg)

    # 2 scenes × (4 frames - 1 tail) = 6 samples, clean pass only
    assert len(ds) == 6

    img1, img2, flow, valid, meta = ds[0]
    assert img1.shape == (1, 8, 12, 3)
    assert img2.shape == (1, 8, 12, 3)
    assert flow.shape == (1, 8, 12, 2)
    assert valid.shape == (1, 8, 12)
    assert valid.dtype == bool
    assert meta[0].dataset_id == "synthetic-sintel"
    assert "clean" in str(meta[0].sample_id)

    # config round-trips
    cfg2 = ds.get_config()
    assert cfg2["type"] == "dataset"
    assert cfg2["parameters"] == {"pass": "clean"}


def test_dataset_backwards_layout(tmp_path):
    _make_sintel_like(tmp_path)

    spec = dict(SPEC)
    spec["layout"] = dict(SPEC["layout"], type="generic-backwards")

    ds = data_config.load(tmp_path, {"type": "dataset", "spec": spec,
                                     "parameters": {"pass": "clean"}})
    assert len(ds) == 6

    # backwards pairs (idx, idx-1): first frame of a scene is dropped
    ids = sorted(str(m.sample_id) for _, _, _, _, m0 in [ds[i] for i in range(6)] for m in m0)
    assert all("0001" not in s or True for s in ids)  # smoke: ids exist
    _, _, _, _, meta = ds[0]
    assert meta[0].sample_id.img2.kwargs["idx"] == meta[0].sample_id.img1.kwargs["idx"] - 1


def test_dataset_file_filter(tmp_path):
    _make_sintel_like(tmp_path)
    # 6 samples in sorted key order; keep only token '1' entries
    (tmp_path / "split.txt").write_text("1\n0\n1\n0\n1\n0\n")

    cfg = {
        "type": "dataset",
        "spec": SPEC,
        "parameters": {"pass": "clean"},
        "filter": {"type": "file", "file": "split.txt", "value": "1"},
    }
    ds = data_config.load(tmp_path, cfg)
    assert len(ds) == 3


# -- combinators ------------------------------------------------------------


class FakeSource(Collection):
    type = "fake"

    def __init__(self, n, h=6, w=8):
        self.n, self.h, self.w = n, h, w

    def __getitem__(self, index):
        rng = np.random.RandomState(index)
        img1 = rng.rand(1, self.h, self.w, 3).astype(np.float32)
        img2 = rng.rand(1, self.h, self.w, 3).astype(np.float32)
        flow = rng.randn(1, self.h, self.w, 2).astype(np.float32)
        valid = np.ones((1, self.h, self.w), dtype=bool)
        meta = [Metadata(True, "fake", SampleId("s{idx}", SampleArgs([], {"idx": index}),
                                                SampleArgs([], {"idx": index + 1})),
                         ((0, self.h), (0, self.w)))]
        return img1, img2, flow, valid, meta

    def __len__(self):
        return self.n

    def get_config(self):
        return {"type": "fake", "n": self.n}

    def description(self):
        return "fake"


def test_concat_repeat_subset():
    a, b = FakeSource(3), FakeSource(2)

    cat = combinators.Concat([a, b])
    assert len(cat) == 5
    assert cat[4] is not None

    rep = combinators.Repeat(3, a)
    assert len(rep) == 9
    np.testing.assert_array_equal(rep[0][0], rep[3][0])
    with pytest.raises(IndexError):
        rep[9]

    sub = combinators.Subset(4, a)
    assert len(sub) == 4


def test_subset_seed_reproducible():
    """Subset draws from an explicit Generator: the same seed yields the
    same subset regardless of global-RNG consumption in between, and the
    drawn seed round-trips through get_config for --reproduce."""
    a = FakeSource(50)

    s1 = combinators.Subset(8, a, seed=123)
    np.random.rand(100)  # global draws must not perturb the subset
    s2 = combinators.Subset(8, a, seed=123)
    np.testing.assert_array_equal(s1.map, s2.map)

    # without an explicit seed, the drawn one is recorded in the config
    np.random.seed(7)
    s3 = combinators.Subset(8, a)
    cfg = s3.get_config()
    assert cfg["seed"] == s3.seed
    s4 = combinators.Subset(8, a, seed=cfg["seed"])
    np.testing.assert_array_equal(s3.map, s4.map)

    # run-level seeding (utils.seeds seeds the global RNG) reproduces the
    # derived seed itself
    np.random.seed(7)
    s5 = combinators.Subset(8, a)
    assert s5.seed == s3.seed


def test_cache_hits_return_fresh_metadata():
    """A consumer flipping meta.valid in place (the jax adapter does, on
    transiently-bad batches) must not poison the cached sample for later
    epochs."""
    cache = combinators.Cache(FakeSource(2), budget_gib=1.0)

    *_, meta = cache[0]
    assert meta[0].valid
    meta[0].valid = False  # what JaxAdapter._mark_invalid does

    *_, meta2 = cache[0]
    assert meta2[0].valid, "cache hit returned the mutated Metadata"
    assert meta2[0] is not meta[0]


# -- augmentations ----------------------------------------------------------


def _sample(h=16, w=20):
    return FakeSource(1, h, w)[0]


def test_crop():
    aug = augment.Crop([10, 8])  # (w, h)
    img1, img2, flow, valid, meta = aug(*_sample())
    assert img1.shape == (1, 8, 10, 3)
    assert flow.shape == (1, 8, 10, 2)
    assert meta[0].original_extents == ((0, 8), (0, 10))


def test_crop_center():
    aug = augment.CropCenter([10, 8])
    img1, *_ = aug(*_sample())
    assert img1.shape == (1, 8, 10, 3)


def test_flip_horizontal_flow_sign():
    img1, img2, flow, valid, meta = _sample()
    aug = augment.Flip([1.0, 0.0])  # always horizontal, never vertical
    f1, f2, fl, v, m = aug(img1, img2, flow, valid, meta)
    np.testing.assert_allclose(fl[:, :, :, 0], -flow[:, :, ::-1, 0])
    np.testing.assert_allclose(fl[:, :, :, 1], flow[:, :, ::-1, 1])
    np.testing.assert_allclose(f1, img1[:, :, ::-1])


def test_flip_vertical_flow_sign():
    img1, img2, flow, valid, meta = _sample()
    aug = augment.Flip([0.0, 1.0])
    _, _, fl, _, _ = aug(img1, img2, flow, valid, meta)
    np.testing.assert_allclose(fl[:, :, :, 1], -flow[:, ::-1, :, 1])


def test_occlusion_forward_only_touches_img2():
    img1, img2, flow, valid, meta = _sample()
    aug = augment.OcclusionForward(1.0, [3, 3], [4, 4], [8, 8])
    f1, f2, *_ = aug(img1.copy(), img2.copy(), flow, valid, meta)
    np.testing.assert_array_equal(f1, img1)
    assert not np.array_equal(f2, img2)


def test_restrict_flow_magnitude():
    img1, img2, flow, valid, meta = _sample()
    flow = flow * 0 + np.array([3.0, 4.0])  # magnitude 5 everywhere
    aug = augment.RestrictFlowMagnitude(4.0)
    _, _, _, v, _ = aug(img1, img2, flow, valid, meta)
    assert not v.any()


def test_scale_dense():
    img1, img2, flow, valid, meta = _sample(16, 20)
    aug = augment.Scale([0, 0], 2.0, 2.0, 0.0, 0.0, "linear", th_valid=0.99)
    f1, f2, fl, v, m = aug(img1, img2, flow, valid, meta)
    assert f1.shape == (1, 32, 40, 3)
    assert fl.shape == (1, 32, 40, 2)
    # flow vectors double with the resolution
    np.testing.assert_allclose(fl[0, 0, 0], flow[0, 0, 0] * 2.0, rtol=1e-4)


def test_scale_sparse_rescatters():
    img1, img2, flow, valid, meta = _sample(16, 20)
    valid = np.zeros_like(valid)
    valid[0, 4, 5] = True
    aug = augment.ScaleSparse([0, 0], 2.0, 2.0, 0.0, 0.0, "linear")
    _, _, fl, v, _ = aug(img1, img2, flow, valid, meta)
    assert v.sum() == 1
    assert v[0, 8, 10]
    np.testing.assert_allclose(fl[0, 8, 10], flow[0, 4, 5] * 2.0, rtol=1e-5)


def test_translate_adds_offset():
    img1, img2, flow, valid, meta = _sample(16, 20)
    aug = augment.Translate([10, 10], [3, 3])
    f1, f2, fl, v, _ = aug(img1, img2, flow, valid, meta)
    assert f1.shape == f2.shape
    assert f1.shape[1] >= 10 and f1.shape[2] >= 10


def test_color_jitter_stays_in_range():
    img1, img2, flow, valid, meta = _sample()
    aug = augment.ColorJitter(0.5, 0.4, 0.4, 0.4, 0.16)
    f1, f2, *_ = aug(img1, img2, flow, valid, meta)
    assert f1.min() >= 0.0 and f1.max() <= 1.0
    assert f1.shape == img1.shape
    assert f1.dtype == np.float32


def test_color_jitter_8bit_quantizes():
    img1, img2, flow, valid, meta = _sample()
    aug = augment.ColorJitter8bit(0.0, 0.0, 0.0, 0.0, 0.0)
    f1, *_ = aug(img1, img2, flow, valid, meta)
    np.testing.assert_allclose(f1, np.round(img1 * 255) / 255, atol=1e-6)


def test_augment_collection_roundtrip():
    src = FakeSource(2, h=16, w=20)
    aug = augment.Augment([augment.Crop([10, 8])], src, sync=True)
    img1, img2, flow, valid, meta = aug[0]
    assert img1.shape == (1, 8, 10, 3)
    cfg = aug.get_config()
    assert cfg["type"] == "augment"
    assert cfg["augmentations"][0]["type"] == "crop"


# -- fw/bw ------------------------------------------------------------------


def test_backwards_flow_constant_translation():
    h, w = 20, 24
    rng = np.random.RandomState(0)
    img = rng.rand(h, w, 3).astype(np.float32)

    # frame 2 is frame 1 shifted right by 3 pixels
    img2 = np.roll(img, 3, axis=1)
    flow = np.zeros((h, w, 2), dtype=np.float32)
    flow[..., 0] = 3.0
    valid = np.ones((h, w), dtype=bool)

    flow_bw, valid_bw = fw_bw.estimate_backwards_flow_sparse(img, img2, flow, valid)

    # interior pixels: backward flow is exactly -forward flow
    assert valid_bw[:, 4:].all()
    np.testing.assert_allclose(flow_bw[:, 4:, 0], -3.0, atol=1e-6)
    np.testing.assert_allclose(flow_bw[:, 4:, 1], 0.0, atol=1e-6)
    # disoccluded strip on the left receives no splats
    assert not valid_bw[:, :3].any()


def test_fill_min_densifies():
    flow = np.zeros((8, 8, 2))
    flow[..., 0] = 5.0
    valid = np.zeros((8, 8), dtype=bool)
    valid[4, 4] = True

    out, v = fw_bw.fill_min(flow, valid)
    assert v.all()
    np.testing.assert_allclose(out[..., 0], 5.0)


def test_fill_avg_densifies():
    flow = np.zeros((8, 8, 2))
    flow[..., 1] = -2.0
    valid = np.zeros((8, 8), dtype=bool)
    valid[2:6, 2:6] = True

    out, v = fw_bw.fill_avg(flow, valid, threshold=1)
    assert v.all()
    np.testing.assert_allclose(out[..., 1], -2.0)


def test_drop_sequence_tails_forward_and_backward():
    from raft_meets_dicl_tpu.data import dataset

    A, B = ("a",), ("b",)
    # scene A has an index gap (1,2 then 5,6); scene B is one run (1,2)
    fwd = [(A, (), 1), (A, (), 2), (A, (), 5), (A, (), 6),
           (B, (), 1), (B, (), 2)]
    # every run's last frame has no (idx, idx+1) partner and is dropped
    assert dataset._drop_sequence_tails(fwd, step=1) == [
        (A, (), 1), (A, (), 5), (B, (), 1)]

    # backwards layout sorts descending; the partner is (idx, idx-1), so
    # the run's *lowest* index is the tail
    bwd = sorted(fwd, key=lambda g: (g[0], g[1], -g[2]))
    assert dataset._drop_sequence_tails(bwd, step=-1) == [
        (A, (), 6), (A, (), 2), (B, (), 2)]

    assert dataset._drop_sequence_tails([], step=1) == []
    # a single frame has no partner in either direction
    assert dataset._drop_sequence_tails([(A, (), 3)], step=1) == []


class ConstFlowSource(Collection):
    """Constant +3px horizontal translation with a consistent frame 2."""

    type = "const-flow"

    def __init__(self, n=2, h=20, w=24, shift=3):
        self.n, self.h, self.w, self.shift = n, h, w, shift

    def __getitem__(self, index):
        rng = np.random.RandomState(index)
        img1 = rng.rand(1, self.h, self.w, 3).astype(np.float32)
        img2 = np.roll(img1, self.shift, axis=2)
        flow = np.zeros((1, self.h, self.w, 2), np.float32)
        flow[..., 0] = self.shift
        valid = np.ones((1, self.h, self.w), dtype=bool)
        meta = [Metadata(True, "const", SampleId("s{idx}", SampleArgs([], {"idx": index}),
                                                 SampleArgs([], {"idx": index + 1})),
                         ((0, self.h), (0, self.w)))]
        return img1, img2, flow, valid, meta

    def __len__(self):
        return self.n

    def get_config(self):
        return {"type": "const-flow", "n": self.n}

    def description(self):
        return "const-flow"


def test_estimate_backwards_flow_fill_densifies_disocclusions():
    img1, img2, flow, valid, _ = ConstFlowSource()[0]

    for method, args in (("minimum", {}), ("average", {"threshold": 1})):
        flow_bw, valid_bw = fw_bw.estimate_backwards_flow(
            img1[0], img2[0], flow[0], valid[0],
            fill_method=method, fill_args=args)
        assert valid_bw.all()
        # the filled disocclusion strip inherits its valid neighbors'
        # constant motion: exact inverse everywhere
        np.testing.assert_allclose(flow_bw[..., 0], -3.0, atol=1e-5)
        np.testing.assert_allclose(flow_bw[..., 1], 0.0, atol=1e-5)

    with pytest.raises(ValueError):
        fw_bw.estimate_backwards_flow(img1[0], img2[0], flow[0], valid[0],
                                      fill_method="nearest")


def test_fw_bw_estimate_collection():
    src = ConstFlowSource(n=2)
    est = fw_bw.ForwardsBackwardsEstimate(
        src, {}, "average", {"threshold": 1})
    assert len(est) == 2

    img1, img2, flow, valid, meta = est[0]
    s_img1, s_img2, s_flow, *_ = src[0]

    # batch doubles: forward pairs then the swapped backward pairs
    assert img1.shape[0] == 2 and img2.shape[0] == 2
    np.testing.assert_array_equal(img1[0], s_img1[0])
    np.testing.assert_array_equal(img1[1], s_img2[0])
    np.testing.assert_array_equal(img2[1], s_img1[0])

    # estimated backward half: exact inverse of the constant forward flow
    np.testing.assert_array_equal(flow[0], s_flow[0])
    np.testing.assert_allclose(flow[1], -s_flow[0], atol=1e-5)
    assert valid.all()

    assert meta[0].direction == "forwards"
    assert meta[1].direction == "backwards"
    assert meta[0].sample_id.format.endswith("-fwd")
    assert meta[1].sample_id.format.endswith("-bwd")

    cfg = est.get_config()
    assert cfg["type"] == "forwards-backwards-estimate"
    assert cfg["fill"] == {"method": "average",
                           "parameters": {"threshold": 1}}
    assert cfg["source"] == {"type": "const-flow", "n": 2}


def test_fw_bw_batch_pairs():
    fwd, bwd = FakeSource(3), FakeSource(3)

    # fake sources produce matching ids only if we swap img1/img2 args; build
    # a wrapper for the backward side instead
    class Bwd(FakeSource):
        def __getitem__(self, index):
            img1, img2, flow, valid, meta = super().__getitem__(index)
            m = meta[0]
            sid = SampleId(m.sample_id.format, m.sample_id.img2, m.sample_id.img1)
            meta = [Metadata(m.valid, m.dataset_id, sid, m.original_extents)]
            return img2, img1, -flow, valid, meta

    src = fw_bw.ForwardsBackwardsBatch(fwd, Bwd(3))
    img1, img2, flow, valid, meta = src[1]
    assert img1.shape[0] == 2
    assert meta[0].direction == "forwards"
    assert meta[1].direction == "backwards"
